"""Headline benchmarks against BASELINE.md — fault-isolated tiers.

Driver contract: running ``python bench.py`` prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline", ...}``.

Round-2 lesson (VERDICT.md): a single in-process bench lost every tier's
numbers when one tier crashed the TPU worker. Each tier therefore now runs
in its OWN subprocess (``python bench.py --tier NAME --out FILE``) with a
timeout, and the orchestrator appends each tier's result to
``bench_partial.json`` as it lands — a crash in one tier costs exactly that
tier.

Tiers (BASELINE.md configs):

1. ``north_star`` (config 2): PBMC-10k-shaped factorize+combine+consensus,
   K=5..13 x n_iter=100, batch_size=5000. The reference publishes no number;
   ``vs_baseline`` extrapolates its only anchor (PBMC3k: 120 online-MU runs
   of 2,700x2,000 in ~240 s on 4 CPU workers => 2.0 s/run) linearly in rows
   and runs (2.0 x 10000/2700 x 900 = 6,667 s), consensus excluded
   (conservative).
2. ``anchor`` (config 1 shape): the directly comparable 120-run PBMC3k sweep
   vs the published ~240 s.
3. ``kl`` (config 3): the beta=1 kernel, K=9 x 100 replicates — the tier
   whose HBM blowup crashed round 2, now sliced by the beta-aware budget
   (parallel/replicates.py: auto_replicates_per_batch).
4. ``mfu``: fixed-iteration MU probes at the workload shapes; reports
   achieved TFLOP/s, MFU vs chip peak, and effective HBM bandwidth (the MU
   kernel at k=9 is bandwidth-bound: arithmetic intensity ~2k FLOP per
   fp32 element of X).
5. ``rowshard`` (config 5 scaled to one chip): 1M-cell x 2,000-gene CSR
   streamed host->HBM shard-wise (never a host dense copy), then
   row-sharded KL/Frobenius passes — reports streaming GB/s and cells/s.
6. ``harmony`` (config 4 shape): Preprocess (seurat_v3 HVG -> PCA ->
   Harmony -> gene-space MOE ridge) -> cNMF prepare -> factorize ->
   consensus end-to-end.

CAVEAT (stated in the output): counts are synthetic Poisson draws from a
low-rank GEP model with the reference datasets' shapes — the datasets
themselves are not redistributable in this environment — and the north-star
comparator is an extrapolation, not a measurement.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np

PBMC3K_BASELINE_SECONDS = 240.0   # 4 min, 4 CPU workers, 120 runs
NORTH_STAR_BASELINE_SECONDS = PBMC3K_BASELINE_SECONDS / 120 * (10000 / 2700) * 900

# bf16-multiplicand matmul peak by device kind (TPU default precision for
# fp32 operands is one bf16 pass, so this is the honest denominator);
# (peak_flops_per_s, hbm_bytes_per_s)
_CHIP_PEAKS = {
    "TPU v4": (275e12, 1.2e12),
    "TPU v5 lite": (394e12, 0.819e12),
    "TPU v5": (459e12, 2.765e12),
    "TPU v5p": (459e12, 2.765e12),
    "TPU v6 lite": (918e12, 1.64e12),
}

TIERS = ["north_star", "anchor", "kl", "accel", "sketch", "plan", "mfu",
         "rowshard", "grid2d", "ingest", "serve", "fleet", "harmony"]
TIER_TIMEOUT_S = {"north_star": 2400, "anchor": 1200, "kl": 1800,
                  "accel": 1200, "sketch": 1200, "plan": 1200, "mfu": 900,
                  "rowshard": 1500, "grid2d": 1200, "ingest": 1200,
                  "serve": 1200, "fleet": 1800, "harmony": 1500}


def synthetic_pbmc_like(n=2700, g=2000, k_true=12, seed=0, scale=400.0):
    """Structured counts with PBMC-like shape: sparse-ish Poisson draws from
    a low-rank GEP model, variance-scaled the way prepare() feeds the
    solver (unit-variance genes, no centering)."""
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    std = X.std(axis=0, ddof=1)
    std[std == 0] = 1.0
    return X / std


def synthetic_counts_df(n, g, k_true=14, seed=3):
    import pandas as pd

    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    counts = rng.poisson(usage @ spectra * 400.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return pd.DataFrame(counts, index=[f"c{i}" for i in range(n)],
                        columns=[f"g{j}" for j in range(g)])


def _tier_telemetry(workdir=None, name=None):
    """Per-tier telemetry summary for the BENCH json (`telemetry` key —
    additive; existing keys the trajectory tooling reads are untouched):
    stage walls + convergence stats from the run's events.jsonl when a
    pipeline tier produced one, and the device-memory peak always."""
    from cnmf_torch_tpu.utils.telemetry import (device_memory_peak_bytes,
                                                read_events,
                                                summarize_events,
                                                telemetry_enabled)

    # enabled_during_run marks the measurement condition: pipeline tiers
    # time telemetry-ENABLED programs (that's what buys the per-phase
    # attribution), so trajectory comparisons across rounds should compare
    # like with like
    out: dict = {"memory_peak_bytes": device_memory_peak_bytes(),
                 "enabled_during_run": telemetry_enabled()}
    if workdir and name:
        path = os.path.join(workdir, name, "cnmf_tmp",
                            f"{name}.events.jsonl")
        if os.path.exists(path):
            s = summarize_events(read_events(path))
            out["stage_walls_s"] = {
                stage: v["wall_s"] for stage, v in s.get("stages",
                                                         {}).items()}
            if "convergence" in s:
                out["convergence"] = s["convergence"]
            if "memory_peak_bytes" in s:
                out["memory_peak_bytes"] = max(out["memory_peak_bytes"],
                                               s["memory_peak_bytes"])
            out["n_events"] = s.get("n_events")
    return out


def _sink_to_convergence(payloads):
    """Collapse sweep telemetry payloads into the convergence dict shape
    the report uses (fraction capped, spread, nonfinite) — record
    semantics come from the ONE shared converter
    (telemetry.replicate_records), same as the pipeline's events."""
    from cnmf_torch_tpu.utils.telemetry import (replicate_records,
                                                summarize_events)

    events = [{"v": 1, "t": "replicates", "ts": 0.0, "k": pay["k"],
               "beta": pay["beta"], "records": replicate_records(pay)}
              for pay in payloads]
    return summarize_events(events).get("convergence", {})


def iter_stage_rows(timings_tsv):
    """Yield (stage_name, wall_seconds) rows from a StageTimer ledger, in
    file order — the ONE parser of the timings-TSV format in this file."""
    with open(timings_tsv) as f:
        next(f)
        for line in f:
            name, secs = line.split("\t")[:2]
            yield name, float(secs)


def read_stage_seconds(timings_tsv):
    stages = {}
    for name, secs in iter_stage_rows(timings_tsv):
        stages[name] = stages.get(name, 0.0) + secs
    return stages


# ---------------------------------------------------------------------------
# tiers (each runs in its own subprocess)
# ---------------------------------------------------------------------------

def bench_north_star():
    """PBMC-10k-shaped e2e: prepare -> factorize(K=5..13 x 100) -> combine
    -> consensus(k=9), run TWICE in-process. The first pass is the cold
    number (includes whatever compiles/uploads actually happened); the
    second is the warm steady state — the figure the README quotes, now
    emitted by the driver's own capture instead of measured out-of-band
    (VERDICT r4 item 1). The consensus sub-stage ledger
    (consensus.kmeans/refits/ols/writes, models/cnmf.py) is split into
    cold/warm breakdowns so device-program cost, host OLS, and file
    writes are separately attributable."""
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    # telemetry ON for the pipeline tiers: the BENCH json then attributes
    # any trajectory regression to a phase (stage walls + per-K replicate
    # convergence ride under the additive `telemetry` key)
    os.environ.setdefault("CNMF_TPU_TELEMETRY", "1")
    workdir = tempfile.mkdtemp(prefix="bench_ns_")
    counts_fn = os.path.join(workdir, "counts.df.npz")
    save_df_to_npz(synthetic_counts_df(10000, 5000), counts_fn)

    obj = cNMF(output_dir=workdir, name="ns")
    obj.prepare(counts_fn, components=list(range(5, 14)), n_iter=100,
                seed=14, num_highvar_genes=2000, batch_size=5000)
    tsv = os.path.join(workdir, "ns", "cnmf_tmp", "ns.timings.tsv")

    def one_pass():
        t0 = time.perf_counter()
        obj.factorize()
        fact = time.perf_counter() - t0
        t0 = time.perf_counter()
        obj.combine()
        comb = time.perf_counter() - t0
        t0 = time.perf_counter()
        try:
            obj.consensus(k=9, density_threshold=0.5, show_clustering=False)
        except RuntimeError:
            # synthetic replicate spectra can be more dispersed than real
            # PBMC ones; keep the full consensus pipeline in the measurement
            obj.consensus(k=9, density_threshold=2.0, show_clustering=False)
        cons = time.perf_counter() - t0
        return fact, comb, cons

    def consensus_substages():
        return [(name, secs) for name, secs in iter_stage_rows(tsv)
                if name.startswith("consensus.")]

    factorize_cold, combine_cold, consensus_cold = one_pass()
    sub_cold = consensus_substages()
    factorize_warm, combine_warm, consensus_warm = one_pass()
    sub_warm = consensus_substages()[len(sub_cold):]

    # packed stats-only K-selection over all 9 Ks (VERDICT r4 item 8's
    # driver-verifiable number): first call compiles/uploads the shared
    # K_max-padded program set, the second reuses it
    t0 = time.perf_counter()
    obj.k_selection_plot(close_fig=True)
    kselect_cold = time.perf_counter() - t0
    t0 = time.perf_counter()
    obj.k_selection_plot(close_fig=True)
    kselect_warm = time.perf_counter() - t0

    def agg(rows):
        out: dict = {}
        for name, secs in rows:
            key = name.split(".", 1)[1]
            out[key] = round(out.get(key, 0.0) + secs, 3)
        return out

    stages = read_stage_seconds(tsv)
    telemetry = _tier_telemetry(workdir, "ns")
    shutil.rmtree(workdir)
    e2e = factorize_cold + combine_cold + consensus_cold
    warm_e2e = factorize_warm + combine_warm + consensus_warm
    prepare_s = stages.get("prepare", 0.0)
    return {
        "e2e_seconds": round(e2e, 3),
        # the wall-clock a user actually experiences, prepare included
        "e2e_with_prepare_seconds": round(prepare_s + e2e, 3),
        "warm_e2e_seconds": round(warm_e2e, 3),
        "factorize_cold_seconds": round(factorize_cold, 3),
        "factorize_warm_seconds": round(factorize_warm, 3),
        "compile_overhead_seconds": round(factorize_cold - factorize_warm, 3),
        "combine_seconds": round(combine_cold, 3),
        "combine_warm_seconds": round(combine_warm, 3),
        "consensus_seconds": round(consensus_cold, 3),
        "consensus_warm_seconds": round(consensus_warm, 3),
        "consensus_breakdown_cold": agg(sub_cold),
        "consensus_breakdown_warm": agg(sub_warm),
        "k_selection_cold_seconds": round(kselect_cold, 3),
        "k_selection_warm_seconds": round(kselect_warm, 3),
        "prepare_seconds": round(prepare_s, 3),
        "vs_baseline": round(NORTH_STAR_BASELINE_SECONDS / e2e, 2),
        "vs_baseline_warm": round(NORTH_STAR_BASELINE_SECONDS / warm_e2e, 2),
        "telemetry": telemetry,
    }


def bench_anchor():
    import jax.numpy as jnp

    from cnmf_torch_tpu.parallel import default_mesh, replicate_sweep

    X = jnp.asarray(synthetic_pbmc_like())
    mesh = default_mesh()
    master = np.random.RandomState(14)
    ks = [5, 6, 7, 8, 9, 10]
    seeds_per_k = {k: master.randint(1, 2 ** 31 - 1, size=20).tolist()
                   for k in ks}
    for k in ks:  # compile
        replicate_sweep(X, [1] * 20, k, mode="online", online_chunk_size=5000,
                        online_chunk_max_iter=1000, mesh=mesh)
    t0 = time.perf_counter()
    pending = [(k,) + replicate_sweep(
        X, seeds_per_k[k], k, mode="online", online_chunk_size=5000,
        online_chunk_max_iter=1000, mesh=mesh, fetch=False)[::2]
        for k in ks]
    total_err = 0.0
    for k, spectra_d, errs_d in pending:
        assert np.asarray(spectra_d).shape == (20, k, 2000)
        total_err += float(np.sum(np.asarray(errs_d)))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(total_err)
    return {
        "seconds": round(elapsed, 3),
        "vs_baseline": round(PBMC3K_BASELINE_SECONDS / elapsed, 2),
        "baseline": "ref tutorial: ~240 s, 120 runs, 4 CPU workers",
        "telemetry": _tier_telemetry(),
    }


def synthetic_sparse_pbmc_like(n=10000, g=2000, k_true=12, seed=5,
                               scale=10.0):
    """Single-cell-realistic SPARSE counts at the kl-tier shape: the same
    low-rank GEP Poisson model as :func:`synthetic_pbmc_like` but at a
    count depth that leaves ~95% exact zeros (real HVG matrices are
    85-95% zeros). Variance scaling preserves the zero pattern. Returns a
    scipy CSR."""
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    std = X.std(axis=0, ddof=1)
    std[std == 0] = 1.0
    return sp.csr_matrix(X / std)


def _kl_update_probe(n, g, k, R, iters, solo):
    """Two-point fixed-iteration timing of a vmapped beta=1 MU inner
    chain (same methodology as the mfu tier: N vs 3N iters at one program
    shape cancels dispatch overhead AND once-per-solve setup like the ELL
    path's pre-gathered W table). ``solo(h, w, n_it)`` runs n_it inner
    iterations for one replicate."""
    import functools

    import jax

    @functools.partial(jax.jit, static_argnames=("n_it",))
    def batched(H, W, n_it):
        return jax.vmap(lambda h, w: solo(h, w, n_it))(H, W)

    rng = np.random.default_rng(0)
    import jax.numpy as jnp

    H = jnp.asarray(rng.random((R, n, k), np.float32) + 0.1)
    W = jnp.asarray(rng.random((R, k, g), np.float32) + 0.1)
    _device_sync(batched(H, W, iters))
    _device_sync(batched(H, W, 3 * iters))

    def timed(n_it):
        t0 = time.perf_counter()
        _device_sync(batched(H, W, n_it))
        return time.perf_counter() - t0

    d_short = min(timed(iters) for _ in range(2))
    d_long = min(timed(3 * iters) for _ in range(2))
    return max(d_long - d_short, 1e-9) / (2 * iters * R)


def bench_kl():
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import _update_H, _update_W, resolve_bf16_ratio
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put
    from cnmf_torch_tpu.parallel import (auto_replicates_per_batch,
                                         replicate_sweep)

    X = jnp.asarray(synthetic_pbmc_like(n=10000, seed=5))
    seeds = np.random.RandomState(7).randint(1, 2 ** 31 - 1, size=100).tolist()
    slice_size = auto_replicates_per_batch(10000, 2000, 9, beta=1.0,
                                           chunk=5000)
    replicate_sweep(X, seeds[:4], 9, beta_loss="kullback-leibler",
                    mode="online", online_chunk_size=5000)  # compile
    t0 = time.perf_counter()
    _, _, errs = replicate_sweep(X, seeds, 9,
                                 beta_loss="kullback-leibler", mode="online",
                                 online_chunk_size=5000)
    elapsed = time.perf_counter() - t0
    assert np.isfinite(errs).all()
    out = {"seconds": round(elapsed, 3),
           "replicates_per_device_slice": int(slice_size)}

    # --- dense vs fixed-width-ELL beta=1 kernel at single-cell sparsity ---
    # (ISSUE 1): same shape, ~90%-zero counts. The probed unit is the
    # production iteration — the inner H update (the online KL solver
    # spends its iterations there; the W step is once per chunk and is
    # reported separately). Matched f32 precision for both chains (the
    # bf16 memory-format chain is a TPU lever; CPU emulates bf16 and
    # would distort a like-for-like kernel comparison).
    import functools

    import jax

    from cnmf_torch_tpu.ops.sparse import ell_w_table

    n, g, k, R, iters = 10000, 2000, 9, 4, 10
    Xs = synthetic_sparse_pbmc_like(n=n, g=g)
    sparsity = 1.0 - Xs.nnz / (n * g)
    ell = ell_device_put(csr_to_ell(Xs))
    Xd_probe = jnp.asarray(Xs.toarray())

    def dense_solo(h, w, n_it):
        return jax.lax.fori_loop(
            0, n_it,
            lambda _, hh: _update_H(Xd_probe, hh, w, 1.0, 0.0, 0.0), h)

    def ell_solo(h, w, n_it):
        # the W slab table is loop-invariant across the inner solve —
        # gathered once, exactly as _chunk_h_solve does
        table = ell_w_table(w, ell.cols)
        return jax.lax.fori_loop(
            0, n_it,
            lambda _, hh: _update_H(ell, hh, w, 1.0, 0.0, 0.0,
                                    w_table=table), h)

    dense_s = _kl_update_probe(n, g, k, R, iters, dense_solo)
    ell_s = _kl_update_probe(n, g, k, R, iters, ell_solo)

    # the once-per-chunk W step, timed per call (includes its own wh pass
    # and, for ELL, the transpose-side gathers)
    rng_w = np.random.default_rng(1)
    Hp = jnp.asarray(rng_w.random((n, k), np.float32) + 0.1)
    Wp = jnp.asarray(rng_w.random((k, g), np.float32) + 0.1)
    dense_wstep = jax.jit(
        lambda h, w: _update_W(Xd_probe, h, w, 1.0, 0.0, 0.0))
    ell_wstep = jax.jit(lambda h, w: _update_W(ell, h, w, 1.0, 0.0, 0.0))

    def timed_call(f):
        _device_sync(f(Hp, Wp))
        t0 = time.perf_counter()
        for _ in range(3):
            _device_sync(f(Hp, Wp))
        return (time.perf_counter() - t0) / 3

    dense_w_s = timed_call(dense_wstep)
    ell_w_s = timed_call(ell_wstep)

    # per-iteration unique-traffic models for the inner H update: the
    # dense chain streams X + the WH/ratio intermediates (~3 n*g f32
    # buffers); the ELL chain streams the slab table (+ ratio buffers)
    dense_bytes = 3 * n * g * 4
    w_ell = ell.width
    ell_bytes = n * w_ell * (2 * k + 3) * 4
    out["sparse_fixture"] = {
        "sparsity": round(float(sparsity), 4),
        "ell_width": int(w_ell),
        "ell_t_width": int(ell.t_width),
        "genes": g,
        "dense_h_update_us_per_iter_per_replicate":
            round(dense_s * 1e6, 2),
        "ell_h_update_us_per_iter_per_replicate": round(ell_s * 1e6, 2),
        "ell_speedup_vs_dense": round(dense_s / ell_s, 2),
        "dense_w_step_ms": round(dense_w_s * 1e3, 2),
        "ell_w_step_ms": round(ell_w_s * 1e3, 2),
        "dense_effective_gb_per_s": round(dense_bytes / dense_s / 1e9, 1),
        "ell_effective_gb_per_s": round(ell_bytes / ell_s / 1e9, 1),
        "precision": "f32 (matched; bf16 chain is a TPU memory-format "
                     "lever, emulated on CPU)",
    }

    # sweep-level objective parity at the sparse fixture (the same per-seed
    # bounds the bf16 parity test pins: KL 2%); matched f32 for both paths
    from cnmf_torch_tpu.parallel.replicates import _sweep_program

    sw_seeds = seeds[:8]
    saved_env = {k: os.environ.get(k)
                 for k in ("CNMF_TPU_BF16_RATIO", "CNMF_TPU_SPARSE_BETA")}
    os.environ["CNMF_TPU_BF16_RATIO"] = "0"
    try:
        _sweep_program.cache_clear()
        t0 = time.perf_counter()
        _, _, errs_ell = replicate_sweep(
            Xs, sw_seeds, 9, beta_loss="kullback-leibler", mode="online",
            online_chunk_size=5000)
        ell_sweep_s = time.perf_counter() - t0
        os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
        _sweep_program.cache_clear()
        t0 = time.perf_counter()
        _, _, errs_dense = replicate_sweep(
            Xs, sw_seeds, 9, beta_loss="kullback-leibler", mode="online",
            online_chunk_size=5000)
        dense_sweep_s = time.perf_counter() - t0
        _sweep_program.cache_clear()
    finally:
        for key, val in saved_env.items():  # restore, never clobber
            if val is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = val
    rel = np.abs((errs_ell - errs_dense) / np.abs(errs_dense))
    out["sparse_fixture"]["sweep_seconds_ell_8rep"] = round(ell_sweep_s, 3)
    out["sparse_fixture"]["sweep_seconds_dense_8rep"] = round(dense_sweep_s, 3)
    out["sparse_fixture"]["sweep_objective_max_rel_diff"] = round(
        float(rel.max()), 5)

    # convergence telemetry for the tier (additive `telemetry` key): one
    # sink-instrumented 8-replicate sweep. The TIMED sweeps above ran
    # without a sink, so their programs stay the telemetry-free ones —
    # the µs/iter probes measure the unchanged production kernels.
    payloads: list = []
    saved_t = os.environ.get("CNMF_TPU_TELEMETRY")
    os.environ["CNMF_TPU_TELEMETRY"] = "1"
    try:
        replicate_sweep(X, seeds[:8], 9, beta_loss="kullback-leibler",
                        mode="online", online_chunk_size=5000,
                        telemetry_sink=payloads.append)
    finally:
        if saved_t is None:
            os.environ.pop("CNMF_TPU_TELEMETRY", None)
        else:
            os.environ["CNMF_TPU_TELEMETRY"] = saved_t
    out["telemetry"] = dict(_tier_telemetry(),
                            convergence=_sink_to_convergence(payloads))
    # iterations(passes)-to-tolerance from the same sink payloads, so
    # future BENCH trajectories can tell "faster iterations" from
    # "fewer iterations" (ISSUE 9 satellite) — µs/iter above is the
    # former, this is the latter
    itt = _iters_to_tolerance(payloads)
    if itt is not None:
        out["iters_to_tolerance"] = itt
    return out


def _iters_to_tolerance(payloads, tol_rel=1e-3):
    """Iterations(/passes)-to-tolerance per replicate from convergence
    telemetry payloads: the first trace evaluation whose objective is
    within ``tol_rel`` of that replicate's own final objective, scaled by
    the trace cadence. Distinguishes "fewer iterations" from "faster
    iterations" in the BENCH trajectory (ISSUE 9 satellite)."""
    by_unit: dict = {}
    for pay in payloads:
        trace = np.asarray(pay["trace"])
        errs = np.asarray(pay["errs"], np.float64)
        # batch solvers evaluate every EVAL_EVERY iterations; online/
        # rowshard trace once per pass — a pass entry and an iter entry
        # are different units, so aggregate per cadence
        cad = pay.get("cadence", "pass")
        step = int(cad.split("/", 1)[1]) if "/" in cad else 1
        unit = "pass" if step == 1 else "iter"
        for i in range(trace.shape[0]):
            tr = trace[i][~np.isnan(trace[i])]
            if not len(tr) or not np.isfinite(errs[i]):
                continue
            target = errs[i] * (1.0 + tol_rel)
            hit = np.nonzero(tr <= target)[0]
            by_unit.setdefault(unit, []).append(
                int((hit[0] + 1 if len(hit) else len(tr)) * step))
    if not by_unit:
        return None
    # stats over the dominant cadence only; a mixed-mode sink reports the
    # minority entries as a count instead of folding passes into iters
    unit, vals = max(by_unit.items(), key=lambda kv: len(kv[1]))
    per = np.asarray(vals)
    out = {"tol_rel": tol_rel, "unit": unit,
           "mean": round(float(per.mean()), 1),
           "median": int(np.median(per)), "max": int(per.max()),
           "n": int(len(per))}
    if len(by_unit) > 1:
        out["n_other_units"] = {u: len(v) for u, v in by_unit.items()
                                if u != unit}
    return out


def bench_accel():
    """Iteration-count acceleration (ISSUE 9): plain MU vs accelerated-MU
    vs Diagonalized Newton on the batch KL solver, measured as
    wall-clock AND inner-iteration count to a fixed objective tolerance,
    with the telemetry objective traces as the oracle. Two fixtures: the
    dense KL shape and the 95%-sparse single-cell fixture on the
    fixed-width ELL path. The tolerance target is relative to the best
    objective ANY recipe reached, so no recipe is graded against its own
    (possibly worse) optimum."""
    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import EVAL_EVERY, nmf_fit_batch, random_init
    from cnmf_torch_tpu.ops.recipe import auto_inner_repeats, resolve_recipe
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put

    TOL_REL = 2e-3
    # full production shape on accelerators; the CPU container runs a
    # reduced fixture so the tier fits its fault-isolation timeout (the
    # measured quantity is an ITERATION COUNT ratio — shape-stable, and
    # the reduction is what the acceptance tracks; wall-clock is
    # reported per backend as-is)
    if jax.default_backend() == "cpu":
        MAX_IT, R = 240, 2
        shape = (2000, 1000, 9)
    else:
        MAX_IT, R = 400, 4
        shape = (10000, 2000, 9)

    def measure(X_solve, n, g, k, ell_width=None):
        rho = auto_inner_repeats(1.0, n, g, k, ell_width=ell_width)
        recipes = {"mu": dict(), "amu": dict(inner_repeats=rho),
                   "dna": dict(kl_newton=True)}
        rng = np.random.default_rng(0)
        seeds = rng.integers(1, 1 << 31, size=R)
        x_mean = (float(np.asarray(jnp.sum(X_solve.vals)) / (n * g))
                  if ell_width else float(np.asarray(jnp.mean(X_solve))))
        inits = [random_init(jax.random.key(int(s)), n, g, k,
                             jnp.float32(x_mean)) for s in seeds]
        H0 = jnp.stack([h for h, _ in inits])
        W0 = jnp.stack([w for _, w in inits])

        raw = {}
        for name, kw in recipes.items():
            fit = jax.jit(jax.vmap(
                lambda h, w: nmf_fit_batch(
                    X_solve, h, w, beta=1.0, tol=0.0, max_iter=MAX_IT,
                    telemetry=True, **kw)))
            # warm-up must DRAIN before the timer starts (async dispatch)
            jax.block_until_ready(fit(H0, W0))
            t0 = time.perf_counter()
            _, _, errs, tm = jax.block_until_ready(fit(H0, W0))
            wall = time.perf_counter() - t0
            raw[name] = (np.asarray(tm.trace), np.asarray(errs),
                         # identity recipes carry no inner accumulator
                         # (inner == iters by construction)
                         np.asarray(tm.inner_iters
                                    if tm.inner_iters is not None
                                    else tm.iters),
                         np.asarray(tm.iters), wall,
                         int(kw.get("inner_repeats", 1)))

        # fixed tolerance target: TOL_REL above the best objective any
        # recipe reached, per replicate
        best = np.min(np.stack([raw[n_][1] for n_ in raw]), axis=0)
        target = best * (1.0 + TOL_REL)
        out = {"rho_auto": int(rho), "tol_rel": TOL_REL,
               "max_outer_iters": MAX_IT, "replicates": R}
        for name, (trace, errs, inner, iters, wall, rho_k) in raw.items():
            outer_hits, reached = [], 0
            for i in range(R):
                tr = trace[i][~np.isnan(trace[i])]
                hit = np.nonzero(tr <= target[i])[0]
                evals = hit[0] + 1 if len(hit) else len(tr)
                reached += bool(len(hit))
                outer_hits.append(int(evals * EVAL_EVERY))
            # telemetry only carries whole-run inner totals, and the amu
            # repeat loop stagnation-exits more often AFTER the tolerance
            # crossing than before it — a whole-run mean would UNDERcount
            # amu's pre-crossing inner rate and overstate its reduction.
            # Grade amu conservatively at the full configured rho per
            # outer iteration (an upper bound on its inner count); mu/dna
            # run exactly one inner update per outer by construction.
            per_outer_run = float(np.mean(inner.astype(np.float64)
                                          / np.maximum(iters, 1)))
            per_outer_bound = float(rho_k)
            out[name] = {
                "outer_iters_to_tol": round(float(np.mean(outer_hits)), 1),
                # lanes that never reached the shared target are censored
                # at the cap — their iters-to-tol is a lower bound
                "reached_tol_fraction": round(reached / R, 2),
                "inner_updates_per_outer_run_mean": round(per_outer_run, 2),
                "inner_iters_to_tol": round(
                    float(np.mean(outer_hits)) * per_outer_bound, 1),
                "final_err_mean": round(float(errs.mean()), 3),
                "wall_seconds_full_cap": round(wall, 3),
            }
        for name in ("amu", "dna"):
            out[name]["reduction_vs_mu_outer"] = round(
                out["mu"]["outer_iters_to_tol"]
                / max(out[name]["outer_iters_to_tol"], 1e-9), 2)
            out[name]["reduction_vs_mu_inner"] = round(
                out["mu"]["inner_iters_to_tol"]
                / max(out[name]["inner_iters_to_tol"], 1e-9), 2)
        return out

    results = {}
    # dense KL fixture (bench kl-tier shape class)
    n, g, k = shape
    Xd = jnp.asarray(synthetic_pbmc_like(n=n, g=g, seed=5))
    results["dense_kl"] = measure(Xd, n, g, k)
    del Xd
    # 95%-sparse fixture on the ELL path
    Xs = synthetic_sparse_pbmc_like(n=n, g=g)
    sparsity = 1.0 - Xs.nnz / (n * g)
    ell = ell_device_put(csr_to_ell(Xs))
    results["sparse_kl"] = dict(
        measure(ell, n, g, k, ell_width=ell.width),
        sparsity=round(float(sparsity), 4), ell_width=int(ell.width))

    # headline gates on INNER reductions only: an outer reduction that
    # costs rho inner updates per step is not an inner-iteration win
    best = max(results[f][r]["reduction_vs_mu_inner"]
               for f in results for r in ("amu", "dna"))
    results["best_inner_iteration_reduction_vs_mu"] = round(best, 2)
    results["engaged_recipes"] = {
        "auto_kl_batch": resolve_recipe(1.0, "batch", accel="auto").label,
        "auto_is_batch": resolve_recipe(0.0, "batch", accel="auto").label,
        "default": resolve_recipe(1.0, "batch").label,
    }
    results["telemetry"] = _tier_telemetry()
    return results


def bench_plan():
    """Execution planner (ISSUE 17): autotuned-auto vs static-default
    dispatch on the 95%-sparse KL fixture. The planner microbenches are
    force-measured into a PRIVATE cache dir (the machine-level cache is
    never written), then the plan is built twice — once with
    CNMF_TPU_AUTOTUNE=0 (static heuristics only, the deterministic
    escape hatch) and once in the shipped auto mode consuming the
    measured points — and the solver configuration each plan resolves
    (encoding + recipe) is timed on the same replicate batch.
    Acceptance: the autotuned-auto wall is no worse than the
    static-default wall (ties expected when both plans agree)."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, random_init
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put
    from cnmf_torch_tpu.runtime.planner import (
        DeviceInventory,
        InputStats,
        build_plan,
    )
    from cnmf_torch_tpu.utils import autotune

    # reduced fixture on the CPU container (same policy as the accel
    # tier); the measured quantity is a wall RATIO between two dispatch
    # choices on the identical batch, which is shape-stable
    if jax.default_backend() == "cpu":
        # scale keeps the REDUCED shape at the fixture's ~95% sparsity
        # contract (the default count depth at 1000 genes lands ~91%
        # and trips the ELL ragged-row width guard, hiding the
        # encoding decision this tier exists to exercise)
        MAX_IT, R = 120, 2
        n, g, k, scale = 2000, 1000, 9, 5.0
    else:
        MAX_IT, R = 200, 4
        n, g, k, scale = 10000, 2000, 9, 10.0

    Xs = synthetic_sparse_pbmc_like(n=n, g=g, scale=scale)
    density = float(Xs.nnz / (n * g))
    ell = ell_device_put(csr_to_ell(Xs))
    Xd = jnp.asarray(Xs.toarray())
    stats = InputStats(n=n, g=g, beta=1.0, mode="batch", init="random",
                       algo="mu", sparse=True, density=density,
                       ell_width=int(ell.width), k_max=k, n_ks=1,
                       max_replicates=R, total_workers=1)
    inv = DeviceInventory.probe()

    x_mean = jnp.float32(np.asarray(jnp.sum(ell.vals)) / (n * g))
    rng = np.random.default_rng(0)
    inits = [random_init(jax.random.key(int(s)), n, g, k, x_mean)
             for s in rng.integers(1, 1 << 31, size=R)]
    H0 = jnp.stack([h for h, _ in inits])
    W0 = jnp.stack([w for _, w in inits])

    def measure(plan):
        """Wall of the plan-resolved solver configuration: the ENCODING
        (ELL vs dense) and the RECIPE are the two plan decisions with
        solver-wall consequences on this fixture."""
        X_solve = ell if plan.use_ell else Xd
        rec = plan.solver_recipe()
        kw = {}
        if rec.kl_newton:
            kw["kl_newton"] = True
        if rec.inner_repeats > 1:
            kw["inner_repeats"] = rec.inner_repeats
        if getattr(rec, "sketch_dim", 0):
            kw["sketch_dim"] = rec.sketch_dim
            kw["sketch_exact_every"] = rec.sketch_exact_every
        fit = jax.jit(jax.vmap(
            lambda h, w: nmf_fit_batch(X_solve, h, w, beta=1.0, tol=0.0,
                                       max_iter=MAX_IT, **kw)))
        # warm-up must DRAIN before the timer starts (async dispatch)
        jax.block_until_ready(fit(H0, W0))
        t0 = time.perf_counter()
        _, _, errs = jax.block_until_ready(fit(H0, W0))
        return time.perf_counter() - t0, float(np.asarray(errs).mean())

    # PRIVATE autotune cache: redirect cache_path's default base so the
    # planner's consumption sites (which use the default dir) read the
    # points measured HERE, and the machine cache is never touched
    env0 = {k_: os.environ.get(k_)
            for k_ in ("CNMF_TPU_AUTOTUNE", "CNMF_TPU_PLAN")}
    os.environ.pop("CNMF_TPU_PLAN", None)
    cache_dir = tempfile.mkdtemp(prefix="cnmf_bench_plan_")
    real_cache_path = autotune.cache_path
    autotune.cache_path = (
        lambda cd=None: real_cache_path(cd or cache_dir))
    try:
        os.environ["CNMF_TPU_AUTOTUNE"] = "0"
        plan_static = build_plan(stats, inv)
        static_wall, static_err = measure(plan_static)

        os.environ.pop("CNMF_TPU_AUTOTUNE", None)
        t0 = time.perf_counter()
        autotune.maybe_autotune_plan(force=True)
        tune_wall = time.perf_counter() - t0
        points = autotune.cached_plan_points()
        plan_auto = build_plan(stats, inv)
        auto_wall, auto_err = measure(plan_auto)
    finally:
        autotune.cache_path = real_cache_path
        for k_, v in env0.items():
            if v is None:
                os.environ.pop(k_, None)
            else:
                os.environ[k_] = v
        shutil.rmtree(cache_dir, ignore_errors=True)

    plans_identical = plan_auto.signature() == plan_static.signature()
    return {
        "fixture": {"n": n, "g": g, "k": k, "max_iter": MAX_IT,
                    "replicates": R,
                    "sparsity": round(1.0 - density, 4),
                    "ell_width": int(ell.width)},
        "measured_plan_points": points,
        "autotune_measure_seconds": round(tune_wall, 3),
        "static_default": {
            "signature": plan_static.signature(),
            "encoding": "ell" if plan_static.use_ell else "dense",
            "recipe": plan_static.recipe_label,
            "sources": dict(plan_static.sources),
            "wall_seconds": round(static_wall, 3),
            "final_err_mean": round(static_err, 3),
        },
        "autotuned_auto": {
            "signature": plan_auto.signature(),
            "encoding": "ell" if plan_auto.use_ell else "dense",
            "recipe": plan_auto.recipe_label,
            "sources": dict(plan_auto.sources),
            "wall_seconds": round(auto_wall, 3),
            "final_err_mean": round(auto_err, 3),
        },
        "plans_identical": plans_identical,
        "speedup_auto_vs_static": round(static_wall / max(auto_wall, 1e-9),
                                        3),
        # ties (identical plans) pass by construction; a 10% band
        # absorbs wall noise when the dispatches genuinely differ
        "autotuned_not_worse": bool(auto_wall <= 1.10 * static_wall),
        "telemetry": _tier_telemetry(),
    }


def bench_sketch():
    """Sketched solvers (ISSUE 11): measured crossovers for both sketch
    consumers against their exact twins.

    * ``consensus``: the distance-bearing clustering stage (KNN local
      density + k-means) on a K=9 x n_iter=100 stacked replicate-spectra
      fixture — full g-width exact vs random-projected to 256 dims —
      wall-clock plus the parity the smoke gates (identical outlier set,
      matching cluster medians).
    * ``solver``: the sketched KL W update on the 95%-sparse ELL fixture
      — per-update microbench (exact transpose-gather statistics vs the
      row-subsampled scatter statistics) and whole-solve us/iter via the
      N-vs-3N probe, with the final-objective gap at a fixed budget.
      Where the sketched update does NOT win on this backend, the
      numbers document the crossover (the scatter path is sized for
      accelerators; CPU scatters cost ~4x the memcpy they replace).
    """
    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops import kmeans, local_density
    from cnmf_torch_tpu.ops.nmf import (_apply_rate_sketched, _update_W,
                                        nmf_fit_batch, random_init)
    from cnmf_torch_tpu.ops.sketch import project_rows
    from cnmf_torch_tpu.ops.sparse import (csr_to_ell, ell_device_put,
                                           ell_kl_w_stats_rows)

    results = {}

    # ---- consensus stage: K=9 x 100 replicates ------------------------
    K, n_iter, g_sp, dim = 9, 100, 2000, 256
    R = K * n_iter
    rng = np.random.default_rng(7)
    base = rng.gamma(0.3, 1.0, size=(K, g_sp))
    rows = (base[rng.integers(0, K, size=R)]
            * rng.uniform(0.8, 1.25, size=(R, 1))
            + rng.gamma(0.1, 0.05, size=(R, g_sp)))
    out_idx = rng.choice(R, size=R // 50, replace=False)
    rows[out_idx] = rng.gamma(0.3, 1.0, size=(len(out_idx), g_sp)) * 4.0
    l2 = (rows / np.linalg.norm(rows, axis=1, keepdims=True)
          ).astype(np.float32)
    n_neighbors = int(0.30 * R / K)

    def exact_stage():
        dens, _ = local_density(l2, n_neighbors)
        labels, _, _ = kmeans(l2, K, n_init=10, seed=1)
        return np.asarray(dens), np.asarray(labels)

    def sketched_stage():
        proj = project_rows(l2, dim)
        dens, _ = local_density(proj, n_neighbors)
        labels, _, _ = kmeans(proj, K, n_init=10, seed=1)
        return np.asarray(dens), np.asarray(labels)

    def timed(fn, reps=3):
        fn()  # warm (compile + upload)
        walls = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            walls.append(time.perf_counter() - t0)
        return sorted(walls)[len(walls) // 2], out

    exact_wall, (dens_e, lab_e) = timed(exact_stage)
    sk_wall, (dens_s, lab_s) = timed(sketched_stage)
    thr = 0.5

    def medians(labels, keep):
        # k-means can leave a cluster empty among the density-kept rows
        # (collapsed programs); medians over the PRESENT clusters only —
        # an empty-slice np.median would silently turn the parity figure
        # into NaN
        present = [c for c in range(K) if (labels[keep] == c).any()]
        med = np.stack([np.median(l2[keep][labels[keep] == c], axis=0)
                        for c in present])
        return med / np.maximum(
            np.linalg.norm(med, axis=1, keepdims=True), 1e-12)

    keep_e, keep_s = dens_e < thr, dens_s < thr
    cos = (medians(lab_e, keep_e) @ medians(lab_s, keep_s).T).max(axis=1)
    results["consensus"] = {
        "replicates": R, "spectra_width": g_sp, "sketch_dim": dim,
        "exact_wall_s": round(exact_wall, 3),
        "sketch_wall_s": round(sk_wall, 3),
        "speedup": round(exact_wall / max(sk_wall, 1e-9), 2),
        "outlier_set_identical": bool((keep_e == keep_s).all()),
        "outliers": int((~keep_e).sum()),
        "median_cosine_min": round(float(cos.min()), 5),
    }

    # ---- solver: sketched W update on the 95%-sparse KL fixture -------
    if jax.default_backend() == "cpu":
        n, g, k = 4000, 1000, 9
        fit_iters = 40
    else:
        n, g, k = 10000, 2000, 9
        fit_iters = 80
    Xs = synthetic_sparse_pbmc_like(n=n, g=g)
    sparsity = 1.0 - Xs.nnz / (n * g)
    E = ell_device_put(csr_to_ell(Xs))
    m = max(256, n // 8)
    x_mean = float(Xs.sum() / (n * g))
    H0, W0 = random_init(jax.random.key(0), n, g, k, jnp.float32(x_mean))

    w_exact = jax.jit(lambda h, w: _update_W(E, h, w, 1.0, 0.0, 0.0))

    @jax.jit
    def w_sketched(h, w, it):
        idx = jax.random.randint(
            jax.random.fold_in(jax.random.key(0), it), (m,), 0, n)
        numer, denom = ell_kl_w_stats_rows(E, h, w, idx)
        return _apply_rate_sketched(w, numer, denom, 0.0, 0.0)

    # the warm-then-median timing discipline lives in ONE place
    # (utils/autotune.py:_time_call) — the autotuner and this tier must
    # never measure differently
    from cnmf_torch_tpu.utils.autotune import _time_call

    us_exact = _time_call(w_exact, H0, W0, repeats=7) * 1e6
    us_sk = _time_call(w_sketched, H0, W0, jnp.int32(1), repeats=7) * 1e6

    # whole-solve us/iter via the N-vs-3N probe (amortizes the fixed
    # end-of-solve objective recompute out of the per-iteration figure)
    def solve_wall(n_it, **kw):
        out = nmf_fit_batch(E, H0, W0, beta=1.0, tol=0.0, max_iter=n_it,
                            **kw)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        jax.block_until_ready(nmf_fit_batch(
            E, H0, W0, beta=1.0, tol=0.0, max_iter=n_it, **kw))
        return time.perf_counter() - t0, float(out[2])

    sk_kw = dict(sketch_dim=m, sketch_exact_every=4)
    t1_mu, _ = solve_wall(fit_iters)
    t3_mu, err_mu = solve_wall(3 * fit_iters)
    t1_sk, _ = solve_wall(fit_iters, **sk_kw)
    t3_sk, err_sk = solve_wall(3 * fit_iters, **sk_kw)
    us_it_mu = (t3_mu - t1_mu) / (2 * fit_iters) * 1e6
    us_it_sk = (t3_sk - t1_sk) / (2 * fit_iters) * 1e6
    results["solver"] = {
        "fixture": {"n": n, "g": g, "k": k,
                    "sparsity": round(float(sparsity), 4),
                    "ell_width": int(E.width)},
        "sketch_dim": int(m), "exact_every": 4,
        "w_update_exact_us": round(us_exact, 1),
        "w_update_sketched_us": round(us_sk, 1),
        "w_update_speedup": round(us_exact / max(us_sk, 1e-9), 2),
        "solve_us_per_iter_mu": round(us_it_mu, 1),
        "solve_us_per_iter_sketch": round(us_it_sk, 1),
        "solve_per_iter_speedup": round(us_it_mu / max(us_it_sk, 1e-9),
                                        2),
        "final_err_mu": round(err_mu, 2),
        "final_err_sketch": round(err_sk, 2),
        "objective_rel_gap": round(abs(err_sk - err_mu) / err_mu, 5),
        "crossover_note": (
            "sketched W update slower than exact on this backend at "
            "this shape (scatter-bound); lane sized for accelerators"
            if us_sk >= us_exact else ""),
    }
    results["telemetry"] = _tier_telemetry()
    return results


def _chip_peaks():
    import jax

    kind = jax.devices()[0].device_kind
    peak = _CHIP_PEAKS.get(kind, (None, None))
    return kind, peak[0], peak[1]


def _device_sync(x) -> float:
    """True device sync: fetch a scalar reduction. (On the axon-tunneled
    TPU, ``jax.block_until_ready`` returns before the work drains — only a
    device->host read is a real barrier.)"""
    import jax.numpy as jnp

    return float(jnp.sum(x if not isinstance(x, tuple) else x[0]))


def bench_mfu():
    """Fixed-iteration MU probes with exact analytic matmul FLOP counts, at
    the bench workload shapes. Two-point timing (N vs 3N iterations, same
    program shape) cancels the constant dispatch + tunnel round-trip
    overhead, so the rate is the kernel's own. MFU = achieved / chip bf16
    peak; HBM utilization uses per-iteration X traffic (the k=9 kernel's
    actual bound — arithmetic intensity ~2k FLOP per fp32 element)."""
    import functools

    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import (_bundle_mask, _update_H, _update_W,
                                        bundle_stacks, bundle_width,
                                        bundled_beta2_update)

    kind, peak_flops, peak_bw = _chip_peaks()
    results = {"device_kind": kind}

    def probe(n, g, k, R, iters, beta):
        bundled = beta == 2.0 and bundle_width(k) > 1

        if bundled:
            # the PRODUCTION beta=2 sweep kernel (nmf_fit_batch_bundled's
            # update): replicate bundles packed into ~128-wide contractions
            per_b = bundle_width(k)
            mask = _bundle_mask(per_b, k)

            @functools.partial(jax.jit, static_argnames=("iters",))
            def batched(H, W, X, iters):
                Hb, Wb = bundle_stacks(H, W, per_b)

                def body(_, hw):
                    return bundled_beta2_update(X, hw[0], hw[1], mask,
                                                0.0, 0.0, 0.0, 0.0)
                return jax.lax.fori_loop(0, iters, body, (Hb, Wb))
        else:
            # the PRODUCTION beta!=2 chain: online KL sweeps run with bf16
            # X/WH/ratio intermediates (ops/nmf.py:resolve_bf16_ratio)
            from cnmf_torch_tpu.ops.nmf import resolve_bf16_ratio

            bf16 = resolve_bf16_ratio(beta, "online")

            @functools.partial(jax.jit, static_argnames=("iters",))
            def batched(H, W, X, iters):
                def solo(h, w):
                    def body(_, hw):
                        h, w = hw
                        h = _update_H(X, h, w, beta, 0.0, 0.0,
                                      bf16_ratio=bf16)
                        w = _update_W(X, h, w, beta, 0.0, 0.0,
                                      bf16_ratio=bf16)
                        return h, w
                    return jax.lax.fori_loop(0, iters, body, (h, w))
                return jax.vmap(solo)(H, W)

        rng = np.random.default_rng(0)
        x_dtype = (jnp.bfloat16 if beta != 2.0 and bf16 else jnp.float32)
        X = jnp.asarray(rng.random((n, g), np.float32) + 0.1, x_dtype)
        H = jnp.asarray(rng.random((R, n, k), np.float32) + 0.1)
        W = jnp.asarray(rng.random((R, k, g), np.float32) + 0.1)
        _device_sync(batched(H, W, X, iters))      # compile short
        _device_sync(batched(H, W, X, 3 * iters))  # compile long

        def timed(n_it):
            t0 = time.perf_counter()
            _device_sync(batched(H, W, X, n_it))
            return time.perf_counter() - t0

        d_short = min(timed(iters) for _ in range(2))
        d_long = min(timed(3 * iters) for _ in range(2))
        dt = max(d_long - d_short, 1e-6)  # time for exactly 2*iters

        if beta == 2.0:
            # H: X@W.T + W@W.T + H@WWT ; W: H.T@X + H.T@H + HtH@W
            flops_iter = 4 * n * g * k + 4 * n * k * k + 4 * g * k * k
        else:
            # H: H@W + R@W.T ; W: H@W + H.T@R (denominators are reductions)
            flops_iter = 8 * n * g * k
        total_flops = flops_iter * 2 * iters * R
        achieved = total_flops / dt
        out = {
            "achieved_tflops": round(achieved / 1e12, 3),
            "kernel_seconds_per_iter_per_replicate":
                round(dt / (2 * iters * R), 6),
            "timed_iters": 2 * iters, "replicates": R,
            # flop model counts USEFUL per-replicate work only — the
            # bundled kernel's masked-Gram padding flops are overhead, so
            # its MFU is conservative
            "kernel": ("bundled" if bundled else
                       "vmapped-bf16" if beta != 2.0 and bf16 else
                       "vmapped"),
        }
        if peak_flops:
            # the vmapped replicate batch is what makes a skinny-k MU
            # update MXU-friendly: X reads amortize across R replicates,
            # so effective contraction width is R*k, not k
            out["mfu"] = round(achieved / peak_flops, 4)
        return out

    results["frobenius_k9"] = probe(10000, 2000, 9, 128, 250, 2.0)
    results["kl_k9"] = probe(10000, 2000, 9, 16, 100, 1.0)
    # k=64 shows the kernel's compute ceiling once the matmuls stop being
    # bandwidth-starved (arithmetic intensity scales with k)
    results["frobenius_k64"] = probe(10000, 2000, 64, 16, 100, 2.0)
    # the sparse ELL KL lane (ISSUE 16): interpret-mode runs (CPU) keep
    # the parity gate but are exempt from any perf expectation
    results["sparse_kl_k9"] = _sparse_kl_probe(
        10000, 2000, 9, 8, 10 if _pallas_interpret_backend() else 50, 0.05)
    results["telemetry"] = _tier_telemetry()
    return results


def _pallas_interpret_backend() -> bool:
    from cnmf_torch_tpu.ops.pallas import pallas_interpret

    return pallas_interpret()


def _sparse_kl_probe(n, g, k, R, iters, density):
    """The ELL β=1 lane at its win case (a ~95%-sparse KL fixture):
    ``ell-jnp`` vs ``ell-pallas`` per-iteration delta plus the dense
    ``vmapped-bf16`` reference, each labelled with the same ``kernel:``
    spelling telemetry and provenance use. Off-TPU the Pallas kernels
    run in interpret mode — the parity gate applies but the timing is
    NOT a perf configuration (``interpret: true`` marks the lane exempt
    from any perf bar)."""
    import functools

    import jax
    import jax.numpy as jnp
    import scipy.sparse as sp

    from cnmf_torch_tpu.ops.nmf import (_update_H, _update_W,
                                        resolve_bf16_ratio)
    from cnmf_torch_tpu.ops.pallas import pallas_interpret
    from cnmf_torch_tpu.ops.sparse import (csr_to_ell, ell_device_put,
                                           ell_to_dense)

    rng = np.random.default_rng(7)
    Xs = sp.random(
        n, g, density=density, format="csr",
        random_state=int(rng.integers(1 << 31)),
        data_rvs=lambda size: (rng.gamma(2.0, 1.0, size)
                               + 0.1).astype(np.float32))
    Xe = ell_device_put(csr_to_ell(Xs))
    H0 = jnp.asarray(rng.random((R, n, k), np.float32) + 0.1)
    W0 = jnp.asarray(rng.random((R, k, g), np.float32) + 0.1)

    @functools.partial(jax.jit, static_argnames=("iters", "use_pallas"))
    def ell_batched(H, W, X, iters, use_pallas=False):
        def solo(h, w):
            def body(_, hw):
                h, w = hw
                h = _update_H(X, h, w, 1.0, 0.0, 0.0,
                              use_pallas=use_pallas)
                w = _update_W(X, h, w, 1.0, 0.0, 0.0,
                              use_pallas=use_pallas)
                return h, w
            return jax.lax.fori_loop(0, iters, body, (h, w))
        return jax.vmap(solo)(H, W)

    bf16 = resolve_bf16_ratio(1.0, "online")
    Xd = jnp.asarray(ell_to_dense(Xe),
                     jnp.bfloat16 if bf16 else jnp.float32)

    @functools.partial(jax.jit, static_argnames=("iters",))
    def dense_batched(H, W, X, iters):
        def solo(h, w):
            def body(_, hw):
                h, w = hw
                h = _update_H(X, h, w, 1.0, 0.0, 0.0, bf16_ratio=bf16)
                w = _update_W(X, h, w, 1.0, 0.0, 0.0, bf16_ratio=bf16)
                return h, w
            return jax.lax.fori_loop(0, iters, body, (h, w))
        return jax.vmap(solo)(H, W)

    def time_lane(run):
        _device_sync(run(H0, W0, iters))
        _device_sync(run(H0, W0, 3 * iters))

        def timed(n_it):
            t0 = time.perf_counter()
            _device_sync(run(H0, W0, n_it))
            return time.perf_counter() - t0

        d_short = min(timed(iters) for _ in range(2))
        d_long = min(timed(3 * iters) for _ in range(2))
        dt = max(d_long - d_short, 1e-6)
        return dt / (2 * iters * R) * 1e6  # us / iter / replicate

    lanes = {
        "ell-jnp": {"us_per_iter_per_replicate": round(time_lane(
            lambda H, W, n_it: ell_batched(H, W, Xe, n_it)), 2)},
        "ell-pallas": {"us_per_iter_per_replicate": round(time_lane(
            lambda H, W, n_it: ell_batched(H, W, Xe, n_it,
                                           use_pallas=True)), 2)},
        ("vmapped-bf16" if bf16 else "vmapped"):
            {"us_per_iter_per_replicate": round(time_lane(
                lambda H, W, n_it: dense_batched(H, W, Xd, n_it)), 2)},
    }
    # parity gate: same init, same iteration count, both ELL kernels —
    # the fused kernels change accumulation order, so f32 tolerance,
    # not bit equality
    Wj = ell_batched(H0, W0, Xe, iters)[1]
    Wp = ell_batched(H0, W0, Xe, iters, use_pallas=True)[1]
    parity = float(jnp.linalg.norm(Wp - Wj)
                   / jnp.maximum(jnp.linalg.norm(Wj), 1e-30))
    us_j = lanes["ell-jnp"]["us_per_iter_per_replicate"]
    us_p = lanes["ell-pallas"]["us_per_iter_per_replicate"]
    return {
        "shape": [n, g, k], "replicates": R,
        "density": density, "ell_width": Xe.width,
        "interpret": bool(pallas_interpret()),
        "lanes": lanes,
        "pallas_vs_jnp_us_delta": round(us_j - us_p, 2),
        "pallas_speedup_vs_jnp": round(us_j / max(us_p, 1e-9), 3),
        "parity_rel_w": parity,
        "parity_ok": bool(parity < 1e-4),
    }


def bench_rowshard():
    """Config 5 scaled to one chip: stream a 1M x 2000 CSR host->HBM
    (shard-wise, no host dense copy) and run row-sharded solver passes."""
    import jax
    import scipy.sparse as sp
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import (nmf_fit_rowsharded,
                                                  prepare_rowsharded)

    n, g, density = 1_000_000, 2000, 0.05
    rng = np.random.default_rng(11)
    blocks = []
    block_rows = 100_000
    for b in range(n // block_rows):
        m = sp.random(block_rows, g, density=density, format="csr",
                      random_state=int(rng.integers(1 << 31)),
                      data_rvs=lambda size: rng.gamma(2.0, 1.0, size).astype(
                          np.float32))
        blocks.append(m.astype(np.float32))
    X = sp.vstack(blocks, format="csr")
    nbytes_sparse = X.data.nbytes + X.indices.nbytes + X.indptr.nbytes
    dense_gb = n * g * 4 / 1e9

    from cnmf_torch_tpu.parallel.streaming import (StreamStats,
                                                   stream_threads)

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
    dense_stats = StreamStats()
    t0 = time.perf_counter()
    Xd, n_orig = prepare_rowsharded(X, mesh, stats=dense_stats)
    _device_sync(Xd)
    stream_s = time.perf_counter() - t0

    # compile pass excluded from the steady-state rate
    nmf_fit_rowsharded(Xd, 9, mesh, seed=1, n_passes=1, n_orig=n_orig)
    n_passes = 3
    t0 = time.perf_counter()
    _, _, err = nmf_fit_rowsharded(Xd, 9, mesh, seed=2, n_passes=n_passes,
                                   n_orig=n_orig)
    solve_s = time.perf_counter() - t0
    assert np.isfinite(err)

    # atlas-scale beta!=2 spectra refit, STAGED: X is already HBM-resident
    # (reuse the solver's staged array) and the whole MU loop is one XLA
    # dispatch (rowshard._refit_w_staged_jit). Two dispatches differing
    # only in max_iter cancel the constant costs, so the reported rate is
    # the on-device per-iteration HBM pass — independent of the host link
    # (round 3 re-streamed X per iteration: ~22 s/iter at this shape on
    # the tunnel)
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cnmf_torch_tpu.parallel.rowshard import _refit_w_staged_jit

    rng_h = np.random.default_rng(3)
    k = 9
    n_pad = int(Xd.shape[0])
    blk = n_pad // (n_pad // min(65536, n_pad))
    while n_pad % blk:
        blk -= 1
    Hd = jax.device_put(
        jnp.asarray(rng_h.gamma(1.0, 1.0, size=(n_pad, k)).astype(
            np.float32)), NamedSharding(mesh, P("cells", None)))
    Wd = jax.device_put(
        jnp.asarray(rng_h.random((k, g), np.float32) + 0.1),
        NamedSharding(mesh, P()))
    refit_iters = 20

    def refit(iters):
        # h_tol=0 disables the early stop -> exactly max_iter MU iterations
        t0 = time.perf_counter()
        W = _refit_w_staged_jit(Xd, Hd, Wd, mesh, "cells", 1.0, iters,
                                jnp.float32(0.0), int(blk), 0.0, 0.0)
        assert np.isfinite(_device_sync(W))  # true device barrier
        return time.perf_counter() - t0

    refit(1)                      # compile short
    refit(1 + refit_iters)        # compile long
    t1 = min(refit(1) for _ in range(2))
    t2 = min(refit(1 + refit_iters) for _ in range(2))
    refit_s = max(t2 - t1, 1e-9)

    # ELL staging measured separately (the beta != 2 sparse path): free the
    # dense shard first so both paths stage into the same headroom
    del Xd, Hd, Wd
    from cnmf_torch_tpu.parallel.rowshard import stream_ell_to_mesh

    ell_stats = StreamStats()
    t0 = time.perf_counter()
    E, _pad = stream_ell_to_mesh(X, mesh, "cells", stats=ell_stats)
    _device_sync(E.vals)
    for leaf in (E.cols, E.rows_t, E.perm_t):
        leaf.block_until_ready()
    ell_s = time.perf_counter() - t0
    ell_bytes = sum(int(leaf.nbytes)
                    for leaf in (E.vals, E.cols, E.rows_t, E.perm_t))
    del E

    return {
        "cells": n, "genes": g, "csr_gb": round(nbytes_sparse / 1e9, 2),
        "stream_threads": stream_threads(),
        # dense staging rate is DENSE-EQUIVALENT GB/s (what a naive
        # densify-then-upload would move) — comparable across rounds;
        # wire bytes are in stream_dense_wire_gb_per_s
        "stream_dense_seconds": round(stream_s, 3),
        "stream_dense_gb_per_s": round(dense_gb / stream_s, 2),
        "stream_dense_wire_gb_per_s": round(dense_stats.gb_per_s(), 2),
        "stream_dense_host_prep_seconds": round(dense_stats.host_prep_s, 3),
        "stream_dense_h2d_seconds": round(dense_stats.h2d_s, 3),
        "stream_dense_overlap_fraction": round(
            dense_stats.overlap_fraction, 3),
        # ELL staging rate is ACTUAL leaf bytes landed per second (the
        # encoding is what crosses the wire on this path)
        "stream_ell_seconds": round(ell_s, 3),
        "stream_ell_gb_per_s": round(ell_bytes / 1e9 / ell_s, 2),
        "stream_ell_host_prep_seconds": round(ell_stats.host_prep_s, 3),
        "stream_ell_h2d_seconds": round(ell_stats.h2d_s, 3),
        "stream_ell_overlap_fraction": round(
            ell_stats.overlap_fraction, 3),
        "solve_seconds_3pass_k9": round(solve_s, 3),
        "cells_per_second": int(n * n_passes / solve_s),
        "staged_kl_refit_seconds_per_mu_iter": round(refit_s / refit_iters, 3),
        "telemetry": _tier_telemetry(),
    }


def bench_grid2d():
    """ISSUE 13 tier: the true 2-D (cells x genes) grid. Measures the
    per-pass statistics-collective wall and the overlap fraction the
    double-buffered dispatch hides (pass-with-overlap vs pass-with-
    barrier vs collectives-only probe — the three programs compute
    bit-identical results, so the difference is pure scheduling), and
    1-D rowshard vs 2-D grid weak scaling at 4 and 8 simulated devices
    (per-device rows held fixed; ideal efficiency 1.0 — on an
    oversubscribed CPU host the simulated devices timeshare cores, so
    the absolute numbers are structural, not hardware, signals)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from cnmf_torch_tpu.ops.nmf import random_init
    from cnmf_torch_tpu.parallel.grid2d import (_grid_pass_jit,
                                                grid_blocks,
                                                measure_collectives,
                                                mesh_grid2d,
                                                stage_x_grid)
    from cnmf_torch_tpu.parallel.rowshard import (_rowshard_pass_jit,
                                                  stream_rows_to_mesh)

    n_dev = len(jax.devices())
    k, g = 10, 1024
    rows_per_dev = 2048
    rng = np.random.default_rng(17)
    results: dict = {"devices": n_dev,
                     "rows_per_device": rows_per_dev, "genes": g, "k": k}
    if n_dev < 8:
        # a pre-pinned smaller device count would collapse the 4-vs-8
        # weak-scaling comparison into one point and fabricate an
        # ideal-looking efficiency — refuse to report that
        results["error"] = (
            "grid2d tier needs >= 8 simulated devices; XLA_FLAGS pinned "
            "%d before the tier could set them" % n_dev)
        return results

    def fixture(n):
        return rng.gamma(2.0, 1.0, size=(n, g)).astype(np.float32)

    # --- collective wall + hidden-overlap fraction on the full grid ---
    n_full = rows_per_dev * n_dev
    X_full = fixture(n_full)
    mesh_full = mesh_grid2d()
    Xd_full, _, _ = stage_x_grid(X_full, mesh_full)
    for beta, label in ((2.0, "frobenius"), (1.0, "kl")):
        results[f"collectives_{label}"] = measure_collectives(
            Xd_full, k, mesh_full, beta=beta)
    del Xd_full

    # --- 1-D vs 2-D weak scaling at 4 and 8 devices -------------------
    h_tol = jnp.float32(0.05)

    def pass_wall(kind, use_dev, beta):
        n = rows_per_dev * use_dev
        X = fixture(n)
        key = jax.random.key(3)
        if kind == "1d":
            mesh = Mesh(np.asarray(jax.devices()[:use_dev]), ("cells",))
            Xd, _ = stream_rows_to_mesh(X, mesh, "cells")
            H0, W0 = random_init(key, n, g, k, float(X.mean()))
            H0 = jax.device_put(H0, NamedSharding(mesh, P("cells", None)))
            W0 = jax.device_put(W0, NamedSharding(mesh, P()))

            def run():
                out = _rowshard_pass_jit(Xd, H0, W0, mesh, "cells", beta,
                                         h_tol, 30, 0.0, 0.0, 0.0, 0.0)
                jax.block_until_ready(out[1])
        else:
            mesh = mesh_grid2d(devices=jax.devices()[:use_dev])
            Xd, _, _ = stage_x_grid(X, mesh)
            H0, W0 = random_init(key, n, g, k, float(X.mean()))
            caxis, gaxis = mesh.axis_names
            H0 = jax.device_put(H0, NamedSharding(mesh, P(caxis, None)))
            W0 = jax.device_put(W0, NamedSharding(mesh, P(None, gaxis)))
            # block counts from the PADDED per-device extents (the tile
            # shapes the kernels actually see) — the kernels reject
            # non-divisors rather than dropping tails
            n_pad, g_pad = int(Xd.shape[0]), int(Xd.shape[1])
            c_dim, g_dim = (int(d) for d in mesh.devices.shape)
            nblk_h = grid_blocks(g_pad // g_dim)
            nblk_w = grid_blocks(n_pad // c_dim)

            def run():
                out = _grid_pass_jit(Xd, H0, W0, mesh, beta, h_tol, 30,
                                     0.0, 0.0, 0.0, 0.0, nblk_h=nblk_h,
                                     nblk_w=nblk_w, overlap=True)
                jax.block_until_ready(out[1])

        run()  # compile
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            run()
            walls.append(time.perf_counter() - t0)
        del Xd
        return float(np.median(walls))

    for beta, label in ((2.0, "frobenius"), (1.0, "kl")):
        row: dict = {}
        for kind in ("1d", "grid2d"):
            t4 = pass_wall(kind, min(4, n_dev), beta)
            t8 = pass_wall(kind, n_dev, beta)
            row[kind] = {
                "pass_s_4dev": round(t4, 4),
                "pass_s_%ddev" % n_dev: round(t8, 4),
                # fixed per-device work: ideal 1.0 (t8 == t4)
                "weak_scaling_efficiency": round(t4 / t8, 3)
                if t8 > 0 else None,
            }
        results[f"weak_scaling_{label}"] = row

    results["caveat"] = (
        "simulated CPU devices timeshare %d host core(s); collective "
        "walls and scaling efficiencies are structural comparisons "
        "(same host, same fixture), not hardware throughput"
        % (os.cpu_count() or 1))
    results["telemetry"] = _tier_telemetry()
    return results


def bench_ingest():
    """ISSUE 10 tier: out-of-core shard-store ingestion. Measures the
    prepare-side store write, the disk->host->device streamed staging
    (read GB/s + disk/h2d overlap + host slab-residency peak vs the
    budget), and the slab-looped pass wall against the resident pass —
    plus the process RSS peak, the signal the "host footprint bounded by
    the budget, not matrix size" claim is judged by."""
    import tempfile

    import jax
    import scipy.sparse as sp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded
    from cnmf_torch_tpu.parallel.streaming import (StreamStats,
                                                   stream_store_sharded)
    from cnmf_torch_tpu.utils import shardstore

    n, g, density = 200_000, 2000, 0.05
    rng = np.random.default_rng(17)
    X = sp.random(n, g, density=density, format="csr", random_state=7,
                  data_rvs=lambda size: rng.gamma(2.0, 1.0, size).astype(
                      np.float32)).astype(np.float32)
    csr_bytes = int(X.data.nbytes + X.indices.nbytes + X.indptr.nbytes)
    budget = 256 << 20
    os.environ[shardstore.OOC_BUDGET_ENV] = str(budget)
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
    out = {"cells": n, "genes": g, "csr_gb": round(csr_bytes / 1e9, 3),
           "budget_bytes": budget}
    store_dir = tempfile.mkdtemp(prefix="bench_ingest_store_")
    try:
        t0 = time.perf_counter()
        shardstore.write_shard_store(store_dir, X)
        write_s = time.perf_counter() - t0
        store = shardstore.open_shard_store(store_dir)
        out.update(
            store_write_seconds=round(write_s, 3),
            store_write_gb_per_s=round(store.store_bytes / 1e9 / write_s, 2),
            store_bytes=int(store.store_bytes),
            slabs=len(store.slabs))

        # streamed resident staging: disk -> host prep -> h2d pipeline
        stats = StreamStats()
        sharding = NamedSharding(mesh, P("cells", None))
        cursor = shardstore.SlabCursor(store)
        t0 = time.perf_counter()
        Xd = stream_store_sharded(cursor, sharding, stats=stats)
        _device_sync(Xd)
        stage_s = time.perf_counter() - t0
        out.update(
            stage_seconds=round(stage_s, 3),
            stage_dense_equiv_gb_per_s=round(n * g * 4 / 1e9 / stage_s, 2),
            disk_read_gb_per_s=round(stats.read_gb_per_s(), 2),
            disk_read_seconds=round(stats.disk_s, 3),
            overlap_fraction=round(stats.overlap_fraction, 3),
            host_peak_bytes=int(stats.host_peak_bytes),
            host_peak_under_budget=bool(stats.host_peak_bytes <= budget))

        # resident pass wall (store-backed staging, bit-identical programs)
        n_passes = 3
        nmf_fit_rowsharded(Xd, 9, mesh, seed=1, n_passes=1, n_orig=n)
        t0 = time.perf_counter()
        _, _, err = nmf_fit_rowsharded(Xd, 9, mesh, seed=2,
                                       n_passes=n_passes, n_orig=n)
        resident_s = time.perf_counter() - t0
        assert np.isfinite(err)
        del Xd

        # slab-looped pass wall: per-device shard forced over the budget
        # so every pass re-streams X group-wise from the store
        os.environ[shardstore.OOC_SHARD_BYTES_ENV] = str(budget // 4)
        try:
            t0 = time.perf_counter()
            _, _, err2 = nmf_fit_rowsharded(store, 9, mesh, seed=2,
                                            n_passes=n_passes)
            ooc_s = time.perf_counter() - t0
        finally:
            os.environ.pop(shardstore.OOC_SHARD_BYTES_ENV, None)
        assert np.isfinite(err2)
        out.update(
            resident_pass_seconds=round(resident_s, 3),
            slab_loop_pass_seconds=round(ooc_s, 3),
            slab_loop_overhead_x=round(ooc_s / max(resident_s, 1e-9), 2),
            host_rss_peak_bytes=int(shardstore.host_rss_peak_bytes()),
            telemetry=_tier_telemetry())
        return out
    finally:
        os.environ.pop(shardstore.OOC_BUDGET_ENV, None)
        shardstore.remove_store(store_dir)


def bench_serve():
    """ISSUE 12 tier: the warm serving daemon under sustained concurrent
    load. Builds a consensus-complete run, serves its reference through
    the REAL unix-socket HTTP daemon, and drives client threads at it —
    reporting sustained QPS, the p50/p95/p99 latency histogram (shared
    helper: utils/profiling.latency_summary), cross-request batching
    engagement from the daemon's telemetry, and the zero-compiles-after-
    warmup program-cache claim."""
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.serving import (ProjectionService, ServeClient,
                                        ServeDaemon, load_reference)
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.profiling import latency_summary
    from cnmf_torch_tpu.utils.telemetry import read_events

    os.environ.setdefault("CNMF_TPU_TELEMETRY", "1")
    # the observability plane rides the measured load (ISSUE 18): the
    # reported QPS/latency INCLUDE live metrics publication, and the
    # scraped /metrics histogram is attached to the result so the two
    # latency surfaces (client-side stopwatch, daemon-side histogram)
    # can be compared in one output
    os.environ.setdefault("CNMF_TPU_METRICS", "1")
    n, g, k = 400, 200, 5
    workdir = tempfile.mkdtemp(prefix="bench_serve_")
    try:
        save_df_to_npz(synthetic_counts_df(n, g, k_true=k, seed=23),
                       os.path.join(workdir, "counts.df.npz"))
        obj = cNMF(output_dir=workdir, name="srv")
        obj.prepare(os.path.join(workdir, "counts.df.npz"),
                    components=[k], n_iter=20, seed=23,
                    num_highvar_genes=150)
        obj.factorize()
        obj.combine()
        obj.consensus(k=k, density_threshold=2.0, show_clustering=False)
        run_dir = os.path.join(workdir, "srv")

        ref = load_reference(run_dir)
        from cnmf_torch_tpu.utils.telemetry import EventLog

        events = EventLog(os.path.join(run_dir, "cnmf_tmp",
                                       "srv.serve.events.jsonl"),
                          manifest_extra={"run_name": "srv",
                                          "role": "serve"})
        svc = ProjectionService(ref, events=events)
        sock = os.path.join(workdir, "serve.sock")
        t0 = time.perf_counter()
        daemon = ServeDaemon(svc, socket_path=sock).start()
        warm_s = time.perf_counter() - t0

        n_clients, reqs_per_client = 6, 60
        sizes = (16, 32, 64, 96, 128)
        rng = np.random.default_rng(29)
        queries = [rng.gamma(1.0, 1.0, size=(s, ref.n_genes))
                   .astype(np.float32) for s in sizes]

        def run_client(idx, n_reqs, record):
            cli = ServeClient(socket_path=sock, timeout=120.0)
            for j in range(n_reqs):
                X = queries[(idx + j) % len(queries)]
                t1 = time.perf_counter()
                cli.project(X, tenant=f"tenant{idx}")
                if record is not None:
                    record.append((time.perf_counter() - t1) * 1e3)

        # warmup traffic (not timed): fills the warm-start cache and
        # proves the program buckets are hot
        warm_threads = [threading.Thread(target=run_client,
                                         args=(i, 5, None))
                        for i in range(n_clients)]
        for t in warm_threads:
            t.start()
        for t in warm_threads:
            t.join()

        lat_by_client = [[] for _ in range(n_clients)]
        t0 = time.perf_counter()
        threads = [threading.Thread(target=run_client,
                                    args=(i, reqs_per_client,
                                          lat_by_client[i]))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        lat_ms = [v for lats in lat_by_client for v in lats]

        stats = svc.stats()
        # solo-dispatch comparator: the same request stream without
        # batching or the daemon (direct refit-path dispatch wall)
        from cnmf_torch_tpu.ops.nmf import fit_h

        X0 = queries[2]
        fit_h(X0, ref.W, chunk_size=ref.chunk_size,
              chunk_max_iter=ref.chunk_max_iter, h_tol=ref.h_tol,
              beta=ref.beta)
        t1 = time.perf_counter()
        for _ in range(10):
            fit_h(X0, ref.W, chunk_size=ref.chunk_size,
                  chunk_max_iter=ref.chunk_max_iter, h_tol=ref.h_tol,
                  beta=ref.beta)
        solo_ms = (time.perf_counter() - t1) / 10 * 1e3

        # scrape the live registry through the daemon's own endpoint
        # before shutdown — the exposition must parse back, and its
        # request histogram is the attached serve-side latency surface
        from cnmf_torch_tpu.obs.metrics import parse_exposition

        scraped = parse_exposition(
            ServeClient(socket_path=sock, timeout=60.0).metrics())
        hist = {
            "buckets": {labels[0][1]: int(v)
                        for (name, labels), v in
                        scraped["samples"].items()
                        if name == "cnmf_serve_request_ms_bucket"},
            "count": scraped["samples"].get(
                ("cnmf_serve_request_ms_count", ()), 0),
            "sum_ms": round(scraped["samples"].get(
                ("cnmf_serve_request_ms_sum", ()), 0.0), 3),
        }

        daemon.close()
        ev_path = os.path.join(run_dir, "cnmf_tmp",
                               "srv.serve.events.jsonl")
        batch_events = [e for e in read_events(ev_path)
                        if e["t"] == "serve_batch"] \
            if os.path.exists(ev_path) else []
        multi = sum(1 for e in batch_events if e["requests"] > 1)
        out = {
            "reference": {"k": ref.k, "genes": ref.n_genes,
                          "beta": ref.beta},
            "clients": n_clients,
            "requests": len(lat_ms),
            "request_rows": list(sizes),
            "warmup_seconds": round(warm_s, 3),
            "programs_warmed": stats["programs_warmed"],
            "cold_dispatches_after_warmup":
                stats["cold_dispatches_after_warmup"],
            "qps": round(len(lat_ms) / wall, 1),
            "latency_ms": {kk: (round(v, 3) if isinstance(v, float)
                                else v)
                           for kk, v in latency_summary(lat_ms).items()},
            "solo_dispatch_ms": round(solo_ms, 3),
            "batches": stats["batches"],
            "mean_lanes_per_batch": stats["mean_lanes"],
            "max_lanes_per_batch": stats["max_lanes"],
            "batched_fraction": stats["batched_fraction"],
            "multi_request_batches_telemetry": multi,
            "warm_started_requests": stats["warm_started"],
            "scraped_request_ms_histogram": hist,
            "latency_samples_kept": stats.get("latency_samples_kept"),
            "latency_samples_dropped":
                stats.get("latency_samples_dropped"),
            "telemetry": _tier_telemetry(),
        }
        # the acceptance gates, surfaced as booleans the driver can read
        out["p50_under_10ms"] = bool(
            out["latency_ms"].get("p50", 1e9) <= 10.0)
        out["zero_compiles_after_warmup"] = bool(
            stats["cold_dispatches_after_warmup"] == 0)
        out["batching_engaged"] = bool(multi > 0)
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_fleet():
    """ISSUE 20 tier: the replicated serving fleet under sustained
    concurrent load at 1, 2, and 4 replicas — the same multi-tenant
    request stream routed through the consistent-hash router over REAL
    serve daemon subprocesses, reporting sustained QPS and the
    p50/p95/p99 client-side latency histogram per fleet size (shared
    helper: utils/profiling.latency_summary). On a single host the
    replicas share the device, so this measures routing + process
    overhead and tail behavior, not linear scaling."""
    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.serving.fleet import FleetClient, FleetDaemon, \
        FleetRouter
    from cnmf_torch_tpu.utils import save_df_to_npz
    from cnmf_torch_tpu.utils.profiling import latency_summary

    # replica subprocesses inherit this env; keep their telemetry off
    # (router-side accounting only) and the shared XLA compile cache on
    # so fleet warmup measures process + reference staging, not
    # recompiles
    os.environ.setdefault("CNMF_TPU_TELEMETRY", "0")
    n, g, k = 400, 200, 5
    workdir = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        save_df_to_npz(synthetic_counts_df(n, g, k_true=k, seed=23),
                       os.path.join(workdir, "counts.df.npz"))
        obj = cNMF(output_dir=workdir, name="flt")
        obj.prepare(os.path.join(workdir, "counts.df.npz"),
                    components=[k], n_iter=20, seed=23,
                    num_highvar_genes=150)
        obj.factorize()
        obj.combine()
        obj.consensus(k=k, density_threshold=2.0, show_clustering=False)
        run_dir = os.path.join(workdir, "flt")

        n_clients, reqs_per_client = 6, 40
        sizes = (16, 32, 64, 96, 128)
        rng = np.random.default_rng(29)
        n_genes = 150
        queries = [rng.gamma(1.0, 1.0, size=(s, n_genes))
                   .astype(np.float32) for s in sizes]

        def run_client(sock, idx, n_reqs, record):
            cli = FleetClient(socket_path=sock, timeout=180.0)
            for j in range(n_reqs):
                X = queries[(idx + j) % len(queries)]
                t1 = time.perf_counter()
                cli.project(X, tenant=f"tenant{idx}",
                            request_id=f"b-{idx}-{j}")
                if record is not None:
                    record.append((time.perf_counter() - t1) * 1e3)

        out = {"clients": n_clients,
               "requests_per_fleet": n_clients * reqs_per_client,
               "request_rows": list(sizes), "fleets": {}}
        for replicas in (1, 2, 4):
            router = FleetRouter(run_dir, replicas=replicas)
            sock = os.path.join(workdir, f"fleet{replicas}.sock")
            daemon = FleetDaemon(router, socket_path=sock)
            t0 = time.perf_counter()
            daemon.start()
            warm_s = time.perf_counter() - t0
            try:
                # untimed warmup: every tenant's route + program warm
                warm = [threading.Thread(target=run_client,
                                         args=(sock, i, 4, None))
                        for i in range(n_clients)]
                for t in warm:
                    t.start()
                for t in warm:
                    t.join()
                lat_by_client = [[] for _ in range(n_clients)]
                t0 = time.perf_counter()
                threads = [threading.Thread(
                    target=run_client,
                    args=(sock, i, reqs_per_client, lat_by_client[i]))
                    for i in range(n_clients)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                lat_ms = [v for lats in lat_by_client for v in lats]
                stats = router.stats()
            finally:
                daemon.close()
            shares = sorted(r["requests"] for r in stats["replicas"])
            out["fleets"][str(replicas)] = {
                "replicas": replicas,
                "fleet_warmup_seconds": round(warm_s, 3),
                "qps": round(len(lat_ms) / wall, 1),
                "latency_ms": {kk: (round(v, 3) if isinstance(v, float)
                                    else v)
                               for kk, v in
                               latency_summary(lat_ms).items()},
                "requests_ok": stats["ok"],
                "router_retries": stats["retries"],
                "requests_by_replica": shares,
            }
        one = out["fleets"]["1"]["qps"]
        out["qps_1_replica"] = one
        out["qps_2_replicas"] = out["fleets"]["2"]["qps"]
        out["qps_4_replicas"] = out["fleets"]["4"]["qps"]
        out["p99_ms_2_replicas"] = \
            out["fleets"]["2"]["latency_ms"].get("p99")
        out["telemetry"] = _tier_telemetry()
        # acceptance gates as booleans the driver can read
        out["all_requests_ok"] = bool(all(
            f["requests_ok"] >= n_clients * reqs_per_client
            for f in out["fleets"].values()))
        out["load_spread_over_replicas"] = bool(
            sum(1 for s in out["fleets"]["4"]["requests_by_replica"]
                if s > 0) >= 2)
        return out
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def bench_harmony():
    """Config 4 shape (Baron islets: ~8.5k cells, 4 donors): Preprocess
    (HVG -> PCA -> Harmony -> gene-space MOE ridge) -> cNMF e2e."""
    import pandas as pd

    from cnmf_torch_tpu import Preprocess, cNMF
    from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite

    os.environ.setdefault("CNMF_TPU_TELEMETRY", "1")
    n, g, k_true, n_batches = 8500, 5000, 8, 4
    rng = np.random.default_rng(21)
    usage = rng.dirichlet(np.ones(k_true) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(k_true, g)) * 50.0 / g
    batch = rng.integers(0, n_batches, size=n)
    # per-batch multiplicative gene effects — what Harmony removes
    batch_fx = rng.gamma(20.0, 0.05, size=(n_batches, g))
    counts = rng.poisson(usage @ spectra * 300.0 * batch_fx[batch])
    counts = counts.astype(np.float32)
    counts[counts.sum(axis=1) == 0, 0] = 1.0

    import scipy.sparse as sp
    adata = AnnDataLite(
        X=sp.csr_matrix(counts),
        obs=pd.DataFrame({"batch": pd.Categorical(batch.astype(str))},
                         index=[f"c{i}" for i in range(n)]),
        var=pd.DataFrame(index=[f"g{j}" for j in range(g)]))

    workdir = tempfile.mkdtemp(prefix="bench_harmony_")
    base = os.path.join(workdir, "islets_pre")
    t0 = time.perf_counter()
    p = Preprocess(random_seed=14)
    p.preprocess_for_cnmf(adata, harmony_vars="batch", n_top_rna_genes=2000,
                          librarysize_targetsum=1e6, save_output_base=base)
    preprocess_s = time.perf_counter() - t0
    counts_fn = base + ".Corrected.HVG.Varnorm.h5ad"
    tpm_fn = base + ".TP10K.h5ad"
    genes_fn = base + ".Corrected.HVGs.txt"

    obj = cNMF(output_dir=workdir, name="islets")
    t0 = time.perf_counter()
    obj.prepare(counts_fn, components=[8], n_iter=30, seed=14,
                tpm_fn=tpm_fn, genes_file=genes_fn)
    obj.factorize()
    obj.combine()
    try:
        obj.consensus(k=8, density_threshold=0.5, show_clustering=False)
    except RuntimeError:
        obj.consensus(k=8, density_threshold=2.0, show_clustering=False)
    cnmf_s = time.perf_counter() - t0
    telemetry = _tier_telemetry(workdir, "islets")
    shutil.rmtree(workdir)
    return {
        "cells": n, "genes": g, "batches": n_batches,
        "preprocess_seconds": round(preprocess_s, 3),
        "cnmf_seconds": round(cnmf_s, 3),
        "e2e_seconds": round(preprocess_s + cnmf_s, 3),
        "telemetry": telemetry,
    }


# ---------------------------------------------------------------------------
# orchestrator
# ---------------------------------------------------------------------------

def run_tier_subprocess(tier: str) -> dict:
    out_fd, out_path = tempfile.mkstemp(suffix=".json", prefix=f"bench_{tier}_")
    os.close(out_fd)
    cmd = [sys.executable, os.path.abspath(__file__), "--tier", tier,
           "--out", out_path]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=TIER_TIMEOUT_S[tier],
                              cwd=os.path.dirname(os.path.abspath(__file__)))
        rc = proc.returncode
        stderr_tail = proc.stderr[-2000:] if proc.stderr else ""
    except subprocess.TimeoutExpired as e:
        rc = -1
        stderr_tail = f"TIMEOUT after {TIER_TIMEOUT_S[tier]}s: " + (
            (e.stderr or b"")[-1500:].decode("utf-8", "replace")
            if isinstance(e.stderr, bytes) else str(e.stderr or "")[-1500:])
    wall = round(time.perf_counter() - t0, 1)
    result: dict
    if rc == 0 and os.path.exists(out_path) and os.path.getsize(out_path):
        with open(out_path) as f:
            result = json.load(f)
        result["tier_wall_seconds"] = wall
    else:
        result = {"error": f"tier subprocess rc={rc}", "rc": rc,
                  "tier_wall_seconds": wall, "stderr_tail": stderr_tail}
    try:
        os.unlink(out_path)
    except OSError:
        pass
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--tier", choices=TIERS)
    parser.add_argument("--out")
    parser.add_argument("--skip", nargs="*", default=[],
                        help="tiers to skip (debugging)")
    parser.add_argument("--only", nargs="*", default=None, choices=TIERS,
                        metavar="TIER",
                        help="run only these tiers (a cheap subset for the "
                             "perf-regression gate; default: all tiers)")
    parser.add_argument("--json-out", dest="json_out",
                        help="also write the results as a schema-versioned "
                             "bench snapshot (obs/regress.py cnmf-bench "
                             "schema, keyed by the autotune device "
                             "fingerprint) — the format cnmf-tpu benchdiff "
                             "and scripts/perf_gate.py consume")
    parser.add_argument("--label",
                        help="free-form label recorded in the --json-out "
                             "snapshot (e.g. a git rev)")
    args = parser.parse_args()

    if args.tier:
        if not args.out:
            parser.error("--tier requires --out (checked before the tier "
                         "runs so a multi-minute measurement is never lost)")
        # persistent XLA compile cache: the same production default the CLI
        # enables — steady-state numbers, compile_overhead_seconds still
        # reports whatever compilation actually happened this run
        from cnmf_torch_tpu.utils.compile_cache import (
            enable_persistent_compilation_cache,
        )

        enable_persistent_compilation_cache()
        fn = {"north_star": bench_north_star, "anchor": bench_anchor,
              "kl": bench_kl, "accel": bench_accel, "mfu": bench_mfu,
              "rowshard": bench_rowshard, "grid2d": bench_grid2d,
              "ingest": bench_ingest, "harmony": bench_harmony,
              "serve": bench_serve, "fleet": bench_fleet,
              "sketch": bench_sketch, "plan": bench_plan}[args.tier]
        result = fn()
        with open(args.out, "w") as f:
            json.dump(result, f)
        return

    partial_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_partial.json")
    results: dict = {}
    selected = args.only if args.only else TIERS
    for tier in selected:
        if tier in args.skip:
            continue
        print(f"[bench] running tier {tier} ...", file=sys.stderr, flush=True)
        results[tier] = run_tier_subprocess(tier)
        # land partial results after EVERY tier: a later crash (or an
        # orchestrator kill) cannot erase what already completed
        with open(partial_path, "w") as f:
            json.dump(results, f, indent=1)
        status = ("ok" if "error" not in results[tier]
                  else results[tier]["error"])
        print(f"[bench] tier {tier}: {status} "
              f"({results[tier].get('tier_wall_seconds')}s)",
              file=sys.stderr, flush=True)

    ns = results.get("north_star", {})
    if "e2e_seconds" in ns:
        value = ns["e2e_seconds"]
        vs = ns.get("vs_baseline")
    else:
        value = None
        vs = None
    mfu = results.get("mfu", {})
    print(json.dumps({
        "metric": "pbmc10k_factorize_consensus_e2e",
        "value": value,
        "unit": ("seconds (factorize K=5..13 x 100 online-MU runs of "
                 "10000x2000 incl. compiles, + combine + consensus k=9; "
                 "warm_e2e_seconds/vs_baseline_warm are the steady-state "
                 "second pass of the same stages)"),
        "vs_baseline": vs,
        "warm_e2e_seconds": ns.get("warm_e2e_seconds"),
        "vs_baseline_warm": ns.get("vs_baseline_warm"),
        "tiers": results,
        "mfu_frobenius_k9": mfu.get("frobenius_k9", {}).get("mfu"),
        "achieved_tflops_frobenius_k9":
            mfu.get("frobenius_k9", {}).get("achieved_tflops"),
        "caveats": ("synthetic counts at the reference datasets' shapes "
                    "(the datasets are not redistributable here); the "
                    "north-star baseline is the reference's PBMC3k "
                    "2.0 s/run anchor extrapolated linearly in rows and "
                    "runs (6667 s), consensus excluded; each tier runs "
                    "fault-isolated in its own subprocess; pipeline tiers "
                    "(north_star, harmony) time telemetry-ENABLED "
                    "programs — telemetry.enabled_during_run marks the "
                    "measurement condition for cross-round comparisons"),
    }))

    if args.json_out:
        # schema-versioned snapshot for the regression observatory: same
        # validation surface as telemetry events, keyed by the autotune
        # device fingerprint so benchdiff never compares across machines
        import time as _time

        from cnmf_torch_tpu.obs.regress import build_snapshot, save_snapshot
        from cnmf_torch_tpu.utils.autotune import device_fingerprint

        snap = build_snapshot(results, fingerprint=device_fingerprint(),
                              created=_time.time(), label=args.label)
        save_snapshot(snap, args.json_out)
        print(f"[bench] snapshot written to {args.json_out}",
              file=sys.stderr, flush=True)


if __name__ == "__main__":
    main()
