"""Sliding-window SLO tracking for the serving tier (ISSUE 18).

The tracker answers ONE question, live: over the last
``CNMF_TPU_SLO_WINDOW_S`` seconds, did the daemon hold its latency and
error targets? ``CNMF_TPU_SLO_P99_MS`` arms it (unset/0 = off); each
completed request records (timestamp, total latency, ok-or-not); and
:meth:`SloTracker.evaluate` reduces the window to a verdict the daemon
surfaces in ``/metrics``, ``/healthz`` (degraded-when-burning), and the
report's SLO section — the probe a fleet chaos smoke asserts against.

Window semantics (pinned by test): an observation recorded at time
``t`` belongs to the window evaluated at ``now`` iff
``t > now - window_s`` — strictly newer than the left edge, so an
observation exactly ``window_s`` old has just aged out. p99 uses the
same linear-interpolated :func:`~cnmf_torch_tpu.utils.profiling.
percentile` as the report and bench, not a third variant.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..utils.envknobs import env_float
from ..utils.profiling import percentile

__all__ = ["SLO_P99_ENV", "SLO_WINDOW_ENV", "SloTracker",
           "tracker_from_env"]

SLO_P99_ENV = "CNMF_TPU_SLO_P99_MS"
SLO_WINDOW_ENV = "CNMF_TPU_SLO_WINDOW_S"

# error budget: the fraction of windowed requests allowed to end
# not-ok (shed/poison/error) before the SLO burns. A constructor
# parameter rather than a knob — the two registered knobs cover the
# latency target and window; revisit if fleets need to tune this.
DEFAULT_MAX_ERROR_RATE = 0.01


class SloTracker:
    """Thread-safe sliding-window SLO evaluator."""

    def __init__(self, target_p99_ms: float, window_s: float = 300.0,
                 max_error_rate: float = DEFAULT_MAX_ERROR_RATE,
                 clock=time.monotonic):
        if not target_p99_ms > 0:
            raise ValueError("target_p99_ms must be > 0, got %r"
                             % (target_p99_ms,))
        if not window_s > 0:
            raise ValueError("window_s must be > 0, got %r" % (window_s,))
        self.target_p99_ms = float(target_p99_ms)
        self.window_s = float(window_s)
        self.max_error_rate = float(max_error_rate)
        self._clock = clock
        self._lock = threading.Lock()
        self._obs: deque = deque()  # (t, latency_ms, ok)

    def _evict(self, now: float) -> None:
        edge = now - self.window_s
        while self._obs and self._obs[0][0] <= edge:
            self._obs.popleft()

    def record(self, latency_ms: float, ok: bool = True,
               now=None) -> None:
        t = self._clock() if now is None else float(now)
        with self._lock:
            self._obs.append((t, float(latency_ms), bool(ok)))
            self._evict(t)

    def evaluate(self, now=None) -> dict:
        """The windowed verdict: request/error counts, measured p99,
        and ``burning`` (latency target missed OR error budget blown).
        An empty window is trivially not burning — no evidence, no
        alarm."""
        t = self._clock() if now is None else float(now)
        with self._lock:
            self._evict(t)
            obs = list(self._obs)
        n = len(obs)
        errors = sum(1 for _, _, ok in obs if not ok)
        out = {
            "target_p99_ms": self.target_p99_ms,
            "window_s": self.window_s,
            "max_error_rate": self.max_error_rate,
            "requests": n,
            "errors": errors,
        }
        if n == 0:
            out.update(p99_ms=None, error_rate=0.0, burning=False,
                       ok=True)
            return out
        p99 = percentile([lat for _, lat, _ in obs], 99.0)
        error_rate = errors / n
        burning = (p99 > self.target_p99_ms
                   or error_rate > self.max_error_rate)
        out.update(p99_ms=round(p99, 3),
                   error_rate=round(error_rate, 6),
                   burning=burning, ok=not burning)
        return out


def tracker_from_env():
    """Build the tracker the knobs describe, or ``None`` when
    ``CNMF_TPU_SLO_P99_MS`` is unset/0 (SLO tracking off)."""
    target = env_float(SLO_P99_ENV, 0.0, lo=0.0)
    if target <= 0:
        return None
    window = env_float(SLO_WINDOW_ENV, 300.0, lo=1.0)
    return SloTracker(target, window_s=window)
