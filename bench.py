"""Headline benchmark: the reference's PBMC3k factorize workload.

The only wall-clock number the reference publishes is "~4 minutes" for the
PBMC3k tutorial factorize sweep — 2,700 cells x 2,000 HVGs, K=5..10 x
n_iter=20 = 120 online-MU NMF runs on 4 CPU workers via GNU parallel
(/root/reference/Tutorials/analyze_pbmc_example_data.ipynb, "Using GNU
parallel" cell; BASELINE.md). This benchmark runs the same-shaped sweep as
batched XLA programs (one vmapped call per K) on the local device(s) and
reports wall-clock vs that 240 s anchor.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_SECONDS = 240.0  # reference: 4 min, 4 CPU workers, same workload
N_CELLS, N_GENES = 2700, 2000
KS = [5, 6, 7, 8, 9, 10]
N_ITER = 20


def synthetic_pbmc_like(n=N_CELLS, g=N_GENES, k_true=12, seed=0):
    """Structured counts with PBMC3k's shape: sparse-ish Poisson draws from
    a low-rank GEP model, variance-scaled the way prepare() feeds the
    solver (unit-variance genes, no centering)."""
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k_true, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * 400.0).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    std = X.std(axis=0, ddof=1)
    std[std == 0] = 1.0
    return X / std


def main():
    import jax.numpy as jnp

    from cnmf_torch_tpu.parallel import default_mesh, replicate_sweep

    # one host->HBM transfer, shared by every per-K sweep program
    X = jnp.asarray(synthetic_pbmc_like())
    mesh = default_mesh()
    master = np.random.RandomState(14)
    seeds_per_k = {
        k: master.randint(1, 2 ** 31 - 1, size=N_ITER).tolist() for k in KS
    }

    # warmup: compile every measured (R, k) shape (vmap batch size is part
    # of the compiled shape) so the sweep measures steady-state solver cost
    # — the reference's 4-minute figure likewise excludes env startup
    for k in KS:
        replicate_sweep(X, [1] * N_ITER, k, mode="online",
                        online_chunk_size=5000, online_chunk_max_iter=1000,
                        mesh=mesh)

    t0 = time.perf_counter()
    total_err = 0.0
    # dispatch every K's program before fetching any result: device->host
    # copies of early Ks overlap later Ks' compute (factorize() pipelines
    # its sweep the same way)
    pending = []
    for k in KS:
        spectra_d, _, errs_d = replicate_sweep(
            X, seeds_per_k[k], k, mode="online", online_chunk_size=5000,
            online_chunk_max_iter=1000, mesh=mesh, fetch=False)
        pending.append((k, spectra_d, errs_d))
    for k, spectra_d, errs_d in pending:
        spectra = np.asarray(spectra_d)
        assert spectra.shape == (N_ITER, k, N_GENES)
        total_err += float(np.sum(np.asarray(errs_d)))
    elapsed = time.perf_counter() - t0
    assert np.isfinite(total_err)

    print(json.dumps({
        "metric": "pbmc3k_factorize_sweep_wallclock",
        "value": round(elapsed, 3),
        "unit": "seconds (120 online-MU NMF runs, 2700x2000, K=5..10 x 20)",
        "vs_baseline": round(BASELINE_SECONDS / elapsed, 2),
    }))


if __name__ == "__main__":
    main()
