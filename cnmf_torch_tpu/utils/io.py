"""Artifact serializers and count-matrix loaders.

The DataFrame-as-npz container is the reference pipeline's universal
intermediate format (``/root/reference/src/cnmf/cnmf.py:32-41``): a compressed
``.npz`` holding ``data``, ``index``, and ``columns`` arrays. We keep the
byte-level format identical so artifacts are interchangeable between the two
implementations (and the reference's golden-file test style applies directly).

Count loading mirrors ``cNMF.prepare``'s dispatch on file extension
(``cnmf.py:518-537``): ``.h5ad``, 10x ``.mtx``/``.mtx.gz`` directories,
``.df.npz`` DataFrames, and tab-delimited text.
"""

from __future__ import annotations

import errno
import gzip
import os

import numpy as np
import pandas as pd
import scipy.sparse as sp

from ..runtime.faults import maybe_tear
from .anndata_lite import AnnDataLite, atomic_artifact, read_h5ad, write_h5ad

__all__ = [
    "save_df_to_npz",
    "save_df_to_text",
    "load_df_from_npz",
    "atomic_artifact",
    "check_dir_exists",
    "read_10x_mtx",
    "load_counts",
    "read_h5ad",
    "write_h5ad",
    "AnnDataLite",
]


def save_df_to_npz(obj: pd.DataFrame, filename: str, compress: bool | None = None):
    """Same container as the reference serializer (``cnmf.py:32-33``): an
    npz holding ``data``/``index``/``columns`` arrays, loadable by either
    implementation's ``load_df_from_npz`` (``np.load`` reads compressed and
    stored members alike).

    ``compress=None`` (default) compresses small artifacts like the
    reference but STORES matrices over 2 MB: single-threaded deflate on a
    merged-spectra matrix costs ~20x its write time for ~6% size (dense
    f64 spectra barely compress), and combine's wall was mostly zlib.

    Atomic: the bytes land in a same-directory temp file and ``os.replace``
    onto ``filename`` — a worker killed mid-write leaves no half-written
    artifact that ``--skip-completed-runs`` or ``combine`` could mistake
    for a completed run (the provenance-YAML pattern, models/cnmf.py).
    """
    if compress is None:
        compress = obj.values.nbytes <= (2 << 20)
    writer = np.savez_compressed if compress else np.savez
    with atomic_artifact(filename) as tmp:
        # an open file object: np.savez must not append '.npz' to the
        # extension-less temp name
        with open(tmp, "wb") as fh:
            writer(
                fh,
                data=obj.values,
                index=obj.index.values,
                columns=obj.columns.values,
            )
    maybe_tear(filename)  # fault harness: no-op unless CNMF_TPU_FAULT_SPEC


def save_df_to_text(obj: pd.DataFrame, filename: str):
    with atomic_artifact(filename) as tmp:
        obj.to_csv(tmp, sep="\t")
    maybe_tear(filename)


def load_df_from_npz(filename: str) -> pd.DataFrame:
    with np.load(filename, allow_pickle=True) as f:
        obj = pd.DataFrame(**f)
    return obj


def check_dir_exists(path: str):
    """mkdir -p semantics (``cnmf.py:43-51``)."""
    try:
        os.makedirs(path)
    except OSError as exc:
        if exc.errno != errno.EEXIST:
            raise


def _open_maybe_gz(path: str, mode="rt"):
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def _find_10x_sidecar(counts_dir: str, stems) -> str | None:
    for stem in stems:
        for suffix in ("", ".gz"):
            p = os.path.join(counts_dir, stem + suffix)
            if os.path.exists(p):
                return p
    return None


def read_10x_mtx(path: str) -> AnnDataLite:
    """Load a 10x-Genomics-format mtx directory (``sc.read_10x_mtx`` contract,
    used at ``cnmf.py:520-522``): ``matrix.mtx[.gz]`` plus
    ``features.tsv[.gz]``/``genes.tsv[.gz]`` and ``barcodes.tsv[.gz]``.

    The matrix on disk is genes x cells; returns cells x genes CSR.
    """
    mtx_fn = _find_10x_sidecar(path, ["matrix.mtx"])
    if mtx_fn is None:
        raise FileNotFoundError(f"no matrix.mtx[.gz] in {path}")
    from ..native import read_mtx

    X = read_mtx(mtx_fn).T.tocsr()

    genes_fn = _find_10x_sidecar(path, ["features.tsv", "genes.tsv"])
    barcodes_fn = _find_10x_sidecar(path, ["barcodes.tsv"])
    if genes_fn is None or barcodes_fn is None:
        raise FileNotFoundError(f"missing features/genes or barcodes tsv in {path}")

    genes = pd.read_csv(genes_fn, sep="\t", header=None)
    barcodes = pd.read_csv(barcodes_fn, sep="\t", header=None)
    # 10x feature files carry [id, symbol, (type)]; index by symbol when
    # available, matching scanpy's default var_names='gene_symbols' fallback
    # to unique ids. We use symbols if present else ids.
    sym_col = 1 if genes.shape[1] > 1 else 0
    var = pd.DataFrame({"gene_ids": genes.iloc[:, 0].values} if genes.shape[1] > 1 else {},
                       index=pd.Index(genes.iloc[:, sym_col].astype(str).values))
    obs = pd.DataFrame(index=pd.Index(barcodes.iloc[:, 0].astype(str).values))
    return AnnDataLite(X, obs=obs, var=var)


def load_counts(counts_fn: str, densify: bool = False) -> AnnDataLite:
    """Extension-dispatched counts loader (``cnmf.py:518-541``)."""
    if counts_fn.endswith(".h5ad"):
        adata = read_h5ad(counts_fn)
    elif counts_fn.endswith(".mtx") or counts_fn.endswith(".mtx.gz"):
        adata = read_10x_mtx(os.path.dirname(counts_fn))
    else:
        if counts_fn.endswith(".npz"):
            df = load_df_from_npz(counts_fn)
        else:
            df = pd.read_csv(counts_fn, sep="\t", index_col=0)
        X = df.values if densify else sp.csr_matrix(df.values)
        adata = AnnDataLite(
            X,
            obs=pd.DataFrame(index=df.index),
            var=pd.DataFrame(index=df.columns),
        )
    if sp.issparse(adata.X) and densify:
        adata.X = np.asarray(adata.X.todense())
    return adata
