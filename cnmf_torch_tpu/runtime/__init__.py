"""Execution-resilience runtime: fault injection, quarantine/retry,
mid-run checkpoints, and elastic degraded-mesh recovery.

Four modules, imported explicitly by their consumers (this package pulls
in no heavy dependencies at import time):

  * :mod:`.faults` — the deterministic fault-injection harness behind
    ``CNMF_TPU_FAULT_SPEC`` (NaN replicate lanes, worker SIGKILL, torn
    artifact files, failed device uploads, stalled transfers, simulated
    host loss, injected stragglers). Stdlib-only; every hook is a no-op
    when the spec is unset.
  * :mod:`.resilience` — the recovery layer: per-replicate health
    evaluation, quarantine + reseeded retry bookkeeping
    (``ReplicateGuard``), torn-artifact validation for resume/combine,
    shard-fault ledger records, and the ``CNMF_TPU_MAX_RETRIES`` /
    ``CNMF_TPU_MIN_HEALTHY_FRAC`` policy knobs.
  * :mod:`.checkpoint` — mid-run pass-statistics checkpoints for the
    streaming/rowsharded solvers (``CNMF_TPU_CKPT_EVERY_PASSES``): tiny
    ``(A, B)``/W/cursor state persisted atomically per replicate so an
    interrupted multi-hour pass resumes mid-run instead of from scratch.
  * :mod:`.elastic` — elastic degraded-mesh execution (ISSUE 8):
    heartbeat liveness for mesh participants (named culprits at barrier
    timeouts and straggler deadlines), host/device-loss detection, and
    degraded-mesh re-planning over surviving devices so a topology
    failure becomes a recoverable, chaos-testable degraded mode instead
    of an abort (``CNMF_TPU_ELASTIC`` / ``CNMF_TPU_HEARTBEAT_S`` /
    ``CNMF_TPU_STRAGGLER_S`` / ``CNMF_TPU_MIN_DEVICES``).
"""
