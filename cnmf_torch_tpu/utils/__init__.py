from .anndata_lite import AnnDataLite, read_h5ad, write_h5ad
from .io import (
    check_dir_exists,
    load_counts,
    load_df_from_npz,
    read_10x_mtx,
    save_df_to_npz,
    save_df_to_text,
)
from .paths import build_paths
from .telemetry import (
    EventLog,
    render_report,
    telemetry_enabled,
    validate_events_file,
)

__all__ = [
    "EventLog",
    "render_report",
    "telemetry_enabled",
    "validate_events_file",
    "AnnDataLite",
    "read_h5ad",
    "write_h5ad",
    "check_dir_exists",
    "load_counts",
    "load_df_from_npz",
    "read_10x_mtx",
    "save_df_to_npz",
    "save_df_to_text",
    "build_paths",
]
