"""Tier-1 out-of-core ingestion smoke gate (scripts/verify_tier1.sh).

Runs the mini pipeline — prepare → factorize → combine → consensus →
k_selection — twice on the same seeds: once resident (``CNMF_TPU_OOC=0``)
and once with ``CNMF_TPU_OOC_BUDGET_BYTES`` forced below the fixture's
matrix size, so prepare writes the row-slab shard store, factorize
streams every slab from disk, and consensus + k-selection run their
budget-bounded slab loops (ISSUE 13) instead of assembling the matrix.
Asserts:

  * the store exists with > 1 slab and the h5ad copy is SKIPPED under
    ``CNMF_TPU_OOC=1`` (the double-write satellite);
  * merged spectra AND consensus spectra/usages are BIT-identical to
    the resident run (store-backed staging places values, never sums
    them; the slab-looped usage refit preserves the chunk partition);
  * the store-backed run NEVER assembles the full matrix on host (no
    "assembling the full matrix" warning), and the streamed consensus +
    k-selection slab passes report a host-residency peak UNDER the
    budget (telemetry ``stream`` events, contexts ``consensus_stream``
    / ``kselection_stream``);
  * the k-selection stats match the resident run (silhouette exactly —
    it is spectra-only; prediction error to f64 accumulation-order
    tolerance);
  * a ``shard_read``-injected torn slab is DETECTED by the reader's
    content-digest validation and healed by a disk re-read (telemetry
    ``fault`` kind ``shard_read_torn``), with the run still
    bit-identical;
  * every emitted event validates against the telemetry schema.

Exits nonzero on any violation, failing the gate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"

_OOC_KNOBS = ("CNMF_TPU_OOC", "CNMF_TPU_OOC_BUDGET_BYTES",
              "CNMF_TPU_OOC_SLAB_ROWS", "CNMF_TPU_FAULT_SPEC")

N_CELLS, N_GENES_HV = 450, 100
# below the 450 x 100 f32 fixture (180 KB), so every stage must stream;
# sized so one 64-row refit chunk's TRUE live set (raw CSR slab ~2x +
# f32 block + the error pass's charged f64 copy + the usage-sized
# pass-lifetime buffers — the irreducible floor) still fits under it
BUDGET = 147456


def _pipeline(workdir: str, env: dict, k_selection: bool = True):
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    prior = {k: os.environ.get(k) for k in _OOC_KNOBS}
    os.environ.update(env)
    try:
        rng = np.random.default_rng(3)
        usage = rng.dirichlet(np.ones(5) * 0.3, size=N_CELLS)
        spectra = rng.gamma(0.3, 1.0, size=(5, 130)) * 40.0 / 130
        counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts,
                          index=[f"c{i}" for i in range(N_CELLS)],
                          columns=[f"g{j}" for j in range(130)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        obj = cNMF(output_dir=workdir, name="ooc")
        # batch_size=64: the refit chunk is the slab loop's irreducible
        # unit, and bit-identity pins the chunk partition — a 64-row
        # chunk keeps the streamed blocks well under the budget where
        # the default 5000 would cover the whole mini fixture
        obj.prepare(counts_fn, components=[3], n_iter=4, seed=7,
                    num_highvar_genes=N_GENES_HV, batch_size=64)
        obj.factorize(rowshard=True)
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
        stats = obj.k_selection_plot(close_fig=True) if k_selection \
            else None
        return obj, stats
    finally:
        for k, v in prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def main() -> int:
    import numpy as np

    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    base_dir = tempfile.mkdtemp(prefix="ooc_smoke_base_")
    ooc_dir = tempfile.mkdtemp(prefix="ooc_smoke_ooc_")
    torn_dir = tempfile.mkdtemp(prefix="ooc_smoke_torn_")
    try:
        base, stats_base = _pipeline(base_dir, {"CNMF_TPU_OOC": "0"})

        # budget below the fixture's dense bytes: the store MUST be
        # written, factorize MUST stream slab-wise, and consensus +
        # k-selection MUST run the slab-looped refit/error passes.
        # Slab rows pinned to 64 (matching the refit chunk; the auto
        # sizing floors at 256 rows, which would collapse this mini
        # store to one slab); 450/64 leaves a RAGGED final slab (8
        # slabs, 2-row tail), the boundary case the staging + slab-loop
        # parity must absorb.
        ooc_env = {"CNMF_TPU_OOC": "1",
                   "CNMF_TPU_OOC_BUDGET_BYTES": str(BUDGET),
                   "CNMF_TPU_OOC_SLAB_ROWS": "64"}
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            ooc, stats_ooc = _pipeline(ooc_dir, ooc_env)
        assembled = [w for w in caught
                     if "assembling the full matrix" in str(w.message)]
        assert not assembled, \
            "store-authoritative run assembled the full matrix on host"
        store_manifest = os.path.join(ooc.paths["shard_store"],
                                      "manifest.json")
        assert os.path.exists(store_manifest), "shard store not written"
        assert not os.path.exists(ooc.paths["normalized_counts"]), \
            "CNMF_TPU_OOC=1 must skip the h5ad normalized-counts copy"
        import json

        with open(store_manifest) as f:
            n_slabs = len(json.load(f)["slabs"])
        assert n_slabs > 1, f"budget should force multiple slabs ({n_slabs})"

        def _load(obj, key, *fmt):
            return np.load(obj.paths[key] % fmt, allow_pickle=True)["data"]

        for key, fmt in (("merged_spectra", (3,)),
                         ("consensus_spectra", (3, "2_0")),
                         ("consensus_usages", (3, "2_0"))):
            a, b = _load(base, key, *fmt), _load(ooc, key, *fmt)
            assert np.array_equal(a, b), \
                f"{key}: store-backed run is not bit-identical to resident"

        # k-selection parity: silhouette is spectra-only (exact);
        # prediction error differs only by f64 accumulation order
        sb, so = stats_base.iloc[0], stats_ooc.iloc[0]
        assert sb["silhouette"] == so["silhouette"], \
            "k-selection silhouette diverged under streaming"
        rel = abs(sb["prediction_error"] - so["prediction_error"]) \
            / max(abs(sb["prediction_error"]), 1e-12)
        assert rel < 1e-5, \
            f"k-selection prediction error diverged ({rel:.2e} rel)"

        ev_path = os.path.join(ooc_dir, "ooc", "cnmf_tmp",
                               "ooc.events.jsonl")
        validate_events_file(ev_path)
        evs = list(read_events(ev_path))
        assert any(e["t"] == "dispatch" and e.get("decision") == "ooc_ingest"
                   for e in evs), "no ooc_ingest dispatch event"
        assert any(e["t"] == "stream" and e.get("disk_nbytes")
                   for e in evs), "no disk-producer stream stats recorded"
        # host-residency budget: every streamed consensus/k-selection
        # pass must report a peak under the budget (and therefore under
        # the full-matrix bytes the resident path would hold)
        slab_streams = [e for e in evs if e["t"] == "stream"
                        and e.get("context") in ("consensus_stream",
                                                 "kselection_stream")]
        assert slab_streams, "no streamed consensus/k-selection events"
        peaks = [int(e.get("host_peak_bytes") or 0) for e in slab_streams]
        assert all(0 < p <= BUDGET for p in peaks), \
            f"slab-pass host peak {peaks} exceeds the budget {BUDGET}"
        full_bytes = N_CELLS * N_GENES_HV * 4
        assert max(peaks) < full_bytes, \
            "slab-pass host peak is not below the full-matrix footprint"
        print("[ooc_smoke] store-backed run bit-identical to resident "
              f"({n_slabs} slabs, h5ad skipped); consensus+k_selection "
              f"streamed, host peak {max(peaks)} <= budget {BUDGET} "
              f"(< full {full_bytes}) ... ok")

        # torn-slab containment: the injected corruption must be caught
        # by the digest check and healed by a clean re-read — output
        # still bit-identical, fault event on the record
        torn_env = dict(ooc_env,
                        CNMF_TPU_FAULT_SPEC="shard_read:context=slab")
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            torn, _ = _pipeline(torn_dir, torn_env, k_selection=False)
        heal_warn = [w for w in caught
                     if "re-reading from disk" in str(w.message)]
        assert heal_warn, "torn shard read was not detected/re-read"
        a = _load(base, "consensus_spectra", 3, "2_0")
        b = _load(torn, "consensus_spectra", 3, "2_0")
        assert np.array_equal(a, b), \
            "torn-then-healed run is not bit-identical"
        torn_ev = os.path.join(torn_dir, "ooc", "cnmf_tmp",
                               "ooc.events.jsonl")
        validate_events_file(torn_ev)
        assert any(e["t"] == "fault" and e.get("kind") == "shard_read_torn"
                   for e in read_events(torn_ev)), \
            "no shard_read_torn fault event"
        print("[ooc_smoke] torn slab detected, re-read, bit-identical "
              "output ... ok")
        return 0
    finally:
        for d in (base_dir, ooc_dir, torn_dir):
            shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
