"""Run telemetry subsystem (ISSUE 4): event schema, solver convergence
traces, sweep aggregation, report rendering, and the profiling satellites
(StageTimer sanitization/warn-once, trace() thread safety)."""

import contextlib
import json
import os
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from cnmf_torch_tpu.ops.nmf import (
    EVAL_EVERY,
    TRACE_LEN,
    _update_H,
    _update_W,
    beta_divergence,
    nmf_fit_batch,
    nmf_fit_batch_bundled,
    nmf_fit_online,
    random_init,
    _chunk_rows,
)
from cnmf_torch_tpu.utils import telemetry as tel
from cnmf_torch_tpu.utils.profiling import StageTimer, trace


@pytest.fixture()
def small_problem():
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((60, 40), np.float32))
    H0, W0 = random_init(jax.random.key(1), 60, 40, 4, jnp.mean(X))
    return X, H0, W0


# ---------------------------------------------------------------------------
# solver convergence traces
# ---------------------------------------------------------------------------

class TestSolverTraces:
    def test_batch_trace_matches_independent_objectives(self, small_problem):
        """The trace entries ARE the objectives of the factor iterates:
        re-run the identical MU updates step by step outside the
        while_loop and compare at every EVAL_EVERY point (f32 tolerance —
        the acceptance bar; on CPU the values typically bit-match)."""
        X, H0, W0 = small_problem
        n_iters = 4 * EVAL_EVERY
        _, _, err, tm = nmf_fit_batch(X, H0, W0, beta=2.0, tol=0.0,
                                      max_iter=n_iters, telemetry=True)
        trace_vals = np.asarray(tm.trace)

        H, W = H0, W0
        expected = []
        for i in range(1, n_iters + 1):
            H = _update_H(X, H, W, 2.0, 0.0, 0.0)
            W = _update_W(X, H, W, 2.0, 0.0, 0.0)
            if i % EVAL_EVERY == 0:
                expected.append(float(beta_divergence(X, H, W, beta=2.0)))
        np.testing.assert_allclose(trace_vals[:len(expected)], expected,
                                   rtol=1e-5)
        # slots past the last evaluation stay NaN (never-evaluated marker)
        assert np.isnan(trace_vals[len(expected):]).all()
        # the returned err is the final recompute of the same iterate
        np.testing.assert_allclose(float(err), expected[-1], rtol=1e-5)
        assert not bool(tm.nonfinite)

    def test_capped_vs_converged_flags(self, small_problem):
        X, H0, W0 = small_problem
        cap = 2 * EVAL_EVERY
        # tol=0 can never satisfy the relative-decrease stop -> capped
        _, _, _, tm_cap = nmf_fit_batch(X, H0, W0, beta=2.0, tol=0.0,
                                        max_iter=cap, telemetry=True)
        assert int(tm_cap.iters) == cap  # capped: iters == max_iter
        # a huge tol converges at the first evaluation window
        _, _, _, tm_conv = nmf_fit_batch(X, H0, W0, beta=2.0, tol=10.0,
                                         max_iter=500, telemetry=True)
        assert int(tm_conv.iters) < 500  # converged before the cap

    def test_vmapped_iters_are_per_replicate(self, small_problem):
        """Under vmap the batched while_loop steps every lane until the
        last converges; iters must still reflect each lane's OWN stop."""
        X, H0, W0 = small_problem
        Hs = jnp.stack([H0, H0 * 2.0, H0 * 0.25])
        Ws = jnp.stack([W0, W0 * 0.5, W0 * 3.0])
        solve = jax.jit(jax.vmap(
            lambda h, w: nmf_fit_batch(X, h, w, beta=2.0, tol=1e-3,
                                       max_iter=400, telemetry=True),
            in_axes=(0, 0)))
        _, _, errs, tm = solve(Hs, Ws)
        iters = np.asarray(tm.iters)
        assert tm.trace.shape == (3, TRACE_LEN)
        assert (iters > 0).all() and (iters <= 400).all()
        # bundled solver agrees on results and telemetry shape
        _, _, errs_b, tm_b = nmf_fit_batch_bundled(
            X, Hs, Ws, tol=1e-3, max_iter=400, telemetry=True)
        np.testing.assert_allclose(np.asarray(errs_b), np.asarray(errs),
                                   rtol=1e-4)
        assert tm_b.trace.shape == (3, TRACE_LEN)
        np.testing.assert_array_equal(np.asarray(tm_b.iters), iters)

    def test_online_trace_records_passes(self, small_problem):
        X, H0, W0 = small_problem
        Xc, Hc, _ = _chunk_rows(X, H0, 30)
        out = nmf_fit_online(Xc, Hc, W0, beta=2.0, tol=1e-4, h_tol=3e-3,
                             n_passes=20)
        assert len(out) == 3  # default path unchanged
        _, _, err, tm = nmf_fit_online(Xc, Hc, W0, beta=2.0, tol=1e-4,
                                       h_tol=3e-3, n_passes=20,
                                       telemetry=True)
        passes = int(tm.iters)
        tr = np.asarray(tm.trace)
        assert 1 <= passes <= 20
        assert np.isfinite(tr[:passes]).all()
        assert np.isnan(tr[passes:]).all()
        # per-pass objectives are non-increasing after the first pass
        assert (np.diff(tr[:passes]) <= 1e-3 * tr[0]).all()
        assert not bool(tm.nonfinite)

    def test_telemetry_off_returns_three_outputs(self, small_problem):
        """The disabled path must not grow outputs (no extra device
        transfers) — telemetry is a static flag, not a runtime branch."""
        X, H0, W0 = small_problem
        assert len(nmf_fit_batch(X, H0, W0, beta=2.0)) == 3
        assert len(nmf_fit_batch_bundled(X, jnp.stack([H0]),
                                         jnp.stack([W0]))) == 3


# ---------------------------------------------------------------------------
# sweep aggregation
# ---------------------------------------------------------------------------

class TestSweepTelemetry:
    def test_sink_receives_per_replicate_records(self, monkeypatch):
        from cnmf_torch_tpu.parallel import replicate_sweep

        monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
        rng = np.random.default_rng(1)
        X = rng.random((90, 40)).astype(np.float32)
        got = []
        spectra, _, errs = replicate_sweep(
            X, [11, 12, 13], 3, mode="online", online_chunk_size=45,
            telemetry_sink=got.append)
        assert len(got) == 1
        pay = got[0]
        assert pay["k"] == 3 and pay["seeds"] == [11, 12, 13]
        assert np.asarray(pay["trace"]).shape == (3, TRACE_LEN)
        np.testing.assert_allclose(np.asarray(pay["errs"]), errs)
        iters = np.asarray(pay["iters"])
        assert (iters >= 1).all() and (iters <= pay["cap"]).all()

    def test_sink_not_called_when_disabled(self, monkeypatch):
        from cnmf_torch_tpu.parallel import replicate_sweep

        monkeypatch.delenv(tel.TELEMETRY_ENV, raising=False)
        rng = np.random.default_rng(1)
        X = rng.random((90, 40)).astype(np.float32)
        got = []
        replicate_sweep(X, [11, 12], 3, mode="online", online_chunk_size=45,
                        telemetry_sink=got.append)
        assert got == []

    def test_rowsharded_solver_telemetry(self, monkeypatch):
        from jax.sharding import Mesh

        from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

        monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
        rng = np.random.default_rng(2)
        X = rng.random((96, 30)).astype(np.float32)
        mesh = Mesh(np.asarray(jax.devices()[:2]), ("cells",))
        got = []
        _, _, err = nmf_fit_rowsharded(X, 3, mesh, seed=5,
                                       telemetry_sink=got.append)
        assert len(got) == 1
        pay = got[0]
        tr = np.asarray(pay["trace"])
        assert tr.shape == (1, TRACE_LEN)
        passes = int(np.asarray(pay["iters"])[0])
        assert 1 <= passes <= pay["cap"]
        assert np.isfinite(tr[0, :passes]).all()
        # the last recorded pass objective is the returned err
        np.testing.assert_allclose(tr[0, passes - 1], err, rtol=1e-6)


# ---------------------------------------------------------------------------
# event log + schema + pipeline integration + report
# ---------------------------------------------------------------------------

def _mini_counts(n=200, g=120, seed=3):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(5) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(5, g)) * 40.0 / g
    counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return pd.DataFrame(counts, index=[f"c{i}" for i in range(n)],
                        columns=[f"g{j}" for j in range(g)])


class TestEventsAndReport:
    def test_pipeline_emits_schema_valid_events(self, tmp_path, monkeypatch):
        from cnmf_torch_tpu import cNMF
        from cnmf_torch_tpu.utils import save_df_to_npz

        monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
        counts_fn = str(tmp_path / "counts.df.npz")
        save_df_to_npz(_mini_counts(), counts_fn)
        obj = cNMF(output_dir=str(tmp_path), name="ev")
        obj.prepare(counts_fn, components=[3, 4], n_iter=4, seed=7,
                    num_highvar_genes=80)
        obj.factorize()
        obj.combine()

        ev_path = tmp_path / "ev" / "cnmf_tmp" / "ev.events.jsonl"
        assert ev_path.exists()
        n = tel.validate_events_file(str(ev_path))
        events = tel.read_events(str(ev_path))
        assert n == len(events)
        by_type = {}
        for e in events:
            by_type.setdefault(e["t"], []).append(e)

        # manifest: first event, complete, self-describing
        assert events[0]["t"] == "manifest"
        man = by_type["manifest"][0]
        assert len(by_type["manifest"]) == 1
        assert man["jax_version"] == jax.__version__
        assert man["backend"] == "cpu"
        assert isinstance(man["devices"], list) and man["devices"]
        assert man["env"].get(tel.TELEMETRY_ENV) == "1"
        assert man["ledger"]["ks"] == [3, 4]
        assert man["ledger"]["n_tasks"] == 8
        assert "seed_min" in man["ledger"]

        # dispatch: the engaged solver path is recorded
        decisions = {d["decision"] for d in by_type["dispatch"]}
        assert "solver_path" in decisions
        solver = [d for d in by_type["dispatch"]
                  if d["decision"] == "solver_path"][0]
        assert solver["context"]["engaged_path"] in (
            "batched", "batched-packed", "batched-ell")

        # per-stage events and replicate convergence records per K
        assert {e["stage"] for e in by_type["stage"]} >= {"prepare",
                                                          "factorize",
                                                          "combine"}
        reps = by_type["replicates"]
        assert {int(e["k"]) for e in reps} == {3, 4}
        for e in reps:
            assert len(e["records"]) == 4
            for rec in e["records"]:
                assert rec["iters"] >= 1
                assert isinstance(rec["capped"], bool)
                assert rec["trace"], "objective trace must be non-empty"
                assert np.isfinite(rec["trace"]).all()

        # memory watermark at stage boundaries (CPU: live-buffer fallback)
        assert by_type["memory"]
        assert all(isinstance(m["devices"], list) for m in by_type["memory"])

        # the report renders the stream without error and names the pieces
        report = tel.render_report(str(tmp_path / "ev"))
        for needle in ("Manifest", "Dispatch decisions", "Stage waterfall",
                       "Replicate convergence", "factorize"):
            assert needle in report

        # report CLI (positional run_dir form)
        from cnmf_torch_tpu.cli import main as cli_main

        cli_main(["report", str(tmp_path / "ev")])

    def test_telemetry_off_emits_nothing(self, tmp_path, monkeypatch):
        from cnmf_torch_tpu import cNMF
        from cnmf_torch_tpu.utils import save_df_to_npz

        monkeypatch.delenv(tel.TELEMETRY_ENV, raising=False)
        counts_fn = str(tmp_path / "counts.df.npz")
        save_df_to_npz(_mini_counts(n=120, g=80), counts_fn)
        obj = cNMF(output_dir=str(tmp_path), name="off")
        obj.prepare(counts_fn, components=[3], n_iter=2, seed=7,
                    num_highvar_genes=60)
        obj.factorize()
        assert not (tmp_path / "off" / "cnmf_tmp"
                    / "off.events.jsonl").exists()
        # the report falls back to the timings TSV instead of failing
        report = tel.render_report(str(tmp_path / "off"))
        assert "timings TSV" in report and "factorize" in report

    def test_faults_and_recoveries_report_section(self, tmp_path):
        """ISSUE 6 satellite: the report renders a "Faults & recoveries"
        table (per-class counts + retried/recovered/quarantined) and the
        checkpoint lifecycle line from the same events file."""
        import json
        import time

        events = [
            {"v": 1, "t": "manifest", "ts": time.time(),
             "package_version": "x", "jax_version": "x", "backend": "cpu",
             "devices": [], "env": {}},
            {"v": 1, "t": "fault", "ts": time.time(),
             "kind": "nonfinite_replicate",
             "context": {"k": 3, "iter": 1, "seed": 9, "attempt": 0}},
            {"v": 1, "t": "fault", "ts": time.time(), "kind": "retry",
             "context": {"k": 3, "iter": 1, "seed": 9, "attempt": 1,
                         "healthy": True}},
            {"v": 1, "t": "fault", "ts": time.time(), "kind": "shard_retry",
             "context": {"context": "stream_dense", "task": "0",
                         "attempt": 1, "error": "RuntimeError: x"}},
            {"v": 1, "t": "fault", "ts": time.time(), "kind": "quarantine",
             "context": {"k": 4, "iter": 0, "seed": 5, "attempt": 2}},
            {"v": 1, "t": "checkpoint", "ts": time.time(),
             "action": "write", "context": {"k": 3, "iter": 1,
                                            "pass_idx": 4}},
            {"v": 1, "t": "checkpoint", "ts": time.time(),
             "action": "resume", "context": {"k": 3, "iter": 1,
                                             "pass_idx": 4}},
            {"v": 1, "t": "checkpoint", "ts": time.time(),
             "action": "discard", "context": {"k": 3, "iter": 1}},
        ]
        run_dir = tmp_path / "faultrun"
        (run_dir / "cnmf_tmp").mkdir(parents=True)
        ev_path = run_dir / "cnmf_tmp" / "faultrun.events.jsonl"
        with open(ev_path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        tel.validate_events_file(str(ev_path))  # checkpoint type is schema-valid

        summary = tel.summarize_events(events)
        assert summary["faults"]["by_kind"] == {
            "nonfinite_replicate": 1, "retry": 1, "shard_retry": 1,
            "quarantine": 1}
        assert summary["faults"]["retried"] == 1
        assert summary["faults"]["recovered"] == 1
        assert summary["faults"]["quarantined"] == 1
        assert summary["checkpoints"]["actions"] == {
            "write": 1, "resume": 1, "discard": 1}
        assert summary["checkpoints"]["max_resume_pass"] == 4

        report = tel.render_report(str(run_dir))
        assert "Faults & recoveries" in report
        assert "shard_retry" in report
        assert "retried 1 (recovered 1), quarantined 1" in report
        assert "deepest resume: pass 4" in report

    def test_cli_rejects_stray_positional_for_non_report(self, capsys):
        """The optional run_dir positional serves `report` only — a stray
        positional on any other subcommand (e.g. `consensus 9` meaning
        `-k 9`) must fail fast, not be silently swallowed."""
        from cnmf_torch_tpu.cli import main as cli_main

        with pytest.raises(SystemExit) as exc:
            cli_main(["consensus", "9"])
        assert exc.value.code == 2
        assert "unrecognized argument" in capsys.readouterr().err

    def test_validate_event_rejects_malformed(self):
        tel.validate_event({"v": 1, "t": "stage", "ts": 1.0,
                            "stage": "x", "wall_s": 0.1})
        with pytest.raises(ValueError, match="missing required field"):
            tel.validate_event({"t": "stage", "ts": 1.0})
        with pytest.raises(ValueError, match="unknown event type"):
            tel.validate_event({"v": 1, "t": "nope", "ts": 1.0})
        with pytest.raises(ValueError, match="missing required fields"):
            tel.validate_event({"v": 1, "t": "stage", "ts": 1.0})
        with pytest.raises(ValueError, match="schema version"):
            tel.validate_event({"v": 99, "t": "stage", "ts": 1.0,
                                "stage": "x", "wall_s": 0.1})
        with pytest.raises(ValueError, match="replicate record missing"):
            tel.validate_event({"v": 1, "t": "replicates", "ts": 1.0,
                                "k": 3, "beta": 2.0,
                                "records": [{"seed": 1}]})

    def test_validate_events_file_requires_manifest_first(self, tmp_path):
        p = tmp_path / "e.jsonl"
        p.write_text(json.dumps({"v": 1, "t": "stage", "ts": 1.0,
                                 "stage": "x", "wall_s": 0.1}) + "\n")
        with pytest.raises(ValueError, match="manifest"):
            tel.validate_events_file(str(p))

    def test_eventlog_nonfinite_values_stay_parseable(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
        log = tel.EventLog(str(tmp_path / "e.jsonl"))
        log.emit("replicates", k=3, beta=2.0, records=[
            {"seed": 1, "err": float("inf"), "iters": 5, "capped": False,
             "nonfinite": True}])
        # strict JSON (bare NaN/Infinity rejected) must parse every line
        with open(tmp_path / "e.jsonl") as f:
            for line in f:
                json.loads(line, parse_constant=lambda c: pytest.fail(
                    f"non-strict JSON constant {c!r} in event stream"))
        assert tel.validate_events_file(str(tmp_path / "e.jsonl")) == 2


# ---------------------------------------------------------------------------
# profiling satellites
# ---------------------------------------------------------------------------

class TestStageTimerSatellites:
    def test_meta_with_tabs_newlines_stays_single_row(self, tmp_path):
        path = str(tmp_path / "t.tsv")
        timer = StageTimer(path)
        timer.record("stage_a", 1.0, note="bad\tvalue\nwith\rbreaks")
        timer.record("stage_b", 2.0)
        with open(path) as f:
            lines = f.read().splitlines()
        assert len(lines) == 3  # header + exactly one row per record
        row = lines[1].split("\t")
        assert len(row) == 7  # no column shift from the embedded tab
        assert row[0] == "stage_a"
        assert "note=bad value with breaks" in row[6]
        # bench's positional parser still reads (stage, wall)
        import bench

        rows = list(bench.iter_stage_rows(path))
        assert rows == [("stage_a", 1.0), ("stage_b", 2.0)]

    def test_oserror_warns_once_per_process(self, tmp_path):
        timer = StageTimer(str(tmp_path / "no_such_dir" / "t.tsv"))
        saved = StageTimer._oserror_warned
        StageTimer._oserror_warned = False
        try:
            with warnings.catch_warnings(record=True) as caught:
                warnings.simplefilter("always")
                timer.record("s1", 1.0)
                timer.record("s2", 1.0)
            mine = [w for w in caught
                    if issubclass(w.category, RuntimeWarning)
                    and "StageTimer" in str(w.message)]
            assert len(mine) == 1  # warned exactly once, not per record
        finally:
            StageTimer._oserror_warned = saved

    def test_stage_events_mirror_rows(self, tmp_path, monkeypatch):
        monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
        log = tel.EventLog(str(tmp_path / "e.jsonl"))
        timer = StageTimer(str(tmp_path / "t.tsv"), events=log)
        with timer.stage("work", nbytes=1000):
            pass
        events = tel.read_events(str(tmp_path / "e.jsonl"))
        stage_evs = [e for e in events if e["t"] == "stage"]
        assert len(stage_evs) == 1
        assert stage_evs[0]["stage"] == "work"
        assert stage_evs[0]["nbytes"] == 1000
        tel.validate_events_file(str(tmp_path / "e.jsonl"))


class TestTraceReentrancy:
    def test_concurrent_stages_open_one_profiler_session(self, tmp_path,
                                                         monkeypatch):
        """k_selection runs up to 4 concurrent stats passes; only ONE may
        hold a jax.profiler session (a second concurrent session raises
        inside JAX). The old module-global flag let two threads race past
        the check; the lock must serialize them."""
        state = {"depth": 0, "max_depth": 0, "entries": 0}
        state_lock = threading.Lock()

        @contextlib.contextmanager
        def fake_profiler_trace(path):
            with state_lock:
                state["depth"] += 1
                state["entries"] += 1
                state["max_depth"] = max(state["max_depth"], state["depth"])
            try:
                time.sleep(0.02)
                yield
            finally:
                with state_lock:
                    state["depth"] -= 1

        monkeypatch.setattr(jax.profiler, "trace", fake_profiler_trace)
        monkeypatch.setenv("CNMF_TPU_PROFILE_DIR", str(tmp_path))

        barrier = threading.Barrier(4)

        def worker(i):
            barrier.wait()
            for _ in range(5):
                with trace(f"stage_{i}"):
                    time.sleep(0.002)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert state["entries"] >= 1
        assert state["max_depth"] == 1

    def test_nested_stage_is_noop(self, tmp_path, monkeypatch):
        entries = []

        @contextlib.contextmanager
        def fake_profiler_trace(path):
            entries.append(path)
            yield

        monkeypatch.setattr(jax.profiler, "trace", fake_profiler_trace)
        monkeypatch.setenv("CNMF_TPU_PROFILE_DIR", str(tmp_path))
        with trace("outer"):
            with trace("inner"):
                pass
        assert len(entries) == 1 and entries[0].endswith("outer")
        # the session is released afterwards — a later stage traces again
        with trace("later"):
            pass
        assert len(entries) == 2
