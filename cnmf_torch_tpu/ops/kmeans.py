"""Multi-init Lloyd k-means in JAX.

Replaces sklearn's ``KMeans(n_clusters=k, n_init=10, random_state=1)`` used
for consensus clustering of replicate spectra
(``/root/reference/src/cnmf/cnmf.py:18, 1082-1084``). Bitwise parity with
sklearn is implementation-defined and impossible to pin (SURVEY.md §7); the
parity contract for consensus is identical cluster *medians up to label
permutation*, which multi-init Lloyd from a fixed key satisfies.

Design: kmeans++ seeding via a ``lax.scan`` over centers, Lloyd iterations
via ``lax.while_loop`` on sklearn's center-shift criterion (``tol`` scaled
by the data variance), and the ``n_init`` restarts batched with ``vmap`` —
one compiled program, no host round-trips.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.jax_compat import assert_threefry_partitionable

__all__ = ["kmeans"]


def _sq_dists(X, C):
    """(n, k) squared euclidean distances."""
    x2 = jnp.sum(X * X, axis=1)[:, None]
    c2 = jnp.sum(C * C, axis=1)[None, :]
    return jnp.maximum(x2 + c2 - 2.0 * (X @ C.T), 0.0)


def _kmeanspp(key, X, k: int):
    n = X.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n)
    c0 = X[first]
    min_d2 = jnp.sum((X - c0[None, :]) ** 2, axis=1)

    def pick(carry, sub):
        min_d2 = carry
        p = min_d2 / jnp.maximum(min_d2.sum(), 1e-30)
        idx = jax.random.choice(sub, n, p=p)
        c = X[idx]
        d2 = jnp.sum((X - c[None, :]) ** 2, axis=1)
        return jnp.minimum(min_d2, d2), c

    subs = jax.random.split(key, k - 1)
    _, rest = jax.lax.scan(pick, min_d2, subs)
    return jnp.concatenate([c0[None, :], rest], axis=0)


def _lloyd(X, C0, max_iter: int, shift_tol):
    def assign(C):
        return jnp.argmin(_sq_dists(X, C), axis=1)

    def body(carry):
        C, _, it = carry
        labels = assign(C)
        onehot = jax.nn.one_hot(labels, C.shape[0], dtype=X.dtype)
        counts = onehot.sum(axis=0)
        sums = onehot.T @ X
        newC = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], C)
        shift = jnp.sum((newC - C) ** 2)
        return (newC, shift, it + 1)

    def cond(carry):
        _, shift, it = carry
        return (it < max_iter) & (shift > shift_tol)

    C, _, _ = jax.lax.while_loop(cond, body, (C0, jnp.asarray(jnp.inf, X.dtype), jnp.int32(0)))
    labels = assign(C)
    inertia = jnp.sum(jnp.min(_sq_dists(X, C), axis=1))
    return labels, C, inertia


def _kmeanspp_masked(key, X, k: int, mask):
    """kmeans++ seeding restricted to rows with mask=1 (excluded rows have
    zero selection probability, so they can never become centers)."""
    n = X.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.choice(sub, n, p=mask / jnp.maximum(mask.sum(), 1e-30))
    c0 = X[first]
    min_d2 = jnp.sum((X - c0[None, :]) ** 2, axis=1)

    def pick(carry, sub):
        min_d2 = carry
        w = min_d2 * mask
        # degenerate round (every masked row already at distance 0 from a
        # center — the n_keep < k warn-and-degrade path): an all-zero w
        # would let jax.random.choice return an arbitrary index, including
        # a masked-out row; fall back to uniform over the masked rows
        w = jnp.where(w.sum() > 1e-30, w, mask)
        p = w / jnp.maximum(w.sum(), 1e-30)
        idx = jax.random.choice(sub, n, p=p)
        c = X[idx]
        d2 = jnp.sum((X - c[None, :]) ** 2, axis=1)
        return jnp.minimum(min_d2, d2), c

    subs = jax.random.split(key, k - 1)
    _, rest = jax.lax.scan(pick, min_d2, subs)
    return jnp.concatenate([c0[None, :], rest], axis=0)


def _lloyd_masked(X, C0, max_iter: int, shift_tol, mask):
    """Lloyd iterations where only mask=1 rows contribute to center updates
    and inertia. Labels are produced for EVERY row (callers discard the
    masked-out ones); the clustering is exactly k-means on the masked
    subset, at the full array's static shape."""
    def assign(C):
        return jnp.argmin(_sq_dists(X, C), axis=1)

    def body(carry):
        C, _, it = carry
        labels = assign(C)
        onehot = jax.nn.one_hot(labels, C.shape[0], dtype=X.dtype)
        onehot = onehot * mask[:, None]
        counts = onehot.sum(axis=0)
        sums = onehot.T @ X
        newC = jnp.where(counts[:, None] > 0,
                         sums / jnp.maximum(counts, 1.0)[:, None], C)
        shift = jnp.sum((newC - C) ** 2)
        return (newC, shift, it + 1)

    def cond(carry):
        _, shift, it = carry
        return (it < max_iter) & (shift > shift_tol)

    C, _, _ = jax.lax.while_loop(
        cond, body, (C0, jnp.asarray(jnp.inf, X.dtype), jnp.int32(0)))
    labels = assign(C)
    inertia = jnp.sum(jnp.min(_sq_dists(X, C), axis=1) * mask)
    return labels, C, inertia


def _kmeanspp_packed(key, X, k_pad: int, k_actual, n_rows, row_mask):
    """kmeans++ seeding at K_max-padded static shape, reproducing the
    per-K unmasked stream: rows beyond ``n_rows`` are zero padding (masked
    out of the selection weights), picks beyond ``k_actual`` draw but are
    discarded to zero centers. Threefry prefix properties make the first
    ``k_actual`` picks bit-compatible with ``_kmeanspp`` on the unpadded
    array: ``split(key, K_max-1)[:k-1] == split(key, k-1)`` and
    ``randint``/``choice`` are invariant to traced bounds and zero-padded
    probability tails (pinned by test)."""
    n = X.shape[0]
    key, sub = jax.random.split(key)
    first = jax.random.randint(sub, (), 0, n_rows)
    c0 = X[first]
    min_d2 = jnp.sum((X - c0[None, :]) ** 2, axis=1)

    def pick(carry, sub_j):
        min_d2 = carry
        sub, j = sub_j
        w = min_d2 * row_mask
        w = jnp.where(w.sum() > 1e-30, w, row_mask)
        p = w / jnp.maximum(w.sum(), 1e-30)
        idx = jax.random.choice(sub, n, p=p)
        c = X[idx]
        d2 = jnp.sum((X - c[None, :]) ** 2, axis=1)
        take = j < (k_actual - 1)
        return (jnp.where(take, jnp.minimum(min_d2, d2), min_d2),
                jnp.where(take, c, jnp.zeros_like(c)))

    subs = jax.random.split(key, k_pad - 1)
    _, rest = jax.lax.scan(pick, min_d2, (subs, jnp.arange(k_pad - 1)))
    return jnp.concatenate([c0[None, :], rest], axis=0)


def _lloyd_packed(X, C0, max_iter: int, shift_tol, row_mask, col_mask):
    """Lloyd at padded static shape: padding rows contribute nothing to
    center updates or inertia; padding clusters (``col_mask=0``) never win
    an assignment and their (zero) centers never move, so the shift
    criterion accumulates exact +0.0 from them."""
    def assign(C):
        d2 = _sq_dists(X, C)
        return jnp.argmin(jnp.where(col_mask[None, :], d2, jnp.inf), axis=1)

    def body(carry):
        C, _, it = carry
        labels = assign(C)
        onehot = jax.nn.one_hot(labels, C.shape[0], dtype=X.dtype)
        onehot = onehot * row_mask[:, None]
        counts = onehot.sum(axis=0)
        sums = onehot.T @ X
        newC = jnp.where(counts[:, None] > 0,
                         sums / jnp.maximum(counts, 1.0)[:, None], C)
        shift = jnp.sum((newC - C) ** 2)
        return (newC, shift, it + 1)

    def cond(carry):
        _, shift, it = carry
        return (it < max_iter) & (shift > shift_tol)

    C, _, _ = jax.lax.while_loop(
        cond, body, (C0, jnp.asarray(jnp.inf, X.dtype), jnp.int32(0)))
    labels = assign(C)
    d2 = _sq_dists(X, C)
    inertia = jnp.sum(
        jnp.min(jnp.where(col_mask[None, :], d2, jnp.inf), axis=1) * row_mask)
    return labels, C, inertia


@functools.partial(jax.jit, static_argnames=("k_pad", "n_init", "max_iter"))
def _kmeans_packed_jit(X, k_actual, n_rows, k_pad: int, n_init: int,
                       max_iter: int, tol, key):
    row_mask = (jnp.arange(X.shape[0]) < n_rows).astype(X.dtype)
    col_mask = jnp.arange(k_pad) < k_actual
    # sklearn's tol scaling over the REAL rows only (weighted population
    # variance; matches jnp.var on the unpadded array up to summation order)
    wm = row_mask / jnp.maximum(row_mask.sum(), 1e-30)
    mu = (X * wm[:, None]).sum(axis=0)
    var = (wm[:, None] * (X - mu[None, :]) ** 2).sum(axis=0)
    shift_tol = tol * jnp.mean(var)

    def one(key):
        C0 = _kmeanspp_packed(key, X, k_pad, k_actual, n_rows, row_mask)
        return _lloyd_packed(X, C0, max_iter, shift_tol, row_mask, col_mask)

    labels, Cs, inertias = jax.vmap(one)(jax.random.split(key, n_init))
    best = jnp.argmin(inertias)
    return labels[best], Cs[best], inertias[best]


@functools.partial(jax.jit,
                   static_argnames=("k", "n_init", "max_iter", "has_mask"))
def _kmeans_jit(X, k: int, n_init: int, max_iter: int, tol, key,
                has_mask: bool = False, mask=None):
    if has_mask:
        # sklearn scales tol by the mean per-feature variance of the
        # (masked-subset) data: equal-weight weighted population variance
        wm = mask / jnp.maximum(mask.sum(), 1e-30)
        mu = (X * wm[:, None]).sum(axis=0)
        var = (wm[:, None] * (X - mu[None, :]) ** 2).sum(axis=0)
        shift_tol = tol * jnp.mean(var)

        def one(key):
            C0 = _kmeanspp_masked(key, X, k, mask)
            return _lloyd_masked(X, C0, max_iter, shift_tol, mask)
    else:
        # sklearn scales tol by the mean per-feature variance of X
        shift_tol = tol * jnp.mean(jnp.var(X, axis=0))

        def one(key):
            C0 = _kmeanspp(key, X, k)
            return _lloyd(X, C0, max_iter, shift_tol)

    labels, Cs, inertias = jax.vmap(one)(jax.random.split(key, n_init))
    best = jnp.argmin(inertias)
    return labels[best], Cs[best], inertias[best]


def kmeans(X, k: int, n_init: int = 10, max_iter: int = 300,
           tol: float = 1e-4, seed: int = 1, mask=None,
           n_rows: int | None = None, k_pad: int | None = None):
    """Cluster rows of X; returns ``(labels, centers, inertia)`` as numpy.

    ``seed=1`` mirrors the reference's fixed ``random_state=1``
    (cnmf.py:1082) so repeated consensus runs are deterministic.

    ``mask``: optional boolean/0-1 row weights. Rows with mask=0 are
    excluded from seeding, center updates, and inertia — the clustering of
    the masked subset at the FULL array's static shape, so a consensus
    density-threshold sweep reuses ONE compiled program instead of
    recompiling per surviving-count (labels come back for every row;
    callers subset them). Without ``mask`` the program (and its RNG stream)
    is unchanged.

    ``n_rows``/``k_pad`` (the packed K-selection entry, both required
    together, exclusive with ``mask``): X arrives zero-row-padded to a
    shared R_max and the program is compiled at cluster width ``k_pad``;
    only the first ``n_rows`` rows and ``k`` clusters are real. One
    compiled program then serves EVERY K of a selection sweep (k and
    n_rows are traced scalars), reproducing each per-K program's RNG
    stream via the threefry prefix properties. Labels come back for all
    padded rows; callers slice ``[:n_rows]``.
    """
    X = jnp.asarray(np.asarray(X), dtype=jnp.float32)
    if (n_rows is None) != (k_pad is None):
        raise ValueError("n_rows and k_pad must be passed together")
    if k_pad is not None:
        if mask is not None:
            raise ValueError("mask is not supported with the packed entry")
        if not (0 < k <= k_pad and 0 < n_rows <= X.shape[0]):
            raise ValueError(f"invalid packed dims k={k} k_pad={k_pad} "
                             f"n_rows={n_rows} R_max={X.shape[0]}")
        # _kmeanspp_packed's split-prefix seeding parity needs the
        # partitionable threefry (ADVICE r5 #1)
        assert_threefry_partitionable("kmeans(k_pad=...)")
        labels, C, inertia = _kmeans_packed_jit(
            X, jnp.int32(k), jnp.int32(n_rows), int(k_pad), int(n_init),
            int(max_iter), jnp.float32(tol), jax.random.key(seed))
    elif mask is None:
        labels, C, inertia = _kmeans_jit(
            X, int(k), int(n_init), int(max_iter), jnp.float32(tol),
            jax.random.key(seed))
    else:
        mask = jnp.asarray(np.asarray(mask), dtype=jnp.float32)
        labels, C, inertia = _kmeans_jit(
            X, int(k), int(n_init), int(max_iter), jnp.float32(tol),
            jax.random.key(seed), has_mask=True, mask=mask)
    return np.asarray(labels), np.asarray(C), float(inertia)
