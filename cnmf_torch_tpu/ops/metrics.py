"""Pairwise distances, KNN local density, and silhouette — JAX kernels.

Replaces the consensus stage's native-dependency metric surface:
``sklearn.metrics.euclidean_distances`` full R x R distance matrix
(``/root/reference/src/cnmf/cnmf.py:20, 1065``), the ``np.argpartition``
K-nearest-neighbor mean distance used for the local-density outlier filter
(``cnmf.py:1067-1070``), and ``sklearn.metrics.silhouette_score``
(``cnmf.py:19, 1097``). All are fused jit expressions over the on-device
distance matrix; the KNN selection maps to ``lax.top_k``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["pairwise_euclidean", "local_density", "silhouette_score"]


@jax.jit
def _pairwise_euclidean(A):
    sq = jnp.sum(A * A, axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (A @ A.T), 0.0)
    # exact-zero self distances (the quadratic form leaves fp32 residue on
    # the diagonal; sklearn zeroes it too) — local density relies on it
    d2 = d2 * (1.0 - jnp.eye(A.shape[0], dtype=A.dtype))
    return jnp.sqrt(d2)


def pairwise_euclidean(A) -> np.ndarray:
    """Full pairwise euclidean distance matrix (R x R)."""
    return np.asarray(_pairwise_euclidean(jnp.asarray(np.asarray(A), jnp.float32)))


@functools.partial(jax.jit, static_argnames=("n_neighbors",))
def _local_density(D, n_neighbors: int):
    # mean distance to the n nearest neighbors, excluding self: the n+1
    # smallest distances include self at distance 0, so summing n+1 and
    # dividing by n reproduces cnmf.py:1067-1070 exactly.
    neg_top, _ = jax.lax.top_k(-D, n_neighbors + 1)
    return -neg_top.sum(axis=1) / n_neighbors


def local_density(l2_spectra, n_neighbors: int, D=None):
    """Per-row mean KNN distance over L2-normalized spectra.

    Returns ``(density (R,), D (R,R))`` so the caller can reuse the distance
    matrix for the clustergram (cnmf.py:1160-1166).
    """
    A = jnp.asarray(np.asarray(l2_spectra), jnp.float32)
    Dj = _pairwise_euclidean(A) if D is None else jnp.asarray(np.asarray(D), jnp.float32)
    dens = _local_density(Dj, int(n_neighbors))
    return np.asarray(dens), np.asarray(Dj)


@functools.partial(jax.jit, static_argnames=("k",))
def _silhouette_from_dists(D, labels, k: int):
    n = D.shape[0]
    onehot = jax.nn.one_hot(labels, k, dtype=D.dtype)       # (n, k)
    counts = onehot.sum(axis=0)                              # (k,)
    sums = D @ onehot                                        # (n, k) sum dist to each cluster

    own_count = counts[labels]
    own_sum = jnp.take_along_axis(sums, labels[:, None], axis=1)[:, 0]
    a = own_sum / jnp.maximum(own_count - 1.0, 1.0)

    mean_other = sums / jnp.maximum(counts[None, :], 1.0)
    # exclude own cluster and empty clusters from the b_i minimum
    mask = (jax.nn.one_hot(labels, k, dtype=bool)) | (counts[None, :] == 0)
    b = jnp.min(jnp.where(mask, jnp.inf, mean_other), axis=1)

    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
    s = jnp.where(own_count <= 1.0, 0.0, s)                  # sklearn: singletons score 0
    return jnp.mean(s)


@functools.partial(jax.jit, static_argnames=("k_pad",))
def _silhouette_packed(X, labels, k_pad: int, n_rows):
    """Silhouette at K_max/R_max-padded static shape: rows beyond
    ``n_rows`` (zero padding) are excluded from cluster sums, counts, and
    the final mean; clusters with no real members (including every index
    >= the sweep's actual k) are excluded from the b_i minimum exactly as
    empty clusters already are. Real-pair distances are computed on the
    same g-length contractions as the unpadded program, so per-K values
    match the per-K executable to fp-summation order."""
    n = X.shape[0]
    row_mask = (jnp.arange(n) < n_rows)
    row_maskf = row_mask.astype(X.dtype)
    D = _pairwise_euclidean(X)
    onehot = jax.nn.one_hot(labels, k_pad, dtype=D.dtype) * row_maskf[:, None]
    counts = onehot.sum(axis=0)
    sums = D @ onehot

    own_count = counts[labels]
    own_sum = jnp.take_along_axis(sums, labels[:, None], axis=1)[:, 0]
    a = own_sum / jnp.maximum(own_count - 1.0, 1.0)

    mean_other = sums / jnp.maximum(counts[None, :], 1.0)
    mask = (jax.nn.one_hot(labels, k_pad, dtype=bool)) | (counts[None, :] == 0)
    b = jnp.min(jnp.where(mask, jnp.inf, mean_other), axis=1)

    s = (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30)
    s = jnp.where(own_count <= 1.0, 0.0, s)
    return jnp.sum(s * row_maskf) / jnp.maximum(n_rows.astype(D.dtype), 1.0)


def silhouette_score(X, labels, k: int | None = None, D=None,
                     n_rows: int | None = None,
                     k_pad: int | None = None) -> float:
    """Mean silhouette coefficient, euclidean metric (cnmf.py:1097).

    ``n_rows``/``k_pad`` (together): the packed K-selection entry — X and
    labels arrive padded to a shared (R_max,) shape and one compiled
    program serves every K of a sweep (see :func:`~..ops.kmeans.kmeans`).
    """
    labels = jnp.asarray(np.asarray(labels), jnp.int32)
    if (n_rows is None) != (k_pad is None):
        raise ValueError("n_rows and k_pad must be passed together")
    if k_pad is not None:
        if D is not None:
            raise ValueError("precomputed D is not supported when packed")
        X = jnp.asarray(np.asarray(X), jnp.float32)
        return float(_silhouette_packed(X, labels, int(k_pad),
                                        jnp.int32(n_rows)))
    if k is None:
        k = int(np.max(np.asarray(labels))) + 1
    if D is None:
        D = _pairwise_euclidean(jnp.asarray(np.asarray(X), jnp.float32))
    else:
        D = jnp.asarray(np.asarray(D), jnp.float32)
    return float(_silhouette_from_dists(D, labels, int(k)))
