"""Tier-1 execution-planner smoke gate (ISSUE 17, wired in
scripts/verify_tier1.sh).

Runs the mini pipeline three times against the same prepared counts and
asserts the planner contract end to end:

  * run A (shipped ``auto`` defaults, telemetry on) records exactly ONE
    schema-valid ``plan`` event for the factorize, and
    ``cnmf-tpu plan <run_dir>`` renders it and dumps replayable JSON;
  * run B replays the dumped plan via ``CNMF_TPU_PLAN`` and reproduces
    run A bit-identically — same plan signature, byte-equal spectra for
    every replicate;
  * run C sets the ``=0`` escape hatches (``CNMF_TPU_ACCEL=0``,
    ``CNMF_TPU_PALLAS=0``) and stays byte-identical to the ``auto``
    defaults on this fixture — the flipped defaults only change stock
    programs where a measured win says so, never silently.

Exit 0 on success; any assertion or schema failure exits nonzero and
fails the gate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile

# runnable as `python scripts/plan_smoke.py` without installing the
# package: sys.path[0] is scripts/, the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ["CNMF_TPU_TELEMETRY"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.cli import main as cli_main
    from cnmf_torch_tpu.runtime.planner import ExecutionPlan, PLAN_ENV
    from cnmf_torch_tpu.utils import load_df_from_npz, save_df_to_npz
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    workdir = tempfile.mkdtemp(prefix="plan_smoke_")
    env0 = dict(os.environ)
    try:
        rng = np.random.default_rng(11)
        usage = rng.dirichlet(np.ones(4) * 0.3, size=180)
        spectra = rng.gamma(0.3, 1.0, size=(4, 120)) * 40.0 / 120
        counts = rng.poisson(usage @ spectra * 250.0).astype(np.float64)
        counts[counts.sum(axis=1) == 0, 0] = 1.0
        df = pd.DataFrame(counts, index=[f"c{i}" for i in range(180)],
                          columns=[f"g{j}" for j in range(120)])
        counts_fn = os.path.join(workdir, "counts.df.npz")
        save_df_to_npz(df, counts_fn)

        def run(name):
            """One prepare+factorize under the current environment;
            returns (run_dir, plan events, {file: spectra array})."""
            obj = cNMF(output_dir=workdir, name=name)
            obj.prepare(counts_fn, components=[3], n_iter=4, seed=7,
                        num_highvar_genes=90)
            obj.factorize()
            run_dir = os.path.join(workdir, name)
            tmp_dir = os.path.join(run_dir, "cnmf_tmp")
            ev_path = os.path.join(tmp_dir, name + ".events.jsonl")
            validate_events_file(ev_path)  # raises on any malformed line
            plans = [e for e in read_events(ev_path) if e["t"] == "plan"]
            # key by the `spectra.k_%d.iter_%d` suffix so the three
            # differently-named runs compare file-for-file
            mats = {f.split(".", 1)[1]:
                    load_df_from_npz(os.path.join(tmp_dir, f)).to_numpy()
                    for f in sorted(os.listdir(tmp_dir))
                    if ".spectra.k_" in f}
            assert mats, f"{name}: no replicate spectra written"
            return run_dir, plans, mats

        # -- run A: the shipped auto defaults -------------------------
        dir_a, plans_a, mats_a = run("auto")
        assert len(plans_a) == 1, \
            f"expected exactly 1 plan event, got {len(plans_a)}"
        sig_a = plans_a[0]["signature"]
        print(f"[plan-smoke] auto run: 1 schema-valid plan event, "
              f"signature {sig_a}")

        # `cnmf-tpu plan <run_dir>` renders the event and dumps JSON
        plan_fn = os.path.join(workdir, "plan.json")
        cli_main(["plan", dir_a, "--out", plan_fn])
        with open(plan_fn) as f:
            dumped = ExecutionPlan.from_json(f.read())
        assert dumped.signature() == sig_a, \
            (dumped.signature(), sig_a)

        # -- run B: CNMF_TPU_PLAN replay is bit-identical -------------
        os.environ[PLAN_ENV] = plan_fn
        _, plans_b, mats_b = run("replay")
        os.environ.clear()
        os.environ.update(env0)
        assert len(plans_b) == 1, plans_b
        assert plans_b[0]["signature"] == sig_a, \
            ("replay rebuilt a different plan under the pins",
             plans_b[0]["signature"], sig_a)
        assert set(mats_b) == set(mats_a), \
            (sorted(mats_a), sorted(mats_b))
        for fn in mats_a:
            assert np.array_equal(mats_a[fn], mats_b[fn]), \
                f"replay spectra differ: {fn}"
        print(f"[plan-smoke] --plan replay: signature match, "
              f"{len(mats_a)} spectra files byte-identical")

        # -- run C: the =0 escape hatch keeps the stock program -------
        os.environ["CNMF_TPU_ACCEL"] = "0"
        os.environ["CNMF_TPU_PALLAS"] = "0"
        _, _, mats_c = run("stock")
        os.environ.clear()
        os.environ.update(env0)
        for fn in mats_a:
            assert np.array_equal(mats_a[fn], mats_c[fn]), \
                f"ACCEL=0/PALLAS=0 escape hatch diverged: {fn}"
        print(f"[plan-smoke] OK: escape hatch byte-identical on "
              f"{len(mats_a)} spectra files")
        return 0
    finally:
        os.environ.clear()
        os.environ.update(env0)
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
