"""TPU-native consensus-NMF framework for single-cell RNA-seq.

A from-scratch JAX/XLA implementation with the capabilities of the reference
cNMF_torch pipeline (see SURVEY.md): prepare -> factorize -> combine ->
consensus -> k_selection, with factorization replicates running as batched,
sharded XLA programs instead of independent worker processes.
"""

from .utils.io import load_df_from_npz, save_df_to_npz
from .version import __version__

__all__ = ["cNMF", "Preprocess", "main", "save_df_to_npz", "load_df_from_npz", "__version__"]


def __getattr__(name):
    # Lazy imports keep `import cnmf_torch_tpu` light (no matplotlib etc.).
    # ImportError is translated to AttributeError so hasattr()/dir()-driven
    # tooling sees a missing attribute, not a crash.
    _lazy = {
        "cNMF": ("cnmf_torch_tpu.models.cnmf", "cNMF"),
        "Preprocess": ("cnmf_torch_tpu.models.preprocess", "Preprocess"),
        "main": ("cnmf_torch_tpu.cli", "main"),
    }
    if name in _lazy:
        import importlib

        module_name, attr = _lazy[name]
        try:
            return getattr(importlib.import_module(module_name), attr)
        except ImportError as exc:
            raise AttributeError(
                f"module {__name__!r} has no attribute {name!r} ({exc})"
            ) from exc
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
