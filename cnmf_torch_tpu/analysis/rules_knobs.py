"""Knob-hygiene rules: every env knob flows through the ONE registry.

``utils/envknobs.py`` declares every ``CNMF_*``/``JAX_*`` variable the
package consults (name/type/default/doc) and owns the strict typed
accessors. Anything else is drift waiting to happen — PR 6's audit found
38 raw ``os.environ`` sites against 3 modules importing the accessors,
which is how a typo'd knob silently no-ops and how the README table went
stale.

  * ``knob-raw-env`` — ``os.environ[...]``/``.get``/``os.getenv``/
    ``"X" in os.environ`` with a literal ``CNMF_*``/``JAX_*`` name in any
    module but ``utils/envknobs.py``. Dynamic iteration (the telemetry
    manifest's env snapshot) is untouched — the rule targets named reads.
  * ``knob-unregistered`` — an accessor call naming a knob absent from
    the registry (the accessors also refuse at runtime; the rule catches
    it before anything runs).
  * ``knob-doc-drift`` — registry vs README "Environment knobs" table,
    both directions, including stale default cells. The canonical table
    is generated (``cnmf-tpu lint --knob-table``), so the fix is a
    regenerate, never a hand-edit.
  * ``knob-plan-bypass`` (ISSUE 17) — a DISPATCH-class knob (the ones
    that pick WHICH program runs: encoding/recipe/kernel/layout/
    streaming/ingest/store/serve — ``runtime/planner.py:DISPATCH_KNOBS``)
    read through the typed accessors outside the planner-owned files and
    outside the registered resolver functions (``PLAN_ACCESSORS``). One
    resolution site per knob is what makes the logged plan THE dispatch
    rather than a parallel reimplementation that can drift; a new lane
    must register its resolver in the planner, not scatter a knob read.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, dotted_name

KNOB_PREFIXES = ("CNMF_", "JAX_")
ACCESSORS = {"env_int", "env_float", "env_str", "env_flag", "env_is_set"}
ENV_OWNER = "utils/envknobs.py"


def _is_environ(ctx: FileContext, node: ast.AST) -> bool:
    """The expression ``os.environ`` (or a from-imported alias of it)."""
    name = ctx.imports.resolve(dotted_name(node))
    return name in ("os.environ", "environ")


def _literal_knob(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith(KNOB_PREFIXES):
        return node.value
    return None


def _module_str_constants(tree: ast.Module) -> dict[str, str]:
    """Module-level ``NAME = "literal"`` assignments — the ``*_ENV``
    constant idiom every knob-owning module uses. Lets the plan-bypass
    rule resolve ``env_str(PALLAS_ENV, ...)``-style reads, not just
    string literals."""
    out: dict[str, str] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) \
                and isinstance(stmt.value, ast.Constant) \
                and isinstance(stmt.value.value, str):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = stmt.value.value
    return out


def _knob_arg(node: ast.Call, consts: dict[str, str]) -> str | None:
    """The knob NAME an accessor call reads: a string literal, or a
    module-level ``*_ENV`` constant. Unresolvable expressions return
    None (never a false positive)."""
    if not node.args:
        return None
    lit = _literal_knob(node.args[0])
    if lit is not None:
        return lit
    arg = node.args[0]
    if isinstance(arg, ast.Name):
        val = consts.get(arg.id)
        if val is not None and val.startswith(KNOB_PREFIXES):
            return val
    return None


def check(ctx: FileContext):
    findings: list[Finding] = []
    if ctx.relpath.replace("\\", "/").endswith(ENV_OWNER):
        return findings
    from ..runtime.planner import (DISPATCH_KNOBS, PLAN_ACCESSORS,
                                   PLAN_OWNER_FILES)
    from ..utils.envknobs import REGISTRY

    relpath = ctx.relpath.replace("\\", "/")
    plan_owner = any(relpath.endswith(sfx) for sfx in PLAN_OWNER_FILES)
    consts = _module_str_constants(ctx.tree)

    def _in_plan_accessor(node: ast.AST) -> bool:
        """Whether the call sits (possibly nested) inside one of the
        registered resolver functions."""
        return any(isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and a.name in PLAN_ACCESSORS
                   for a in ctx.ancestors(node))

    hint = ("read it through utils/envknobs.py (env_int/env_float/"
            "env_str/env_flag), registering the knob there")
    for node in ast.walk(ctx.tree):
        name = None
        if isinstance(node, ast.Subscript) and _is_environ(ctx, node.value):
            name = _literal_knob(node.slice)
        elif isinstance(node, ast.Call):
            resolved = ctx.resolve_call(node)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("get", "setdefault", "pop") \
                    and _is_environ(ctx, node.func.value) and node.args:
                name = _literal_knob(node.args[0])
            elif resolved == "os.getenv" and node.args:
                name = _literal_knob(node.args[0])
            elif (resolved or "").split(".")[-1] in ACCESSORS \
                    and node.args:
                knob = _literal_knob(node.args[0])
                if knob is not None and knob not in REGISTRY:
                    findings.append(ctx.finding(
                        node, "knob-unregistered",
                        f"env knob `{knob}` is not declared in the "
                        "utils/envknobs.py registry",
                        "add a Knob(name, kind, default, doc) entry"))
                    continue
                plan_knob = _knob_arg(node, consts)
                if plan_knob in DISPATCH_KNOBS and not plan_owner \
                        and not _in_plan_accessor(node):
                    findings.append(ctx.finding(
                        node, "knob-plan-bypass",
                        f"dispatch-class knob `{plan_knob}` read outside "
                        "the execution planner and its registered "
                        "resolvers (runtime/planner.py:PLAN_ACCESSORS)",
                        "resolve it inside the owning resolver function "
                        "(or register a new resolver in PLAN_ACCESSORS) "
                        "so the logged plan stays THE dispatch"))
                continue
        elif isinstance(node, ast.Compare) \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops) \
                and node.comparators \
                and _is_environ(ctx, node.comparators[0]):
            name = _literal_knob(node.left)
        if name is not None:
            findings.append(ctx.finding(
                node, "knob-raw-env",
                f"raw os.environ access to `{name}` outside "
                f"{ENV_OWNER}", hint))
    return findings


def check_knob_docs(readme_path: str) -> list[Finding]:
    """Cross-check the registry against the README knob table, both ways.
    Runs once per lint invocation (project-level, not per-file)."""
    from ..utils.envknobs import REGISTRY, parse_knob_table

    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    table = parse_knob_table(text)
    table_line = 1
    for i, line in enumerate(text.splitlines(), start=1):
        if line.strip().startswith("| knob |"):
            table_line = i
            break

    from .engine import _relpath

    rel = _relpath(readme_path)
    findings: list[Finding] = []
    documented = {n: k for n, k in REGISTRY.items() if k.documented}
    for name, knob in documented.items():
        if name not in table:
            findings.append(Finding(
                rel, table_line, "knob-doc-drift",
                f"registered knob `{name}` is missing from the README "
                "env-knob table",
                "regenerate the table with `cnmf-tpu lint --knob-table`",
                f"missing row: {name}"))
        elif table[name][0] != knob.default:
            findings.append(Finding(
                rel, table_line, "knob-doc-drift",
                f"README default for `{name}` is {table[name][0]!r}; the "
                f"registry says {knob.default!r}",
                "regenerate the table with `cnmf-tpu lint --knob-table`",
                f"stale default: {name}"))
        elif table[name][1] != knob.doc:
            findings.append(Finding(
                rel, table_line, "knob-doc-drift",
                f"README description for `{name}` differs from the "
                "registry doc (the table is generated, not hand-edited)",
                "regenerate the table with `cnmf-tpu lint --knob-table`",
                f"stale doc: {name}"))
    for name in table:
        if name not in documented:
            findings.append(Finding(
                rel, table_line, "knob-doc-drift",
                f"README documents `{name}`, which is not a registered "
                "knob",
                "register it in utils/envknobs.py or drop the row",
                f"unregistered row: {name}"))
    return findings
