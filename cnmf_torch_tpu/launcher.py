"""End-to-end pipeline launcher — the reference ``Extras/run_parallel.py``
equivalent (``/root/reference/Extras/run_parallel.py:1-70``: prepare -> GNU
parallel factorize workers -> combine -> k_selection_plot -> clean).

Two engines replace GNU parallel:

  * ``subprocess`` — N independent OS worker processes, round-robin sharded
    by ``--worker-index`` over the replicate ledger, exactly the reference's
    model (files as the dataplane). Right for a fleet of single-chip hosts
    with a shared filesystem and for CPU dev boxes. Self-healing (ISSUE 5):
    a worker that dies (or exceeds ``CNMF_TPU_WORKER_TIMEOUT`` seconds and
    is killed) is respawned onto its own unfinished ledger shard with
    ``--skip-completed-runs`` — resume rides the eager, atomic per-replicate
    artifacts AND, on the rowsharded path, the newest valid mid-run pass
    checkpoint (``runtime/checkpoint.py``), so a worker killed 40 passes
    into a multi-hour replicate restarts mid-run, not from scratch — after
    an exponential backoff with deterministic per-worker jitter
    (:func:`respawn_delay`), up to ``CNMF_TPU_WORKER_RESPAWNS`` times
    (default 1). Only when the respawn budget is exhausted does the run
    fall back to the reference's dead-worker tolerance: combine with
    ``skip_missing_files=True``.
  * ``multihost`` — ONE single-controller JAX program spanning N processes
    stitched by ``jax.distributed`` (``parallel/multihost.py``); factorize
    runs over the 2-D (replicates x cells) mesh, with the cells-psum on ICI
    and the replicate axis across hosts. On a real TPU pod you normally
    launch that yourself (same command on every host); this engine spawns
    the N processes locally — with ``--devices-per-host`` virtual CPU
    devices each — which is how the multi-host path is CI-tested without a
    pod.

Python API: :func:`run_pipeline`. CLI: ``cnmf-tpu run_parallel ...``.
"""

from __future__ import annotations

import glob
import os
import socket
import subprocess
import sys
import warnings

__all__ = ["run_pipeline", "respawn_delay"]


def respawn_delay(backoff_s: float, attempt: int, worker_i: int) -> float:
    """Respawn backoff for a dead worker: exponential base
    (``backoff_s * 2^(attempt-1)``) times a deterministic per-worker
    jitter factor in [1, 1.5). The jitter derives from the worker index
    alone (Knuth multiplicative hash — no RNG, so resume/replay timing is
    reproducible): when a whole fleet dies at once (node preemption,
    shared-filesystem blip), the respawns fan out across half a backoff
    period instead of restarting in lockstep and re-stampeding whatever
    killed them."""
    base = float(backoff_s) * (2 ** (max(int(attempt), 1) - 1))
    jitter = ((int(worker_i) * 2654435761) & 0xFFFFFFFF) % 1024 / 2048.0
    return base * (1.0 + jitter)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_cmd(output_dir: str, name: str, extra: list[str]) -> list[str]:
    return [sys.executable, "-m", "cnmf_torch_tpu", "factorize",
            "--output-dir", output_dir, "--name", name] + extra


def _run_subprocess_workers(
        output_dir: str, name: str, total_workers: int,
        factorize_flags: list[str], base_env: dict,
        poll_s: float = 0.05) -> tuple[set[int], set[int]]:
    """Run the subprocess-engine worker fleet with self-healing: per-worker
    wall timeouts (``CNMF_TPU_WORKER_TIMEOUT`` seconds; 0/unset = none)
    and bounded exponential-backoff respawn of dead workers
    (``CNMF_TPU_WORKER_RESPAWNS`` attempts, delays
    ``CNMF_TPU_WORKER_BACKOFF_S * 2^(attempt-1)``). A respawned worker
    resumes its OWN round-robin ledger shard via ``--skip-completed-runs``
    — factorize probes AND validates the eager per-replicate artifacts, so
    a SIGKILL'd predecessor's torn files are rerun, not trusted. Returns
    ``(failed, unhealthy)``: worker indices that stayed dead after the
    respawn budget, and workers that exited with
    ``resilience.UNHEALTHY_EXIT_CODE`` (below the min-healthy-frac floor
    — a deterministic policy failure that is neither respawned nor
    degraded around; the caller aborts the pipeline)."""
    import time

    from .runtime.resilience import UNHEALTHY_EXIT_CODE

    from .utils.envknobs import env_float, env_int

    respawn_limit = env_int("CNMF_TPU_WORKER_RESPAWNS", 1, lo=0)
    timeout_s = env_float("CNMF_TPU_WORKER_TIMEOUT", 0.0, lo=0.0)
    backoff_s = env_float("CNMF_TPU_WORKER_BACKOFF_S", 0.5, lo=0.0)

    def spawn(i: int, resume: bool):
        flags = ["--worker-index", str(i),
                 "--total-workers", str(total_workers)]
        if resume and "--skip-completed-runs" not in factorize_flags:
            flags.append("--skip-completed-runs")
        return subprocess.Popen(
            _worker_cmd(output_dir, name, flags + factorize_flags),
            env=base_env)

    now = time.monotonic
    procs = {i: spawn(i, False) for i in range(total_workers)}
    deadline = {i: (now() + timeout_s if timeout_s > 0 else None)
                for i in procs}
    attempts = {i: 0 for i in procs}
    respawn_at: dict[int, float] = {}
    failed: set[int] = set()
    unhealthy: set[int] = set()

    while procs or respawn_at:
        for i in [j for j, t in respawn_at.items() if now() >= t]:
            del respawn_at[i]
            procs[i] = spawn(i, True)
            deadline[i] = now() + timeout_s if timeout_s > 0 else None
        for i in list(procs):
            p = procs[i]
            rc = p.poll()
            if rc is None:
                if deadline[i] is not None and now() > deadline[i]:
                    warnings.warn(
                        "factorize worker %d exceeded CNMF_TPU_WORKER_"
                        "TIMEOUT=%gs; killing it" % (i, timeout_s),
                        RuntimeWarning)
                    p.kill()
                    p.wait()
                    rc = p.returncode
                else:
                    continue
            del procs[i]
            if rc == 0:
                continue
            if rc == UNHEALTHY_EXIT_CODE:
                # below the min-healthy-frac floor: deterministic — a
                # respawn reruns the same derived seeds and fails the
                # same way, so don't burn the budget
                unhealthy.add(i)
                continue
            if attempts[i] < respawn_limit:
                attempts[i] += 1
                delay = respawn_delay(backoff_s, attempts[i], i)
                warnings.warn(
                    "factorize worker %d died (rc=%s); respawning onto its "
                    "unfinished ledger shard in %.1fs (attempt %d/%d)"
                    % (i, rc, delay, attempts[i], respawn_limit),
                    RuntimeWarning)
                respawn_at[i] = now() + delay
            else:
                failed.add(i)
                warnings.warn(
                    "factorize worker %d exited with rc=%d; its replicates "
                    "will be skipped at combine (the reference's dead-worker "
                    "tolerance, cnmf.py:904-909)" % (i, rc),
                    RuntimeWarning)
        if procs or respawn_at:
            time.sleep(poll_s)
    return failed, unhealthy


def run_pipeline(counts: str, output_dir: str, name: str,
                 components, n_iter: int = 100, total_workers: int = 1,
                 seed: int | None = None, numgenes: int = 2000,
                 genes_file: str | None = None, tpm: str | None = None,
                 beta_loss: str = "frobenius", init: str = "random",
                 max_nmf_iter: int = 1000, batch_size: int = 5000,
                 engine: str = "subprocess",
                 devices_per_host: int | None = None,
                 clean: bool = False, k_selection: bool = True,
                 env_extra: dict | None = None,
                 factorize_flags: list[str] | None = None) -> None:
    """prepare -> parallel factorize -> combine -> k_selection_plot.

    ``engine='subprocess'``: ``total_workers`` OS processes shard the ledger
    (the reference's GNU-parallel model). ``engine='multihost'``:
    ``total_workers`` JAX processes form one distributed program over a 2-D
    mesh; ``devices_per_host`` forces that many virtual CPU devices per
    process (pod simulation — omit on real multi-chip hosts).

    ``factorize_flags``: extra CLI flags forwarded verbatim to every
    factorize worker (e.g. ``["--mesh-2d"]``, ``["--rowshard"]``,
    ``["--sequential"]``) — how the run_parallel subcommand's
    factorize-mode options reach the workers.
    """
    factorize_flags = list(factorize_flags or [])
    # the CLI's parser default is -1 ("all"); range(-1) would spawn zero
    # workers and the run would only fail much later at combine
    total_workers = max(int(total_workers), 1)
    if engine not in ("subprocess", "multihost"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "multihost" and devices_per_host is None:
        # this process is about to initialize a JAX backend for prepare();
        # N spawned children sharing the parent's real TPU runtime would
        # contend for the chips and hang or crash. The local-spawn engine
        # is only safe when each child gets its own virtual CPU devices; on
        # a real pod, launch the same command on every host instead
        # (docs/Stepwise_Guide.md). Checked BEFORE prepare so the
        # misconfiguration costs seconds, not an atlas-scale prepare pass.
        import jax

        if jax.default_backend() not in ("cpu",):
            raise RuntimeError(
                "engine='multihost' without devices_per_host spawns "
                "local JAX processes that would contend with this "
                "process's %r backend. Pass devices_per_host=N for a "
                "CPU-simulated pod, or launch one process per host "
                "yourself with CNMF_PROCESS_ID/--distributed (see "
                "docs/Stepwise_Guide.md)." % jax.default_backend())
    from .models.cnmf import cNMF

    obj = cNMF(output_dir=output_dir, name=name)
    obj.prepare(counts, components=components, n_iter=n_iter, seed=seed,
                num_highvar_genes=numgenes, genes_file=genes_file,
                tpm_fn=tpm, beta_loss=beta_loss, init=init,
                max_NMF_iter=max_nmf_iter, batch_size=batch_size,
                total_workers=max(total_workers, 1))

    base_env = dict(os.environ)
    # workers must import this package regardless of their cwd (source
    # checkouts aren't necessarily pip-installed)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([base_env["PYTHONPATH"]]
                      if base_env.get("PYTHONPATH") else []))
    if env_extra:
        base_env.update({k: str(v) for k, v in env_extra.items()})

    any_failed = False
    if engine == "subprocess":
        failed, unhealthy = _run_subprocess_workers(
            output_dir, name, total_workers, factorize_flags, base_env)
        if unhealthy:
            # the min-healthy-frac floor is a hard guarantee end-to-end:
            # degrading around it with skip-missing combine would produce
            # exactly the under-powered consensus it exists to prevent
            raise RuntimeError(
                "factorize worker(s) %s reported too few healthy "
                "replicates (below CNMF_TPU_MIN_HEALTHY_FRAC; see their "
                "output above) — aborting before combine/consensus"
                % sorted(unhealthy))
        any_failed = bool(failed)
        if len(failed) == total_workers:
            # nothing survived — combine/k_selection would only crash on
            # missing files with a misleading traceback
            raise RuntimeError(
                f"all {total_workers} factorize workers failed (respawn "
                "budget exhausted); see their output above")
    elif engine == "multihost":
        port = _free_port()
        procs = []
        for pid in range(total_workers):
            env = dict(base_env,
                       CNMF_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                       CNMF_NUM_PROCESSES=str(total_workers),
                       CNMF_PROCESS_ID=str(pid))
            if devices_per_host:
                env["CNMF_SIM_CPU_DEVICES"] = str(devices_per_host)
            extra = ["--mesh-2d", "--distributed"] + [
                f for f in factorize_flags if f != "--mesh-2d"]
            cmd = _worker_cmd(output_dir, name, extra)
            procs.append((pid, subprocess.Popen(cmd, env=env)))
        rcs = [(pid, p.wait()) for pid, p in procs]
        bad = [(pid, rc) for pid, rc in rcs if rc]
        if bad:
            # a single-controller program has no partial completion: one
            # dead process stalls the collective, unlike the subprocess
            # engine's independent workers
            raise RuntimeError(
                f"multihost factorize failed on processes {bad}")

    obj.combine(skip_missing_files=any_failed)
    if k_selection:
        obj.k_selection_plot(close_fig=True)

    if clean:
        # the reference's `rm .../cnmf_tmp/*.iter_*.df.npz`
        # (run_parallel.py:64): per-replicate spectra are redundant once
        # merged_spectra exists. Also sweep pid-suffixed atomic-write
        # temp files orphaned by killed workers (utils/anndata_lite
        # .atomic_artifact) — no reader ever trusts them, but they
        # accumulate across preemptions; all workers have exited by here,
        # so none are live.
        run_dir = os.path.join(output_dir, name)
        for pattern in (os.path.join("cnmf_tmp", "*.iter_*.df.npz"),
                        # pass checkpoints are normally discarded when
                        # their replicate's artifact lands; a worker that
                        # exhausted its respawn budget can leave one behind
                        os.path.join("cnmf_tmp", "*.ckpt.k_*.npz"),
                        # atomic-write temp orphans land wherever their
                        # artifact lives: intermediates in cnmf_tmp/, the
                        # txt/stats finals in the run dir itself
                        os.path.join("cnmf_tmp", "*.tmp-*"),
                        "*.tmp-*"):
            for f in glob.glob(os.path.join(run_dir, pattern)):
                os.remove(f)
