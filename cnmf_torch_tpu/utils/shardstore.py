"""Out-of-core row-slab shard store for the normalized counts matrix.

ROADMAP item 1's last memory wall: every factorize path starts with
``read_h5ad(normalized_counts)``, so each launcher worker (and each
multihost process) materializes the FULL normalized matrix in host RAM
before a single byte streams to the mesh — an N-workers x full-matrix
host-memory multiplier that caps atlas size at host RAM, not HBM. The
rowshard/online solvers only ever need tiny ``(A, B)`` pass statistics
resident; MPI-FAUN (arXiv 1609.09154) and the distributed out-of-memory
NMF design (arXiv 2202.09518) both reduce to "never load what you don't
own". This module is that ownership layer:

  * :func:`write_shard_store` — prepare-time writer: the matrix lands as
    per-slab ``.npz`` shards (CSR triplets or dense blocks) plus a JSON
    manifest carrying shapes, dtypes, per-slab row ranges / nnz / value
    sums / max-row-nnz, and per-slab content digests. Every file is
    written via ``atomic_artifact`` and the manifest lands LAST, so a
    crash mid-write leaves an unopenable (and therefore ignored) store,
    never a torn one.
  * :class:`ShardStore` — validated reader. ``read_slab`` verifies each
    slab's digest on every read and RE-READS from disk on a mismatch
    (bounded by ``CNMF_TPU_SHARD_RETRIES``) — a torn slab is detected,
    surfaced as a telemetry ``fault``, and healed or failed loudly,
    never trusted. Row-range queries (:meth:`slab_indices_for_rows`,
    :meth:`worker_ranges`) expose slab ownership to launchers/tools;
    in-pipeline ownership is enforced by :class:`SlabCursor` row bounds
    plus the staging layer's addressable-shard overlap
    (``parallel/streaming.py:stream_store_sharded``).
  * :class:`SlabCursor` — a row-range view of the store that the
    streaming engine (``parallel/streaming.py``) consumes as the
    disk-producer stage of its three-stage (disk read -> host prep ->
    h2d) pipeline.
  * :class:`HostResidency` — allocation accounting for the slab-budget
    guarantee: store-backed staging charges every live host slab buffer
    against it, and its high-water mark is asserted in tests / reported
    by ``bench.py --tier ingest`` — the "host footprint bounded by
    ``CNMF_TPU_OOC_BUDGET_BYTES``, not matrix size" claim is measured,
    not vibed.

Knobs (``utils/envknobs.py`` registry): ``CNMF_TPU_OOC`` (auto|0|1),
``CNMF_TPU_OOC_BUDGET_BYTES`` (host slab budget), ``CNMF_TPU_OOC_SLAB_ROWS``
(write-time slab rows; 0 = derived from the budget),
``CNMF_TPU_OOC_SHARD_BYTES`` (per-device resident-shard budget gating the
slab-looped solver pass, ``parallel/rowshard.py``).

All reads and writes flow through a :class:`~.storebackend.StoreBackend`
transport (``utils/storebackend.py``): with ``CNMF_TPU_STORE_URI`` unset
that is the POSIX ``LocalBackend``, byte-for-byte today's behavior; an
``http(s)://`` URI swaps in the ``RemoteBackend`` (retry/backoff,
hedged reads, read-through cache, graceful degradation) with the digest
validation, manifest-last protocol, and torn-read healing here — above
the seam — carrying over unchanged. An unhealable remote object raises
:class:`~.storebackend.RemoteStoreError` (re-exported here), which
deliberately ESCAPES the torn-read retry ladder and propagates to the
resilience ledger like :class:`TornShardError` does.

Kept jax-free so the writer/reader can run in IO-only contexts (prepare,
``--clean`` sweeps, report tooling) without backend initialization.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
import warnings
import zipfile

import numpy as np
import scipy.sparse as sp

from .envknobs import env_int, env_str
from .storebackend import (RemoteStoreError, resolve_backend,
                           store_cache_dir)

__all__ = [
    "OOC_ENV",
    "OOC_BUDGET_ENV",
    "OOC_SLAB_ROWS_ENV",
    "OOC_SHARD_BYTES_ENV",
    "SHARD_RETRIES_ENV",
    "STORE_SCHEMA",
    "TornShardError",
    "RemoteStoreError",
    "ShardStore",
    "SlabCursor",
    "HostResidency",
    "ooc_mode",
    "ooc_budget_bytes",
    "ooc_shard_bytes",
    "shard_reread_retries",
    "host_matrix_bytes",
    "host_rss_peak_bytes",
    "write_shard_store",
    "open_shard_store",
    "probe_shard_store",
    "sweep_store_temps",
]

OOC_ENV = "CNMF_TPU_OOC"
OOC_BUDGET_ENV = "CNMF_TPU_OOC_BUDGET_BYTES"
OOC_SLAB_ROWS_ENV = "CNMF_TPU_OOC_SLAB_ROWS"
OOC_SHARD_BYTES_ENV = "CNMF_TPU_OOC_SHARD_BYTES"
SHARD_RETRIES_ENV = "CNMF_TPU_SHARD_RETRIES"

STORE_SCHEMA = 1

_MANIFEST = "manifest.json"
_NAMES = "names.npz"

_DEFAULT_BUDGET = 1 << 30


class TornShardError(RuntimeError):
    """A shard-store file exists but cannot be trusted (unreadable,
    truncated, digest mismatch, wrong shapes/schema)."""


def ooc_mode() -> str:
    """``CNMF_TPU_OOC``: ``auto`` (default — store written at prepare when
    the matrix exceeds the slab budget, read whenever present), ``1``
    (store forced AND authoritative: the h5ad normalized-counts copy is
    skipped), ``0`` (subsystem off)."""
    raw = env_str(OOC_ENV, "auto").strip().lower() or "auto"
    if raw not in ("auto", "0", "1"):
        raise ValueError(
            f"{OOC_ENV}={raw!r}: expected 'auto', '0', or '1'")
    return raw


def ooc_budget_bytes() -> int:
    """Per-worker HOST slab-residency budget (``CNMF_TPU_OOC_BUDGET_BYTES``,
    default 1 GiB): in-flight host slab buffers during store-backed
    ingestion stay under it (a single slab is the irreducible floor), and
    prepare's ``auto`` mode writes the store when the matrix's host
    footprint exceeds it."""
    return env_int(OOC_BUDGET_ENV, _DEFAULT_BUDGET, lo=1)


def ooc_shard_bytes() -> int:
    """Per-DEVICE resident-shard budget (``CNMF_TPU_OOC_SHARD_BYTES``)
    above which the rowsharded solver runs each pass as a loop over
    streamed X slab groups instead of staging the shard resident.
    ``0`` (default) derives from reported device memory at the dispatch
    site (``parallel/rowshard.py``) — effectively "stage resident" on
    backends that report no stats (CPU tests)."""
    return env_int(OOC_SHARD_BYTES_ENV, 0, lo=0)


def shard_reread_retries() -> int:
    """Shard-layer re-read budget for a torn/digest-mismatched slab read
    (``CNMF_TPU_SHARD_RETRIES``, default 2; ``0`` disables). The same
    knob also bounds the staging pipeline's per-slab upload retries
    (``parallel/streaming.py``) — the two shard-layer scopes; network-
    TRANSPORT retries are governed separately by
    ``CNMF_TPU_STORE_RETRIES`` (``utils/storebackend.py``)."""
    return env_int(SHARD_RETRIES_ENV, 2, lo=0)


def host_matrix_bytes(X) -> int:
    """Host-RAM footprint of a matrix as loaded (CSR buffers or the dense
    array) — the quantity the slab budget bounds."""
    if sp.issparse(X):
        Xc = X.tocsr()
        return int(Xc.data.nbytes + Xc.indices.nbytes + Xc.indptr.nbytes)
    return int(np.asarray(X).nbytes)


def host_rss_peak_bytes() -> int:
    """This process's lifetime peak resident set size in bytes — the
    bench/report signal for the host-memory bound; 0 where unavailable.
    ``ru_maxrss`` is KiB on Linux but BYTES on macOS."""
    try:
        import resource
        import sys

        raw = int(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)
        return raw if sys.platform == "darwin" else raw * 1024
    except Exception:
        return 0


class HostResidency:
    """Thread-safe live-bytes ledger for one staging call: every host slab
    buffer charges on allocation and releases when dropped; ``peak`` is
    the high-water mark the slab-budget tests assert against."""

    def __init__(self):
        self._lock = threading.Lock()
        self.live = 0
        self.peak = 0

    def charge(self, nbytes: int):
        with self._lock:
            self.live += int(nbytes)
            if self.live > self.peak:
                self.peak = self.live

    def release(self, nbytes: int):
        with self._lock:
            self.live = max(0, self.live - int(nbytes))


# ---------------------------------------------------------------------------
# digests
# ---------------------------------------------------------------------------

def _arrays_digest(arrays) -> str:
    """sha1 over the raw bytes of an ordered array list — the per-slab
    content digest verified on every read."""
    h = hashlib.sha1()
    for a in arrays:
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype.str).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _slab_arrays(block, fmt: str):
    if fmt == "csr":
        return (block.data, block.indices, block.indptr)
    return (np.ascontiguousarray(block),)


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------

def _auto_slab_rows(g: int, itemsize: int, budget: int) -> int:
    """Write-time slab rows: dense-equivalent slab bytes <= budget/4 so
    the reader's depth window (>= 2 slabs in flight plus a commit drain)
    fits the budget, floored at 256 rows so tiny budgets don't explode
    the slab count."""
    rows = env_int(OOC_SLAB_ROWS_ENV, 0, lo=0)
    if rows:
        return rows
    row_bytes = max(int(g) * int(itemsize), 1)
    return max(256, (int(budget) // 4) // row_bytes)


def write_shard_store(store_dir, X, obs_names=None, var_names=None,
                      slab_rows: int | None = None, events=None,
                      backend=None) -> dict:
    """Write the row-slab shard store for matrix ``X`` under ``store_dir``.

    Layout: ``slab_NNNNN.npz`` per slab (CSR triplets ``data``/``indices``/
    ``indptr`` or a dense ``block``), ``names.npz`` (obs/var name arrays),
    and ``manifest.json`` — every object through the transport backend
    (local puts via ``atomic_artifact``; remote puts retried), manifest
    LAST so readers only ever see complete stores. Values land as float32
    (the solve dtype; prepare's f64 moment accumulators never reach disk).
    Returns the manifest dict.
    """
    store_dir = os.fspath(store_dir)
    if backend is None:
        backend = resolve_backend(store_dir)
    # a previous prepare's slabs are stale the moment this writer starts;
    # remove them up front so a shrinking slab count can't leave orphans
    # a future manifest never references (the manifest-last protocol makes
    # the store unopenable until this write completes)
    _clear_backend(backend)

    fmt = "csr" if sp.issparse(X) else "dense"
    if fmt == "csr":
        X = X.tocsr().astype(np.float32)
    else:
        X = np.asarray(X, dtype=np.float32)
    n, g = X.shape
    if slab_rows is None:
        slab_rows = _auto_slab_rows(g, 4, ooc_budget_bytes())
    slab_rows = max(int(slab_rows), 1)

    slabs = []
    # n == 0 writes ZERO slabs (the reader's contiguity check expects an
    # empty slab list for an empty matrix, never a degenerate [0, 0) slab)
    for i, lo in enumerate(range(0, n, slab_rows)):
        hi = min(lo + slab_rows, n)
        block = X[lo:hi]
        arrays = _slab_arrays(block, fmt)
        fn = "slab_%05d.npz" % i
        # serialize to memory, hand bytes to the transport (the npz
        # bytes never touch disk non-atomically: the local backend
        # lands them via atomic_artifact, remote puts are whole-object)
        buf = io.BytesIO()
        if fmt == "csr":
            np.savez(buf, data=arrays[0], indices=arrays[1],  # cnmf-lint: disable=artifact-nonatomic
                     indptr=arrays[2])
        else:
            np.savez(buf, block=arrays[0])  # cnmf-lint: disable=artifact-nonatomic
        backend.put(fn, buf.getvalue(), op="slab", events=events)
        if fmt == "csr":
            nnz = int(block.nnz)
            row_nnz = np.diff(block.indptr)
            max_row = int(row_nnz.max()) if row_nnz.size else 0
            value_sum = float(block.data.sum(dtype=np.float64))
            raw_bytes = int(sum(a.nbytes for a in arrays))
        else:
            nnz = int(np.count_nonzero(block))
            max_row = (int(np.count_nonzero(block, axis=1).max())
                       if block.shape[0] else 0)
            value_sum = float(block.sum(dtype=np.float64))
            raw_bytes = int(arrays[0].nbytes)
        slabs.append({
            "i": i, "row0": int(lo), "row1": int(hi), "nnz": nnz,
            "max_row_nnz": max_row, "value_sum": value_sum,
            "raw_bytes": raw_bytes, "digest": _arrays_digest(arrays),
            "file": fn,
        })
        if hi >= n:
            break

    obs = np.asarray([] if obs_names is None
                     else [str(s) for s in obs_names], dtype=object)
    var = np.asarray([] if var_names is None
                     else [str(s) for s in var_names], dtype=object)
    buf = io.BytesIO()
    np.savez(buf, obs=obs, var=var)  # cnmf-lint: disable=artifact-nonatomic
    backend.put(_NAMES, buf.getvalue(), op="meta", events=events)
    names_digest = _arrays_digest(
        (obs.astype(str).astype("U"), var.astype(str).astype("U")))

    from ..runtime.checkpoint import input_digest

    manifest = {
        "schema": STORE_SCHEMA,
        "shape": [int(n), int(g)],
        "dtype": "<f4",
        "format": fmt,
        "slab_rows": int(slab_rows),
        "slabs": slabs,
        "names_file": _NAMES,
        "names_digest": names_digest,
        # pins the store to the exact matrix prepare normalized — the
        # worker-0 staleness sweep compares it against the current h5ad
        "input_digest": input_digest(X),
    }
    core = json.dumps({k: manifest[k] for k in
                       ("schema", "shape", "dtype", "format", "slab_rows",
                        "input_digest")},
                      sort_keys=True)
    h = hashlib.sha1(core.encode())
    for s in slabs:
        h.update(s["digest"].encode())
    # the checkpoint-identity digest: a re-prepare (new slabs, new input)
    # changes it, so resumes across a re-prepare restart instead of
    # splicing two matrices' trajectories (runtime/checkpoint.py)
    manifest["store_digest"] = h.hexdigest()

    backend.put(_MANIFEST, json.dumps(manifest).encode("utf-8"),
                op="manifest", events=events)
    if events is not None:
        events.emit("dispatch", decision="shard_store_write",
                    context={"slabs": len(slabs), "rows": int(n),
                             "format": fmt, "slab_rows": int(slab_rows),
                             "backend": backend.kind,
                             "store_bytes": int(sum(s["raw_bytes"]
                                                    for s in slabs))})
    return manifest


def _clear_backend(backend):
    """Delete a previous store generation through the transport —
    manifest FIRST, so a crash mid-clear leaves an unopenable store,
    never a manifest referencing deleted slabs."""
    stale = [s for s in backend.list()
             if s == _MANIFEST or s == _NAMES or s.startswith("slab_")
             or ".tmp-" in s]
    for s in sorted(stale, key=lambda fn: fn != _MANIFEST):
        backend.delete(s)


def _clear_store(store_dir: str):
    for fn in os.listdir(store_dir):
        if (fn == _MANIFEST or fn == _NAMES or fn.startswith("slab_")
                or ".tmp-" in fn):
            try:
                os.unlink(os.path.join(store_dir, fn))
            except OSError:
                pass


def remove_store(store_dir) -> None:
    """Delete a LOCAL store directory, its contents, and its read-through
    cache (stale store sweep). Remote objects are not touched here — a
    re-prepare clears them through the backend (:func:`_clear_backend`)
    under the manifest-last protocol."""
    store_dir = os.fspath(store_dir)
    cache_dir = store_cache_dir(store_dir)
    if os.path.isdir(cache_dir):
        for fn in os.listdir(cache_dir):
            try:
                os.unlink(os.path.join(cache_dir, fn))
            except OSError:
                pass
        try:
            os.rmdir(cache_dir)
        except OSError:
            pass
    if not os.path.isdir(store_dir):
        return
    _clear_store(store_dir)
    try:
        os.rmdir(store_dir)
    except OSError:
        pass


def sweep_store_temps(store_dir) -> int:
    """Remove orphaned atomic-write temp files inside a store directory
    AND its read-through cache (killed writers leave pid-suffixed temps
    no reader ever trusts); returns the count removed. Complete stores
    and digest-valid cache entries are left intact."""
    store_dir = os.fspath(store_dir)
    n = 0
    for d in (store_dir, store_cache_dir(store_dir)):
        if not os.path.isdir(d):
            continue
        for fn in os.listdir(d):
            if ".tmp-" in fn:
                try:
                    os.unlink(os.path.join(d, fn))
                    n += 1
                except OSError:
                    pass
    return n


# ---------------------------------------------------------------------------
# reader
# ---------------------------------------------------------------------------

class ShardStore:
    """Validated reader over a written store. Open via
    :func:`open_shard_store`; every slab read re-verifies its content
    digest (torn reads retry from disk). Thread-safe for concurrent
    reads (the streaming pipeline's disk-producer stage)."""

    def __init__(self, store_dir: str, manifest: dict, backend=None):
        self.dir = store_dir
        self.backend = backend if backend is not None \
            else resolve_backend(store_dir)
        self.manifest = manifest
        self.shape = tuple(int(s) for s in manifest["shape"])
        self.format = str(manifest["format"])
        self.dtype = np.dtype(str(manifest["dtype"]))
        self.slabs = list(manifest["slabs"])
        self.store_digest = str(manifest["store_digest"])
        self.input_digest = str(manifest["input_digest"])
        self.nnz = int(sum(s["nnz"] for s in self.slabs))
        self.max_row_nnz = int(max((s["max_row_nnz"] for s in self.slabs),
                                   default=0))
        self.value_sum = float(sum(s["value_sum"] for s in self.slabs))
        self.store_bytes = int(sum(s["raw_bytes"] for s in self.slabs))
        self.max_slab_bytes = int(max((s["raw_bytes"] for s in self.slabs),
                                      default=0))
        self._names = None
        self._names_lock = threading.Lock()

    # -- metadata ------------------------------------------------------

    @property
    def n_rows(self) -> int:
        return self.shape[0]

    @property
    def n_genes(self) -> int:
        return self.shape[1]

    @property
    def density(self) -> float:
        n, g = self.shape
        return self.nnz / max(n * g, 1)

    def _load_names(self):
        with self._names_lock:
            if self._names is None:
                raw = self.backend.get(self.manifest["names_file"],
                                       op="meta")
                with np.load(io.BytesIO(raw), allow_pickle=True) as f:
                    obs = [str(s) for s in f["obs"]]
                    var = [str(s) for s in f["var"]]
                want = self.manifest.get("names_digest")
                if want is not None:
                    got = _arrays_digest(
                        (np.asarray(obs, dtype="U"),
                         np.asarray(var, dtype="U")))
                    if got != want:
                        raise TornShardError(
                            "%s: obs/var names digest mismatch (%s != %s) "
                            "— torn or tampered names file"
                            % (self.backend.describe(
                                self.manifest["names_file"]),
                               got, want))
                self._names = (obs, var)
        return self._names

    def obs_names(self) -> list:
        return self._load_names()[0]

    def var_names(self) -> list:
        return self._load_names()[1]

    # -- slab access ---------------------------------------------------

    def slab_indices_for_rows(self, lo: int, hi: int) -> list[int]:
        """Slabs overlapping global rows [lo, hi) — the "open only your
        own row range" primitive."""
        return [s["i"] for s in self.slabs
                if s["row1"] > lo and s["row0"] < hi]

    def worker_ranges(self, total: int) -> list[tuple[int, int]]:
        """Contiguous, slab-aligned row-range partition for ``total``
        workers/hosts (a range may be empty when slabs < workers): each
        participant then opens ONLY its own slabs."""
        total = max(int(total), 1)
        n_slabs = len(self.slabs)
        out = []
        per = n_slabs / total
        for w in range(total):
            a = int(round(w * per))
            b = int(round((w + 1) * per))
            if a >= b:
                out.append((self.n_rows, self.n_rows))
            else:
                out.append((int(self.slabs[a]["row0"]),
                            int(self.slabs[b - 1]["row1"])))
        return out

    def read_slab(self, i: int, events=None, residency=None):
        """One slab, digest-verified, as CSR (``format='csr'``) or ndarray.

        A digest mismatch / unreadable file is a TORN READ: it re-reads
        from disk up to ``CNMF_TPU_SHARD_RETRIES`` times (emitting a
        ``fault`` event per detection) before raising
        :class:`TornShardError` — a damaged slab is healed by a clean
        re-read or failed loudly, never handed to the solver. The
        ``shard_read`` chaos clause (``runtime/faults.py``) injects the
        corruption deterministically. ``residency`` (a
        :class:`HostResidency`) is charged with the slab's raw bytes —
        the caller releases when the buffer is dropped.

        On a remote backend this ladder sits ABOVE the transport's own
        retry/backoff/hedging: a load that already exhausted the
        network budget raises :class:`RemoteStoreError`, which is NOT
        in the catch tuple below (re-reading a dead network is not
        healing) and propagates to the resilience ledger instead."""
        from ..runtime import faults

        meta = self.slabs[i]
        path = self.backend.describe(meta["file"])
        retries = shard_reread_retries()
        attempt = 0
        refresh = False
        while True:
            try:
                arrays = self._load_arrays(meta["file"], refresh=refresh,
                                           events=events)
                if faults.maybe_shard_read(context="slab:%d" % i):
                    # injected torn read: damage what we just loaded so
                    # the digest check below must catch it
                    arrays = tuple(a.copy() for a in arrays)
                    if arrays[0].size:
                        arrays[0].view(np.uint8)[0] ^= 0xFF
                got = _arrays_digest(arrays)
                if got != meta["digest"]:
                    raise TornShardError(
                        "%s: slab %d content digest mismatch (%s != %s) — "
                        "torn or corrupted read" % (path, i, got,
                                                    meta["digest"]))
                break
            except (TornShardError, OSError, ValueError, KeyError,
                    zipfile.BadZipFile) as exc:
                attempt += 1
                # a failed validation must re-read AUTHORITATIVE bytes:
                # bypass the read-through cache from here on (a fetched
                # clean copy re-lands in the cache, healing it)
                refresh = True
                if events is not None:
                    try:
                        events.emit("fault", kind="shard_read_torn",
                                    context={"path": path, "slab": int(i),
                                             "attempt": attempt,
                                             "error": str(exc)})
                    except Exception:
                        pass
                if attempt > retries:
                    raise TornShardError(
                        "%s: slab %d failed validation after %d read "
                        "attempt(s): %s" % (path, i, attempt, exc))
                warnings.warn(
                    "shard store: slab %d read failed validation (%s); "
                    "re-reading from disk (attempt %d/%d)"
                    % (i, exc, attempt, retries),
                    RuntimeWarning, stacklevel=2)
        rows = int(meta["row1"] - meta["row0"])
        if residency is not None:
            residency.charge(meta["raw_bytes"])
        if self.format == "csr":
            return sp.csr_matrix(
                (arrays[0], arrays[1], arrays[2]),
                shape=(rows, self.n_genes))
        return arrays[0]

    def _load_arrays(self, name, refresh=False, events=None):
        raw = self.backend.get(name, op="slab", refresh=refresh,
                               events=events)
        with np.load(io.BytesIO(raw), allow_pickle=False) as f:
            if self.format == "csr":
                return (np.asarray(f["data"]), np.asarray(f["indices"]),
                        np.asarray(f["indptr"]))
            return (np.asarray(f["block"]),)

    # -- whole-matrix assembly (the "everything fits" path) ------------

    def to_matrix(self, events=None):
        """Assemble the full matrix on host — the fits-in-budget path
        (bit-identical to the h5ad round trip: slabs are row slices of
        the same CSR/dense buffers). Callers above the budget should
        stream instead."""
        blocks = [self.read_slab(s["i"], events=events) for s in self.slabs]
        if not blocks:
            if self.format == "csr":
                return sp.csr_matrix(self.shape, dtype=self.dtype)
            return np.zeros(self.shape, dtype=self.dtype)
        if self.format == "csr":
            return sp.vstack(blocks).tocsr()
        return np.vstack(blocks)

    def row_block(self, lo: int, hi: int, events=None):
        """Rows [lo, hi) assembled on host (CSR or dense) — reads only
        the overlapping slabs. Host residency = the block itself."""
        parts = []
        for i in self.slab_indices_for_rows(lo, hi):
            meta = self.slabs[i]
            blk = self.read_slab(i, events=events)
            a = max(lo - meta["row0"], 0)
            b = min(hi, meta["row1"]) - meta["row0"]
            parts.append(blk[a:b])
        if not parts:
            if self.format == "csr":
                return sp.csr_matrix((max(hi - lo, 0), self.n_genes),
                                     dtype=self.dtype)
            return np.zeros((max(hi - lo, 0), self.n_genes),
                            dtype=self.dtype)
        if self.format == "csr":
            return sp.vstack(parts).tocsr() if len(parts) > 1 else parts[0]
        return np.vstack(parts) if len(parts) > 1 else parts[0]


class SlabCursor:
    """A row-range view over a :class:`ShardStore` — the disk-producer
    the streaming engine consumes (``parallel/streaming.py``). ``rows``
    bounds which slabs the cursor will ever open (per-worker/per-host
    ownership); reads outside raise."""

    def __init__(self, store: ShardStore, rows: tuple[int, int] | None = None,
                 events=None, residency: HostResidency | None = None):
        self.store = store
        lo, hi = (0, store.n_rows) if rows is None else rows
        if not (0 <= lo <= hi <= store.n_rows):
            raise ValueError(
                f"cursor rows [{lo}, {hi}) outside store rows "
                f"[0, {store.n_rows})")
        self.rows = (int(lo), int(hi))
        self.events = events
        self.residency = residency if residency is not None \
            else HostResidency()
        self.slabs_read: list[int] = []
        self._lock = threading.Lock()

    @property
    def n_rows(self) -> int:
        return self.rows[1] - self.rows[0]

    @property
    def shape(self) -> tuple[int, int]:
        return (self.n_rows, self.store.n_genes)

    def tasks(self) -> list[tuple[int, int, int]]:
        """Ordered ``(slab_index, row0, row1)`` segments covering this
        cursor's rows (global coordinates, clipped to the range)."""
        lo, hi = self.rows
        out = []
        for i in self.store.slab_indices_for_rows(lo, hi):
            meta = self.store.slabs[i]
            out.append((i, max(meta["row0"], lo), min(meta["row1"], hi)))
        return out

    def read(self, slab_i: int):
        """One slab (digest-verified) — refuses slabs outside the
        cursor's row range, which is exactly the ownership property the
        per-worker ingestion tests pin."""
        meta = self.store.slabs[slab_i]
        lo, hi = self.rows
        if meta["row1"] <= lo or meta["row0"] >= hi:
            raise ValueError(
                f"slab {slab_i} (rows [{meta['row0']}, {meta['row1']})) is "
                f"outside this cursor's range [{lo}, {hi}) — a worker must "
                "only open its own row-range slabs")
        with self._lock:
            self.slabs_read.append(int(slab_i))
        return self.store.read_slab(slab_i, events=self.events,
                                    residency=self.residency)

    def release(self, slab_i: int):
        self.residency.release(self.store.slabs[slab_i]["raw_bytes"])


# ---------------------------------------------------------------------------
# open / probe
# ---------------------------------------------------------------------------

def open_shard_store(store_dir, backend=None, events=None) -> ShardStore:
    """Open + validate a store's manifest; :class:`TornShardError` on any
    structural defect (slab digests are verified lazily per read). Slab
    presence is checked against ONE backend listing — no per-slab
    filesystem probes, so remote stores validate without a filesystem
    (and local opens do strictly fewer stat calls than before)."""
    store_dir = os.fspath(store_dir)
    if backend is None:
        backend = resolve_backend(store_dir)
    path = backend.describe(_MANIFEST)
    try:
        manifest = json.loads(
            backend.get(_MANIFEST, op="manifest",
                        events=events).decode("utf-8"))
    except (OSError, ValueError) as exc:
        # FileNotFoundError (local or HTTP 404) and JSONDecodeError both
        # land here; RemoteStoreError deliberately does NOT — a down
        # remote must fail loudly by name, not read as "no store"
        raise TornShardError(f"{path}: unreadable manifest ({exc})")
    if int(manifest.get("schema", -1)) != STORE_SCHEMA:
        raise TornShardError(
            f"{path}: store schema {manifest.get('schema')!r} (this build "
            f"understands {STORE_SCHEMA})")
    for key in ("shape", "dtype", "format", "slabs", "store_digest",
                "input_digest"):
        if key not in manifest:
            raise TornShardError(f"{path}: manifest missing {key!r}")
    if manifest["format"] not in ("csr", "dense"):
        raise TornShardError(
            f"{path}: unknown slab format {manifest['format']!r}")
    n = int(manifest["shape"][0])
    present = set(backend.list(events=events))
    prev = 0
    for s in manifest["slabs"]:
        if int(s["row0"]) != prev or int(s["row1"]) <= int(s["row0"]):
            raise TornShardError(
                f"{path}: slab row ranges are not a contiguous partition "
                f"(slab {s.get('i')}: [{s.get('row0')}, {s.get('row1')}))")
        prev = int(s["row1"])
        if s["file"] not in present:
            raise TornShardError(
                f"{path}: slab file {s['file']!r} is missing")
    if prev != n and not (n == 0 and not manifest["slabs"]):
        raise TornShardError(
            f"{path}: slabs cover {prev} rows, manifest says {n}")
    return ShardStore(store_dir, manifest, backend=backend)


def probe_shard_store(store_dir, events=None):
    """``(store, None)`` when present AND valid, ``(None, 'missing')``
    when absent, else ``(None, reason)`` — callers treat anything
    non-valid as "no store" (the h5ad path still exists on the default
    double-write mode). A remote endpoint that is DOWN (vs merely
    holding no store) raises :class:`RemoteStoreError` instead — an
    operator who configured ``CNMF_TPU_STORE_URI`` gets a named
    transport failure, never a silent fallback (the exists() probe
    itself degrades to the local cache when one is warm)."""
    store_dir = os.fspath(store_dir)
    backend = resolve_backend(store_dir)
    if not backend.exists(_MANIFEST, events=events):
        return None, "missing"
    try:
        return open_shard_store(store_dir, backend=backend,
                                events=events), None
    except TornShardError as exc:
        return None, str(exc)
