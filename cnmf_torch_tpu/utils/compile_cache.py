"""Persistent XLA compilation cache, on by default for CLI/bench entry points.

Multi-K sweeps compile one executable per (K, slice) and the consensus/
k-selection stages compile several more per K — on a cold process the
compiles dominate wall-clock (measured: a 10000x2000 sweep program is ~14 s
to compile, ~1.3 s to reload from the persistent cache through the same
backend). JAX ships a content-addressed on-disk cache for exactly this;
libraries shouldn't force global config, so this is enabled only from OUR
pipeline entry points — the CLI, bench, and the Preprocess compute entry
(``normalize_batchcorrect``, opt out with ``CNMF_TPU_COMPILE_CACHE=0``) —
and never overrides a user's explicit ``JAX_COMPILATION_CACHE_DIR`` /
``jax.config`` setting.
"""

from __future__ import annotations

import os

__all__ = ["enable_persistent_compilation_cache"]

_DEFAULT_DIR = os.path.join("~", ".cache", "cnmf-tpu", "xla-cache")


def enable_persistent_compilation_cache(path: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at ``path`` (default
    ``~/.cache/cnmf-tpu/xla-cache``) unless the user already configured one.
    Safe to call multiple times. Returns the directory in effect, or None
    when unavailable."""
    import jax

    from .envknobs import env_str

    user_dir = env_str("JAX_COMPILATION_CACHE_DIR")
    if user_dir:
        return user_dir
    try:
        current = jax.config.jax_compilation_cache_dir
    except AttributeError:  # config name changed; don't fight it
        return None
    if current:
        return current
    path = os.path.expanduser(path or _DEFAULT_DIR)
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # pin the threshold ONLY when the user hasn't set their own
        if not env_str("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"):
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              1.0)
    except Exception:
        return None
    return path
