"""Live observability plane tests (ISSUE 18): metrics registry +
exposition round-trip, metrics_snapshot schema validity, distributed
trace propagation (header and env wires, including a real subprocess
hop), deterministic sampling, SLO sliding-window boundary math, and —
load-bearing for production — the off-path guarantees: unset knobs
record nothing and compile byte-identical programs."""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from cnmf_torch_tpu.obs import metrics as obs_metrics
from cnmf_torch_tpu.obs import slo as obs_slo
from cnmf_torch_tpu.obs import tracing as obs_tracing
from cnmf_torch_tpu.utils import telemetry as tel
from cnmf_torch_tpu.utils.profiling import HIST_EDGES


@pytest.fixture(autouse=True)
def _clean_obs_state(monkeypatch):
    """Every test starts with the obs knobs unset and the process-global
    registry/context empty, and leaves them that way."""
    for var in (obs_metrics.METRICS_ENV, obs_tracing.TRACE_SAMPLE_ENV,
                obs_tracing.TRACE_CTX_ENV, obs_slo.SLO_P99_ENV,
                obs_slo.SLO_WINDOW_ENV):
        monkeypatch.delenv(var, raising=False)
    obs_metrics.reset_default_registry()
    obs_tracing.reset_process_context()
    yield
    obs_metrics.reset_default_registry()
    obs_tracing.reset_process_context()


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_concurrent_counters_exact():
    reg = obs_metrics.MetricsRegistry()
    n_threads, n_incs = 8, 500

    def worker(i):
        for _ in range(n_incs):
            reg.inc("hits", worker=i % 2)
            reg.observe("lat_ms", 3.0)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    total = sum(v for k, v in snap["counters"].items()
                if k.startswith("hits"))
    assert total == n_threads * n_incs
    assert snap["histograms"]["lat_ms"]["count"] == n_threads * n_incs


def test_registry_kind_conflict_and_negative_counter():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("x")
    with pytest.raises(ValueError, match="already registered"):
        reg.set("x", 1.0)
    with pytest.raises(ValueError, match=">= 0"):
        reg.inc("x", -1.0)


def test_exposition_round_trip_with_labels():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("reqs", 3, tenant="a b", status="ok")
    reg.set("depth", 7.5)
    for v in (0.5, 3.0, 15.0, 9999.0):
        reg.observe("lat_ms", v)
    parsed = obs_metrics.parse_exposition(reg.render_text())
    assert parsed["types"] == {"reqs": "counter", "depth": "gauge",
                              "lat_ms": "histogram"}
    samples = parsed["samples"]
    assert samples[("reqs", (("status", "ok"), ("tenant", "a b")))] == 3
    assert samples[("depth", ())] == 7.5
    # cumulative buckets: monotone, and +Inf equals _count
    buckets = [(k, v) for k, v in samples.items()
               if k[0] == "lat_ms_bucket"]
    assert samples[("lat_ms_bucket", (("le", "+Inf"),))] == 4
    assert samples[("lat_ms_count", ())] == 4
    assert samples[("lat_ms_sum", ())] == pytest.approx(10017.5)
    by_edge = dict((k[1][0][1], v) for k, v in buckets)
    cum = [by_edge["%g" % e] for e in HIST_EDGES] + [by_edge["+Inf"]]
    assert cum == sorted(cum)
    # the overflow observation (9999 > last edge) only lands in +Inf
    assert by_edge["%g" % HIST_EDGES[-1]] == 3


def test_label_escaping_round_trips():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("odd", path='a"b\\c\nd')
    parsed = obs_metrics.parse_exposition(reg.render_text())
    (key,) = [k for k in parsed["samples"] if k[0] == "odd"]
    assert key[1] == (("path", 'a"b\\c\nd'),)


def test_gated_helpers_noop_when_off(monkeypatch):
    obs_metrics.counter_inc("c")
    obs_metrics.gauge_set("g", 1.0)
    obs_metrics.observe("h", 1.0)
    snap = obs_metrics.default_registry().snapshot()
    assert snap == {"counters": {}, "gauges": {}, "histograms": {}}
    assert "disabled" in obs_metrics.render_text()
    monkeypatch.setenv(obs_metrics.METRICS_ENV, "1")
    obs_metrics.counter_inc("c")
    assert obs_metrics.default_registry().snapshot()["counters"] == {
        "c": 1.0}
    assert "disabled" not in obs_metrics.render_text()


def test_metrics_snapshot_event_schema_valid(tmp_path, monkeypatch):
    monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
    monkeypatch.setenv(obs_metrics.METRICS_ENV, "1")
    path = str(tmp_path / "run.events.jsonl")
    events = tel.EventLog(path)
    obs_metrics.counter_inc("c", tenant="t")
    obs_metrics.observe("h", 12.0)
    slo = obs_slo.SloTracker(50.0, window_s=10.0).evaluate()
    assert obs_metrics.emit_snapshot(events, slo=slo)
    n = tel.validate_events_file(path)
    assert n >= 2  # manifest + snapshot
    snaps = [e for e in tel.read_events(path)
             if e["t"] == "metrics_snapshot"]
    assert len(snaps) == 1
    assert snaps[0]["metrics"]["counters"] == {"c{tenant=t}": 1.0}
    assert snaps[0]["slo"]["burning"] is False
    # off paths: no telemetry, or no metrics -> no event
    monkeypatch.setenv(obs_metrics.METRICS_ENV, "0")
    assert not obs_metrics.emit_snapshot(events)
    monkeypatch.setenv(obs_metrics.METRICS_ENV, "1")
    monkeypatch.setenv(tel.TELEMETRY_ENV, "0")
    assert not obs_metrics.emit_snapshot(events)


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------

def test_sampling_deterministic_and_bounded():
    ids = ["%032x" % i for i in range(200)]
    for rate in (0.0, 0.3, 1.0):
        first = [obs_tracing.is_sampled(t, rate) for t in ids]
        again = [obs_tracing.is_sampled(t, rate) for t in ids]
        assert first == again
    assert not any(obs_tracing.is_sampled(t, 0.0) for t in ids)
    assert all(obs_tracing.is_sampled(t, 1.0) for t in ids)
    # a kept id stays kept at any higher rate (hash is rate-independent)
    kept_03 = {t for t in ids if obs_tracing.is_sampled(t, 0.3)}
    kept_07 = {t for t in ids if obs_tracing.is_sampled(t, 0.7)}
    assert kept_03 <= kept_07


def test_new_trace_off_by_default_and_child_chains():
    assert obs_tracing.new_trace() is None  # knob unset -> never samples
    ctx = obs_tracing.new_trace(rate=1.0)
    assert ctx is not None and ctx.parent_id is None
    c1 = obs_tracing.child(ctx)
    c2 = obs_tracing.child(c1)
    assert c1.trace_id == c2.trace_id == ctx.trace_id
    assert c1.parent_id == ctx.span_id and c2.parent_id == c1.span_id
    assert obs_tracing.child(None) is None


def test_header_round_trip_and_malformed_dropped():
    ctx = obs_tracing.new_trace(rate=1.0)
    back = obs_tracing.from_header(obs_tracing.header_value(ctx))
    assert (back.trace_id, back.span_id) == (ctx.trace_id, ctx.span_id)
    for bad in (None, "", "noseparator", "a:b:c", ":x", "x:"):
        assert obs_tracing.from_header(bad) is None


def test_env_propagation_into_subprocess(monkeypatch):
    """The launcher wire: a parent-planted CNMF_TPU_TRACE_CTX is picked
    up by a real child interpreter's process_context()."""
    parent = obs_tracing.new_trace(rate=1.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env[obs_tracing.TRACE_CTX_ENV] = obs_tracing.env_value(parent)
    env[obs_tracing.TRACE_SAMPLE_ENV] = "1"
    code = ("import json\n"
            "from cnmf_torch_tpu.obs import tracing as t\n"
            "ctx = t.child(t.process_context())\n"
            "print(json.dumps({'trace': ctx.trace_id,"
            " 'parent': ctx.parent_id}))\n")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    got = json.loads(out.stdout.strip().splitlines()[-1])
    assert got["trace"] == parent.trace_id
    assert got["parent"] == parent.span_id


def test_span_events_schema_valid_and_waterfall(tmp_path, monkeypatch):
    monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
    run_dir = tmp_path / "run"
    (run_dir / "cnmf_tmp").mkdir(parents=True)
    path = str(run_dir / "cnmf_tmp" / "x.events.jsonl")
    events = tel.EventLog(path)
    root = obs_tracing.new_trace(rate=1.0)
    with obs_tracing.span(events, root, "client.request", tenant="t0"):
        with obs_tracing.span(events, obs_tracing.child(root),
                              "serve.solve"):
            pass
    tel.validate_events_file(path)
    spans = [e for e in tel.read_events(path) if e["t"] == "span"]
    assert [e["name"] for e in spans] == ["serve.solve", "client.request"]
    assert spans[0]["parent"] == root.span_id
    assert "parent" not in spans[1]  # None fields are omitted on emit
    text = obs_tracing.render_run_traces(str(run_dir))
    assert root.trace_id in text
    assert "client.request" in text and "serve.solve" in text
    # the child renders indented under its parent
    lines = text.splitlines()
    (solve_line,) = [ln for ln in lines if "serve.solve" in ln]
    assert solve_line.startswith("    serve.solve"[:4] or "  ")


def test_emit_span_noop_paths(tmp_path, monkeypatch):
    monkeypatch.setenv(tel.TELEMETRY_ENV, "1")
    path = str(tmp_path / "e.jsonl")
    events = tel.EventLog(path)
    obs_tracing.emit_span(events, None, "x", 0.0, 1.0)  # unsampled
    obs_tracing.emit_span(None, obs_tracing.new_trace(rate=1.0),
                          "x", 0.0, 1.0)  # no log
    assert not os.path.exists(path)
    assert "no span events" in obs_tracing.render_run_traces(
        str(tmp_path))


# ---------------------------------------------------------------------------
# SLO tracking
# ---------------------------------------------------------------------------

def test_slo_window_boundary_math():
    trk = obs_slo.SloTracker(50.0, window_s=5.0)
    trk.record(10.0, now=1.0)
    # strictly inside the window
    ev = trk.evaluate(now=5.99)
    assert ev["requests"] == 1 and not ev["burning"]
    # exactly window_s old -> just aged out; empty window never burns
    ev = trk.evaluate(now=6.0)
    assert ev["requests"] == 0 and ev["p99_ms"] is None
    assert ev["burning"] is False and ev["ok"] is True


def test_slo_burns_on_latency_and_error_budget():
    trk = obs_slo.SloTracker(50.0, window_s=100.0)
    for i in range(49):
        trk.record(10.0, now=1.0 + i * 0.01)
    assert not trk.evaluate(now=2.0)["burning"]
    # interpolated p99 over 50 samples reaches well into the outlier
    trk.record(500.0, now=2.0)
    ev = trk.evaluate(now=2.0)
    assert ev["p99_ms"] > 50.0 and ev["burning"]

    trk2 = obs_slo.SloTracker(1000.0, window_s=100.0,
                              max_error_rate=0.01)
    for i in range(99):
        trk2.record(1.0, ok=True, now=1.0)
    trk2.record(1.0, ok=False, now=1.0)
    assert not trk2.evaluate(now=1.0)["burning"]  # 1% == budget, not >
    trk2.record(1.0, ok=False, now=1.0)
    ev = trk2.evaluate(now=1.0)
    assert ev["errors"] == 2 and ev["burning"]


def test_slo_tracker_from_env(monkeypatch):
    assert obs_slo.tracker_from_env() is None
    monkeypatch.setenv(obs_slo.SLO_P99_ENV, "25")
    monkeypatch.setenv(obs_slo.SLO_WINDOW_ENV, "60")
    trk = obs_slo.tracker_from_env()
    assert trk.target_p99_ms == 25.0 and trk.window_s == 60.0
    with pytest.raises(ValueError):
        obs_slo.SloTracker(0.0)
    with pytest.raises(ValueError):
        obs_slo.SloTracker(10.0, window_s=0.0)


# ---------------------------------------------------------------------------
# serving integration: /stats honesty + SLO surface
# ---------------------------------------------------------------------------

def test_stats_expose_reservoir_honesty_and_slo(monkeypatch):
    from cnmf_torch_tpu.serving import ProjectionService, ResidentReference

    monkeypatch.setenv(obs_slo.SLO_P99_ENV, "10000")
    rng = np.random.default_rng(0)
    W = rng.gamma(0.3, 1.0, size=(4, 40)).astype(np.float32)
    ref = ResidentReference(W, beta=2.0, chunk_size=5000,
                            chunk_max_iter=40, h_tol=0.05, l1_H=0.0)
    with ProjectionService(ref, max_batch=4, linger_ms=0.0,
                           warm_start=False) as svc:
        X = (rng.random((8, 40)) + 0.01).astype(np.float32)
        svc.project(X)
        stats = svc.stats()
    assert stats["latency_samples_kept"] == 1
    assert stats["latency_samples_dropped"] == 0
    assert stats["latency_window_coverage"] == 1.0
    assert stats["slo"]["requests"] == 1
    assert stats["slo"]["burning"] is False


# ---------------------------------------------------------------------------
# the production guarantee: off-path compiles byte-identical programs
# ---------------------------------------------------------------------------

def test_compiled_programs_byte_identical_with_knobs_on(monkeypatch):
    import jax
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, random_init

    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.gamma(1.0, 1.0, (60, 30)).astype(np.float32))
    H0, W0 = random_init(jax.random.key(0), 60, 30, 3, jnp.mean(X))

    def lowered():
        return nmf_fit_batch.lower(X, H0, W0, beta=1.0,
                                   max_iter=10).as_text()

    base = lowered()
    monkeypatch.setenv(obs_metrics.METRICS_ENV, "1")
    monkeypatch.setenv(obs_tracing.TRACE_SAMPLE_ENV, "1")
    monkeypatch.setenv(obs_slo.SLO_P99_ENV, "25")
    monkeypatch.setenv(obs_slo.SLO_WINDOW_ENV, "60")
    assert lowered() == base
