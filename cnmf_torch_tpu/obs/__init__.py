"""Live observability plane (ISSUE 18).

Three surfaces over one event layer:

* :mod:`~cnmf_torch_tpu.obs.metrics` — a process-local metrics registry
  (counters / gauges / fixed-log-bucket histograms) with a text
  exposition format served from ``GET /metrics`` on the serve daemon and
  the object-store server, plus periodic ``metrics_snapshot`` telemetry
  events so batch runs leave a scrape-equivalent trail in the JSONL.
* :mod:`~cnmf_torch_tpu.obs.tracing` — sampled distributed traces:
  a trace/span context propagated client -> daemon via the
  ``X-CNMF-Trace`` header and launcher parent -> worker via env, each
  span landing as a schema-valid ``span`` event; ``cnmf-tpu trace``
  renders per-request waterfalls.
* :mod:`~cnmf_torch_tpu.obs.slo` — a sliding-window SLO tracker
  (target p99 + error budget) evaluated inside the daemon and surfaced
  in ``/metrics``, ``/healthz``, and the report's SLO section.
* :mod:`~cnmf_torch_tpu.obs.costmodel` — the roofline cost model
  (ISSUE 19): analytic flop/byte/collective-word accounting per kernel
  lane instantiated from the ExecutionPlan, joined with measured walls
  into ``perf_model`` events (achieved MFU, bandwidth fraction,
  compute- vs memory-bound verdict) and the report's Roofline section.
* :mod:`~cnmf_torch_tpu.obs.regress` — the perf-regression observatory:
  schema-versioned bench snapshots keyed by the autotune device
  fingerprint, noise-aware diffing (`cnmf-tpu benchdiff`), and the
  tier-1 perf gate (scripts/perf_gate.py).

Everything here is host-side and off by default: with the knobs unset
no instrument records, no span emits, and compiled programs are
byte-identical to a build without this package (pinned by test).
"""

from . import costmodel, metrics, regress, slo, tracing  # noqa: F401

__all__ = ["metrics", "tracing", "slo", "costmodel", "regress"]
