"""Tier-1 accel parity smoke (ISSUE 9, wired in verify_tier1.sh).

Runs a mini replicate sweep under each solver recipe — plain MU,
accelerated-MU, Diagonalized Newton (β=1), and HALS (β=2) — and asserts:

  * matched final objectives across the mu-family KL recipes (same
    optimization problem, different iteration schemes: the accelerated
    recipes must land within a small relative band of plain MU, and
    never worse beyond it);
  * HALS lands within the same band of batch MU on the Frobenius
    objective;
  * every engaged recipe is visible end-to-end in telemetry: the
    ``dispatch`` events carry the full recipe context, the
    ``replicates`` events carry the recipe label and (for dna) the
    fallback-lane fraction, and the whole stream validates against the
    event schema.

Exit 0 on success; any assertion or schema failure exits nonzero and
fails the gate.
"""

import os
import sys
import tempfile

# package: sys.path[0] is scripts/, the package lives one level up
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"

import numpy as np  # noqa: E402


def fixture(n=200, g=80, k=4, seed=3):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * 6.0).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return X


def main() -> int:
    from cnmf_torch_tpu.ops.recipe import SolverRecipe, resolve_recipe
    from cnmf_torch_tpu.parallel import replicate_sweep
    from cnmf_torch_tpu.utils.telemetry import (EventLog, replicate_records,
                                                summarize_events,
                                                validate_events_file)

    X = fixture()
    seeds = [1, 2, 3]
    tmp = tempfile.mkdtemp(prefix="accel_smoke_")
    log = EventLog(os.path.join(tmp, "smoke.events.jsonl"))

    payloads = {}

    def run(label, beta_loss, recipe):
        sink_box = []
        _, _, errs = replicate_sweep(X, seeds, 4, beta_loss=beta_loss,
                                     mode="batch", recipe=recipe,
                                     telemetry_sink=sink_box.append)
        assert np.isfinite(errs).all(), (label, errs)
        log.emit("dispatch", decision="solver_recipe",
                 context=recipe.as_context())
        (pay,) = sink_box
        assert pay.get("recipe") == recipe.label, (label, pay.get("recipe"))
        log.emit("replicates", k=pay["k"], beta=pay["beta"],
                 mode=pay["mode"], cap=int(pay["cap"]),
                 cadence=pay["cadence"], recipe=pay["recipe"],
                 records=replicate_records(pay))
        payloads[label] = np.asarray(errs, np.float64)
        print(f"[accel-smoke] {label:10s} errs={np.round(errs, 2)}")

    run("mu", "kullback-leibler", SolverRecipe())
    run("amu", "kullback-leibler",
        SolverRecipe("amu", 3, False, "caller"))
    run("dna", "kullback-leibler",
        SolverRecipe("dna", 1, True, "caller"))
    run("mu-f2", "frobenius", SolverRecipe())
    run("hals", "frobenius", SolverRecipe("hals", 1, False, "caller"))

    # matched final objectives: same problem, different iteration schemes
    TOL = 2e-2
    for label in ("amu", "dna"):
        rel = np.abs(payloads[label] - payloads["mu"]) / payloads["mu"]
        assert (rel < TOL).all(), (label, payloads[label], payloads["mu"])
    rel = np.abs(payloads["hals"] - payloads["mu-f2"]) / payloads["mu-f2"]
    assert (rel < TOL).all(), ("hals", payloads["hals"], payloads["mu-f2"])

    # the auto lane resolves the documented recipes — and since the
    # execution planner (ISSUE 17) it IS the shipped default, with
    # CNMF_TPU_ACCEL=0 as the byte-identical plain-MU escape hatch
    assert resolve_recipe(1.0, "batch", accel="auto").label == "dna"
    assert resolve_recipe(1.0, "batch").label == "dna"  # default: auto
    assert resolve_recipe(1.0, "batch", accel="0").label == "mu"

    # schema-valid stream + recipe/fallback visible in the summary
    n_events = validate_events_file(log.path)
    assert n_events >= 11, n_events  # manifest + 5x(dispatch+replicates)
    from cnmf_torch_tpu.utils.telemetry import read_events

    summary = summarize_events(read_events(log.path))
    conv = summary["convergence"]["4"]
    assert "dna" in conv["recipe"] and "hals" in conv["recipe"], conv
    assert conv.get("dna_fallback_mean") is not None, conv
    recipes_dispatched = [d["context"].get("recipe")
                          for d in summary["dispatch"]
                          if d.get("decision") == "solver_recipe"]
    assert set(recipes_dispatched) == {"mu", "amu(rho=3)", "dna", "hals"}, \
        recipes_dispatched
    print(f"[accel-smoke] OK: {n_events} schema-valid events, recipes "
          f"{sorted(set(recipes_dispatched))}, dna fallback "
          f"{conv['dna_fallback_mean']:.1%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
