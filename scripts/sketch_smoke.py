"""Tier-1 sketch parity smoke (ISSUE 11, wired in verify_tier1.sh).

Two lanes, both asserted against their exact twins within declared
tolerances, with schema-valid sketch-carrying telemetry:

  * **solver**: a mini batch-KL replicate sweep under the ``sketch``
    recipe (row-subsampled W updates, exact interleaves) must land
    within a small relative band of plain MU on BOTH the dense and the
    ELL encodings, and the sketch-off programs must lower byte-identical
    to the defaults;
  * **consensus**: the KNN-density outlier filter + k-means cluster
    medians computed on random-projected replicate spectra must
    reproduce the exact stage's outlier set bit-for-bit at the default
    threshold and its cluster medians to high cosine, on a synthetic
    replicate-spectra stack.

Exit 0 on success; any assertion or schema failure exits nonzero and
fails the gate.
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"

import numpy as np  # noqa: E402
import scipy.sparse as sp  # noqa: E402


def kl_fixture(n=400, g=80, k=4, seed=3, scale=1.2):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return X


def spectra_stack(R=240, g=600, k=4, seed=5):
    """Synthetic merged-replicate L2 spectra: k planted programs plus
    noise, with a few far-outlier rows the density filter must catch."""
    rng = np.random.default_rng(seed)
    base = rng.gamma(0.3, 1.0, size=(k, g))
    rows = base[rng.integers(0, k, size=R)] * \
        rng.uniform(0.8, 1.25, size=(R, 1))
    rows += rng.gamma(0.1, 0.05, size=(R, g))
    out_idx = rng.choice(R, size=6, replace=False)
    rows[out_idx] = rng.gamma(0.3, 1.0, size=(6, g)) * 4.0
    l2 = rows / np.linalg.norm(rows, axis=1, keepdims=True)
    return l2.astype(np.float32)


def main() -> int:
    import jax.numpy as jnp

    from cnmf_torch_tpu.ops import kmeans, local_density
    from cnmf_torch_tpu.ops.nmf import nmf_fit_batch
    from cnmf_torch_tpu.ops.recipe import SolverRecipe
    from cnmf_torch_tpu.ops.sketch import project_rows
    from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put
    from cnmf_torch_tpu.parallel import replicate_sweep
    from cnmf_torch_tpu.utils.telemetry import (EventLog, read_events,
                                                summarize_events,
                                                validate_events_file)

    tmp = tempfile.mkdtemp(prefix="sketch_smoke_")
    log = EventLog(os.path.join(tmp, "smoke.events.jsonl"))

    # ---- solver lane --------------------------------------------------
    X = kl_fixture()
    recipe = SolverRecipe("sketch", sketch_dim=120, sketch_exact_every=4,
                          source="caller")
    TOL = 6e-2
    errs = {}
    for label, rec in (("mu", None), ("sketch", recipe)):
        _, _, e = replicate_sweep(X, [1, 2, 3], 4,
                                  beta_loss="kullback-leibler",
                                  mode="batch", recipe=rec)
        assert np.isfinite(e).all(), (label, e)
        errs[label] = np.asarray(e, np.float64)
        if rec is not None:
            log.emit("dispatch", decision="solver_recipe",
                     context=rec.as_context())
    rel = np.abs(errs["sketch"] - errs["mu"]) / errs["mu"]
    assert (rel < TOL).all(), ("dense", rel)
    print(f"[sketch-smoke] solver dense: rel objective gap "
          f"{rel.max():.3%} (< {TOL:.0%})")

    E = ell_device_put(csr_to_ell(sp.csr_matrix(X)))
    Hk = jnp.asarray(np.random.default_rng(0).uniform(
        size=(X.shape[0], 4)).astype(np.float32))
    Wk = jnp.asarray(np.random.default_rng(1).uniform(
        size=(4, X.shape[1])).astype(np.float32))
    _, _, e_mu = nmf_fit_batch(E, Hk, Wk, beta=1.0, max_iter=120)
    _, _, e_sk = nmf_fit_batch(E, Hk, Wk, beta=1.0, max_iter=120,
                               sketch_dim=120, sketch_exact_every=4)
    rel_e = abs(float(e_sk) - float(e_mu)) / float(e_mu)
    assert rel_e < TOL, ("ell", float(e_mu), float(e_sk))
    print(f"[sketch-smoke] solver ELL:   rel objective gap "
          f"{rel_e:.3%} (< {TOL:.0%})")

    # sketch-off byte identity (the recipe layer's core contract)
    base = nmf_fit_batch.lower(jnp.asarray(X), Hk, Wk, beta=1.0,
                               max_iter=40).as_text()
    ident = nmf_fit_batch.lower(jnp.asarray(X), Hk, Wk, beta=1.0,
                                max_iter=40, sketch_dim=0,
                                sketch_exact_every=1).as_text()
    assert base == ident, "sketch-off lowering differs from defaults"
    print("[sketch-smoke] sketch-off lowering byte-identical to defaults")

    # ---- consensus lane ----------------------------------------------
    l2 = spectra_stack()
    R, k, thr, dim = l2.shape[0], 4, 0.5, 128
    n_neighbors = int(0.30 * R / k)

    dens_exact, _ = local_density(l2, n_neighbors)
    proj = project_rows(l2, dim)
    dens_sk, _ = local_density(proj, n_neighbors)
    keep_exact = dens_exact < thr
    keep_sk = dens_sk < thr
    assert (keep_exact == keep_sk).all(), \
        (int((keep_exact != keep_sk).sum()), "outlier sets differ")
    assert 0 < (~keep_exact).sum() < R, "fixture grew no outliers"

    def medians(feats, keep):
        labels, _, _ = kmeans(feats[keep], k, n_init=10, seed=1)
        present = [c for c in range(k) if (labels == c).any()]
        med = np.stack([np.median(l2[keep][labels == c], axis=0)
                        for c in present])
        return med / np.maximum(
            np.linalg.norm(med, axis=1, keepdims=True), 1e-12)

    med_exact = medians(l2, keep_exact)
    med_sk = medians(proj, keep_sk)
    C = med_exact @ med_sk.T
    assert med_sk.shape == med_exact.shape
    best = C.max(axis=1)
    assert (best > 0.995).all(), best
    log.emit("dispatch", decision="consensus_path",
             context={"stage": "consensus", "k": k, "replicates": int(R),
                      "packed": False, "sketch": True, "sketch_dim": dim,
                      "sketch_source": "env", "distance_width": dim,
                      "distance_shape": [int(R), int(R)]})
    print(f"[sketch-smoke] consensus: outlier set identical "
          f"({int((~keep_exact).sum())} outliers), median cosine "
          f"{best.min():.4f} (> 0.995)")

    # ---- telemetry surface -------------------------------------------
    n_events = validate_events_file(log.path)
    summary = summarize_events(read_events(log.path))
    cons = summary.get("consensus") or []
    assert any(c.get("sketch") and c.get("sketch_dim") == dim
               for c in cons), cons
    disp = [d for d in summary["dispatch"]
            if d.get("decision") == "solver_recipe"]
    assert any("sketch(" in (d["context"].get("recipe") or "")
               for d in disp), disp
    print(f"[sketch-smoke] OK: {n_events} schema-valid events, "
          f"sketch lanes visible in dispatch + consensus summaries")
    return 0


if __name__ == "__main__":
    sys.exit(main())
