"""Replicate quarantine, reseeded retry, and torn-artifact validation.

cNMF's statistical robustness (n_iter seeded replicates per K, consensus
over their spectra — PAPER.md) assumed operational robustness it never
had: a replicate whose MU chain went nonfinite used to pollute the merged
spectra silently, and a preempted worker could leave truncated artifact
files that resume then trusted. This module is the recovery policy layer:

  * :func:`lane_health` (re-exported from ``ops.nmf``) grades every
    replicate of a sweep from outputs the solvers already return — no
    program changes when telemetry is off.
  * :class:`ReplicateGuard` books unhealthy lanes for retry with
    deterministically derived seeds (:func:`derive_retry_seed`:
    ``seed XOR attempt`` — reproducible on resume without any state),
    quarantines lanes that exhaust ``CNMF_TPU_MAX_RETRIES``, writes the
    per-worker resilience ledger, emits telemetry ``fault`` events, and
    enforces ``CNMF_TPU_MIN_HEALTHY_FRAC`` per K (degrade gracefully
    above it, hard-fail with a clear error below).
  * :func:`load_spectra_checked` / :func:`probe_spectra_file` — the ONE
    definition of "is this replicate artifact trustworthy", shared by
    ``--skip-completed-runs`` resume and ``combine_nmf`` so a torn npz
    can never be mistaken for a completed run on either path.
"""

from __future__ import annotations

import glob
import json
import os
import warnings

import numpy as np

from ..ops.nmf import lane_health  # noqa: F401  (re-export: ONE definition)

__all__ = [
    "MAX_RETRIES_ENV",
    "MIN_HEALTHY_FRAC_ENV",
    "max_retries",
    "min_healthy_frac",
    "derive_retry_seed",
    "lane_health",
    "TornArtifactError",
    "UnhealthySweepError",
    "UNHEALTHY_EXIT_CODE",
    "load_spectra_checked",
    "probe_spectra_file",
    "ReplicateGuard",
    "load_quarantined_tasks",
    "load_quarantine_records",
    "sweep_stale_ledgers",
]

MAX_RETRIES_ENV = "CNMF_TPU_MAX_RETRIES"
MIN_HEALTHY_FRAC_ENV = "CNMF_TPU_MIN_HEALTHY_FRAC"

_DEFAULT_MAX_RETRIES = 2
_DEFAULT_MIN_HEALTHY_FRAC = 0.8


class TornArtifactError(RuntimeError):
    """A replicate artifact exists but cannot be trusted (unreadable,
    truncated, wrong shape, or nonfinite)."""


class UnhealthySweepError(RuntimeError):
    """Too few healthy replicates survived for a K after retries —
    consensus over the remainder would be statistically meaningless."""


# process exit code the CLI uses for UnhealthySweepError: the launcher
# must distinguish "below the min-healthy-frac floor" (deterministic
# policy failure — respawning reruns the same derived seeds and fails
# identically, and falling back to skip-missing combine would produce
# exactly the degraded consensus the floor exists to prevent) from a
# crash/preemption (respawn + degrade is right). 1 is any uncaught
# exception, 2 is argparse's usage-error code.
UNHEALTHY_EXIT_CODE = 3


def max_retries() -> int:
    """Retry budget per unhealthy replicate (``CNMF_TPU_MAX_RETRIES``,
    default 2; 0 disables retries — unhealthy lanes quarantine
    immediately)."""
    from ..utils.envknobs import env_int

    return env_int(MAX_RETRIES_ENV, _DEFAULT_MAX_RETRIES, lo=0)


def min_healthy_frac() -> float:
    """Per-K survival floor (``CNMF_TPU_MIN_HEALTHY_FRAC``, default 0.8):
    consensus proceeds while at least this fraction of a K's replicates
    end healthy; below it factorize hard-fails.

    Scope: evaluated over the replicates THIS WORKER's ledger shard owns
    (workers are independent processes and cannot see each other's
    outcomes until combine). With one worker — the common case — shard
    and global coincide; with many thin shards, size the floor against
    the per-shard replicate count (e.g. a 3-replicate shard quantizes to
    thirds)."""
    from ..utils.envknobs import env_float

    return env_float(MIN_HEALTHY_FRAC_ENV, _DEFAULT_MIN_HEALTHY_FRAC,
                     lo=0.0, hi=1.0)


def derive_retry_seed(seed: int, attempt: int) -> int:
    """Deterministic retry seed for attempt N >= 1: ``seed XOR attempt``,
    masked to the ledger's 31-bit seed domain. Derivable from the ledger
    seed alone, so an interrupted-and-resumed run retries with the exact
    seeds the uninterrupted run would have used (the ledger sidecar
    records them anyway, for auditability). Under the threefry PRNG two
    keys differing in one bit yield statistically independent streams, so
    the retried replicate is a genuinely fresh draw."""
    if int(attempt) < 1:
        raise ValueError(f"retry attempts start at 1, got {attempt}")
    return (int(seed) ^ int(attempt)) & 0x7FFFFFFF


# ---------------------------------------------------------------------------
# artifact validation (shared by resume and combine)
# ---------------------------------------------------------------------------

def load_spectra_checked(path, k: int | None = None,
                         n_genes: int | None = None):
    """Load a per-replicate spectra npz, validating it is COMPLETE: the
    zip opens, all three members parse, the matrix is 2-D with ``k`` rows
    (and ``n_genes`` columns when known), labels match the data shape,
    and every value is finite. Raises :class:`TornArtifactError`
    otherwise — a SIGKILL mid-write, a truncated copy, or a quarantine-
    worthy nonfinite replicate all land here. Returns the DataFrame."""
    import pandas as pd

    try:
        with np.load(path, allow_pickle=True) as f:
            data = np.asarray(f["data"])
            index = np.asarray(f["index"])
            columns = np.asarray(f["columns"])
    except Exception as exc:
        raise TornArtifactError(
            f"{path}: unreadable replicate artifact "
            f"({type(exc).__name__}: {exc})")
    if data.ndim != 2:
        raise TornArtifactError(
            f"{path}: expected a 2-D spectra matrix, got ndim={data.ndim}")
    if k is not None and data.shape[0] != int(k):
        raise TornArtifactError(
            f"{path}: expected {int(k)} component rows, got {data.shape[0]}")
    if n_genes is not None and data.shape[1] != int(n_genes):
        raise TornArtifactError(
            f"{path}: expected {int(n_genes)} gene columns, "
            f"got {data.shape[1]}")
    if len(index) != data.shape[0] or len(columns) != data.shape[1]:
        raise TornArtifactError(
            f"{path}: label arrays ({len(index)}, {len(columns)}) do not "
            f"match the data shape {data.shape}")
    try:
        finite = bool(np.isfinite(data).all())
    except (TypeError, ValueError) as exc:
        raise TornArtifactError(f"{path}: non-numeric spectra data ({exc})")
    if not finite:
        raise TornArtifactError(f"{path}: nonfinite spectra values")
    return pd.DataFrame(data, index=index, columns=columns)


def probe_spectra_file(path, k: int | None = None,
                       n_genes: int | None = None) -> str | None:
    """Resume-side probe: ``None`` when the artifact is present AND
    valid, ``"missing"`` when absent, else the torn-artifact reason
    string. ``--skip-completed-runs`` treats anything non-None as
    incomplete — a half-written file is rerun, never trusted."""
    if not os.path.exists(path):
        return "missing"
    try:
        load_spectra_checked(path, k=k, n_genes=n_genes)
        return None
    except TornArtifactError as exc:
        return str(exc)


# ---------------------------------------------------------------------------
# quarantine + retry bookkeeping
# ---------------------------------------------------------------------------

class ReplicateGuard:
    """Per-factorize health bookkeeping: observe sweep results, queue
    retries, quarantine exhausted lanes, persist the resilience ledger,
    and enforce the min-healthy-frac floor.

    The guard is execution-path-agnostic: every factorize path (batched
    per-K, packed, ELL, row-sharded, sequential) reports through
    :meth:`observe` and the retry waves re-solve through a caller-
    supplied ``rerun`` closure, so quarantine/retry semantics cannot
    drift between solver families. Accounting is per worker — each
    worker only ever sees (and can only rerun) its own ledger shard.
    """

    def __init__(self, events=None, ledger_path: str | None = None,
                 max_retries_: int | None = None,
                 min_healthy_frac_: float | None = None):
        self.events = events
        self.ledger_path = ledger_path
        self.max_retries = (max_retries() if max_retries_ is None
                            else int(max_retries_))
        self.min_healthy_frac = (min_healthy_frac()
                                 if min_healthy_frac_ is None
                                 else float(min_healthy_frac_))
        self._totals: dict[int, int] = {}
        self._healthy: dict[int, int] = {}
        self._pending: list[dict] = []
        self.retries: list[dict] = []
        self.quarantined: list[dict] = []
        self.shard_faults: list[dict] = []

    def _emit(self, kind: str, context: dict):
        if self.events is not None:
            self.events.emit("fault", kind=kind, context=context)

    def observe(self, k: int, iters, seeds, health, attempt: int = 0,
                derived_seeds=None) -> np.ndarray:
        """Record one sweep's (or retry wave's) per-lane health. Returns
        the boolean healthy mask (callers write artifacts for healthy
        lanes only). Unhealthy lanes enqueue a retry at ``attempt + 1``
        while the budget lasts, else quarantine. ``seeds`` are always the
        ORIGINAL ledger seeds — retry seeds are re-derived from them, so
        resume retries reproduce interrupted ones."""
        k = int(k)
        health = np.asarray(health, dtype=bool).reshape(-1)
        if len(health) != len(list(iters)):
            raise ValueError(
                f"health mask has {len(health)} lanes for {len(list(iters))}"
                " tasks")
        if attempt == 0:
            self._totals[k] = self._totals.get(k, 0) + len(health)
        for j, ok in enumerate(health):
            it, seed = int(iters[j]), int(seeds[j])
            if attempt > 0:
                rec = {"k": k, "iter": it, "seed": seed,
                       "attempt": int(attempt),
                       "derived_seed": int(derived_seeds[j]),
                       "healthy": bool(ok)}
                self.retries.append(rec)
                self._emit("retry", rec)
            if ok:
                self._healthy[k] = self._healthy.get(k, 0) + 1
                continue
            ctx = {"k": k, "iter": it, "seed": seed, "attempt": int(attempt)}
            self._emit("nonfinite_replicate", ctx)
            if attempt < self.max_retries:
                self._pending.append({"k": k, "iter": it, "seed": seed,
                                      "attempt": int(attempt) + 1})
            else:
                rec = dict(ctx, attempts=int(attempt))
                self.quarantined.append(rec)
                self._emit("quarantine", rec)
                warnings.warn(
                    "replicate k=%d iter=%d (seed %d) quarantined after "
                    "%d attempt(s): solver output nonfinite. It is excluded "
                    "from combine; raise %s to retry more."
                    % (k, it, seed, int(attempt) + 1, MAX_RETRIES_ENV),
                    RuntimeWarning, stacklevel=2)
        return health

    def take_pending(self) -> list[dict]:
        """Pop the queued retry tasks (one wave — all share one attempt
        number, since waves are processed synchronously)."""
        pending, self._pending = self._pending, []
        return pending

    def credit_existing(self, k: int, n: int):
        """Count ``n`` replicates of K that are already VALID on disk and
        skipped by a resume. Without this credit the min-healthy-frac
        floor would be evaluated over only the session's rerun subset —
        a resume that reruns 1 of 10 replicates and quarantines it would
        spuriously hard-fail at 0/1 when the K is really 9/10 healthy."""
        k = int(k)
        self._totals[k] = self._totals.get(k, 0) + int(n)
        self._healthy[k] = self._healthy.get(k, 0) + int(n)

    def carry_quarantined(self, k: int, it: int, seed: int,
                          attempts: int | None = None):
        """Re-record a still-unresolved quarantine from a previous run's
        ledger during a resume that does NOT rerun the lane: it counts
        toward the K's total (not healthy) so the floor reflects the true
        state, and it rides into this session's ledger rewrite so the
        quarantine record (and combine's exclusion) survives the resume.
        ``attempts`` preserves the original record's exhausted budget, so
        a later resume under a RAISED ``CNMF_TPU_MAX_RETRIES`` can still
        tell the lane has retries left."""
        k = int(k)
        self._totals[k] = self._totals.get(k, 0) + 1
        rec = {"k": k, "iter": int(it), "seed": int(seed), "carried": True}
        if attempts is not None:
            rec["attempts"] = int(attempts)
        self.quarantined.append(rec)

    def record_torn(self, path: str, reason: str):
        self._emit("torn_artifact", {"path": str(path), "reason": reason})

    def record_shard_fault(self, kind: str, context: dict):
        """Book a shard-granular staging fault (ISSUE 6: exhausted upload
        retries, stalled transfers) into the SAME ledger the replicate
        quarantines live in, so a degraded run's audit trail covers every
        recovery layer. ``kind`` is the fault class (``shard_upload_failed``
        / ``shard_stall``, plus the store-read classes ``shard_read_torn``
        for a slab that failed digest validation past the re-read budget
        and ``remote_store`` for a remote object store down past the
        transport retry budget with no cached copy, ISSUE 15); per-slab
        retry events are emitted by the streaming engine itself."""
        rec = dict(context, kind=str(kind))
        self.shard_faults.append(rec)
        self._emit(str(kind), dict(context))

    def finalize(self):
        """Persist the resilience ledger (when anything happened) and
        enforce the per-K survival floor. Raises
        :class:`UnhealthySweepError` when any K ends below
        ``min_healthy_frac`` — consensus over too few replicates is
        worse than a loud failure."""
        if self._pending:
            # defensive: a caller that skipped the retry waves must not
            # silently drop unhealthy lanes on the floor
            for t in self.take_pending():
                rec = {"k": t["k"], "iter": t["iter"], "seed": t["seed"],
                       "attempts": t["attempt"] - 1}
                self.quarantined.append(rec)
                self._emit("quarantine", rec)
        if self.ledger_path:
            if self.retries or self.quarantined or self.shard_faults:
                from ..utils.anndata_lite import atomic_artifact

                payload = {"schema": 1,
                           "max_retries": self.max_retries,
                           "min_healthy_frac": self.min_healthy_frac,
                           "retries": self.retries,
                           "quarantined": self.quarantined}
                if self.shard_faults:
                    payload["shard_faults"] = self.shard_faults
                with atomic_artifact(self.ledger_path) as tmp:
                    with open(tmp, "w") as f:
                        json.dump(payload, f, indent=1)
            elif os.path.exists(self.ledger_path):
                # a clean pass supersedes any previous run's quarantine
                # records for this worker's shard — a stale ledger would
                # make combine silently drop now-healthy replicates
                os.unlink(self.ledger_path)
        bad = []
        for k, total in sorted(self._totals.items()):
            frac = self._healthy.get(k, 0) / max(total, 1)
            if frac < self.min_healthy_frac:
                bad.append((k, frac, total))
        if bad:
            detail = "; ".join(
                "k=%d: %.0f%% of %d replicates healthy" % (k, 100 * f, t)
                for k, f, t in bad)
            raise UnhealthySweepError(
                "factorize: too few healthy replicates after %d retry "
                "attempt(s) — %s (floor %s=%.2f, evaluated over this "
                "worker's ledger shard). Consensus over so few survivors "
                "would be unreliable; inspect the solver inputs "
                "(nonfinite counts? pathological scaling?), or lower the "
                "floor explicitly to accept the degraded sweep."
                % (self.max_retries, detail, MIN_HEALTHY_FRAC_ENV,
                   self.min_healthy_frac))


def load_quarantine_records(
        ledger_path_template: str) -> dict[tuple[int, int], int | None]:
    """Quarantined ``(k, iter) -> exhausted attempt count`` across every
    worker's resilience ledger (``...resilience.w*.json``); ``None`` when
    a record carries no attempt count. Resume uses the attempts to honor
    a RAISED ``CNMF_TPU_MAX_RETRIES`` (a record exhausted at 2 attempts
    is not final under a budget of 5)."""
    out: dict[tuple[int, int], int | None] = {}
    for path in glob.glob(str(ledger_path_template).replace("%d", "*")):
        try:
            with open(path) as f:
                payload = json.load(f)
            for rec in payload.get("quarantined", []):
                key = (int(rec["k"]), int(rec["iter"]))
                att = rec.get("attempts")
                att = None if att is None else int(att)
                # several ledgers may mention one lane: a known attempt
                # count beats unknown, larger beats smaller
                if key not in out:
                    out[key] = att
                elif att is not None and (out[key] is None
                                          or att > out[key]):
                    out[key] = att
        except (OSError, ValueError, KeyError, TypeError):
            warnings.warn(
                f"unreadable resilience ledger {path}; its quarantine "
                "records are ignored", RuntimeWarning, stacklevel=2)
    return out


def load_quarantined_tasks(ledger_path_template: str) -> set[tuple[int, int]]:
    """Union of quarantined ``(k, iter)`` pairs across every worker's
    resilience ledger: combine treats these as deliberately absent — no
    warning, no skip flag needed — instead of crashing on their missing
    artifacts."""
    return set(load_quarantine_records(ledger_path_template))


def sweep_stale_ledgers(ledger_path_template: str, total_workers: int):
    """Delete resilience ledgers whose worker index is outside the
    current fleet (a previous run with more workers left them; no live
    process owns those indices, and in-range ledgers are rewritten or
    removed by their own worker's finalize). Called by worker 0 at the
    start of a FRESH (non-resume) factorize — a fresh run recomputes
    every replicate, so prior quarantine records are void."""
    import re

    pattern = str(ledger_path_template).replace("%d", "*")
    rx = re.compile(re.escape(str(ledger_path_template)).replace(
        re.escape("%d"), r"(\d+)") + "$")
    for path in glob.glob(pattern):
        m = rx.match(path)
        if m and int(m.group(1)) >= int(total_workers):
            try:
                os.unlink(path)
            except OSError:
                pass
