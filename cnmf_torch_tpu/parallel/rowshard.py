"""Row-sharded (data-parallel) NMF over a device mesh — the atlas-scale path.

The reference scales cells only by streaming 5,000-row chunks through one
process (``/root/reference/src/cnmf/cnmf.py:765-767, 350-381``). The TPU
analog (SURVEY.md §5.7, BASELINE.json config 5) shards the cells axis of the
normalized matrix across the mesh and keeps the small factors replicated:

  * H rows live with their X rows — the H-subproblem is embarrassingly
    parallel (W is replicated, no communication).
  * The W-subproblem needs only the k x g / k x k sufficient statistics
    A = H^T X and B = H^T H, which are summed across shards with ``psum``
    over ICI — bytes moved per pass are O(k·(g+k)), independent of cells.

Implemented with ``shard_map`` so the collectives are explicit and the
per-device program is exactly the single-chip kernel.
"""

from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs import metrics as obs_metrics
from ..utils.jax_compat import shard_map

from .streaming import (
    StreamStats,
    _stream_csr_sharded,
    _stream_dense_sharded,
    stream_store_sharded,
)
from ..utils.shardstore import ShardStore, SlabCursor

from ..ops.nmf import (
    EPS,
    TRACE_LEN,
    lane_health,  # noqa: F401  (re-export: per-solve health surface)
    resolve_online_schedule,
    _apply_rate,
    mu_gamma,
    _beta_div_dense,
    _chunk_h_solve,
    _solve_w_from_stats,
    beta_loss_to_float,
    nndsvd_init_gram,
    random_init,
    split_regularization,
)
from ..ops.nmf import _apply_rate_sketched
from ..ops.pallas import resolve_pallas
from ..ops.sparse import (
    EllMatrix,
    csr_to_ell,
    ell_beta_err,
    ell_is_w_stats,
    ell_kl_w_numer,
    ell_kl_w_stats_rows,
    ell_row_width,
    resolve_sparse_beta,
)

__all__ = ["nmf_fit_rowsharded", "fit_h_rowsharded", "refit_w_rowsharded",
           "pad_rows_to_mesh", "stream_rows_to_mesh", "stream_ell_to_mesh",
           "prepare_rowsharded", "lane_health", "store_dispatch"]


def pad_rows_to_mesh(X, multiple: int):
    """Zero-pad the cells axis to a multiple (mesh size, or mesh size x
    block rows for the staged refit). Padded rows are benign: their usage
    rows collapse to zero in one MU step and contribute nothing to the
    psum'd statistics."""
    n = X.shape[0]
    pad = (-n) % multiple
    if pad:
        if sp.issparse(X):
            X = sp.vstack([X.tocsr(), sp.csr_matrix((pad, X.shape[1]), dtype=X.dtype)])
        else:
            X = np.pad(np.asarray(X), ((0, pad), (0, 0)))
    return X, pad


def stream_rows_to_mesh(X, mesh: Mesh, axis: str, dtype=jnp.float32,
                        pad_multiple: int | None = None,
                        stats: StreamStats | None = None, events=None,
                        liveness=None):
    """Out-of-core host→HBM transfer: build the row-sharded device array
    straight from a host CSR (or dense) matrix. Sparse inputs densify
    slab-by-slab (on device via ``streaming._csr_densify``, or on host per
    ``streaming._csr_transport``) — the full dense matrix never exists on
    host; dense inputs stream slab-wise. This is the reference's 5,000-row
    streaming contract (``cnmf.py:350-381``) with the slab as the
    streaming unit.

    Rows shard over the named ``axis`` of ``mesh`` (1-D cells mesh or the
    2-D replicates x cells mesh — in the latter the array is replicated
    over the other axis). Multi-host safe: every process supplies only its
    addressable shards. Returns ``(X_device, pad)`` where ``pad`` rows of
    zeros were appended to make the rows axis divide the mesh axis.

    Both branches run through the :mod:`.streaming` pipeline: slab prep on
    the stream thread pool, transfers round-robin across devices, donated
    on-device assembly — overlapped, with host memory bounded by
    ``CNMF_TPU_STREAM_DEPTH``. Pass ``stats`` to collect per-phase
    host_prep/H2D/device walls and bytes.
    """
    from ..runtime.faults import maybe_fail

    maybe_fail("upload", context="stream_rows_to_mesh")
    n_shards = dict(mesh.shape)[axis]
    multiple = int(pad_multiple) if pad_multiple else n_shards
    if multiple % n_shards:
        raise ValueError(
            f"pad_multiple={multiple} must be a multiple of the mesh axis "
            f"size {n_shards} so shards stay equal-sized")
    if isinstance(X, (ShardStore, SlabCursor)):
        # out-of-core ingestion (ISSUE 10): rows stream straight from the
        # shard store's per-slab files through the three-stage pipeline —
        # the full matrix never exists in host RAM, and each process
        # reads ONLY the slabs overlapping its addressable shards. The
        # assembled device array is bit-identical to staging the
        # in-memory matrix (values are placed, never summed).
        cursor = (X if isinstance(X, SlabCursor)
                  else SlabCursor(X, events=events))
        n = cursor.n_rows
        pad = (-n) % multiple
        sharding = NamedSharding(mesh, P(axis, None))
        return stream_store_sharded(cursor, sharding, dtype, stats=stats,
                                    events=events, liveness=liveness,
                                    pad_rows=pad), pad
    X, pad = pad_rows_to_mesh(X, multiple)
    sharding = NamedSharding(mesh, P(axis, None))
    if sp.issparse(X):
        return _stream_csr_sharded(X.tocsr(), sharding, dtype,
                                   stats=stats, events=events,
                                   liveness=liveness), pad
    return _stream_dense_sharded(np.asarray(X), sharding, dtype,
                                 stats=stats, events=events,
                                 liveness=liveness), pad


def stream_ell_to_mesh(X, mesh: Mesh, axis: str, width: int | None = None,
                       pad_multiple: int | None = None,
                       stats: StreamStats | None = None, events=None,
                       liveness=None):
    """Row-shard a host CSR matrix as fixed-width ELL — the beta != 2
    sparse staging path. The CSR buffers are already what crosses the wire
    on this path (``_stream_csr_sharded``); instead of densifying into an
    (rows, genes) HBM shard, each shard lands its ``(values, col_indices)``
    ELL slabs directly — HBM bytes scale with ``rows x width`` (~2x nnz
    including the int32 indices), not ``rows x genes``, and the sparse
    kernels then skip the dense WH/ratio passes entirely for KL.

    The ELL width is the GLOBAL max row nnz (padded to a lane multiple) so
    every shard compiles one program at one static shape. Returns
    ``(EllMatrix with (n, width) leaves sharded P(axis, None), pad)``.

    ``X`` may also be a :class:`~cnmf_torch_tpu.utils.shardstore.
    ShardStore` (out-of-core ingestion): each shard's CSR rows assemble
    from ONLY the slabs overlapping that shard — host residency is one
    shard's CSR at a time (nnz-scaled), never the full matrix — and the
    manifest's per-slab max-row-nnz gives the global ELL width without a
    data pass. The converted leaves are bit-identical to the in-memory
    path (same rows, same widths, same ``csr_to_ell``).
    """
    store = X if isinstance(X, ShardStore) else None
    if store is None and not sp.issparse(X):
        raise TypeError(
            "stream_ell_to_mesh takes a scipy-sparse matrix or a ShardStore")
    n_shards = dict(mesh.shape)[axis]
    multiple = int(pad_multiple) if pad_multiple else n_shards
    if multiple % n_shards:
        raise ValueError(
            f"pad_multiple={multiple} must be a multiple of the mesh axis "
            f"size {n_shards} so shards stay equal-sized")
    if store is not None:
        if store.format != "csr":
            raise TypeError("stream_ell_to_mesh needs a CSR-format store")
        n_data, g = store.shape
        pad = (-n_data) % multiple
        n = n_data + pad
        nnz_total = store.nnz

        def take_rows(lo, hi):
            """Shard rows [lo, hi) as CSR — reads only overlapping slabs;
            rows past the true row count are the mesh padding (zero)."""
            parts = []
            if lo < n_data:
                parts.append(store.row_block(lo, min(hi, n_data),
                                             events=events))
            tail = hi - max(lo, n_data)
            if tail > 0:
                parts.append(sp.csr_matrix((tail, g), dtype=np.float32))
            return (sp.vstack(parts).tocsr() if len(parts) > 1
                    else parts[0].tocsr())

        if width is None:
            from ..ops.sparse import _pad_width

            width = _pad_width(int(store.max_row_nnz) if n_data else 1)
    else:
        X, pad = pad_rows_to_mesh(X.tocsr(), multiple)
        n, g = X.shape
        nnz_total = X.nnz

        def take_rows(lo, hi):
            return X[lo:hi]

        if width is None:
            width = ell_row_width(X)
    # the GLOBAL transpose width must be derived from ALL shards, not just
    # this process's addressable ones: every process holds the same host
    # CSR and shards are equal row blocks, so scanning every block keeps
    # the static shape identical across a multi-host pod (a per-process
    # local max would lower different programs per host). One bincount
    # over each block's indices slice — no tocsc() (which re-sorts the
    # whole block's nnz) on this path.
    rows_per_shard = n // n_shards
    t_width = 8
    if g and nnz_total:
        if store is not None:
            # same per-shard column-count maxima, accumulated slab-wise
            # (one pass over slab index arrays — no data assembly)
            for s0 in range(0, n, rows_per_shard):
                s1 = min(s0 + rows_per_shard, n_data)
                if s1 <= s0:
                    continue
                counts = np.zeros((g,), dtype=np.int64)
                for si in store.slab_indices_for_rows(s0, s1):
                    blk = store.read_slab(si, events=events)
                    meta = store.slabs[si]
                    a = max(s0 - meta["row0"], 0)
                    b = min(s1, meta["row1"]) - meta["row0"]
                    seg = blk[a:b]
                    if seg.nnz:
                        counts += np.bincount(seg.indices, minlength=g)
                if counts.size:
                    t_width = max(t_width, int(counts.max()))
        else:
            ip = X.indptr
            for s0 in range(0, n, rows_per_shard):
                lo, hi = ip[s0], ip[min(s0 + rows_per_shard, n)]
                if hi > lo:
                    t_width = max(t_width, int(np.bincount(
                        X.indices[lo:hi], minlength=g).max()))
    # one static transpose width across shards => one compiled program
    t_width = -(-t_width // 8) * 8
    sharding = NamedSharding(mesh, P(axis, None))
    idx_map = sharding.addressable_devices_indices_map((n, int(width)))
    devs = list(idx_map)
    bounds = {dev: ((idx[0].start or 0),
                    (idx[0].stop if idx[0].stop is not None else n))
              for dev, idx in idx_map.items()}

    # pipeline the per-shard dual-ELL conversion (the expensive host prep:
    # row/transpose index builds) and the four leaf uploads per shard —
    # at most CNMF_TPU_STREAM_DEPTH shards' host ELL buffers are alive at
    # once (a shard's 4 leaves are the slab unit, so the bytes budget
    # clamps the window by the per-shard ELL footprint), and shards headed
    # to different devices convert/transfer concurrently instead of
    # serially
    from .streaming import run_pipeline, stream_depth, stream_threads

    shard_bytes = (rows_per_shard * int(width) * (4 + 4)
                   + g * int(t_width) * (4 + 4))
    ell_threads = stream_threads()
    ell_depth = stream_depth(slab_bytes=shard_bytes, threads=ell_threads)

    leaf_arrs: dict = {dev: None for dev in devs}

    def prep(dev):
        lo, hi = bounds[dev]
        t0 = time.perf_counter()
        ell = csr_to_ell(take_rows(lo, hi), width=int(width),
                         t_width=int(t_width))
        host = (ell.vals, ell.cols, ell.rows_t, ell.perm_t)
        t1 = time.perf_counter()
        parts = tuple(jax.device_put(a, dev) for a in host)
        jax.block_until_ready(parts)
        t2 = time.perf_counter()
        if stats is not None:
            stats.add(host_prep_s=t1 - t0, h2d_s=t2 - t1, slabs=1,
                      nbytes=sum(a.nbytes for a in host))
        return parts

    def commit(dev, parts):
        leaf_arrs[dev] = parts

    t_wall = time.perf_counter()
    run_pipeline(devs, prep, commit, depth=ell_depth, threads=ell_threads,
                 fault_context="stream_ell", events=events,
                 liveness=liveness)

    def assemble(shape, leaf_i, leaf_shard):
        arrs = [leaf_arrs[dev][leaf_i] for dev in devs]
        return jax.make_array_from_single_device_arrays(
            shape, leaf_shard, arrs)

    vals = assemble((n, int(width)), 0, sharding)
    cols = assemble((n, int(width)), 1, sharding)
    # transpose leaves: per-shard (g, t_width) blocks stack into a global
    # (n_shards * g, t_width) array split over the same axis — inside
    # shard_map each device sees exactly its shard's column grouping, with
    # perm_t indexing that shard's local flat value buffer
    t_shape = (n_shards * g, int(t_width))
    rows_t = assemble(t_shape, 2, sharding)
    perm_t = assemble(t_shape, 3, sharding)
    if stats is not None:
        stats.wall_s += time.perf_counter() - t_wall
    return EllMatrix(vals, cols, g, rows_t, perm_t), pad


def prepare_rowsharded(X, mesh: Mesh, stats: StreamStats | None = None,
                       events=None, liveness=None):
    """Stage a counts matrix for repeated row-sharded solves (one transfer,
    many replicates). Returns ``(X_device, n_orig)`` to pass to
    :func:`nmf_fit_rowsharded` / :func:`fit_h_rowsharded`. ``liveness``
    (a ``runtime.elastic.Heartbeat``) is stamped per committed slab so a
    multi-minute atlas stage stays diagnosably alive."""
    n_orig = int(X.shape[0])
    Xd, _ = stream_rows_to_mesh(X, mesh, mesh.axis_names[0], stats=stats,
                                events=events, liveness=liveness)
    return Xd, n_orig


def _rowsharded_pass(X_local, H_local, W, axis, beta, h_tol, chunk_max_iter,
                     l1_H, l2_H, l1_W, l2_W, kl_newton: bool = False,
                     sketch=None, pass_idx=0, use_pallas: bool = False):
    """One block-coordinate pass on this shard's rows + the global W update.

    Runs identically on every device; `psum` makes the W statistics global,
    so the replicated W stays bit-identical across shards.

    ``kl_newton`` (static; ISSUE 9): the per-shard usage solve runs the
    Diagonalized-Newton KL recipe (``ops/nmf.py:_chunk_h_solve``); the
    psum'd W statistics and the pass structure are unchanged, so ICI
    bytes per pass are identical.

    ``sketch`` (static ``(sketch_dim, exact_every)`` or None; β=1 only —
    the 'sketch' recipe, ISSUE 11): the per-shard usage solve and the
    psum'd objective stay exact, while the W statistics come from a
    per-shard ``sketch_dim``-row subsample of the LOCAL shard (the
    replicated per-``pass_idx`` key draws the same local indices on
    every shard — different physical rows, since shards hold different
    rows); every ``exact_every``-th pass (and pass 0) runs the exact
    statistics. The psum'd objects stay the same k x g / k-sized
    arrays, so ICI bytes per pass are unchanged — only the local
    statistics FLOPs shrink. Zero-evidence W entries hold their value
    (``ops/nmf.py:_apply_rate_sketched``). ``pass_idx`` is a traced
    scalar so the per-pass program is compiled once.

    ``use_pallas`` (static; ISSUE 16): the ELL β=1 branch computes the W
    numerator and the psum'd objective with the fused Pallas kernels
    (``ops/pallas_kl.py``) — the kernels run per-shard on the local rows
    BEFORE the psum, so the collective shapes and ICI bytes are
    unchanged. Default ``False`` traces the jnp chain unchanged.

    Returns ``(H_local, W, err, A, B)``. For beta=2, ``(A, B)`` are the
    pass's psum'd sufficient statistics (``H^T X``, ``H^T H``) — already
    computed for the W-subproblem, and exactly what the mid-run
    checkpoint persists (runtime/checkpoint.py; they are also the seed of
    ROADMAP item 4's incremental updates). For beta != 2 the W step has
    no cross-pass statistics, so ``(A, B)`` are ``None`` (callers inside
    while_loops drop them; the checkpoint stores zeros).
    """
    A = B = None
    WWT = W @ W.T if beta == 2.0 else None
    H_local = _chunk_h_solve(X_local, H_local, W, WWT, beta, l1_H, l2_H,
                             chunk_max_iter, h_tol, kl_newton=kl_newton,
                             use_pallas=use_pallas)
    if beta == 2.0:
        A = jax.lax.psum(H_local.T @ X_local, axis)
        B = jax.lax.psum(H_local.T @ H_local, axis)
        W = _solve_w_from_stats(W, A, B, l1_W, l2_W, chunk_max_iter, h_tol)
    elif beta == 1.0 and sketch is not None:
        # sketched KL W statistics (ISSUE 11): local cond picks exact vs
        # subsampled stats BEFORE the psum, so the collectives are
        # branch-free and uniform across shards
        sketch_dim, exact_every = sketch[0], max(sketch[1], 1)
        n_loc = (X_local.vals.shape[0] if isinstance(X_local, EllMatrix)
                 else X_local.shape[0])
        m = min(sketch_dim, n_loc)

        def _stats_exact(_):
            if isinstance(X_local, EllMatrix):
                numer = ell_kl_w_numer(X_local, H_local, W)
            else:
                numer = H_local.T @ (
                    X_local / jnp.maximum(H_local @ W, EPS))
            return numer, H_local.sum(axis=0)

        def _stats_sketched(_):
            idx = jax.random.randint(
                jax.random.fold_in(jax.random.key(2), pass_idx),
                (m,), 0, n_loc)
            hs = jnp.take(H_local, idx, axis=0)
            if isinstance(X_local, EllMatrix):
                numer, _ = ell_kl_w_stats_rows(X_local, H_local, W, idx)
            else:
                xs = jnp.take(X_local, idx, axis=0)
                numer = hs.T @ (xs / jnp.maximum(hs @ W, EPS))
            return numer, hs.sum(axis=0)

        exact_now = pass_idx % exact_every == 0
        numer_l, hsum_l = jax.lax.cond(
            exact_now, _stats_exact, _stats_sketched, operand=None)
        numer = jax.lax.psum(numer_l, axis)
        denom = jnp.broadcast_to(
            jax.lax.psum(hsum_l, axis)[:, None], W.shape)
        # exact passes apply the plain MU rate — zero numerators DECAY,
        # matching the batch lane's exact branch; only sketched passes
        # hold zero-evidence entries (a subsample that saw no nonzero in
        # a column is absence of evidence, an exact pass is evidence of
        # absence). Sketched passes scale the penalties by the sampled
        # fraction so the m/n-scaled statistics see m/n-scaled l1/l2
        # (full penalties would over-regularize by ~n/m)
        sc = m / float(n_loc)
        W_new = jnp.where(exact_now,
                          _apply_rate(W, numer, denom, l1_W, l2_W),
                          _apply_rate(W, numer, denom,
                                      l1_W * sc, l2_W * sc))
        W = jnp.where(exact_now | (numer > 0.0), W_new, W)
        if isinstance(X_local, EllMatrix):
            err = jax.lax.psum(ell_beta_err(X_local, H_local, W, beta),
                               axis)
            return H_local, W, err, A, B
    elif isinstance(X_local, EllMatrix):
        # ELL shard (stream_ell_to_mesh): nonzero-only W statistics; the
        # psum'd objects stay the same k x g / k-sized arrays as the dense
        # path, so ICI bytes per pass are unchanged
        if beta == 1.0:
            if use_pallas:
                from ..ops.pallas_kl import pallas_kl_w_numer

                numer_l = pallas_kl_w_numer(X_local, H_local, W)
            else:
                numer_l = ell_kl_w_numer(X_local, H_local, W)
            numer = jax.lax.psum(numer_l, axis)
            denom = jnp.broadcast_to(
                jax.lax.psum(H_local.sum(axis=0), axis)[:, None], W.shape)
        else:  # beta == 0.0 (itakura-saito, hybrid: dense WH denominator)
            numer, denom = ell_is_w_stats(X_local, H_local, W)
            numer = jax.lax.psum(numer, axis)
            denom = jax.lax.psum(denom, axis)
        W = _apply_rate(W, numer, denom, l1_W, l2_W, gamma=mu_gamma(beta))
        if use_pallas and beta == 1.0:
            from ..ops.pallas_kl import pallas_kl_beta_err

            err_l = pallas_kl_beta_err(X_local, H_local, W)
        else:
            err_l = ell_beta_err(X_local, H_local, W, beta)
        err = jax.lax.psum(err_l, axis)
        return H_local, W, err, A, B
    else:
        WH = jnp.maximum(H_local @ W, EPS)
        if beta == 1.0:
            numer = jax.lax.psum(H_local.T @ (X_local / WH), axis)
            denom = jnp.broadcast_to(
                jax.lax.psum(H_local.sum(axis=0), axis)[:, None], W.shape)
        else:  # beta == 0.0 (itakura-saito)
            numer = jax.lax.psum(H_local.T @ (X_local / (WH * WH)), axis)
            denom = jax.lax.psum(H_local.T @ (1.0 / WH), axis)
        W = _apply_rate(W, numer, denom, l1_W, l2_W, gamma=mu_gamma(beta))
    # objective of the updated (H, W): the cancellation-safe per-element
    # forms from _beta_div_dense (the naive KL/IS sums lose the O(u^2)
    # near-convergence terms to fp32 cancellation, breaking the pass-loop
    # convergence test)
    err = jax.lax.psum(_beta_div_dense(X_local, H_local @ W, beta), axis)
    return H_local, W, err, A, B


def _rowsharded_solve_local(X_local, H_local, W, axis, beta, tol, h_tol,
                            n_passes, chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                            telemetry: bool = False,
                            kl_newton: bool = False, sketch=None,
                            use_pallas: bool = False):
    """Per-device block-coordinate solve loop (runs inside ``shard_map``):
    passes of :func:`_rowsharded_pass` until the psum'd objective's relative
    improvement drops below ``tol`` or ``n_passes`` is reached. Shared by the
    1-D cells mesh (:func:`_fit_rowsharded_jit`) and the 2-D
    replicates x cells sweep (``multihost.replicate_sweep_2d``), so both
    paths have identical solver semantics.

    ``telemetry`` (static; default off adds zero ops): additionally
    returns ``(trace (TRACE_LEN,), passes (), nonfinite ())`` — the
    per-pass psum'd objectives are replicated across shards, so the
    telemetry leaves are too (``P()`` out-specs at the shard_map
    boundary)."""
    def body(carry):
        if telemetry:
            H_local, W, err_prev, err, it, trace, nonfin = carry
        else:
            H_local, W, err_prev, err, it = carry
        H_local, W, err_new, _, _ = _rowsharded_pass(
            X_local, H_local, W, axis, beta, h_tol, chunk_max_iter,
            l1_H, l2_H, l1_W, l2_W, kl_newton=kl_newton, sketch=sketch,
            pass_idx=it, use_pallas=use_pallas)
        if telemetry:
            # pass it+1's objective lands at 0-based slot it (slot 0 holds
            # the first pass's err0 from the init below)
            trace = trace.at[jnp.minimum(it, TRACE_LEN - 1)].set(err_new)
            nonfin = nonfin | ~jnp.isfinite(err_new)
            return (H_local, W, err, err_new, it + 1, trace, nonfin)
        return (H_local, W, err, err_new, it + 1)

    def cond(carry):
        err_prev, err, it = carry[2], carry[3], carry[4]
        rel = (err_prev - err) / jnp.maximum(err_prev, EPS)
        keep = (it < 2) | (rel >= tol)
        if sketch is not None:
            # the convergence test may only STOP on an exact-pass state
            # (pass index it-1 exact): a sketched pass whose subsample
            # noise reads as a sub-tol improvement must not freeze a
            # sketched W as the result — the same anchoring contract as
            # nmf_fit_batch's eval-boundary exact updates
            keep = keep | ((it - 1) % max(sketch[1], 1) != 0)
        return (it < n_passes) & keep

    H_local, W, err0, _, _ = _rowsharded_pass(
        X_local, H_local, W, axis, beta, h_tol, chunk_max_iter,
        l1_H, l2_H, l1_W, l2_W, kl_newton=kl_newton, sketch=sketch,
        pass_idx=jnp.int32(0), use_pallas=use_pallas)
    init = (H_local, W, err0 * (1.0 + 2.0 * tol) + 1.0, err0, jnp.int32(1))
    if telemetry:
        init = init + (jnp.full((TRACE_LEN,), jnp.nan,
                                jnp.float32).at[0].set(err0),
                       ~jnp.isfinite(err0))
    out = jax.lax.while_loop(cond, body, init)
    if telemetry:
        H_local, W, _, err, it, trace, nonfin = out
        return H_local, W, err, trace, it, nonfin | ~jnp.isfinite(err)
    H_local, W, _, err, _ = out
    return H_local, W, err


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "beta", "chunk_max_iter",
                     "l1_H", "l2_H", "l1_W", "l2_W", "kl_newton",
                     "sketch", "use_pallas"),
)
def _rowshard_pass_jit(X, H, W, mesh, axis, beta, h_tol, chunk_max_iter,
                       l1_H, l2_H, l1_W, l2_W, kl_newton: bool = False,
                       sketch=None, pass_idx=0, use_pallas: bool = False):
    """ONE block-coordinate pass as its own dispatch — the unit of the
    checkpointed host-driven loop (``_fit_rowsharded_checkpointed``). The
    per-device program is exactly the ``_rowsharded_pass`` body the fused
    while_loop runs, so per-pass results match the fused program's pass
    steps. Returns ``(H, W, err)`` plus, at beta=2, the pass's psum'd
    sufficient statistics ``(A, B)`` for the checkpoint."""
    with_stats = beta == 2.0
    out_specs = ((P(axis, None), P(), P(), P(), P()) if with_stats
                 else (P(axis, None), P(), P()))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P()),
        out_specs=out_specs,
    )
    def run(X_local, H_local, W, pass_idx_r):
        H_local, W, err, A, B = _rowsharded_pass(
            X_local, H_local, W, axis, beta, h_tol, chunk_max_iter,
            l1_H, l2_H, l1_W, l2_W, kl_newton=kl_newton, sketch=sketch,
            pass_idx=pass_idx_r, use_pallas=use_pallas)
        if with_stats:
            return H_local, W, err[None], A, B
        return H_local, W, err[None]

    out = run(X, H, W, jnp.asarray(pass_idx, jnp.int32))
    if with_stats:
        H, W, err, A, B = out
        return H, W, err[0], A, B
    H, W, err = out
    return H, W, err[0], None, None


def _fit_rowsharded_checkpointed(Xd, H0, W0, mesh, axis, beta, tol, h_tol,
                                 n_passes, chunk_max_iter,
                                 l1_H, l2_H, l1_W, l2_W, ckpt,
                                 heartbeat=None, n_orig=None,
                                 kl_newton: bool = False, sketch=None,
                                 use_pallas: bool = False):
    """Host-driven pass loop with mid-run checkpoints — the checkpointed
    twin of :func:`_fit_rowsharded_jit`'s fused while_loop (same per-pass
    program, same f32 convergence test, same stopping rule; the loop
    merely lives on host so state can be persisted between dispatches).

    Every ``ckpt.every`` completed passes the replicated ``W``, the pass
    statistics, the cursor, and (under the byte budget) ``H`` land on
    disk atomically; ``ckpt.load()`` on a resume restores them and the
    loop continues mid-run instead of re-deriving from scratch. With H in
    the checkpoint the resumed trajectory is bit-identical; without it, H
    re-derives from the restored W (one tightly solved block-coordinate
    pass — the sufficient-statistics trade, runtime/checkpoint.py).

    Liveness + elasticity (ISSUE 8): ``heartbeat`` (a
    ``runtime.elastic.Heartbeat``) is stamped at every pass boundary
    with the pass cursor, so a wedged or dead participant is diagnosable
    by name; the per-pass ``hostloss`` chaos hook fires here too — the
    boundary where a real dead device surfaces as the next dispatch
    failing — and the raised loss propagates to the elastic controller
    in ``models/cnmf.py``, which re-meshes over the survivors and
    re-enters this loop with ``resume=True`` (checkpointed state
    restores bit-exactly; remaining passes run on the shrunk mesh).

    Returns ``(H, W, err, trace (TRACE_LEN,) np, passes, nonfinite)``.
    """
    from ..runtime.faults import maybe_hostloss

    row_sh = NamedSharding(mesh, P(axis, None))
    rep_sh = NamedSharding(mesh, P())
    k, g = int(W0.shape[0]), int(W0.shape[1])
    n_pad = int(Xd.shape[0])
    h_tol_j = jnp.float32(h_tol)
    f32 = np.float32

    def one_pass(H, W, pass_idx):
        # pass_idx is traced, so every pass reuses ONE compiled program;
        # it feeds the sketch recipe's exact-interleave cadence and its
        # per-pass subsample stream (ignored when sketch is None). A
        # resumed run passes the restored cursor, so the cadence is
        # continuation-invariant.
        return _rowshard_pass_jit(
            Xd, H, W, mesh, axis, beta, h_tol_j, int(chunk_max_iter),
            l1_H, l2_H, l1_W, l2_W, kl_newton=kl_newton, sketch=sketch,
            pass_idx=pass_idx, use_pallas=use_pallas)

    trace = np.full((TRACE_LEN,), np.nan, np.float32)
    A = B = None
    ran_pass = False

    # H row validation: exact pad match on a stable mesh; with n_orig
    # known, a floor instead — an elastic continuation resumes a
    # checkpoint whose H was zero-padded for the ORIGINAL (larger) mesh,
    # and the zero tail re-fits the shrunk mesh's padding below
    state = (ckpt.load(n_rows_min=int(n_orig), n_genes=g)
             if n_orig is not None else ckpt.load(n_rows=n_pad, n_genes=g))
    if state is not None:
        W = jax.device_put(jnp.asarray(state["W"]), rep_sh)
        if state["H"] is not None:
            h_np = np.asarray(state["H"], np.float32)
            if h_np.shape[0] > n_pad:
                # rows past this mesh's padding are the writing mesh's
                # padding rows — exactly zero (a zero X row collapses
                # its usage row in one multiplicative step)
                h_np = h_np[:n_pad]
            elif h_np.shape[0] < n_pad:
                h_np = np.pad(h_np, ((0, n_pad - h_np.shape[0]), (0, 0)))
            H = jax.device_put(jnp.asarray(h_np), row_sh)
        else:
            H = H0
        resumed_without_h = state["H"] is None
        it = int(state["pass_idx"])
        err_prev, err = f32(state["err_prev"]), f32(state["err"])
        n_tr = min(len(state["trace"]), TRACE_LEN)
        trace[:n_tr] = state["trace"][:n_tr]
        A, B = state["A"], state["B"]
    else:
        resumed_without_h = False
        H, W, err0, A, B = one_pass(H0, W0, 0)
        ran_pass = True
        err = f32(err0)
        # same f32 arithmetic as the fused loop's init, so the resumed
        # convergence test sees bit-identical operands
        err_prev = f32(err * f32(1.0 + 2.0 * tol) + f32(1.0))
        it = 1
        trace[0] = err

    def _save():
        # the H byte budget gates the device->host gather itself (shape is
        # known up front) — an over-budget atlas-scale H must not cross
        # the host link every pass just to be discarded by the saver
        h_np = (np.asarray(H) if n_pad * k * 4 <= ckpt.h_budget else None)
        ckpt.save(pass_idx=it, err_prev=err_prev, err=err, trace=trace,
                  W=np.asarray(W),
                  A=(np.asarray(A) if A is not None
                     else np.zeros((k, g), np.float32)),
                  B=(np.asarray(B) if B is not None
                     else np.zeros((k, k), np.float32)),
                  H=h_np)

    def _pass_boundary():
        # liveness stamp + injectable topology loss, AFTER any checkpoint
        # write for this pass landed: an injected (or real) loss here
        # leaves exactly the on-disk state a preempted host leaves, and
        # the resumed continuation picks up from this pass's cursor
        obs_metrics.counter_inc("cnmf_rowshard_passes_total")
        if heartbeat is not None:
            heartbeat.beat(phase="pass", cursor=it)
        maybe_hostloss(context="pass")

    if ran_pass and ckpt.every and it % ckpt.every == 0 and ckpt.due():
        _save()
    _pass_boundary()

    def active() -> bool:
        # the fused loop's cond, in the same f32 arithmetic
        if it >= int(n_passes):
            return False
        if it < 2:
            return True
        if sketch is not None and (it - 1) % max(sketch[1], 1) != 0:
            # only exact-pass states may stop (see _rowsharded_solve_local)
            return True
        rel = (f32(err_prev) - f32(err)) / max(f32(err_prev), f32(EPS))
        return bool(rel >= f32(tol))

    while active():
        H, W, err_new, A, B = one_pass(H, W, it)
        ran_pass = True
        err_prev, err = err, f32(err_new)
        it += 1
        trace[min(it - 1, TRACE_LEN - 1)] = err
        if ckpt.every and it % ckpt.every == 0 and ckpt.due():
            _save()
        _pass_boundary()

    if resumed_without_h and not ran_pass:
        # already-converged checkpoint without H: the spectra (W) are
        # final, but the caller also gets usages — re-derive them from W
        # with one fixed-W solve (W untouched, solver-tolerance H)
        H = _fit_h_rowsharded_jit(Xd, H0, W, mesh, axis, beta,
                                  int(chunk_max_iter), h_tol_j, l1_H, l2_H)
    nonfin = not bool(np.isfinite(f32(err)))
    return H, W, float(err), trace, it, nonfin


def store_dispatch(store, mesh, beta, init: str = "random",
                   force_dense: bool = False):
    """How a store-backed rowshard solve will ingest on this mesh:
    ``(use_ell, slab_loop)``. ``use_ell`` mirrors the in-memory dispatch
    exactly (manifest density/width stand in for the host scan);
    ``slab_loop`` is True when the per-device resident shard would
    exceed the OOC shard budget AND the dense random-init lane (the only
    one with a slab-looped pass program) applies.

    ``force_dense`` (the model path): `cNMF._factorize_rowsharded` stages
    DENSE like its in-memory twin (store-backed runs must stay
    bit-identical to in-memory runs on the same ledger), so its budget
    decision must be sized with dense shard bytes — sizing with ELL
    bytes while staging dense would under-estimate the resident
    footprint by the dense/ELL ratio in exactly the over-budget regime."""
    n_dev = int(np.prod(mesh.devices.shape))
    n = store.n_rows
    per_dev_rows = max(-(-n // n_dev), 1) if n else 1
    use_ell = False
    w_ell = 0
    if not force_dense and store.format == "csr" and init == "random":
        from ..ops.sparse import _pad_width

        w_ell = _pad_width(int(store.max_row_nnz) if n else 1)
        use_ell = resolve_sparse_beta(beta, density=store.density,
                                      width=w_ell, g=store.n_genes)
    shard_bytes = per_dev_rows * (w_ell * 8 if use_ell
                                  else store.n_genes * 4)
    over = shard_bytes > _ooc_shard_budget_bytes()
    if over and (use_ell or init != "random"):
        import warnings

        warnings.warn(
            "shard store: per-device shard (%d bytes) exceeds the "
            "resident budget but the %s lane has no slab-looped pass "
            "program — staging resident anyway"
            % (shard_bytes, "ELL" if use_ell else f"init={init!r}"),
            RuntimeWarning, stacklevel=2)
        over = False
    return use_ell, over and beta in (2.0, 1.0, 0.0)


def _ooc_shard_budget_bytes() -> int:
    """Per-device resident-shard budget for store-backed solves:
    ``CNMF_TPU_OOC_SHARD_BYTES`` when set, else the reported device
    headroom (derated like the staged-refit budget; a conservative 8 GB
    on backends without memory stats — CPU tests then always stage
    resident unless the knob forces the slab loop)."""
    from ..utils.shardstore import ooc_shard_bytes

    explicit = ooc_shard_bytes()
    if explicit > 0:
        return explicit
    return _staged_refit_budget_bytes()


def _per_shard_sketch(recipe, mesh):
    """The recipe's GLOBAL sketch_dim as a per-shard static ``(rows,
    exact_every)`` tuple for this mesh (min 1 row per shard) — the ONE
    accounting shared by the resident and out-of-core tiers, so the two
    never sample different row budgets for the same recipe. ``None``
    for non-sketch recipes."""
    if recipe.algo != "sketch":
        return None
    n_shards = int(np.prod(mesh.devices.shape))
    return (max(1, -(-int(recipe.sketch_dim) // n_shards)),
            int(recipe.sketch_exact_every))


def _nmf_fit_rowsharded_ooc_entry(store, k, mesh, axis, beta, *, seed, tol,
                                  h_tol, n_passes, chunk_max_iter, alpha_W,
                                  l1_ratio_W, alpha_H, l1_ratio_H,
                                  telemetry_sink=None, checkpoint=None,
                                  heartbeat=None, recipe=None, events=None):
    """Dispatch shim for the slab-looped tier: resolves regularization +
    recipe exactly like the resident path, runs
    :func:`_fit_rowsharded_ooc`, and emits the same telemetry payload
    shape (``mode='rowshard-ooc'``)."""
    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)
    n_orig, g = store.shape
    if recipe is None:
        from ..ops.recipe import resolve_recipe

        recipe = resolve_recipe(beta, "rowshard", ell=False, n=int(n_orig),
                                g=int(g), k=int(k))
    if (recipe.kl_newton or recipe.algo == "sketch") and beta != 1.0:
        raise ValueError(
            f"recipe {recipe.label!r} requires beta=1 (KL), got "
            f"beta={beta}")
    sketch = _per_shard_sketch(recipe, mesh)
    ckpt = (checkpoint if checkpoint is not None
            and getattr(checkpoint, "every", 0) > 0 else None)
    stats = StreamStats()
    H, W, err, trace_np, passes, nonfin = _fit_rowsharded_ooc(
        store, int(k), mesh, axis, beta, int(seed), float(tol),
        float(h_tol), int(n_passes), int(chunk_max_iter), l1_H, l2_H,
        l1_W, l2_W, _ooc_shard_budget_bytes(), ckpt=ckpt,
        heartbeat=heartbeat, kl_newton=bool(recipe.kl_newton),
        sketch=sketch, events=events, stats=stats)
    if events is not None:
        try:
            events.emit_stream("rowshard_ooc_passes", stats)
        except Exception:
            pass
    if telemetry_sink is not None:
        from ..utils.telemetry import telemetry_enabled

        if telemetry_enabled():
            telemetry_sink({
                "k": int(k), "beta": float(beta), "mode": "rowshard-ooc",
                "seeds": [int(seed)], "cap": int(n_passes),
                "cadence": "pass", "trace": trace_np[None],
                "iters": np.asarray([passes]),
                "nonfinite": np.asarray([nonfin]),
                "errs": np.asarray([err], np.float64),
                "recipe": recipe.label, "kernel": "dense-jnp"})
    return (np.asarray(H)[:n_orig], np.asarray(W), float(err))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "beta", "chunk_max_iter",
                     "l1_H", "l2_H", "kl_newton", "sketch"),
)
def _ooc_group_pass_jit(Xg, Hg, W, A, B, err_acc, mesh, axis, beta, h_tol,
                        chunk_max_iter, l1_H, l2_H, kl_newton: bool = False,
                        sketch=None, pass_idx=0, group_idx=0):
    """One GROUP's contribution to a slab-looped out-of-core pass
    (ISSUE 10): solve this group's usage block with W frozen, then fold
    its psum'd statistics into the carried accumulators — strictly
    sequential adds across groups, so the pass is deterministic no matter
    how the disk pipeline overlapped the staging.

    beta=2 (``A``/``B`` carried): returns ``(Hg, A', B', err')`` — the
    W-subproblem solves ONCE per pass from the accumulated stats
    (``nmf_fit_online``'s block-coordinate flavor; the objective is
    evaluated against the pass-start W). beta in {1, 0} (``A``/``B``
    are numer/denom placeholders): returns the group's psum'd MU
    numerator/denominator for the caller's per-group online W step."""
    with_stats = beta == 2.0

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(), P(), P(), P(), P()),
        out_specs=(P(axis, None), P(), P(), P()),
    )
    def run(x, h, W, A, B, err_acc, cursor_r):
        # cursor_r: replicated (pass_idx, flat_step) — the pass index
        # drives the sketch recipe's exact-interleave cadence, the flat
        # step seeds a fresh subsample per (pass, group)
        WWT = W @ W.T if with_stats else None
        h = _chunk_h_solve(x, h, W, WWT, beta, l1_H, l2_H, chunk_max_iter,
                           h_tol, kl_newton=kl_newton)
        if with_stats:
            A = A + jax.lax.psum(h.T @ x, axis)
            B = B + jax.lax.psum(h.T @ h, axis)
            err = err_acc + jax.lax.psum(
                _beta_div_dense(x, h @ W, beta), axis)[None]
            return h, A, B, err
        WH = jnp.maximum(h @ W, EPS)
        if beta == 1.0 and sketch is not None:
            # sketched slab-loop W statistics (ISSUE 11): WH is needed
            # for the exact per-group objective anyway, so the sketched
            # branch only gathers sampled rows of the ratio and shrinks
            # the k x g numerator contraction from the group's rows to
            # sketch_dim of them; every exact_every-th PASS stays exact
            # sketch is a STATIC (dim, every) tuple of Python ints
            # (jit static_argnames) — no conversion, so the lint's
            # traced-concretization rule sees none either
            sk_dim, sk_every = sketch[0], max(sketch[1], 1)
            m = min(sk_dim, h.shape[0])
            n_loc = h.shape[0]
            ratio = x / WH

            def _stats_exact(_):
                return h.T @ ratio, h.sum(axis=0)

            def _stats_sk(_):
                idx = jax.random.randint(
                    jax.random.fold_in(jax.random.key(3), cursor_r[1]),
                    (m,), 0, n_loc)
                hs = jnp.take(h, idx, axis=0)
                return hs.T @ jnp.take(ratio, idx, axis=0), hs.sum(axis=0)

            numer_l, hsum_l = jax.lax.cond(
                cursor_r[0] % sk_every == 0, _stats_exact, _stats_sk,
                operand=None)
            numer = jax.lax.psum(numer_l, axis)
            denom = jnp.broadcast_to(
                jax.lax.psum(hsum_l, axis)[:, None], W.shape)
        elif beta == 1.0:
            numer = jax.lax.psum(h.T @ (x / WH), axis)
            denom = jnp.broadcast_to(
                jax.lax.psum(h.sum(axis=0), axis)[:, None], W.shape)
        else:  # beta == 0.0 (itakura-saito)
            numer = jax.lax.psum(h.T @ (x / (WH * WH)), axis)
            denom = jax.lax.psum(h.T @ (1.0 / WH), axis)
        err = err_acc + jax.lax.psum(
            _beta_div_dense(x, WH, beta), axis)[None]
        return h, numer, denom, err

    return run(Xg, Hg, W, A, B, err_acc,
               jnp.stack([jnp.asarray(pass_idx, jnp.int32),
                          jnp.asarray(group_idx, jnp.int32)]))


# l1_W/l2_W are static: _apply_rate branches on their truthiness in
# Python (regularization is resolved once per solve, so one compile)
_solve_w_from_stats_jit = jax.jit(
    _solve_w_from_stats, static_argnames=("l1_W", "l2_W", "max_iter"))


def _fit_rowsharded_ooc(store, k, mesh, axis, beta, seed, tol, h_tol,
                        n_passes, chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                        shard_budget, ckpt=None, heartbeat=None,
                        kl_newton: bool = False, sketch=None, events=None,
                        stats: StreamStats | None = None):
    """Slab-looped out-of-core rowsharded solve: X NEVER becomes resident
    — each pass streams slab GROUPS (per-device resident bytes bounded by
    ``shard_budget``) from the shard store through the three-stage disk
    pipeline, solves each group's usage block with W frozen, and
    accumulates the same tiny ``(A, B)`` pass statistics the resident
    pass psums (MPI-FAUN / distributed out-of-memory NMF: global factor
    state + local data blocks). The usage matrix H stays resident as
    per-group sharded blocks (k/g-fold smaller than X).

    Semantics: the ONLINE solver's block-coordinate pass
    (``ops/nmf.py:nmf_fit_online``) with the group as the chunk — beta=2
    solves W once per pass from the accumulated statistics, beta in
    {1, 0} takes one MU W step per group. Group granularity makes this
    tier solver-tolerance-equivalent to the resident pass program, NOT
    bit-identical (the resident shard solves its usage block jointly);
    the store-backed RESIDENT path keeps bit-parity — this tier only
    engages when the shard cannot be resident at all. Stopping rule,
    pass caps, checkpoint protocol (``ckpt``: the full
    ``PassCheckpointer`` contract incl. the H byte budget and the store
    digest in the identity), heartbeat stamps, and the ``hostloss`` /
    ``shard_read`` chaos hooks mirror ``_fit_rowsharded_checkpointed``.

    Returns ``(H (n_pad, k) np, W np, err, trace, passes, nonfinite)``.
    """
    from ..runtime.faults import maybe_hostloss

    n_orig, g = store.shape
    n_dev = int(np.prod(mesh.devices.shape))
    per_dev_rows = max(8, int(shard_budget) // max(g * 4, 1))
    per_dev_rows = min(per_dev_rows, max(-(-n_orig // n_dev), 1))
    group_rows = per_dev_rows * n_dev
    n_groups = max(-(-n_orig // group_rows), 1)
    n_pad = n_groups * group_rows

    row_sh = NamedSharding(mesh, P(axis, None))
    rep_sh = NamedSharding(mesh, P())
    f32 = np.float32
    h_tol_j = jnp.float32(h_tol)

    def _split_h(H_full):
        """(n_pad, k) host/device array -> per-group sharded blocks."""
        out = []
        for gi in range(n_groups):
            blk = jnp.asarray(np.asarray(
                H_full[gi * group_rows:(gi + 1) * group_rows], np.float32))
            out.append(jax.device_put(blk, row_sh))
        return out

    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    # the manifest's exact f64 value sum stands in for the resident
    # path's on-device mean — no data pass needed before the first slab
    x_mean = jnp.float32(store.value_sum / max(n_pad * g, 1))
    H0_full, W0 = random_init(key, n_pad, g, int(k), x_mean)
    H_groups = _split_h(H0_full)
    del H0_full
    W = jax.device_put(W0, rep_sh)

    def stage_group(gi):
        lo = gi * group_rows
        hi = min(lo + group_rows, n_orig)
        cursor = SlabCursor(store, rows=(lo, hi), events=events)
        return stream_store_sharded(
            cursor, row_sh, jnp.float32, stats=stats, events=events,
            liveness=heartbeat, pad_rows=group_rows - (hi - lo))

    zero_A = jax.device_put(jnp.zeros((int(k), g), jnp.float32), rep_sh)
    zero_B = jax.device_put(jnp.zeros((int(k), int(k)), jnp.float32),
                            rep_sh)
    zero_err = jax.device_put(jnp.zeros((1,), jnp.float32), rep_sh)

    def one_pass(W, pass_i=0):
        A, B, err_acc = zero_A, zero_B, zero_err
        for gi in range(n_groups):
            Xg = stage_group(gi)
            Hg, A, B, err_acc = _ooc_group_pass_jit(
                Xg, H_groups[gi], W, A, B, err_acc, mesh, axis, beta,
                h_tol_j, int(chunk_max_iter), l1_H, l2_H,
                kl_newton=kl_newton, sketch=sketch, pass_idx=pass_i,
                group_idx=pass_i * n_groups + gi)
            if beta != 2.0:
                # online flavor: one MU W step per group from its own
                # statistics (cross-group accumulation would mix
                # inconsistent (h, W) pairs — nmf_fit_online's contract)
                if (sketch is not None and beta == 1.0
                        and pass_i % max(sketch[1], 1) != 0):
                    # sketched-pass statistics: zero-evidence entries
                    # hold (ops/nmf.py:_apply_rate_sketched;
                    # gamma(beta=1)=1); exact passes take the plain rate
                    # below so genuinely dead entries still decay,
                    # matching the batch lane. Penalties scale with the
                    # sampled fraction of the group (m per shard of
                    # group_rows/n_shards rows), like every sketched lane
                    sc = min(1.0, sketch[0] * n_dev
                             / max(group_rows, 1))
                    W = _apply_rate_sketched(W, A, B,
                                             l1_W * sc, l2_W * sc)
                else:
                    W = _apply_rate(W, A, B, l1_W, l2_W,
                                    gamma=mu_gamma(beta))
                A, B = zero_A, zero_B
            jax.block_until_ready(Hg)
            _delete_group(Xg)
            H_groups[gi] = Hg
            if heartbeat is not None:
                heartbeat.beat(phase="ooc_group", cursor=gi)
        if beta == 2.0:
            W = _solve_w_from_stats_jit(W, A, B, l1_W, l2_W,
                                        int(chunk_max_iter), h_tol_j)
            return W, float(np.asarray(err_acc)[0]), A, B
        return W, float(np.asarray(err_acc)[0]), None, None

    trace = np.full((TRACE_LEN,), np.nan, np.float32)
    A = B = None
    ran_pass = False
    state = (ckpt.load(n_rows_min=n_orig, n_genes=g)
             if ckpt is not None and ckpt.every > 0 else None)
    if state is not None:
        W = jax.device_put(jnp.asarray(state["W"]), rep_sh)
        if state["H"] is not None:
            h_np = np.asarray(state["H"], np.float32)
            if h_np.shape[0] > n_pad:
                h_np = h_np[:n_pad]
            elif h_np.shape[0] < n_pad:
                h_np = np.pad(h_np, ((0, n_pad - h_np.shape[0]), (0, 0)))
            H_groups = _split_h(h_np)
        it = int(state["pass_idx"])
        err_prev, err = f32(state["err_prev"]), f32(state["err"])
        n_tr = min(len(state["trace"]), TRACE_LEN)
        trace[:n_tr] = state["trace"][:n_tr]
        A, B = state["A"], state["B"]
    else:
        W, err0, A, B = one_pass(W, 0)
        ran_pass = True
        err = f32(err0)
        err_prev = f32(err * f32(1.0 + 2.0 * tol) + f32(1.0))
        it = 1
        trace[0] = err

    def _gather_h():
        if n_pad * int(k) * 4 > ckpt.h_budget:
            return None
        return np.concatenate([np.asarray(Hg) for Hg in H_groups], axis=0)

    def _save():
        ckpt.save(pass_idx=it, err_prev=err_prev, err=err, trace=trace,
                  W=np.asarray(W),
                  A=(np.asarray(A) if A is not None
                     else np.zeros((int(k), g), np.float32)),
                  B=(np.asarray(B) if B is not None
                     else np.zeros((int(k), int(k)), np.float32)),
                  H=_gather_h())

    def _pass_boundary():
        obs_metrics.counter_inc("cnmf_rowshard_passes_total")
        if heartbeat is not None:
            heartbeat.beat(phase="ooc_pass", cursor=it)
        maybe_hostloss(context="pass")

    ckpt_on = ckpt is not None and ckpt.every > 0
    if ran_pass and ckpt_on and it % ckpt.every == 0 and ckpt.due():
        _save()
    _pass_boundary()

    def active() -> bool:
        if it >= int(n_passes):
            return False
        if it < 2:
            return True
        if sketch is not None and (it - 1) % max(sketch[1], 1) != 0:
            # only exact-pass states may stop (see _rowsharded_solve_local)
            return True
        rel = (f32(err_prev) - f32(err)) / max(f32(err_prev), f32(EPS))
        return bool(rel >= f32(tol))

    while active():
        W, err_new, A, B = one_pass(W, it)
        ran_pass = True
        err_prev, err = err, f32(err_new)
        it += 1
        trace[min(it - 1, TRACE_LEN - 1)] = err
        if ckpt_on and it % ckpt.every == 0 and ckpt.due():
            _save()
        _pass_boundary()

    H = np.concatenate([np.asarray(Hg) for Hg in H_groups], axis=0)
    nonfin = not bool(np.isfinite(f32(err)))
    return H, np.asarray(W), float(err), trace, it, nonfin


def _delete_group(Xg):
    """Free a staged group's device buffers ahead of the next group's
    upload (best-effort; see ``models.cnmf._delete_staged``)."""
    try:
        Xg.delete()
    except Exception:
        pass


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "beta", "n_passes", "chunk_max_iter",
                     "l1_H", "l2_H", "l1_W", "l2_W", "telemetry",
                     "kl_newton", "sketch", "use_pallas"),
)
def _fit_rowsharded_jit(X, H0, W0, mesh, axis, beta, tol, h_tol, n_passes,
                        chunk_max_iter, l1_H, l2_H, l1_W, l2_W,
                        telemetry: bool = False, kl_newton: bool = False,
                        sketch=None, use_pallas: bool = False):
    out_specs = ((P(axis, None), P(), P()) if not telemetry
                 else (P(axis, None), P(), P(), P(), P(), P()))

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P()),
        out_specs=out_specs,
    )
    def run(X_local, H_local, W):
        out = _rowsharded_solve_local(
            X_local, H_local, W, axis, beta, tol, h_tol, n_passes,
            chunk_max_iter, l1_H, l2_H, l1_W, l2_W, telemetry=telemetry,
            kl_newton=kl_newton, sketch=sketch, use_pallas=use_pallas)
        if telemetry:
            H_local, W, err, trace, passes, nonfin = out
            return (H_local, W, err[None], trace, passes[None],
                    nonfin[None])
        H_local, W, err = out
        return H_local, W, err[None]

    out = run(X, H0, W0)
    if telemetry:
        H, W, err, trace, passes, nonfin = out
        return H, W, err[0], trace, passes[0], nonfin[0]
    H, W, err = out
    return H, W, err[0]


def nmf_fit_rowsharded(X, k: int, mesh: Mesh, beta_loss="frobenius",
                       seed: int = 0, tol: float = 1e-4, h_tol: float = 0.05,
                       n_passes: int | None = None,
                       chunk_max_iter: int = 1000,
                       alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                       alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                       n_orig: int | None = None, init: str = "random",
                       telemetry_sink=None, checkpoint=None,
                       heartbeat=None, recipe=None, events=None,
                       store_slab_loop: bool = False):
    """Factorize a cells-sharded X over ``mesh`` (1-D). Returns
    ``(H (n,k), W (k,g), err)`` as numpy arrays.

    ``telemetry_sink``: optional callable receiving one convergence
    record dict (per-pass objective trace, passes run, capped/nonfinite
    flags) — active only under ``CNMF_TPU_TELEMETRY``; the telemetry-off
    program is unchanged.

    ``checkpoint``: optional
    :class:`~cnmf_torch_tpu.runtime.checkpoint.PassCheckpointer` — the
    solve then runs the checkpointed host-driven pass loop
    (:func:`_fit_rowsharded_checkpointed`): pass state persists every
    ``checkpoint.every`` passes and a valid checkpoint resumes mid-run.
    ``None`` (or ``every <= 0``) keeps the fused single-dispatch
    while_loop program, byte-identical to the pre-checkpoint build.

    ``heartbeat``: optional ``runtime.elastic.Heartbeat`` stamped with
    the pass cursor at every pass boundary of the checkpointed loop —
    pass-granular liveness for the elastic layer (the fused program is
    a single dispatch, so it cannot beat mid-run).

    ``recipe``: the resolved :class:`~cnmf_torch_tpu.ops.recipe.
    SolverRecipe` (ISSUE 9) — its ``kl_newton`` field threads the
    Diagonalized-Newton β=1 usage solves into the pass program; the
    ``amu`` repeat schedule is native here (the pass loop already
    repeats the cheap usage solve per W update). ``None`` resolves from
    the env knobs (default: plain MU, byte-identical programs). The
    engaged recipe is labeled in the telemetry record, and callers fold
    ``recipe.signature()`` into the checkpoint identity ``params`` so a
    resumed run never splices two recipes' trajectories.

    ``X`` may be a host matrix (dense or CSR — streamed shard-by-shard to
    HBM without a host dense copy) or a device array already staged by
    :func:`prepare_rowsharded` (pass its ``n_orig``), which amortizes the
    transfer across a replicate sweep.

    The semantic contract matches the single-chip online solver (block
    coordinate descent with tightly solved usage blocks and an exact
    statistics-based W subproblem per pass); the shard boundary replaces the
    chunk boundary as the streaming unit.
    """
    beta = beta_loss_to_float(beta_loss)
    # same per-loss pass-cap resolution as the single-chip online solver, so
    # crossing the rowshard threshold never changes the convergence
    # schedule; measured at 300k x 2k KL on v5e: 60 vs 20 passes costs +14%
    # wall-clock (the objective-tol stop fires early) for a better optimum
    _, n_passes, _ = resolve_online_schedule(beta, h_tol, n_passes)
    if beta not in (2.0, 1.0, 0.0):
        # the generic-beta update exists only on the single-chip path
        # (ops.nmf._update_W); the sharded pass implements the three named
        # losses — silently running IS updates for beta=1.5 would optimize
        # a different objective than the convergence test evaluates
        raise ValueError(
            f"nmf_fit_rowsharded supports beta in {{2, 1, 0}}, got {beta}")
    axis = mesh.axis_names[0]
    ooc_deep = False
    if isinstance(X, (jax.Array, EllMatrix)):
        Xd = X
        if n_orig is None:
            n_orig = int(X.shape[0])
    elif isinstance(X, ShardStore):
        # out-of-core ingestion (ISSUE 10): X streams from the shard
        # store. Under the per-device shard budget it stages RESIDENT
        # through the disk pipeline — the assembled array (and therefore
        # every downstream program) is bit-identical to the in-memory
        # path; over the budget the dense random-init solve runs the
        # slab-looped pass program instead (solver tolerance).
        store = X
        n_orig = store.n_rows
        if store_slab_loop:
            # the caller (cNMF._factorize_rowsharded) already sized the
            # budget decision — with DENSE bytes, since its staging twin
            # is dense — and handed the store over specifically for the
            # slab-looped tier; re-deciding here with ELL sizing could
            # disagree and silently re-stage resident once per replicate
            use_ell, ooc_deep = False, True
        else:
            use_ell, ooc_deep = store_dispatch(store, mesh, beta,
                                               init=init)
        if ooc_deep:
            Xd = None
        elif use_ell:
            Xd, _ = stream_ell_to_mesh(store, mesh, axis, events=events)
        else:
            Xd, _ = stream_rows_to_mesh(store, mesh, axis, events=events,
                                        liveness=heartbeat)
    else:
        n_orig = int(X.shape[0])
        if (sp.issparse(X) and init == "random" and resolve_sparse_beta(
                beta, density=X.nnz / max(X.shape[0] * X.shape[1], 1),
                width=ell_row_width(X), g=X.shape[1])):
            # CSR is already what crosses the wire on this path — land it
            # as fixed-width ELL shards instead of densifying on-device
            # (stream_ell_to_mesh); the solver then runs the nonzero-only
            # kernels with identical psum'd statistics shapes
            Xd, _ = stream_ell_to_mesh(X, mesh, axis)
        else:
            Xd, _ = stream_rows_to_mesh(X, mesh, axis)
    if ooc_deep:
        return _nmf_fit_rowsharded_ooc_entry(
            X, int(k), mesh, axis, beta, seed=seed, tol=tol, h_tol=h_tol,
            n_passes=n_passes, chunk_max_iter=chunk_max_iter,
            alpha_W=alpha_W, l1_ratio_W=l1_ratio_W, alpha_H=alpha_H,
            l1_ratio_H=l1_ratio_H, telemetry_sink=telemetry_sink,
            checkpoint=checkpoint, heartbeat=heartbeat, recipe=recipe,
            events=events)
    n, g = Xd.shape

    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    if init == "random":
        # on-device reduction over the sharded array; the ELL mean counts
        # the implicit zeros (vals sum over all n*g positions)
        x_mean = (jnp.sum(Xd.vals) / (n * g) if isinstance(Xd, EllMatrix)
                  else jnp.mean(Xd))
        H0, W0 = random_init(key, n, g, int(k), x_mean)
    elif isinstance(Xd, EllMatrix):
        raise ValueError(
            f"ELL-encoded rowshard solves require init='random', "
            f"got {init!r} (the nndsvd gram base needs the dense matrix)")
    elif init in ("nndsvd", "nndsvda", "nndsvdar"):
        # gram-based nndsvd: the only replicated object is the g x g gram;
        # per-replicate seeded zero-fill keeps consensus sweeps non-vacuous
        # (same mapping as the single-chip path, ops/nmf.py:init_factors)
        variant = "nndsvdar" if init == "nndsvd" else init
        H0, W0 = nndsvd_init_gram(Xd, int(k), variant=variant, key=key)
    else:
        raise ValueError(f"unknown init {init!r}")

    row_sh = NamedSharding(mesh, P(axis, None))
    rep_sh = NamedSharding(mesh, P())
    H0 = jax.device_put(H0, row_sh)
    W0 = jax.device_put(W0, rep_sh)

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)

    if recipe is None:
        from ..ops.recipe import resolve_recipe

        recipe = resolve_recipe(beta, "rowshard",
                                ell=isinstance(Xd, EllMatrix), n=int(n),
                                g=int(g), k=int(k),
                                ell_width=(Xd.width
                                           if isinstance(Xd, EllMatrix)
                                           else None))
    if (recipe.kl_newton or recipe.algo == "sketch") and beta != 1.0:
        # same contract as run_nmf/nmf_fit_batch: a caller-pinned dna or
        # sketch recipe on a non-KL solve must fail loudly — silently
        # running plain MU would leave telemetry and the checkpoint-
        # identity signature describing math that never ran
        raise ValueError(
            f"recipe {recipe.label!r} requires beta=1 (KL), got "
            f"beta={beta}")
    kl_newton = bool(recipe.kl_newton)
    # the recipe's sketch_dim counts GLOBAL sampled rows per W update;
    # each shard samples its share so a d-device mesh still touches
    # ~sketch_dim rows total (min 1 per shard), instead of d times that
    sketch = _per_shard_sketch(recipe, mesh)
    # fused Pallas KL kernels (ISSUE 16): ELL β=1 shards only; the sketch
    # recipe's row-subsampled W statistics need a scatter the transpose
    # index set cannot serve, so it keeps the jnp chain. Default-off
    # resolution passes False — static default, so the compiled programs
    # are byte-identical to a build without the kernel layer.
    use_pallas = (isinstance(Xd, EllMatrix) and beta == 1.0
                  and recipe.algo != "sketch" and resolve_pallas())
    kernel = ("dense-jnp" if not isinstance(Xd, EllMatrix)
              else ("ell-pallas" if use_pallas else "ell-jnp"))

    want_telem = False
    if telemetry_sink is not None:
        from ..utils.telemetry import telemetry_enabled

        want_telem = telemetry_enabled()
    if checkpoint is not None and getattr(checkpoint, "every", 0) > 0:
        H, W, err, trace_np, passes, nonfin = _fit_rowsharded_checkpointed(
            Xd, H0, W0, mesh, axis, beta, float(tol), float(h_tol),
            int(n_passes), int(chunk_max_iter), l1_H, l2_H, l1_W, l2_W,
            checkpoint, heartbeat=heartbeat, n_orig=n_orig,
            kl_newton=kl_newton, sketch=sketch, use_pallas=use_pallas)
        if want_telem:
            telemetry_sink({
                "k": int(k), "beta": float(beta), "mode": "rowshard",
                "seeds": [int(seed)], "cap": int(n_passes),
                "cadence": "pass", "trace": trace_np[None],
                "iters": np.asarray([passes]),
                "nonfinite": np.asarray([nonfin]),
                "errs": np.asarray([err], np.float64),
                "recipe": recipe.label, "kernel": kernel})
        return (np.asarray(H)[:n_orig], np.asarray(W), float(err))
    out = _fit_rowsharded_jit(
        Xd, H0, W0, mesh, axis, beta, jnp.float32(tol), jnp.float32(h_tol),
        int(n_passes), int(chunk_max_iter), l1_H, l2_H, l1_W, l2_W,
        telemetry=want_telem, kl_newton=kl_newton, sketch=sketch,
        use_pallas=use_pallas)
    H, W, err = out[:3]
    if want_telem:
        trace, passes, nonfin = out[3:]
        telemetry_sink({
            "k": int(k), "beta": float(beta), "mode": "rowshard",
            "seeds": [int(seed)], "cap": int(n_passes), "cadence": "pass",
            "trace": trace[None], "iters": passes[None],
            "nonfinite": nonfin[None], "errs": err[None],
            "recipe": recipe.label, "kernel": kernel})
    return (np.asarray(H)[:n_orig], np.asarray(W), float(err))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "beta", "chunk_max_iter", "l1_H", "l2_H"),
)
def _fit_h_rowsharded_jit(X, H0, W, mesh, axis, beta, chunk_max_iter, h_tol,
                          l1_H, l2_H):
    fn = shard_map(
        lambda x, h, w: _chunk_h_solve(
            x, h, w, w @ w.T if beta == 2.0 else None, beta,
            l1_H, l2_H, chunk_max_iter, h_tol),
        mesh=mesh, in_specs=(P(axis, None), P(axis, None), P()),
        out_specs=P(axis, None))
    return fn(X, H0, W)


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "axis", "beta", "max_iter", "blk",
                     "l1_W", "l2_W"),
)
def _refit_w_staged_jit(X, H, W0, mesh, axis, beta, max_iter, h_tol, blk,
                        l1_W, l2_W):
    """Whole-refit-in-one-dispatch W solve against an HBM-RESIDENT sharded X.

    Each MU iteration is a ``fori_loop`` of dynamic (blk x genes) row
    slices of the local shard — the WH intermediate never exceeds one
    block, and slicing (unlike a blocked reshape) never changes the
    physical layout, so XLA does not materialize a second full-size copy
    of the resident shard. Numerator/denominator are ``psum``'d across
    shards; the whole while_loop runs on device: per-iteration cost is one
    HBM pass over X, independent of the host link entirely."""
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P()), out_specs=P(),
    )
    def run(X_local, H_local, W):
        rows, g = X_local.shape
        k = H_local.shape[1]
        if rows % blk:
            # the reshape this fori_loop replaced failed loudly on
            # indivisible shards; keep that guard — silently skipping the
            # tail rows would corrupt the W statistics
            raise ValueError(
                f"shard rows {rows} not divisible by block {blk}; pad rows "
                "to a blk * n_dev multiple (refit_w_rowsharded does)")
        nblk = rows // blk

        # the KL denominator (column sums of the FIXED H) is loop-invariant:
        # compute its psum once, not one ICI collective per MU iteration
        # (XLA does not hoist collectives out of while_loop bodies)
        kl_denom = (jnp.broadcast_to(
            jax.lax.psum(H_local.sum(axis=0), axis)[:, None], W.shape)
            if beta == 1.0 else None)

        def stats(W):
            # dynamic row slices, NOT a (nblk, blk, g) reshape: the reshape
            # changes the physical layout, so XLA materializes a second
            # full-size copy of the HBM-resident shard — an instant OOM at
            # atlas scale (8 GB + 8 GB on a 16 GB chip)
            def blk_stats(b, acc):
                x = jax.lax.dynamic_slice_in_dim(X_local, b * blk, blk)
                h = jax.lax.dynamic_slice_in_dim(H_local, b * blk, blk)
                WH = jnp.maximum(h @ W, EPS)
                if beta == 1.0:
                    return acc + h.T @ (x / WH)
                # beta == 0.0 (itakura-saito): numer and denom stacked
                return acc + jnp.stack((h.T @ (x / (WH * WH)),
                                        h.T @ (1.0 / WH)))

            shape = (k, g) if beta == 1.0 else (2, k, g)
            # init derived from the shard (not a literal) so its varying
            # manual axes match the body's under shard_map — same trick as
            # ops.nmf._chunk_h_solve's rel0
            acc0 = jnp.zeros(shape, jnp.float32) + 0.0 * X_local[0, 0]
            acc = jax.lax.fori_loop(0, nblk, blk_stats, acc0)
            acc = jax.lax.psum(acc, axis)
            if beta == 1.0:
                return acc, kl_denom
            return acc[0], acc[1]

        def body(carry):
            W, _, it = carry
            numer, denom = stats(W)
            W_new = _apply_rate(W, numer, denom, l1_W, l2_W,
                                gamma=mu_gamma(beta))
            rel = jnp.linalg.norm(W_new - W) / (jnp.linalg.norm(W) + EPS)
            return (W_new, rel, it + 1)

        def cond(carry):
            _, rel, it = carry
            return (it < max_iter) & (rel >= h_tol)

        rel0 = jnp.inf + 0.0 * jnp.sum(W)
        W, _, _ = jax.lax.while_loop(cond, body, (W, rel0, jnp.int32(0)))
        return W

    return run(X, H, W0)


def _staged_refit_budget_bytes() -> int:
    """Per-device HBM headroom for staging X in the spectra refit: what the
    runtime reports free, derated; a conservative 8 GB when the backend
    (CPU tests) doesn't report memory stats."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        free = int(stats["bytes_limit"]) - int(stats["bytes_in_use"])
        return int(free * 0.6)
    except Exception:
        return 8 << 30


def refit_w_rowsharded(X, H, beta=2.0, h_tol: float = 0.05,
                       max_iter: int = 200, l1_reg_W: float = 0.0,
                       l2_reg_W: float = 0.0, seed: int = 0,
                       row_block: int = 100_000, mesh: Mesh | None = None,
                       stage: bool | str = "auto",
                       stage_budget_bytes: int | None = None) -> np.ndarray:
    """Fixed-usage spectra refit at atlas scale WITHOUT the transpose trick.

    The reference's ``refit_spectra`` is ``refit_usage(X.T, usage.T).T``
    (``cnmf.py:979-994``): its row chunks become (chunk x n_cells) dense
    buffers — at 1M cells that is ~20 GB *per chunk*, the wall BASELINE
    config 5 hits in consensus. The W-subproblem (H fixed) is convex, so it
    never needs transposed data:

      * beta = 2: the MU fixed point depends on X only through the
        sufficient statistics A = H^T X (k x g) and B = H^T H (k x k).
        A comes from one sparse host matmul (CSR-aware, no densify);
        the MU iteration then runs on-device on k-sized arrays only.
      * beta != 2: each MU step needs WH per row, so X must be visited once
        per iteration. When the dense matrix fits the mesh's HBM headroom
        (``stage='auto'``; 1M x 2k fp32 = 8 GB does, even on one v5e chip)
        the CSR blocks are staged to device ONCE and the entire MU loop
        runs as a single XLA dispatch (:func:`_refit_w_staged_jit`) —
        per-iteration cost is an HBM pass, independent of host link speed.
        Above budget it falls back to re-streaming (row_block x genes)
        host blocks per iteration (memory-bounded, link-bound).

    Both paths match :func:`fit_h`'s stopping rule (relative Frobenius
    change < ``h_tol``, ``max_iter`` cap) and its seeded uniform init, so
    sub- and super-threshold consensus runs agree to solver tolerance.
    Returns W (k x genes) as numpy.
    """
    beta = beta_loss_to_float(beta)
    if beta not in (2.0, 1.0, 0.0):
        # same contract as nmf_fit_rowsharded: block_stats implements the
        # three named losses; a generic beta would silently run the IS
        # statistics under the wrong divergence
        raise ValueError(
            f"refit_w_rowsharded supports beta in {{2, 1, 0}}, got {beta}")
    H = np.asarray(H, dtype=np.float32)
    n, k = H.shape
    g = int(X.shape[1])
    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    W = jax.random.uniform(key, (k, g), dtype=jnp.float32)

    if beta == 2.0:
        if sp.issparse(X):
            A = jnp.asarray(np.asarray((X.T @ H).T, dtype=np.float32))
        elif isinstance(X, jax.Array):
            A = jnp.asarray(H).T @ X
        else:
            A = jnp.asarray(H.T @ np.asarray(X, dtype=np.float32))
        B = jnp.asarray(H.T @ H)
        W = _solve_w_from_stats(W, A, B, float(l1_reg_W), float(l2_reg_W),
                                int(max_iter), float(h_tol))
        return np.asarray(W)

    if mesh is None:
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
    axis = mesh.axis_names[0]
    n_dev = int(np.prod(mesh.devices.shape))

    if isinstance(stage, str) and stage != "auto":
        raise ValueError(f"stage must be True, False, or 'auto'; got {stage!r}")
    if stage == "auto":
        budget = (stage_budget_bytes if stage_budget_bytes is not None
                  else _staged_refit_budget_bytes())
        already_resident = isinstance(X, jax.Array)
        stage = already_resident or (n * g * 4 <= budget * n_dev)

    if stage:
        # block rows for the on-device scan: bound the WH intermediate to
        # ~512 MB while keeping blocks MXU-friendly — and never larger than
        # a shard's rows, or the pad-to-block-multiple would multiply the
        # per-iteration work (a 64-row refit must not scan 100k padded rows)
        local_rows = -(-n // n_dev)
        blk = int(min(max(256, (1 << 27) // max(g, 1)), row_block,
                      max(local_rows, 8)))
        if isinstance(X, jax.Array):
            # direct API callers holding X device-resident (the cNMF
            # pipeline always reaches here with a host matrix: its staged
            # consensus matrices are capped below rowshard_threshold)
            pad = (-n) % (blk * n_dev)
            target = NamedSharding(mesh, P(axis, None))
            if (pad == 0 and X.dtype == jnp.float32
                    and X.sharding.is_equivalent_to(target, X.ndim)):
                # already laid out for the scan: pad+device_put here would
                # materialize a full-size second copy of a device-resident
                # matrix (near-HBM-sized inputs OOMed where the budgeted
                # streaming path would not)
                Xd = X
            else:
                Xd = jax.device_put(
                    jnp.pad(X.astype(jnp.float32), ((0, pad), (0, 0))),
                    target)
        else:
            Xd, _ = stream_rows_to_mesh(
                X if sp.issparse(X) else np.asarray(X, np.float32),
                mesh, axis, pad_multiple=n_dev * blk)
        n_pad = int(Xd.shape[0])
        Hd = jax.device_put(
            jnp.pad(jnp.asarray(H), ((0, n_pad - n), (0, 0))),
            NamedSharding(mesh, P(axis, None)))
        Wd = jax.device_put(W, NamedSharding(mesh, P()))
        W = _refit_w_staged_jit(Xd, Hd, Wd, mesh, axis, beta, int(max_iter),
                                jnp.float32(h_tol), int(blk),
                                float(l1_reg_W), float(l2_reg_W))
        return np.asarray(W)

    if sp.issparse(X):
        X = X.tocsr()
    Hd = jnp.asarray(H)

    @functools.partial(jax.jit, static_argnames=("beta",))
    def block_stats(x, h, W, beta):
        WH = jnp.maximum(h @ W, EPS)
        if beta == 1.0:
            return h.T @ (x / WH), jnp.broadcast_to(
                h.sum(axis=0)[:, None], W.shape)
        return h.T @ (x / (WH * WH)), h.T @ (1.0 / WH)

    # above-budget fallback: only one (row_block x genes) dense buffer
    # exists at a time, on host or device — X re-streams host->HBM each MU
    # iteration
    for _ in range(int(max_iter)):
        numer = jnp.zeros((k, g), jnp.float32)
        denom = jnp.zeros((k, g), jnp.float32)
        for start in range(0, n, row_block):
            blk = X[start:start + row_block]
            blk = blk.toarray() if sp.issparse(blk) else np.asarray(blk)
            nb, db = block_stats(jnp.asarray(blk, jnp.float32),
                                 Hd[start:start + row_block], W, beta)
            numer, denom = numer + nb, denom + db
        W_new = _apply_rate(W, numer, denom, float(l1_reg_W),
                            float(l2_reg_W), gamma=mu_gamma(beta))
        rel = float(jnp.linalg.norm(W_new - W)
                    / (jnp.linalg.norm(W) + EPS))
        W = W_new
        if rel < h_tol:
            break
    return np.asarray(W)


def fit_h_rowsharded(X, W, mesh: Mesh, h_tol: float = 0.05,
                     chunk_max_iter: int = 200, l1_reg_H: float = 0.0,
                     l2_reg_H: float = 0.0, beta=2.0, seed: int = 0,
                     n_orig: int | None = None):
    """Row-sharded fixed-W usage refit: zero communication (W replicated,
    every H row depends only on its own X row) — the distributed form of
    ``fit_h`` / the reference's ``fit_H_online`` (cnmf.py:260-388).

    ``X`` may be a host matrix (streamed shard-by-shard, no host dense copy)
    or a device array from :func:`prepare_rowsharded` with its ``n_orig``.
    """
    beta = beta_loss_to_float(beta)
    axis = mesh.axis_names[0]
    if isinstance(X, (jax.Array, EllMatrix)):
        Xd = X
        if n_orig is None:
            n_orig = int(X.shape[0])
    elif isinstance(X, ShardStore):
        # store-backed refit: rows stream from disk (host-bounded), then
        # the identical fixed-W solve runs on the resident sharded array
        n_orig = X.n_rows
        use_ell = False
        if X.format == "csr":
            from ..ops.sparse import _pad_width

            use_ell = resolve_sparse_beta(
                beta, density=X.density,
                width=_pad_width(int(X.max_row_nnz) if n_orig else 1),
                g=X.n_genes)
        if use_ell:
            Xd, _ = stream_ell_to_mesh(X, mesh, axis)
        else:
            Xd, _ = stream_rows_to_mesh(X, mesh, axis)
    else:
        n_orig = int(X.shape[0])
        if sp.issparse(X) and resolve_sparse_beta(
                beta, density=X.nnz / max(X.shape[0] * X.shape[1], 1),
                width=ell_row_width(X), g=X.shape[1]):
            Xd, _ = stream_ell_to_mesh(X, mesh, axis)
        else:
            Xd, _ = stream_rows_to_mesh(X, mesh, axis)
    W = jnp.asarray(np.asarray(W), jnp.float32)
    k = W.shape[0]

    key = jax.random.key(int(seed) & 0x7FFFFFFF)
    H0 = jax.random.uniform(key, (Xd.shape[0], k), dtype=jnp.float32)

    row_sh = NamedSharding(mesh, P(axis, None))
    H0 = jax.device_put(H0, row_sh)
    Wd = jax.device_put(W, NamedSharding(mesh, P()))

    H = _fit_h_rowsharded_jit(Xd, H0, Wd, mesh, axis, beta,
                              int(chunk_max_iter), jnp.float32(h_tol),
                              float(l1_reg_H), float(l2_reg_H))
    return np.asarray(H)[:n_orig]
