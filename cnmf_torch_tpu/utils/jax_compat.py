"""JAX version-compatibility shims (the package supports jax>=0.4.36).

Three surfaces moved or changed defaults across the supported range:

  * ``shard_map`` — top-level ``jax.shard_map`` on modern JAX,
    ``jax.experimental.shard_map.shard_map`` before that (same signature
    for the keyword form this package uses);
  * ``enable_x64`` — ``jax.enable_x64`` context manager on modern JAX,
    ``jax.experimental.enable_x64`` before that;
  * ``jax_threefry_partitionable`` — defaults ON in modern JAX, OFF in
    older releases. The packed K-sweep parity claims (the fit_h/packed-init
    flat-prefix gathers and the kmeans++ ``split(key, K_max-1)[:k-1] ==
    split(key, k-1)`` seeding) hold only for the counter-based
    partitionable threefry: the legacy implementation derives bits from
    the DRAW SIZE (odd-length counter padding, size-dependent split
    halves), so prefixes of differently-sized draws disagree. Importing
    this module therefore defaults the flag ON — unless the user pinned
    ``JAX_THREEFRY_PARTITIONABLE`` themselves — and the packed entry
    points assert it (ADVICE r5 #1).
"""

from __future__ import annotations

import os

import jax

try:
    from jax import shard_map  # noqa: F401  (modern location)
except ImportError:  # pragma: no cover - exercised on older jax only
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map_exp

    @functools.wraps(_shard_map_exp)
    def shard_map(f, **kwargs):
        # the experimental checker has no replication rule for while_loop
        # (every solver here runs one inside shard_map); check_rep is a
        # static verifier only — the psum'd statistics maintain the
        # replication invariant by construction, so disabling it does not
        # change program semantics
        kwargs.setdefault("check_rep", False)
        return _shard_map_exp(f, **kwargs)

try:
    enable_x64 = jax.enable_x64
except AttributeError:  # pragma: no cover - exercised on older jax only
    from jax.experimental import enable_x64  # noqa: F401

__all__ = ["shard_map", "enable_x64", "assert_threefry_partitionable",
           "default_threefry_partitionable", "force_cpu_devices"]


def force_cpu_devices(n: int) -> None:
    """Force an ``n``-device virtual CPU backend across jax versions: the
    XLA host-device-count flag is read at backend init (so it works even
    when jax is already imported, as long as no backend has initialized),
    and the modern ``jax_num_cpu_devices`` config option is applied where
    it exists (older releases raise AttributeError — the flag covers them).
    Used by the CLI pod-simulation hook and the multihost test workers."""
    import re

    # replace (not append-if-missing): simulated-pod workers inherit the
    # parent's XLA_FLAGS, and a stale device count must not win
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   os.environ.get("XLA_FLAGS", ""))
    os.environ["XLA_FLAGS"] = (
        flags.strip() + " --xla_force_host_platform_device_count=%d" % int(n)
    ).strip()
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", int(n))
    except AttributeError:
        pass


def default_threefry_partitionable() -> None:
    """Flip ``jax_threefry_partitionable`` ON where an older JAX defaults
    it OFF. An explicit user env pin wins (the packed entry points will
    then refuse loudly instead of silently diverging)."""
    from .envknobs import env_is_set

    if not env_is_set("JAX_THREEFRY_PARTITIONABLE"):
        try:
            jax.config.update("jax_threefry_partitionable", True)
        except AttributeError:  # future jax that removed the legacy impl
            pass


def assert_threefry_partitionable(where: str) -> None:
    """Raise if the packed bit-parity contract cannot hold. Called at the
    packed entry points (``ops/kmeans.py`` ``k_pad`` path, ``ops/nmf.py``
    ``fit_h`` ``k_pad`` path) so a pinned ``JAX_THREEFRY_PARTITIONABLE=0``
    fails fast instead of silently breaking the per-K RNG-stream parity
    the padded programs are tested against."""
    if not jax.config.jax_threefry_partitionable:
        raise RuntimeError(
            "%s requires jax_threefry_partitionable=True: the padded "
            "program reproduces the per-K RNG streams via threefry prefix "
            "properties that the legacy (size-dependent) threefry breaks. "
            "Unset JAX_THREEFRY_PARTITIONABLE=0, or use the per-K "
            "(unpacked) path." % where)


default_threefry_partitionable()
