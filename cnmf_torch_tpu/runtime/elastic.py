"""Elastic degraded-mesh execution: survive host/device loss mid-run.

On pod-class meshes the probability that *some* participant dies or
stalls during a multi-hour sweep approaches 1, yet until ISSUE 8 every
topology failure was terminal: a dead host became a clean
``HostBarrierTimeout`` abort, a dead launcher worker was only ever
respawned onto its own shard, and a wedged shard could stall a sweep
forever. MPI-FAUN's 2-D processor grid (arxiv 1609.09154) loses exactly
one row/column block per dead processor, and the out-of-memory NMF
design (arxiv 2202.09518) shows the per-pass ``(A, B)`` sufficient
statistics — which ``runtime/checkpoint.py`` already persists — are all
the state needed to rebuild that block on survivors. Elastic
continuation is therefore cheap here in a way it is not for general
training; this module is the recovery-policy half:

  * **Liveness** — :class:`Heartbeat`: each mesh participant (pod
    process, launcher worker) stamps an atomic JSON heartbeat file at
    pass/stage boundaries (throttled to ``CNMF_TPU_HEARTBEAT_S``).
    :meth:`Heartbeat.culprits` turns a generic barrier timeout into a
    NAMED diagnosis — which peer went silent, how long ago, at which
    pass — consumed by ``sync_hosts`` (``parallel/multihost.py``) and
    the launcher's straggler containment.
  * **Loss detection** — :func:`is_device_loss` recognizes both the
    injected :class:`~cnmf_torch_tpu.runtime.faults.HostLossError` and
    the error-string shapes real dead-device/dead-peer failures take
    (XLA "device halted", collective transport resets), so the same
    recovery path handles chaos tests and production preemptions.
  * **Degraded re-mesh** — :func:`plan_degraded_mesh` re-plans a
    smaller mesh over the surviving devices (1-D cells mesh, or the 2-D
    replicates x cells layout via ``mesh_2d``), refusing to shrink
    below ``CNMF_TPU_MIN_DEVICES``. The callers
    (``models/cnmf.py:_factorize_rowsharded`` / ``_factorize_2d``)
    re-stage X through ``parallel/streaming.py`` from the original
    input and resume each in-flight replicate from its pass-statistics
    checkpoint: checkpointed state restores bit-exactly, so a loss at a
    replicate's post-checkpoint boundary completes **bit-identically**
    (the chaos-gate construction, H under its byte budget); a loss
    mid-replicate continues the remaining passes on the shrunk mesh,
    whose collective reduction order differs at float rounding —
    consensus parity is then at solver tolerance.

``CNMF_TPU_ELASTIC=0`` restores the pre-elastic behavior everywhere:
losses abort cleanly (checkpoint-resumable by relaunch) and the
launcher falls back to fixed-shard respawn only.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

__all__ = [
    "ELASTIC_ENV",
    "HEARTBEAT_ENV",
    "STRAGGLER_ENV",
    "MIN_DEVICES_ENV",
    "elastic_enabled",
    "heartbeat_s",
    "straggler_deadline_s",
    "min_surviving_devices",
    "DegradedMeshError",
    "Heartbeat",
    "is_device_loss",
    "resolve_lost_devices",
    "plan_degraded_mesh",
]

ELASTIC_ENV = "CNMF_TPU_ELASTIC"
HEARTBEAT_ENV = "CNMF_TPU_HEARTBEAT_S"
STRAGGLER_ENV = "CNMF_TPU_STRAGGLER_S"
MIN_DEVICES_ENV = "CNMF_TPU_MIN_DEVICES"


def elastic_enabled() -> bool:
    """Elastic degraded-mode execution on/off (``CNMF_TPU_ELASTIC``,
    default on): in-process re-mesh onto surviving devices after a
    host/device loss, and launcher work-stealing adoption of dead or
    straggling workers' shards. ``0`` restores abort-and-relaunch."""
    from ..utils.envknobs import env_flag

    return env_flag(ELASTIC_ENV, True)


def heartbeat_s() -> float:
    """Liveness stamp interval in seconds (``CNMF_TPU_HEARTBEAT_S``,
    default 0 = off). A participant is presumed dead/wedged once its
    heartbeat is older than 3x this interval (:meth:`Heartbeat.culprits`
    default) — generous enough that one slow filesystem write never
    convicts a healthy peer."""
    from ..utils.envknobs import env_float

    return env_float(HEARTBEAT_ENV, 0.0, lo=0.0)


def straggler_deadline_s() -> float:
    """Launcher straggler grace (``CNMF_TPU_STRAGGLER_S``, default 0 =
    off; part of the elastic layer, inert under ``CNMF_TPU_ELASTIC=0``
    and REQUIRING ``CNMF_TPU_HEARTBEAT_S`` — conviction is
    evidence-based): the longest clean finisher's wall time is the
    fleet's observed shard runtime; a worker whose own run (from its own
    spawn, so an adoption redoing a full shard gets a full allowance)
    exceeds that baseline by this many seconds AND whose heartbeat is
    stale (older than ``max(grace, 3 x heartbeat interval)``) is killed
    and its shard adopted by the fleet: quarantine-style containment for
    a shard that would otherwise wedge the sweep, while a worker
    stamping liveness on schedule is never convicted."""
    from ..utils.envknobs import env_float

    return env_float(STRAGGLER_ENV, 0.0, lo=0.0)


def min_surviving_devices() -> int:
    """Degraded-mesh floor (``CNMF_TPU_MIN_DEVICES``, default 1):
    elastic continuation refuses to shrink below this many surviving
    devices and re-raises the loss instead (abort, relaunch on a
    repaired topology, resume from checkpoints)."""
    from ..utils.envknobs import env_int

    return env_int(MIN_DEVICES_ENV, 1, lo=1)


class DegradedMeshError(RuntimeError):
    """Too few devices survived a topology loss for degraded
    continuation (below ``CNMF_TPU_MIN_DEVICES``) — the loss is
    re-raised and the run aborts cleanly (checkpoint-resumable)."""


# ---------------------------------------------------------------------------
# liveness: heartbeat files
# ---------------------------------------------------------------------------

class Heartbeat:
    """One participant's liveness stamp, as an atomic JSON file.

    The filesystem is already this pipeline's durable dataplane
    (artifacts, ledgers, checkpoints), so it carries liveness too: every
    participant — pod process, launcher worker — owns one file
    (``<dir>/<prefix>.heartbeat.<index>.json``) it rewrites atomically
    with ``{index, pid, ts, phase, cursor}``. Peers (the coordinator at
    a barrier timeout, the launcher at a straggler deadline) read the
    whole set and name exactly who went silent and where — the
    difference between "barrier timed out" and "process 3 last beat 94 s
    ago at pass 41".

    Stamps are throttled to ``interval_s`` (default
    ``CNMF_TPU_HEARTBEAT_S``) so per-pass hooks cost one monotonic-clock
    read in the steady state; a forced beat (``force=True``) bypasses
    the throttle at phase transitions. ``interval_s <= 0`` disables the
    writer entirely (every call is a no-op) — the pre-liveness build.
    """

    def __init__(self, directory, prefix: str, index: int,
                 interval_s: float | None = None, events=None):
        self.directory = os.fspath(directory)
        self.prefix = str(prefix)
        self.index = int(index)
        self.interval_s = (heartbeat_s() if interval_s is None
                           else float(interval_s))
        self.events = events
        self._last = 0.0

    @property
    def enabled(self) -> bool:
        return self.interval_s > 0

    def path_for(self, index: int) -> str:
        return os.path.join(self.directory,
                            f"{self.prefix}.heartbeat.{int(index)}.json")

    @property
    def path(self) -> str:
        return self.path_for(self.index)

    def beat(self, phase: str | None = None, cursor=None,
             force: bool = False) -> bool:
        """Stamp liveness (throttled); returns True when a file was
        written. Never raises — a full disk must not take the solve
        down; liveness then degrades to "no heartbeat", which reads as
        unknown, not dead-certain."""
        if not self.enabled:
            return False
        now = time.monotonic()
        if not force and now - self._last < self.interval_s:
            return False
        self._last = now
        payload = {"index": self.index, "pid": os.getpid(),
                   "ts": time.time()}
        if phase is not None:
            payload["phase"] = str(phase)
        if cursor is not None:
            payload["cursor"] = int(cursor)
        try:
            from ..utils.anndata_lite import atomic_artifact

            with atomic_artifact(self.path) as tmp:
                with open(tmp, "w") as f:
                    json.dump(payload, f)
            return True
        except Exception:
            return False

    @staticmethod
    def read(path) -> dict | None:
        """One participant's last stamp, or ``None`` (missing/torn —
        atomic writes make torn unlikely, but a reader must never crash
        on a file it does not own)."""
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def probe_peers(self, n: int) -> dict[int, float | None]:
        """``{index: age_seconds | None}`` for every participant index in
        ``range(n)`` — ``None`` when the peer never stamped (missing
        file)."""
        now = time.time()
        out: dict[int, float | None] = {}
        for i in range(int(n)):
            rec = self.read(self.path_for(i))
            out[i] = None if rec is None else max(0.0, now - float(rec["ts"]))
        return out

    def culprits(self, n: int, stale_after_s: float | None = None,
                 include_self: bool = False) -> list[dict]:
        """Peers presumed dead/wedged: heartbeat missing or older than
        ``stale_after_s`` (default ``3 x interval_s``). Each culprit dict
        carries ``index``, ``age_s`` (None = never stamped), and the last
        recorded ``phase``/``cursor`` for the diagnosis message."""
        if stale_after_s is None:
            stale_after_s = 3.0 * max(self.interval_s, 1e-9)
        out = []
        for i, age in self.probe_peers(n).items():
            if not include_self and i == self.index:
                continue
            if age is not None and age <= stale_after_s:
                continue
            rec = self.read(self.path_for(i)) or {}
            out.append({"index": i,
                        "age_s": None if age is None else round(age, 1),
                        "phase": rec.get("phase"),
                        "cursor": rec.get("cursor")})
        return out

    @staticmethod
    def describe(culprits: list[dict]) -> str:
        """Human-readable culprit list for error messages / warnings."""
        if not culprits:
            return "no stale heartbeats (culprit unknown)"
        parts = []
        for c in culprits:
            where = "" if c.get("phase") is None else (
                " at %s%s" % (c["phase"],
                              "" if c.get("cursor") is None
                              else " (cursor %d)" % c["cursor"]))
            when = ("never stamped" if c.get("age_s") is None
                    else "last beat %.1fs ago" % c["age_s"])
            parts.append("participant %d (%s%s)" % (c["index"], when, where))
        return ", ".join(parts)


# ---------------------------------------------------------------------------
# loss detection + degraded-mesh planning
# ---------------------------------------------------------------------------

# error-string shapes real topology failures take: XLA dead-device
# aborts, collective-transport resets (gloo/NCCL-style), distributed
# runtime peer failures. Conservative on purpose — a numerics bug or an
# ordinary filesystem/socket error must never be "recovered" by
# silently shrinking the mesh, so only RuntimeError (the class XLA and
# the distributed runtime surface) is eligible, never OSError: an EBUSY
# from a checkpoint write ("Device or resource busy") or a stray
# connection reset from unrelated IO is a retry/abort case, not a
# topology loss.
_LOSS_MARKERS = (
    "device halted",
    "data_loss",
    "socket closed",
    "connection reset",
    "peer closed",
    "transport closed",
    "remote peer",
    "heartbeat timeout",
)


def is_device_loss(exc: BaseException) -> bool:
    """Whether an exception signals a topology (host/device) loss that
    degraded continuation can recover from — the injected
    :class:`~cnmf_torch_tpu.runtime.faults.HostLossError`, or a
    ``RuntimeError`` (the class XLA/distributed-runtime failures
    surface as) whose message matches the known dead-device/dead-peer
    shapes. Deliberately narrow: plain ``OSError`` never qualifies."""
    from .faults import HostLossError

    if isinstance(exc, HostLossError):
        return True
    if not isinstance(exc, RuntimeError):
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in _LOSS_MARKERS)


def resolve_lost_devices(exc: BaseException, mesh) -> list:
    """The devices presumed lost, as device objects of ``mesh``. An
    injected :class:`HostLossError` names ids (or a trailing ``count``);
    a real loss cannot be probed reliably from the surviving process
    (the runtime is wedged, not introspectable), so it also falls back
    to the trailing-device convention — the caller's re-staging then
    validates the survivors by actually using them."""
    from .faults import HostLossError

    devices = list(mesh.devices.flat)
    if isinstance(exc, HostLossError) and exc.lost:
        by_id = {int(d.id): d for d in devices}
        return [by_id[i] for i in exc.lost if i in by_id]
    count = exc.count if isinstance(exc, HostLossError) else 1
    count = max(1, min(int(count), len(devices) - 1)) \
        if len(devices) > 1 else len(devices)
    return devices[-count:]


def plan_degraded_mesh(mesh, lost_devices):
    """Re-plan ``mesh`` over its surviving devices after a loss.

    1-D meshes keep their axis name with every survivor on it; the 2-D
    (replicates x cells) layout re-plans through
    ``parallel.multihost.mesh_2d`` (``_balanced_rc`` factorization), the
    same planner that built the original mesh. Raises
    :class:`DegradedMeshError` when fewer than ``CNMF_TPU_MIN_DEVICES``
    devices survive — a mesh that small cannot meaningfully continue,
    so the loss propagates as a clean abort instead."""
    lost_ids = {int(d.id) for d in lost_devices}
    surviving = [d for d in mesh.devices.flat if int(d.id) not in lost_ids]
    floor = min_surviving_devices()
    if len(surviving) < floor:
        raise DegradedMeshError(
            "host/device loss left %d surviving device(s), below the "
            "degraded-mesh floor %s=%d — aborting instead of continuing "
            "on a mesh that small (relaunch on a repaired topology to "
            "resume from checkpoints)"
            % (len(surviving), MIN_DEVICES_ENV, floor))
    from jax.sharding import Mesh

    axis_names = tuple(mesh.axis_names)
    if axis_names == ("replicates", "cells"):
        from ..parallel.multihost import mesh_2d

        return mesh_2d(devices=surviving)
    if axis_names == ("cells", "genes"):
        # the true 2-D grid (ISSUE 13) re-plans through its own
        # DCN-aware planner, like the original mesh was built
        from ..parallel.grid2d import mesh_grid2d

        return mesh_grid2d(devices=surviving)
    if len(axis_names) != 1:
        raise DegradedMeshError(
            f"cannot re-plan a degraded mesh over axes {axis_names!r}")
    return Mesh(np.asarray(surviving), axis_names)
