"""Randomized sketching kernels (ISSUE 11) — the jax half of the sketch
layer whose knob/recipe resolution lives in ``ops/recipe.py``.

Two consumers:

  * **Sketched KL-NMF** (the ``sketch`` solver recipe): the W-update row
    subsample itself is traced inline in ``ops/nmf.py`` /
    ``parallel/rowshard.py`` (a two-line fold_in + randint per update);
    this module only owns the shared resolution/doc surface.
  * **Sketched consensus** (this module): the replicate-spectra
    clustering stage is O((K·n_iter)²·g_hv) in distance computations —
    the pairwise-distance/KNN-density outlier filter, k-means, and
    silhouette all reduce over the full g_hv-wide spectra. A seeded
    Gaussian random projection to ``dim`` (~256) columns preserves all
    pairwise euclidean distances to Johnson–Lindenstrauss tolerance
    (entries N(0, 1/dim), so E‖Px‖² = ‖x‖²), after which those stages
    cost O(R²·dim). Cluster MEDIANS are always recovered from the
    original full-width spectra within the final clusters — only the
    geometry that picks the clusters is compressed, never the artifact.

Resolution (``resolve_consensus_sketch``): ``CNMF_TPU_SKETCH`` ``0`` off
/ ``1`` forced / ``auto`` engages when the replicate stack is tall
enough that the projection pays for itself (R >= 4x dim) and the
spectra are wider than the target dim. The decision is recorded in the
``consensus_path`` dispatch telemetry event (``models/cnmf.py``).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .recipe import SKETCH_DIM_ENV, SKETCH_ENV

__all__ = ["ConsensusSketch", "resolve_consensus_sketch", "project_rows",
           "DEFAULT_CONSENSUS_DIM", "CONSENSUS_AUTO_MIN_RATIO"]

# default JL projection width for consensus spectra: 256 dims keeps the
# distance distortion well under the local-density threshold margins at
# fixture and production replicate counts (ROADMAP item 4's "~256")
DEFAULT_CONSENSUS_DIM = 256

# the auto lane engages only when R >= ratio * dim: below that the R x R
# distance pass is cheap enough that the projection matmul dominates
CONSENSUS_AUTO_MIN_RATIO = 4


@dataclass(frozen=True)
class ConsensusSketch:
    """One resolved consensus-sketch decision.

    ``engaged``: project before the distance/density/k-means stage.
    ``dim``: projection width (meaningful when engaged). ``source``:
    who decided (``off`` / ``env`` / ``auto``) for the dispatch event.
    """

    engaged: bool
    dim: int
    source: str

    def as_context(self) -> dict:
        return {"sketch": bool(self.engaged),
                "sketch_dim": int(self.dim) if self.engaged else 0,
                "sketch_source": self.source}


def resolve_consensus_sketch(n_rows: int, n_cols: int) -> ConsensusSketch:
    """Resolve the consensus-stage sketch from the shared knobs.

    ``n_rows``: stacked replicate-spectra count (K·n_iter). ``n_cols``:
    spectra width (HVG count). Never engages when the projection would
    not shrink the distance reductions (``dim >= n_cols``)."""
    from ..utils.envknobs import env_int, env_str

    raw = env_str(SKETCH_ENV, "0").strip().lower()
    if raw in ("", "0", "off", "false", "no"):
        return ConsensusSketch(False, 0, "off")
    # 'auto'/'' are the dim knob's unset sentinel (its documented
    # default cell), mirroring resolve_recipe's parse
    raw_dim = env_str(SKETCH_DIM_ENV, "auto").strip().lower()
    dim = (0 if raw_dim in ("", "auto")
           else (env_int(SKETCH_DIM_ENV, 0, lo=0) or 0))
    dim = int(dim or DEFAULT_CONSENSUS_DIM)
    if dim >= n_cols and DEFAULT_CONSENSUS_DIM < n_cols:
        # the knob is shared with the solver lane's sampled-ROW count: a
        # solver-sized pin (e.g. n/8 = 2048 against 2000-wide spectra)
        # must not silently disable a forced consensus sketch — fall
        # back to the JL default width, which still projects down
        dim = DEFAULT_CONSENSUS_DIM
    if raw == "auto":
        engaged = (n_rows >= CONSENSUS_AUTO_MIN_RATIO * dim
                   and n_cols > dim)
        return ConsensusSketch(engaged, dim if engaged else 0, "auto")
    if raw in ("1", "on", "true", "yes", "force"):
        if n_cols <= dim:
            # projecting UP never pays; forced mode degrades cleanly to
            # the exact stage instead of inflating the distance width
            return ConsensusSketch(False, 0, "env")
        return ConsensusSketch(True, dim, "env")
    raise ValueError(f"{SKETCH_ENV}={raw!r}: expected 0, 1, or auto")


@functools.partial(jax.jit, static_argnames=("dim", "seed"))
def _project_rows_jit(A, dim: int, seed: int):
    g = A.shape[1]
    P = jax.random.normal(jax.random.key(seed), (g, dim),
                          jnp.float32) * (1.0 / np.sqrt(dim))
    return jnp.matmul(A, P, precision=jax.lax.Precision.HIGHEST)


def project_rows(A, dim: int, seed: int = 0) -> np.ndarray:
    """Seeded Gaussian JL projection of the rows of ``A`` to ``dim``
    columns (entries N(0, 1/dim): squared distances are preserved in
    expectation, concentrated to ~(1 ± sqrt(8 ln R / dim))). The fixed
    default seed keeps repeated consensus runs deterministic, mirroring
    k-means' fixed ``random_state=1``. Returns a host f32 array."""
    A = jnp.asarray(np.asarray(A), jnp.float32)
    if dim >= A.shape[1]:
        return np.asarray(A)
    return np.asarray(_project_rows_jit(A, int(dim), int(seed)))
