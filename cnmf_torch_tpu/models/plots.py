"""Host-side diagnostic figures (matplotlib; plot-only, never a TPU kernel).

Equivalents of the reference's consensus clustergram
(``/root/reference/src/cnmf/cnmf.py:1160-1253``) and the twin-axis
stability/error k-selection plot (``cnmf.py:1311-1331``). The within-cluster
hierarchical leaf ordering uses scipy on host — it is O(n_iter^2) display
work (SURVEY.md §2.3 flags it as acceptably host-side).
"""

from __future__ import annotations

import numpy as np

from ..utils.anndata_lite import atomic_artifact

__all__ = ["clustergram", "k_selection_figure", "cluster_ordering"]


def _save_fig_atomic(fig, out_png: str, dpi: int):
    """Figures are pipeline artifacts too (`--skip-completed-runs` probes
    the run directory): land them with the same temp+rename dance as the
    npz/h5ad writers. The temp name has no extension, so the format comes
    from the target's suffix explicitly."""
    import os

    ext = os.path.splitext(os.path.basename(out_png))[1]
    with atomic_artifact(out_png) as tmp:
        fig.savefig(tmp, dpi=dpi, format=ext[1:] if ext else "png")


def cluster_ordering(topics_dist: np.ndarray, cluster_labels) -> list[int]:
    """Row order for the clustergram: clusters in label order, rows within a
    cluster ordered by average-linkage hierarchical leaves
    (``cnmf.py:1168-1184``)."""
    from scipy.cluster.hierarchy import leaves_list, linkage
    from scipy.spatial.distance import squareform

    labels = np.asarray(cluster_labels)
    order: list[int] = []
    for cl in sorted(set(labels)):
        members = np.where(labels == cl)[0]
        if len(members) > 1:
            cl_dist = squareform(topics_dist[np.ix_(members, members)],
                                 checks=False)
            cl_dist[cl_dist < 0] = 0.0
            leaves = leaves_list(linkage(cl_dist, "average"))
            order += list(members[leaves])
        else:
            order += list(members)
    return order


def clustergram(topics_dist, cluster_labels, local_density, density_filter,
                density_threshold, out_png: str, close_fig: bool = False):
    """Distance-matrix clustergram with cluster color strips and the local
    density histogram + filter threshold annotation."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    from matplotlib import gridspec

    labels = np.asarray(cluster_labels)
    order = cluster_ordering(np.asarray(topics_dist), labels)
    D = np.asarray(topics_dist)[np.ix_(order, order)]

    width_ratios = [0.5, 9, 0.5, 4, 1]
    height_ratios = [0.5, 9]
    fig = plt.figure(figsize=(sum(width_ratios), sum(height_ratios)))
    gs = gridspec.GridSpec(len(height_ratios), len(width_ratios), fig,
                           0.01, 0.01, 0.98, 0.98,
                           height_ratios=height_ratios,
                           width_ratios=width_ratios, wspace=0, hspace=0)

    dist_ax = fig.add_subplot(gs[1, 1], xticks=[], yticks=[], frameon=True)
    dist_im = dist_ax.imshow(D, interpolation="none", cmap="viridis",
                             aspect="auto", rasterized=True)

    left_ax = fig.add_subplot(gs[1, 0], xticks=[], yticks=[], frameon=True)
    left_ax.imshow(labels[order].reshape(-1, 1), interpolation="none",
                   cmap="Spectral", aspect="auto", rasterized=True)
    top_ax = fig.add_subplot(gs[0, 1], xticks=[], yticks=[], frameon=True)
    top_ax.imshow(labels[order].reshape(1, -1), interpolation="none",
                  cmap="Spectral", aspect="auto", rasterized=True)

    hist_gs = gridspec.GridSpecFromSubplotSpec(3, 1, subplot_spec=gs[1, 3],
                                               wspace=0, hspace=0)
    hist_ax = fig.add_subplot(hist_gs[0, 0], frameon=True,
                              title="Local density histogram")
    if local_density is not None:
        hist_ax.hist(np.asarray(local_density).ravel(),
                     bins=np.linspace(0, 1, 50))
        hist_ax.yaxis.tick_right()
        xlim = hist_ax.get_xlim()
        ylim = hist_ax.get_ylim()
        if density_threshold < xlim[1]:
            hist_ax.axvline(density_threshold, linestyle="--", color="k")
            hist_ax.text(density_threshold + 0.02, ylim[1] * 0.95,
                         "filtering\nthreshold\n\n", va="top")
        hist_ax.set_xlim(xlim)
        if density_filter is not None:
            df = np.asarray(density_filter)
            hist_ax.set_xlabel(
                "Mean distance to k nearest neighbors\n\n"
                "%d/%d (%.0f%%) spectra above threshold\nwere removed prior "
                "to clustering" % ((~df).sum(), len(df), 100 * (~df).mean()))

    cbar_gs = gridspec.GridSpecFromSubplotSpec(8, 1,
                                               subplot_spec=hist_gs[1, 0],
                                               wspace=0, hspace=0)
    cbar_ax = fig.add_subplot(cbar_gs[4, 0], frameon=True,
                              title="Euclidean Distance")
    fig.colorbar(dist_im, cax=cbar_ax,
                 ticks=np.linspace(D.min(), D.max(), 3),
                 orientation="horizontal")

    _save_fig_atomic(fig, out_png, dpi=250)
    if close_fig:
        plt.close(fig)
    return fig


def k_selection_figure(stats, out_png: str, close_fig: bool = False):
    """Twin-axis stability (silhouette, left) / error (right) vs K."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig = plt.figure(figsize=(6, 4))
    ax1 = fig.add_subplot(111)
    ax2 = ax1.twinx()
    ax1.plot(stats.k, stats.silhouette, "o-", color="b")
    ax1.set_ylabel("Stability", color="b", fontsize=15)
    for tl in ax1.get_yticklabels():
        tl.set_color("b")
    ax2.plot(stats.k, stats.prediction_error, "o-", color="r")
    ax2.set_ylabel("Error", color="r", fontsize=15)
    for tl in ax2.get_yticklabels():
        tl.set_color("r")
    ax1.set_xlabel("Number of Components", fontsize=15)
    ax1.grid("on")
    plt.tight_layout()
    _save_fig_atomic(fig, out_png, dpi=250)
    if close_fig:
        plt.close(fig)
    return fig
