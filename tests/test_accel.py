"""Iteration-count acceleration layer (ISSUE 9): solver recipes, the
Diagonalized-Newton KL safeguards, the accelerated-MU repeat schedule,
recipe dispatch/telemetry plumbing, and the checkpoint identity pin."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.ops.nmf import (
    _dna_h_step,
    _dna_w_step,
    beta_divergence,
    nmf_fit_batch,
    nmf_fit_batch_hals,
    random_init,
    run_nmf,
)
from cnmf_torch_tpu.ops.recipe import (
    SolverRecipe,
    auto_inner_repeats,
    resolve_recipe,
)
from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put, ell_w_table


def _counts(n, g, k, seed, scale=6.0):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return X


def _sparse_counts(n=240, g=100, k=4, seed=11, scale=0.8):
    X = _counts(n, g, k, seed, scale=scale)
    return sp.csr_matrix(X)


# ---------------------------------------------------------------------------
# recipe resolution
# ---------------------------------------------------------------------------

class TestRecipeResolution:
    def test_default_is_auto_zero_is_identity_plain_mu(self, monkeypatch):
        # shipped default since the execution planner (ISSUE 17): unset
        # == auto, so batch KL engages dna; '0' is the identity hatch
        monkeypatch.delenv("CNMF_TPU_ACCEL", raising=False)
        assert resolve_recipe(1.0, "batch").label == "dna"
        assert resolve_recipe(1.0, "online").label == "mu"
        monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
        rec = resolve_recipe(1.0, "batch")
        assert rec.algo == "mu" and rec.is_identity
        assert rec.label == "mu"

    def test_auto_lane_picks_dna_for_kl_amu_for_is(self):
        assert resolve_recipe(1.0, "batch", accel="auto").label == "dna"
        amu = resolve_recipe(0.0, "batch", accel="auto")
        assert amu.algo == "amu" and amu.inner_repeats >= 2
        # auto stays off outside the batch lane (online/rowshard pass
        # loops already repeat the cheap H solve per W update)
        assert resolve_recipe(1.0, "online", accel="auto").is_identity
        # forcing engages the dna lane wherever _chunk_h_solve runs
        assert resolve_recipe(1.0, "online", accel="1").label == "dna"
        assert resolve_recipe(1.0, "rowshard", accel="1").label == "dna"

    def test_env_knobs_pin_fields(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_ACCEL", "1")
        monkeypatch.setenv("CNMF_TPU_KL_NEWTON", "0")
        monkeypatch.setenv("CNMF_TPU_INNER_REPEATS", "5")
        rec = resolve_recipe(1.0, "batch")
        assert rec.algo == "amu" and rec.inner_repeats == 5
        monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
        assert resolve_recipe(1.0, "batch").is_identity
        monkeypatch.setenv("CNMF_TPU_ACCEL", "bogus")
        with pytest.raises(ValueError, match="CNMF_TPU_ACCEL"):
            resolve_recipe(1.0, "batch")

    def test_halsvar_maps_to_hals_recipe(self):
        rec = resolve_recipe(2.0, "batch", algo="halsvar")
        assert rec.algo == "hals" and rec.is_identity

    def test_signature_distinguishes_recipes(self):
        sigs = {SolverRecipe().signature(),
                SolverRecipe("dna", 1, True, "env").signature(),
                SolverRecipe("amu", 3, False, "env").signature(),
                SolverRecipe("amu", 4, False, "env").signature(),
                SolverRecipe("hals").signature()}
        assert len(sigs) == 5

    def test_auto_inner_repeats_cost_ratio(self):
        # dense beta!=2: repeat == full WH pass -> the mild schedule
        assert auto_inner_repeats(1.0, 1000, 500, 8) == 2
        # ELL: repeats re-use the slab table -> one more
        assert auto_inner_repeats(1.0, 1000, 500, 8, ell_width=64) == 3
        # beta=2: repeats are k-sized against hoisted stats -> capped max
        assert auto_inner_repeats(2.0, 1000, 500, 8) == 8
        # width-free resolution (run_nmf resolves before staging) must
        # land the same ELL schedule — the width cancels in the ratio
        assert auto_inner_repeats(1.0, ell=True) == 3
        assert auto_inner_repeats(1.0, 1000, 500, 8, ell=True) == 3
        assert resolve_recipe(0.0, "batch", accel="1", kl_newton=False,
                              ell=True).inner_repeats == 3
        assert SolverRecipe("amu", auto_inner_repeats(1.0), False, "auto")


# ---------------------------------------------------------------------------
# satellite (a): DNA + fallback composite is monotone per outer step
# ---------------------------------------------------------------------------

class TestDnaMonotone:
    @pytest.mark.parametrize("seed", [0, 7, 23])
    def test_dense_composite_monotone(self, seed):
        X = jnp.asarray(_counts(120, 60, 4, seed))
        H, W = random_init(jax.random.key(seed), 120, 60, 4, jnp.mean(X))
        step_h = jax.jit(lambda x, h, w: _dna_h_step(x, h, w, 0.0, 0.0))
        step_w = jax.jit(lambda x, h, w: _dna_w_step(x, h, w, 0.0, 0.0))
        err = float(beta_divergence(X, H, W, beta=1.0))
        for _ in range(25):
            H, _ = step_h(X, H, W)
            W, _ = step_w(X, H, W)
            err_new = float(beta_divergence(X, H, W, beta=1.0))
            # strict per-outer-step monotonicity up to f32 evaluation noise
            assert err_new <= err * (1 + 1e-6) + 1e-3, (err, err_new)
            err = err_new

    def test_ell_h_step_monotone_and_matches_dense(self):
        Xs = _sparse_counts()
        Xd = jnp.asarray(Xs.toarray())
        ell = ell_device_put(csr_to_ell(Xs))
        H, W = random_init(jax.random.key(5), Xs.shape[0], Xs.shape[1], 4,
                           jnp.asarray(np.float32(Xs.mean())))
        table = ell_w_table(W, ell.cols)
        He, fbe = _dna_h_step(ell, H, W, 0.0, 0.0, w_table=table)
        Hd, fbd = _dna_h_step(Xd, H, W, 0.0, 0.0)
        # same math, nonzero-only evaluation: candidates agree to f32
        np.testing.assert_allclose(np.asarray(He), np.asarray(Hd),
                                   rtol=2e-4, atol=2e-4)
        err0 = float(beta_divergence(ell, H, W, beta=1.0))
        err1 = float(beta_divergence(ell, He, W, beta=1.0))
        assert err1 <= err0 * (1 + 1e-6) + 1e-3

    def test_solver_trace_monotone_and_fallback_reported(self):
        X = jnp.asarray(_counts(150, 70, 4, 2))
        H0, W0 = random_init(jax.random.key(9), 150, 70, 4, jnp.mean(X))
        _, _, err, tm = nmf_fit_batch(X, H0, W0, beta=1.0, tol=0.0,
                                      max_iter=80, telemetry=True,
                                      kl_newton=True)
        tr = np.asarray(tm.trace)
        tr = tr[~np.isnan(tr)]
        assert (np.diff(tr) <= np.abs(tr[:-1]) * 1e-6 + 1e-3).all(), tr
        assert 0.0 <= float(tm.dna_fallback) <= 1.0
        assert int(tm.inner_iters) == int(tm.iters)

    def test_dna_converges_in_fewer_iterations_than_mu(self):
        """The point of the recipe: outer iterations to a fixed KL
        tolerance drop by >=1.5x vs plain MU (the bench measures 4-6x at
        production shapes; this pins the property at test scale)."""
        X = jnp.asarray(_counts(200, 90, 5, 1))
        H0, W0 = random_init(jax.random.key(4), 200, 90, 5, jnp.mean(X))
        cap = 300

        def to_tol(kl_newton):
            _, _, err, tm = nmf_fit_batch(X, H0, W0, beta=1.0, tol=0.0,
                                          max_iter=cap, telemetry=True,
                                          kl_newton=kl_newton)
            return np.asarray(tm.trace), float(err)

        tr_mu, err_mu = to_tol(False)
        tr_dna, err_dna = to_tol(True)
        target = min(err_mu, err_dna) * 1.001

        def first_hit(tr):
            tr = tr[~np.isnan(tr)]
            hit = np.nonzero(tr <= target)[0]
            return (hit[0] + 1) if len(hit) else len(tr)

        assert first_hit(tr_mu) >= 1.5 * first_hit(tr_dna), (
            first_hit(tr_mu), first_hit(tr_dna))


# ---------------------------------------------------------------------------
# satellite (b): accelerated-MU reaches a tighter objective in equal
# outer iterations on the sparse fixture
# ---------------------------------------------------------------------------

def test_amu_tighter_objective_equal_outer_iterations():
    Xs = _sparse_counts(n=300, g=140, k=5, seed=9, scale=0.7)
    ell = ell_device_put(csr_to_ell(Xs))
    H0, W0 = random_init(jax.random.key(3), Xs.shape[0], Xs.shape[1], 5,
                         jnp.asarray(np.float32(Xs.mean())))

    def err_at(rho, cap=40):
        _, _, err, tm = nmf_fit_batch(ell, H0, W0, beta=1.0, tol=0.0,
                                      max_iter=cap, telemetry=True,
                                      inner_repeats=rho)
        # the identity program (rho=1) carries no inner accumulator
        inner = tm.inner_iters if tm.inner_iters is not None else tm.iters
        return float(err), int(inner)

    err_mu, inner_mu = err_at(1)
    err_amu, inner_amu = err_at(3)
    assert inner_mu == 40 and inner_amu > 40
    assert err_amu <= err_mu, (err_amu, err_mu)


# ---------------------------------------------------------------------------
# satellite (c): CNMF_TPU_ACCEL=0 programs are byte-identical
# ---------------------------------------------------------------------------

class TestAccelOffByteIdentical:
    def test_resolved_identity_recipe_hits_the_same_program_cache(
            self, monkeypatch):
        """The telemetry-flag guarantee style: with the knob off, the
        sweep dispatches the EXACT lru_cache entry a build without the
        recipe layer would (identity statics == the pre-layer defaults),
        so the compiled executable is the same object, byte for byte."""
        from cnmf_torch_tpu.parallel.replicates import (_recipe_statics,
                                                        _sweep_program)

        monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
        rec = resolve_recipe(1.0, "batch")
        assert rec.is_identity
        args = (100, 40, 4, 2, "random", "batch", 1.0, 1e-4, 1e-3, 100,
                50, 20, 60, 0.0, 0.0, 0.0, 0.0, None, False)
        prog_default = _sweep_program(*args)
        prog_recipe = _sweep_program(*args, **_recipe_statics(rec))
        assert prog_default is prog_recipe

    def test_identity_lowering_matches_defaults(self):
        """The jitted solver's lowered HLO with the identity recipe
        explicitly passed equals the no-argument default lowering — no
        inner while_loop, no Newton lanes, nothing."""
        X = jnp.asarray(_counts(60, 30, 3, 0))
        H0, W0 = random_init(jax.random.key(0), 60, 30, 3, jnp.mean(X))
        base = nmf_fit_batch.lower(X, H0, W0, beta=1.0,
                                   max_iter=20).as_text()
        ident = nmf_fit_batch.lower(X, H0, W0, beta=1.0, max_iter=20,
                                    inner_repeats=1,
                                    kl_newton=False).as_text()
        assert base == ident
        # with telemetry on, the identity program must still carry NO
        # inner/fallback accumulators (the pre-recipe-layer carry shape)
        _, _, _, tm = nmf_fit_batch(X, H0, W0, beta=1.0, max_iter=5,
                                    telemetry=True)
        assert tm.inner_iters is None and tm.dna_fallback is None


# ---------------------------------------------------------------------------
# satellite (d): checkpoint resume across a recipe change restarts
# ---------------------------------------------------------------------------

def test_checkpoint_resume_across_recipe_change_restarts(tmp_path):
    from cnmf_torch_tpu.runtime.checkpoint import PassCheckpointer

    path = tmp_path / "ckpt.k4.it0.npz"
    k, g, n = 4, 30, 50

    def meta(recipe_sig):
        return {"k": k, "iter": 0, "seed": 1, "attempt": 0,
                "digest": "deadbeef", "beta": 1.0,
                "params": f"tol=1e-4,{recipe_sig}"}

    mu_sig = SolverRecipe().signature()
    dna_sig = SolverRecipe("dna", 1, True, "env").signature()
    writer = PassCheckpointer(path, 1, meta=meta(mu_sig))
    writer.save(pass_idx=3, err_prev=2.0, err=1.5,
                trace=np.full(8, np.nan, np.float32),
                W=np.ones((k, g), np.float32),
                A=np.zeros((k, g), np.float32),
                B=np.zeros((k, k), np.float32),
                H=np.ones((n, k), np.float32))

    # same recipe: resume trusts the file
    same = PassCheckpointer(path, 1, meta=meta(mu_sig), resume=True)
    state = same.load(n_rows=n, n_genes=g)
    assert state is not None and int(state["pass_idx"]) == 3

    # recipe change (mu -> dna): identity mismatch, replicate restarts
    writer.save(pass_idx=3, err_prev=2.0, err=1.5,
                trace=np.full(8, np.nan, np.float32),
                W=np.ones((k, g), np.float32),
                A=np.zeros((k, g), np.float32),
                B=np.zeros((k, k), np.float32))
    with pytest.warns(RuntimeWarning, match="failed validation"):
        changed = PassCheckpointer(path, 1, meta=meta(dna_sig), resume=True)
        assert changed.load(n_rows=n, n_genes=g) is None
    assert not path.exists()  # discarded, not silently spliced


# ---------------------------------------------------------------------------
# HALS recipe wiring (satellite: dispatch site + sklearn parity)
# ---------------------------------------------------------------------------

class TestHalsRecipe:
    def test_hals_batch_matches_sklearn_cd(self):
        """sklearn's 'cd' solver IS coordinate descent on the Frobenius
        objective — the same family as HALS. From the same init both
        must land at near-identical objectives."""
        sklearn = pytest.importorskip("sklearn.decomposition")
        X = _counts(150, 60, 4, 13, scale=20.0)
        Xj = jnp.asarray(X)
        H0, W0 = random_init(jax.random.key(2), 150, 60, 4, jnp.mean(Xj))
        H, W, err = nmf_fit_batch_hals(Xj, H0, W0, tol=1e-6, max_iter=400)
        model = sklearn.NMF(n_components=4, init="custom", solver="cd",
                            tol=1e-6, max_iter=400)
        # np.array copies: sklearn's cd solver writes in place, and
        # buffers exported from jax arrays are read-only
        Wsk = model.fit_transform(X, W=np.array(H0, X.dtype),
                                  H=np.array(W0, X.dtype))
        err_sk = 0.5 * np.linalg.norm(X - Wsk @ model.components_) ** 2
        assert float(err) <= err_sk * 1.02, (float(err), err_sk)

    def test_hals_recipe_dispatches_through_sweeps(self, monkeypatch):
        from cnmf_torch_tpu.parallel import replicate_sweep

        X = _counts(120, 50, 4, 3, scale=12.0)
        monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
        pays = []
        spectra, _, errs = replicate_sweep(
            X, [1, 2], 4, mode="batch",
            recipe=SolverRecipe("hals", 1, False, "caller"),
            telemetry_sink=pays.append)
        assert spectra.shape == (2, 4, 50) and np.isfinite(errs).all()
        assert pays[0]["recipe"] == "hals"
        # the hals objective is at least as good as plain batch MU's
        _, _, errs_mu = replicate_sweep(X, [1, 2], 4, mode="batch")
        assert (errs <= errs_mu * 1.01).all(), (errs, errs_mu)

    def test_hals_recipe_rejects_kl(self):
        from cnmf_torch_tpu.parallel import replicate_sweep

        with pytest.raises(ValueError, match="[Ff]robenius"):
            replicate_sweep(_counts(60, 30, 3, 1), [1], 3,
                            beta_loss="kullback-leibler", mode="batch",
                            recipe=SolverRecipe("hals", 1, False, "caller"))


# ---------------------------------------------------------------------------
# dispatch plumbing: run_nmf, online/rowshard dna, payload fields
# ---------------------------------------------------------------------------

class TestRecipeDispatch:
    def test_run_nmf_recipe_objective_parity(self):
        X = _counts(150, 60, 4, 21)
        errs = {}
        for rec in (None, SolverRecipe("dna", 1, True, "caller"),
                    SolverRecipe("amu", 3, False, "caller")):
            label = "mu" if rec is None else rec.label
            _, _, errs[label] = run_nmf(
                X, 4, beta_loss="kullback-leibler", mode="batch",
                random_state=5, batch_max_iter=200, recipe=rec)
        base = errs.pop("mu")
        for label, e in errs.items():
            assert abs(e - base) / base < 2e-2, (label, e, base)

    def test_run_nmf_dna_rejects_wrong_beta(self):
        with pytest.raises(ValueError, match="beta=1"):
            run_nmf(_counts(60, 30, 3, 1), 3, beta_loss="frobenius",
                    mode="batch",
                    recipe=SolverRecipe("dna", 1, True, "caller"))

    def test_rowshard_dna_matches_mu_class(self):
        from jax.sharding import Mesh

        from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

        X = _counts(200, 60, 4, 8)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("cells",))
        _, _, err_mu = nmf_fit_rowsharded(
            X, 4, mesh, beta_loss="kullback-leibler", seed=3, n_passes=8)
        _, _, err_dna = nmf_fit_rowsharded(
            X, 4, mesh, beta_loss="kullback-leibler", seed=3, n_passes=8,
            recipe=SolverRecipe("dna", 1, True, "caller"))
        assert np.isfinite(err_dna)
        assert err_dna <= err_mu * 1.02, (err_dna, err_mu)

    def test_payload_and_records_carry_recipe_accounting(self, monkeypatch):
        from cnmf_torch_tpu.parallel import replicate_sweep
        from cnmf_torch_tpu.utils.telemetry import replicate_records

        monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
        X = _counts(120, 50, 4, 3)
        pays = []
        replicate_sweep(X, [1, 2], 4, beta_loss="kullback-leibler",
                        mode="batch",
                        recipe=SolverRecipe("dna", 1, True, "caller"),
                        telemetry_sink=pays.append)
        (pay,) = pays
        assert pay["recipe"] == "dna"
        recs = replicate_records(pay)
        assert all("inner_iters" in r and "dna_fallback" in r for r in recs)
        assert all(0.0 <= r["dna_fallback"] <= 1.0 for r in recs)


# ---------------------------------------------------------------------------
# accelerated-trajectory parity suite (ISSUE 11 satellite — PR 8 follow-on)
# ---------------------------------------------------------------------------

class TestAccelTrajectoryParity:
    """The tolerance-band suite PR 8 left open: mu vs amu/dna final
    objectives AND consensus-level agreement of the spectra their sweeps
    produce, across the bench fixture classes. This is the evidence that
    would justify flipping ``CNMF_TPU_ACCEL`` to ``auto`` by default —
    the bands hold (see the assertions), but the default STAYS ``'0'``:
    the byte-identity contract (default programs identical to a
    pre-recipe build) is what pins this reproduction against the
    reference's golden artifacts and the sklearn/nmf-torch oracles, and
    an `auto` default would silently change every default KL trajectory
    those goldens were regenerated under. The flip is deferred to the
    declarative-planner item (ROADMAP 5), where per-run plans are
    recorded whole; users opt in today with CNMF_TPU_ACCEL=auto, covered
    by these bands. Rationale also in README "Solver recipes"."""

    OBJ_TOL = 2e-2

    def _sweep_spectra(self, X, recipe, k=4, seeds=(1, 2, 3, 4, 5, 6)):
        from cnmf_torch_tpu.parallel import replicate_sweep

        spectra, _, errs = replicate_sweep(
            X, list(seeds), k, beta_loss="kullback-leibler", mode="batch",
            recipe=recipe)
        return np.asarray(spectra), np.asarray(errs, np.float64)

    def test_final_objective_bands_dense_and_ell(self):
        X = _counts(300, 80, 4, 21)
        _, errs_mu = self._sweep_spectra(X, SolverRecipe())
        for rec in (SolverRecipe("amu", 3, False, "caller"),
                    SolverRecipe("dna", 1, True, "caller")):
            _, errs = self._sweep_spectra(X, rec)
            rel = np.abs(errs - errs_mu) / errs_mu
            assert (rel < self.OBJ_TOL).all(), (rec.label, rel)
        Xs = _sparse_counts(300, 80, 4, 22)
        from cnmf_torch_tpu.ops.sparse import csr_to_ell, ell_device_put

        E = ell_device_put(csr_to_ell(Xs))
        _, errs_mu = self._sweep_spectra(E, SolverRecipe())
        _, errs_dna = self._sweep_spectra(
            E, SolverRecipe("dna", 1, True, "caller"))
        rel = np.abs(errs_dna - errs_mu) / errs_mu
        assert (rel < self.OBJ_TOL).all(), rel

    def test_consensus_spectra_band_mu_vs_dna(self):
        """The consensus-level contract: clustering each recipe's
        replicate spectra stack yields matching cluster medians (greedy
        cosine matching > 0.98) — the artifact consensus actually
        publishes, not just the scalar objectives."""
        from cnmf_torch_tpu.ops import kmeans

        X = _counts(300, 80, 4, 23)
        k = 4

        def medians(recipe):
            spectra, _ = self._sweep_spectra(X, recipe, k=k)
            flat = spectra.reshape(-1, spectra.shape[-1])
            l2 = flat / np.maximum(
                np.linalg.norm(flat, axis=1, keepdims=True), 1e-12)
            labels, _, _ = kmeans(l2, k, n_init=10, seed=1)
            med = np.stack([np.median(l2[labels == c], axis=0)
                            for c in range(k)])
            return med / np.maximum(
                np.linalg.norm(med, axis=1, keepdims=True), 1e-12)

        med_mu = medians(SolverRecipe())
        med_dna = medians(SolverRecipe("dna", 1, True, "caller"))
        C = med_mu @ med_dna.T
        best = C.max(axis=1)
        assert (best > 0.98).all(), best

    def test_default_accel_auto_with_zero_escape_hatch(self, monkeypatch):
        """The documented outcome of this suite: the bands above hold,
        which is what let the execution planner (ISSUE 17) flip the
        shipped default to 'auto' (batch KL engages dna out of the box).
        CNMF_TPU_ACCEL=0 remains the byte-identical plain-MU escape
        hatch (golden/oracle-pinned programs). README's Solver recipes
        section records the why."""
        monkeypatch.delenv("CNMF_TPU_ACCEL", raising=False)
        rec = resolve_recipe(1.0, "batch")
        assert rec.label == "dna" and rec.source == "auto"
        monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
        rec0 = resolve_recipe(1.0, "batch")
        assert rec0.is_identity
        readme = open(os.path.join(os.path.dirname(__file__), os.pardir,
                                   "README.md")).read()
        assert "CNMF_TPU_ACCEL" in readme
