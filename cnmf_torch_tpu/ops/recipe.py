"""Solver recipes — the ONE resolution of *which convergence math runs*.

Every previous perf PR changed the memory system (ELL encoding, bf16
chains, bundled contractions); COMPLETENESS closes that line with
"further gains need different math, not a better kernel". This module is
the different math's dispatch layer: a :class:`SolverRecipe` names the
iteration scheme a β-divergence solve runs —

  * ``mu``   — plain alternating multiplicative updates (the seed
    behavior; the only recipe whose trajectories are pinned element-wise
    against the sklearn/nmf-torch oracles);
  * ``amu``  — accelerated MU (Gillis & Glineur, arXiv:1107.5194):
    ``inner_repeats`` cheap H sub-iterations per expensive W update,
    with a stagnation early-exit per lane. The repeats re-use the
    loop-invariant W products (β=2: the hoisted ``XWᵀ``/``WWᵀ``
    statistics; ELL β∈{1,0}: the pre-gathered W slab table), which is
    where the per-repeat cost collapses;
  * ``dna``  — Diagonalized Newton for KL (Van hamme, arXiv:1301.3389):
    per-element diagonal-Hessian steps clipped to the nonnegativity
    boundary, with a per-row/per-column monotone MU fallback lane
    selected by comparing the two candidates' exact objective
    contributions (rows of D_KL(X‖HW) decouple for fixed W, columns for
    fixed H, so the selection preserves MU's monotonicity guarantee
    outright). Measured on the bench fixtures: 4–6× fewer outer
    iterations to a fixed KL objective tolerance than plain MU
    (``bench.py --tier accel``);
  * ``hals`` — the β=2 hierarchical-ALS family (``algo='halsvar'``),
    previously reachable only through ``run_nmf`` — the recipe selector
    is now its dispatch site for replicate sweeps too.

Resolution order: explicit caller arguments > env knobs > the auto
heuristic. Knobs (registered in ``utils/envknobs.py``):

  * ``CNMF_TPU_ACCEL``: ``0`` (default) pins plain MU — the compiled
    programs are byte-identical to a build without this module (same
    guarantee style as the telemetry flag); ``1`` forces acceleration
    wherever the recipe is defined; ``auto`` engages it for batch
    β∈{1,0} MU solves (the lane whose trajectories are NOT pinned
    bit-exact by the parity suite) and resolves ``amu``/``dna`` from β.
  * ``CNMF_TPU_INNER_REPEATS``: pins ρ; unset derives it from the
    1107.5194 cost ratio (H-repeat flops vs W-update flops — static in
    n/g/k and the ELL width, :func:`auto_inner_repeats`).
  * ``CNMF_TPU_KL_NEWTON``: ``1`` (default) lets an *engaged*
    acceleration pick DNA for β=1; ``0`` restricts it to the MU repeat
    schedule.

The resolved recipe is recorded whole: in the factorize provenance and
telemetry ``dispatch`` events (``models/cnmf.py``), in every sweep's
``replicates`` telemetry payload, and in the mid-run checkpoint identity
``params`` signature (``runtime/checkpoint.py``) — a resumed run must
never splice an MU trajectory onto a DNA one.

Stdlib-only (no jax import): the light runtime modules share it.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = ["SolverRecipe", "resolve_recipe", "auto_inner_repeats",
           "ACCEL_ENV", "INNER_REPEATS_ENV", "KL_NEWTON_ENV"]

ACCEL_ENV = "CNMF_TPU_ACCEL"
INNER_REPEATS_ENV = "CNMF_TPU_INNER_REPEATS"
KL_NEWTON_ENV = "CNMF_TPU_KL_NEWTON"

_OFF_WORDS = ("", "0", "off", "false", "no")
_ON_WORDS = ("1", "on", "true", "yes", "force")


@dataclass(frozen=True)
class SolverRecipe:
    """One resolved iteration scheme for a β-divergence solve.

    ``algo``: ``mu`` | ``amu`` | ``dna`` | ``hals``. ``inner_repeats``:
    H sub-iterations per W update (``amu`` only; 1 otherwise).
    ``kl_newton``: the β=1 updates run diagonal-Newton steps with the
    MU fallback lane (``dna`` only). ``source`` records who decided
    (``default`` / ``env`` / ``auto`` / ``caller``) for provenance.
    """

    algo: str = "mu"
    inner_repeats: int = 1
    kl_newton: bool = False
    source: str = "default"

    def __post_init__(self):
        if self.algo not in ("mu", "amu", "dna", "hals"):
            raise ValueError(f"unknown recipe algo {self.algo!r}")
        if self.inner_repeats < 1:
            raise ValueError(
                f"inner_repeats={self.inner_repeats}: must be >= 1")
        if self.kl_newton and self.algo != "dna":
            raise ValueError("kl_newton is the dna recipe's flag")

    @property
    def label(self) -> str:
        """Short human/telemetry label: ``mu``, ``amu(rho=3)``, ``dna``,
        ``hals``."""
        if self.algo == "amu":
            return f"amu(rho={self.inner_repeats})"
        return self.algo

    @property
    def is_identity(self) -> bool:
        """True when the recipe compiles the exact seed (plain-MU/HALS)
        programs — no inner repeats, no Newton lane."""
        return self.inner_repeats == 1 and not self.kl_newton

    def signature(self) -> str:
        """Stable string for the checkpoint identity ``params`` field —
        two runs whose signatures differ must not splice trajectories."""
        return (f"algo={self.algo},rho={int(self.inner_repeats)},"
                f"newton={int(self.kl_newton)}")

    def as_context(self) -> dict:
        """The telemetry ``dispatch`` event context."""
        return {"recipe": self.label, "algo": self.algo,
                "inner_repeats": int(self.inner_repeats),
                "kl_newton": bool(self.kl_newton), "source": self.source}


def auto_inner_repeats(beta: float, n: int | None = None,
                       g: int | None = None, k: int | None = None,
                       ell_width: int | None = None,
                       ell: bool = False) -> int:
    """ρ from the 1107.5194 cost ratio: 1 + (W-update flops) //
    (H-repeat flops), clamped to [2, 8]. All inputs are static shape
    facts, so ρ never changes a compiled program's cache key at run time.

    The H-*repeat* cost is what a second-and-later H update costs with
    the loop-invariant W products hoisted out of the repeat loop:

      * β=2: the repeat is ``H @ (WWᵀ)`` against the precomputed
        ``XWᵀ``/``WWᵀ`` — k-sized, so the ratio is ~2g/k and ρ caps at 8;
      * ELL β∈{1,0}: the repeat re-reads the pre-gathered W slab table
        (``~n·w·(2k+2)`` flops) while the W update additionally rebuilds
        tables and walks the transpose index set (``~n·w·(4k+2)``) — ρ=3;
      * dense β∈{1,0}: repeat and W update are the same full WH pass —
        ρ=2 (the mild schedule; the measured win here is wall-clock
        per objective, not per-iteration).
    """
    beta = float(beta)
    ell = bool(ell) or ell_width is not None
    if n and g and k:
        if beta == 2.0:
            h_rep = n * k * k
            w_upd = 2 * n * g * k
        elif ell_width:
            h_rep = n * ell_width * (2 * k + 2)
            w_upd = n * ell_width * (4 * k + 2)
        elif ell:
            # ELL-encoded but the width is not known at this resolution
            # site (run_nmf resolves before staging): the width cancels
            # in the ratio, (4k+2)/(2k+2) -> rho=3 for any width
            return 3
        else:
            h_rep = 2 * n * g * k
            w_upd = 2 * n * g * k
        return int(max(2, min(8, 1 + round(w_upd / max(h_rep, 1)))))
    # shape-free fallbacks of the same ratios (the width cancels in the
    # ELL ratio, so flag-only resolution lands the same schedule)
    if beta == 2.0:
        return 8
    return 3 if ell else 2


def resolve_recipe(beta: float, mode: str, *, algo: str = "mu",
                   ell: bool = False, n: int | None = None,
                   g: int | None = None, k: int | None = None,
                   ell_width: int | None = None,
                   accel: str | None = None,
                   inner_repeats: int | None = None,
                   kl_newton: bool | None = None) -> SolverRecipe:
    """Resolve the solver recipe for one (β, mode) solve.

    ``mode``: ``batch`` | ``online`` | ``rowshard``. ``algo`` is the
    ledger/caller algorithm choice (``mu`` or nmf-torch's ``halsvar``,
    which maps to the ``hals`` recipe outright). Explicit ``accel`` /
    ``inner_repeats`` / ``kl_newton`` arguments win over the env knobs.

    Capability map (acceleration engages only where the scheme is
    defined; everything else resolves to plain ``mu``):

      * ``dna`` — β=1 anywhere ``_chunk_h_solve``/``nmf_fit_batch``
        run (batch, online, rowshard);
      * ``amu`` — batch solves (the online/rowshard pass loops ALREADY
        repeat the cheap H solve per W update — their chunk inner loop
        is the 1107.5194 schedule natively, so there is nothing to add).
    """
    beta = float(beta)
    if algo in ("hals", "halsvar"):
        return SolverRecipe("hals", 1, False, "caller")
    if algo != "mu":
        raise ValueError(f"unknown solver algo {algo!r}")

    from ..utils.envknobs import env_flag, env_int, env_str

    if accel is None:
        accel_raw, source = env_str(ACCEL_ENV, "0"), "env"
    else:
        accel_raw, source = str(accel), "caller"
    accel_raw = accel_raw.strip().lower()
    if accel_raw in _OFF_WORDS:
        return SolverRecipe("mu", 1, False,
                            "default" if accel is None else source)
    if accel_raw in _ON_WORDS:
        engaged = True
    elif accel_raw == "auto":
        # the auto lane: batch β∈{1,0} MU solves — where the iteration
        # count dominates and no parity suite pins the plain trajectory
        # bit-exact across encodings
        engaged = mode == "batch" and beta in (1.0, 0.0)
        source = source if accel is not None else "auto"
    else:
        raise ValueError(
            f"{ACCEL_ENV}={accel_raw!r}: expected 0, 1, or auto")
    if not engaged:
        return SolverRecipe("mu", 1, False, source)

    if kl_newton is None:
        kl_newton = env_flag(KL_NEWTON_ENV, True)
    if kl_newton and beta == 1.0:
        return SolverRecipe("dna", 1, True, source)
    if mode == "batch":
        rho = inner_repeats
        if rho is None:
            # the documented default is the string 'auto' (README knob
            # table): accept it (and '') as the unset sentinel, like
            # CNMF_TPU_SPARSE_BETA; anything else must parse as an int
            raw = env_str(INNER_REPEATS_ENV, "auto").strip().lower()
            rho = 0 if raw in ("", "auto") \
                else (env_int(INNER_REPEATS_ENV, 0, lo=0) or 0)
        if not rho:
            rho = auto_inner_repeats(beta, n, g, k,
                                     ell_width=ell_width if ell else None,
                                     ell=ell)
        if int(rho) > 1:
            return SolverRecipe("amu", int(rho), False, source)
    return SolverRecipe("mu", 1, False, source)
