"""Pipeline-stage tests, mirroring the reference's two-tier test strategy
(SURVEY.md §4): synthetic smoke tests for artifacts/error paths plus
end-to-end consensus validation on structured data with known GEPs."""

import os

import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu import cNMF, load_df_from_npz, save_df_to_npz
from cnmf_torch_tpu.utils.anndata_lite import AnnDataLite, write_h5ad

NUM_CELLS = 100
NUM_GENES = 500
SEED = 42


@pytest.fixture
def mock_cnmf(tmp_path):
    return cNMF(output_dir=str(tmp_path), name="test")


def generate_counts_file(tmp_path, file_format, dtype=np.int64,
                         zero_count=False):
    """The reference's synthetic fixture (test_prepare.py:20-59): binomial
    counts in each supported container format."""
    np.random.seed(SEED)
    data = np.random.binomial(n=100, p=0.01,
                              size=(NUM_CELLS, NUM_GENES)).astype(dtype)
    if zero_count:
        data[0, :] = 0

    if file_format == "txt":
        df = pd.DataFrame(data,
                          columns=[f"gene{i}" for i in range(NUM_GENES)],
                          index=[f"cell{i}" for i in range(NUM_CELLS)])
        counts_fn = tmp_path / f"counts_{dtype.__name__}.txt"
        df.to_csv(counts_fn, sep="\t")
    elif file_format == "npz":
        df = pd.DataFrame(data,
                          columns=[f"gene{i}" for i in range(NUM_GENES)],
                          index=[f"cell{i}" for i in range(NUM_CELLS)])
        counts_fn = tmp_path / f"counts_{dtype.__name__}.npz"
        save_df_to_npz(df, counts_fn)
    elif file_format == "h5ad":
        counts_fn = tmp_path / f"counts_{dtype.__name__}.h5ad"
        write_h5ad(str(counts_fn), AnnDataLite(sp.csr_matrix(data)))
    else:
        raise ValueError(file_format)
    return str(counts_fn)


@pytest.mark.parametrize("file_format", ["txt", "npz", "h5ad"])
@pytest.mark.parametrize("dtype", [np.int64, np.float32, np.float64])
@pytest.mark.parametrize("densify", [True, False])
def test_prepare(mock_cnmf, file_format, dtype, densify, tmp_path):
    counts_fn = generate_counts_file(tmp_path, file_format, dtype)
    mock_cnmf.prepare(counts_fn, components=[5, 10], n_iter=10,
                      densify=densify)
    for key in ["normalized_counts", "nmf_replicate_parameters",
                "nmf_run_parameters", "nmf_genes_list", "tpm", "tpm_stats"]:
        assert os.path.exists(mock_cnmf.paths[key]), key


@pytest.mark.parametrize("file_format", ["txt", "npz", "h5ad"])
@pytest.mark.parametrize("densify", [True, False])
def test_prepare_raises_on_zero_count_cells(mock_cnmf, file_format, densify,
                                            tmp_path):
    counts_fn = generate_counts_file(tmp_path, file_format, np.int64,
                                     zero_count=True)
    with pytest.raises(
            Exception,
            match="Error: .* cells have zero counts of overdispersed genes.*"):
        mock_cnmf.prepare(counts_fn, components=[5, 10], n_iter=10,
                          densify=densify)


def test_seed_ledger_matches_reference_derivation(mock_cnmf, tmp_path):
    """Pins the seed-derivation algorithm the reference golden tests pin
    (test_reproducibility.py:160-165): master-seeded randint(1, 2^31-1)
    consumed in product(sorted-unique-K, iters) order."""
    counts_fn = generate_counts_file(tmp_path, "npz", np.int64)
    mock_cnmf.prepare(counts_fn, components=[7, 5], n_iter=3, seed=14)
    ledger = load_df_from_npz(mock_cnmf.paths["nmf_replicate_parameters"])

    np.random.seed(14)
    expected_seeds = np.random.randint(low=1, high=(2 ** 31) - 1, size=6)
    assert list(ledger.columns) == ["n_components", "iter", "nmf_seed",
                                    "completed"]
    assert list(ledger.n_components) == [5, 5, 5, 7, 7, 7]
    assert list(ledger["iter"]) == [0, 1, 2, 0, 1, 2]
    np.testing.assert_array_equal(ledger.nmf_seed.values, expected_seeds)
    assert not ledger.completed.any()


def _structured_counts(n=120, g=300, k_true=4, seed=0):
    """Counts with planted GEP structure so consensus can be validated
    against ground truth, not just for artifact existence."""
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(k_true, g)) * 50.0 / g
    lam = usage @ spectra * 200.0
    counts = rng.poisson(lam).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0  # no zero cells
    return counts, usage, spectra


@pytest.fixture(scope="module")
def e2e_run(tmp_path_factory):
    """One full prepare -> factorize -> combine run shared by the e2e tests."""
    tmp = tmp_path_factory.mktemp("e2e")
    counts, usage, spectra = _structured_counts()
    df = pd.DataFrame(counts,
                      index=[f"cell{i}" for i in range(counts.shape[0])],
                      columns=[f"g{j}" for j in range(counts.shape[1])])
    counts_fn = str(tmp / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    obj = cNMF(output_dir=str(tmp), name="e2e")
    obj.prepare(counts_fn, components=[4, 5], n_iter=6, seed=14,
                num_highvar_genes=200, batch_size=64, max_NMF_iter=200)
    obj.factorize()
    obj.combine()
    return obj, usage


def test_factorize_writes_ledgered_spectra(e2e_run):
    obj, _ = e2e_run
    ledger = load_df_from_npz(obj.paths["nmf_replicate_parameters"])
    for _, p in ledger.iterrows():
        fn = obj.paths["iter_spectra"] % (p["n_components"], p["iter"])
        assert os.path.exists(fn)
        spec = load_df_from_npz(fn)
        assert spec.shape[0] == p["n_components"]
        assert (spec.values >= 0).all()
        assert np.isfinite(spec.values).all()


def test_factorize_records_provenance(e2e_run):
    """Run artifacts must say which execution path actually ran (batched vs
    rowshard vs sequential) with its effective solver params — the ledger
    YAML alone describes intent, not execution."""
    import yaml

    obj, _ = e2e_run
    with open(obj.paths["factorize_provenance"] % 0) as f:
        record = yaml.safe_load(f)
    # 2 Ks x 6 replicates -> auto resolves to the per-K programs
    assert record["engaged_path"] == "batched"
    assert record["effective_params"]["beta_loss"] == "frobenius"
    assert "mesh_devices" in record["effective_params"]


def test_combine_shapes_and_labels(e2e_run):
    obj, _ = e2e_run
    merged = load_df_from_npz(obj.paths["merged_spectra"] % 4)
    assert merged.shape[0] == 6 * 4
    assert merged.index[0] == "iter0_topic1"
    assert merged.index[-1] == "iter5_topic4"


def test_consensus_artifacts_and_ground_truth_recovery(e2e_run):
    obj, true_usage = e2e_run
    obj.consensus(4, density_threshold=2.0, show_clustering=True,
                  close_clustergram_fig=True)
    dt = "2_0"
    for key in ["consensus_spectra", "consensus_usages", "gene_spectra_tpm",
                "gene_spectra_score", "starcat_spectra"]:
        assert os.path.exists(obj.paths[key] % (4, dt)), key
        assert os.path.exists(obj.paths[key + "__txt"] % (4, dt)), key
    assert os.path.exists(obj.paths["clustering_plot"] % (4, dt))

    usages = load_df_from_npz(obj.paths["consensus_usages"] % (4, dt))
    norm_usage = usages.div(usages.sum(axis=1), axis=0).values
    # each true GEP's usage should correlate strongly with exactly one
    # recovered GEP (greedy matching over the correlation matrix)
    C = np.corrcoef(true_usage.T, norm_usage.T)[:4, 4:]
    best = C.max(axis=1)
    assert (best > 0.7).all(), f"GEP recovery too weak: {best}"

    spectra = load_df_from_npz(obj.paths["consensus_spectra"] % (4, dt))
    np.testing.assert_allclose(spectra.sum(axis=1), 1.0, atol=1e-4)


def test_consensus_density_filter_and_cache(e2e_run):
    obj, _ = e2e_run
    obj.consensus(4, density_threshold=0.6, show_clustering=False,
                  build_ref=False)
    assert os.path.exists(obj.paths["local_density_cache"] % 4)
    dens = load_df_from_npz(obj.paths["local_density_cache"] % 4)
    assert dens.shape == (24, 1)
    assert (dens.values >= 0).all()
    # threshold below the minimum density must leave zero spectra -> error
    with pytest.raises(RuntimeError, match="Zero components remain"):
        obj.consensus(4, density_threshold=float(dens.values.min()) / 2,
                      show_clustering=False, build_ref=False)
    # a threshold keeping >=1 but < k spectra silently collapses the program
    # count (the reference crashes in sklearn instead) -> warn the operator
    thin = float(np.sort(dens.values.ravel())[1]) + 1e-6
    if (dens.values < thin).sum() < 4:
        with pytest.warns(UserWarning, match="fewer than k"):
            obj.consensus(4, density_threshold=thin,
                          show_clustering=False, build_ref=False)


def test_k_selection_plot(e2e_run):
    obj, _ = e2e_run
    stats = obj.k_selection_plot(close_fig=True)
    assert os.path.exists(obj.paths["k_selection_stats"])
    assert os.path.exists(obj.paths["k_selection_plot"])
    assert list(stats.k) == [4, 5]
    assert (stats.silhouette <= 1.0).all()
    assert (stats.prediction_error > 0).all()


def test_load_results(e2e_run):
    obj, _ = e2e_run
    usage, scores, tpm, top_genes = obj.load_results(4, 2.0, n_top_genes=10)
    assert usage.shape[1] == 4
    np.testing.assert_allclose(usage.sum(axis=1), 1.0, atol=1e-6)
    assert scores.shape[1] == 4
    assert top_genes.shape == (10, 4)


def test_worker_sharding_and_skip_missing(tmp_path):
    """The reference's elastic-completion contract (cnmf.py:876-880,
    904-909): workers write disjoint files; combine tolerates dead workers;
    skip_completed_runs resumes only missing work."""
    counts, _, _ = _structured_counts(n=60, g=150)
    df = pd.DataFrame(counts,
                      index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(150)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    obj = cNMF(output_dir=str(tmp_path), name="shard")
    obj.prepare(counts_fn, components=[3], n_iter=4, seed=1,
                num_highvar_genes=100, batch_size=64, max_NMF_iter=100)
    # worker 0 of 2 runs tasks 0, 2 only
    obj.factorize(worker_i=0, total_workers=2)
    done = [os.path.exists(obj.paths["iter_spectra"] % (3, i))
            for i in range(4)]
    assert done == [True, False, True, False]

    with pytest.raises(FileNotFoundError):
        obj.combine_nmf(3, skip_missing_files=False)
    merged = obj.combine_nmf(3, skip_missing_files=True)
    assert merged.shape[0] == 2 * 3

    # resume: worker 1's share appears once skip_completed_runs reruns it
    obj.update_nmf_iter_params()
    obj.factorize(worker_i=0, total_workers=1, skip_completed_runs=True)
    assert all(os.path.exists(obj.paths["iter_spectra"] % (3, i))
               for i in range(4))
    merged = obj.combine_nmf(3)
    assert merged.shape[0] == 4 * 3


def test_sequential_path_matches_batched(tmp_path):
    """batched=False (per-task loop) and batched=True (one vmapped program)
    must produce identical spectra for the same ledger seeds."""
    counts, _, _ = _structured_counts(n=50, g=120)
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(50)],
                      columns=[f"g{j}" for j in range(120)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    a = cNMF(output_dir=str(tmp_path), name="seq")
    a.prepare(counts_fn, components=[3], n_iter=2, seed=7,
              num_highvar_genes=80, batch_size=50, max_NMF_iter=50)
    a.factorize(batched=False)

    b = cNMF(output_dir=str(tmp_path), name="bat")
    b.prepare(counts_fn, components=[3], n_iter=2, seed=7,
              num_highvar_genes=80, batch_size=50, max_NMF_iter=50)
    b.factorize(batched=True)

    for it in range(2):
        sa = load_df_from_npz(a.paths["iter_spectra"] % (3, it)).values
        sb = load_df_from_npz(b.paths["iter_spectra"] % (3, it)).values
        np.testing.assert_allclose(sa, sb, rtol=2e-3, atol=2e-4)


def test_device_residency_cache_detects_content_change(tmp_path):
    """The consensus device cache must not serve a stale matrix when a
    same-shape but different-content X arrives (consensus accepts a
    caller-supplied norm_counts)."""
    import numpy as np

    from cnmf_torch_tpu import cNMF

    obj = cNMF(output_dir=str(tmp_path), name="cachetest")
    a = np.random.default_rng(0).random((40, 30))
    b = a * 2.0
    da = obj._stage_dense("norm_counts", a)
    da2 = obj._stage_dense("norm_counts", a)
    assert da2 is da  # same content -> cache hit
    db = obj._stage_dense("norm_counts", b)
    np.testing.assert_allclose(np.asarray(db), b.astype(np.float32))
