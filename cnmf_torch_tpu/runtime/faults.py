"""Deterministic fault injection behind ``CNMF_TPU_FAULT_SPEC``.

Every failure mode the resilience layer claims to survive must be
reproducible on demand, or the recovery paths rot untested (the
chaos-engineering argument MPI-FAUN-scale NMF deployments make for
first-class failure containment, PAPERS.md). This module turns an env
spec into injected faults at fixed hook points in the pipeline:

  * ``nonfinite`` — poison replicate lanes with NaN after a sweep
    returns (exercises quarantine + reseeded retry);
  * ``kill`` — SIGKILL this process at a stage hook (exercises launcher
    respawn + torn-artifact-proof resume);
  * ``torn`` — truncate an artifact file AFTER its atomic write lands
    (exercises reader-side validation: resume and combine must detect
    the damage rather than trust the file);
  * ``upload`` — raise from a host→device staging entry point;
  * ``stall`` — sleep inside a per-slab staging hook (``seconds=N``,
    default 30), simulating a hung transfer so the
    ``CNMF_TPU_STREAM_STALL_S`` watchdog path is testable on demand;
  * ``hostloss`` — raise :class:`HostLossError` at a pass/replicate
    boundary, simulating a mesh participant (host or device) dying
    mid-run; carries the simulated lost-device set so the elastic
    controller (``runtime/elastic.py``) can re-plan a degraded mesh
    over the survivors. Default ``limit`` 1 (one loss per process);
  * ``straggler`` — sleep ``seconds=N`` (default 1) at per-task hooks
    of a matching worker, turning it into a deterministic straggler so
    the launcher's ``CNMF_TPU_STRAGGLER_S`` containment is testable;
  * ``shard_read`` — corrupt the next shard-store slab READ (the
    reader's digest validation must detect it and re-read from disk —
    ``utils/shardstore.py``). Default ``limit`` 1.
  * ``netflake`` / ``netslow`` / ``netdown`` / ``nettorn`` — network
    faults injected at the remote store-backend seam
    (``utils/storebackend.py``, hook context ``<method>:<object>``, e.g.
    ``get:slab_00003.npz``): a transient connection error that the
    retry/backoff ladder must heal (``netflake``, default ``limit`` 1),
    a slow response (``netslow``, ``seconds=N`` default 2, ``limit`` 1)
    that the hedged-read path must beat, a hard outage (``netdown``,
    UNBOUNDED by default — every matching request fails until the spec
    changes) that must degrade to the local cache or raise
    ``RemoteStoreError``, and a torn response body (``nettorn``,
    ``limit`` 1) the digest check must catch.
  * ``replicadeath`` / ``replicawedge`` — serving-fleet faults
    (``serving/fleet.py``): a matching clause tells the fleet router's
    supervisor to SIGKILL (``replicadeath``) or SIGSTOP
    (``replicawedge``) one of its serve replicas at the supervision
    tick, so dead-replica failover and wedge conviction + respawn are
    testable on demand. Selectors ``context``/``worker`` (the replica
    slot index); both default ``limit`` 1.

Spec grammar (semicolon-separated clauses)::

    CNMF_TPU_FAULT_SPEC="nonfinite:k=5,iter=2;kill:stage=factorize,worker=1;torn:artifact=iter_"

Each clause is ``kind`` or ``kind:key=val[,key=val...]``. Selector keys
(``k``, ``iter``, ``attempt``, ``stage``, ``worker``, ``artifact``,
``context``) narrow where the fault fires; control keys modulate it:
``after=N`` skips the first N matching hook hits, ``limit=N`` caps
injections per process (torn only; default 1), and ``once=PATH`` claims
a filesystem sentinel with O_CREAT|O_EXCL so exactly ONE process ever
injects the clause (a respawned worker must not re-kill itself).

Unset/empty spec: every hook returns immediately after one cached dict
lookup — zero allocation, no behavior or trace changes anywhere. The
module is stdlib-only (no jax/numpy at import) so IO-layer hooks stay
cheap to import.
"""

from __future__ import annotations

import os
import signal
import threading

__all__ = [
    "FAULT_SPEC_ENV",
    "FaultClause",
    "HostLossError",
    "parse_fault_spec",
    "active_spec",
    "maybe_poison_lanes",
    "maybe_kill",
    "maybe_tear",
    "maybe_fail",
    "maybe_stall",
    "maybe_hostloss",
    "maybe_straggle",
    "maybe_shard_read",
    "maybe_netfault",
    "maybe_replicadeath",
    "maybe_replicawedge",
]

FAULT_SPEC_ENV = "CNMF_TPU_FAULT_SPEC"

_KINDS = ("nonfinite", "kill", "torn", "upload", "stall", "hostloss",
          "straggler", "shard_read", "netflake", "netslow", "netdown",
          "nettorn", "replicadeath", "replicawedge")
_CONTROL_KEYS = ("after", "limit", "once")


class HostLossError(RuntimeError):
    """Injected topology failure: a mesh participant (host/device) died.

    ``lost`` names the simulated lost device ids (empty = "lose the last
    ``count`` devices of whatever mesh the catcher holds"). The elastic
    controller treats this exactly like a real XLA device-loss error —
    the only difference is that a real loss identifies its dead devices
    by probing, an injected one by decree."""

    def __init__(self, message: str, lost=(), count: int = 1):
        super().__init__(message)
        self.lost = tuple(int(d) for d in lost)
        self.count = int(count)


class FaultClause:
    """One parsed clause: ``kind`` + params + per-process hit counters.
    Counter state lives on the clause object, and parsed specs are cached
    per raw string, so ``after``/``limit`` semantics survive repeated
    hook calls without re-parsing the env on every hit."""

    __slots__ = ("kind", "params", "hits", "injected")

    def __init__(self, kind: str, params: dict):
        self.kind = kind
        self.params = params
        self.hits = 0
        self.injected = 0

    def __repr__(self):
        return f"FaultClause({self.kind!r}, {self.params!r})"


def parse_fault_spec(raw: str) -> list[FaultClause]:
    """Parse a spec string; raises ``ValueError`` on malformed input so a
    typo'd chaos run fails loudly instead of silently injecting nothing."""
    clauses = []
    for part in raw.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        kind = kind.strip()
        if kind not in _KINDS:
            raise ValueError(
                f"{FAULT_SPEC_ENV}: unknown fault kind {kind!r} in "
                f"{part!r} (known: {', '.join(_KINDS)})")
        params: dict = {}
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            key, sep, val = kv.partition("=")
            if not sep or not key:
                raise ValueError(
                    f"{FAULT_SPEC_ENV}: expected key=value, got {kv!r} "
                    f"in clause {part!r}")
            val = val.strip()
            params[key.strip()] = int(val) if val.lstrip("-").isdigit() \
                else val
        clauses.append(FaultClause(kind, params))
    return clauses


# parsed-spec cache keyed on the raw env value: hook sites call
# active_spec() on every hit, so toggling the env mid-process (tests,
# chaos drivers) re-parses exactly once per distinct value while the
# steady state costs one getenv + one lock-free string compare. The lock
# covers the whole check-then-parse-then-swap, so concurrent first hits
# (staging worker threads all consult the upload hook) parse once.
_cache: tuple[str, list[FaultClause]] | None = None
_cache_lock = threading.Lock()


def active_spec() -> list[FaultClause] | None:
    global _cache
    from ..utils.envknobs import env_str

    raw = env_str(FAULT_SPEC_ENV, "")
    if not raw.strip():
        return None
    cache = _cache
    if cache is None or cache[0] != raw:
        with _cache_lock:
            cache = _cache
            if cache is None or cache[0] != raw:
                cache = (raw, parse_fault_spec(raw))
                _cache = cache
    return cache[1]


def _take_once(params: dict) -> bool:
    """Claim the clause's ``once`` sentinel; True when this process may
    inject. A single O_CREAT|O_EXCL open is the atomic cross-process
    claim — the second claimant (e.g. a respawned worker) loses."""
    path = params.get("once")
    if path is None:
        return True
    try:
        os.close(os.open(str(path), os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return True
    except FileExistsError:
        return False
    except OSError:
        return False


def _selector_match(params: dict, ctx: dict) -> bool:
    for key, want in params.items():
        if key in _CONTROL_KEYS:
            continue
        if key not in ctx:
            return False
        have = ctx[key]
        if isinstance(want, int):
            try:
                if int(have) != want:
                    return False
            except (TypeError, ValueError):
                return False
        elif str(want) != str(have):
            return False
    return True


def maybe_poison_lanes(k, iters, spectra, errs, attempt: int = 0,
                       seeds=None):
    """NaN-poison replicate lanes matching a ``nonfinite`` clause.

    ``spectra``/``errs`` are the fetched numpy results of one sweep (lane
    axis first); matching lanes get both set to NaN, exactly what a
    diverged MU chain produces. Clause selectors: ``k`` (sweep K),
    ``iter`` (a single ledger iter; omitted = every lane), ``attempt``
    (default 0 — retries run clean so recovery is observable). Returns
    possibly-copied ``(spectra, errs)``; the unset-spec path returns the
    inputs untouched."""
    spec = active_spec()
    if spec is None:
        return spectra, errs
    import numpy as np

    lanes = []
    for clause in spec:
        if clause.kind != "nonfinite":
            continue
        params = clause.params
        if int(params.get("attempt", 0)) != int(attempt):
            continue
        if "k" in params and int(params["k"]) != int(k):
            continue
        if "iter" in params:
            clause_lanes = [j for j, it in enumerate(iters)
                            if int(it) == int(params["iter"])]
        elif "seed" in params:
            # a seed selector at a hook site without seed info is a
            # NO-MATCH, not match-everything: poisoning every lane would
            # misattribute a whole-sweep failure to a one-lane spec
            clause_lanes = ([] if seeds is None else
                            [j for j, s in enumerate(seeds)
                             if int(s) == int(params["seed"])])
        else:
            clause_lanes = list(range(len(iters)))
        if not clause_lanes:
            continue
        # the shared control keys apply here like every other hook: one
        # matching sweep observation = one hit; `limit` caps injections
        # per process (default unbounded — a spec without controls keeps
        # poisoning every matching sweep), `once` is the cross-process
        # single-injection sentinel
        clause.hits += 1
        if clause.hits <= int(params.get("after", 0)):
            continue
        if "limit" in params and clause.injected >= int(params["limit"]):
            continue
        if not _take_once(params):
            continue
        clause.injected += 1
        lanes.extend(clause_lanes)
    if not lanes:
        return spectra, errs
    spectra = np.array(spectra, dtype=np.float32, copy=True)
    errs = np.array(errs, dtype=np.float64, copy=True)
    for j in set(lanes):
        spectra[j] = np.nan
        errs[j] = np.nan
    return spectra, errs


def maybe_kill(stage: str, worker=None) -> None:
    """SIGKILL this process when a ``kill`` clause matches the hook —
    the real preemption signal, not an exception anything can catch.
    Hooks sit AFTER artifact writes land, so the torn/partial state a
    kill leaves behind is exactly what a real preemption leaves."""
    spec = active_spec()
    if spec is None:
        return
    for clause in spec:
        if clause.kind != "kill":
            continue
        if not _selector_match(clause.params,
                               {"stage": stage, "worker": worker}):
            continue
        clause.hits += 1
        if clause.hits <= int(clause.params.get("after", 0)):
            continue
        if not _take_once(clause.params):
            continue
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_tear(path) -> bool:
    """Truncate ``path`` (to ~1/3 of its bytes) when a ``torn`` clause's
    ``artifact`` substring matches its basename — a simulated mid-write
    kill that predates the atomic-write layer, kept injectable so the
    READER-side validation (resume probing, combine) stays tested.
    ``limit`` caps injections per clause (default 1). Returns True when
    the file was torn."""
    spec = active_spec()
    if spec is None:
        return False
    name = os.path.basename(os.fspath(path))
    for clause in spec:
        if clause.kind != "torn":
            continue
        sub = str(clause.params.get("artifact", ""))
        if sub and sub not in name:
            continue
        clause.hits += 1
        if clause.hits <= int(clause.params.get("after", 0)):
            continue
        if clause.injected >= int(clause.params.get("limit", 1)):
            continue
        if not _take_once(clause.params):
            continue
        try:
            size = os.path.getsize(path)
            # deliberately tearing the artifact IS this injector's job
            with open(path, "r+b") as f:  # cnmf-lint: disable=artifact-nonatomic
                f.truncate(max(1, size // 3))
            clause.injected += 1
            return True
        except OSError:
            return False
    return False


def maybe_stall(context=None) -> float:
    """Sleep when a ``stall`` clause matches ``context`` (substring match,
    like ``upload``'s) — the injectable form of a hung shard transfer.
    ``seconds`` bounds the hang (default 30, so a stalled worker thread
    eventually exits even after the watchdog gave up on it); ``limit``
    defaults to 1 injection per clause. Returns the seconds slept (0.0
    when nothing matched), so hook sites stay assertable."""
    spec = active_spec()
    if spec is None:
        return 0.0
    import time

    for clause in spec:
        if clause.kind != "stall":
            continue
        if not _clause_fires(clause, context, None, default_limit=1):
            continue
        secs = float(clause.params.get("seconds", 30.0))
        time.sleep(secs)
        return secs
    return 0.0


def _clause_fires(clause: FaultClause, context, worker,
                  default_limit: int | None) -> bool:
    """Shared selector + control evaluation for the topology hooks
    (``hostloss``/``straggler``): ``context`` substring match, ``worker``
    int match, then the ``after``/``limit``/``once`` controls.
    ``default_limit=None`` means unbounded unless the clause caps it.
    Mutates the clause's hit/injected counters; True = inject now."""
    params = clause.params
    sub = params.get("context")
    if sub is not None and str(sub) not in str(context or ""):
        return False
    if "worker" in params:
        try:
            if worker is None or int(worker) != int(params["worker"]):
                return False
        except (TypeError, ValueError):
            return False
    clause.hits += 1
    if clause.hits <= int(params.get("after", 0)):
        return False
    limit = params.get("limit", default_limit)
    if limit is not None and clause.injected >= int(limit):
        return False
    if not _take_once(params):
        return False
    clause.injected += 1
    return True


def maybe_hostloss(context=None, worker=None) -> None:
    """Raise :class:`HostLossError` when a ``hostloss`` clause matches —
    the injectable form of a host/device dying mid-run. Selectors:
    ``context`` (substring match against the hook site — ``pass`` for the
    rowsharded per-pass boundary, ``replicate`` for the post-solve
    boundary, ``sweep2d`` for the 2-D sweep's slice loop), ``worker``.
    Clause params ``devices`` (``+``-separated ids, e.g. ``devices=2+3``)
    or ``count=N`` (default 1: lose the last N devices of the mesh the
    catcher holds) describe WHAT died. ``limit`` defaults to 1 — one
    topology loss per process, so the degraded continuation itself runs
    clean and the recovery is observable."""
    spec = active_spec()
    if spec is None:
        return
    for clause in spec:
        if clause.kind != "hostloss":
            continue
        if not _clause_fires(clause, context, worker, default_limit=1):
            continue
        params = clause.params
        lost = [int(d) for d in
                str(params.get("devices", "")).split("+") if d != ""]
        raise HostLossError(
            "cnmf-tpu injected fault: hostloss (context=%s, lost=%s, "
            "count=%s) — a mesh participant died"
            % (context, lost or "last-%d" % int(params.get("count", 1)),
               params.get("count", 1)),
            lost=lost, count=int(params.get("count", 1)))


def maybe_straggle(context=None, worker=None) -> float:
    """Sleep when a ``straggler`` clause matches — the injectable form of
    a slow shard/worker. Unlike ``stall`` (one hung transfer), a
    straggler is CONSISTENTLY slow: ``limit`` defaults to unbounded, so
    every matching per-task hook hit sleeps ``seconds`` (default 1) and
    the worker falls steadily behind its peers. Returns seconds slept."""
    spec = active_spec()
    if spec is None:
        return 0.0
    import time

    for clause in spec:
        if clause.kind != "straggler":
            continue
        if not _clause_fires(clause, context, worker, default_limit=None):
            continue
        secs = float(clause.params.get("seconds", 1.0))
        time.sleep(secs)
        return secs
    return 0.0


def maybe_shard_read(context=None, worker=None) -> bool:
    """True when a ``shard_read`` clause matches — the injectable form of
    a torn/bit-rotted shard-store slab READ (a truncated page-cache read,
    an NFS blip, silent disk corruption). The shard-store reader
    (``utils/shardstore.py``) corrupts the slab it just loaded when this
    fires, so its content-digest validation MUST detect the damage and
    the retry loop re-reads from disk — exactly the reader-side
    containment the ooc smoke gate asserts. ``limit`` defaults to 1 (one
    torn read per clause; the re-read then sees clean bytes)."""
    spec = active_spec()
    if spec is None:
        return False
    for clause in spec:
        if clause.kind != "shard_read":
            continue
        if _clause_fires(clause, context, worker, default_limit=1):
            return True
    return False


def maybe_netfault(op=None, context=None) -> str | None:
    """Network-fault hook at the remote store-backend seam
    (``utils/storebackend.py``): called once per HTTP request BEFORE the
    socket opens, with ``op`` the lowercased method (``get``/``put``/
    ``head``/``delete``) and ``context`` the object name. The clause
    ``context`` selector substring-matches the combined ``op:object``
    string, so ``netdown:context=get:slab`` downs slab GETs while
    manifest reads, HEAD probes, and listings stay up.

      * ``netflake`` — raise ``ConnectionError`` (transient; the
        retry/backoff ladder heals it). Default ``limit`` 1.
      * ``netslow`` — sleep ``seconds`` (default 2) then proceed, the
        deterministic tail-latency request a hedged read must beat.
        Default ``limit`` 1.
      * ``netdown`` — raise ``ConnectionError`` on EVERY matching
        request (default limit unbounded): a hard outage that must end
        in cache-degraded service or a named ``RemoteStoreError``.
      * ``nettorn`` — return ``"tear"``: the backend flips a byte of the
        response body it is about to hand back, so the shard reader's
        content-digest validation must catch the damage and re-fetch.
        Default ``limit`` 1.

    Returns ``"tear"`` when the caller must corrupt the body, else None.
    """
    spec = active_spec()
    if spec is None:
        return None
    import time

    ctx = "%s:%s" % (op or "", context or "")
    for clause in spec:
        if clause.kind not in ("netflake", "netslow", "netdown", "nettorn"):
            continue
        limit = None if clause.kind == "netdown" else 1
        if not _clause_fires(clause, ctx, None, default_limit=limit):
            continue
        if clause.kind == "netslow":
            time.sleep(float(clause.params.get("seconds", 2.0)))
            return None
        if clause.kind == "nettorn":
            return "tear"
        raise ConnectionError(
            "cnmf-tpu injected fault: %s (%s) — remote store unreachable"
            % (clause.kind, ctx))
    return None


def maybe_replicadeath(context=None, worker=None) -> bool:
    """True when a ``replicadeath`` clause matches — the injectable form
    of a serve replica dying (OOM kill, preemption, segfault). The fleet
    router's supervisor (``serving/fleet.py``) calls this once per up
    replica per supervision tick with ``worker`` = the replica's slot
    index and SIGKILLs the subprocess when it fires, so the next poll
    sees a real dead process and the failover + respawn machinery runs
    against the genuine article. ``limit`` defaults to 1 (one death per
    clause; the respawned replica runs clean so recovery is
    observable)."""
    spec = active_spec()
    if spec is None:
        return False
    for clause in spec:
        if clause.kind != "replicadeath":
            continue
        if _clause_fires(clause, context, worker, default_limit=1):
            return True
    return False


def maybe_replicawedge(context=None, worker=None) -> bool:
    """True when a ``replicawedge`` clause matches — the injectable form
    of a replica that is alive but unresponsive (GIL-bound spin, stuck
    device dispatch, paging storm). The fleet supervisor SIGSTOPs the
    subprocess when this fires: the process keeps its socket backlog
    (connects succeed, replies never come) and its heartbeat goes stale,
    which is exactly the evidence profile the wedge-conviction path must
    convict on before SIGKILLing + respawning. ``limit`` defaults to
    1."""
    spec = active_spec()
    if spec is None:
        return False
    for clause in spec:
        if clause.kind != "replicawedge":
            continue
        if _clause_fires(clause, context, worker, default_limit=1):
            return True
    return False


def maybe_fail(kind: str, **ctx) -> None:
    """Raise ``RuntimeError`` when a clause of ``kind`` matches ``ctx``
    (used for the ``upload`` fault class at staging entry points)."""
    spec = active_spec()
    if spec is None:
        return
    for clause in spec:
        if clause.kind != kind:
            continue
        params = clause.params
        # `context` selects by substring so one clause can target e.g.
        # every rowshard staging call without naming each site
        sub = params.get("context")
        if sub is not None and str(sub) not in str(ctx.get("context", "")):
            continue
        rest = {key: val for key, val in params.items()
                if key not in _CONTROL_KEYS and key != "context"}
        if not _selector_match(rest, ctx):
            continue
        clause.hits += 1
        if clause.hits <= int(params.get("after", 0)):
            continue
        if not _take_once(params):
            continue
        raise RuntimeError(
            f"cnmf-tpu injected fault: {kind} "
            f"({', '.join(f'{key}={val}' for key, val in sorted(ctx.items()))})")
