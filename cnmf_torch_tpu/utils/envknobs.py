"""Strict env-knob parsing and the ONE knob registry (ISSUE 6 + ISSUE 7).

Every ``CNMF_*``/``JAX_*`` environment variable the package consults is
declared here — name, type, display default, and one-line doc — and read
exclusively through the typed accessors below. Two gates hang off the
registry:

  * ``cnmf-tpu lint`` (``analysis/rules_knobs.py``) flags any raw
    ``os.environ`` access to a ``CNMF_*``/``JAX_*`` name outside this
    module, and any accessor call naming a knob that is not registered;
  * the registry is cross-checked both ways against the README's
    "Environment knobs" table (:func:`knob_table` prints the canonical
    table; ``cnmf-tpu lint --knob-table`` regenerates it), so doc drift
    fails tier-1 instead of accumulating.

Accessors reject bad values at parse time with a one-line message naming
the knob (a typo'd ``CNMF_TPU_STREAM_DEPTH=tow`` used to surface as a
confusing downstream error). Stdlib-only so the light runtime modules
(``runtime/checkpoint.py``) can share them with the jax-heavy staging
layer (``parallel/streaming.py``, ``parallel/multihost.py``) without
import-order consequences.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

__all__ = [
    "Knob",
    "REGISTRY",
    "env_int",
    "env_float",
    "env_str",
    "env_flag",
    "env_is_set",
    "pin_knob",
    "knob_table",
    "parse_knob_table",
]

_FALSE_WORDS = ("0", "false", "off", "no")


@dataclass(frozen=True)
class Knob:
    """One registered environment knob.

    ``default`` is the *display* default — the exact cell text for the
    README table (some defaults are computed at runtime: "device-derived",
    "`2×threads+1`"). ``documented=False`` marks upstream variables we
    merely respect (``JAX_*``): registered so the accessors and the lint
    hygiene rule cover them, excluded from the README cross-check.
    """

    name: str
    kind: str  # "int" | "float" | "str" | "flag"
    default: str
    doc: str
    documented: bool = True


def _knobs(*entries) -> dict:
    return {k.name: k for k in entries}


REGISTRY: dict[str, Knob] = _knobs(
    # -- solver / dispatch ------------------------------------------------
    Knob("CNMF_TPU_SPARSE_BETA", "str", "auto",
         "β∈{1,0} ELL sparse path: `0` force dense, `1` force ELL, a value "
         "in (0,1) replaces the auto density threshold (default 0.10, plus "
         "a width≤genes/8 ragged-row guard)"),
    Knob("CNMF_TPU_ACCEL", "str", "auto",
         "iteration-count acceleration recipes (ISSUE 9): `auto` "
         "(default since the planner, ISSUE 17) engages them for batch "
         "β∈{1,0} MU solves and derives amu/dna from β; `0` pins plain "
         "MU (programs byte-identical to a build without the recipe "
         "layer — the parity escape hatch), `1` forces acceleration "
         "wherever defined — the chosen recipe lands in the plan event, "
         "provenance, and the checkpoint identity"),
    Knob("CNMF_TPU_PALLAS", "str", "auto",
         "fused Pallas kernels for the ELL β=1 (KL) statistics + "
         "objective (ISSUE 16): `auto` (default since the planner, "
         "ISSUE 17) engages them only on a TPU backend (consulting the "
         "measured Pallas-vs-jnp microbench point when cached); `0` "
         "pins the jnp ELL path (programs byte-identical to a build "
         "without the kernel layer), `1` forces the fused kernels "
         "(interpret mode off-TPU — parity runs, not perf) — the "
         "engaged kernel label lands in the plan event, provenance, and "
         "the checkpoint identity"),
    Knob("CNMF_TPU_INNER_REPEATS", "int", "auto",
         "accelerated-MU ρ (H sub-iterations per W update, arXiv "
         "1107.5194); unset derives ρ from the H-repeat vs W-update "
         "cost ratio (n/g/k/ELL width) — corrected by the per-device "
         "measured-ratio cache when the startup microbench has run "
         "(`utils/autotune.py`, clamp [2, 12]), else the static flop "
         "ratio clamped to [2, 8]"),
    Knob("CNMF_TPU_KL_NEWTON", "flag", "`1`",
         "when acceleration is engaged, β=1 solves take the Diagonalized "
         "Newton recipe (arXiv 1301.3389: diagonal-Hessian steps + "
         "per-lane monotone MU fallback); `0` restricts engaged "
         "acceleration to the MU repeat schedule"),
    Knob("CNMF_TPU_SKETCH", "str", "`0`",
         "randomized sketching (ISSUE 11, arXiv 1604.04026): `0` pins "
         "exact updates (programs byte-identical to a build without the "
         "sketch layer) and full-width consensus distances; `1` forces "
         "the sketched-KL solver recipe (`sketch` lane: exact H updates, "
         "row-subsampled W updates with exact interleaves) AND the "
         "random-projected consensus/k-selection distance stage; `auto` "
         "engages the consensus-side sketch on large replicate stacks "
         "(R >= 4x the projection dim) and leaves the solver lane off"),
    Knob("CNMF_TPU_SKETCH_DIM", "int", "auto",
         "sketch size: rows sampled per sketched W update (auto derives "
         "n/8 clamped to [256, n]) and the consensus random-projection "
         "dimension (auto = 256, clamped below the spectra width)"),
    Knob("CNMF_TPU_SKETCH_EXACT_EVERY", "int", "`4`",
         "bias control for the sketched W update: iteration 0 and every "
         "E-th outer iteration/pass run the exact full-data update; `1` "
         "makes every update exact (the sketch lane's identity schedule)"),
    Knob("CNMF_TPU_BF16_RATIO", "flag", "`1`",
         "bf16 X/WH/ratio intermediates for online KL/IS (1.78–2.09× on "
         "v5e); `0` restores strict f32 (announced once per process when "
         "active)"),
    Knob("CNMF_TPU_PLAN", "str", "unset",
         "path to a dumped execution-plan JSON (the env spelling of "
         "`cnmf-tpu factorize --plan`): loaded before any dispatch "
         "resolves and pinned knob-by-knob, so the run reproduces the "
         "recorded dispatch bit-identically (`runtime/planner.py`; the "
         "resolved plan of every factorize is logged as a `plan` "
         "telemetry event and printed by `cnmf-tpu plan <run_dir>`)"),
    Knob("CNMF_TPU_AUTOTUNE", "str", "auto",
         "the microbench autotuner behind the execution planner "
         "(`utils/autotune.py`): `auto` (default) consumes an existing "
         "per-device measured cache (ρ cost ratios, ELL density "
         "crossover, Pallas-vs-jnp, grid blocks, stream threads, sketch "
         "dim) but only measures when an explicitly engaged lane needs "
         "it; `1` measures all plan points up front (once per device "
         "fingerprint, ~2 s); `0` disables measuring AND consuming — "
         "static heuristics only, the deterministic escape hatch"),
    Knob("CNMF_TPU_BUDGET_ELEMS", "int", "device-derived",
         "fp32 element budget for replicate-sweep slicing"),
    Knob("CNMF_TPU_WARM_DUMMY_BUDGET_BYTES", "int", "`2<<30`",
         "cap on dataset-sized warm-up dummy allocations "
         "(consensus/K-selection/Harmony warms)"),
    # -- staging ----------------------------------------------------------
    Knob("CNMF_TPU_STREAM_DEPTH", "int", "`2×threads+1`",
         "max in-flight (prepared-but-uncommitted) staging slabs; `1` = "
         "exact serial fallback; clamped by the bytes budget"),
    Knob("CNMF_TPU_STREAM_THREADS", "int", "`min(4, cpus−1)`",
         "host-prep worker threads for pipelined staging; `0` = serial"),
    Knob("CNMF_TPU_STREAM_BYTES", "int", "`4<<30`",
         "host-RAM budget for in-flight staging slab buffers (caps depth × "
         "slab bytes)"),
    Knob("CNMF_TPU_STREAM_TRANSPORT", "str", "auto",
         "sparse staging transport: `csr` ships CSR buffers + on-device "
         "scatter densify (accelerators — wire bytes scale with nnz), "
         "`dense` densifies slab-by-slab on host (auto on CPU backends, "
         "where XLA's scatter costs ~4× the memcpy it replaces)"),
    Knob("CNMF_TPU_SHARD_RETRIES", "int", "`2`",
         "shard-LAYER retry budget, two scopes: per-slab upload retries in "
         "the staging pipeline (exhausted slabs raise `ShardUploadError`) "
         "and per-slab disk re-reads after a torn/digest-mismatched store "
         "read (exhausted raises `TornShardError`). Network-transport "
         "retries are separate (`CNMF_TPU_STORE_RETRIES`). `0` disables "
         "retries"),
    Knob("CNMF_TPU_SHARD_BACKOFF_S", "float", "`0.1`",
         "shard-retry backoff base: attempt N waits `base * 2^(N-1)` "
         "seconds"),
    Knob("CNMF_TPU_STREAM_STALL_S", "float", "`0` (off)",
         "per-slab progress watchdog on the pipelined staging path: a "
         "transfer hung longer than this raises `ShardStallError` "
         "(diagnosable, checkpoint-resumable) instead of hanging the mesh"),
    # -- out-of-core ingestion (utils/shardstore.py) -----------------------
    Knob("CNMF_TPU_OOC", "str", "auto",
         "out-of-core shard-store ingestion: `auto` writes the row-slab "
         "store at prepare when the normalized matrix exceeds the slab "
         "budget and factorize streams from it when present; `1` forces "
         "the store (the h5ad normalized-counts copy is then skipped — "
         "the store is authoritative); `0` disables writing AND reading"),
    Knob("CNMF_TPU_OOC_BUDGET_BYTES", "int", "`1<<30`",
         "per-worker HOST slab-residency budget for store-backed "
         "ingestion: in-flight slab buffers stay under it (depth clamp + "
         "slab sizing), so factorize's host footprint is bounded by the "
         "budget, not the matrix size; also the `auto` store-write "
         "threshold at prepare"),
    Knob("CNMF_TPU_OOC_SLAB_ROWS", "int", "`0` (auto)",
         "rows per shard-store slab at write time; `0` derives from the "
         "slab budget (slab bytes ≤ budget/4, floored at 256 rows)"),
    Knob("CNMF_TPU_OOC_SHARD_BYTES", "int", "`0` (device-derived)",
         "per-DEVICE resident-shard budget for the rowsharded solver: a "
         "store-backed shard larger than this runs each pass as a loop "
         "over streamed X slab groups (tiny (A,B) statistics resident, X "
         "re-read per pass — solver-tolerance, not bit-identical); `0` "
         "derives from reported device memory (effectively resident on "
         "backends without memory stats)"),
    # -- remote store transport (utils/storebackend.py) --------------------
    Knob("CNMF_TPU_STORE_URI", "str", "unset",
         "shard-store transport: unset/empty keeps today's POSIX paths; "
         "`file:///base/dir` relocates the store under that directory "
         "(still the local backend); `http(s)://host:port/prefix` speaks "
         "GET/PUT/HEAD/DELETE against an object-store endpoint (the "
         "in-repo `utils/netstore.py` fixture stands in for GCS) with "
         "retry/backoff/hedging/read-through caching — staging is pinned "
         "bit-identical between backends"),
    Knob("CNMF_TPU_STORE_RETRIES", "int", "`3`",
         "network-transport retry budget per store operation (GET/PUT/"
         "HEAD/LIST/DELETE): transient network faults retry with bounded "
         "exponential backoff + deterministic jitter; exhausted "
         "operations raise `RemoteStoreError` (or degrade to the local "
         "cache where a digest-valid copy exists). Distinct from the "
         "shard-layer `CNMF_TPU_SHARD_RETRIES`. `0` disables retries"),
    Knob("CNMF_TPU_STORE_BACKOFF_S", "float", "`0.05`",
         "store-retry backoff base: attempt N waits "
         "`base * 2^(N-1) * (1 + 0.5*jitter)` seconds, jitter derived "
         "deterministically from (object, attempt) so chaos runs replay "
         "exactly"),
    Knob("CNMF_TPU_STORE_TIMEOUT_S", "float", "`30`",
         "per-request socket timeout for slab transfers; metadata "
         "operations (manifest/HEAD/LIST) use the tighter "
         "`max(1, timeout/4)` so a down remote is detected at metadata "
         "speed, not slab speed"),
    Knob("CNMF_TPU_STORE_HEDGE_S", "float", "`0` (off)",
         "hedged reads for tail latency: a store GET still unanswered "
         "after this many seconds issues a second identical request and "
         "the first valid response wins (the loser is abandoned, its "
         "daemon thread drains harmlessly); `0` never hedges"),
    Knob("CNMF_TPU_STORE_CACHE_BYTES", "int", "`1<<30`",
         "read-through local slab cache budget for remote stores (LRU by "
         "recency, entries landed via `atomic_artifact` + sha1 sidecar "
         "and revalidated on every hit; `<store>.cache/` beside the "
         "store path, swept by `--clean` and the fresh-run orphan "
         "sweep): warm entries serve repeat reads without touching the "
         "network and let a fully-down remote degrade gracefully. `0` "
         "disables caching"),
    # -- 2-D (cells x genes) grid (parallel/grid2d.py) ---------------------
    Knob("CNMF_TPU_GRID_OVERLAP", "flag", "`1`",
         "compute-overlapped grid collectives (MPI-FAUN): each statistics "
         "block's psum dispatches while the next block's local gemm "
         "computes; `0` serializes reduce→gemm with a barrier — results "
         "are bit-identical either way, only scheduling freedom differs"),
    Knob("CNMF_TPU_GRID_BLOCKS", "int", "`0` (auto)",
         "statistics sub-blocks per overlapped reduction on the "
         "(cells × genes) grid (clamped to a divisor of the local "
         "rows/cols); `0` derives 4 blocks when the tile affords them, "
         "`1` disables blocking (one psum per statistic)"),
    Knob("CNMF_TPU_GRID_SHAPE", "str", "auto",
         "pin the (cells × genes) grid factorization as `CxG` (e.g. "
         "`4x2`); `auto` lays cells across hosts / genes within a host "
         "on pods (the O(rows·k) reductions stay on ICI, only k×g/k×k "
         "crosses DCN) and factors most-square single-host"),
    # -- checkpointing / multihost ----------------------------------------
    Knob("CNMF_TPU_CKPT_EVERY_PASSES", "int", "`1`",
         "mid-run checkpoint cadence for the rowsharded solver, in solver "
         "passes: each replicate's `(A,B)`/W/cursor state persists "
         "atomically and `--skip-completed-runs` resumes mid-run. `0` "
         "disables the subsystem (exact pre-checkpoint fused programs)"),
    Knob("CNMF_TPU_CKPT_H_BYTES", "int", "`256<<20`",
         "byte budget under which the usage matrix H also rides the "
         "checkpoint (resume then bit-identical); above it resume "
         "re-derives H from W within solver tolerance"),
    Knob("CNMF_TPU_CKPT_MIN_INTERVAL_S", "float",
         "`0` (every eligible pass)",
         "wall-clock floor between checkpoint writes: caps the "
         "gather+write amplification on runs whose passes take seconds "
         "(resume restarts from a slightly older pass)"),
    Knob("CNMF_TPU_BARRIER_TIMEOUT_S", "float", "`0` (off)",
         "cross-host barrier watchdog: a barrier a dead host can never "
         "join raises `HostBarrierTimeout` (clean abort; relaunch resumes "
         "from checkpoints) instead of a distributed hang"),
    # -- warm-up / caching / io -------------------------------------------
    Knob("CNMF_WARM_CONSENSUS", "flag", "`1`",
         "`0` disables the concurrent consensus program warm-up"),
    Knob("CNMF_WARM_PREPROCESS", "flag", "`1`",
         "`0` disables the concurrent Harmony/PCA preprocess program "
         "warm-up"),
    Knob("CNMF_TPU_COMPILE_CACHE", "flag", "`1`",
         "`0` stops the pipeline entry points from enabling the persistent "
         "XLA compile cache (a user's explicit JAX cache config is never "
         "overridden either way)"),
    Knob("CNMF_H5_COMPRESSION", "str", "`none`",
         "h5ad artifact compression: `none` (reference-matching default; "
         "gzip-1 was ~5 s of a 22 s prepare), `gzip` (level 1), or `lzf`"),
    # -- warm serving tier (serving/, ISSUE 12) ---------------------------
    Knob("CNMF_TPU_SERVE_BATCH", "int", "`8`",
         "projection daemon (`cnmf-tpu serve`): max request lanes "
         "coalesced into one batched device dispatch; `1` disables "
         "cross-request batching (every request solves solo)"),
    Knob("CNMF_TPU_SERVE_LINGER_MS", "float", "`2`",
         "micro-batching linger: after the first queued request, the "
         "dispatcher waits up to this many milliseconds for batchmates "
         "before launching the (possibly smaller) batch; `0` dispatches "
         "immediately"),
    Knob("CNMF_TPU_SERVE_BUCKETS", "str", "`64,256,1024`",
         "padded-shape bucket schedule for the serve program cache: "
         "request row counts round up to the next bucket (the run's "
         "online chunk size is always appended as the top bucket) so a "
         "bounded program set serves every request shape with zero "
         "steady-state compiles"),
    Knob("CNMF_TPU_SERVE_TIMEOUT_S", "float", "`30`",
         "admission deadline: a request still undispatched this long "
         "after arrival is shed with a clear error instead of waiting "
         "behind an overloaded queue (the queue itself is bounded at "
         "4x the batch size; arrivals beyond it shed immediately)"),
    Knob("CNMF_TPU_SERVE_WARM_START", "flag", "`1`",
         "serve-path usage warm starts: a repeat (tenant, matrix) "
         "projection re-solves from the tenant's previous usage matrix "
         "instead of the constant init — repeat projections converge in "
         "a fraction of the inner iterations; `0` restores the "
         "stateless solo-identical init for every request"),
    Knob("CNMF_TPU_SERVE_DRAIN_S", "float", "`30`",
         "shutdown drain budget: on `POST /shutdown` (or any daemon "
         "close) the accept loop stops first, then every "
         "already-accepted request runs to its real reply for up to "
         "this many seconds before the batcher is torn down — no "
         "accepted request is lost across a clean shutdown, which is "
         "what the fleet's zero-downtime rollover drains ride"),
    # -- replicated serving fleet (serving/fleet.py, ISSUE 20) ------------
    Knob("CNMF_TPU_FLEET_REPLICAS", "int", "`2`",
         "`cnmf-tpu fleet`: serve replicas the router spawns and "
         "fronts (also `--replicas`); each is a full `serve` daemon "
         "subprocess with its own unix socket, heartbeat file, and "
         "AOT-warmed program cache"),
    Knob("CNMF_TPU_FLEET_HEALTH_S", "float", "`0.5`",
         "fleet supervision cadence: every tick the router reaps dead "
         "replica processes, polls `/healthz`, reads heartbeat stamps, "
         "and runs the wedge-conviction bookkeeping"),
    Knob("CNMF_TPU_FLEET_WEDGE_POLLS", "int", "`3`",
         "wedge conviction threshold: a replica whose `/healthz` fails "
         "this many CONSECUTIVE ticks while its heartbeat is stale or "
         "absent is convicted as wedged (alive-but-unresponsive), "
         "SIGKILLed, and respawned — one failed poll on a busy replica "
         "never convicts"),
    Knob("CNMF_TPU_FLEET_RESPAWNS", "int", "`3`",
         "respawn budget per replica slot: each death schedules a "
         "respawn after the launcher's deterministic exponential "
         "backoff (`CNMF_TPU_WORKER_BACKOFF_S` base) until the budget "
         "is exhausted, after which the slot stays down and its "
         "tenants remain failed over to the survivors"),
    Knob("CNMF_TPU_FLEET_WARM_TIMEOUT_S", "float", "`300`",
         "rollover warm budget: `POST /rollover` spawns a fresh "
         "replica set against the new reference and waits up to this "
         "long for every one to answer `/healthz` before any traffic "
         "moves; on timeout (or a fresh replica dying) the new set is "
         "killed and the old generation keeps serving untouched"),
    Knob("CNMF_TPU_FLEET_TENANT_QPS", "float", "`0` (off)",
         "per-tenant token-bucket admission rate at the router "
         "(requests/s): a tenant exceeding its bucket sheds with HTTP "
         "429 BEFORE consuming replica queue space, so one hot tenant "
         "cannot starve the fleet; `0` disables quota admission"),
    Knob("CNMF_TPU_FLEET_TENANT_BURST", "float", "`0` (auto)",
         "token-bucket burst capacity per tenant; `0` defaults to "
         "`2x` the rate (one second of headroom on top of sustained "
         "`CNMF_TPU_FLEET_TENANT_QPS`)"),
    Knob("CNMF_TPU_FLEET_RETRIES", "int", "`2`",
         "router failover retries per request on TRANSPORT errors "
         "(replica died mid-request, connect refused): the retry "
         "carries the same idempotency id to the next consistent-hash "
         "candidate, so a request that actually solved is never solved "
         "twice; replica-side verdicts (shed/poison/quarantine) pass "
         "through without retry"),
    Knob("CNMF_TPU_FLEET_HEDGE_MS", "float", "`0` (off)",
         "tail hedging: when the primary replica has not replied after "
         "this many milliseconds the router launches ONE duplicate "
         "attempt (same idempotency id) on the next candidate and "
         "takes whichever answers first — bounds the p99 paid for a "
         "momentarily slow replica; `0` disables hedging"),
    Knob("CNMF_TPU_FLEET_REPLICA_TELEMETRY", "flag", "`0`",
         "`1` leaves telemetry ON inside fleet replica subprocesses "
         "(their per-replica events land in `<name>.r<ordinal>.events."
         "jsonl`). Default off: the router's own event stream already "
         "carries per-request outcomes, and N replicas would otherwise "
         "multi-count `serve_request` in `cnmf-tpu report`"),
    # -- observability ----------------------------------------------------
    Knob("CNMF_TPU_TELEMETRY", "flag", "`0`",
         "`1` enables the structured run-telemetry event log "
         "(`<run>/cnmf_tmp/<name>.events.jsonl`): manifest, dispatch "
         "decisions, stage walls, per-replicate solver convergence "
         "records, stream stats, device-memory watermarks — rendered by "
         "`cnmf-tpu report`. Off = zero ops added to the jitted solvers "
         "and no file I/O"),
    Knob("CNMF_TPU_PROFILE_DIR", "str", "unset",
         "per-stage `jax.profiler` traces into this directory"),
    Knob("CNMF_TPU_METRICS", "flag", "`0`",
         "`1` enables the live metrics plane (`obs/metrics.py`): the "
         "process-local counter/gauge/histogram registry records, "
         "`GET /metrics` on the serve daemon and the object-store "
         "server exposes it as text, and `metrics_snapshot` telemetry "
         "events carry the registry state into the run JSONL. Off = "
         "every publication site is a no-op and compiled programs are "
         "byte-identical"),
    Knob("CNMF_TPU_TRACE_SAMPLE", "float", "`0`",
         "distributed-trace sampling probability in [0, 1] "
         "(`obs/tracing.py`): sampled requests carry an `X-CNMF-Trace` "
         "header client->daemon (and `CNMF_TPU_TRACE_CTX` launcher "
         "parent->worker), each hop landing as a `span` telemetry "
         "event; `cnmf-tpu trace <run_dir>` renders the waterfalls. "
         "The keep/drop decision is deterministic in the trace id, so "
         "every process agrees; `0` (default) disables tracing"),
    Knob("CNMF_TPU_TRACE_CTX", "str", "unset",
         "serialized `trace_id:span_id` context a launcher parent "
         "plants in worker env so batch-run spans stitch into one "
         "trace — set by the launcher when sampling engages, not "
         "normally set by hand"),
    Knob("CNMF_TPU_SLO_P99_MS", "float", "`0` (off)",
         "arms the serve daemon's sliding-window SLO tracker "
         "(`obs/slo.py`) with this target p99 latency in ms: the "
         "verdict (burning or not) is surfaced in `/metrics`, "
         "`/healthz` (`degraded: true` while burning), periodic "
         "`metrics_snapshot` events, and the report's SLO section"),
    Knob("CNMF_TPU_SLO_WINDOW_S", "float", "`300`",
         "SLO evaluation window in seconds: only requests completing "
         "within the last window count toward the p99/error-rate "
         "verdict (an observation exactly one window old has just "
         "aged out)"),
    Knob("CNMF_TPU_PERF_MODEL", "flag", "`0`",
         "`1` emits `perf_model` telemetry events (`obs/costmodel.py`): "
         "per-stage/per-kernel-lane analytic flop/byte/collective "
         "predictions from the ExecutionPlan joined with the measured "
         "walls — achieved MFU, bandwidth fraction, and the compute- "
         "vs memory-bound roofline verdict rendered by `cnmf-tpu "
         "report`. Host-side accounting only: compiled programs are "
         "byte-identical either way (requires telemetry to be on to "
         "land anywhere)"),
    Knob("CNMF_TPU_PERF_GATE_BAND", "float", "`0.6`",
         "relative band a comparable bench metric must move past "
         "before `cnmf-tpu benchdiff` / scripts/perf_gate.py flags a "
         "regression: generous by default for oversubscribed CI "
         "containers whose honest walls wobble; tighten on calm "
         "dedicated hardware"),
    Knob("CNMF_TPU_PERF_GATE_N", "int", "`3`",
         "perf-gate sample count: gate walls are measured N times and "
         "compared min-of-N (the low-noise estimator under scheduler "
         "interference)"),
    # -- fault tolerance ---------------------------------------------------
    Knob("CNMF_TPU_MAX_RETRIES", "int", "`2`",
         "retry budget per unhealthy (nonfinite) replicate: each attempt "
         "re-runs the lane with the derived seed `seed XOR attempt`; `0` "
         "quarantines immediately"),
    Knob("CNMF_TPU_MIN_HEALTHY_FRAC", "float", "`0.8`",
         "per-K survival floor: factorize degrades gracefully (quarantined "
         "replicates excluded from combine) while at least this fraction "
         "of a K's replicates end healthy, and hard-fails with a clear "
         "error below it. Evaluated over each worker's own ledger shard "
         "(workers can't see each other's outcomes); with many thin "
         "shards size it against the per-shard replicate count"),
    Knob("CNMF_TPU_FAULT_SPEC", "str", "unset",
         "deterministic fault injection (`runtime/faults.py`), e.g. "
         "`nonfinite:k=5,iter=2;kill:stage=factorize,worker=1;"
         "torn:artifact=iter_` — NaN lanes, worker SIGKILL, torn "
         "artifacts, failed uploads, stalls; every hook is a no-op when "
         "unset"),
    Knob("CNMF_TPU_WORKER_TIMEOUT", "float", "`0` (off)",
         "per-worker wall timeout in seconds for the subprocess launcher "
         "engine; an over-budget worker is killed (and respawned, below)"),
    Knob("CNMF_TPU_WORKER_RESPAWNS", "int", "`1`",
         "how many times the launcher respawns a dead/timed-out worker "
         "onto its unfinished ledger shard (`--skip-completed-runs`) "
         "before falling back to skip-missing combine"),
    Knob("CNMF_TPU_WORKER_BACKOFF_S", "float", "`0.5`",
         "respawn backoff base: attempt N waits `base * 2^(N-1)` seconds"),
    # -- elastic degraded-mesh execution ----------------------------------
    Knob("CNMF_TPU_ELASTIC", "flag", "`1`",
         "elastic degraded-mode execution: after a host/device loss the "
         "rowshard and 2-D factorize paths re-plan a smaller mesh over "
         "the surviving devices, re-stage X, and resume in-flight "
         "replicates from their pass checkpoints; the launcher "
         "additionally lets the idle fleet adopt a dead or straggling "
         "worker's shard (work-stealing). `0` restores abort-and-relaunch"),
    Knob("CNMF_TPU_HEARTBEAT_S", "float", "`0` (off)",
         "mesh-participant liveness interval: each process/worker stamps "
         "an atomic heartbeat file (pass cursor included) at pass/stage "
         "boundaries; barrier timeouts and straggler containment then "
         "name the silent culprit (index, last-beat age, pass) instead "
         "of a generic timeout. A peer is presumed dead after 3x this "
         "interval"),
    Knob("CNMF_TPU_STRAGGLER_S", "float", "`0` (off)",
         "launcher straggler grace (elastic layer; needs "
         "`CNMF_TPU_HEARTBEAT_S` — conviction is evidence-based): a "
         "worker whose run exceeds the longest clean finisher's wall "
         "time by this many seconds AND whose heartbeat is stale (older "
         "than max(grace, 3× heartbeat interval)) is killed and its "
         "shard adopted by the fleet — containment before a slow shard "
         "wedges the sweep. Clocks start at each process's own spawn, "
         "so adoptions redoing a full shard get a full allowance; a "
         "worker stamping liveness on schedule is never convicted"),
    Knob("CNMF_TPU_MIN_DEVICES", "int", "`1`",
         "degraded-mesh floor: elastic continuation refuses to shrink "
         "below this many surviving devices and re-raises the loss "
         "(clean, checkpoint-resumable abort) instead"),
    # -- testing / sanitizers ---------------------------------------------
    Knob("CNMF_TPU_SANITIZE", "flag", "`0`",
         "`1` wraps the designated tier-1 solver subset in "
         "`jax.transfer_guard(\"disallow\")` + NaN debugging "
         "(`tests/conftest.py`): an implicit host transfer or a NaN "
         "escaping a jitted hot path fails the test instead of silently "
         "costing a sync"),
    # -- multi-host coordinates -------------------------------------------
    Knob("CNMF_COORDINATOR_ADDRESS", "str", "unset",
         "multi-host pod coordinate: coordinator `host:port` (set all "
         "three together)"),
    Knob("CNMF_NUM_PROCESSES", "int", "unset",
         "multi-host pod coordinate: total process count (set all three "
         "together)"),
    Knob("CNMF_PROCESS_ID", "int", "unset",
         "multi-host pod coordinate: this process's id (set all three "
         "together)"),
    Knob("CNMF_SIM_CPU_DEVICES", "int", "unset",
         "simulate an N-device CPU pod host (launcher/tests)"),
    # -- upstream JAX variables we respect (not ours to document) ---------
    Knob("JAX_COMPILATION_CACHE_DIR", "str", "unset",
         "user-configured persistent compile cache wins over ours",
         documented=False),
    Knob("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "str", "unset",
         "user-configured cache threshold wins over ours",
         documented=False),
    Knob("JAX_CPU_COLLECTIVES_IMPLEMENTATION", "str", "unset",
         "user-selected CPU collectives implementation wins over gloo",
         documented=False),
    Knob("JAX_THREEFRY_PARTITIONABLE", "str", "on (package default)",
         "the packed K-selection's bit-parity needs the partitionable "
         "threefry; pinning `0` makes the packed entry points fail fast "
         "instead of silently diverging"),
)


def _raw(name: str) -> str | None:
    if name not in REGISTRY:
        raise ValueError(
            f"env knob {name!r} is not registered; declare it in "
            "cnmf_torch_tpu/utils/envknobs.py (name/type/default/doc) so "
            "the lint gate and the README knob table stay complete")
    return os.environ.get(name)


def env_int(name: str, default: int | None,
            lo: int | None = None, hi: int | None = None) -> int | None:
    """Parse an integer knob: empty/unset -> ``default``; non-numeric or
    outside ``[lo, hi]`` raises ``ValueError`` naming the knob."""
    raw = _raw(name)
    raw = (raw or "").strip()
    if not raw:
        return default
    try:
        val = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer")
    if lo is not None and val < lo:
        raise ValueError(f"{name}={raw!r}: must be >= {lo}")
    if hi is not None and val > hi:
        raise ValueError(f"{name}={raw!r}: must be <= {hi}")
    return val


def env_float(name: str, default: float | None,
              lo: float | None = None,
              hi: float | None = None) -> float | None:
    """Parse a float knob with the same strictness as :func:`env_int`."""
    raw = _raw(name)
    raw = (raw or "").strip()
    if not raw:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number")
    if lo is not None and val < lo:
        raise ValueError(f"{name}={raw!r}: must be >= {lo}")
    if hi is not None and val > hi:
        raise ValueError(f"{name}={raw!r}: must be <= {hi}")
    return val


def env_str(name: str, default: str = "") -> str:
    """Read a string knob verbatim; unset -> ``default``."""
    raw = _raw(name)
    return default if raw is None else raw


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob: unset/empty -> ``default``; ``0/false/off/no`` (any
    case) -> False; anything else -> True."""
    raw = _raw(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in _FALSE_WORDS


def env_is_set(name: str) -> bool:
    """True when the knob is present in the environment (even empty) —
    the "an explicit user pin wins" predicate."""
    if name not in REGISTRY:
        raise ValueError(
            f"env knob {name!r} is not registered; declare it in "
            "cnmf_torch_tpu/utils/envknobs.py")
    return name in os.environ


def pin_knob(name: str, value) -> None:
    """Set a registered knob's process-environment value — the execution
    planner's replay mechanism (``runtime/planner.py:apply_plan``): a
    loaded ``--plan`` pins each dispatch knob so every scattered
    consumer resolves the recorded decision. Lives here (the env owner)
    so no other module writes ``os.environ`` for knobs."""
    if name not in REGISTRY:
        raise ValueError(
            f"env knob {name!r} is not registered; declare it in "
            "cnmf_torch_tpu/utils/envknobs.py")
    os.environ[name] = str(value)


# ---------------------------------------------------------------------------
# canonical README table
# ---------------------------------------------------------------------------

TABLE_HEADER = ("| knob | default | what it does |", "|---|---|---|")


def knob_table() -> str:
    """The canonical markdown "Environment knobs" table, generated from
    the registry (``cnmf-tpu lint --knob-table``). The README's table must
    match it byte-for-byte — the lint gate's doc-drift rule compares both
    directions, so regenerate with this instead of hand-editing."""
    lines = list(TABLE_HEADER)
    for k in REGISTRY.values():
        if k.documented:
            lines.append(f"| `{k.name}` | {k.default} | {k.doc} |")
    return "\n".join(lines)


def parse_knob_table(text: str) -> dict[str, tuple[str, str]]:
    """Parse a markdown knob table (README or :func:`knob_table` output)
    into ``{name: (default_cell, doc_cell)}``. Rows are ``| `NAME` |
    default | doc |``; non-table lines and the header are ignored."""
    import re

    # non-greedy name/default cells, greedy doc cell: a doc that contains
    # a literal `|` still parses (only name/default cells must be `|`-free,
    # which the knob kinds guarantee)
    row_re = re.compile(r"^\| (.+?) \| (.+?) \| (.+) \|$")
    out: dict[str, tuple[str, str]] = {}
    for line in text.splitlines():
        m = row_re.match(line.strip())
        if not m:
            continue
        name_cell, default_cell, doc_cell = (c.strip() for c in m.groups())
        if name_cell in ("knob", "Variable"):
            continue
        for name in re.findall(r"`((?:CNMF|JAX)[A-Z0-9_]*)`", name_cell):
            out[name] = (default_cell, doc_cell)
    return out
