"""Replicated serving fleet tests (ISSUE 20): consistent-hash ring
stability, per-tenant admission quotas, idempotent retry (at-most-once
solve), router failover/respawn/quarantine against fake replicas,
drain-then-swap rollover ordering, the daemon's shutdown drain (no
accepted request is ever lost), and the fleet CLI surface."""

import json
import os
import threading
import time

import numpy as np
import pytest

from cnmf_torch_tpu.ops.nmf import fit_h
from cnmf_torch_tpu.serving import (
    ProjectionService,
    ResidentReference,
    ServeClient,
    ServeDaemon,
)
from cnmf_torch_tpu.serving.fleet import (
    FleetClient,
    FleetDaemon,
    FleetRouter,
    HashRing,
    TokenBucket,
)

K, G = 6, 90


def _reference(beta=2.0, chunk_size=5000, seed=0, g=G, k=K, **kw):
    rng = np.random.default_rng(seed)
    W = rng.gamma(0.3, 1.0, size=(k, g)).astype(np.float32)
    return ResidentReference(W, beta=beta, chunk_size=chunk_size,
                             chunk_max_iter=150, h_tol=0.05, l1_H=0.0,
                             **kw)


def _query(ref, n, seed):
    rng = np.random.default_rng(seed)
    u = rng.dirichlet(np.ones(ref.k) * 0.3, size=n)
    return (u @ ref.W * 40.0
            + rng.random((n, ref.n_genes)) * 0.01).astype(np.float32)


def _solo(ref, X, H_init=None):
    return fit_h(X, ref.W, H_init=H_init, chunk_size=ref.chunk_size,
                 chunk_max_iter=ref.chunk_max_iter, h_tol=ref.h_tol,
                 l1_reg_H=ref.l1_H, l2_reg_H=0.0, beta=ref.beta)


# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------

def test_hashring_spread_and_route_stability():
    ring = HashRing([0, 1, 2, 3])
    tenants = [f"tenant-{i}" for i in range(4000)]
    before = {t: ring.route(t) for t in tenants}
    counts = {n: sum(1 for v in before.values() if v == n)
              for n in range(4)}
    # even-ish spread: no replica owns more than ~2x its fair share
    assert all(200 < c < 2000 for c in counts.values()), counts
    ring.remove(2)
    after = {t: ring.route(t) for t in tenants}
    # THE consistent-hashing property: removing a node remaps ONLY the
    # tenants it owned — every other tenant keeps its warm replica
    moved = [t for t in tenants if before[t] != after[t]]
    assert len(moved) == counts[2]
    assert all(before[t] == 2 for t in moved)
    # adding it back restores the exact original assignment
    ring.add(2)
    assert {t: ring.route(t) for t in tenants} == before


def test_hashring_candidates_are_the_failover_order():
    ring = HashRing(["a", "b", "c"])
    for tenant in ("acme", "globex", "initech"):
        cands = ring.candidates(tenant)
        assert cands[0] == ring.route(tenant)
        assert sorted(cands) == ["a", "b", "c"]  # all nodes, no dupes
    assert HashRing().candidates("x") == []
    assert HashRing().route("x") is None


# ---------------------------------------------------------------------------
# token-bucket admission
# ---------------------------------------------------------------------------

def test_token_bucket_accounting():
    now = [0.0]
    tb = TokenBucket(rate=2.0, burst=4.0, clock=lambda: now[0])
    assert [tb.allow() for _ in range(5)] == [True] * 4 + [False]
    now[0] += 1.0  # 2 tokens refill at rate=2/s
    assert [tb.allow() for _ in range(3)] == [True, True, False]
    now[0] += 100.0  # refill caps at burst, not 200 tokens
    assert [tb.allow() for _ in range(5)] == [True] * 4 + [False]
    # burst defaults to 2x rate
    assert TokenBucket(rate=3.0).burst == 6.0


# ---------------------------------------------------------------------------
# idempotent request ids: at-most-once solve on the real service
# ---------------------------------------------------------------------------

def test_idempotent_request_id_solves_once():
    ref = _reference()
    with ProjectionService(ref, max_batch=4, linger_ms=5.0,
                           warm_start=False) as svc:
        X = _query(ref, 17, 5)
        H1, meta1 = svc.project(X, request_id="rid-1")
        H2, meta2 = svc.project(X, request_id="rid-1")  # router retry
        assert np.array_equal(H1, _solo(ref, X))
        assert np.array_equal(H1, H2)
        stats = svc.stats()
        # ONE solve, one dedup hit — the retry never re-entered the queue
        assert stats["ok"] == 1
        assert stats["deduped"] == 1
        # a different id is a different request
        H3, _ = svc.project(X, request_id="rid-2")
        assert np.array_equal(H3, H1)
        assert svc.stats()["ok"] == 2


def test_idempotent_ids_do_not_cross_tenants_or_leak_unbounded():
    ref = _reference()
    with ProjectionService(ref, max_batch=4, linger_ms=2.0,
                           warm_start=False) as svc:
        X = _query(ref, 9, 6)
        svc.project(X, tenant="a", request_id="r-a")
        assert len(svc._idem) == 1
        # no id -> no claim kept
        svc.project(X, tenant="a")
        assert len(svc._idem) == 1


# ---------------------------------------------------------------------------
# daemon shutdown drain: no accepted request is ever lost (satellite 1)
# ---------------------------------------------------------------------------

def test_daemon_shutdown_drains_every_accepted_request(tmp_path):
    """The pin for the drain fix: requests sitting in the batcher's
    linger window when the daemon is told to stop must ALL complete with
    their correct usage matrices — close() previously tore the service
    down under them."""
    ref = _reference()
    svc = ProjectionService(ref, max_batch=8, linger_ms=300.0,
                            warm_start=False)
    sock = str(tmp_path / "drain.sock")
    daemon = ServeDaemon(svc, socket_path=sock).start()
    n_req = 5
    results = [None] * n_req
    errors = []

    def worker(i):
        try:
            X = _query(ref, 16 + i, 100 + i)
            H, _ = ServeClient(socket_path=sock).project(X)
            results[i] = (X, H)
        except Exception as exc:  # pragma: no cover - the failure mode
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_req)]
    for t in threads:
        t.start()
    # wait until every request is ACCEPTED (inflight counts at the
    # accept loop), i.e. all five are inside the linger window
    deadline = time.monotonic() + 10.0
    while daemon.server.inflight < n_req:
        assert time.monotonic() < deadline, "requests never accepted"
        time.sleep(0.005)
    daemon.close()  # must drain, not drop
    for t in threads:
        t.join(timeout=60)
    assert not errors
    for X, H in results:
        assert H is not None
        assert np.array_equal(H, _solo(ref, X))
    assert not os.path.exists(sock)  # no orphaned socket either


# ---------------------------------------------------------------------------
# the router, against fake replicas
# ---------------------------------------------------------------------------

class _Events:
    def __init__(self):
        self.emitted = []

    def emit(self, event_type, **fields):
        self.emitted.append((event_type, fields))

    def of(self, event_type):
        return [f for t, f in self.emitted if t == event_type]


class FakeReplica:
    """In-process stand-in for SubprocessReplica: same duck interface,
    scripted behavior, shared append-only log for ordering assertions."""

    def __init__(self, slot, ordinal, generation, spectra, log,
                 behavior=None):
        self.slot, self.ordinal = slot, ordinal
        self.generation, self.spectra_path = generation, spectra
        self.log = log
        self.behavior = dict(behavior or {})
        self.requests = 0
        self.pid = 40000 + ordinal
        self._alive = False
        self.gate = None  # optional Event a /project blocks on

    def start(self):
        if self.behavior.get("fail_start"):
            raise OSError("spawn failed")
        self._alive = True
        self.log.append(("start", self.ordinal))
        return self

    def alive(self):
        return self._alive

    def uptime_s(self):
        return 1.0

    def kill(self, wedge=False):
        if wedge:
            self.behavior["wedged"] = True
        else:
            self._alive = False

    def reap(self, timeout=0.0):
        pass

    def healthz(self, timeout=0.0):
        if not self._alive or self.behavior.get("wedged"):
            raise OSError("no reply")
        return {"ok": True}

    def heartbeat_age(self):
        if self.behavior.get("wedged"):
            return None  # stamp went stale/absent
        return 0.0

    def forward(self, method, path, body=None, headers=None,
                timeout=0.0):
        if not self._alive or self.behavior.get("wedged"):
            raise ConnectionRefusedError("replica down")
        self.log.append((f"{method} {path}", self.ordinal))
        if self.gate is not None:
            self.gate.wait(timeout=30)
        reply = self.behavior.get("project")
        if reply is not None:
            status, payload = reply
            return status, json.dumps(payload).encode()
        return 200, json.dumps(
            {"ok": True, "status": "ok", "usage": [[1.0] * K],
             "meta": {"generation": self.generation}}).encode()

    def shutdown(self, grace_s=60.0):
        self.log.append(("shutdown", self.ordinal))
        self._alive = False

    def _cleanup(self):
        pass


def _fake_router(log, events=None, replicas=2, behaviors=None, **kw):
    behaviors = behaviors or {}

    def factory(slot, ordinal, generation, spectra):
        return FakeReplica(slot, ordinal, generation, spectra, log,
                           behavior=behaviors.get(generation))

    return FleetRouter(replicas=replicas, replica_factory=factory,
                       events=events, **kw)


def _body(tenant, request_id=None, n=3):
    payload = {"tenant": tenant, "data": [[0.5] * G] * n}
    if request_id is not None:
        payload["request_id"] = request_id
    return json.dumps(payload).encode()


def test_router_routes_and_accounts(monkeypatch):
    log, ev = [], _Events()
    router = _fake_router(log, events=ev).start(supervise=False)
    try:
        status, blob = router.handle_project(_body("acme"), {})
        assert status == 200
        assert json.loads(blob)["status"] == "ok"
        # same tenant -> same replica (warm cache locality)
        router.handle_project(_body("acme"), {})
        served = [o for op, o in log if op == "POST /project"]
        assert len(served) == 2 and served[0] == served[1]
        st = router.stats()
        assert st["ok"] == 2 and st["requests"] == 2
        reqs = ev.of("serve_request")
        assert len(reqs) == 2
        assert all(r["status"] == "ok" and "replica" in r for r in reqs)
    finally:
        router.close()


def test_router_tenant_quota_sheds_before_forwarding(monkeypatch):
    # burst auto-sizes to max(1, 2*rate) = 1 token: the second
    # back-to-back request from one tenant is shed at admission
    monkeypatch.setenv("CNMF_TPU_FLEET_TENANT_QPS", "0.001")
    log = []
    router = _fake_router(log).start(supervise=False)
    try:
        assert router.handle_project(_body("hot"), {})[0] == 200
        status, reply = router.handle_project(_body("hot"), {})
        assert status == 429
        assert reply["status"] == "shed"
        # the shed request never consumed replica queue space
        assert len([1 for op, _ in log if op == "POST /project"]) == 1
        # quotas are PER tenant: another tenant still gets through
        assert router.handle_project(_body("cold"), {})[0] == 200
        assert router.stats()["shed"] == 1
    finally:
        router.close()


def test_router_fleet_scoped_poison_quarantine():
    log = []
    poison = {"project": (422, {"ok": False, "status": "poison",
                                "error": "NaN input"})}
    router = _fake_router(log, behaviors={0: poison}).start(
        supervise=False)
    try:
        for _ in range(3):  # three strikes, counted AT THE ROUTER
            status, _reply = router.handle_project(_body("toxic"), {})
            assert status == 422
        status, reply = router.handle_project(_body("toxic"), {})
        assert status == 403
        assert reply["status"] == "quarantined"
        # the 4th request was refused at admission, not forwarded
        assert len([1 for op, _ in log if op == "POST /project"]) == 3
        assert "toxic" in router.stats()["quarantined_tenants"]
    finally:
        router.close()


def test_router_failover_retry_is_idempotent_and_respawns():
    log, ev = [], _Events()
    router = _fake_router(log, events=ev).start(supervise=False)
    try:
        status, blob = router.handle_project(
            _body("acme", request_id="rid-9"), {})
        assert status == 200
        home = [o for op, o in log if op == "POST /project"][-1]
        # SIGKILL the tenant's home replica
        victim = next(s for s in router._slots
                      if s.replica.ordinal == home)
        victim.replica.kill()
        # the router retries the SAME request id on a survivor — the
        # idempotency header makes that retry at-most-once end to end
        status, blob = router.handle_project(
            _body("acme", request_id="rid-9"), {})
        assert status == 200
        survivor = [o for op, o in log if op == "POST /project"][-1]
        assert survivor != home
        assert router.stats()["retries"] >= 1
        # supervision notices the corpse: ring shrinks, events emitted
        router._tick()
        assert len(router._ring) == 1
        deaths = ev.of("replica_death")
        assert deaths and deaths[0]["reason"] == "exit"
        assert deaths[0]["replica"] == victim.index
        fo = ev.of("failover")
        assert fo and fo[0]["survivors"] == 1
        # ...and respawns within budget: due -> spawn -> healthy -> ring
        victim.down_until = 0.0
        router._tick()  # spawns (warming, not yet in ring)
        router._tick()  # first healthy poll joins the ring
        assert len(router._ring) == 2
        assert victim.replica.ordinal != home  # a NEW ordinal, new node
    finally:
        router.close()


def test_router_wedge_conviction_needs_both_evidence_kinds(monkeypatch):
    monkeypatch.setenv("CNMF_TPU_FLEET_WEDGE_POLLS", "2")
    log, ev = [], _Events()
    router = _fake_router(log, events=ev).start(supervise=False)
    try:
        slot = router._slots[0]
        slot.replica.behavior["wedged"] = True  # SIGSTOP profile
        router._tick()  # strike 1: healthz failed, heartbeat stale
        assert len(router._ring) == 2  # not convicted yet
        router._tick()  # strike 2: convicted, killed, failed over
        assert len(router._ring) == 1
        deaths = ev.of("replica_death")
        assert deaths and deaths[0]["reason"] == "wedge"
    finally:
        router.close()


def test_router_respawn_budget_exhausts(monkeypatch):
    monkeypatch.setenv("CNMF_TPU_FLEET_RESPAWNS", "0")
    log, ev = [], _Events()
    router = _fake_router(log, events=ev).start(supervise=False)
    try:
        router._slots[0].replica.kill()
        router._tick()
        assert len(router._ring) == 1
        router._tick()  # budget 0: no respawn attempt
        assert router._slots[0].replica is None
        reasons = [d["reason"] for d in ev.of("replica_death")]
        assert "respawns_exhausted" in reasons
    finally:
        router.close()


def test_router_all_replicas_down_is_503_not_a_hang():
    log = []
    router = _fake_router(log).start(supervise=False)
    try:
        for slot in router._slots:
            slot.replica.kill()
        status, reply = router.handle_project(_body("acme"), {})
        assert status == 503
        assert reply["status"] == "error"
    finally:
        router.close()


# ---------------------------------------------------------------------------
# rollover: drain-then-swap ordering, zero downtime
# ---------------------------------------------------------------------------

def test_rollover_orders_warm_swap_drain_and_updates_respawn_ref():
    log, ev = [], _Events()
    router = _fake_router(log, events=ev,
                          spectra_path="v1.df.npz").start(supervise=False)
    try:
        gen0 = {s.replica.ordinal for s in router._slots}
        # hold one in-flight request on the OLD generation across the
        # whole rollover: it must complete, not be torn down
        victim = router._slots[0].replica
        victim.gate = threading.Event()
        inflight = {}

        def old_request():
            tenant = next(t for t in (f"t{i}" for i in range(64))
                          if router._ring.route(t) == victim.ordinal)
            inflight["reply"] = router.handle_project(_body(tenant), {})

        t = threading.Thread(target=old_request)
        t.start()
        deadline = time.monotonic() + 5.0
        while not any(op == "POST /project" for op, _ in log):
            assert time.monotonic() < deadline
            time.sleep(0.005)

        status, reply = router.handle_rollover({"spectra": "v2.df.npz"})
        assert status == 200 and reply["generation"] == 1

        # ordering: every NEW replica started before any OLD replica was
        # told to shut down (warm first, swap, then drain the old set)
        starts_gen1 = [i for i, (op, o) in enumerate(log)
                       if op == "start" and o not in gen0]
        shutdowns_gen0 = [i for i, (op, o) in enumerate(log)
                          if op == "shutdown" and o in gen0]
        assert len(starts_gen1) == 2 and len(shutdowns_gen0) == 2
        assert max(starts_gen1) < min(shutdowns_gen0)

        # the held old-generation request still completes (drain, not
        # drop) — zero downtime means IT never observed the swap
        victim.gate.set()
        t.join(timeout=30)
        assert inflight["reply"][0] == 200

        # new requests land on generation 1, and a future death-respawn
        # would load the NEW reference
        status, blob = router.handle_project(_body("anyone"), {})
        assert json.loads(blob)["meta"]["generation"] == 1
        assert router._spectra_path == "v2.df.npz"
        roll = ev.of("rollover")
        assert roll and roll[0]["generation"] == 1
        assert roll[0]["wall_s"] >= 0
    finally:
        router.close()


def test_rollover_warm_failure_leaves_old_generation_serving():
    log = []
    router = _fake_router(
        log, spectra_path="v1.df.npz",
        behaviors={1: {"fail_start": True}}).start(supervise=False)
    try:
        status, reply = router.handle_rollover({"spectra": "v2.df.npz"})
        assert status == 500
        assert "old reference still serving" in reply["error"]
        assert router._generation == 0
        assert router._spectra_path == "v1.df.npz"
        assert len(router._ring) == 2  # untouched
        assert router.handle_project(_body("acme"), {})[0] == 200
    finally:
        router.close()


def test_rollover_rejects_concurrent_and_malformed():
    log = []
    router = _fake_router(log).start(supervise=False)
    try:
        assert router.handle_rollover({})[0] == 400
        router._rollover_lock.acquire()
        try:
            assert router.handle_rollover(
                {"spectra": "x.df.npz"})[0] == 409
        finally:
            router._rollover_lock.release()
    finally:
        router.close()


# ---------------------------------------------------------------------------
# the fleet daemon over HTTP (fake replicas, real router + handler)
# ---------------------------------------------------------------------------

def test_fleet_daemon_http_surface(tmp_path):
    log = []
    router = _fake_router(log, spectra_path="v1.df.npz")
    sock = str(tmp_path / "fleet.sock")
    daemon = FleetDaemon(router, socket_path=sock)
    router.start(supervise=False)
    thread = threading.Thread(target=daemon.server.serve_forever,
                              daemon=True)
    daemon._thread = thread
    thread.start()
    try:
        cli = FleetClient(socket_path=sock)
        hz = cli.healthz()
        assert hz["ok"] and hz["replicas_up"] == 2
        H, meta = cli.project(np.ones((1, G), np.float32),
                              tenant="acme", request_id="rid-http")
        assert H.shape == (1, K)
        st = cli.stats()
        assert st["ok"] == 1 and st["generation"] == 0
        out = cli.rollover("v2.df.npz")
        assert out["generation"] == 1
        assert cli.stats()["generation"] == 1
        assert cli.shutdown()
    finally:
        daemon.close()
    assert not os.path.exists(sock)


# ---------------------------------------------------------------------------
# CLI surface
# ---------------------------------------------------------------------------

def test_cli_fleet_argument_validation(tmp_path):
    from cnmf_torch_tpu.cli import main

    with pytest.raises(SystemExit) as exc:
        main(["fleet", str(tmp_path / "nope")])
    assert exc.value.code == 2
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    with pytest.raises(SystemExit) as exc:
        main(["fleet", str(run_dir), "--socket", "s.sock",
              "--port", "8080"])
    assert exc.value.code == 2
    with pytest.raises(SystemExit) as exc:
        main(["fleet", str(run_dir), "--replicas", "0"])
    assert exc.value.code == 2
    # stray positionals still fail fast for non-run_dir subcommands
    with pytest.raises(SystemExit) as exc:
        main(["consensus", "9"])
    assert exc.value.code == 2
