"""Cross-request micro-batching for the projection daemon (ISSUE 12).

Every projection request is a fixed-W usage refit — row-separable work:
``fit_h`` solves row chunks INDEPENDENTLY (``ops/nmf.py:_fit_h_chunked``
scans ``_chunk_h_solve`` with no cross-chunk carry). That independence is
what makes cross-request batching exact: each request becomes one or
more *lanes* (its solo chunk partition, ``chunk = min(online_chunk_size,
n)``), lanes zero-pad to a bucketed row count, and the whole batch runs
as ONE vmapped ``_chunk_h_solve`` dispatch against the resident
reference. Padding is benign by the same exact-zero-absorption argument
the packed K-selection relies on (zero X rows with zero H rows stay
exactly zero under every beta's MU step and contribute exact ``+0.0`` to
the chunk's convergence norm), and the H init is the solo draw's prefix
(:func:`~cnmf_torch_tpu.ops.nmf.fit_h_default_init`) — so every lane of
a batch is BIT-IDENTICAL to its solo ``refit_usage`` dispatch, pinned by
``tests/test_serving.py`` and the tier-1 serve smoke.

Layers:

  * :class:`MicroBatcher` — bounded admission queue + single dispatcher
    thread. The first queued request opens a batch; the dispatcher
    lingers up to ``CNMF_TPU_SERVE_LINGER_MS`` collecting batchmates (at
    most ``CNMF_TPU_SERVE_BATCH`` lanes), then launches. Requests older
    than ``CNMF_TPU_SERVE_TIMEOUT_S`` shed with a clear error (the
    launcher-supervision timeout adapted to request admission), and a
    full queue sheds immediately instead of building unbounded backlog.
  * :class:`ProjectionService` — the daemon core: resident reference,
    AOT-warmed program cache keyed by padded ``(lane_count, rows)``
    buckets, per-(tenant, matrix) usage warm starts, per-lane health
    grading (:func:`~cnmf_torch_tpu.ops.nmf.lane_health` — the PR-4
    grading) with tenant quarantine so one poison input cannot sink its
    batchmates or the daemon, and ``serve_request``/``serve_batch``
    telemetry.
"""

from __future__ import annotations

import functools
import itertools
import queue
import threading
import time

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import slo as obs_slo
from ..obs import tracing as obs_tracing
from ..runtime import faults
from ..utils.envknobs import env_flag, env_float, env_int, env_str

__all__ = [
    "ServeError",
    "ShedError",
    "PoisonError",
    "QuarantinedError",
    "resolve_buckets",
    "bucket_for",
    "lane_count",
    "ProjectionService",
]

# poison strikes before a tenant is quarantined (admission-rejected):
# mirrors the factorize retry budget's "repeated unhealthiness is a
# property of the input, not the run" stance (runtime/resilience.py)
POISON_QUARANTINE_STRIKES = 3

# bounded reservoir of per-request total latencies for stats()
_LATENCY_SAMPLES = 4096

# warm-start cache entries kept (LRU): one usage matrix per (tenant,
# matrix fingerprint) — bounds daemon host memory against tenant growth
_WARM_CACHE_ENTRIES = 256

# idempotency claims kept (FIFO eviction): request_id -> its _Request,
# so a router retry of an id that already solved (or graded poison)
# gets the ORIGINAL reply instead of a second solve — the fleet's
# at-most-once contract (ISSUE 20). Shed outcomes are forgotten on
# purpose: shed means "not done, retry", so the retry must re-enter
_IDEM_CACHE_ENTRIES = 1024


class ServeError(RuntimeError):
    """Base class for request-level serve failures (maps to a clear
    client-visible error, never a daemon crash)."""

    status = "error"


class ShedError(ServeError):
    """Admission shed: bounded queue full or deadline exceeded."""

    status = "shed"


class PoisonError(ServeError):
    """The request's lane graded unhealthy (nonfinite input or result).
    Batchmates are unaffected — lanes are independent."""

    status = "poison"


class QuarantinedError(ServeError):
    """Tenant exceeded the poison-strike budget; admission rejects."""

    status = "quarantined"


# ---------------------------------------------------------------------------
# shape buckets
# ---------------------------------------------------------------------------

def resolve_buckets(chunk_size: int, spec: str | None = None) -> tuple:
    """The padded-rows bucket schedule: parsed ``CNMF_TPU_SERVE_BUCKETS``
    entries below the run's chunk size, with the chunk size itself as the
    top bucket (a lane is never taller than one solo chunk)."""
    if spec is None:
        spec = env_str("CNMF_TPU_SERVE_BUCKETS", "64,256,1024")
    chunk_size = int(chunk_size)
    out = {chunk_size}
    for part in str(spec).split(","):
        part = part.strip()
        if not part:
            continue
        try:
            b = int(part)
        except ValueError:
            raise ValueError(
                f"CNMF_TPU_SERVE_BUCKETS={spec!r}: expected "
                f"comma-separated integers")
        if b < 1:
            raise ValueError(
                f"CNMF_TPU_SERVE_BUCKETS={spec!r}: buckets must be >= 1")
        if b < chunk_size:
            out.add(b)
    return tuple(sorted(out))


def bucket_for(n: int, buckets: tuple) -> int:
    """Smallest bucket >= n (buckets sorted ascending; the top bucket is
    the chunk size, and lanes never exceed it)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def lane_buckets(max_batch: int) -> tuple:
    """Power-of-two lane-count buckets up to (and including) the batch
    cap — the program cache's batch-axis schedule."""
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return tuple(sorted(set(out)))


def lane_count(n: int, chunk_size: int) -> int:
    """How many lanes (solo chunks) a request of ``n`` rows occupies."""
    chunk = min(int(chunk_size), int(n))
    return max(1, -(-int(n) // chunk))


# ---------------------------------------------------------------------------
# the batched device program
# ---------------------------------------------------------------------------

def _make_batched_project():
    import jax

    from ..ops.nmf import _chunk_h_solve

    @functools.partial(jax.jit,
                       static_argnames=("beta", "max_iter", "l1", "l2"))
    def _batched_project(Xb, Hb, W, WWT, w_colsum, h_tol, *, beta,
                         max_iter, l1, l2):
        """One vmapped usage solve over request lanes: each lane is the
        exact solo per-chunk program (``_chunk_h_solve`` with the same
        statics ``_fit_h_chunked`` uses), so lane results are
        bit-identical to solo dispatch; ``return_resid`` adds the
        per-lane convergence residual the host-side health grading
        reads (zero extra device ops on the H values). ``WWT`` (beta=2)
        / ``w_colsum`` (beta=1) are the reference's resident
        loop-invariant products — computed once per daemon by the same
        device ops the solo program derives them with (bit-equal)."""

        def lane(x, h):
            return _chunk_h_solve(x, h, W, WWT, beta, l1, l2, max_iter,
                                  h_tol, w_colsum=w_colsum,
                                  return_resid=True)

        return jax.vmap(lane)(Xb, Hb)

    return _batched_project


_batched_project = None
_batched_project_lock = threading.Lock()


def batched_project():
    """The lazily-built jitted batch program (module-level so every
    service instance shares ONE jit cache; jax imports stay off the
    module import path for jax-free consumers of the error types)."""
    global _batched_project
    with _batched_project_lock:
        if _batched_project is None:
            _batched_project = _make_batched_project()
        return _batched_project


# ---------------------------------------------------------------------------
# requests
# ---------------------------------------------------------------------------

_SENTINEL = object()
_req_ids = itertools.count(1)


class _Request:
    __slots__ = ("rid", "tenant", "X", "n", "h_init", "warm",
                 "t_enqueue", "t_dequeue", "event", "_rlock", "result",
                 "error", "meta", "trace", "request_id")

    def __init__(self, tenant: str, X: np.ndarray, h_init, warm: bool,
                 trace=None, request_id: str | None = None):
        self.rid = next(_req_ids)
        self.tenant = tenant
        self.X = X
        self.n = int(X.shape[0])
        self.h_init = h_init
        self.warm = warm
        self.t_enqueue = time.perf_counter()
        # stamped by the dispatcher when it pulls the request off the
        # queue: splits queue wait (enqueue->dequeue) from batch linger
        # (dequeue->launch) on the trace waterfall
        self.t_dequeue = None
        self.event = threading.Event()
        self._rlock = threading.Lock()
        self.result = None
        self.error = None
        self.meta: dict = {}
        # sampled trace context (obs/tracing.py) or None
        self.trace = trace
        # client-chosen idempotency key (or None): the service's dedup
        # map points ids at their original request so a retry waits on
        # the SAME event instead of enqueueing a second solve
        self.request_id = request_id

    def reply(self, result=None, error=None, **meta):
        # first reply wins: the dispatcher and the shutdown drain can
        # race on a request caught mid-close — the loser must not
        # overwrite a delivered result
        with self._rlock:
            if self.event.is_set():
                return
            self.result = result
            self.error = error
            self.meta.update(meta)
            self.event.set()

    def wait(self, timeout=None):
        if not self.event.wait(timeout):
            raise ShedError(
                f"request {self.rid}: no reply within {timeout} s (daemon "
                f"overloaded or gone)")
        if self.error is not None:
            raise self.error
        return self.result, self.meta


# ---------------------------------------------------------------------------
# the service
# ---------------------------------------------------------------------------

class ProjectionService:
    """Resident-reference projection with cross-request batching.

    Construct with a staged (or stageable)
    :class:`~cnmf_torch_tpu.serving.reference.ResidentReference`, call
    :meth:`start` (which stages the reference, AOT-warms the bucketed
    program cache, and starts the dispatcher), then :meth:`project`
    from any number of threads. :meth:`close` drains and stops.
    """

    def __init__(self, reference, *, max_batch: int | None = None,
                 linger_ms: float | None = None,
                 timeout_s: float | None = None,
                 buckets: str | None = None,
                 warm_start: bool | None = None,
                 events=None, liveness=None):
        self.reference = reference
        self.max_batch = (env_int("CNMF_TPU_SERVE_BATCH", 8, lo=1)
                          if max_batch is None else int(max_batch))
        linger = (env_float("CNMF_TPU_SERVE_LINGER_MS", 2.0, lo=0.0)
                  if linger_ms is None else float(linger_ms))
        self.linger_s = linger / 1000.0
        self.timeout_s = (env_float("CNMF_TPU_SERVE_TIMEOUT_S", 30.0,
                                    lo=0.0)
                          if timeout_s is None else float(timeout_s))
        self.warm_start = (env_flag("CNMF_TPU_SERVE_WARM_START", True)
                           if warm_start is None else bool(warm_start))
        self.buckets = resolve_buckets(reference.chunk_size, buckets)
        self.b_buckets = lane_buckets(self.max_batch)
        self.events = events
        self.liveness = liveness
        # bounded admission queue: beyond ~4 batches of backlog the
        # daemon sheds instead of queueing into timeout territory
        self._q: queue.Queue = queue.Queue(maxsize=4 * self.max_batch)
        self._thread: threading.Thread | None = None
        self._running = False
        self._lock = threading.Lock()
        # program cache bookkeeping: (b_pad, n_pad) -> warmed at startup?
        self._programs: dict = {}
        self._warmup_done = False
        # default-init cache: ONE grow-only uniform draw whose row prefix
        # serves every request size (fit_h_default_init's prefix
        # property) — avoids a device draw + fetch per request
        self._init_cache: np.ndarray | None = None
        # warm starts: (tenant, n) -> last healthy usage matrix (LRU)
        self._warm_cache: dict = {}
        # tenant poison strikes / quarantine
        self._strikes: dict = {}
        self._quarantined: set = set()
        # idempotency: request_id -> the original _Request (FIFO-bounded
        # at _IDEM_CACHE_ENTRIES); dedup hits wait on the original's
        # event, so one id solves at most once
        self._idem: dict = {}
        # counters
        self._stats = {
            "requests": 0, "ok": 0, "shed": 0, "poison": 0,
            "quarantined": 0, "error": 0, "deduped": 0, "batches": 0,
            "multi_request_batches": 0, "lanes_total": 0,
            "max_lanes": 0, "warm_started": 0,
            "cold_dispatches_after_warmup": 0,
        }
        self._latencies: list = []
        # latency-reservoir honesty (ISSUE 18): the reservoir halves
        # itself when full; count what it dropped so /stats and /metrics
        # state what the p99 was computed over instead of implying
        # all-time coverage
        self._lat_dropped = 0
        # sliding-window SLO tracker, armed by CNMF_TPU_SLO_P99_MS
        self._slo = obs_slo.tracker_from_env()
        # roofline accounting (ISSUE 19): per-dispatch analytic cost +
        # solve wall accumulated here, flushed as ONE perf_model event
        # at daemon shutdown (emit_perf_model)
        self._perf = {"solve_s": 0.0, "flops": 0.0, "bytes": 0.0,
                      "lanes": 0, "batches": 0}

    # -- lifecycle -----------------------------------------------------

    def start(self, warmup: bool = True):
        """Stage the reference device-resident, AOT-warm the program
        buckets, and start the dispatcher thread. Idempotent."""
        with self._lock:
            if self._running:
                return self
            # the lane builder's default-init prefix slicing
            # (_default_init) is only bit-compatible with solo
            # fit_h inits under the partitionable threefry (the
            # fit_h(k_pad=...) contract) — an explicit legacy-threefry
            # pin must refuse loudly, never serve silently-divergent
            # projections
            from ..utils.jax_compat import assert_threefry_partitionable

            assert_threefry_partitionable("cnmf-tpu serve")
            self.reference.stage(events=self.events)
            self._running = True
        if warmup:
            self.warmup()
        t = threading.Thread(target=self._dispatch_loop,
                             name="cnmf-serve-dispatch", daemon=True)
        self._thread = t
        t.start()
        return self

    def close(self):
        """Stop the dispatcher; queued requests get a clear shed error."""
        with self._lock:
            if not self._running:
                return
            self._running = False
        self._q.put(_SENTINEL)
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not _SENTINEL:
                self._idem_forget(req)
                req.reply(error=ShedError("daemon shutting down"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- warmup --------------------------------------------------------

    def warmup(self):
        """AOT-compile every (lane-count, rows) bucket program
        CONCURRENTLY (the replicate-sweep warmer's approach,
        ``parallel/replicates.py:warm_sweep_programs``: compiles release
        the GIL and populate the same jit cache the dispatch hits), then
        execute the budget-sized ones once on zeros so first dispatch
        pays no executable-upload cost either. After this returns, a
        steady-traffic daemon compiles nothing — cold dispatches are
        counted and reported by :meth:`stats`."""
        import jax
        import jax.numpy as jnp

        from ..parallel.replicates import run_warm_jobs

        ref = self.reference
        prog = batched_project()
        g, k = ref.n_genes, ref.k
        budget = env_int("CNMF_TPU_WARM_DUMMY_BUDGET_BYTES", 2 << 30,
                         lo=0)

        def warm_one(spec):
            b_pad, n_pad = spec
            xs = jax.ShapeDtypeStruct((b_pad, n_pad, g), jnp.float32)
            hs = jax.ShapeDtypeStruct((b_pad, n_pad, k), jnp.float32)
            ws = jax.ShapeDtypeStruct((k, g), jnp.float32)
            wwts = (jax.ShapeDtypeStruct((k, k), jnp.float32)
                    if ref.WWT is not None else None)
            cols = (jax.ShapeDtypeStruct((k,), jnp.float32)
                    if ref.w_colsum is not None else None)
            ts = jax.ShapeDtypeStruct((), jnp.float32)
            prog.lower(xs, hs, ws, wwts, cols, ts, beta=ref.beta,
                       max_iter=ref.chunk_max_iter, l1=ref.l1_H,
                       l2=0.0).compile()
            if b_pad * n_pad * g * 4 <= budget:
                # one real dispatch so the first request pays warm
                # dispatch cost, not executable upload (the consensus
                # warmers' lesson: AOT compile alone does not move the
                # program to a tunneled device)
                Xb = jnp.zeros((b_pad, n_pad, g), jnp.float32)
                Hb = jnp.zeros((b_pad, n_pad, k), jnp.float32)
                jax.block_until_ready(prog(
                    Xb, Hb, ref.Wd, ref.WWT, ref.w_colsum,
                    ref.h_tol_dev, beta=ref.beta,
                    max_iter=ref.chunk_max_iter, l1=ref.l1_H, l2=0.0))
            self._programs[spec] = True

        specs = [(b, n) for b in self.b_buckets for n in self.buckets]
        run_warm_jobs([functools.partial(warm_one, s) for s in specs],
                      swallow=False)
        self._warmup_done = True
        return len(specs)

    # -- admission -----------------------------------------------------

    def submit(self, X, tenant: str = "default", trace=None,
               request_id: str | None = None) -> _Request:
        """Validate + enqueue one projection request; returns the pending
        handle (``.wait()`` for the result). Raises ``ServeError``
        subclasses on admission failure. ``trace`` is an optional
        sampled trace context; the dispatcher emits queue/linger/solve
        spans under it. ``request_id`` is an optional client-chosen
        idempotency key: resubmitting an id that already solved (or
        graded poison) returns the ORIGINAL request handle — at most one
        solve per id, so a router may retry after a replica death
        without double-dispatching work that actually completed. Shed
        outcomes release the id (shed means "not done, retry")."""
        tenant = str(tenant)
        if not self._running:
            raise ShedError("daemon not running")
        if request_id is not None:
            with self._lock:
                cached = self._idem.get(request_id)
            if cached is not None:
                self._count("deduped")
                return cached
        if tenant in self._quarantined:
            self._count("quarantined")
            self._emit_request(tenant, getattr(X, "shape", (0,))[0],
                              "quarantined")
            raise QuarantinedError(
                f"tenant {tenant!r} is quarantined after "
                f"{POISON_QUARANTINE_STRIKES} poison inputs; restart the "
                f"daemon (or fix the inputs) to clear it")
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float32)
        if X.ndim != 2 or X.shape[0] < 1:
            raise self._reject(tenant, 0, ServeError(
                f"request must be a (cells, genes) matrix, got shape "
                f"{X.shape}"))
        if X.shape[1] != self.reference.n_genes:
            raise self._reject(tenant, X.shape[0], ServeError(
                f"request has {X.shape[1]} genes; the resident reference "
                f"expects {self.reference.n_genes} (its gene order — see "
                f"/healthz)"))
        # cap request size at one full batch of lanes: every dispatch
        # then stays inside the AOT-warmed (lanes, rows) bucket schedule
        # — an unbounded request would compile a fresh program shape on
        # the hot path and grow the program cache for the daemon's
        # lifetime
        max_cells = self.reference.chunk_size * self.max_batch
        if X.shape[0] > max_cells:
            raise self._reject(tenant, X.shape[0], ServeError(
                f"request has {X.shape[0]} cells; this daemon accepts at "
                f"most {max_cells} per request (chunk "
                f"{self.reference.chunk_size} x CNMF_TPU_SERVE_BATCH="
                f"{self.max_batch} lanes) — split the matrix into row "
                f"blocks and project them separately (results are "
                f"row-independent)"))
        h_init, warm = self._warm_init_for(tenant, X)
        req = _Request(tenant, X, h_init, warm, trace=trace,
                       request_id=request_id)
        if request_id is not None:
            with self._lock:
                existing = self._idem.get(request_id)
                if existing is not None:
                    # a concurrent duplicate claimed the id first — wait
                    # on its event instead of enqueueing a second solve
                    self._stats["deduped"] += 1
                    return existing
                self._idem[request_id] = req
                while len(self._idem) > _IDEM_CACHE_ENTRIES:
                    self._idem.pop(next(iter(self._idem)))
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._idem_forget(req)
            self._count("shed")
            self._slo_record(0.0, ok=False)
            self._emit_request(tenant, X.shape[0], "shed")
            raise ShedError(
                f"admission queue full ({self._q.maxsize} requests in "
                f"flight); retry with backoff")
        obs_metrics.gauge_set("cnmf_serve_queue_depth", self._q.qsize())
        if not self._running:
            # close() raced us: the dispatcher may already have drained
            # the queue, so nobody would ever reply — shed immediately.
            # First-reply-wins makes this a no-op if the dispatcher DID
            # handle the request before exiting; wait() then surfaces
            # whichever reply won.
            self._idem_forget(req)
            req.reply(error=ShedError("daemon shutting down"))
        return req

    def _reject(self, tenant: str, n_cells, error: ServeError):
        """Account an admission rejection (counter + telemetry) and hand
        the error back for raising — rejected traffic must be as visible
        to the operator as served traffic."""
        self._count(error.status)
        self._emit_request(tenant, n_cells, error.status)
        return error

    def project(self, X, tenant: str = "default", timeout: float | None
                = None, trace=None, request_id: str | None = None
                ) -> tuple[np.ndarray, dict]:
        """Blocking projection: returns ``(usage (n, k), meta)``."""
        req = self.submit(X, tenant=tenant, trace=trace,
                          request_id=request_id)
        wait = timeout
        if wait is None:
            wait = (self.timeout_s + 120.0) if self.timeout_s else None
        return req.wait(wait)

    def _idem_forget(self, req):
        """Release a request's idempotency claim (shed paths only): shed
        is a promise the work was NOT done, so the same id must be free
        to re-enter and actually solve on retry."""
        rid = getattr(req, "request_id", None)
        if rid is None:
            return
        with self._lock:
            if self._idem.get(rid) is req:
                self._idem.pop(rid, None)

    # -- dispatcher ----------------------------------------------------

    def _dispatch_loop(self):
        carry = None
        while True:
            if carry is not None:
                req, carry = carry, None
            else:
                req = self._q.get()
            if req is _SENTINEL:
                break
            if req.t_dequeue is None:
                req.t_dequeue = time.perf_counter()
            if self._expired(req):
                continue
            batch = [req]
            lanes = lane_count(req.n, self.reference.chunk_size)
            deadline = time.perf_counter() + self.linger_s
            while lanes < self.max_batch:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:
                    nxt = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
                if nxt is _SENTINEL:
                    carry = _SENTINEL
                    break
                nxt.t_dequeue = time.perf_counter()
                if self._expired(nxt):
                    continue
                n_lanes = lane_count(nxt.n, self.reference.chunk_size)
                if lanes + n_lanes > self.max_batch:
                    carry = nxt
                    break
                batch.append(nxt)
                lanes += n_lanes
            try:
                self._dispatch(batch)
            except Exception as exc:  # pragma: no cover - defensive
                for r in batch:
                    if not r.event.is_set():
                        self._idem_forget(r)
                        r.reply(error=ServeError(
                            f"batch dispatch failed: {exc}"))
            if carry is _SENTINEL:
                break

    def _expired(self, req) -> bool:
        if not self.timeout_s:
            return False
        waited = time.perf_counter() - req.t_enqueue
        if waited <= self.timeout_s:
            return False
        self._count("shed")
        self._slo_record(waited * 1e3, ok=False)
        self._emit_request(req.tenant, req.n, "shed",
                           wait_ms=round(waited * 1e3, 3))
        self._idem_forget(req)
        req.reply(error=ShedError(
            f"request {req.rid}: shed after waiting "
            f"{waited:.2f} s (> CNMF_TPU_SERVE_TIMEOUT_S="
            f"{self.timeout_s:g}); the daemon is overloaded"))
        return True

    # -- batched solve -------------------------------------------------

    def _default_init(self, n: int) -> np.ndarray:
        """Rows ``[0:n]`` of the solo default H init (grow-only cache:
        the partitionable-threefry prefix property makes one large draw's
        prefix bit-equal to every smaller draw)."""
        with self._lock:
            cached = self._init_cache
        if cached is None or cached.shape[0] < n:
            from ..ops.nmf import fit_h_default_init

            size = max(int(n), self.buckets[-1])
            fresh = np.asarray(fit_h_default_init(size, self.reference.k))
            with self._lock:
                if (self._init_cache is None
                        or self._init_cache.shape[0] < size):
                    self._init_cache = fresh
                cached = self._init_cache
        return cached[:n]

    @staticmethod
    def _x_token(X: np.ndarray) -> tuple:
        """Cheap content fingerprint (shape + f64 sum + strided sample —
        the residency cache's approach in ``models/cnmf.py``): warm
        starts must only fire for a REPEAT of the same matrix. A
        different matrix of the same shape must never inherit a previous
        solve's exact-zero entries — zeros are absorbing under MU, so a
        shape-keyed warm start could silently pin genuinely-active
        components to zero rather than merely converge faster."""
        buf = X.ravel()
        step = max(1, buf.size // 64)
        return (X.shape, float(buf.sum(dtype=np.float64)),
                buf[::step][:64].tobytes())

    def _warm_init_for(self, tenant: str, X: np.ndarray):
        """The (h_init, warm?) pair for a request: the tenant's previous
        healthy usage for this exact matrix when warm starts are on,
        else None (solo default init)."""
        if not self.warm_start:
            return None, False
        with self._lock:
            H = self._warm_cache.get((tenant, self._x_token(X)))
        if H is None:
            return None, False
        return H, True

    def _dispatch(self, batch: list):
        t0 = time.perf_counter()
        # injectable consistent slowness at the dispatch seam — the
        # deterministic load the obs smoke uses to flip the SLO verdict
        faults.maybe_straggle(context="serve:dispatch")
        ref = self.reference
        chunk_size = ref.chunk_size
        g, k = ref.n_genes, ref.k

        # lane plan: (request, row_lo, row_hi) in request coordinates —
        # the solo chunk partition, so each lane is exactly one chunk of
        # the request's own fit_h dispatch
        lanes = []
        for req in batch:
            chunk = min(chunk_size, req.n)
            for lo in range(0, req.n, chunk):
                lanes.append((req, lo, min(lo + chunk, req.n)))
        n_pad = bucket_for(max(hi - lo for _, lo, hi in lanes),
                           self.buckets)
        # admission caps a request at chunk_size * max_batch cells, so a
        # batch's lane count always fits the warmed bucket schedule
        b_pad = bucket_for(len(lanes), self.b_buckets)

        Xb = np.zeros((b_pad, n_pad, g), np.float32)
        Hb = np.zeros((b_pad, n_pad, k), np.float32)
        inits: dict = {}
        for i, (req, lo, hi) in enumerate(lanes):
            Xb[i, :hi - lo] = req.X[lo:hi]
            H0 = inits.get(req.rid)
            if H0 is None:
                if req.h_init is not None:
                    # the solo comparator is fit_h(H_init=prev), which
                    # clamps at zero — mirror it exactly
                    H0 = np.maximum(
                        np.asarray(req.h_init, np.float32), 0.0)
                else:
                    H0 = self._default_init(req.n)
                inits[req.rid] = H0
            Hb[i, :hi - lo] = H0[lo:hi]

        key = (int(b_pad), int(n_pad))
        cache_hit = bool(self._programs.get(key))
        if not cache_hit:
            self._programs[key] = True
            if self._warmup_done:
                self._count("cold_dispatches_after_warmup")

        import jax

        t_solve = time.perf_counter()
        Xd = jax.device_put(Xb)
        Hd = jax.device_put(Hb)
        out_h, out_rel = batched_project()(
            Xd, Hd, ref.Wd, ref.WWT, ref.w_colsum, ref.h_tol_dev,
            beta=ref.beta, max_iter=ref.chunk_max_iter, l1=ref.l1_H,
            l2=0.0)
        H_all = np.asarray(jax.device_get(out_h))
        rel_all = np.asarray(jax.device_get(out_rel))
        solve_ms = (time.perf_counter() - t_solve) * 1e3

        # PR-4 health grading, per lane: a nonfinite residual or factor
        # block marks ONLY its own lane — batchmates are independent
        from ..ops.nmf import lane_health

        health = lane_health(rel_all, spectra=H_all)

        from ..obs.costmodel import serve_project_cost

        perf_c = serve_project_cost(int(b_pad), int(n_pad), g, k,
                                    beta=ref.beta,
                                    iters=int(ref.chunk_max_iter))
        with self._lock:
            self._stats["batches"] += 1
            self._stats["lanes_total"] += len(lanes)
            self._stats["max_lanes"] = max(self._stats["max_lanes"],
                                           len(lanes))
            if len(batch) > 1:
                self._stats["multi_request_batches"] += 1
            self._perf["solve_s"] += solve_ms / 1e3
            self._perf["flops"] += perf_c["flops"]
            self._perf["bytes"] += perf_c["bytes"]
            self._perf["lanes"] += len(lanes)
            self._perf["batches"] += 1
        if self.events is not None:
            self.events.emit(
                "serve_batch", lanes=len(lanes), requests=len(batch),
                bucket=[int(b_pad), int(n_pad)],
                solve_ms=round(solve_ms, 3), cache_hit=cache_hit,
                queue_depth=self._q.qsize())
        obs_metrics.counter_inc("cnmf_serve_batches_total")
        obs_metrics.counter_inc("cnmf_serve_lanes_total", len(lanes))
        obs_metrics.observe("cnmf_serve_solve_ms", solve_ms)
        obs_metrics.gauge_set("cnmf_serve_queue_depth", self._q.qsize())
        if self.liveness is not None:
            try:
                self.liveness(phase="serve", cursor=self._stats["batches"])
            except Exception:
                pass

        # deterministic unpadding: each request's usage is the ordered
        # concatenation of its lanes' real rows
        by_req: dict = {}
        for i, (req, lo, hi) in enumerate(lanes):
            ok, rows = by_req.get(req.rid, (True, []))
            by_req[req.rid] = (ok and bool(health[i]),
                               rows + [H_all[i, :hi - lo]])
        for req in batch:
            healthy, rows = by_req[req.rid]
            wait_ms = round((t_solve - req.t_enqueue) * 1e3, 3)
            self._emit_req_spans(req, t0, t_solve, solve_ms)
            if healthy:
                H = np.concatenate(rows, axis=0)
                if self.warm_start:
                    self._store_warm(req.tenant, self._x_token(req.X), H)
                self._count("ok")
                if req.warm:
                    self._count("warm_started")
                total = round(
                    (time.perf_counter() - req.t_enqueue) * 1e3, 3)
                with self._lock:
                    self._latencies.append(total)
                    if len(self._latencies) > _LATENCY_SAMPLES:
                        dropped = len(self._latencies) // 2
                        del self._latencies[:dropped]
                        self._lat_dropped += dropped
                self._slo_record(total, ok=True)
                obs_metrics.observe("cnmf_serve_request_ms", total)
                self._emit_request(
                    req.tenant, req.n, "ok", wait_ms=wait_ms,
                    solve_ms=round(solve_ms, 3), total_ms=total,
                    batch_lanes=len(lanes), batch_requests=len(batch),
                    warm_start=req.warm)
                req.reply(result=H, batch_lanes=len(lanes),
                          batch_requests=len(batch), warm_start=req.warm,
                          wait_ms=wait_ms, solve_ms=round(solve_ms, 3))
                # the idempotency map may pin this request for its whole
                # cache lifetime — keep the (small) usage result for
                # retries, drop the (large) input + init now
                req.X = None
                req.h_init = None
            else:
                strikes = self._strike(req.tenant)
                self._count("poison")
                self._slo_record(
                    (time.perf_counter() - req.t_enqueue) * 1e3,
                    ok=False)
                self._emit_request(
                    req.tenant, req.n, "poison", wait_ms=wait_ms,
                    solve_ms=round(solve_ms, 3),
                    batch_lanes=len(lanes), batch_requests=len(batch))
                if self.events is not None:
                    self.events.emit(
                        "fault", kind="serve_poison",
                        context={"tenant": req.tenant, "n_cells": req.n,
                                 "strikes": strikes,
                                 "quarantined":
                                     req.tenant in self._quarantined})
                req.reply(error=PoisonError(
                    f"request {req.rid} (tenant {req.tenant!r}): "
                    f"projection graded unhealthy (nonfinite input or "
                    f"usage); strike {strikes}/"
                    f"{POISON_QUARANTINE_STRIKES}"))
                # poison stays claimed (a retry of the same id must NOT
                # re-solve and take a second strike); free the input
                req.X = None
                req.h_init = None

    def _emit_req_spans(self, req, t0: float, t_solve: float,
                        solve_ms: float):
        """The request's hop spans on the batcher side: queue wait
        (enqueue -> dequeue), batch linger (dequeue -> launch), device
        dispatch (launch -> fetched). Nothing happens for untraced
        requests."""
        if req.trace is None or self.events is None:
            return
        dq = req.t_dequeue if req.t_dequeue is not None else t0
        obs_tracing.emit_span(
            self.events, obs_tracing.child(req.trace), "serve.queue",
            start_ts=obs_tracing.perf_to_wall(req.t_enqueue),
            wall_ms=(dq - req.t_enqueue) * 1e3, tenant=req.tenant)
        obs_tracing.emit_span(
            self.events, obs_tracing.child(req.trace), "serve.linger",
            start_ts=obs_tracing.perf_to_wall(dq),
            wall_ms=(t_solve - dq) * 1e3)
        obs_tracing.emit_span(
            self.events, obs_tracing.child(req.trace), "serve.solve",
            start_ts=obs_tracing.perf_to_wall(t_solve),
            wall_ms=solve_ms)

    def _slo_record(self, latency_ms: float, ok: bool):
        if self._slo is not None:
            self._slo.record(latency_ms, ok=ok)

    def slo_status(self, refresh_metrics: bool = False):
        """The windowed SLO verdict, or ``None`` when the tracker is not
        armed. With ``refresh_metrics`` the verdict is also published as
        gauges so a /metrics scrape carries it."""
        if self._slo is None:
            return None
        verdict = self._slo.evaluate()
        if refresh_metrics:
            obs_metrics.gauge_set("cnmf_slo_target_p99_ms",
                                  verdict["target_p99_ms"])
            obs_metrics.gauge_set("cnmf_slo_window_requests",
                                  verdict["requests"])
            obs_metrics.gauge_set("cnmf_slo_window_errors",
                                  verdict["errors"])
            if verdict.get("p99_ms") is not None:
                obs_metrics.gauge_set("cnmf_slo_p99_ms",
                                      verdict["p99_ms"])
            obs_metrics.gauge_set("cnmf_slo_burning",
                                  1.0 if verdict["burning"] else 0.0)
        return verdict

    def metrics_text(self) -> str:
        """The daemon's /metrics body: refresh the point-in-time gauges
        (queue depth, reservoir honesty, SLO verdict) and render the
        process registry."""
        if obs_metrics.metrics_enabled():
            obs_metrics.gauge_set("cnmf_serve_queue_depth",
                                  self._q.qsize())
            with self._lock:
                kept = len(self._latencies)
                dropped = self._lat_dropped
            obs_metrics.gauge_set("cnmf_serve_latency_samples_kept", kept)
            obs_metrics.gauge_set("cnmf_serve_latency_samples_dropped",
                                  dropped)
            obs_metrics.gauge_set(
                "cnmf_serve_latency_window_coverage",
                round(kept / (kept + dropped), 6) if kept + dropped
                else 1.0)
            self.slo_status(refresh_metrics=True)
        return obs_metrics.render_text()

    def _store_warm(self, tenant: str, token: tuple, H: np.ndarray):
        with self._lock:
            cache = self._warm_cache
            cache.pop((tenant, token), None)
            cache[(tenant, token)] = H
            while len(cache) > _WARM_CACHE_ENTRIES:
                cache.pop(next(iter(cache)))

    def _strike(self, tenant: str) -> int:
        with self._lock:
            strikes = self._strikes.get(tenant, 0) + 1
            self._strikes[tenant] = strikes
            if strikes >= POISON_QUARANTINE_STRIKES:
                self._quarantined.add(tenant)
            return strikes

    # -- accounting ----------------------------------------------------

    def _count(self, key: str):
        is_status = key in ("ok", "shed", "poison", "quarantined",
                            "error")
        with self._lock:
            self._stats["requests"] += is_status
            self._stats[key] = self._stats.get(key, 0) + 1
        if is_status:
            obs_metrics.counter_inc("cnmf_serve_requests_total",
                                    status=key)

    def _emit_request(self, tenant: str, n_cells, status: str, **fields):
        if self.events is not None:
            self.events.emit("serve_request", tenant=str(tenant),
                             n_cells=int(n_cells), status=status,
                             **fields)

    def emit_perf_model(self):
        """Flush the accumulated serve-dispatch roofline accounting as
        ONE ``perf_model`` event (ISSUE 19) — called at daemon
        shutdown, after the batcher drained. No-op without telemetry +
        CNMF_TPU_PERF_MODEL, or when nothing dispatched."""
        from ..obs.costmodel import (chip_peaks, perf_model_enabled,
                                     roofline)

        if self.events is None or not perf_model_enabled():
            return
        with self._lock:
            perf = dict(self._perf)
        if not perf.get("batches"):
            return
        try:
            import jax

            kind = jax.devices()[0].device_kind
            backend = jax.default_backend()
        except Exception:
            kind, backend = None, "unknown"
        roof = roofline(perf["flops"], perf["bytes"], perf["solve_s"],
                        chip_peaks(kind), perf_exempt=backend != "tpu")
        self.events.emit(
            "perf_model", stage="serve", lane="serve-project",
            predicted={"flops": perf["flops"], "bytes": perf["bytes"],
                       "iters_assumed_cap": True},
            measured={"wall_s": round(perf["solve_s"], 4),
                      "passes": int(perf["batches"]),
                      "lanes": int(perf["lanes"])},
            roofline=roof)

    def stats(self) -> dict:
        from ..utils.profiling import latency_summary

        with self._lock:
            out = dict(self._stats)
            lat = list(self._latencies)
            lat_dropped = self._lat_dropped
            out["quarantined_tenants"] = sorted(self._quarantined)
            out["programs_warmed"] = sum(
                1 for v in self._programs.values() if v)
        out["batched_fraction"] = (
            round(out["multi_request_batches"] / out["batches"], 3)
            if out["batches"] else 0.0)
        out["mean_lanes"] = (round(out["lanes_total"] / out["batches"], 2)
                             if out["batches"] else 0.0)
        out["latency_ms"] = latency_summary(lat)
        # reservoir honesty: state what the latency summary was computed
        # over — kept sample count, samples the halving dropped, and the
        # fraction of all recorded latencies still in the window
        out["latency_samples_kept"] = len(lat)
        out["latency_samples_dropped"] = lat_dropped
        out["latency_window_coverage"] = (
            round(len(lat) / (len(lat) + lat_dropped), 6)
            if len(lat) + lat_dropped else 1.0)
        slo = self.slo_status()
        if slo is not None:
            out["slo"] = slo
        out["reference"] = self.reference.describe()
        out["buckets"] = list(self.buckets)
        out["lane_buckets"] = list(self.b_buckets)
        return out
