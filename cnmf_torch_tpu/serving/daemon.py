"""The projection daemon's protocol front end (ISSUE 12).

Stdlib-only HTTP/JSON over either a 127.0.0.1 TCP port or (the default)
a unix domain socket next to the run's artifacts — no new dependencies,
no open network surface unless asked for. One daemon serves one resident
reference; concurrent client connections are handled by a threading
server whose request threads all feed the ONE micro-batching dispatcher
(``batcher.py``) — which is exactly how cross-request batching happens:
N racing HTTP clients become one vmapped device dispatch.

Protocol (all bodies JSON):

  * ``POST /project`` — ``{"tenant": "...", "data": [[...]]}`` or
    ``{"tenant": "...", "shape": [n, g], "b64": "<base64 f32
    row-major>"}``. Success: ``{"ok": true, "shape": [n, k], "b64" |
    "usage": ..., "meta": {...}}`` (the reply mirrors the request's
    encoding). Errors carry ``{"ok": false, "status", "error"}`` with
    HTTP 429 (shed), 422 (poison), 403 (quarantined), 400 (bad
    request).
  * ``GET /healthz`` — liveness + resident-reference summary; when the
    SLO tracker is armed (``CNMF_TPU_SLO_P99_MS``) the reply carries the
    windowed verdict and ``"degraded": true`` while the SLO burns.
  * ``GET /reference`` — full reference description incl. gene order.
  * ``GET /stats`` — serving counters + latency summary
    (``utils/profiling.latency_summary``).
  * ``GET /metrics`` — text exposition of the live metrics registry
    (``obs/metrics.py``; a "disabled" banner unless
    ``CNMF_TPU_METRICS=1``).
  * ``POST /shutdown`` — clean stop WITH drain: the accept loop closes
    first, then every already-accepted request finishes (in-flight
    batches complete, queued requests flush) before the batcher stops
    and the socket file is removed. The drain wait is bounded by
    ``CNMF_TPU_SERVE_DRAIN_S`` (default 30 s) so a wedged client cannot
    hold shutdown hostage. No accepted request is ever lost across a
    shutdown — pinned by ``tests/test_fleet.py`` and relied on by the
    fleet router's zero-downtime rollover (ISSUE 20).

Idempotent retries (ISSUE 20): a client may stamp ``X-CNMF-Request-Id``
(or payload key ``"request_id"``); resubmitting the same id returns the
original solve's reply instead of dispatching again — the at-most-once
contract the fleet router's failover retry rides.

Tracing: a sampled client sends ``X-CNMF-Trace: <trace>:<span>`` and
the daemon threads a child context through admission -> batcher queue ->
linger -> AOT dispatch, each hop landing as a ``span`` event in the
daemon's telemetry stream (``obs/tracing.py``).
"""

from __future__ import annotations

import base64
import json
import os
import socket
import socketserver
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracing as obs_tracing
from ..utils.envknobs import env_float
from .batcher import (PoisonError, ProjectionService, QuarantinedError,
                      ServeError, ShedError)

__all__ = ["ServeDaemon", "ServeClient", "serve_forever",
           "default_socket_path", "REQUEST_ID_HEADER"]

# client-chosen idempotency key: same id -> at most one solve (the
# payload key "request_id" is equivalent; the header wins when both set)
REQUEST_ID_HEADER = "X-CNMF-Request-Id"

_STATUS_HTTP = {"shed": 429, "poison": 422, "quarantined": 403,
                "error": 400}


def default_socket_path(run_dir: str) -> str:
    name = os.path.basename(os.path.normpath(run_dir))
    return os.path.join(run_dir, "cnmf_tmp", name + ".serve.sock")


def _decode_matrix(payload: dict) -> np.ndarray:
    if "b64" in payload:
        shape = payload.get("shape")
        if (not isinstance(shape, (list, tuple)) or len(shape) != 2):
            raise ValueError("b64 requests need \"shape\": [n, g]")
        raw = base64.b64decode(payload["b64"])
        n, g = int(shape[0]), int(shape[1])
        if len(raw) != n * g * 4:
            raise ValueError(
                f"b64 payload is {len(raw)} bytes; shape {n}x{g} needs "
                f"{n * g * 4} (f32 row-major)")
        return np.frombuffer(raw, np.float32).reshape(n, g)
    if "data" in payload:
        return np.asarray(payload["data"], dtype=np.float32)
    raise ValueError("request needs \"data\" (nested lists) or "
                     "\"b64\" + \"shape\"")


def _encode_matrix(H: np.ndarray, like: dict) -> dict:
    if "b64" in like:
        return {"shape": list(H.shape),
                "b64": base64.b64encode(
                    np.ascontiguousarray(H, np.float32).tobytes()
                ).decode("ascii")}
    return {"shape": list(H.shape), "usage": H.tolist()}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # the daemon's own telemetry covers request accounting; stderr
    # access logs would interleave with the pipeline's prints
    def log_message(self, fmt, *args):  # noqa: D401 - BaseHTTP override
        pass

    @property
    def service(self) -> ProjectionService:
        return self.server.service

    def _reply(self, code: int, obj: dict):
        body = json.dumps(obj).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_text(self, code: int, text: str):
        body = text.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            reply = {"ok": True,
                     "reference": self.service.reference.describe()}
            slo = self.service.slo_status()
            if slo is not None:
                reply["slo"] = slo
                reply["degraded"] = bool(slo.get("burning"))
            self._reply(200, reply)
        elif self.path == "/reference":
            ref = self.service.reference
            self._reply(200, dict(
                ref.describe(), genes=ref.genes,
                components=[str(c) for c in ref.components]))
        elif self.path == "/stats":
            self._reply(200, {"ok": True, "stats": self.service.stats()})
        elif self.path == "/metrics":
            self._reply_text(200, self.service.metrics_text())
        else:
            self._reply(404, {"ok": False, "error": f"no route "
                              f"{self.path!r}"})

    def do_POST(self):
        if self.path == "/shutdown":
            self._reply(200, {"ok": True, "stopping": True})
            threading.Thread(target=self.server.shutdown,
                             daemon=True).start()
            return
        if self.path != "/project":
            self._reply(404, {"ok": False,
                              "error": f"no route {self.path!r}"})
            return
        try:
            length = int(self.headers.get("Content-Length", "0"))
            payload = json.loads(self.rfile.read(length) or b"{}")
            X = _decode_matrix(payload)
        except (ValueError, json.JSONDecodeError) as exc:
            self._reply(400, {"ok": False, "status": "error",
                              "error": str(exc)})
            return
        tenant = str(payload.get("tenant", "default"))
        request_id = (self.headers.get(REQUEST_ID_HEADER)
                      or payload.get("request_id"))
        if request_id is not None:
            request_id = str(request_id)
        # sampled distributed tracing: the client's context arrives in
        # the X-CNMF-Trace header; everything the daemon does for this
        # request nests under one serve.http child span
        ctx = obs_tracing.from_header(
            self.headers.get(obs_tracing.TRACE_HEADER))
        hctx = obs_tracing.child(ctx)
        with obs_tracing.span(self.service.events, hctx, "serve.http",
                              tenant=tenant, n_cells=int(X.shape[0])):
            try:
                H, meta = self.service.project(X, tenant=tenant,
                                               trace=hctx,
                                               request_id=request_id)
            except (ShedError, PoisonError, QuarantinedError,
                    ServeError) as exc:
                self._reply(_STATUS_HTTP.get(exc.status, 400),
                            {"ok": False, "status": exc.status,
                             "error": str(exc)})
                return
            self._reply(200, dict({"ok": True, "meta": meta},
                                  **_encode_matrix(H, payload)))


class _DrainMixin:
    """Connection-accounted threading server.

    ``daemon_threads = True`` means ``server_close()`` does NOT join
    handler threads — a bare shutdown races whatever those threads are
    doing, which is exactly how a queued request can be accepted and
    then lost. The fix: count every accepted connection in
    ``process_request`` (which runs IN the accept loop, synchronously
    with ``shutdown()``, so no accepted connection can slip past the
    count) and decrement when its handler thread finishes. After
    ``shutdown()`` returns, :meth:`wait_drained` blocks until the count
    hits zero — every in-flight request has its real reply — before the
    service underneath is torn down.
    """

    def __init__(self, *args, **kwargs):
        self.inflight = 0
        self._inflight_cv = threading.Condition()
        super().__init__(*args, **kwargs)

    def process_request(self, request, client_address):
        with self._inflight_cv:
            self.inflight += 1
        try:
            super().process_request(request, client_address)
        except Exception:
            # the handler thread never spawned; give its count back
            with self._inflight_cv:
                self.inflight -= 1
                self._inflight_cv.notify_all()
            raise

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            with self._inflight_cv:
                self.inflight -= 1
                self._inflight_cv.notify_all()

    def wait_drained(self, timeout: float) -> bool:
        """Block until every accepted connection finished handling, or
        ``timeout`` seconds elapsed. Returns whether the drain
        completed."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        with self._inflight_cv:
            while self.inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(remaining)
        return True


class _TCPHTTPServer(_DrainMixin, ThreadingHTTPServer):
    pass


class _UnixHTTPServer(_DrainMixin, ThreadingHTTPServer):
    address_family = socket.AF_UNIX

    def server_bind(self):
        # BaseHTTPServer's server_bind unpacks (host, port); a unix
        # address is a path string
        socketserver.TCPServer.server_bind(self)
        self.server_name = "localhost"
        self.server_port = 0


class ServeDaemon:
    """One resident reference behind one HTTP endpoint.

    ``socket_path`` (default) binds a unix domain socket —
    collision-free for tests/CI and invisible off-host; ``port`` binds
    ``127.0.0.1:port`` instead. Construction binds and warms; call
    :meth:`serve_forever` (blocking) or :meth:`start` (background
    thread). :meth:`close` stops the batcher, closes the server, and
    removes the socket file.
    """

    def __init__(self, service: ProjectionService,
                 socket_path: str | None = None, port: int | None = None):
        self.service = service
        self.socket_path = None
        if port is not None:
            self.server = _TCPHTTPServer(("127.0.0.1", int(port)),
                                         _Handler)
        else:
            if socket_path is None:
                raise ValueError("need socket_path or port")
            # a stale socket file from a crashed daemon is unconnectable
            # garbage; replace it (a LIVE daemon still owns the inode and
            # keeps serving its existing connections — same model as the
            # launcher's stale-ledger sweep)
            if os.path.exists(socket_path):
                os.unlink(socket_path)
            self.server = _UnixHTTPServer(socket_path, _Handler)
            self.socket_path = socket_path
        self.server.daemon_threads = True
        self.server.service = service
        self._thread = None
        self._closed = False

    @property
    def address(self) -> str:
        if self.socket_path:
            return self.socket_path
        host, port = self.server.server_address[:2]
        return f"{host}:{port}"

    def start(self):
        self.service.start()
        t = threading.Thread(target=self.server.serve_forever,
                             name="cnmf-serve-http", daemon=True)
        self._thread = t
        t.start()
        return self

    def serve_forever(self):
        self.service.start()
        try:
            self.server.serve_forever()
        finally:
            self.close()

    def close(self):
        if self._closed:
            return
        self._closed = True
        # ordering is the drain guarantee (ISSUE 20 satellite): stop
        # ACCEPTING first, then let every already-accepted request run
        # to its real reply (the service is still up, so handler threads
        # blocked in project() complete normally), and only then stop
        # the batcher and close the listener. A client that wedges its
        # connection open cannot hold shutdown hostage — the wait is
        # bounded by CNMF_TPU_SERVE_DRAIN_S, after which stragglers get
        # the batcher's shutting-down shed like before.
        self.server.shutdown()
        drain_s = env_float("CNMF_TPU_SERVE_DRAIN_S", 30.0, lo=0.0)
        if not self.server.wait_drained(drain_s):
            print(f"cnmf-tpu serve: shutdown drain timed out after "
                  f"{drain_s:g} s with {self.server.inflight} "
                  f"connection(s) still open (CNMF_TPU_SERVE_DRAIN_S)")
        self.service.close()
        self.server.server_close()
        if self.socket_path and os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


class _UnixHTTPConnection(HTTPConnection):
    def __init__(self, path: str, timeout=None):
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self):
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if self.timeout is not None:
            s.settimeout(self.timeout)
        s.connect(self._unix_path)
        self.sock = s


class ServeClient:
    """Minimal stdlib client for the daemon (tests, smoke, bench, and a
    copy-paste example for real clients). One connection per call —
    correctness over connection reuse."""

    def __init__(self, socket_path: str | None = None,
                 host: str = "127.0.0.1", port: int | None = None,
                 timeout: float = 180.0, events=None):
        if socket_path is None and port is None:
            raise ValueError("need socket_path or port")
        self.socket_path = socket_path
        self.host, self.port = host, port
        self.timeout = timeout
        # optional EventLog: a traced client with one writes its own
        # client.request root span next to the daemon's spans (the
        # O_APPEND event log interleaves multi-process writers safely)
        self.events = events

    def _request(self, method: str, path: str, payload: dict | None = None,
                 headers: dict | None = None, raw: bool = False):
        if self.socket_path:
            conn = _UnixHTTPConnection(self.socket_path,
                                       timeout=self.timeout)
        else:
            conn = HTTPConnection(self.host, self.port,
                                  timeout=self.timeout)
        try:
            body = (json.dumps(payload).encode("utf-8")
                    if payload is not None else None)
            hdrs = dict(headers or {})
            if body:
                hdrs["Content-Type"] = "application/json"
            conn.request(method, path, body=body, headers=hdrs)
            resp = conn.getresponse()
            blob = resp.read()
            if raw:
                return resp.status, blob.decode("utf-8", "replace")
            return resp.status, json.loads(blob or b"{}")
        finally:
            conn.close()

    def project(self, X, tenant: str = "default",
                encoding: str = "b64", request_id: str | None = None):
        """Project ``X`` (n x genes) onto the resident reference;
        returns ``(usage (n, k) np.ndarray, meta dict)``. Raises the
        matching :class:`ServeError` subclass on a daemon-side error.
        With ``CNMF_TPU_TRACE_SAMPLE`` > 0 a sampled call carries an
        ``X-CNMF-Trace`` header so the daemon's spans stitch to this
        client's trace. ``request_id`` stamps the idempotency header:
        retrying the same id never solves twice."""
        X = np.ascontiguousarray(np.asarray(X), dtype=np.float32)
        payload: dict = {"tenant": tenant}
        if encoding == "b64":
            payload["shape"] = list(X.shape)
            payload["b64"] = base64.b64encode(X.tobytes()).decode("ascii")
        else:
            payload["data"] = X.tolist()
        ctx = obs_tracing.new_trace()
        headers = ({obs_tracing.TRACE_HEADER: obs_tracing.header_value(ctx)}
                   if ctx is not None else None)
        if request_id is not None:
            headers = dict(headers or {})
            headers[REQUEST_ID_HEADER] = str(request_id)
        with obs_tracing.span(self.events, ctx, "client.request",
                              tenant=tenant):
            status, data = self._request("POST", "/project", payload,
                                         headers=headers)
        if status != 200 or not data.get("ok"):
            err = {"shed": ShedError, "poison": PoisonError,
                   "quarantined": QuarantinedError}.get(
                data.get("status"), ServeError)
            raise err(data.get("error", f"HTTP {status}"))
        if "b64" in data:
            H = np.frombuffer(base64.b64decode(data["b64"]),
                              np.float32).reshape(data["shape"])
        else:
            H = np.asarray(data["usage"], dtype=np.float32)
        return H, data.get("meta", {})

    def healthz(self) -> dict:
        status, data = self._request("GET", "/healthz")
        if status != 200:
            raise ServeError(f"healthz: HTTP {status}: {data}")
        return data

    def reference(self) -> dict:
        status, data = self._request("GET", "/reference")
        if status != 200:
            raise ServeError(f"reference: HTTP {status}: {data}")
        return data

    def stats(self) -> dict:
        status, data = self._request("GET", "/stats")
        if status != 200:
            raise ServeError(f"stats: HTTP {status}: {data}")
        return data["stats"]

    def metrics(self) -> str:
        """The daemon's ``GET /metrics`` text exposition, verbatim."""
        status, text = self._request("GET", "/metrics", raw=True)
        if status != 200:
            raise ServeError(f"metrics: HTTP {status}: {text}")
        return text

    def shutdown(self):
        status, data = self._request("POST", "/shutdown")
        return status == 200


def serve_forever(run_dir: str, k: int | None = None,
                  density_threshold=None, spectra_path: str | None = None,
                  socket_path: str | None = None, port: int | None = None,
                  replica: int = 0):
    """The ``cnmf-tpu serve <run_dir>`` entry: load + stage the
    reference, warm the program buckets, bind, and serve until
    SIGINT/SIGTERM (clean close: batcher drained, socket removed).
    ``replica`` is the fleet router's ordinal (ISSUE 20): it keys this
    daemon's heartbeat file and events stream so N replicas of one run
    directory never collide on either."""
    import signal

    from ..utils.telemetry import EventLog
    from .reference import load_reference

    name = os.path.basename(os.path.normpath(run_dir))
    replica = int(replica)
    leaf = (name + ".events.jsonl" if replica == 0
            else f"{name}.r{replica}.events.jsonl")
    events = EventLog(
        os.path.join(run_dir, "cnmf_tmp", leaf),
        manifest_extra={"run_name": name, "role": "serve",
                        "replica": replica})
    ref = load_reference(run_dir, k=k, density_threshold=density_threshold,
                         spectra_path=spectra_path)

    liveness = None
    from ..runtime.elastic import Heartbeat

    hb = Heartbeat(os.path.join(run_dir, "cnmf_tmp"), name + ".serve",
                   replica, events=events)
    if hb.enabled:
        liveness = hb.beat

    service = ProjectionService(ref, events=events, liveness=liveness)
    if port is None and socket_path is None:
        socket_path = default_socket_path(run_dir)
    daemon = ServeDaemon(service, socket_path=socket_path, port=port)

    # live metrics -> telemetry bridge: periodic metrics_snapshot events
    # (plus one at shutdown) carrying registry state and the SLO verdict
    snapshotter = None
    if obs_metrics.metrics_enabled() and events.enabled:
        snapshotter = obs_metrics.Snapshotter(
            events, interval_s=30.0,
            slo_fn=lambda: service.slo_status(refresh_metrics=True))
        snapshotter.start()

    def _stop(signum, frame):
        threading.Thread(target=daemon.server.shutdown,
                         daemon=True).start()

    prev = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            prev[sig] = signal.signal(sig, _stop)
        except ValueError:  # non-main thread (tests)
            pass
    print(f"cnmf-tpu serve: reference k={ref.k} x {ref.n_genes} genes "
          f"(beta={ref.beta:g}) from {ref.source}")
    try:
        daemon.service.start()
        print(f"cnmf-tpu serve: listening on {daemon.address} "
              f"(buckets {list(service.buckets)}, batch <= "
              f"{service.max_batch} lanes, linger "
              f"{service.linger_s * 1e3:g} ms)")
        daemon.serve_forever()
    finally:
        if snapshotter is not None:
            snapshotter.stop()
        daemon.close()
        # one perf_model event over the whole serve session (ISSUE 19):
        # emitted after close so every dispatch is in the accounting
        try:
            service.emit_perf_model()
        except Exception:
            pass
        for sig, handler in prev.items():
            try:
                signal.signal(sig, handler)
            except ValueError:
                pass
    return 0
