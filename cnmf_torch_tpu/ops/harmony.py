"""Harmony batch correction (Korsunsky et al. 2019, Nat. Methods) in JAX.

The reference delegates to the ``harmonypy`` package
(``/root/reference/src/cnmf/preprocess.py:373-378``) and then applies the
mixture-of-experts ridge to the *gene expression matrix* itself
(``preprocess.py:9-18, 382``). Both live here as device kernels:

  * :func:`run_harmony` — the iterative soft-kmeans-with-diversity-penalty
    clustering plus per-cluster ridge correction of the PC embedding. The
    maximum-diversity clustering objective and update equations follow the
    published method; the heavy steps (K x n assignment matrix updates,
    centroid refresh, ridge solves) are jit-compiled matmuls.
  * :func:`moe_correct_ridge` — the per-cluster ridge correction applied to
    an arbitrary (features x cells) matrix, as a ``lax.scan`` over clusters;
    this is what corrects genes, not just PCs, in the preprocess sidecar.

Determinism: all stochastic choices (kmeans init, block update order) are
driven by a seeded generator, unlike harmonypy's global numpy state.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from .kmeans import kmeans

__all__ = ["run_harmony", "moe_correct_ridge", "HarmonyResult"]

_HI = jax.lax.Precision.HIGHEST


class HarmonyResult:
    """Mirror of the harmonypy result surface the reference consumes
    (``preprocess.py:378-382``): ``Z_corr`` (d x n corrected embedding),
    ``R`` (K x n soft assignments), ``Phi_moe`` ((B+1) x n design),
    ``lamb`` ((B+1) x (B+1) ridge matrix), ``K``, ``objective_harmony``."""

    def __init__(self, Z_corr, Z_cos, R, Phi_moe, lamb, K, objectives):
        self.Z_corr = Z_corr
        self.Z_cos = Z_cos
        self.R = R
        self.Phi_moe = Phi_moe
        self.lamb = lamb
        self.K = K
        self.objective_harmony = objectives


def _one_hot_design(meta_data: pd.DataFrame, vars_use) -> np.ndarray:
    """(B x n) stacked one-hot encoding of the batch columns."""
    if isinstance(vars_use, str):
        vars_use = [vars_use]
    blocks = []
    for v in vars_use:
        dummies = pd.get_dummies(meta_data[v].astype("category"))
        blocks.append(dummies.values.T.astype(np.float32))
    return np.concatenate(blocks, axis=0)


def design_width(meta_data: pd.DataFrame, vars_use) -> int:
    """B — the row count :func:`_one_hot_design` will produce — without
    materializing the (B x n) matrix. Kept next to the encoder so the
    ``Preprocess`` program warmer's shape derivation can never drift from
    production's."""
    if isinstance(vars_use, str):
        vars_use = [vars_use]
    return sum(meta_data[v].astype("category").cat.categories.size
               for v in vars_use)


@jax.jit
def _normalize_cols(M):
    return M / jnp.maximum(jnp.linalg.norm(M, axis=0, keepdims=True), 1e-12)


@jax.jit
def _assign_R(Y, Z_cos, sigma):
    """Soft assignments without the diversity term (initialization)."""
    dist = 2.0 * (1.0 - jnp.matmul(Y.T, Z_cos, precision=_HI))
    Rl = -dist / sigma[:, None]
    Rl = Rl - jnp.max(Rl, axis=0, keepdims=True)
    R = jnp.exp(Rl)
    return R / jnp.sum(R, axis=0, keepdims=True)


@jax.jit
def _block_R_update(dist_blk, phi_blk, E, O, R_blk, Pr_b, sigma, theta):
    """Update one cell block's assignments with the diversity penalty:
    R ~ exp(-dist/sigma) * prod_b ((E+1)/(O+1))^theta, with the block's
    contribution removed from E/O first (out-of-block statistics)."""
    E = E - jnp.outer(R_blk.sum(axis=1), Pr_b)
    O = O - jnp.matmul(R_blk, phi_blk.T, precision=_HI)
    # Harmony's published update: the (K x B) penalty matrix
    # ((E+1)/(O+1))^theta projected onto each cell's active batch levels by
    # a dot product — i.e. a SUM over batch variables when several are
    # corrected at once, not a product (the two only coincide for a single
    # batch variable, where exactly one level is active per cell)
    dist_term = jnp.exp(-dist_blk / sigma[:, None])
    penalty = jnp.matmul(
        jnp.power((E + 1.0) / (O + 1.0), theta[None, :]), phi_blk,
        precision=_HI)
    R_new = dist_term * penalty
    R_new = R_new / jnp.maximum(
        jnp.sum(R_new, axis=0, keepdims=True), 1e-30)
    E = E + jnp.outer(R_new.sum(axis=1), Pr_b)
    O = O + jnp.matmul(R_new, phi_blk.T, precision=_HI)
    return R_new, E, O


def _one_round(Z_cos, R_pad, phi_pad, E, O, blocks, valid_b, Pr_b, sigma,
               theta):
    """One clustering round on the padded state: centroid refresh + every
    block's diversity-penalty R update, scanned over the blocks of a padded
    permutation. Numerics per block are identical to
    :func:`_block_R_update` (same update order, same out-of-block E/O);
    sentinel entries (valid 0) contribute nothing to the E/O bookkeeping
    and scatter only into the phantom column."""
    n = Z_cos.shape[1]
    Y = _normalize_cols(jnp.matmul(Z_cos, R_pad[:, :n].T, precision=_HI))
    dist = 2.0 * (1.0 - jnp.matmul(Y.T, Z_cos, precision=_HI))
    dist_pad = jnp.pad(dist, ((0, 0), (0, 1)))

    def body(carry, inp):
        R_pad, E, O = carry
        idx, v = inp                                  # (blk,), (blk,)
        R_blk = R_pad[:, idx] * v[None, :]
        phi_blk = phi_pad[:, idx] * v[None, :]
        E = E - jnp.outer(R_blk.sum(axis=1), Pr_b)
        O = O - jnp.matmul(R_blk, phi_blk.T, precision=_HI)
        dist_term = jnp.exp(-dist_pad[:, idx] / sigma[:, None])
        penalty = jnp.matmul(
            jnp.power((E + 1.0) / (O + 1.0), theta[None, :]), phi_blk,
            precision=_HI)
        R_new = dist_term * penalty
        R_new = R_new / jnp.maximum(
            jnp.sum(R_new, axis=0, keepdims=True), 1e-30)
        R_new = R_new * v[None, :]
        E = E + jnp.outer(R_new.sum(axis=1), Pr_b)
        O = O + jnp.matmul(R_new, phi_blk.T, precision=_HI)
        return (R_pad.at[:, idx].set(R_new), E, O), ()

    (R_pad, E, O), _ = jax.lax.scan(body, (R_pad, E, O), (blocks, valid_b))
    obj = _clustering_objective(Y, Z_cos, R_pad[:, :n], E, O, sigma, theta)
    return R_pad, E, O, obj


@functools.partial(jax.jit, static_argnames=("n_blocks",))
def _cluster_round(Z_cos, R, phi, E, O, perm_pad, valid, Pr_b, sigma, theta,
                   n_blocks):
    """One full clustering round as ONE device program (testing/oracle
    surface for :func:`_one_round`). Returns ``(R, E, O, objective)``."""
    R_pad = jnp.pad(R, ((0, 0), (0, 1)))
    phi_pad = jnp.pad(phi, ((0, 0), (0, 1)))
    R_pad, E, O, obj = _one_round(
        Z_cos, R_pad, phi_pad, E, O, perm_pad.reshape(n_blocks, -1),
        valid.reshape(n_blocks, -1), Pr_b, sigma, theta)
    return R_pad[:, :Z_cos.shape[1]], E, O, obj


@functools.partial(jax.jit, static_argnames=("n_blocks", "max_iter"))
def _cluster_phase(Z_cos, R, phi, E, O, perms, Pr_b, sigma, theta,
                   eps, n_blocks, max_iter):
    """The whole clustering phase (up to ``max_iter`` rounds with the
    original early-exit rule) as ONE device program.

    The reference path (harmonypy, and a host loop like it) issues one
    host->device round trip per cell block — thousands of tiny dispatches
    per harmony iteration, which dominates wall-clock on high-latency
    links. Here ALL ``max_iter`` per-round permutations are precomputed
    host-side up front, padded to ``n_blocks`` equal blocks, and the
    rounds run under a ``while_loop`` that stops when the objective's
    relative change drops below ``eps`` (after at least 2 rounds, as
    harmonypy does).

    Determinism: same seed -> same result. But the seeded STREAM differs
    from a host loop that draws one permutation per executed round (early
    exit leaves the precomputed tail unused), and the equal-size block
    split differs from ``np.array_split``'s first-blocks-larger split —
    so same-seed outputs are not bit-identical to pre-fusion versions of
    this module (both are valid optima of the same objective; the
    reference itself has no cross-version guarantee here, as harmonypy
    draws from global numpy state).

    Returns ``(R, E, O, obj_prev, obj, rounds_run)`` — the last two
    objectives so the caller can reproduce the host loop's bookkeeping.
    """
    R_pad0 = jnp.pad(R, ((0, 0), (0, 1)))
    phi_pad = jnp.pad(phi, ((0, 0), (0, 1)))
    n = Z_cos.shape[1]
    # validity is derivable: sentinel entries of the padded permutations
    # point at the phantom column n
    valids = (perms < n).astype(R.dtype)

    def run_round(R_pad, E, O, it):
        return _one_round(
            Z_cos, R_pad, phi_pad, E, O,
            perms[it].reshape(n_blocks, -1),
            valids[it].reshape(n_blocks, -1), Pr_b, sigma, theta)

    def body(carry):
        R_pad, E, O, _obj_prev, obj, it = carry
        R_pad, E, O, obj_new = run_round(R_pad, E, O, it)
        return (R_pad, E, O, obj, obj_new, it + 1)

    def cond(carry):
        _, _, _, obj_prev, obj, it = carry
        converged = jnp.abs(obj_prev - obj) < eps * jnp.abs(obj_prev)
        return (it < max_iter) & ((it < 2) | ~converged)

    R_pad, E, O, obj0 = run_round(R_pad0, E, O, jnp.int32(0))
    R_pad, E, O, obj_prev, obj, it = jax.lax.while_loop(
        cond, body, (R_pad, E, O, jnp.float32(jnp.inf), obj0, jnp.int32(1)))
    return R_pad[:, :Z_cos.shape[1]], E, O, obj_prev, obj, it


@jax.jit
def _clustering_objective(Y, Z_cos, R, E, O, sigma, theta):
    dist = 2.0 * (1.0 - jnp.matmul(Y.T, Z_cos, precision=_HI))
    kmeans_err = jnp.sum(R * dist)
    entropy = jnp.sum(R * jnp.log(jnp.maximum(R, 1e-12)) * sigma[:, None])
    diversity = jnp.sum(
        sigma[:, None] * theta * O * jnp.log((O + 1.0) / (E + 1.0)))
    return kmeans_err + entropy + diversity


@jax.jit
def _moe_ridge_scan(Z_orig, R, Phi_moe, lamb):
    """Z_corr = Z_orig - sum_k W_k^T Phi_Rk with per-cluster ridge experts
    W_k = (Phi_Rk Phi_moe^T + lamb)^{-1} Phi_Rk Z_orig^T, intercept row
    zeroed (the correction never removes the global mean) — the
    ``moe_correct_ridge`` contract (preprocess.py:9-18).

    ``lamb``: full (B+1) x (B+1) ridge matrix (harmonypy carries a matrix;
    callers with a diagonal pass ``jnp.diag`` of it)."""

    def body(Z_corr, Rk):
        Phi_Rk = Phi_moe * Rk[None, :]
        x = jnp.matmul(Phi_Rk, Phi_moe.T, precision=_HI) + lamb
        rhs = jnp.matmul(Phi_Rk, Z_orig.T, precision=_HI)
        W = jnp.linalg.solve(x, rhs)
        W = W.at[0, :].set(0.0)
        Z_corr = Z_corr - jnp.matmul(W.T, Phi_Rk, precision=_HI)
        return Z_corr, None

    Z_corr, _ = jax.lax.scan(body, Z_orig, R)
    return Z_corr


def moe_correct_ridge(Z_orig, R, Phi_moe, lamb) -> np.ndarray:
    """Apply the mixture-of-experts ridge correction to a (features x cells)
    matrix. ``lamb`` is either the (B+1,) ridge diagonal (intercept entry 0)
    or the full (B+1) x (B+1) matrix as harmonypy's result object carries it
    (``preprocess.py:382`` passes ``ho.lamb`` straight through)."""
    lamb = jnp.asarray(np.asarray(lamb), jnp.float32)
    if lamb.ndim == 1:
        lamb = jnp.diag(lamb)
    return np.asarray(_moe_ridge_scan(
        jnp.asarray(np.asarray(Z_orig), jnp.float32),
        jnp.asarray(np.asarray(R), jnp.float32),
        jnp.asarray(np.asarray(Phi_moe), jnp.float32),
        lamb))


def harmony_program_shapes(n: int, nclust: int | None = None,
                           block_size: float = 0.05):
    """``(K, n_blocks, n_pad)`` for an n-cell harmony run — the ONE
    derivation of the cluster count and block split, shared by
    :func:`run_harmony` and the ``Preprocess`` program warmer so the
    warmer can never compile for shapes production won't dispatch."""
    if nclust is None:
        nclust = int(min(np.round(n / 30.0), 100))
    K = max(int(nclust), 2)
    n_blocks = max(1, int(np.ceil(1.0 / block_size)))
    blk_len = int(np.ceil(n / n_blocks))
    return K, n_blocks, n_blocks * blk_len


def run_harmony(data_mat, meta_data: pd.DataFrame, vars_use, theta=2.0,
                lamb=1.0, sigma: float = 0.1, nclust: int | None = None,
                max_iter_harmony: int = 10, max_iter_kmeans: int = 20,
                epsilon_cluster: float = 1e-5, epsilon_harmony: float = 1e-4,
                block_size: float = 0.05, random_state: int = 0) -> HarmonyResult:
    """Harmonize a (cells x d) embedding over the batch variables.

    Returns a :class:`HarmonyResult`; ``Z_corr`` is d x n (harmonypy
    orientation, transpose for cells x d).
    """
    Z = np.asarray(data_mat, dtype=np.float32).T      # d x n
    d, n = Z.shape
    phi = _one_hot_design(meta_data, vars_use)        # B x n
    B = phi.shape[0]
    K, _n_blocks_shared, _n_pad_shared = harmony_program_shapes(
        n, nclust, block_size)

    theta_vec = np.full(B, float(theta), dtype=np.float32)
    lamb_diag = np.concatenate([[0.0], np.full(B, float(lamb))]).astype(np.float32)
    sigma_vec = jnp.full((K,), float(sigma), dtype=jnp.float32)
    Pr_b = jnp.asarray(phi.sum(axis=1) / n, jnp.float32)
    Phi_moe = np.concatenate([np.ones((1, n), np.float32), phi], axis=0)

    Z_cos = np.asarray(_normalize_cols(jnp.asarray(Z)))
    phi_d = jnp.asarray(phi)
    Phi_moe_d = jnp.asarray(Phi_moe)
    theta_d = jnp.asarray(theta_vec)

    # init: hard kmeans on the cosine embedding, then soft assignments
    labels, centers, _ = kmeans(Z_cos.T, K, n_init=10, max_iter=25,
                                seed=random_state)
    Y = _normalize_cols(jnp.asarray(centers.T))       # d x K
    R = _assign_R(Y, jnp.asarray(Z_cos), sigma_vec)   # K x n
    E = jnp.outer(R.sum(axis=1), Pr_b)
    O = jnp.matmul(R, phi_d.T, precision=_HI)

    rng = np.random.default_rng(random_state)
    n_blocks, n_pad = _n_blocks_shared, _n_pad_shared
    objectives: list[float] = []
    Z_corr = jnp.asarray(Z)
    lamb_mat = jnp.diag(jnp.asarray(lamb_diag))

    for _harmony_iter in range(max_iter_harmony):
        # --- clustering phase: ONE device program (ops/harmony.py:
        # _cluster_phase) instead of one dispatch per cell block — the
        # permutations are drawn host-side up front, padded with sentinel
        # index n (masked out on device)
        perms = np.full((max_iter_kmeans, n_pad), n, dtype=np.int32)
        for i in range(max_iter_kmeans):
            perms[i, :n] = rng.permutation(n)
        R, E, O, obj_prev, obj, _rounds = _cluster_phase(
            _normalize_cols(Z_corr), R, phi_d, E, O,
            jnp.asarray(perms), Pr_b, sigma_vec,
            theta_d, jnp.float32(epsilon_cluster), n_blocks,
            int(max_iter_kmeans))
        obj_prev, obj = float(obj_prev), float(obj)
        # the host loop appended the pre-break objective on convergence and
        # the final one on exhaustion
        converged = abs(obj_prev - obj) < epsilon_cluster * abs(obj_prev)
        objectives.append(obj_prev if converged else obj)

        # --- correction ----------------------------------------------
        Z_corr = _moe_ridge_scan(jnp.asarray(Z), R, Phi_moe_d, lamb_mat)

        if len(objectives) >= 3:
            o = objectives
            if abs(o[-2] - o[-1]) < epsilon_harmony * abs(o[-2]):
                break

    return HarmonyResult(
        Z_corr=np.asarray(Z_corr),
        Z_cos=np.asarray(_normalize_cols(Z_corr)),
        R=np.asarray(R),
        Phi_moe=Phi_moe,
        # full matrix, matching the harmonypy result surface the reference
        # forwards into moe_correct_ridge (preprocess.py:382)
        lamb=np.diag(lamb_diag),
        K=K,
        objectives=objectives,
    )
