"""Out-of-core shard-store ingestion (ISSUE 10): writer/reader round
trips, digest-validated torn-read containment, per-worker slab ownership,
store-backed staging parity (dense / CSR / ELL / 2-D, ragged + all-zero
slabs), the slab-residency budget accounting, the slab-looped rowshard
tier, and the prepare-side store lifecycle (auto threshold, h5ad skip,
stale-store sweep, f32 norm counts).

Runs on the simulated multi-device CPU mesh from conftest.
"""

import json
import os

import jax
import numpy as np
import pytest
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cnmf_torch_tpu.utils import shardstore as ss
from cnmf_torch_tpu.utils.shardstore import (
    HostResidency,
    ShardStore,
    SlabCursor,
    TornShardError,
    open_shard_store,
    probe_shard_store,
    write_shard_store,
)


@pytest.fixture()
def mesh():
    return Mesh(np.asarray(jax.devices()[:4]), ("cells",))


def _dense(n=219, g=37, seed=0):
    return np.abs(np.random.default_rng(seed).random((n, g))
                  ).astype(np.float32)


def _csr(n=219, g=37, seed=1, density=0.15):
    X = sp.random(n, g, density=density, format="lil", random_state=seed)
    X[40:60, :] = 0.0              # a fully-zero row band spanning a slab
    X[n - 1, :] = 0.0              # empty final row (ragged tail)
    return sp.csr_matrix(X).astype(np.float32)


# ---------------------------------------------------------------------------
# writer / reader round trips
# ---------------------------------------------------------------------------

def test_write_open_roundtrip_dense(tmp_path):
    X = _dense()
    man = write_shard_store(tmp_path / "st", X, slab_rows=50)
    store = open_shard_store(tmp_path / "st")
    assert store.shape == X.shape and store.format == "dense"
    # ragged final slab: 219 rows at 50/slab -> 5 slabs, last is 19 rows
    assert len(store.slabs) == 5
    assert store.slabs[-1]["row1"] - store.slabs[-1]["row0"] == 19
    assert np.array_equal(store.to_matrix(), X)
    assert man["store_digest"] == store.store_digest


def test_write_open_roundtrip_csr(tmp_path):
    X = _csr()
    write_shard_store(tmp_path / "st", X, slab_rows=64)
    store = open_shard_store(tmp_path / "st")
    assert store.format == "csr"
    assert store.nnz == X.nnz
    assert store.max_row_nnz == int(np.diff(X.indptr).max())
    out = store.to_matrix()
    assert sp.issparse(out)
    assert np.array_equal(out.toarray(), X.toarray())


def test_names_roundtrip_and_row_block(tmp_path):
    X = _csr(101, 23)
    obs = [f"cell{i}" for i in range(101)]
    var = [f"gene{j}" for j in range(23)]
    write_shard_store(tmp_path / "st", X, obs_names=obs, var_names=var,
                      slab_rows=40)
    store = open_shard_store(tmp_path / "st")
    assert store.obs_names() == obs and store.var_names() == var
    blk = store.row_block(35, 85)  # spans slabs 0, 1, 2
    assert np.array_equal(blk.toarray(), X[35:85].toarray())


def test_store_write_is_f32(tmp_path):
    X = np.random.default_rng(2).random((40, 8)).astype(np.float64)
    write_shard_store(tmp_path / "st", X, slab_rows=16)
    store = open_shard_store(tmp_path / "st")
    assert store.dtype == np.float32
    assert store.read_slab(0).dtype == np.float32


def test_rewrite_clears_stale_slabs(tmp_path):
    write_shard_store(tmp_path / "st", _dense(219), slab_rows=20)  # 11 slabs
    write_shard_store(tmp_path / "st", _dense(60), slab_rows=30)   # 2 slabs
    store = open_shard_store(tmp_path / "st")
    assert len(store.slabs) == 2
    files = [f for f in os.listdir(tmp_path / "st") if f.startswith("slab_")]
    assert len(files) == 2  # no orphans a manifest never references


# ---------------------------------------------------------------------------
# validation + torn-read containment
# ---------------------------------------------------------------------------

def test_open_rejects_structural_damage(tmp_path):
    X = _dense(100, 10)
    write_shard_store(tmp_path / "st", X, slab_rows=40)
    man_path = tmp_path / "st" / "manifest.json"
    man = json.loads(man_path.read_text())

    os.unlink(tmp_path / "st" / man["slabs"][1]["file"])
    with pytest.raises(TornShardError, match="missing"):
        open_shard_store(tmp_path / "st")

    write_shard_store(tmp_path / "st", X, slab_rows=40)
    man = json.loads(man_path.read_text())
    man["slabs"][1]["row0"] += 1  # ranges no longer a contiguous partition
    man_path.write_text(json.dumps(man))
    with pytest.raises(TornShardError, match="contiguous"):
        open_shard_store(tmp_path / "st")

    man_path.write_text("{not json")
    store, reason = probe_shard_store(tmp_path / "st")
    assert store is None and "manifest" in reason
    assert probe_shard_store(tmp_path / "missing") == (None, "missing")


def test_torn_slab_detected_and_fails_loudly(tmp_path):
    X = _dense(80, 12)
    write_shard_store(tmp_path / "st", X, slab_rows=40)
    store = open_shard_store(tmp_path / "st")
    path = os.path.join(store.dir, store.slabs[1]["file"])
    with open(path, "r+b") as f:  # persistent corruption: flip one byte
        f.seek(30)
        b = f.read(1)
        f.seek(30)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.warns(RuntimeWarning, match="re-reading from disk"):
        with pytest.raises(TornShardError, match="after"):
            store.read_slab(1)
    assert np.array_equal(store.read_slab(0), X[:40])  # slab 0 untouched


def test_injected_torn_read_heals_by_reread(tmp_path, monkeypatch):
    from cnmf_torch_tpu.runtime import faults

    X = _csr(90, 15)
    write_shard_store(tmp_path / "st", X, slab_rows=30)
    store = open_shard_store(tmp_path / "st")
    # distinct spec string per test: the parsed-clause cache keys on the
    # raw spec, and clause hit counters live inside the cached objects
    monkeypatch.setenv(faults.FAULT_SPEC_ENV,
                       "shard_read:context=slab,limit=1")
    with pytest.warns(RuntimeWarning, match="re-reading from disk"):
        blk = store.read_slab(0)
    # healed: the re-read saw clean bytes, data is exact
    assert np.array_equal(blk.toarray(), X[:30].toarray())
    # clause limit=1: subsequent reads are clean
    assert np.array_equal(store.read_slab(1).toarray(),
                          X[30:60].toarray())


# ---------------------------------------------------------------------------
# ownership: per-worker / per-host row ranges
# ---------------------------------------------------------------------------

def test_worker_ranges_partition_and_ownership(tmp_path):
    X = _dense(219)
    write_shard_store(tmp_path / "st", X, slab_rows=50)  # 5 slabs
    store = open_shard_store(tmp_path / "st")
    ranges = store.worker_ranges(2)
    assert ranges[0][0] == 0 and ranges[-1][1] == 219
    assert ranges[0][1] == ranges[1][0]  # contiguous
    # slab-aligned: no slab is opened by two workers
    opened = [set(store.slab_indices_for_rows(*r)) for r in ranges]
    assert opened[0].isdisjoint(opened[1])
    assert opened[0] | opened[1] == set(range(5))
    # more workers than slabs -> empty trailing ranges, never an error
    many = store.worker_ranges(9)
    assert len(many) == 9
    assert sum(1 for lo, hi in many if hi > lo) <= 5


def test_cursor_reads_only_own_slabs(tmp_path):
    """The acceptance pin: a worker's cursor physically cannot open
    another worker's slabs, and the spy ledger proves which were read."""
    X = _dense(219)
    write_shard_store(tmp_path / "st", X, slab_rows=50)
    store = open_shard_store(tmp_path / "st")
    lo, hi = store.worker_ranges(2)[0]
    cur = SlabCursor(store, rows=(lo, hi))
    for si, _, _ in cur.tasks():
        cur.read(si)
    own = set(store.slab_indices_for_rows(lo, hi))
    assert set(cur.slabs_read) == own and own < set(range(5))
    other = next(i for i in range(5) if i not in own)
    with pytest.raises(ValueError, match="own row-range"):
        cur.read(other)
    with pytest.raises(ValueError, match="outside store rows"):
        SlabCursor(store, rows=(0, 10_000))


def test_simulated_pod_process_reads_only_its_slabs(tmp_path, mesh,
                                                    monkeypatch):
    """A multihost process enumerates only its ADDRESSABLE shards
    (streaming._shard_slices) — simulate a 2-process pod by restricting
    the map to the first half of the mesh and pin, via the cursor's
    read ledger, that only the overlapping slabs were opened."""
    from cnmf_torch_tpu.parallel import streaming
    from cnmf_torch_tpu.parallel.streaming import stream_store_sharded

    X = _dense(200, 16)
    write_shard_store(tmp_path / "st", X, slab_rows=25)  # 8 slabs
    store = open_shard_store(tmp_path / "st")
    sharding = NamedSharding(mesh, P("cells", None))
    orig = streaming._shard_slices

    def first_half(sh, shape):
        out = sorted(orig(sh, shape), key=lambda t: t[1])
        return out[:2]  # "this process" addresses devices 0-1 = rows 0:100

    monkeypatch.setattr(streaming, "_shard_slices", first_half)
    # a single-process jax cannot assemble a global array from half the
    # shards (on a real pod the other processes contribute theirs) —
    # capture the local blocks instead of assembling
    got = {}
    monkeypatch.setattr(
        jax, "make_array_from_single_device_arrays",
        lambda shape, sh, blocks: got.update(blocks=blocks) or blocks)
    cur = SlabCursor(store)
    stream_store_sharded(cur, sharding, pad_rows=0)
    own = set(store.slab_indices_for_rows(0, 100))
    assert set(cur.slabs_read) == own < set(range(8))
    # and this process's blocks carry exactly its rows
    local = np.concatenate([np.asarray(b) for b in got["blocks"]], axis=0)
    assert np.array_equal(local, X[:100])


def test_host_residency_ledger():
    r = HostResidency()
    r.charge(100)
    r.charge(50)
    r.release(100)
    r.charge(30)
    assert r.live == 80 and r.peak == 150


# ---------------------------------------------------------------------------
# staging parity (the bit-identity backbone of the dispatch claim)
# ---------------------------------------------------------------------------

def test_stream_store_dense_parity_ragged(tmp_path, mesh):
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = _dense(219)  # ragged final slab AND ragged vs the 4-way mesh
    write_shard_store(tmp_path / "st", X, slab_rows=50)
    store = open_shard_store(tmp_path / "st")
    A, pad_a = stream_rows_to_mesh(store, mesh, "cells")
    B, pad_b = stream_rows_to_mesh(X, mesh, "cells")
    assert pad_a == pad_b
    assert np.array_equal(np.asarray(A), np.asarray(B))


def test_stream_store_csr_parity_zero_slab(tmp_path, mesh):
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = _csr(219)
    write_shard_store(tmp_path / "st", X, slab_rows=20)
    store = open_shard_store(tmp_path / "st")
    # the zero band covers rows 40:60 -> slab 2 is entirely zero rows
    assert store.slabs[2]["nnz"] == 0
    A, _ = stream_rows_to_mesh(store, mesh, "cells")
    B, _ = stream_rows_to_mesh(X, mesh, "cells")
    assert np.array_equal(np.asarray(A), np.asarray(B))


def test_stream_store_ell_parity(tmp_path, mesh):
    from cnmf_torch_tpu.parallel.rowshard import stream_ell_to_mesh

    X = _csr(219)
    write_shard_store(tmp_path / "st", X, slab_rows=60)
    store = open_shard_store(tmp_path / "st")
    E1, pad1 = stream_ell_to_mesh(store, mesh, "cells")
    E2, pad2 = stream_ell_to_mesh(X, mesh, "cells")
    assert pad1 == pad2 and E1.width == E2.width
    for leaf in ("vals", "cols", "rows_t", "perm_t"):
        assert np.array_equal(np.asarray(getattr(E1, leaf)),
                              np.asarray(getattr(E2, leaf)))


def test_stream_store_pad_only_shards(tmp_path, mesh):
    """Fewer data rows than devices: trailing shards are pure mesh
    padding — all zeros, zero disk reads."""
    from cnmf_torch_tpu.parallel.rowshard import stream_rows_to_mesh

    X = _dense(2, 9)
    write_shard_store(tmp_path / "st", X, slab_rows=1)
    store = open_shard_store(tmp_path / "st")
    A, pad = stream_rows_to_mesh(store, mesh, "cells")
    assert pad == 2 and A.shape == (4, 9)
    got = np.asarray(A)
    assert np.array_equal(got[:2], X) and not got[2:].any()


def test_stage_x_2d_from_store_ragged_and_zero_slab(tmp_path):
    """Satellite: the 2-D path accepts cursor/store input — parity against
    the ndarray/CSR path with a ragged final slab and an all-zero-row
    slab in the store."""
    from cnmf_torch_tpu.parallel.multihost import stage_x_2d

    devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
    mesh2 = Mesh(devs, ("replicates", "cells"))
    X = _csr(219)
    write_shard_store(tmp_path / "st", X, slab_rows=20)
    store = open_shard_store(tmp_path / "st")
    assert store.slabs[2]["nnz"] == 0
    A = stage_x_2d(store, mesh2)
    B = stage_x_2d(X, mesh2)
    assert np.array_equal(np.asarray(A), np.asarray(B))
    # cursor spelling (what a pod process holds) stages identically
    C = stage_x_2d(SlabCursor(store), mesh2)
    assert np.array_equal(np.asarray(C), np.asarray(B))


def test_host_residency_bounded_by_budget(tmp_path, mesh, monkeypatch):
    """The tentpole's allocation-accounting pin: in-flight host slab
    bytes during store-backed staging never exceed the budget, and stay
    far below the matrix's own footprint."""
    from cnmf_torch_tpu.parallel.streaming import (StreamStats,
                                                   stream_store_sharded)

    X = _dense(512, 64)  # 128 KB matrix
    write_shard_store(tmp_path / "st", X, slab_rows=32)  # 8 KB slabs
    store = open_shard_store(tmp_path / "st")
    budget = 64 << 10
    monkeypatch.setenv(ss.OOC_BUDGET_ENV, str(budget))
    stats = StreamStats()
    sharding = NamedSharding(mesh, P("cells", None))
    out = stream_store_sharded(SlabCursor(store), sharding, stats=stats)
    assert np.array_equal(np.asarray(out), X)
    assert 0 < stats.host_peak_bytes <= budget
    assert stats.host_peak_bytes < X.nbytes
    assert stats.disk_nbytes == store.store_bytes
    assert stats.disk_s > 0 and stats.read_gb_per_s() > 0


# ---------------------------------------------------------------------------
# slab-looped rowshard tier
# ---------------------------------------------------------------------------

def test_store_dispatch_budget(tmp_path, mesh, monkeypatch):
    from cnmf_torch_tpu.parallel.rowshard import store_dispatch

    write_shard_store(tmp_path / "st", _dense(512, 64), slab_rows=64)
    store = open_shard_store(tmp_path / "st")
    use_ell, slab_loop = store_dispatch(store, mesh, 2.0)
    assert not use_ell and not slab_loop  # fits the default budget
    monkeypatch.setenv(ss.OOC_SHARD_BYTES_ENV, "1024")
    assert store_dispatch(store, mesh, 2.0) == (False, True)
    # nndsvd init has no slab-looped program: stays resident, loudly
    with pytest.warns(RuntimeWarning, match="staging resident"):
        _, slab_loop = store_dispatch(store, mesh, 2.0, init="nndsvd")
    assert not slab_loop


def test_rowshard_store_resident_bit_parity(tmp_path, mesh):
    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

    X = _dense(256, 32)
    write_shard_store(tmp_path / "st", X, slab_rows=60)
    store = open_shard_store(tmp_path / "st")
    H1, W1, e1 = nmf_fit_rowsharded(X, 5, mesh, seed=3, n_passes=4)
    H2, W2, e2 = nmf_fit_rowsharded(store, 5, mesh, seed=3, n_passes=4)
    assert np.array_equal(H1, H2) and np.array_equal(W1, W2) and e1 == e2


@pytest.mark.parametrize("beta_loss", ["frobenius", "kullback-leibler"])
def test_rowshard_slab_loop_solver_tolerance(tmp_path, mesh, monkeypatch,
                                             beta_loss):
    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

    X = _dense(256, 32)
    write_shard_store(tmp_path / "st", X, slab_rows=60)
    store = open_shard_store(tmp_path / "st")
    H1, W1, e1 = nmf_fit_rowsharded(X, 5, mesh, beta_loss=beta_loss,
                                    seed=3, n_passes=6)
    monkeypatch.setenv(ss.OOC_SHARD_BYTES_ENV, "2048")
    H2, W2, e2 = nmf_fit_rowsharded(store, 5, mesh, beta_loss=beta_loss,
                                    seed=3, n_passes=6)
    assert H2.shape == H1.shape and W2.shape == W1.shape
    assert np.isfinite(e2) and (H2 >= 0).all() and (W2 >= 0).all()
    # group-wise H solves make this tier tolerance-equivalent, not
    # bit-identical: objectives agree to a few percent after 6 passes
    assert abs(e2 - e1) / max(abs(e1), 1e-9) < 0.1


def test_slab_loop_checkpoint_resume_bit_parity(tmp_path, mesh, monkeypatch):
    """Interrupt the slab-looped tier mid-run and resume: with H inside
    the checkpoint byte budget the continuation is BIT-identical to the
    uninterrupted solve (same contract as the resident checkpointed
    loop)."""
    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded
    from cnmf_torch_tpu.runtime.checkpoint import PassCheckpointer

    X = _dense(256, 32)
    write_shard_store(tmp_path / "st", X, slab_rows=60)
    store = open_shard_store(tmp_path / "st")
    monkeypatch.setenv(ss.OOC_SHARD_BYTES_ENV, "2048")
    meta = {"k": 5, "iter": 0, "seed": 3, "attempt": 0,
            "digest": "store:" + store.store_digest, "beta": 2.0,
            "params": "t"}
    kw = dict(seed=3, n_passes=5)

    # spy disk reads: a resume that silently restarted from scratch would
    # stream every pass's slabs again and still match bit-for-bit (the
    # solver is deterministic) — the read count is what proves the
    # continuation actually started from the pass-2 cursor
    reads = {"n": 0}
    orig_read = ShardStore.read_slab

    def counting_read(self, i, **kwargs):
        reads["n"] += 1
        return orig_read(self, i, **kwargs)

    monkeypatch.setattr(ShardStore, "read_slab", counting_read)
    full_ck = PassCheckpointer(str(tmp_path / "full.npz"), 1, meta=meta)
    H1, W1, e1 = nmf_fit_rowsharded(store, 5, mesh, checkpoint=full_ck,
                                    **kw)
    full_reads, reads["n"] = reads["n"], 0

    part = PassCheckpointer(str(tmp_path / "part.npz"), 1, meta=meta)
    nmf_fit_rowsharded(store, 5, mesh, seed=3, n_passes=2,
                       checkpoint=part)  # "interrupted" after pass 2
    reads["n"] = 0
    resumed = PassCheckpointer(str(tmp_path / "part.npz"), 1, meta=meta,
                               resume=True)
    H2, W2, e2 = nmf_fit_rowsharded(store, 5, mesh, checkpoint=resumed,
                                    **kw)
    assert np.array_equal(H1, H2) and np.array_equal(W1, W2) and e1 == e2
    # the full solve streams 5 passes' worth of slabs; the resumed one
    # only passes 3..5 (3/5 of the reads)
    assert 0 < reads["n"] <= (full_reads * 3) // 5 + 1


# ---------------------------------------------------------------------------
# prepare/factorize lifecycle
# ---------------------------------------------------------------------------

def _mini_cnmf(tmp_path, name="st"):
    import pandas as pd

    from cnmf_torch_tpu import cNMF
    from cnmf_torch_tpu.utils import save_df_to_npz

    rng = np.random.default_rng(3)
    usage = rng.dirichlet(np.ones(4) * 0.3, size=120)
    spectra = rng.gamma(0.3, 1.0, size=(4, 90)) * 40.0 / 90
    counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(120)],
                      columns=[f"g{j}" for j in range(90)])
    os.makedirs(tmp_path, exist_ok=True)
    fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, fn)
    obj = cNMF(output_dir=str(tmp_path), name=name)
    return obj, fn


def test_prepare_auto_store_threshold(tmp_path, monkeypatch):
    obj, fn = _mini_cnmf(tmp_path / "a")
    # default budget (1 GiB) >> matrix: auto mode writes NO store
    obj.prepare(fn, components=[3], n_iter=2, seed=7,
                num_highvar_genes=60)
    assert obj._probe_store() is None
    assert os.path.exists(obj.paths["normalized_counts"])
    # budget below the matrix: auto writes the store AND keeps the h5ad
    monkeypatch.setenv(ss.OOC_BUDGET_ENV, "4096")
    obj2, fn2 = _mini_cnmf(tmp_path / "b")
    obj2.prepare(fn2, components=[3], n_iter=2, seed=7,
                 num_highvar_genes=60)
    store = obj2._probe_store()
    assert store is not None
    assert os.path.exists(obj2.paths["normalized_counts"])
    # the store holds exactly the h5ad's matrix (f32 both sides)
    from cnmf_torch_tpu.utils.anndata_lite import read_h5ad

    h5 = read_h5ad(obj2.paths["normalized_counts"])
    a = h5.X.toarray() if sp.issparse(h5.X) else np.asarray(h5.X)
    b = store.to_matrix()
    b = b.toarray() if sp.issparse(b) else b
    assert np.array_equal(a.astype(np.float32), b)


def test_ooc1_skips_h5ad_and_assembles(tmp_path, monkeypatch):
    monkeypatch.setenv(ss.OOC_ENV, "1")
    obj, fn = _mini_cnmf(tmp_path)
    obj.prepare(fn, components=[3], n_iter=2, seed=7, num_highvar_genes=60)
    assert not os.path.exists(obj.paths["normalized_counts"])
    store = obj._probe_store()
    assert store is not None
    with pytest.warns(RuntimeWarning, match="assembling the full matrix"):
        nc = obj._read_norm_counts()
    assert nc.X.shape == store.shape
    assert list(nc.var.index) == store.var_names()


def test_norm_counts_land_f32(tmp_path):
    """Satellite: the normalized h5ad lands f32 (f64 only ever lives in
    the moment accumulators), and the values are the f32 rounding of the
    exact f64 quotients."""
    obj, fn = _mini_cnmf(tmp_path)
    obj.prepare(fn, components=[3], n_iter=2, seed=7, num_highvar_genes=60)
    from cnmf_torch_tpu.utils.anndata_lite import read_h5ad

    X = read_h5ad(obj.paths["normalized_counts"]).X
    assert X.dtype == np.float32


def test_stale_store_swept(tmp_path, monkeypatch):
    monkeypatch.setenv(ss.OOC_BUDGET_ENV, "4096")
    obj, fn = _mini_cnmf(tmp_path)
    obj.prepare(fn, components=[3], n_iter=2, seed=7, num_highvar_genes=60)
    store = obj._probe_store()
    assert store is not None and not obj._store_stale(store)
    # tamper: shrink the manifest's shape -> metadata mismatch vs h5ad
    man_path = os.path.join(obj.paths["shard_store"], "manifest.json")
    man = json.loads(open(man_path).read())
    man["shape"][1] -= 1
    open(man_path, "w").write(json.dumps(man))
    store = obj._probe_store()
    assert obj._store_stale(store)
    # plus an orphaned atomic temp from a "killed" writer
    orphan = os.path.join(obj.paths["shard_store"], "slab_9.npz.tmp-123")
    open(orphan, "w").write("junk")
    with pytest.warns(RuntimeWarning, match="stale store"):
        obj._sweep_stale_store(store)
    assert not os.path.exists(orphan)
    assert obj._probe_store() is None  # store removed


def test_scale_columns_out_dtype_parity():
    """Satellite: f32 output is the rounding of the exact f64 quotients —
    identical to casting the legacy f64 result — for dense and CSR."""
    from cnmf_torch_tpu.ops.stats import scale_columns

    rng = np.random.default_rng(5)
    Xd = rng.random((50, 17)) * 100
    ref, std_ref = scale_columns(Xd, ddof=1)
    got, std = scale_columns(Xd, ddof=1, out_dtype=np.float32)
    assert got.dtype == np.float32
    assert np.array_equal(got, ref.astype(np.float32))
    assert np.array_equal(std, std_ref)
    Xs = sp.random(60, 17, density=0.3, format="csr", random_state=1)
    Xs.data *= 50
    ref_s, _ = scale_columns(Xs, ddof=1)
    got_s, _ = scale_columns(Xs, ddof=1, out_dtype=np.float32)
    assert got_s.dtype == np.float32
    assert np.array_equal(got_s.toarray(),
                          ref_s.toarray().astype(np.float32))


def test_launcher_clean_sweeps_store(tmp_path, monkeypatch):
    """Satellite: --clean removes shard-store temp orphans (the store
    itself survives — it is a prepare artifact, reusable on resume)."""
    monkeypatch.setenv(ss.OOC_BUDGET_ENV, "4096")
    obj, fn = _mini_cnmf(tmp_path, name="cl")
    obj.prepare(fn, components=[3], n_iter=2, seed=7, num_highvar_genes=60)
    store_dir = obj.paths["shard_store"]
    orphan = os.path.join(store_dir, "slab_00007.npz.tmp-999")
    open(orphan, "w").write("junk")
    from cnmf_torch_tpu.launcher import _clean_run_dir

    _clean_run_dir(os.path.join(str(tmp_path), "cl"))
    assert not os.path.exists(orphan)
    assert os.path.exists(os.path.join(store_dir, "manifest.json"))
