"""Principal component analysis: device matmuls, host eigensolve.

Replaces the reference's ``sc.pp.pca`` call in the batch-correction path
(``/root/reference/src/cnmf/preprocess.py:250-338``). The factorization is
computed from the smaller gram matrix (g x g or n x n, whichever is
smaller): TPU's iterative SVD on an 8.5k x 2k input takes minutes, the gram
path is one MXU matmul (squared condition number is harmless for the
leading components PCA keeps).

The eigensolve itself runs on HOST LAPACK in float64, not ``jnp.eigh``:
the device eigh program for a 2000 x 2000 operand is a ~30 s XLA compile
whose persistent-cache entry still costs ~10-30 s per process to
deserialize + upload through a tunneled link (measured, round 5), while
host ``dsyevd`` at that shape is a flat ~1.9 s with no compile at all —
and is more accurate than the f32 device solve. Only the O(n g min(n,g))
matmuls (gram, projection) run on device; their programs compile in ~1 s.
Signs are fixed to scanpy/sklearn's ``svd_flip`` convention
(largest-|loading| positive per component) so downstream Harmony runs see
the same basis orientation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

__all__ = ["pca"]

_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("zero_center", "small_side"))
def _pca_gram(X, zero_center: bool, small_side: str):
    """Gram matrix of the (optionally centered) data over its smaller side:
    ``small_side='g'`` -> (g, g) X^T X, ``'n'`` -> (n, n) X X^T."""
    if zero_center:
        X = X - jnp.mean(X, axis=0, keepdims=True)
    if small_side == "g":
        return jnp.matmul(X.T, X, precision=_HI)
    return jnp.matmul(X, X.T, precision=_HI)


@functools.partial(jax.jit, static_argnames=("zero_center",))
def _pca_project(X, V, zero_center: bool):
    """(n, k) scores: (X - mean) @ V."""
    if zero_center:
        X = X - jnp.mean(X, axis=0, keepdims=True)
    return jnp.matmul(X, V, precision=_HI)


@functools.partial(jax.jit, static_argnames=("zero_center",))
def _pca_components(X, U_over_S, zero_center: bool):
    """(k, g) loadings for the n < g branch: (U / S)^T @ (X - mean)."""
    if zero_center:
        X = X - jnp.mean(X, axis=0, keepdims=True)
    return jnp.matmul(U_over_S.T, X, precision=_HI)


def pca(X, n_comps: int = 50, zero_center: bool = True):
    """Returns ``(X_pca (n, n_comps), components (n_comps, g),
    explained_variance_ratio (n_comps,))`` as numpy arrays."""
    if sp.issparse(X):
        X = X.toarray()
    X = np.asarray(X, dtype=np.float32)
    n, g = X.shape
    n_comps = int(min(n_comps, min(X.shape) - 1 if zero_center else min(X.shape)))
    Xd = jnp.asarray(X)

    small_side = "g" if g <= n else "n"
    G = np.asarray(_pca_gram(Xd, bool(zero_center), small_side),
                   dtype=np.float64)
    evals, evecs = np.linalg.eigh(G)                       # ascending
    S = np.sqrt(np.clip(evals[::-1][:n_comps], 0.0, None))

    if small_side == "g":
        V = np.ascontiguousarray(evecs[:, ::-1][:, :n_comps])   # (g, k)
        Vt = V.T
        X_pca = np.asarray(_pca_project(Xd, jnp.asarray(V, jnp.float32),
                                        bool(zero_center)),
                           dtype=np.float64)               # = U * S
    else:
        U = np.ascontiguousarray(evecs[:, ::-1][:, :n_comps])   # (n, k)
        # rank-overflow guard (cf. ops/nmf.py:gram_svd_base): S ~ 0 columns
        # would divide fp32 noise by EPS
        ok = S > 1e-6 * max(S[0] if S.size else 0.0, 1e-30)
        U_over_S = np.where(ok[None, :], U / np.maximum(S, 1e-30)[None, :],
                            0.0)
        Vt = np.asarray(_pca_components(
            Xd, jnp.asarray(U_over_S, jnp.float32), bool(zero_center)),
            dtype=np.float64)
        Vt = np.where(ok[:, None], Vt, 0.0)
        X_pca = U * S[None, :]

    # svd_flip: orient each component so its largest-|value| loading is
    # positive (removes the sign ambiguity; matches sklearn/scanpy)
    max_idx = np.argmax(np.abs(Vt), axis=1)
    signs = np.sign(Vt[np.arange(n_comps), max_idx])
    signs[signs == 0] = 1.0
    Vt = Vt * signs[:, None]
    X_pca = X_pca * signs[None, :]
    explained_var = (S ** 2) / max(n - 1, 1)

    if zero_center:
        total_var = float(np.var(X, axis=0, ddof=1).sum())
    else:
        # uncentered SVD energy includes the mean component, so the ratio
        # denominator must be the uncentered second moment or ratios blow
        # past 1 for data with a large mean offset
        total_var = float((np.asarray(X, np.float64) ** 2).sum()
                          / max(X.shape[0] - 1, 1))
    ratio = np.asarray(explained_var, dtype=np.float64) / max(total_var, 1e-30)
    return np.asarray(X_pca, np.float32), np.asarray(Vt, np.float32), ratio
