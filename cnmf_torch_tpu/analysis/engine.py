"""``cnmf-tpu lint`` — the codebase-aware AST rule engine.

The package's hardest-won guarantees are invariants no generic linter
knows about: artifact writes must be atomic (``--skip-completed-runs``
and ``combine`` trust what they probe), telemetry events must match the
ONE schema in ``utils/telemetry.py``, env knobs must parse through the
``utils/envknobs.py`` registry, host-sync calls must never hide inside a
jitted scope (a silent ``.item()`` in a ``shard_map`` body is a
per-dispatch device flush at pod scale), and module-level mutable state
must be mutated under its module's lock (the StageTimer/``trace()`` bug
class from PRs 1 and 3). This engine makes those invariants machine
checked: per-file AST visitors produce :class:`Finding`\\ s with
``file:line``, a stable rule id, and a fix hint; ``# cnmf-lint:
disable=RULE`` comments suppress single sites; a checked-in baseline
file grandfathers legacy findings (the shipped baseline is EMPTY — the
package itself lints clean); and ``scripts/lint_gate.py`` wires the whole
thing into tier-1.

Rule families live in sibling modules (``rules_trace``, ``rules_knobs``,
``rules_artifacts``, ``rules_telemetry``, ``rules_concurrency``); this
module owns the shared AST utilities (import-alias resolution, parent
links, dotted-name resolution), suppression/baseline semantics, output
formatting, and the CLI. Nothing here imports jax — lint runs anywhere,
instantly.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

__all__ = [
    "Finding",
    "FileContext",
    "LintResult",
    "ALL_RULE_IDS",
    "RULE_FAMILIES",
    "DEFAULT_BASELINE",
    "lint_paths",
    "load_baseline",
    "write_baseline",
    "format_text",
    "format_json",
    "main",
]

SUPPRESS_RE = re.compile(r"#\s*cnmf-lint:\s*disable=([A-Za-z0-9_,\- ]+)")

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")

# rule id -> family (the gate echoes one count per family)
RULE_FAMILIES = {
    "trace-host-sync": "trace",
    "trace-nondet": "trace",
    "trace-branch": "trace",
    "knob-raw-env": "knobs",
    "knob-unregistered": "knobs",
    "knob-doc-drift": "knobs",
    "knob-plan-bypass": "knobs",
    "artifact-nonatomic": "artifact",
    "telemetry-schema": "telemetry",
    "lock-discipline": "concurrency",
    "lint-parse-error": "engine",
}
ALL_RULE_IDS = tuple(RULE_FAMILIES)


@dataclass
class Finding:
    """One rule violation at ``path:line``. ``text`` is the stripped
    source line — the line-number-drift-proof component of the baseline
    fingerprint."""

    path: str
    line: int
    rule: str
    message: str
    hint: str = ""
    text: str = ""

    def key(self) -> tuple:
        return (self.path.replace(os.sep, "/"), self.rule, self.text)

    def as_dict(self) -> dict:
        return {"path": self.path.replace(os.sep, "/"), "line": self.line,
                "rule": self.rule, "message": self.message,
                "hint": self.hint, "text": self.text}


# ---------------------------------------------------------------------------
# per-file context: parse once, share alias map + parent links across rules
# ---------------------------------------------------------------------------

class ImportMap:
    """Resolve local names to dotted module paths: ``import numpy as np``
    makes ``np.asarray`` resolve to ``numpy.asarray``; ``from jax import
    lax`` makes ``lax.while_loop`` resolve to ``jax.lax.while_loop``."""

    def __init__(self, tree: ast.AST):
        self.alias: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.alias[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.alias[a.asname or a.name] = (
                        f"{node.module}.{a.name}")

    def resolve(self, dotted: str | None) -> str | None:
        if not dotted:
            return None
        head, _, rest = dotted.partition(".")
        base = self.alias.get(head, head)
        return f"{base}.{rest}" if rest else base


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class FileContext:
    def __init__(self, path: str, relpath: str, src: str, tree: ast.AST):
        self.path = path
        self.relpath = relpath
        self.src = src
        self.tree = tree
        self.lines = src.splitlines()
        self.imports = ImportMap(tree)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def resolve_call(self, call: ast.Call) -> str | None:
        return self.imports.resolve(dotted_name(call.func))

    def finding(self, node: ast.AST, rule: str, message: str,
                hint: str = "") -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(self.relpath, line, rule, message, hint,
                       self.line_text(line))

    def in_atomic_with(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside a ``with atomic_artifact(...)``
        block — the write is then the temp-file half of an atomic
        rename."""
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    if isinstance(item.context_expr, ast.Call):
                        name = self.resolve_call(item.context_expr) or ""
                        if name.split(".")[-1] == "atomic_artifact":
                            return True
        return False


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

def _suppressions(lines: list[str]) -> dict[int, set[str]]:
    """Map line number -> suppressed rule ids. A tag on a code line
    covers that line; a tag on its own comment line covers the next
    non-blank, non-comment line."""
    out: dict[int, set[str]] = {}
    pending: set[str] | None = None
    for i, raw in enumerate(lines, start=1):
        stripped = raw.strip()
        m = SUPPRESS_RE.search(raw)
        rules = ({r.strip() for r in m.group(1).split(",") if r.strip()}
                 if m else None)
        if rules and stripped.startswith("#"):
            pending = (pending or set()) | rules
            continue
        if rules:
            out.setdefault(i, set()).update(rules)
        if pending is not None and stripped and not stripped.startswith("#"):
            out.setdefault(i, set()).update(pending)
            pending = None
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str | None) -> dict[tuple, int]:
    """Baseline file -> multiset of finding fingerprints. Missing/None ->
    empty (everything is a new finding)."""
    if not path or not os.path.exists(path):
        return {}
    with open(path) as f:
        data = json.load(f)
    out: dict[tuple, int] = {}
    for ent in data.get("findings", []):
        key = (ent["path"], ent["rule"], ent.get("text", ""))
        out[key] = out.get(key, 0) + 1
    return out


def write_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "version": 1,
        "comment": "grandfathered cnmf-tpu lint findings; regenerate with "
                   "`cnmf-tpu lint --write-baseline` (the goal state is an "
                   "empty list)",
        "findings": [
            {"path": f.path.replace(os.sep, "/"), "rule": f.rule,
             "text": f.text}
            for f in sorted(findings, key=lambda f: f.key())
        ],
    }
    from ..utils.anndata_lite import atomic_artifact

    with atomic_artifact(path) as tmp:
        with open(tmp, "w") as fh:
            json.dump(data, fh, indent=1)
            fh.write("\n")


def split_baselined(findings: list[Finding],
                    baseline: dict[tuple, int]
                    ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined): each baseline fingerprint absorbs up to its
    recorded multiplicity, in file order."""
    budget = dict(baseline)
    new, old = [], []
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

@dataclass
class LintResult:
    findings: list[Finding] = field(default_factory=list)   # new (gating)
    baselined: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    files: int = 0

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def family_counts(self) -> dict[str, int]:
        out = {fam: 0 for fam in dict.fromkeys(RULE_FAMILIES.values())}
        for f in self.findings:
            fam = RULE_FAMILIES.get(f.rule, "engine")
            out[fam] = out.get(fam, 0) + 1
        return out


def _iter_python_files(paths: list[str]):
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        else:
            raise FileNotFoundError(f"lint: no such path: {p}")


def _all_rules():
    from . import (rules_artifacts, rules_concurrency, rules_knobs,
                   rules_telemetry, rules_trace)

    return (rules_trace.check, rules_knobs.check, rules_artifacts.check,
            rules_telemetry.check, rules_concurrency.check)


def _find_readme(paths: list[str]) -> str | None:
    """Locate the project README whose knob table the registry is
    cross-checked against: walk up from each linted path looking for a
    README.md that contains an "Environment knobs" heading."""
    seen = set()
    for p in paths:
        cur = os.path.abspath(p if os.path.isdir(p) else os.path.dirname(p))
        for _ in range(4):
            if cur in seen:
                break
            seen.add(cur)
            cand = os.path.join(cur, "README.md")
            if os.path.exists(cand):
                with open(cand, encoding="utf-8") as f:
                    if "Environment knobs" in f.read():
                        return cand
            nxt = os.path.dirname(cur)
            if nxt == cur:
                break
            cur = nxt
    return None


def _relpath(path: str) -> str:
    ap = os.path.abspath(path)
    cwd = os.getcwd()
    if ap == cwd or ap.startswith(cwd + os.sep):
        return os.path.relpath(ap, cwd).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def lint_paths(paths: list[str], baseline_path: str | None = None,
               doc_check: bool = True) -> LintResult:
    """Lint ``paths`` (files or directory trees). Returns a
    :class:`LintResult` whose ``findings`` are the NEW (unbaselined,
    unsuppressed) violations; ``baselined`` carries the grandfathered
    matches for reporting."""
    rules = _all_rules()
    result = LintResult()
    all_findings: list[Finding] = []
    for path in _iter_python_files(paths):
        relpath = _relpath(path)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        result.files += 1
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as exc:
            all_findings.append(Finding(
                relpath, exc.lineno or 1, "lint-parse-error",
                f"file does not parse: {exc.msg}", "fix the syntax error"))
            continue
        ctx = FileContext(path, relpath, src, tree)
        file_findings: list[Finding] = []
        for check in rules:
            file_findings.extend(check(ctx))
        sup = _suppressions(ctx.lines)
        for f in file_findings:
            if f.rule in sup.get(f.line, ()):  # inline opt-out
                result.suppressed += 1
            else:
                all_findings.append(f)
    if doc_check:
        readme = _find_readme(paths)
        if readme:
            from .rules_knobs import check_knob_docs

            all_findings.extend(check_knob_docs(readme))
    all_findings.sort(key=lambda f: (f.path, f.line, f.rule))
    result.findings, result.baselined = split_baselined(
        all_findings, load_baseline(baseline_path))
    return result


# ---------------------------------------------------------------------------
# output + CLI
# ---------------------------------------------------------------------------

def format_text(result: LintResult) -> str:
    lines = []
    for f in result.findings:
        hint = f" (fix: {f.hint})" if f.hint else ""
        lines.append(f"{f.path}:{f.line}: {f.rule}: {f.message}{hint}")
    counts = result.counts()
    per_rule = " ".join(f"{r}={n}" for r, n in sorted(counts.items()))
    lines.append(
        f"lint: {len(result.findings)} finding(s) across {result.files} "
        f"file(s); {len(result.baselined)} baselined, "
        f"{result.suppressed} suppressed"
        + (f" [{per_rule}]" if per_rule else ""))
    return "\n".join(lines)


def format_json(result: LintResult) -> str:
    return json.dumps({
        "version": 1,
        "findings": [f.as_dict() for f in result.findings],
        "counts": result.counts(),
        "families": result.family_counts(),
        "baselined": len(result.baselined),
        "suppressed": result.suppressed,
        "files": result.files,
    }, indent=1)


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="cnmf-tpu lint",
        description="Codebase-aware static analysis: trace-safety, knob "
                    "hygiene, artifact atomicity, telemetry schema, lock "
                    "discipline")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to lint (default: the "
                             "installed cnmf_torch_tpu package)")
    parser.add_argument("--format", choices=["text", "json"],
                        default="text")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline file of grandfathered findings "
                             f"(default: {DEFAULT_BASELINE}); pass an "
                             "empty string to disable")
    parser.add_argument("--write-baseline", action="store_true",
                        help="rewrite the baseline file from the current "
                             "findings and exit 0")
    parser.add_argument("--no-doc-check", action="store_true",
                        help="skip the README knob-table drift check")
    parser.add_argument("--knob-table", action="store_true",
                        help="print the canonical README env-knob table "
                             "generated from the registry, then exit")
    args = parser.parse_args(argv)

    if args.knob_table:
        from ..utils.envknobs import knob_table

        print(knob_table())
        return 0

    paths = args.paths or [os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))]
    baseline = args.baseline or None
    try:
        if args.write_baseline:
            if baseline is None:
                # `--baseline ''` means "no baseline"; silently writing
                # the checked-in default instead would grandfather the
                # findings the caller asked to see
                parser.error("--write-baseline needs a baseline path "
                             "(--baseline FILE)")
            pre = lint_paths(paths, baseline_path=None,
                             doc_check=not args.no_doc_check)
            write_baseline(baseline, pre.findings)
            print(f"lint: wrote {len(pre.findings)} finding(s) to "
                  f"{baseline}")
            return 0
        result = lint_paths(paths, baseline_path=baseline,
                            doc_check=not args.no_doc_check)
    except FileNotFoundError as exc:
        parser.error(str(exc))
    print(format_json(result) if args.format == "json"
          else format_text(result))
    return 1 if result.findings else 0
