"""End-to-end pipeline launcher — the reference ``Extras/run_parallel.py``
equivalent (``/root/reference/Extras/run_parallel.py:1-70``: prepare -> GNU
parallel factorize workers -> combine -> k_selection_plot -> clean).

Two engines replace GNU parallel:

  * ``subprocess`` — N independent OS worker processes, round-robin sharded
    by ``--worker-index`` over the replicate ledger, exactly the reference's
    model (files as the dataplane). Right for a fleet of single-chip hosts
    with a shared filesystem and for CPU dev boxes. A dead worker costs only
    its own replicates: combine runs with ``skip_missing_files=True`` when
    any worker exits nonzero.
  * ``multihost`` — ONE single-controller JAX program spanning N processes
    stitched by ``jax.distributed`` (``parallel/multihost.py``); factorize
    runs over the 2-D (replicates x cells) mesh, with the cells-psum on ICI
    and the replicate axis across hosts. On a real TPU pod you normally
    launch that yourself (same command on every host); this engine spawns
    the N processes locally — with ``--devices-per-host`` virtual CPU
    devices each — which is how the multi-host path is CI-tested without a
    pod.

Python API: :func:`run_pipeline`. CLI: ``cnmf-tpu run_parallel ...``.
"""

from __future__ import annotations

import glob
import os
import socket
import subprocess
import sys
import warnings

__all__ = ["run_pipeline"]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _worker_cmd(output_dir: str, name: str, extra: list[str]) -> list[str]:
    return [sys.executable, "-m", "cnmf_torch_tpu", "factorize",
            "--output-dir", output_dir, "--name", name] + extra


def run_pipeline(counts: str, output_dir: str, name: str,
                 components, n_iter: int = 100, total_workers: int = 1,
                 seed: int | None = None, numgenes: int = 2000,
                 genes_file: str | None = None, tpm: str | None = None,
                 beta_loss: str = "frobenius", init: str = "random",
                 max_nmf_iter: int = 1000, batch_size: int = 5000,
                 engine: str = "subprocess",
                 devices_per_host: int | None = None,
                 clean: bool = False, k_selection: bool = True,
                 env_extra: dict | None = None,
                 factorize_flags: list[str] | None = None) -> None:
    """prepare -> parallel factorize -> combine -> k_selection_plot.

    ``engine='subprocess'``: ``total_workers`` OS processes shard the ledger
    (the reference's GNU-parallel model). ``engine='multihost'``:
    ``total_workers`` JAX processes form one distributed program over a 2-D
    mesh; ``devices_per_host`` forces that many virtual CPU devices per
    process (pod simulation — omit on real multi-chip hosts).

    ``factorize_flags``: extra CLI flags forwarded verbatim to every
    factorize worker (e.g. ``["--mesh-2d"]``, ``["--rowshard"]``,
    ``["--sequential"]``) — how the run_parallel subcommand's
    factorize-mode options reach the workers.
    """
    factorize_flags = list(factorize_flags or [])
    # the CLI's parser default is -1 ("all"); range(-1) would spawn zero
    # workers and the run would only fail much later at combine
    total_workers = max(int(total_workers), 1)
    if engine not in ("subprocess", "multihost"):
        raise ValueError(f"unknown engine {engine!r}")
    if engine == "multihost" and devices_per_host is None:
        # this process is about to initialize a JAX backend for prepare();
        # N spawned children sharing the parent's real TPU runtime would
        # contend for the chips and hang or crash. The local-spawn engine
        # is only safe when each child gets its own virtual CPU devices; on
        # a real pod, launch the same command on every host instead
        # (docs/Stepwise_Guide.md). Checked BEFORE prepare so the
        # misconfiguration costs seconds, not an atlas-scale prepare pass.
        import jax

        if jax.default_backend() not in ("cpu",):
            raise RuntimeError(
                "engine='multihost' without devices_per_host spawns "
                "local JAX processes that would contend with this "
                "process's %r backend. Pass devices_per_host=N for a "
                "CPU-simulated pod, or launch one process per host "
                "yourself with CNMF_PROCESS_ID/--distributed (see "
                "docs/Stepwise_Guide.md)." % jax.default_backend())
    from .models.cnmf import cNMF

    obj = cNMF(output_dir=output_dir, name=name)
    obj.prepare(counts, components=components, n_iter=n_iter, seed=seed,
                num_highvar_genes=numgenes, genes_file=genes_file,
                tpm_fn=tpm, beta_loss=beta_loss, init=init,
                max_NMF_iter=max_nmf_iter, batch_size=batch_size,
                total_workers=max(total_workers, 1))

    base_env = dict(os.environ)
    # workers must import this package regardless of their cwd (source
    # checkouts aren't necessarily pip-installed)
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base_env["PYTHONPATH"] = os.pathsep.join(
        [pkg_root] + ([base_env["PYTHONPATH"]]
                      if base_env.get("PYTHONPATH") else []))
    if env_extra:
        base_env.update({k: str(v) for k, v in env_extra.items()})

    any_failed = False
    if engine == "subprocess":
        procs = []
        for i in range(total_workers):
            cmd = _worker_cmd(output_dir, name,
                              ["--worker-index", str(i),
                               "--total-workers", str(total_workers)]
                              + factorize_flags)
            procs.append((i, subprocess.Popen(cmd, env=base_env)))
        n_failed = 0
        for i, p in procs:
            if p.wait() != 0:
                any_failed = True
                n_failed += 1
                warnings.warn(
                    "factorize worker %d exited with rc=%d; its replicates "
                    "will be skipped at combine (the reference's dead-worker "
                    "tolerance, cnmf.py:904-909)" % (i, p.returncode),
                    RuntimeWarning)
        if n_failed == total_workers:
            # nothing survived — combine/k_selection would only crash on
            # missing files with a misleading traceback
            raise RuntimeError(
                f"all {total_workers} factorize workers failed; see their "
                "output above")
    elif engine == "multihost":
        port = _free_port()
        procs = []
        for pid in range(total_workers):
            env = dict(base_env,
                       CNMF_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                       CNMF_NUM_PROCESSES=str(total_workers),
                       CNMF_PROCESS_ID=str(pid))
            if devices_per_host:
                env["CNMF_SIM_CPU_DEVICES"] = str(devices_per_host)
            extra = ["--mesh-2d", "--distributed"] + [
                f for f in factorize_flags if f != "--mesh-2d"]
            cmd = _worker_cmd(output_dir, name, extra)
            procs.append((pid, subprocess.Popen(cmd, env=env)))
        rcs = [(pid, p.wait()) for pid, p in procs]
        bad = [(pid, rc) for pid, rc in rcs if rc]
        if bad:
            # a single-controller program has no partial completion: one
            # dead process stalls the collective, unlike the subprocess
            # engine's independent workers
            raise RuntimeError(
                f"multihost factorize failed on processes {bad}")

    obj.combine(skip_missing_files=any_failed)
    if k_selection:
        obj.k_selection_plot(close_fig=True)

    if clean:
        # the reference's `rm .../cnmf_tmp/*.iter_*.df.npz`
        # (run_parallel.py:64): per-replicate spectra are redundant once
        # merged_spectra exists
        pattern = os.path.join(output_dir, name, "cnmf_tmp",
                               "*.iter_*.df.npz")
        for f in glob.glob(pattern):
            os.remove(f)
