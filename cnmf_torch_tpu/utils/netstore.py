"""In-repo HTTP object-store fixture — the GCS stand-in.

A stdlib-only (``http.server``) in-memory object store the remote
shard-store backend (``utils/storebackend.py``) speaks to in tests and
the netstore chaos smoke (``scripts/netstore_smoke.py``). One server
hosts many stores: object names are flat URL paths under any prefix
(``/run1/cnmf.norm_counts.store/slab_00000.npz``), so the backend's
per-store prefix namespacing maps directly.

Verbs (the object-store subset the backend needs, plus range reads):

  * ``GET /name``         — 200 full body; ``Range: bytes=a-b`` → 206
    with the slice (the range-GET surface a real object store offers);
  * ``GET /prefix/?list=1`` — 200 JSON array of object names under the
    prefix (relative, the backend's listing verb);
  * ``GET /metrics``      — 200 text exposition of this process's live
    metrics registry (``obs/metrics.py``; a "disabled" banner unless
    ``CNMF_TPU_METRICS=1``). The path is reserved: an object literally
    named ``metrics`` is shadowed by the endpoint;
  * ``PUT /name``         — 201, body stored verbatim;
  * ``HEAD /name``        — 200 with Content-Length, or 404;
  * ``DELETE /name``      — 204, or 404.

Network faults are NOT injected here — the chaos seam is client-side
(``runtime/faults.py:maybe_netfault`` fires before the socket opens),
so a "down" remote needs no special server mode and the same fixture
serves every scenario. Threaded (concurrent hedged reads hit one
server) with daemon workers; ``stop()`` joins the serve loop, leaving
no lingering threads behind a passed test.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..obs import metrics as obs_metrics

__all__ = ["ObjectStoreServer"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    # self.server is the ObjectStoreServer below (objects + lock)

    def log_message(self, format, *args):  # noqa: A002 - stdlib signature
        pass  # tests assert on pipeline output, not request logs

    def _key(self) -> str:
        return urllib.parse.unquote(
            urllib.parse.urlsplit(self.path).path).lstrip("/")

    def _send(self, code: int, body: bytes = b"",
              content_type: str = "application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def do_GET(self):
        parts = urllib.parse.urlsplit(self.path)
        query = urllib.parse.parse_qs(parts.query)
        if parts.path == "/metrics" and not query:
            self._send(200, obs_metrics.render_text().encode("utf-8"),
                       content_type="text/plain; charset=utf-8")
            return
        obs_metrics.counter_inc("cnmf_netstore_requests_total",
                                verb="get")
        if query.get("list"):
            prefix = urllib.parse.unquote(parts.path).lstrip("/")
            if prefix and not prefix.endswith("/"):
                prefix += "/"
            with self.server.lock:
                names = sorted(k[len(prefix):] for k in self.server.objects
                               if k.startswith(prefix))
            self._send(200, json.dumps(names).encode("utf-8"),
                       content_type="application/json")
            return
        key = self._key()
        with self.server.lock:
            body = self.server.objects.get(key)
        if body is None:
            self._send(404)
            return
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo_s, _, hi_s = rng[len("bytes="):].partition("-")
            lo = int(lo_s) if lo_s else 0
            hi = int(hi_s) + 1 if hi_s else len(body)
            part = body[lo:hi]
            self.send_response(206)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Range", "bytes %d-%d/%d"
                             % (lo, lo + len(part) - 1, len(body)))
            self.send_header("Content-Length", str(len(part)))
            self.end_headers()
            self.wfile.write(part)
            return
        self._send(200, body)

    def do_HEAD(self):
        obs_metrics.counter_inc("cnmf_netstore_requests_total",
                                verb="head")
        key = self._key()
        with self.server.lock:
            body = self.server.objects.get(key)
        if body is None:
            self._send(404)
        else:
            self._send(200, body)  # _send skips the body for HEAD

    def do_PUT(self):
        obs_metrics.counter_inc("cnmf_netstore_requests_total",
                                verb="put")
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        with self.server.lock:
            self.server.objects[self._key()] = body
        self._send(201)

    def do_DELETE(self):
        obs_metrics.counter_inc("cnmf_netstore_requests_total",
                                verb="delete")
        with self.server.lock:
            existed = self.server.objects.pop(self._key(), None) is not None
        self._send(204 if existed else 404)


class ObjectStoreServer(ThreadingHTTPServer):
    """``with ObjectStoreServer() as srv: ... srv.url ...`` — binds
    127.0.0.1 on an ephemeral port (``port=0``), serves on a background
    thread until ``stop()``/``__exit__``. ``objects`` maps flat names
    to bytes; mutate it directly to seed or corrupt fixtures."""

    daemon_threads = True

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        super().__init__((host, port), _Handler)
        self.objects: dict = {}
        self.lock = threading.Lock()
        self._thread = None

    @property
    def url(self) -> str:
        host, port = self.server_address[:2]
        return "http://%s:%d" % (host, port)

    def start(self) -> "ObjectStoreServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="cnmf-netstore", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.server_close()

    def __enter__(self) -> "ObjectStoreServer":
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False
