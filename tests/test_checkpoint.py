"""Mid-run pass-statistics checkpoints (runtime/checkpoint.py) and the
checkpointed rowsharded executor (ISSUE 6): knob validation, save/load
validation (torn/mismatched checkpoints are never trusted), interrupted+
resumed parity against uninterrupted runs (bit-identical while H rides
the checkpoint, solver-tolerance otherwise), the checkpoint-off fused
path, and the launcher's deterministic-jitter respawn backoff.
"""

import os
import warnings

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded
from cnmf_torch_tpu.runtime import checkpoint as ck


@pytest.fixture()
def mesh():
    return Mesh(np.asarray(jax.devices()[:4]), ("cells",))


@pytest.fixture()
def X():
    rng = np.random.default_rng(0)
    return (rng.gamma(0.8, 1.0, size=(64, 24))
            * rng.binomial(1, 0.4, size=(64, 24))).astype(np.float32)


def _meta(digest, **kw):
    meta = dict(k=3, iter=0, seed=7, attempt=0, digest=digest, beta=2.0)
    meta.update(kw)
    return meta


class _Interrupt(Exception):
    """Stands in for SIGKILL in-process: raised AFTER a checkpoint write
    lands, which is exactly the state a mid-run preemption leaves."""


class _KillAt(ck.PassCheckpointer):
    def __init__(self, *a, kill_pass, **kw):
        super().__init__(*a, **kw)
        self._kill_pass = kill_pass

    def save(self, *, pass_idx, **kw):
        super().save(pass_idx=pass_idx, **kw)
        if pass_idx == self._kill_pass:
            raise _Interrupt


class _Events:
    def __init__(self):
        self.events = []

    def emit(self, t, **kw):
        self.events.append(dict(kw, t=t))


# ---------------------------------------------------------------------------
# knobs + digest + file validation
# ---------------------------------------------------------------------------

def test_ckpt_knob_validation(monkeypatch):
    monkeypatch.delenv(ck.CKPT_EVERY_ENV, raising=False)
    assert ck.ckpt_every_passes() == 1            # default: every pass
    monkeypatch.setenv(ck.CKPT_EVERY_ENV, "0")
    assert ck.ckpt_every_passes() == 0            # 0 disables
    monkeypatch.setenv(ck.CKPT_EVERY_ENV, "3")
    assert ck.ckpt_every_passes() == 3
    for bad in ("-1", "often"):
        monkeypatch.setenv(ck.CKPT_EVERY_ENV, bad)
        with pytest.raises(ValueError, match=ck.CKPT_EVERY_ENV):
            ck.ckpt_every_passes()
    monkeypatch.setenv(ck.CKPT_H_BUDGET_ENV, "x")
    with pytest.raises(ValueError, match=ck.CKPT_H_BUDGET_ENV):
        ck.ckpt_h_budget_bytes()


def test_input_digest_distinguishes_inputs(X):
    import scipy.sparse as sp

    assert ck.input_digest(X) == ck.input_digest(X.copy())
    Y = X.copy()
    Y[5, 3] += 1.0
    assert ck.input_digest(X) != ck.input_digest(Y)
    # sparse and dense encodings of the same values hash consistently
    # with themselves (they need not match each other)
    S = sp.csr_matrix(X)
    assert ck.input_digest(S) == ck.input_digest(S.copy())


def test_checkpoint_roundtrip_and_validation(tmp_path):
    path = str(tmp_path / "a.ckpt.npz")
    W = np.abs(np.random.default_rng(1).normal(size=(3, 24))).astype(
        np.float32)
    ck.save_pass_checkpoint(
        path, k=3, it=0, seed=7, attempt=0, digest="d1", beta=2.0,
        pass_idx=4, err_prev=np.float32(5.5), err=np.float32(4.5),
        trace=np.zeros(8, np.float32), W=W, A=np.zeros((3, 24), np.float32),
        B=np.zeros((3, 3), np.float32))
    state = ck.load_pass_checkpoint(path, expect=_meta("d1"), n_genes=24)
    assert state["pass_idx"] == 4 and state["H"] is None
    np.testing.assert_array_equal(state["W"], W)
    assert state["err"] == np.float32(4.5)

    # identity mismatches are torn, not trusted
    for bad in ({"seed": 8}, {"k": 4}, {"beta": 1.0}):
        with pytest.raises(ck.TornCheckpointError):
            ck.load_pass_checkpoint(path, expect=_meta("d1", **bad))
    with pytest.raises(ck.TornCheckpointError, match="digest"):
        ck.load_pass_checkpoint(path, expect=_meta("other"))
    # a different resolved solver recipe is a different solve
    with pytest.raises(ck.TornCheckpointError, match="params"):
        ck.load_pass_checkpoint(
            path, expect=dict(_meta("d1"), params="tol=1e-5"))
    with pytest.raises(ck.TornCheckpointError, match="gene"):
        ck.load_pass_checkpoint(path, expect=_meta("d1"), n_genes=25)

    # a truncated file (mid-write kill) is torn
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 3))
    state2, reason = ck.probe_pass_checkpoint(path, expect=_meta("d1"))
    assert state2 is None and "unreadable" in reason
    assert ck.probe_pass_checkpoint(str(tmp_path / "nope.npz"))[1] == \
        "missing"


def test_min_interval_floors_write_rate(tmp_path, monkeypatch):
    """The wall-clock floor skips back-to-back saves (and tells the
    solver up front via due(), so the device->host gather is skipped
    too); default 0 persists every eligible pass."""
    saves = []
    orig = ck.save_pass_checkpoint
    monkeypatch.setattr(ck, "save_pass_checkpoint",
                        lambda path, **kw: saves.append(kw["pass_idx"]))
    kw = dict(err_prev=1.0, err=0.5, trace=np.zeros(2, np.float32),
              W=np.ones((3, 4), np.float32), A=np.zeros((3, 4), np.float32),
              B=np.zeros((3, 3), np.float32))
    c = ck.PassCheckpointer(str(tmp_path / "m.ckpt.npz"), 1,
                            meta=_meta("d"), min_interval_s=3600.0)
    assert c.due()
    c.save(pass_idx=1, **kw)
    assert not c.due()
    c.save(pass_idx=2, **kw)        # dropped by the floor
    assert saves == [1]
    c0 = ck.PassCheckpointer(str(tmp_path / "n.ckpt.npz"), 1,
                             meta=_meta("d"), min_interval_s=0.0)
    c0.save(pass_idx=1, **kw)
    assert c0.due()
    c0.save(pass_idx=2, **kw)
    assert saves == [1, 1, 2]
    monkeypatch.setattr(ck, "save_pass_checkpoint", orig)


def test_fresh_run_discards_stale_checkpoint(tmp_path):
    path = str(tmp_path / "b.ckpt.npz")
    with open(path, "wb") as f:
        f.write(b"stale")
    ck.PassCheckpointer(path, 1, meta=_meta("d"), resume=False)
    assert not os.path.exists(path)


# ---------------------------------------------------------------------------
# checkpointed executor parity
# ---------------------------------------------------------------------------

def test_checkpointed_matches_fused_and_off_path(tmp_path, X, mesh):
    """The checkpointed host loop must reproduce the fused while_loop
    program (same per-pass body, same f32 convergence test), and
    checkpoint=None must still BE the fused program."""
    H0, W0, e0 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12)
    ckpt = ck.PassCheckpointer(str(tmp_path / "c.ckpt.npz"), 1,
                               meta=_meta(ck.input_digest(X)), resume=False)
    H1, W1, e1 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                                    checkpoint=ckpt)
    np.testing.assert_allclose(W1, W0, rtol=2e-3, atol=1e-5)
    assert abs(e1 - e0) / max(e0, 1e-9) < 1e-3
    H2, W2, e2 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12)
    np.testing.assert_array_equal(W2, W0)   # off path byte-stable


def test_interrupt_resume_bit_identical(tmp_path, X, mesh):
    """Kill-after-checkpoint at pass 4, relaunch, resume: while H rides
    the checkpoint the resumed run is BIT-IDENTICAL to the uninterrupted
    checkpointed run, and the telemetry trail shows write -> resume."""
    dig = ck.input_digest(X)
    path = str(tmp_path / "d.ckpt.npz")
    ck_full = ck.PassCheckpointer(str(tmp_path / "full.ckpt.npz"), 1,
                                  meta=_meta(dig), resume=False)
    H1, W1, e1 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                                    checkpoint=ck_full)

    killer = _KillAt(path, 1, meta=_meta(dig), resume=False, kill_pass=4)
    with pytest.raises(_Interrupt):
        nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                           checkpoint=killer)
    assert os.path.exists(path)

    events = _Events()
    resumer = ck.PassCheckpointer(path, 1, meta=_meta(dig), resume=True,
                                  events=events)
    H2, W2, e2 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                                    checkpoint=resumer)
    np.testing.assert_array_equal(W2, W1)
    np.testing.assert_array_equal(H2, H1)
    assert e2 == e1
    resumes = [e for e in events.events
               if e["t"] == "checkpoint" and e["action"] == "resume"]
    assert len(resumes) == 1 and resumes[0]["context"]["pass_idx"] == 4


def test_resume_without_h_within_tolerance(tmp_path, X, mesh):
    """Above the H byte budget only (A, B)/W ride the checkpoint; the
    resumed trajectory re-derives H from W and must land within solver
    tolerance of the uninterrupted run (the sufficient-statistics trade
    the out-of-core designs make)."""
    dig = ck.input_digest(X)
    meta = _meta(dig, beta=1.0)
    ck_full = ck.PassCheckpointer(str(tmp_path / "f.ckpt.npz"), 1,
                                  meta=meta, resume=False, h_budget_bytes=0)
    _, W1, e1 = nmf_fit_rowsharded(X, 3, mesh,
                                   beta_loss="kullback-leibler", seed=7,
                                   n_passes=8, checkpoint=ck_full)
    path = str(tmp_path / "g.ckpt.npz")
    killer = _KillAt(path, 1, meta=meta, resume=False, h_budget_bytes=0,
                     kill_pass=3)
    with pytest.raises(_Interrupt):
        nmf_fit_rowsharded(X, 3, mesh, beta_loss="kullback-leibler",
                           seed=7, n_passes=8, checkpoint=killer)
    resumer = ck.PassCheckpointer(path, 1, meta=meta, resume=True,
                                  h_budget_bytes=0)
    _, W2, e2 = nmf_fit_rowsharded(X, 3, mesh,
                                   beta_loss="kullback-leibler", seed=7,
                                   n_passes=8, checkpoint=resumer)
    assert abs(e2 - e1) / max(e1, 1e-9) < 0.05
    assert np.isfinite(W2).all() and (W2 >= 0).all()


def test_torn_checkpoint_restarts_from_scratch(tmp_path, X, mesh):
    """A checkpoint truncated mid-write is detected on resume, discarded,
    and the replicate restarts from scratch — producing the exact
    uninterrupted result, never trusting damaged state."""
    dig = ck.input_digest(X)
    ck_full = ck.PassCheckpointer(str(tmp_path / "h.ckpt.npz"), 1,
                                  meta=_meta(dig), resume=False)
    _, W1, e1 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                                   checkpoint=ck_full)
    path = str(tmp_path / "i.ckpt.npz")
    killer = _KillAt(path, 1, meta=_meta(dig), resume=False, kill_pass=4)
    with pytest.raises(_Interrupt):
        nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                           checkpoint=killer)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(1, size // 3))
    events = _Events()
    resumer = ck.PassCheckpointer(path, 1, meta=_meta(dig), resume=True,
                                  events=events)
    with pytest.warns(RuntimeWarning, match="restarts from scratch"):
        _, W2, e2 = nmf_fit_rowsharded(X, 3, mesh, seed=7, n_passes=12,
                                       checkpoint=resumer)
    np.testing.assert_array_equal(W2, W1)
    kinds = [e for e in events.events if e["t"] == "fault"]
    assert kinds and kinds[0]["kind"] == "torn_artifact"
    # no stray resume event — the run restarted
    assert not any(e["t"] == "checkpoint" and e["action"] == "resume"
                   for e in events.events)


# ---------------------------------------------------------------------------
# factorize wiring + launcher jitter
# ---------------------------------------------------------------------------

def test_factorize_rowshard_checkpoint_lifecycle(tmp_path, monkeypatch):
    """Pipeline-level wiring: under the default cadence every replicate
    writes pass checkpoints and discards them once its spectra artifact
    lands (no litter); CNMF_TPU_CKPT_EVERY_PASSES=0 never touches the
    checkpoint layer (byte-identical pre-checkpoint programs)."""
    import glob

    import pandas as pd
    import scipy.sparse as sp

    from cnmf_torch_tpu.models.cnmf import cNMF
    from cnmf_torch_tpu.utils.io import save_df_to_npz

    rng = np.random.default_rng(3)
    counts = sp.csr_matrix(
        rng.binomial(40, 0.02, size=(60, 100)).astype(np.float64))
    df = pd.DataFrame(counts.toarray(),
                      index=[f"c{i}" for i in range(60)],
                      columns=[f"g{j}" for j in range(100)])
    counts_fn = str(tmp_path / "counts.df.npz")
    save_df_to_npz(df, counts_fn)

    saves = []
    orig_save = ck.save_pass_checkpoint

    def spy(path, **kw):
        saves.append(kw["pass_idx"])
        return orig_save(path, **kw)

    monkeypatch.setattr(ck, "save_pass_checkpoint", spy)

    obj = cNMF(output_dir=str(tmp_path), name="ckpl")
    obj.prepare(counts_fn, components=[3], n_iter=2, seed=4,
                num_highvar_genes=50, total_workers=1)
    obj.factorize(rowshard=True)
    assert saves, "checkpoints never written under the default cadence"
    for it in range(2):
        assert os.path.exists(obj.paths["iter_spectra"] % (3, it))
    assert not glob.glob(str(tmp_path / "ckpl" / "cnmf_tmp" / "*.ckpt.*"))

    saves.clear()
    monkeypatch.setenv(ck.CKPT_EVERY_ENV, "0")
    obj2 = cNMF(output_dir=str(tmp_path), name="ckoff")
    obj2.prepare(counts_fn, components=[3], n_iter=2, seed=4,
                 num_highvar_genes=50, total_workers=1)
    obj2.factorize(rowshard=True)
    assert not saves, "checkpoint layer touched with cadence 0"
    # the two runs share ledger seeds; spectra must agree across the
    # fused and checkpointed executors
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    a = load_df_from_npz(obj.paths["iter_spectra"] % (3, 0)).values
    b = load_df_from_npz(obj2.paths["iter_spectra"] % (3, 0)).values
    np.testing.assert_allclose(a, b, rtol=2e-3, atol=1e-5)


def test_launcher_respawn_jitter():
    from cnmf_torch_tpu.launcher import respawn_delay

    # deterministic: same inputs, same delay (resume/replay reproducible)
    assert respawn_delay(0.5, 1, 3) == respawn_delay(0.5, 1, 3)
    # exponential in the attempt
    assert respawn_delay(0.5, 2, 3) == pytest.approx(
        2.0 * respawn_delay(0.5, 1, 3))
    # jitter factor stays in [1, 1.5) of the exponential base
    for i in range(16):
        d = respawn_delay(1.0, 1, i)
        assert 1.0 <= d < 1.5
    # simultaneous deaths fan out: worker delays are not all equal
    delays = {round(respawn_delay(1.0, 1, i), 6) for i in range(8)}
    assert len(delays) > 4, delays
