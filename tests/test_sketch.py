"""Sketched solvers (ISSUE 11): the sketch recipe lane (row-subsampled
KL W updates with exact interleaves), the consensus random-projection
stage, byte-identity when off, the measured-rho autotune cache, and the
sketch-carrying telemetry surface."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.ops.nmf import nmf_fit_batch, run_nmf
from cnmf_torch_tpu.ops.recipe import (
    SolverRecipe,
    auto_sketch_rows,
    resolve_recipe,
)
from cnmf_torch_tpu.ops.sketch import (
    DEFAULT_CONSENSUS_DIM,
    project_rows,
    resolve_consensus_sketch,
)
from cnmf_torch_tpu.ops.sparse import (
    csr_to_ell,
    ell_device_put,
    ell_kl_w_stats_rows,
)


def _counts(n, g, k, seed, scale=6.0):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.2, size=n)
    spectra = rng.gamma(0.25, 1.0, size=(k, g)) * 40.0 / g
    X = rng.poisson(usage @ spectra * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return X


# ---------------------------------------------------------------------------
# recipe resolution
# ---------------------------------------------------------------------------

class TestSketchRecipeResolution:
    def test_default_is_off_and_identity(self, monkeypatch):
        monkeypatch.delenv("CNMF_TPU_SKETCH", raising=False)
        rec = resolve_recipe(1.0, "batch")
        assert rec.algo != "sketch"  # sketch lane off by default
        # with the accel auto-default hatched off too, the full default
        # stack resolves the identity plain-MU recipe
        monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
        rec = resolve_recipe(1.0, "batch")
        assert rec.algo == "mu" and rec.is_identity

    def test_forced_engages_for_kl_everywhere(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
        for mode in ("batch", "online", "rowshard"):
            rec = resolve_recipe(1.0, mode, n=10000)
            assert rec.algo == "sketch", mode
            assert rec.sketch_dim == 10000 // 8
            assert rec.sketch_exact_every == 4
            assert not rec.is_identity
        # and stays off outside KL (the scheme is beta=1 math); beta=0
        # falls through to the accel lane (amu under the auto default)
        assert resolve_recipe(2.0, "batch").algo == "mu"
        assert resolve_recipe(0.0, "batch").algo == "amu"
        assert resolve_recipe(0.0, "batch", accel="0").algo == "mu"

    def test_auto_leaves_the_solver_lane_off(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_SKETCH", "auto")
        assert resolve_recipe(1.0, "batch", n=100000).algo != "sketch"
        assert resolve_recipe(1.0, "batch", n=100000,
                              accel="0").algo == "mu"

    def test_knobs_pin_dim_and_cadence(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
        monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "512")
        monkeypatch.setenv("CNMF_TPU_SKETCH_EXACT_EVERY", "7")
        rec = resolve_recipe(1.0, "batch", n=100000)
        assert (rec.sketch_dim, rec.sketch_exact_every) == (512, 7)
        assert rec.label == "sketch(m=512,E=7)"
        assert "skdim=512" in rec.signature()
        ctx = rec.as_context()
        assert ctx["sketch_dim"] == 512 and ctx["sketch_exact_every"] == 7

    def test_caller_pin_wins_and_sketch_beats_accel(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_ACCEL", "1")
        rec = resolve_recipe(1.0, "batch", sketch="1", sketch_dim=64,
                             sketch_exact_every=2, n=4096)
        assert rec.algo == "sketch" and rec.sketch_dim == 64
        monkeypatch.delenv("CNMF_TPU_ACCEL")

    def test_env_sketch_never_overrides_caller_accel_pin(self, monkeypatch):
        # precedence contract: explicit caller args > env knobs — an
        # env sketch word must not hijack a caller-pinned dna/amu recipe
        monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
        rec = resolve_recipe(1.0, "batch", accel="1", kl_newton=True)
        assert rec.algo == "dna", rec.label
        rec = resolve_recipe(1.0, "batch", accel="1", kl_newton=False,
                             inner_repeats=3)
        assert rec.algo == "amu", rec.label
        # without caller pins the env word engages as usual
        assert resolve_recipe(1.0, "batch", n=4096).algo == "sketch"

    def test_dim_clamped_to_n(self):
        rec = resolve_recipe(1.0, "batch", sketch="1", sketch_dim=5000,
                             n=300)
        assert rec.sketch_dim == 300

    def test_invalid_word_raises(self, monkeypatch):
        monkeypatch.setenv("CNMF_TPU_SKETCH", "maybe")
        with pytest.raises(ValueError, match="CNMF_TPU_SKETCH"):
            resolve_recipe(1.0, "batch")

    def test_recipe_field_validation(self):
        with pytest.raises(ValueError, match="sketch_dim"):
            SolverRecipe("sketch")
        with pytest.raises(ValueError, match="sketch recipe's field"):
            SolverRecipe("mu", sketch_dim=8)
        with pytest.raises(ValueError, match="exclusive"):
            SolverRecipe("sketch", 3, False, sketch_dim=8)

    def test_auto_sketch_rows(self):
        assert auto_sketch_rows(None) == 2048
        assert auto_sketch_rows(100000) == 12500
        assert auto_sketch_rows(1000) == 256  # floor
        assert auto_sketch_rows(100) == 100   # never above n


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------

def test_ell_sketched_w_stats_match_dense_subset():
    rng = np.random.default_rng(1)
    n, g, k, m = 60, 40, 5, 17
    X = np.where(rng.uniform(size=(n, g)) < 0.85, 0.0,
                 rng.gamma(1.0, 1.0, size=(n, g))).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    E = ell_device_put(csr_to_ell(sp.csr_matrix(X)))
    H = rng.uniform(0.1, 1, size=(n, k)).astype(np.float32)
    W = rng.uniform(0.1, 1, size=(k, g)).astype(np.float32)
    idx = rng.integers(0, n, size=m)  # with replacement, duplicates legal
    numer, denom = ell_kl_w_stats_rows(E, jnp.asarray(H), jnp.asarray(W),
                                       jnp.asarray(idx))
    Xs, Hs = X[idx], H[idx]
    WH = np.maximum(Hs @ W, 1e-16)
    np.testing.assert_allclose(np.asarray(numer), Hs.T @ (Xs / WH),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(denom),
        np.broadcast_to(Hs.sum(axis=0)[:, None], W.shape), rtol=1e-5)


def test_project_rows_preserves_distances():
    rng = np.random.default_rng(3)
    A = rng.normal(size=(40, 2000)).astype(np.float32)
    P = project_rows(A, 256)
    assert P.shape == (40, 256)

    def dists(M):
        sq = (M ** 2).sum(axis=1)
        return np.sqrt(np.maximum(sq[:, None] + sq[None, :]
                                  - 2.0 * M @ M.T, 0.0))

    D, Dp = dists(A), dists(P)
    off = ~np.eye(40, dtype=bool)
    rel = np.abs(Dp[off] - D[off]) / D[off]
    # JL at dim 256: distortion concentrated well under 25%
    assert rel.max() < 0.25, rel.max()
    assert rel.mean() < 0.08, rel.mean()
    # seeded: deterministic across calls
    np.testing.assert_array_equal(P, project_rows(A, 256))
    # projecting "up" is a no-op passthrough
    assert project_rows(A, 4000).shape == A.shape


def test_resolve_consensus_sketch_modes(monkeypatch):
    monkeypatch.delenv("CNMF_TPU_SKETCH", raising=False)
    assert not resolve_consensus_sketch(10000, 2000).engaged
    monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
    sk = resolve_consensus_sketch(100, 2000)
    assert sk.engaged and sk.dim == DEFAULT_CONSENSUS_DIM
    # forced but the spectra are narrower than the dim: degrade to exact
    assert not resolve_consensus_sketch(100, 128).engaged
    monkeypatch.setenv("CNMF_TPU_SKETCH", "auto")
    assert resolve_consensus_sketch(4 * DEFAULT_CONSENSUS_DIM, 2000).engaged
    assert not resolve_consensus_sketch(100, 2000).engaged
    monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "64")
    sk = resolve_consensus_sketch(256, 2000)
    assert sk.engaged and sk.dim == 64
    # a solver-row-sized pin (shared knob) falls back to the JL default
    # width instead of silently disabling a forced sketch
    monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
    monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "2048")
    sk = resolve_consensus_sketch(900, 2000)
    assert sk.engaged and sk.dim == DEFAULT_CONSENSUS_DIM
    # the documented 'auto' string is the unset sentinel, not an error
    monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "auto")
    assert resolve_consensus_sketch(900, 2000).dim == DEFAULT_CONSENSUS_DIM


# ---------------------------------------------------------------------------
# solver parity + byte identity
# ---------------------------------------------------------------------------

def test_sketch_off_lowering_matches_defaults():
    X = jnp.asarray(_counts(40, 16, 3, 0))
    H0 = jnp.ones((40, 3)) * 0.5
    W0 = jnp.ones((3, 16)) * 0.5
    base = nmf_fit_batch.lower(X, H0, W0, beta=1.0,
                               max_iter=20).as_text()
    ident = nmf_fit_batch.lower(X, H0, W0, beta=1.0, max_iter=20,
                                sketch_dim=0,
                                sketch_exact_every=1).as_text()
    assert base == ident


def test_sketched_batch_objective_parity_dense_and_ell():
    X = _counts(1000, 60, 4, 0, scale=0.8)
    Xj = jnp.asarray(X)
    key = jax.random.key(7)
    kh, kw = jax.random.split(key)
    H0 = jax.random.uniform(kh, (1000, 4))
    W0 = jax.random.uniform(kw, (4, 60))
    _, _, err_mu = nmf_fit_batch(Xj, H0, W0, beta=1.0, max_iter=200)
    _, _, err_sk = nmf_fit_batch(Xj, H0, W0, beta=1.0, max_iter=200,
                                 sketch_dim=250, sketch_exact_every=4)
    rel = abs(float(err_sk) - float(err_mu)) / float(err_mu)
    assert rel < 0.05, (float(err_mu), float(err_sk))

    E = ell_device_put(csr_to_ell(sp.csr_matrix(X)))
    _, _, err_mu_e = nmf_fit_batch(E, H0, W0, beta=1.0, max_iter=200)
    _, _, err_sk_e = nmf_fit_batch(E, H0, W0, beta=1.0, max_iter=200,
                                   sketch_dim=250, sketch_exact_every=4)
    rel = abs(float(err_sk_e) - float(err_mu_e)) / float(err_mu_e)
    assert rel < 0.05, (float(err_mu_e), float(err_sk_e))
    # dense and ELL sketched lanes draw the same subsample stream and
    # must agree on the trajectory class
    np.testing.assert_allclose(float(err_sk), float(err_sk_e), rtol=1e-3)


def test_sketched_regularized_solve_stays_close_to_exact():
    """The sketched W update scales l1/l2 by the sampled fraction: the
    m/n-scaled statistics against FULL penalties would over-regularize
    by ~n/m and let l1 kill entries whose sampled numerator is small."""
    X = _counts(1000, 60, 4, 4, scale=0.8)
    Xj = jnp.asarray(X)
    key = jax.random.key(11)
    kh, kw = jax.random.split(key)
    H0 = jax.random.uniform(kh, (1000, 4))
    W0 = jax.random.uniform(kw, (4, 60))
    l1 = 0.5
    _, W_mu, err_mu = nmf_fit_batch(Xj, H0, W0, beta=1.0, max_iter=200,
                                    l1_W=l1)
    _, W_sk, err_sk = nmf_fit_batch(Xj, H0, W0, beta=1.0, max_iter=200,
                                    l1_W=l1, sketch_dim=250,
                                    sketch_exact_every=4)
    rel = abs(float(err_sk) - float(err_mu)) / float(err_mu)
    assert rel < 0.05, (float(err_mu), float(err_sk))
    # the sketched lane must not zero materially more W entries than the
    # exact regularized solve does
    dead_mu = int((np.asarray(W_mu) == 0.0).sum())
    dead_sk = int((np.asarray(W_sk) == 0.0).sum())
    assert dead_sk <= dead_mu + W_mu.size // 20, (dead_mu, dead_sk)


def test_sketch_rejects_wrong_beta_and_recipe_mixes():
    X = jnp.asarray(_counts(40, 16, 3, 0))
    H0 = jnp.ones((40, 3)) * 0.5
    W0 = jnp.ones((3, 16)) * 0.5
    with pytest.raises(ValueError, match="beta=1"):
        nmf_fit_batch(X, H0, W0, beta=2.0, sketch_dim=8)
    with pytest.raises(ValueError, match="exclusive"):
        nmf_fit_batch(X, H0, W0, beta=1.0, sketch_dim=8, kl_newton=True)
    rec = SolverRecipe("sketch", sketch_dim=64, sketch_exact_every=4)
    with pytest.raises(ValueError, match="requires beta=1"):
        run_nmf(_counts(40, 16, 3, 0), 3, beta_loss="frobenius",
                mode="batch", recipe=rec)


def test_run_nmf_sketch_recipe_objective_parity_online():
    X = _counts(600, 50, 4, 2, scale=2.0)
    rec = SolverRecipe("sketch", sketch_dim=128, sketch_exact_every=4,
                       source="caller")
    _, _, err_mu = run_nmf(X, 4, beta_loss="kullback-leibler",
                           mode="online", online_chunk_size=200)
    _, _, err_sk = run_nmf(X, 4, beta_loss="kullback-leibler",
                           mode="online", online_chunk_size=200,
                           recipe=rec)
    assert abs(err_sk - err_mu) / err_mu < 0.05, (err_mu, err_sk)


def test_sweep_identity_recipe_hits_same_program_cache(monkeypatch):
    """CNMF_TPU_SKETCH unset (plus the accel =0 escape hatch) resolves
    the identity recipe, whose sweep program cache entry is the EXACT
    pre-sketch-layer entry."""
    from cnmf_torch_tpu.parallel.replicates import _recipe_statics

    monkeypatch.delenv("CNMF_TPU_SKETCH", raising=False)
    monkeypatch.setenv("CNMF_TPU_ACCEL", "0")
    rec = resolve_recipe(1.0, "batch")
    assert _recipe_statics(rec) == {}
    sk = SolverRecipe("sketch", sketch_dim=64, sketch_exact_every=4)
    stat = _recipe_statics(sk)
    assert stat["sketch_dim"] == 64 and stat["algo"] == "mu"


def test_sketch_recipe_dispatches_through_sweeps():
    from cnmf_torch_tpu.parallel import replicate_sweep

    X = _counts(400, 50, 4, 5, scale=1.5)
    rec = SolverRecipe("sketch", sketch_dim=128, sketch_exact_every=4,
                       source="caller")
    spectra, _, errs = replicate_sweep(
        X, [1, 2], 4, beta_loss="kullback-leibler", mode="batch",
        recipe=rec)
    assert np.isfinite(errs).all()
    _, _, errs_mu = replicate_sweep(
        X, [1, 2], 4, beta_loss="kullback-leibler", mode="batch")
    rel = np.abs(errs - errs_mu) / errs_mu
    assert (rel < 0.05).all(), (errs, errs_mu)


def test_packed_sweep_rejects_sketch():
    from cnmf_torch_tpu.parallel import replicate_sweep_packed

    X = _counts(120, 30, 3, 6)
    rec = SolverRecipe("sketch", sketch_dim=32, sketch_exact_every=4)
    with pytest.raises(ValueError, match="packed"):
        replicate_sweep_packed(X, [3, 4], [1, 2], mode="batch",
                               beta_loss="kullback-leibler", recipe=rec)


def test_rowshard_sketch_matches_mu_class():
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

    n_dev = min(2, len(jax.devices()))
    mesh = Mesh(np.array(jax.devices()[:n_dev]), ("cells",))
    X = _counts(400, 40, 3, 8, scale=2.0)
    _, _, err_mu = nmf_fit_rowsharded(X, 3, mesh,
                                      beta_loss="kullback-leibler", seed=1)
    rec = SolverRecipe("sketch", sketch_dim=96, sketch_exact_every=4,
                       source="caller")
    _, _, err_sk = nmf_fit_rowsharded(X, 3, mesh,
                                      beta_loss="kullback-leibler",
                                      seed=1, recipe=rec)
    assert abs(err_sk - err_mu) / err_mu < 0.08, (err_mu, err_sk)
    with pytest.raises(ValueError, match="requires beta=1"):
        nmf_fit_rowsharded(X, 3, mesh, beta_loss="frobenius", seed=1,
                           recipe=rec)


# ---------------------------------------------------------------------------
# measured-rho autotune cache (satellite)
# ---------------------------------------------------------------------------

class TestAutotuneRho:
    def test_cache_roundtrip_and_lanes(self, tmp_path):
        from cnmf_torch_tpu.utils import autotune

        payload = autotune.maybe_autotune_rho(cache_dir=str(tmp_path),
                                              force=True)
        assert payload is not None
        assert set(payload["scales"]) == {"b2", "dense", "ell"}
        assert payload["fingerprint"] == autotune.device_fingerprint()
        for beta, ell in ((2.0, False), (1.0, False), (1.0, True)):
            v = autotune.cached_rho_scale(beta, ell=ell,
                                          cache_dir=str(tmp_path))
            assert v is not None and v > 0
        # a second call reads the cache instead of re-measuring
        again = autotune.maybe_autotune_rho(cache_dir=str(tmp_path))
        # (guard: force=False short-circuits on the accel knobs; load
        # directly to prove the file is valid)
        assert autotune._load(autotune.cache_path(str(tmp_path))) \
            is not None
        del again

    def test_missing_cache_falls_back_to_static(self, tmp_path):
        from cnmf_torch_tpu.utils import autotune

        assert autotune.cached_rho_scale(2.0,
                                         cache_dir=str(tmp_path)) is None

    def test_skips_when_accel_off(self, tmp_path, monkeypatch):
        from cnmf_torch_tpu.utils import autotune

        monkeypatch.delenv("CNMF_TPU_ACCEL", raising=False)
        assert autotune.maybe_autotune_rho(cache_dir=str(tmp_path)) is None
        assert not os.path.exists(autotune.cache_path(str(tmp_path)))

    def test_measured_scale_steers_auto_inner_repeats(self, monkeypatch):
        import cnmf_torch_tpu.ops.recipe as recipe_mod

        monkeypatch.setattr(recipe_mod, "_measured_rho_scale",
                            lambda beta, ell: 0.25)
        # static b2 ratio at this shape is ~2g/k = 444 -> clamp 8;
        # measured scale 0.25 shrinks it through the widened clamp
        rho = recipe_mod.auto_inner_repeats(2.0, 10000, 2000, 9)
        assert 2 <= rho <= 12
        monkeypatch.setattr(recipe_mod, "_measured_rho_scale",
                            lambda beta, ell: None)
        assert recipe_mod.auto_inner_repeats(2.0, 10000, 2000, 9) == 8


# ---------------------------------------------------------------------------
# sketched consensus end-to-end (pytest fixture pipeline)
# ---------------------------------------------------------------------------

def _structured_counts(n=120, g=300, k_true=4, seed=0):
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k_true) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(k_true, g)) * 50.0 / g
    counts = rng.poisson(usage @ spectra * 200.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    return counts


@pytest.fixture(scope="module")
def sketch_e2e(tmp_path_factory):
    """One prepare -> factorize -> combine run shared by the consensus
    sketch-parity tests."""
    from cnmf_torch_tpu.models.cnmf import cNMF
    from cnmf_torch_tpu.utils.io import save_df_to_npz

    tmp = tmp_path_factory.mktemp("sketch_e2e")
    counts = _structured_counts()
    df = pd.DataFrame(counts,
                      index=[f"cell{i}" for i in range(counts.shape[0])],
                      columns=[f"g{j}" for j in range(counts.shape[1])])
    counts_fn = str(tmp / "counts.df.npz")
    save_df_to_npz(df, counts_fn)
    obj = cNMF(output_dir=str(tmp), name="sk")
    obj.prepare(counts_fn, components=[4], n_iter=8, seed=14,
                num_highvar_genes=200, batch_size=64, max_NMF_iter=200)
    obj.factorize()
    obj.combine()
    return obj


def _consensus_outputs(obj, k=4, dt=0.5):
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    dt_repl = str(dt).replace(".", "_")
    spectra = load_df_from_npz(obj.paths["consensus_spectra"]
                               % (k, dt_repl))
    usages = load_df_from_npz(obj.paths["consensus_usages"] % (k, dt_repl))
    return spectra, usages


def test_sketched_consensus_matches_exact(sketch_e2e, monkeypatch):
    """The satellite's parity contract: same cluster count, identical
    outlier set at the default threshold, cluster-median spectra within
    tolerance — while the distance stage ran at the sketched width."""
    obj = sketch_e2e
    k, thr = 4, 0.5

    monkeypatch.delenv("CNMF_TPU_SKETCH", raising=False)
    cache = obj.paths["local_density_cache"] % k
    if os.path.exists(cache):
        os.remove(cache)
    obj.consensus(k, density_threshold=thr, show_clustering=False,
                  build_ref=False)
    exact_spectra, exact_usages = _consensus_outputs(obj, k, thr)
    from cnmf_torch_tpu.utils.io import load_df_from_npz

    exact_density = load_df_from_npz(cache)

    # sketched lane: force the sketch at a dim below the 200-gene HVG
    # width (the fixture is far smaller than production spectra)
    monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
    monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "96")
    os.remove(cache)
    obj.consensus(k, density_threshold=thr, show_clustering=False,
                  build_ref=False)
    sk_spectra, sk_usages = _consensus_outputs(obj, k, thr)

    # the sketched run must not write the (exact) density cache
    assert not os.path.exists(cache)

    # same cluster count
    assert sk_spectra.shape == exact_spectra.shape

    # identical outlier set at the default threshold: recompute the
    # sketched densities the run used and compare the filter bit vector
    from cnmf_torch_tpu.ops import local_density as knn_local_density
    from cnmf_torch_tpu.ops.sketch import project_rows

    merged = load_df_from_npz(obj.paths["merged_spectra"] % k)
    l2 = (merged.T / np.sqrt((merged ** 2).sum(axis=1))).T.values
    n_neighbors = int(0.30 * merged.shape[0] / k)
    dens_sk, _ = knn_local_density(project_rows(l2, 96), n_neighbors)
    assert ((dens_sk < thr)
            == (exact_density.values[:, 0] < thr)).all()

    # cluster medians within tolerance up to label permutation: greedy
    # cosine matching row-by-row
    A = exact_spectra.values / np.linalg.norm(exact_spectra.values,
                                             axis=1, keepdims=True)
    B = sk_spectra.values / np.linalg.norm(sk_spectra.values, axis=1,
                                           keepdims=True)
    C = A @ B.T
    best = C.max(axis=1)
    assert (best > 0.995).all(), best
    # usages follow the spectra (same refit against matched medians)
    assert sk_usages.shape == exact_usages.shape


def test_sketched_consensus_dispatch_event(sketch_e2e, monkeypatch):
    """Satellite: the consensus stage emits an auditable dispatch event
    carrying the engaged geometry, rendered by summarize_events."""
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                summarize_events,
                                                validate_events_file)

    obj = sketch_e2e
    monkeypatch.setenv("CNMF_TPU_TELEMETRY", "1")
    monkeypatch.setenv("CNMF_TPU_SKETCH", "1")
    monkeypatch.setenv("CNMF_TPU_SKETCH_DIM", "96")
    obj.consensus(4, density_threshold=0.5, show_clustering=False,
                  build_ref=False)
    validate_events_file(obj._events.path)
    events = read_events(obj._events.path)
    summary = summarize_events(events)
    rows = [r for r in summary.get("consensus", [])
            if r.get("stage") == "consensus"]
    assert rows, summary.get("consensus")
    last = rows[-1]
    assert last["sketch"] is True and last["sketch_dim"] == 96
    assert last["distance_width"] == 96
    assert last["replicates"] == 32  # 8 iters x k=4
    # and the report renders the section
    from cnmf_torch_tpu.utils.telemetry import render_report

    report = render_report(os.path.dirname(obj._events.path)
                           .replace("/cnmf_tmp", ""))
    assert "Consensus / k-selection dispatch" in report
    assert "sketch=on dim=96" in report


def test_ooc_slab_loop_sketch_matches_mu_class(tmp_path):
    """The sketch recipe composes with the out-of-core slab loop: the
    per-pass sketch of streamed slab groups lands the same objective
    class as the exact slab-looped solve."""
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded
    from cnmf_torch_tpu.utils.shardstore import (open_shard_store,
                                                 write_shard_store)

    X = _counts(600, 50, 3, 0, scale=2.0)
    path = str(tmp_path / "store")
    write_shard_store(path, X, slab_rows=128)
    store = open_shard_store(path)
    mesh = Mesh(np.array(jax.devices()[:1]), ("cells",))
    _, _, err_mu = nmf_fit_rowsharded(
        store, 3, mesh, beta_loss="kullback-leibler", seed=1,
        store_slab_loop=True)
    rec = SolverRecipe("sketch", sketch_dim=96, sketch_exact_every=4,
                       source="caller")
    _, _, err_sk = nmf_fit_rowsharded(
        store, 3, mesh, beta_loss="kullback-leibler", seed=1,
        store_slab_loop=True, recipe=rec)
    assert abs(err_sk - err_mu) / err_mu < 0.08, (err_mu, err_sk)
