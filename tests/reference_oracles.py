"""Test-only numeric oracles re-deriving the reference implementation's math.

The golden tier (tests/golden/) pins the repo against its own snapshots; this
module exists so the suite can also detect drift from the *reference's*
numerics (VERDICT r1 item 2). Each oracle is an independent torch/numpy
implementation of the algorithm specified by the cited reference function —
same math and iteration semantics, written from the spec. They run on the
same dependency stack the reference uses (torch CPU, sklearn, numpy float64)
so their outputs stand in for the reference's, which cannot be imported here
(its `nmf-torch`/`scanpy` deps are absent).

Citations refer to /root/reference/src/cnmf/cnmf.py.
"""

from __future__ import annotations

import numpy as np
import pandas as pd
import scipy.sparse as sp
from sklearn.preprocessing import StandardScaler


def mean_var_oracle(Y):
    """Population column moments via StandardScaler — the reference's
    `get_mean_var` (cnmf.py:128-131) delegates to this exact sklearn call."""
    s = StandardScaler(with_mean=False).fit(Y)
    return s.mean_, s.var_


def ols_oracle(X, Y, batch_size: int = 1024, normalize_y: bool = False):
    """Batched normal-equation OLS, spec of `efficient_ols_all_cols`
    (cnmf.py:56-126): float64 XtX/XtY accumulated over row batches; when
    `normalize_y`, Y's columns are z-scored with *global* population moments
    (variance floored at 1e-12) one densified batch at a time; solved with
    `np.linalg.lstsq` on the accumulated system."""
    X = np.asarray(X, dtype=np.float64)
    n, p = X.shape
    g = Y.shape[1]
    if normalize_y:
        mu, var = mean_var_oracle(Y)
        sd = np.sqrt(np.where(var < 1e-12, 1e-12, var))
    xtx = np.zeros((p, p))
    xty = np.zeros((p, g))
    for lo in range(0, n, batch_size):
        hi = min(lo + batch_size, n)
        xb = X[lo:hi]
        yb = Y[lo:hi]
        if normalize_y:
            if sp.issparse(yb):
                yb = yb.toarray()
            yb = (yb - mu) / sd
        xtx += xb.T @ xb
        xty += xb.T @ yb
    return np.linalg.lstsq(xtx, xty, rcond=None)[0]


def highvar_genes_oracle(expression, expected_fano_threshold=None,
                         minimal_mean: float = 0.5, numgenes=None):
    """Fano-factor over-dispersion scoring, spec of `get_highvar_genes_sparse`
    (cnmf.py:133-184): expected-Fano line A²·mean + B² with A = min CV of the
    20 highest-mean genes and B² = median Fano inside the 10–90th-percentile
    winsor box; selection by top-`numgenes` fano_ratio, or by
    `fano_ratio > T` (T = 1 + std of winsorized Fano unless given) with a
    `minimal_mean` floor. Returns (stats_df, params_dict)."""
    mean_, var_ = mean_var_oracle(expression)
    mean_s = pd.Series(mean_)
    var_s = pd.Series(var_)
    fano = var_s / mean_s

    top20 = mean_s.sort_values(ascending=False).index[:20]
    a_param = (np.sqrt(var_s) / mean_s)[top20].min()

    m_lo, m_hi = mean_s.quantile([0.10, 0.90])
    f_lo, f_hi = fano.quantile([0.10, 0.90])
    in_box = (fano > f_lo) & (fano < f_hi) & (mean_s > m_lo) & (mean_s < m_hi)
    b_param = np.sqrt(fano[in_box].median())

    expected = a_param ** 2 * mean_s + b_param ** 2
    ratio = fano / expected

    if numgenes is not None:
        chosen = ratio.sort_values(ascending=False).index[:numgenes]
        high_var = ratio.index.isin(chosen)
        t_param = None
    else:
        t_param = (expected_fano_threshold
                   if expected_fano_threshold else 1.0 + fano[in_box].std())
        high_var = (ratio > t_param) & (mean_s > minimal_mean)

    stats = pd.DataFrame({
        "mean": mean_s, "var": var_s, "fano": fano,
        "expected_fano": expected, "high_var": high_var,
        "fano_ratio": ratio,
    })
    return stats, {"A": a_param, "B": b_param, "T": t_param,
                   "minimal_mean": minimal_mean}


def fit_h_online_oracle(X, W, H_init, chunk_size: int = 5000,
                        chunk_max_iter: int = 200, h_tol: float = 0.05,
                        l1_reg_H: float = 0.0, l2_reg_H: float = 0.0,
                        eps: float = 1e-16):
    """Fixed-W online MU usage solver in torch fp32, spec of `fit_H_online`
    (cnmf.py:260-388): one pass over row chunks; per chunk, the numerator
    X·Wᵀ is computed once (L1 subtracted and clamped), then MU steps
    H ← H · numer/(H·WWᵀ + l2·H) run until the relative Frobenius change of
    the block is below `h_tol` or `chunk_max_iter`, zeroing rates where the
    denominator underflows `eps`."""
    import torch

    x_t = torch.as_tensor(np.ascontiguousarray(np.asarray(X, np.float32)))
    w_t = torch.as_tensor(np.ascontiguousarray(np.asarray(W, np.float32)))
    h_t = torch.as_tensor(
        np.ascontiguousarray(np.asarray(H_init, np.float32))).clamp(min=0.0).clone()
    gram = w_t @ w_t.T
    n = x_t.shape[0]
    for lo in range(0, n, chunk_size):
        x = x_t[lo:lo + chunk_size]
        h = h_t[lo:lo + chunk_size]
        numer = x @ w_t.T
        if l1_reg_H > 0:
            numer = (numer - l1_reg_H).clamp(min=0.0)
        for _ in range(chunk_max_iter):
            denom = h @ gram
            if l2_reg_H > 0:
                denom = denom + l2_reg_H * h
            step = numer / denom
            step[denom < eps] = 0.0
            h_new = h * step
            rel = torch.norm(h_new - h) / (torch.norm(h) + eps)
            h = h_new
            if rel < h_tol:
                break
        h_t[lo:lo + chunk_size] = h
    return h_t.numpy()


def local_density_oracle(l2_spectra: np.ndarray, n_neighbors: int):
    """KNN local-density outlier score, spec of the consensus density filter
    (cnmf.py:1065-1071): full euclidean distance matrix, argpartition to the
    (n_neighbors+1) closest (self included at distance 0), mean distance to
    the n nearest."""
    from sklearn.metrics import euclidean_distances

    dist = euclidean_distances(l2_spectra)
    order = np.argpartition(dist, n_neighbors + 1)[:, :n_neighbors + 1]
    nearest = dist[np.arange(dist.shape[0])[:, None], order]
    return nearest.sum(axis=1) / n_neighbors


def consensus_medians_oracle(l2_spectra: pd.DataFrame, labels: pd.Series):
    """Cluster-median spectra renormalized to probability distributions,
    spec of cnmf.py:1087-1090."""
    med = l2_spectra.groupby(labels).median()
    return (med.T / med.sum(axis=1)).T


def reorder_oracle(rf_usages: pd.DataFrame, median_spectra: pd.DataFrame):
    """GEP reordering by total normalized usage, spec of cnmf.py:1113-1120;
    returns (rf_usages, norm_usages, median_spectra) with 1..k columns."""
    norm = rf_usages.div(rf_usages.sum(axis=1), axis=0)
    order = norm.sum(axis=0).sort_values(ascending=False).index
    rf_usages = rf_usages.loc[:, order]
    norm = norm.loc[:, order]
    median_spectra = median_spectra.loc[order, :]
    new_cols = np.arange(1, rf_usages.shape[1] + 1)
    rf_usages.columns = new_cols
    norm.columns = new_cols
    median_spectra.index = new_cols
    return rf_usages, norm, median_spectra


def moe_correct_ridge_oracle(Z_orig, R, Phi_moe, lamb):
    """Mixture-of-experts ridge correction, spec of the reference's
    `moe_correct_ridge` (preprocess.py:9-18, itself harmonypy's
    moe_correct_ridge): per cluster i, Phi_Rk = Phi_moe * R[i], W =
    inv(Phi_Rk Phi_moe^T + lamb) Phi_Rk Z_orig^T with the intercept row
    zeroed, Z_corr -= W^T Phi_Rk. Float64 throughout; `lamb` is the full
    (B+1) x (B+1) matrix as harmonypy's result object carries it."""
    Z_orig = np.asarray(Z_orig, dtype=np.float64)
    R = np.asarray(R, dtype=np.float64)
    Phi_moe = np.asarray(Phi_moe, dtype=np.float64)
    lamb = np.asarray(lamb, dtype=np.float64)
    if lamb.ndim == 1:
        lamb = np.diag(lamb)
    Z_corr = Z_orig.copy()
    for i in range(R.shape[0]):
        Phi_Rk = Phi_moe * R[i, :]
        x = Phi_Rk @ Phi_moe.T + lamb
        W = np.linalg.inv(x) @ Phi_Rk @ Z_orig.T
        W[0, :] = 0.0
        Z_corr -= W.T @ Phi_Rk
    return Z_corr


def harmony_cluster_round_oracle(Z_cos, R, phi, Pr_b, sigma, theta, blocks):
    """One Harmony clustering round, spec of harmonypy's `_clustering`
    (the package the reference calls at preprocess.py:373-378; update
    equations from Korsunsky et al. 2019 and harmonypy's implementation):

      1. centroid refresh Y = colnorm(Z_cos R^T), dist = 2(1 - Y^T Z_cos)
      2. per cell block: remove the block from the (K x B) counts E
         (expected) and O (observed); R_blk = exp(-dist/sigma) *
         [((E+1)/(O+1))^theta  phi_blk]  (theta exponentiates per batch
         COLUMN; the penalty projects onto each cell's active levels by a
         dot product); L1-normalize columns; add the block back to E/O.

    Float64 numpy, independent of the JAX kernels. Returns (R, E, O, Y,
    objective) with the objective from harmonypy's `compute_objective`:
    sum(R*dist) + sigma*sum(R log R) + sigma*theta*sum(O log((O+1)/(E+1))).
    """
    Z_cos = np.asarray(Z_cos, dtype=np.float64)
    R = np.asarray(R, dtype=np.float64).copy()
    phi = np.asarray(phi, dtype=np.float64)
    Pr_b = np.asarray(Pr_b, dtype=np.float64)
    sigma = np.asarray(sigma, dtype=np.float64)
    theta = np.asarray(theta, dtype=np.float64)

    Y = Z_cos @ R.T
    Y = Y / np.linalg.norm(Y, ord=2, axis=0)
    dist = 2.0 * (1.0 - Y.T @ Z_cos)
    E = np.outer(R.sum(axis=1), Pr_b)
    O = R @ phi.T
    for blk in blocks:
        E -= np.outer(R[:, blk].sum(axis=1), Pr_b)
        O -= R[:, blk] @ phi[:, blk].T
        Rb = np.exp(-dist[:, blk] / sigma[:, None])
        Rb = Rb * (np.power((E + 1.0) / (O + 1.0), theta) @ phi[:, blk])
        Rb = Rb / np.linalg.norm(Rb, ord=1, axis=0)
        R[:, blk] = Rb
        E += np.outer(Rb.sum(axis=1), Pr_b)
        O += Rb @ phi[:, blk].T
    kmeans_err = float(np.sum(R * dist))
    entropy = float(np.sum(R * np.log(np.maximum(R, 1e-12)) * sigma[:, None]))
    diversity = float(np.sum(
        sigma[:, None] * theta * O * np.log((O + 1.0) / (E + 1.0))))
    return R, E, O, Y, kmeans_err + entropy + diversity
