"""Multi-host execution: ``jax.distributed`` + the 2-D replicates x cells mesh.

The reference's multi-node story is GNU parallel / SGE array jobs — N
independent OS processes sharing a filesystem, statically sharded by
``worker_filter`` (``/root/reference/Extras/run_parallel.py:47-51``,
``Stepwise_Guide.md:46-63``). A TPU pod is a different shape: ONE
single-controller JAX program spans every host; the same Python script runs
on each host, ``jax.distributed.initialize`` stitches their local chips into
one global device set, and collectives ride ICI within a slice / DCN across
slices (SURVEY.md §2.2, §2.4).

This module provides that story:

  * :func:`initialize_distributed` — env-driven, idempotent
    ``jax.distributed.initialize``. On Cloud TPU pods the three coordinates
    are auto-detected; elsewhere (CPU fleets, tests) they come from
    ``CNMF_COORDINATOR_ADDRESS`` / ``CNMF_NUM_PROCESSES`` /
    ``CNMF_PROCESS_ID``.
  * :func:`mesh_2d` — the (replicates, cells) mesh. The replicate axis is
    laid out ACROSS hosts (replicates never communicate, so the slow DCN
    hop carries zero solver traffic); the cells axis stays WITHIN a host so
    the per-pass psum of W sufficient statistics rides ICI.
  * :func:`replicate_sweep_2d` — the full replicate sweep over that mesh:
    every replicate row-shards its cells over the mesh's cell axis (the
    row-sharded block-coordinate solver, identical semantics to
    :func:`~cnmf_torch_tpu.parallel.rowshard.nmf_fit_rowsharded`), and the
    replicate axis vmaps/shards over hosts — the reference's "900 worker
    processes" as one XLA program spanning the pod.

Host-side IO remains the coordinator's job: every process computes, process
0 writes artifacts (the filesystem stays the durable checkpoint layer, as in
the reference — SURVEY.md §1.1).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.jax_compat import shard_map

from ..ops.nmf import (
    resolve_online_schedule,
    _nndsvd_from_svd,
    beta_loss_to_float,
    gram_svd_base,
    random_init,
    split_regularization,
)
from .rowshard import _rowsharded_solve_local, stream_rows_to_mesh

__all__ = [
    "initialize_distributed",
    "is_coordinator",
    "mesh_2d",
    "replicate_sweep_2d",
    "sync_hosts",
    "HostBarrierTimeout",
    "barrier_timeout_s",
]

_ENV_COORD = "CNMF_COORDINATOR_ADDRESS"
_ENV_NPROC = "CNMF_NUM_PROCESSES"
_ENV_PID = "CNMF_PROCESS_ID"
BARRIER_TIMEOUT_ENV = "CNMF_TPU_BARRIER_TIMEOUT_S"
_initialized = False


class HostBarrierTimeout(RuntimeError):
    """A cross-host barrier did not complete within
    ``CNMF_TPU_BARRIER_TIMEOUT_S`` — a peer host is dead or wedged. The
    single-controller program cannot make progress without it, so this
    converts the distributed hang into a clean abort: relaunch the SAME
    command on every host and factorize resumes from its per-replicate
    artifacts and the newest valid pass checkpoint.

    Under ``CNMF_TPU_HEARTBEAT_S`` liveness (``runtime/elastic.py``) the
    timeout is additionally DIAGNOSED: ``culprits`` names the peers whose
    heartbeats went stale or were never stamped (index, last-beat age,
    pass cursor), so the operator relaunches minus the right host instead
    of bisecting a generic timeout."""

    def __init__(self, message: str, culprits: list | None = None):
        super().__init__(message)
        self.culprits = list(culprits or [])


def barrier_timeout_s() -> float:
    """Cross-host barrier watchdog in seconds
    (``CNMF_TPU_BARRIER_TIMEOUT_S``, default 0 = wait forever — the
    pre-watchdog behavior). Non-numeric or negative values reject at
    parse time with a one-line message (``utils/envknobs.py``)."""
    from ..utils.envknobs import env_float

    return env_float(BARRIER_TIMEOUT_ENV, 0.0, lo=0.0)


import threading

# one abandonment log line per barrier name per process: the watchdog may
# fire on the same wedged barrier repeatedly across retries, and a log
# storm would bury the diagnosis it exists to provide
_abandoned_lock = threading.Lock()
_abandoned_names: set[str] = set()


def _wait_with_timeout(fn, timeout_s: float, name: str):
    """Run a (blocking, uninterruptible) collective with a wall-clock
    watchdog: the collective runs on a daemon thread and the caller waits
    ``timeout_s`` for it.

    No-zombie-thread invariant (aligned with the streaming watchdog,
    ``parallel/streaming.py:run_pipeline``): only a GENUINE wedge — the
    collective still running at expiry — abandons the thread (a wedged
    collective cannot be cancelled, only diagnosed), and that abandonment
    is logged once per barrier name. Every other path — completion,
    collective raised its own error — joins the thread before returning,
    so no barrier thread outlives a successful or failed barrier call.
    ``timeout_s <= 0`` runs inline, unchanged."""
    if not timeout_s or timeout_s <= 0:
        fn()
        return

    done = threading.Event()
    errs: list[BaseException] = []

    def run():
        try:
            fn()
        except BaseException as exc:  # surfaced to the caller below
            errs.append(exc)
        finally:
            done.set()

    t = threading.Thread(target=run, name=f"cnmf-barrier-{name}",
                         daemon=True)
    t.start()
    if not done.wait(timeout_s):
        with _abandoned_lock:
            first = name not in _abandoned_names
            _abandoned_names.add(name)
        if first:
            import warnings

            warnings.warn(
                "abandoning wedged barrier thread %r after %gs — a hung "
                "collective cannot be cancelled, only diagnosed; it exits "
                "with the process" % (f"cnmf-barrier-{name}", timeout_s),
                RuntimeWarning, stacklevel=2)
        raise HostBarrierTimeout(
            "barrier %r did not complete within %gs (%s) — a peer host is "
            "likely dead. Aborting with state checkpointed; relaunch the "
            "same command on every host to resume from the newest valid "
            "checkpoint." % (name, timeout_s, BARRIER_TIMEOUT_ENV))
    # the collective finished (ok or raising): the thread is past fn() and
    # about to exit — join it so no barrier thread outlives its call
    t.join()
    if errs:
        raise errs[0]


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None,
                           auto: bool = False) -> tuple[int, int]:
    """Idempotent ``jax.distributed.initialize`` and (process_id, count).

    Coordinates resolve in order: explicit arguments, ``CNMF_*`` env vars,
    then — only with ``auto=True`` (the CLI's explicit ``--distributed``
    flag) — JAX's own auto-detection (Cloud TPU pod metadata), which fails
    loud rather than silently running single-process when detection isn't
    possible. With no coordinates and ``auto=False`` this is a no-op
    single-process setup — safe to call unconditionally.

    Multi-host runs launch like a TPU pod job: the SAME command on every
    host, differing only in ``CNMF_PROCESS_ID`` (see
    ``docs/Stepwise_Guide.md``), not like the reference's per-worker task
    sharding (its ``--worker-index`` maps to a *replicate* subset; a
    process here is a *mesh* participant and runs every replicate's
    program).
    """
    global _initialized
    if _initialized or getattr(jax.distributed, "is_initialized", lambda: False)():
        return jax.process_index(), jax.process_count()

    from ..utils.envknobs import env_int, env_str

    coordinator_address = coordinator_address or env_str(_ENV_COORD) or None
    if num_processes is None:
        num_processes = env_int(_ENV_NPROC, None, lo=1)
    if process_id is None:
        process_id = env_int(_ENV_PID, None, lo=0)

    given = {"coordinator_address": coordinator_address,
             "num_processes": num_processes, "process_id": process_id}
    missing = [k for k, v in given.items() if v is None]
    if len(missing) == 3:
        if auto:
            # the caller explicitly asked for distributed execution: let JAX
            # auto-detect the pod coordinates (Cloud TPU metadata). A silent
            # single-process fallback here would have every pod host run the
            # full program independently and race on artifact writes.
            try:
                jax.distributed.initialize()
            except Exception as exc:
                raise RuntimeError(
                    "distributed initialization was requested "
                    "(--distributed) but JAX could not auto-detect the "
                    "cluster and no CNMF_COORDINATOR_ADDRESS / "
                    "CNMF_NUM_PROCESSES / CNMF_PROCESS_ID are set"
                ) from exc
            # single-threaded by construction: runs once from CLI/worker
            # startup before any thread pool exists
            _initialized = True  # cnmf-lint: disable=lock-discipline
            return jax.process_index(), jax.process_count()
        # plain single-process call. Don't force initialize — and don't
        # latch: a later call WITH coordinates must still be able to
        # initialize.
        return jax.process_index(), jax.process_count()
    if missing:
        # partial coordinates (e.g. a stale CNMF_COORDINATOR_ADDRESS left in
        # the env) would make jax.distributed.initialize hang or misconfigure
        # — fail loud instead
        raise ValueError(
            "distributed launch needs all three coordinates; missing "
            f"{missing} (set the CNMF_COORDINATOR_ADDRESS / "
            "CNMF_NUM_PROCESSES / CNMF_PROCESS_ID env vars together, or "
            "unset them all for single-process runs)")

    # older jax (< 0.5) defaults the CPU backend's cross-process
    # collectives OFF ("Multiprocess computations aren't implemented on
    # the CPU backend"); the gloo implementation ships in jaxlib — enable
    # it when simulating pods on CPU so the same code path works across
    # versions (modern jax ignores/auto-handles this)
    if not env_str("JAX_CPU_COLLECTIVES_IMPLEMENTATION"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # option absent (modern jax auto-selects) — nothing to do
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    # single-threaded by construction (same once-at-startup path as above)
    _initialized = True  # cnmf-lint: disable=lock-discipline
    return jax.process_index(), jax.process_count()


def is_coordinator() -> bool:
    """True on the process that owns host-side IO (artifact writes)."""
    return jax.process_index() == 0


def sync_hosts(name: str = "cnmf", timeout_s: float | None = None,
               heartbeat=None) -> None:
    """Barrier across hosts (no-op single-process). Used around artifact
    writes so non-coordinator hosts don't race ahead and read files the
    coordinator hasn't written yet — the same write-then-read discipline the
    reference gets from stage boundaries (SURVEY.md §5.2).

    Bounded (ISSUE 6): under ``CNMF_TPU_BARRIER_TIMEOUT_S`` (or an
    explicit ``timeout_s``) a barrier a dead host can never join raises
    :class:`HostBarrierTimeout` — a clean, checkpoint-resumable abort —
    instead of wedging every surviving host forever.

    Named culprits (ISSUE 8): pass a
    :class:`~cnmf_torch_tpu.runtime.elastic.Heartbeat` and this process
    stamps its own liveness before waiting; on timeout the peers' stale
    or missing heartbeats are read back and the raised
    :class:`HostBarrierTimeout` NAMES the dead/wedged participant(s)
    (``.culprits``) — plus a telemetry ``fault`` event (kind
    ``host_loss``) when the heartbeat carries an event log — instead of
    a generic barrier timeout."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        if heartbeat is not None:
            heartbeat.beat(phase=f"barrier:{name}", force=True)
        timeout = barrier_timeout_s() if timeout_s is None else timeout_s
        try:
            _wait_with_timeout(
                lambda: multihost_utils.sync_global_devices(name), timeout,
                name)
        except HostBarrierTimeout as exc:
            if heartbeat is None or not heartbeat.enabled:
                raise
            culprits = heartbeat.culprits(jax.process_count())
            detail = heartbeat.describe(culprits)
            if heartbeat.events is not None:
                heartbeat.events.emit(
                    "fault", kind="host_loss",
                    context={"barrier": name, "culprits": culprits})
            raise HostBarrierTimeout(
                f"{exc} Liveness diagnosis: {detail}.",
                culprits=culprits) from None


def _balanced_rc(n_dev: int, n_proc: int) -> tuple[int, int]:
    """Factor the device count into (replicate_shards, cell_shards).

    Multi-host: one replicate shard per host, cells within the host — the
    cells-axis psum (the only per-pass collective) never crosses DCN.
    Single host: the most-square factorization, biased so cells get the
    larger factor (cell counts exceed replicate counts in every BASELINE
    config)."""
    if n_proc > 1 and n_dev % n_proc == 0:
        return n_proc, n_dev // n_proc
    r = 1
    for cand in range(int(math.isqrt(n_dev)), 0, -1):
        if n_dev % cand == 0:
            r = cand
            break
    return r, n_dev // r


def mesh_2d(replicate_shards: int | None = None,
            devices=None) -> Mesh:
    """The (replicates, cells) mesh over all global devices.

    Device order: ``jax.devices()`` lists process 0's chips first, so
    reshaping to (replicate_shards, cell_shards) with one replicate shard
    per host puts each host's chips in one mesh row — the promoted layout
    from the driver dryrun (``__graft_entry__.py``), now reachable from
    ``factorize(mesh_shape='2d')``.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    n_dev = len(devices)
    if replicate_shards is None:
        r, c = _balanced_rc(n_dev, jax.process_count())
    else:
        r = int(replicate_shards)
        if n_dev % r:
            raise ValueError(
                f"replicate_shards={r} does not divide {n_dev} devices")
        c = n_dev // r
    return Mesh(np.asarray(devices).reshape(r, c), ("replicates", "cells"))


@functools.lru_cache(maxsize=64)
def _sweep2d_program(n: int, g: int, k: int, R: int, init: str, beta: float,
                     tol: float, h_tol: float, n_passes: int,
                     chunk_max_iter: int,
                     l1_H: float, l2_H: float, l1_W: float, l2_W: float,
                     mesh: Mesh):
    """Compile (once per static config) the 2-D sweep
    ``(X (n,g) cells-sharded, seeds (R,)) -> (spectra (R,k,g), errs (R,))``.

    Inits are generated inside the program (vmapped seeded uniform, same
    mapping as the row-sharded solver) under sharding constraints, so no
    host materializes an (R, n, k) array.
    """
    rep_ax, cell_ax = mesh.axis_names

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(cell_ax, None),            # X: rows over cells, same on
                                               # every replicate shard
                  P(rep_ax, cell_ax, None),    # H0: (R, n, k)
                  P(rep_ax, None, None)),      # W0: (R, k, g)
        out_specs=(P(rep_ax, None, None), P(rep_ax)),
    )
    def run(X_blk, H_blk, W_blk):
        def one(h, w):
            h, w, err = _rowsharded_solve_local(
                X_blk, h, w, cell_ax, beta, tol, h_tol, n_passes,
                chunk_max_iter, l1_H, l2_H, l1_W, l2_W)
            return w, err

        # replicate axis: pure vmap, zero communication; cells axis: the
        # psums inside _rowsharded_pass (ICI-local by mesh construction)
        return jax.vmap(one)(H_blk, W_blk)

    def sweep(X, seeds):
        x_mean = jnp.mean(X)

        if init == "random":
            H0, W0 = jax.vmap(
                lambda s: random_init(jax.random.key(s), n, g, k, x_mean)
            )(seeds)
        elif init in ("nndsvd", "nndsvda", "nndsvdar"):
            # gram-based nndsvd (the sharding-friendly form — the only
            # all-to-all object is the g x g gram); the deterministic base
            # computes ONCE, only the seeded zero-fill vmaps over replicate
            # keys (nndsvdar semantics, same mapping as the 1-D sweep)
            variant = "nndsvdar" if init == "nndsvd" else init
            U, S, Vt = gram_svd_base(X, k)
            H0, W0 = jax.vmap(
                lambda s: _nndsvd_from_svd(U, S, Vt, k, variant,
                                           jax.random.key(s), x_mean)
            )(seeds)
        else:
            raise ValueError(f"unknown init {init!r}")
        H0 = jax.lax.with_sharding_constraint(
            H0, NamedSharding(mesh, P(rep_ax, cell_ax, None)))
        W0 = jax.lax.with_sharding_constraint(
            W0, NamedSharding(mesh, P(rep_ax, None, None)))
        return run(X, H0, W0)

    return jax.jit(sweep)


def replicate_sweep_2d(X, seeds, k: int, mesh: Mesh, beta_loss="frobenius",
                       init: str = "random",
                       tol: float = 1e-4, h_tol: float = 0.05,
                       n_passes: int | None = None,
                       chunk_max_iter: int = 1000,
                       alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                       alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                       replicates_per_batch: int | None = None,
                       fetch: bool = True):
    """Run ``len(seeds)`` NMF replicates over a 2-D (replicates, cells) mesh.

    Each replicate is the row-sharded block-coordinate solve of
    :func:`~cnmf_torch_tpu.parallel.rowshard.nmf_fit_rowsharded` (identical
    init + pass loop, so per-seed results agree to collective-reduction
    rounding); replicates are sharded over the replicate axis. This is the
    layout for the regime where BOTH axes are big — atlas-scale X *and* a
    wide sweep — and for multi-host pods, where the replicate axis spans
    hosts (no cross-host solver traffic) and the cells-psum stays on ICI.

    ``X``: host matrix (dense/CSR — streamed, never host-densified whole) or
    a cells-sharded device array staged by :func:`stage_x_2d` (padded rows
    are benign: only the returned W depends on them, and zero rows
    contribute nothing to its psum'd statistics). Returns
    ``(spectra (R,k,g), errs (R,))`` — numpy on every host with
    ``fetch=True`` (multi-host: all-gathered across processes).
    ``fetch=False`` keeps device arrays single-process; multi-process it
    still gathers to numpy (sliced sweeps' sharded handles cannot be
    stitched without cross-host resharding).
    """
    beta = beta_loss_to_float(beta_loss)
    _, n_passes, _ = resolve_online_schedule(beta, h_tol, n_passes)
    if beta not in (2.0, 1.0, 0.0):
        raise ValueError(
            f"replicate_sweep_2d supports beta in {{2, 1, 0}}, got {beta}")
    r_dim, c_dim = mesh.devices.shape
    Xd = X if isinstance(X, jax.Array) else stage_x_2d(X, mesh)
    n, g = int(Xd.shape[0]), int(Xd.shape[1])

    seeds = [int(s) & 0x7FFFFFFF for s in seeds]
    R = len(seeds)
    if R == 0:
        return np.zeros((0, int(k), g), np.float32), np.zeros((0,), np.float32)

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)

    # memory-bounded slicing, same budget model as the 1-D sweep: per-device
    # live state per replicate is H (n/c_dim rows) + W, and beta != 2
    # materializes block x genes MU intermediates. _slice_specs keeps slices
    # replicate-shard multiples; without this a wide sweep at atlas scale
    # admits an unbounded (R/r_dim, n/c_dim, k) H stack per device and OOMs.
    from .replicates import _slice_specs

    n_local = -(-n // c_dim)
    _, slices = _slice_specs(n_local, g, int(k), R, beta, "batch", n_local,
                             replicates_per_batch, r_dim)

    from ..runtime.faults import maybe_hostloss

    # every slice stays PADDED on device: trimming (w[:r]) or concatenating
    # sharded arrays eagerly would cut across shard boundaries of
    # non-fully-addressable arrays on a real multi-host pod — gather first,
    # trim in numpy (single-process arrays are fully addressable, so the
    # same order is merely free there)
    parts = []
    for start, r, r_pad in slices:
        # injectable topology loss at the slice boundary (hostloss:
        # context=sweep2d) — where a real dead device would surface as
        # the next dispatch failing; the elastic controller re-meshes
        maybe_hostloss(context="sweep2d")
        sl = seeds[start:start + r]
        if r_pad > r:
            sl = sl + [sl[i % r] for i in range(r_pad - r)]
        prog = _sweep2d_program(n, g, int(k), len(sl), str(init), beta,
                                float(tol), float(h_tol), int(n_passes),
                                int(chunk_max_iter),
                                l1_H, l2_H, l1_W, l2_W, mesh)
        w, e = prog(Xd, jnp.asarray(sl, jnp.uint32))
        parts.append((r, w, e))

    multiproc = jax.process_count() > 1
    if not fetch and not multiproc:
        # device arrays, trimmed/concatenated (fully addressable here)
        if len(parts) == 1:
            r, w, e = parts[0]
            return w[:r], e[:r]
        return (jnp.concatenate([w[:r] for r, w, _ in parts]),
                jnp.concatenate([e[:r] for r, _, e in parts]))

    # fetch=True, or multi-process (where device handles of a sliced sweep
    # cannot be safely stitched — every host needs the full result anyway)
    if multiproc:
        from jax.experimental import multihost_utils

        host_parts = [
            (r, multihost_utils.process_allgather(w, tiled=True),
             multihost_utils.process_allgather(e, tiled=True))
            for r, w, e in parts]
    else:
        host_parts = [(r, np.asarray(w), np.asarray(e)) for r, w, e in parts]
    spectra = np.concatenate([w[:r] for r, w, _ in host_parts])
    errs = np.concatenate([e[:r] for r, _, e in host_parts])
    return spectra, errs


def stage_x_2d(X, mesh: Mesh, dtype=jnp.float32, events=None,
               liveness=None):
    """Stage a host matrix for repeated 2-D sweeps: rows sharded over the
    cells axis, replicated over the replicate axis; one shard-sized CSR
    block densifies at a time (no whole-matrix host densify).
    ``liveness`` is stamped per committed slab (heartbeat — a long stage
    must not read as a wedge at the next barrier).

    ``X`` may also be a shard store or :class:`~cnmf_torch_tpu.utils.
    shardstore.SlabCursor` (out-of-core ingestion, ISSUE 10): each pod
    process then reads ONLY the store slabs overlapping its addressable
    cell shards from disk — no process ever materializes the full matrix
    in host RAM, which is exactly the N-hosts x full-matrix multiplier
    the single-controller load path used to pay."""
    Xd, _pad = stream_rows_to_mesh(X, mesh, mesh.axis_names[1], dtype=dtype,
                                   events=events, liveness=liveness)
    return Xd
