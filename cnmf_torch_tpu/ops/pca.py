"""Principal component analysis on device.

Replaces the reference's ``sc.pp.pca`` call in the batch-correction path
(``/root/reference/src/cnmf/preprocess.py:310``). One economy SVD of the
(optionally centered) matrix on the MXU; signs are fixed to scanpy/sklearn's
``svd_flip`` convention (largest-|loading| positive per component) so
downstream Harmony runs see the same basis orientation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

__all__ = ["pca"]

_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("n_comps", "zero_center"))
def _pca_jit(X, n_comps: int, zero_center: bool):
    if zero_center:
        X = X - jnp.mean(X, axis=0, keepdims=True)
    U, S, Vt = jnp.linalg.svd(X, full_matrices=False)
    U, S, Vt = U[:, :n_comps], S[:n_comps], Vt[:n_comps, :]
    # svd_flip: orient each component so its largest-|value| loading is
    # positive (removes SVD sign ambiguity; matches sklearn/scanpy)
    max_idx = jnp.argmax(jnp.abs(Vt), axis=1)
    signs = jnp.sign(Vt[jnp.arange(n_comps), max_idx])
    Vt = Vt * signs[:, None]
    U = U * signs[None, :]
    X_pca = U * S[None, :]
    n = X.shape[0]
    explained_var = (S ** 2) / jnp.maximum(n - 1, 1)
    return X_pca, Vt, explained_var


def pca(X, n_comps: int = 50, zero_center: bool = True):
    """Returns ``(X_pca (n, n_comps), components (n_comps, g),
    explained_variance_ratio (n_comps,))`` as numpy arrays."""
    if sp.issparse(X):
        X = X.toarray()
    X = np.asarray(X, dtype=np.float32)
    n_comps = int(min(n_comps, min(X.shape) - 1 if zero_center else min(X.shape)))
    X_pca, Vt, ev = _pca_jit(jnp.asarray(X), n_comps, bool(zero_center))
    if zero_center:
        total_var = float(np.var(X, axis=0, ddof=1).sum())
    else:
        # uncentered SVD energy includes the mean component, so the ratio
        # denominator must be the uncentered second moment or ratios blow
        # past 1 for data with a large mean offset
        total_var = float((np.asarray(X, np.float64) ** 2).sum()
                          / max(X.shape[0] - 1, 1))
    ratio = np.asarray(ev, dtype=np.float64) / max(total_var, 1e-30)
    return np.asarray(X_pca), np.asarray(Vt), ratio
