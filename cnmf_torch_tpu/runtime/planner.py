"""Declarative execution planner — the ONE place dispatch is decided.

Every perf PR so far added a fast lane behind its own knob and its own
call-site heuristic: dense-vs-ELL encoding (``ops/sparse.py``), the
solver recipe (``ops/recipe.py``), the fused Pallas kernels
(``ops/pallas``), packed K-sweeps and the mesh layouts
(``models/cnmf.py`` + ``parallel/``), streaming transport/depth
(``parallel/streaming.py``), the OOC ingest tier (``utils/shardstore``),
the store backend (``utils/storebackend``), and the serve bucket
schedule (``serving/batcher.py``). The scatter worked while each lane
shipped off-by-default; honest ``auto`` defaults need the decisions in
one auditable object. This module provides it:

  * :class:`ExecutionPlan` — the resolved dispatch surface as one flat,
    JSON-able dataclass, with a per-field ``sources`` map recording WHO
    decided (``pin`` — an explicit env knob; ``autotuned`` — a measured
    microbench point from ``utils/autotune.py``; ``heuristic`` — the
    static shape-driven default). Precedence is exactly that order.
  * :func:`build_plan` — one call per factorize, from
    :class:`InputStats` (matrix shape/sparsity/β/mode) and
    :class:`DeviceInventory` (backend/devices/hosts). It delegates to
    the SAME registered resolver functions the dispatch sites consume
    (``resolve_sparse_beta``, ``resolve_recipe``, ``resolve_pallas``,
    ``stream_threads`` …), so the plan IS the dispatch — not a parallel
    re-implementation that can drift. The lint rule ``knob-plan-bypass``
    (``analysis/rules_knobs.py``) pins that property: dispatch-class
    knob reads outside this module / the allowlisted resolvers fail the
    gate.
  * JSON round-trip (:meth:`ExecutionPlan.to_json` / :func:`load_plan`)
    plus :func:`apply_plan`, which pins the corresponding env knobs so
    ``cnmf-tpu factorize --plan <file>`` (or ``CNMF_TPU_PLAN=<file>``)
    reproduces a run's dispatch bit-identically — every scattered
    consumer resolves the pinned values, and re-building the plan under
    the pins round-trips to the same plan.
  * The resolved plan is logged whole as one ``plan`` telemetry event
    per factorize (``utils/telemetry.py`` schema), rendered by
    ``cnmf-tpu report`` / ``cnmf-tpu plan <run_dir>``, and its
    math-affecting fragment (:meth:`ExecutionPlan.identity_fragment`)
    rides the checkpoint identity — a plan change restarts a mid-run
    replicate instead of splicing trajectories.

Stdlib-only at import time (jax imports are lazy): the lint engine and
the CLI's pre-jax paths import this module.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import asdict, dataclass, field, fields

__all__ = [
    "PLAN_VERSION", "PLAN_ENV", "AUTOTUNE_ENV",
    "DISPATCH_KNOBS", "PLAN_ACCESSORS", "PLAN_OWNER_FILES",
    "InputStats", "DeviceInventory", "ExecutionPlan",
    "build_plan", "resolve_encoding", "apply_plan", "load_plan",
    "maybe_apply_plan_env", "render_plan", "plan_from_run_dir",
]

PLAN_VERSION = 1
PLAN_ENV = "CNMF_TPU_PLAN"
AUTOTUNE_ENV = "CNMF_TPU_AUTOTUNE"

# the dispatch-class knobs: every env variable that picks WHICH program
# runs (encoding / recipe / kernel / layout / streaming / ingest tier /
# store backend / serve schedule) as opposed to capacity or resilience
# tuning. The `knob-plan-bypass` lint rule fails any read of these
# outside PLAN_OWNER_FILES that is not inside a PLAN_ACCESSORS resolver.
DISPATCH_KNOBS = frozenset({
    "CNMF_TPU_SPARSE_BETA",
    "CNMF_TPU_ACCEL",
    "CNMF_TPU_INNER_REPEATS",
    "CNMF_TPU_KL_NEWTON",
    "CNMF_TPU_SKETCH",
    "CNMF_TPU_SKETCH_DIM",
    "CNMF_TPU_SKETCH_EXACT_EVERY",
    "CNMF_TPU_PALLAS",
    "CNMF_TPU_BF16_RATIO",
    "CNMF_TPU_STREAM_TRANSPORT",
    "CNMF_TPU_STREAM_THREADS",
    "CNMF_TPU_STREAM_DEPTH",
    "CNMF_TPU_GRID_BLOCKS",
    "CNMF_TPU_GRID_SHAPE",
    "CNMF_TPU_GRID_OVERLAP",
    "CNMF_TPU_OOC",
    "CNMF_TPU_SERVE_BUCKETS",
    "CNMF_TPU_STORE_URI",
    "CNMF_TPU_PLAN",
    "CNMF_TPU_AUTOTUNE",
})

# the registered resolver functions — the ONLY non-planner code allowed
# to read a DISPATCH_KNOBS name. One resolution site per knob; dispatch
# sites call these, never the env accessors directly.
PLAN_ACCESSORS = frozenset({
    "resolve_sparse_beta",       # ops/sparse.py       (encoding)
    "resolve_recipe",            # ops/recipe.py       (solver recipe)
    "resolve_consensus_sketch",  # ops/sketch.py       (consensus lane)
    "resolve_pallas",            # ops/pallas          (kernel)
    "resolve_bf16_ratio",        # ops/nmf.py          (kernel band)
    "stream_threads",            # parallel/streaming.py
    "stream_depth",              # parallel/streaming.py
    "_csr_transport",            # parallel/streaming.py
    "grid_overlap_enabled",      # parallel/grid2d.py
    "grid_blocks",               # parallel/grid2d.py
    "_grid_rc",                  # parallel/grid2d.py
    "ooc_mode",                  # utils/shardstore.py
    "resolve_backend",           # utils/storebackend.py
    "resolve_buckets",           # serving/batcher.py
})

# files that own dispatch-knob resolution outright (relpath suffixes)
PLAN_OWNER_FILES = (
    "runtime/planner.py",
    "utils/autotune.py",
    "utils/envknobs.py",
)

_OFF_WORDS = ("", "0", "off", "false", "no")
_ON_WORDS = ("1", "on", "true", "yes", "force")


# ---------------------------------------------------------------------------
# inputs
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class InputStats:
    """The matrix/ledger facts a plan is a function of. All static shape
    facts — two runs with equal stats (and equal env/autotune state)
    build equal plans (determinism is pinned by tests/test_planner.py)."""

    n: int
    g: int
    beta: float = 1.0
    mode: str = "online"
    init: str = "random"
    algo: str = "mu"
    sparse: bool = False
    density: float | None = None
    ell_width: int | None = None
    k_max: int | None = None
    n_ks: int = 1
    max_replicates: int = 1
    total_workers: int = 1
    has_store: bool = False


@dataclass(frozen=True)
class DeviceInventory:
    """The hardware facts: backend, device kind/count, host count."""

    backend: str = "cpu"
    device_kind: str = "unknown"
    n_devices: int = 1
    n_processes: int = 1
    cpu_count: int = 1

    @classmethod
    def probe(cls) -> "DeviceInventory":
        """Inventory of the live jax runtime (lazy import)."""
        import jax

        devs = jax.devices()
        kind = str(getattr(devs[0], "device_kind", "unknown"))
        return cls(backend=jax.default_backend(),
                   device_kind=kind.replace(" ", "_"),
                   n_devices=len(devs),
                   n_processes=int(jax.process_count()),
                   cpu_count=os.cpu_count() or 1)


# ---------------------------------------------------------------------------
# the plan
# ---------------------------------------------------------------------------

@dataclass
class ExecutionPlan:
    """The resolved dispatch surface for one factorize. ``sources`` maps
    field groups to who decided: ``pin`` | ``autotuned`` | ``heuristic``.
    """

    plan_version: int = PLAN_VERSION
    package_version: str = ""
    fingerprint: str = ""
    beta: float = 1.0
    mode: str = "online"
    # encoding
    use_ell: bool = False
    density: float | None = None
    density_threshold: float | None = None
    ell_width: int | None = None
    # solver recipe
    recipe_algo: str = "mu"
    inner_repeats: int = 1
    kl_newton: bool = False
    sketch_dim: int = 0
    sketch_exact_every: int = 1
    recipe_label: str = "mu"
    # kernel
    use_pallas: bool = False
    bf16_ratio: bool = False
    kernel: str = "vmapped"
    # program shape + layout
    packed: bool = False
    layout: str = "1d"
    mesh_devices: int = 1
    grid_shape: list | None = None
    grid_blocks: int | None = None
    grid_overlap: bool | None = None
    # streaming
    stream_transport: str = "auto"
    stream_threads: int = 1
    stream_depth: int = 3
    # ingest tier + store backend
    ooc_engaged: bool = False
    store_backend: str = "local"
    # serve schedule
    serve_buckets: list = field(default_factory=list)
    # provenance: field -> "pin" | "autotuned" | "heuristic"
    sources: dict = field(default_factory=dict)

    # -- serialization --------------------------------------------------

    def to_dict(self) -> dict:
        return asdict(self)

    def to_json(self, indent: int | None = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "ExecutionPlan":
        if int(d.get("plan_version", 0)) != PLAN_VERSION:
            raise ValueError(
                f"plan_version={d.get('plan_version')!r}: this build "
                f"understands {PLAN_VERSION}")
        known = {f.name for f in fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown plan fields {sorted(unknown)}")
        return cls(**d)

    @classmethod
    def from_json(cls, text: str) -> "ExecutionPlan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str) -> None:
        from ..utils.anndata_lite import atomic_artifact

        with atomic_artifact(path) as tmp:
            with open(tmp, "w") as f:
                f.write(self.to_json() + "\n")

    # -- identity -------------------------------------------------------

    def signature(self) -> str:
        """Stable digest over the dispatch-relevant fields (``sources``
        and the measured-input ``density`` excluded: two runs that
        DISPATCH identically share a signature even when one was pinned
        and the other autotuned its way to the same program)."""
        d = self.to_dict()
        d.pop("sources", None)
        d.pop("density", None)
        blob = json.dumps(d, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def identity_fragment(self) -> str:
        """The math-affecting plan fragment carried into the checkpoint
        identity ``params`` signature: recipe + kernel + encoding. A
        layout or streaming change replays the same trajectory, so it
        does NOT restart; a fragment change must (never splice)."""
        rec = self.solver_recipe()
        return (rec.signature(kernel=self.kernel if self.use_pallas
                              else None)
                + f",enc={'ell' if self.use_ell else 'dense'}")

    def solver_recipe(self):
        """Rebuild the :class:`~cnmf_torch_tpu.ops.recipe.SolverRecipe`
        this plan resolved (the object the sweeps are keyed on)."""
        from ..ops.recipe import SolverRecipe

        return SolverRecipe(
            self.recipe_algo, int(self.inner_repeats),
            bool(self.kl_newton),
            self.sources.get("recipe", "heuristic"),
            sketch_dim=int(self.sketch_dim),
            sketch_exact_every=int(self.sketch_exact_every))

    # -- cost model (ISSUE 19) ------------------------------------------

    def cost_inputs(self) -> dict:
        """The normalized lane + layout inputs the roofline cost model
        instantiates its per-iteration formulas from
        (:mod:`~cnmf_torch_tpu.obs.costmodel` — everything it needs
        beyond the problem shape, which arrives per dispatch). Plain
        data, stable keys: a costmodel built from a replayed plan must
        predict identically."""
        return {
            "beta": float(self.beta),
            "kernel": str(self.kernel),
            "use_ell": bool(self.use_ell),
            "use_pallas": bool(self.use_pallas),
            "bf16_ratio": bool(self.bf16_ratio),
            "packed": bool(self.packed),
            "layout": str(self.layout),
            "ell_width": (int(self.ell_width)
                          if self.ell_width is not None else None),
            "density": (float(self.density)
                        if self.density is not None else None),
            "mesh_devices": int(self.mesh_devices),
            "grid_shape": (list(self.grid_shape)
                           if self.grid_shape else None),
            "grid_blocks": (int(self.grid_blocks)
                            if self.grid_blocks is not None else None),
            "recipe_algo": str(self.recipe_algo),
            "inner_repeats": int(self.inner_repeats),
        }


# ---------------------------------------------------------------------------
# building
# ---------------------------------------------------------------------------

def _env_set(*names: str) -> bool:
    from ..utils.envknobs import env_is_set

    return any(env_is_set(n) for n in names)


def _tuned_points() -> dict:
    """The measured microbench points for this device (empty when the
    tuner never ran, is disabled, or jax is unavailable)."""
    try:
        from ..utils.autotune import cached_plan_points

        return cached_plan_points() or {}
    except Exception:
        return {}


def resolve_encoding(stats: InputStats,
                     tuned: dict | None = None) -> tuple[bool, float | None]:
    """Dense vs ELL for this input — the factorize dispatch site calls
    THIS (not ``resolve_sparse_beta`` directly) so the measured density
    crossover is consumed in exactly one place. Returns
    ``(use_ell, effective_threshold)``; the lane is only defined for
    sparse random-init plain-MU β∈{1,0} inputs (everything else is
    dense, as before)."""
    if not (stats.sparse and stats.beta in (1.0, 0.0)
            and stats.init == "random" and stats.algo == "mu"):
        return False, None
    from ..ops.sparse import SPARSE_DENSITY_THRESHOLD, resolve_sparse_beta

    tuned = _tuned_points() if tuned is None else tuned
    thr = tuned.get("ell_density_crossover")
    use_ell = resolve_sparse_beta(stats.beta, density=stats.density,
                                  width=stats.ell_width, g=stats.g,
                                  threshold=thr)
    eff = thr if (thr is not None
                  and not _env_set("CNMF_TPU_SPARSE_BETA")) \
        else SPARSE_DENSITY_THRESHOLD
    return bool(use_ell), float(eff)


def _auto_packed(stats: InputStats, use_ell: bool) -> bool:
    """The packed-K-sweep regime heuristic (measured on the K=5..13 x 100
    production sweep: packed wins only the compile-dominated many-Ks x
    few-replicates scans; see models/cnmf.py for the derivation)."""
    return (not use_ell and stats.algo == "mu" and stats.init == "random"
            and stats.n_ks >= 4
            and stats.max_replicates * max(1, stats.total_workers) <= 32)


def build_plan(stats: InputStats,
               inv: DeviceInventory | None = None,
               overrides: dict | None = None) -> ExecutionPlan:
    """Resolve the full dispatch surface for one factorize.

    ``overrides`` carries the caller-level facts factorize already
    resolved from its arguments (they are pins, not heuristics):
    ``layout`` / ``mesh_devices`` / ``packed`` (tri-state: None = auto)
    / ``use_ell`` (factorize resolved encoding before staging) /
    ``ooc_engaged`` / ``serve_chunk``.

    Precedence per field: explicit knob (or caller override) >
    autotuned microbench point > static heuristic — recorded per field
    group in ``plan.sources``.
    """
    ov = dict(overrides or {})
    if inv is None:
        inv = DeviceInventory.probe()
    try:
        from ..version import __version__ as pkg_version
    except Exception:
        pkg_version = "unknown"
    try:
        from ..utils.autotune import device_fingerprint

        fp = device_fingerprint()
    except Exception:
        fp = f"{pkg_version}-{inv.backend}-{inv.device_kind}" \
             f"-x{inv.n_devices}"

    tuned = _tuned_points()
    sources: dict = {}

    # -- encoding -------------------------------------------------------
    if "use_ell" in ov:
        use_ell = bool(ov["use_ell"])
        _, thr = resolve_encoding(stats, tuned)
    else:
        use_ell, thr = resolve_encoding(stats, tuned)
    sources["encoding"] = (
        "pin" if _env_set("CNMF_TPU_SPARSE_BETA")
        else ("autotuned" if "ell_density_crossover" in tuned
              and stats.sparse else "heuristic"))

    # -- solver recipe --------------------------------------------------
    from ..ops.recipe import resolve_recipe

    recipe = resolve_recipe(
        stats.beta, stats.mode, algo=stats.algo, ell=use_ell,
        n=stats.n, g=stats.g, k=stats.k_max,
        ell_width=stats.ell_width if use_ell else None)
    if _env_set("CNMF_TPU_ACCEL", "CNMF_TPU_INNER_REPEATS",
                "CNMF_TPU_KL_NEWTON", "CNMF_TPU_SKETCH"):
        sources["recipe"] = "pin"
    else:
        sources["recipe"] = "heuristic"
        if recipe.algo == "amu":
            # the amu rho schedule consumes the measured cost-ratio
            # cache (ISSUE 11) when one exists for this device
            try:
                from ..utils.autotune import cached_rho_scale

                if cached_rho_scale(stats.beta, use_ell) is not None:
                    sources["recipe"] = "autotuned"
            except Exception:
                pass
        elif recipe.algo == "sketch" and "sketch_dim" in tuned:
            sources["recipe"] = "autotuned"

    # -- kernel ---------------------------------------------------------
    from ..ops.nmf import resolve_bf16_ratio
    from ..ops.pallas import kernel_label, resolve_pallas

    use_pallas = bool(use_ell and stats.beta == 1.0
                      and recipe.algo != "sketch" and resolve_pallas())
    bf16 = bool(resolve_bf16_ratio(stats.beta, stats.mode))
    kern = kernel_label(use_ell, use_pallas, bf16)
    sources["kernel"] = (
        "pin" if _env_set("CNMF_TPU_PALLAS", "CNMF_TPU_BF16_RATIO")
        else ("autotuned" if "pallas_wins" in tuned and use_ell
              else "heuristic"))

    # -- program shape --------------------------------------------------
    packed = ov.get("packed")
    if packed is None:
        packed = _auto_packed(stats, use_ell)
        sources["packed"] = "heuristic"
    else:
        packed = bool(packed)
        sources["packed"] = "pin"
    if packed and recipe.algo == "sketch":
        packed = False  # the packed program compiles mu-family only

    # -- layout ---------------------------------------------------------
    layout = str(ov.get("layout", "1d"))
    mesh_devices = int(ov.get("mesh_devices", inv.n_devices))
    grid_shape = grid_blk = grid_ovl = None
    if layout == "grid2d":
        from ..parallel.grid2d import (_grid_rc, grid_blocks,
                                       grid_overlap_enabled)

        r, c = _grid_rc(inv.n_devices, inv.n_processes)
        grid_shape = [int(r), int(c)]
        grid_blk = int(grid_blocks(max(1, stats.n // max(r, 1))))
        grid_ovl = bool(grid_overlap_enabled())
        sources["grid"] = (
            "pin" if _env_set("CNMF_TPU_GRID_BLOCKS",
                              "CNMF_TPU_GRID_SHAPE",
                              "CNMF_TPU_GRID_OVERLAP")
            else ("autotuned" if "grid_blocks" in tuned else "heuristic"))

    # -- streaming ------------------------------------------------------
    from ..parallel.streaming import (_csr_transport, stream_depth,
                                      stream_threads)

    try:
        import jax

        transport = _csr_transport(jax.local_devices())
    except Exception:
        transport = "auto"
    threads = int(stream_threads())
    depth = int(stream_depth())
    sources["streaming"] = (
        "pin" if _env_set("CNMF_TPU_STREAM_TRANSPORT",
                          "CNMF_TPU_STREAM_THREADS",
                          "CNMF_TPU_STREAM_DEPTH")
        else ("autotuned" if "stream_threads" in tuned else "heuristic"))

    # -- ingest tier + store backend ------------------------------------
    ooc = bool(ov.get("ooc_engaged", stats.has_store))
    sources["ooc"] = "pin" if _env_set("CNMF_TPU_OOC") else "heuristic"
    from ..utils.envknobs import env_str

    uri = env_str("CNMF_TPU_STORE_URI", "").strip()
    store = ("http" if uri.startswith(("http://", "https://"))
             else ("file" if uri.startswith("file://") else "local"))
    sources["store"] = "pin" if uri else "heuristic"

    # -- serve schedule -------------------------------------------------
    from ..serving.batcher import resolve_buckets

    buckets = [int(b) for b in resolve_buckets(
        int(ov.get("serve_chunk", 1024)))]
    sources["serve"] = ("pin" if _env_set("CNMF_TPU_SERVE_BUCKETS")
                        else "heuristic")

    return ExecutionPlan(
        package_version=str(pkg_version), fingerprint=fp,
        beta=float(stats.beta), mode=str(stats.mode),
        use_ell=use_ell,
        density=(None if stats.density is None
                 else round(float(stats.density), 6)),
        density_threshold=thr,
        ell_width=(int(stats.ell_width) if use_ell
                   and stats.ell_width is not None else None),
        recipe_algo=recipe.algo, inner_repeats=int(recipe.inner_repeats),
        kl_newton=bool(recipe.kl_newton),
        sketch_dim=int(recipe.sketch_dim),
        sketch_exact_every=int(recipe.sketch_exact_every),
        recipe_label=recipe.label,
        use_pallas=use_pallas, bf16_ratio=bf16, kernel=kern,
        packed=bool(packed), layout=layout, mesh_devices=mesh_devices,
        grid_shape=grid_shape, grid_blocks=grid_blk, grid_overlap=grid_ovl,
        stream_transport=str(transport), stream_threads=threads,
        stream_depth=depth,
        ooc_engaged=ooc, store_backend=store, serve_buckets=buckets,
        sources=sources)


# ---------------------------------------------------------------------------
# replay: plan -> env pins
# ---------------------------------------------------------------------------

def apply_plan(plan: ExecutionPlan) -> dict:
    """Pin the dispatch knobs to this plan's resolved values so every
    scattered consumer reproduces its dispatch bit-identically. Returns
    the applied ``{knob: value}`` map. The autotuner is pinned OFF — a
    replay must not re-measure its way to a different program — and
    ``CNMF_TPU_STORE_URI`` is deliberately NOT pinned (the recorded
    backend kind is provenance; a dumped URI's credentials/host rarely
    survive the machine the plan replays on)."""
    pins: dict[str, str] = {}
    pins["CNMF_TPU_AUTOTUNE"] = "0"
    pins["CNMF_TPU_SPARSE_BETA"] = "1" if plan.use_ell else "0"
    if plan.recipe_algo == "sketch":
        pins["CNMF_TPU_SKETCH"] = "1"
        pins["CNMF_TPU_SKETCH_DIM"] = str(int(plan.sketch_dim))
        pins["CNMF_TPU_SKETCH_EXACT_EVERY"] = str(
            int(plan.sketch_exact_every))
        pins["CNMF_TPU_ACCEL"] = "0"
    else:
        pins["CNMF_TPU_SKETCH"] = "0"
        if plan.recipe_algo == "mu":
            pins["CNMF_TPU_ACCEL"] = "0"
        elif plan.recipe_algo == "dna":
            pins["CNMF_TPU_ACCEL"] = "1"
            pins["CNMF_TPU_KL_NEWTON"] = "1"
        elif plan.recipe_algo == "amu":
            pins["CNMF_TPU_ACCEL"] = "1"
            pins["CNMF_TPU_KL_NEWTON"] = "0"
            pins["CNMF_TPU_INNER_REPEATS"] = str(int(plan.inner_repeats))
        # hals is the caller's algo argument, not a knob product
    pins["CNMF_TPU_PALLAS"] = "1" if plan.use_pallas else "0"
    pins["CNMF_TPU_BF16_RATIO"] = "1" if plan.bf16_ratio else "0"
    if plan.stream_transport not in ("", "auto"):
        pins["CNMF_TPU_STREAM_TRANSPORT"] = str(plan.stream_transport)
    pins["CNMF_TPU_STREAM_THREADS"] = str(int(plan.stream_threads))
    pins["CNMF_TPU_STREAM_DEPTH"] = str(int(plan.stream_depth))
    if plan.grid_shape:
        pins["CNMF_TPU_GRID_SHAPE"] = "%dx%d" % tuple(plan.grid_shape)
    if plan.grid_blocks is not None:
        pins["CNMF_TPU_GRID_BLOCKS"] = str(int(plan.grid_blocks))
    if plan.grid_overlap is not None:
        pins["CNMF_TPU_GRID_OVERLAP"] = "1" if plan.grid_overlap else "0"
    if plan.serve_buckets:
        # resolve_buckets keeps sub-chunk entries and re-adds the chunk
        # itself, so pinning the full recorded schedule round-trips
        pins["CNMF_TPU_SERVE_BUCKETS"] = ",".join(
            str(int(b)) for b in plan.serve_buckets)
    from ..utils.envknobs import pin_knob

    for name, value in pins.items():
        pin_knob(name, value)
    return pins


def load_plan(path: str) -> ExecutionPlan:
    with open(path) as f:
        return ExecutionPlan.from_json(f.read())


def maybe_apply_plan_env() -> ExecutionPlan | None:
    """``CNMF_TPU_PLAN=<file>`` (the env spelling of ``--plan``): load
    and pin before any dispatch resolves. Returns the applied plan, or
    ``None`` when the knob is unset. A missing/invalid plan file is an
    error — silently running a DIFFERENT dispatch than the operator
    pinned is exactly what the planner exists to prevent."""
    from ..utils.envknobs import env_str

    path = env_str(PLAN_ENV, "").strip()
    if not path:
        return None
    plan = load_plan(path)
    apply_plan(plan)
    return plan


# ---------------------------------------------------------------------------
# rendering (report / CLI)
# ---------------------------------------------------------------------------

def render_plan(plan_dict: dict) -> list[str]:
    """Text lines for the report's Plan section (takes the event/JSON
    dict form so ``cnmf-tpu report`` renders historical events from
    builds with more/fewer fields without re-validating)."""
    d = dict(plan_dict)
    src = d.get("sources") or {}

    def tag(group):
        s = src.get(group)
        return f" [{s}]" if s else ""

    lines = []
    lines.append(
        f"  plan v{d.get('plan_version')}  package "
        f"{d.get('package_version')}  device {d.get('fingerprint')}")
    enc = "ell" if d.get("use_ell") else "dense"
    dens = d.get("density")
    thr = d.get("density_threshold")
    lines.append(
        f"  encoding: {enc}"
        + (f" (density {dens}" + (f" vs crossover {thr})"
                                  if thr is not None else ")")
           if dens is not None else "")
        + tag("encoding"))
    lines.append(
        f"  recipe:   {d.get('recipe_label')}  (beta={d.get('beta')}, "
        f"mode={d.get('mode')})" + tag("recipe"))
    lines.append(f"  kernel:   {d.get('kernel')}" + tag("kernel"))
    lines.append(
        f"  program:  {'packed K-sweep' if d.get('packed') else 'per-K'}"
        + tag("packed"))
    lay = f"  layout:   {d.get('layout')} x{d.get('mesh_devices')} device(s)"
    if d.get("grid_shape"):
        lay += (f"  grid {d['grid_shape'][0]}x{d['grid_shape'][-1]}"
                f" blocks={d.get('grid_blocks')}"
                f" overlap={'on' if d.get('grid_overlap') else 'off'}"
                + tag("grid"))
    lines.append(lay)
    lines.append(
        f"  stream:   transport={d.get('stream_transport')} "
        f"threads={d.get('stream_threads')} depth={d.get('stream_depth')}"
        + tag("streaming"))
    lines.append(
        f"  ingest:   {'out-of-core shard store' if d.get('ooc_engaged') else 'resident'}"
        + tag("ooc") + f"  store={d.get('store_backend')}" + tag("store"))
    if d.get("serve_buckets"):
        lines.append(
            "  serve:    buckets="
            + ",".join(str(b) for b in d["serve_buckets"]) + tag("serve"))
    return lines


def plan_from_run_dir(run_dir: str) -> dict | None:
    """The last ``plan`` event recorded in a run directory's telemetry
    (the ``cnmf-tpu plan <run_dir>`` source), or ``None``."""
    from ..utils.telemetry import _find_event_files, read_events

    plan = None
    for path in _find_event_files(run_dir):
        for ev in read_events(path):
            if ev.get("t") == "plan":
                plan = ev.get("plan")
    return plan
