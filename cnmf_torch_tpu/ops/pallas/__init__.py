"""Pallas dispatch guard for the fused sparse-KL kernels (ISSUE 16).

The fused kernels themselves live in ``ops/pallas_kl.py`` (which imports
``jax.experimental.pallas`` at module top); THIS package is the thin
guard the dispatch sites consult so the rest of the codebase never
imports Pallas directly:

  * :func:`resolve_pallas` — the one resolution of the
    ``CNMF_TPU_PALLAS`` knob (``0`` | ``1`` | ``auto``, house style per
    ``CNMF_TPU_ACCEL``): ``auto`` (default since the execution planner,
    ISSUE 17) engages the fused kernels only when the default backend is
    a real TPU, deferring to the measured Pallas-vs-jnp microbench point
    when the autotune cache holds one; ``0`` pins the jnp ELL path — the
    compiled programs are byte-identical to a build without the kernel
    layer (the parity escape hatch); ``1`` forces the fused kernels
    wherever defined (off-TPU they run in interpret mode — correct,
    slow, CI-testable). If Pallas itself cannot be imported the resolver
    degrades to the jnp path with one loud announcement instead of
    failing.
  * :func:`pallas_interpret` — whether ``pallas_call`` must run in
    interpret mode (any non-TPU backend: the kernels are written against
    the TPU lowering; interpret mode is the portable reference).
  * :func:`kernel_label` — the one spelling of the engaged-kernel label
    that telemetry dispatch events, provenance, the checkpoint identity,
    and ``bench.py --tier mfu`` all share (``ell-pallas`` / ``ell-jnp``
    / ``vmapped-bf16`` / ``vmapped``).

The kernels cover the ELL β=1 (KL) statistics only: the IS (β=0) chain
is a hybrid with a dense WH matmul (no one-pass nonzero traversal to
fuse) and the sketch recipe's row-subsampled W update needs a scatter
the transpose index set cannot serve — both keep the jnp path
regardless of the knob, as does every dense lane (the old dense-Pallas
experiment lost under vmap; see ``ops/nmf.py:_update_H``).
"""

from __future__ import annotations

import threading

__all__ = ["PALLAS_ENV", "pallas_available", "pallas_interpret",
           "resolve_pallas", "kernel_label", "pallas_stats_cost"]

PALLAS_ENV = "CNMF_TPU_PALLAS"

_OFF_WORDS = ("", "0", "off", "false", "no")
_ON_WORDS = ("1", "on", "true", "yes", "force")

_pallas_import_ok: bool | None = None
_announced = False
_state_lock = threading.Lock()


def pallas_available() -> bool:
    """Whether ``jax.experimental.pallas`` imports at all (cached). The
    repo supports jax>=0.4.36, where it does — this guards exotic
    builds stripped of the experimental tree."""
    global _pallas_import_ok
    if _pallas_import_ok is None:
        try:
            from jax.experimental import pallas  # noqa: F401

            ok = True
        except Exception:
            ok = False
        with _state_lock:
            if _pallas_import_ok is None:
                _pallas_import_ok = ok
    return _pallas_import_ok


def pallas_interpret() -> bool:
    """True when ``pallas_call`` must run in interpret mode: any backend
    that is not a real TPU. Interpret mode executes the kernel body as
    plain jax ops — the CPU tier-1 suite tests the whole dispatch
    surface with it."""
    import jax

    return jax.default_backend() != "tpu"


def _announce(msg: str) -> None:
    global _announced
    with _state_lock:
        first = not _announced
        _announced = True
    if first:
        print(msg)


def resolve_pallas(override=None) -> bool:
    """Resolve the ``CNMF_TPU_PALLAS`` knob to an engage/don't bool.

    An explicit ``override`` wins (same precedence contract as
    ``resolve_bf16_ratio``). The word semantics mirror
    ``CNMF_TPU_ACCEL``: off-words pin the jnp path, on-words force the
    fused kernels (interpret mode off-TPU), ``auto`` engages only on a
    TPU backend. Unknown words raise at resolution time with a one-line
    message naming the knob. The first engagement per process is
    announced on stdout — the kernels change accumulation order vs the
    jnp chain (f32-tolerance parity, not bit parity), and
    parity-sensitive users should find the opt-out without reading
    this docstring."""
    if override is not None:
        want = bool(override)
    else:
        from ...utils.envknobs import env_str

        raw = env_str(PALLAS_ENV, "auto").strip().lower()
        if raw in _OFF_WORDS:
            return False
        if raw in _ON_WORDS:
            want = True
        elif raw == "auto":
            want = not pallas_interpret()
            if want:
                # the planner's measured Pallas-vs-jnp crossover point
                # (utils/autotune.py, cached per device fingerprint):
                # auto defers to the measurement when one exists — a TPU
                # whose jnp ELL chain beats the fused kernels at the
                # probe shape keeps the jnp path. Best-effort: no cache
                # (or autotune disabled) keeps the engage-on-TPU default.
                try:
                    from ...utils.autotune import cached_plan_point

                    tuned = cached_plan_point("pallas_wins")
                    if tuned is not None:
                        want = bool(tuned)
                except Exception:
                    pass
        else:
            raise ValueError(
                f"{PALLAS_ENV}={raw!r}: expected 0, 1, or auto")
    if not want:
        return False
    if not pallas_available():
        _announce(
            "cnmf-tpu: CNMF_TPU_PALLAS requested but jax.experimental."
            "pallas is unavailable in this jax build - degrading to the "
            "jnp ELL path.")
        return False
    _announce(
        "cnmf-tpu: fused Pallas KL kernels active for ELL beta=1 solves"
        + (" (interpret mode: non-TPU backend - parity-testable, "
           "not a perf configuration)." if pallas_interpret()
           else " (set CNMF_TPU_PALLAS=0 for the jnp-parity path)."))
    return True


def kernel_label(use_ell: bool, use_pallas: bool = False,
                 bf16_ratio: bool = False) -> str:
    """The engaged inner-loop kernel label shared by telemetry dispatch
    events, provenance, checkpoint identity, and ``bench.py --tier
    mfu``: ``ell-pallas`` (fused kernels), ``ell-jnp`` (gather-based jnp
    ELL path), ``vmapped-bf16`` / ``vmapped`` (dense chains)."""
    if use_ell:
        return "ell-pallas" if use_pallas else "ell-jnp"
    return "vmapped-bf16" if bf16_ratio else "vmapped"


def pallas_stats_cost(n: int, g: int, k: int, width: int,
                      t_width=None, beta: float = 1.0) -> dict:
    """Analytic flop/byte cost of one fused ELL KL iteration on the
    Pallas lane. The fused kernels do the same useful arithmetic as the
    jnp slab kernels (that is the parity contract pallas_smoke pins),
    so the flop count is shared with :func:`..sparse.ell_stats_cost`;
    fusion removes the intermediate slab materialisations, so the byte
    floor is the operand + output traffic only. Interpret-mode runs are
    NOT a perf configuration — the cost model marks them perf-exempt
    (see ``perf_exempt``), never compared against a roofline."""
    from ..sparse import ell_stats_cost

    c = ell_stats_cost(n, g, k, width, t_width=t_width, beta=beta)
    f = 4.0
    n, g, k, w = int(n), int(g), int(k), int(width)
    if t_width is not None:
        wt = int(t_width)
    else:
        wt = -(-(w * n) // max(g, 1))
        wt = max(8, -(-wt // 8) * 8)
    nw, gwt = n * w, g * wt
    # fused floor: vals + cols + W + H in, stats out, once per side
    c["bytes"] = float(
        (nw * f + nw * 4 + k * g * f + n * k * f + 2 * n * k * f)
        + (gwt * f + gwt * 4 + n * k * f + k * g * f + 2 * k * g * f))
    c["lane"] = "ell-pallas"
    c["perf_exempt"] = bool(pallas_interpret())
    return c
