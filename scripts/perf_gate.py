"""Tier-1 continuous perf-regression gate (scripts/verify_tier1.sh, ISSUE 19).

Machinery self-test first, fingerprint-keyed baseline gate second:

  * self-test — measures one pinned dense MU lane twice (min-of-N
    walls, N = CNMF_TPU_PERF_GATE_N), builds two cnmf-bench snapshots
    (obs/regress.py schema), and asserts the noise-aware diff is GREEN
    on the honest re-measurement and RED after injecting a synthetic
    2x slowdown into the candidate's wall samples — both verdicts
    end-to-end through the ``cnmf-tpu benchdiff`` CLI (exit 0 / 1);
  * baseline gate — when ``scripts/perf_baselines/<fingerprint>.json``
    exists for THIS device fingerprint, today's measurement must stay
    within the relative band of it (CNMF_TPU_PERF_GATE_BAND, default
    ±60%: honest walls on a 2-core oversubscribed container wobble,
    min-of-N plus the band absorb it). A baseline recorded on
    different hardware is exempt by construction — the fingerprint key
    means it can never red a run it cannot speak for.

``--write-baseline`` records the current measurement as the new
baseline for this fingerprint.
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# pinned gate lane: small enough that min-of-N stays honest on the
# 2-core tier-1 container, big enough that the wall is ms-scale (not
# dominated by dispatch overhead)
GATE_SHAPE = (96, 64, 7)  # (n, g, k)
GATE_ITERS = 150


def _fail(msg: str) -> int:
    print("perf gate: " + msg, file=sys.stderr)
    return 1


def _measure(n_samples: int) -> dict:
    """Min-of-N wall for GATE_ITERS dense beta=2 MU iterations at the
    pinned shape (compile excluded; tol=0 pins the trip count)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cnmf_torch_tpu.ops.nmf import dense_update_cost, nmf_fit_batch

    n, g, k = GATE_SHAPE
    rng = np.random.default_rng(0)
    X = jnp.asarray(rng.random((n, g)), jnp.float32)
    H0 = jnp.asarray(rng.random((n, k)), jnp.float32)
    W0 = jnp.asarray(rng.random((k, g)), jnp.float32)
    fit = jax.jit(lambda X, H, W: nmf_fit_batch(
        X, H, W, beta=2.0, tol=0.0, max_iter=GATE_ITERS))
    jax.block_until_ready(fit(X, H0, W0))  # compile outside the clock
    samples = []
    for _ in range(max(1, n_samples)):
        t0 = time.perf_counter()
        jax.block_until_ready(fit(X, H0, W0))
        samples.append(time.perf_counter() - t0)
    cost = dense_update_cost(n, g, k, 2.0)
    wall = min(samples)
    return {"samples": samples, "wall_s": wall,
            "gflops": cost["flops"] * GATE_ITERS / wall / 1e9}


def _snapshot(meas: dict, fingerprint: str, created: float,
              label: str) -> dict:
    from cnmf_torch_tpu.obs.regress import build_snapshot, validate_bench

    raw = {"update_wall_s": meas["wall_s"],
           "achieved_gflops": meas["gflops"],
           "n": GATE_SHAPE[0], "g": GATE_SHAPE[1], "k": GATE_SHAPE[2],
           "iters": GATE_ITERS}
    snap = build_snapshot({"gate": raw}, fingerprint=fingerprint,
                          created=created, label=label)
    # the full sample list rides along so diff's min-of-N estimator has
    # the noise floor, not one draw
    snap["tiers"]["gate"]["metrics"]["update_wall_s"]["samples"] = \
        [float(s) for s in meas["samples"]]
    validate_bench(snap)
    return snap


def _benchdiff_cli(a: str, b: str) -> tuple[int, str]:
    p = subprocess.run(
        [sys.executable, "-m", "cnmf_torch_tpu", "benchdiff", a, b],
        env=dict(os.environ), capture_output=True, text=True, timeout=120)
    return p.returncode, (p.stdout or "") + (p.stderr or "")


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--write-baseline", action="store_true",
                        help="record the current measurement as the "
                             "baseline for this device fingerprint")
    args = parser.parse_args()

    from cnmf_torch_tpu.obs.regress import (diff_snapshots, gate_n,
                                            load_snapshot, render_diff,
                                            save_snapshot)
    from cnmf_torch_tpu.utils.autotune import device_fingerprint

    fp = device_fingerprint()
    n = gate_n()
    workdir = tempfile.mkdtemp(prefix="perf_gate_")
    try:
        # -- self-test: green on an honest re-measurement ------------------
        snap_a = _snapshot(_measure(n), fp, time.time(), "gate-base")
        snap_b = _snapshot(_measure(n), fp, time.time(), "gate-new")
        path_a = save_snapshot(snap_a, os.path.join(workdir, "a.json"))
        path_b = save_snapshot(snap_b, os.path.join(workdir, "b.json"))
        rc, out = _benchdiff_cli(path_a, path_b)
        if rc != 0 or "=> OK" not in out:
            return _fail(f"self-test GREEN leg failed (exit {rc}):\n{out}")

        # -- self-test: red on an injected 2x lane slowdown ----------------
        snap_red = copy.deepcopy(snap_b)
        m = snap_red["tiers"]["gate"]["metrics"]["update_wall_s"]
        m["value"] = 2.0 * float(m["value"])
        m["samples"] = [2.0 * float(s) for s in m["samples"]]
        path_red = save_snapshot(snap_red, os.path.join(workdir, "red.json"))
        rc_red, out_red = _benchdiff_cli(path_a, path_red)
        if rc_red != 1 or "regressed" not in out_red \
                or "=> RED" not in out_red:
            return _fail(f"self-test RED leg failed to regress "
                         f"(exit {rc_red}):\n{out_red}")

        # -- baseline gate (fingerprint-keyed, optional) -------------------
        base_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "perf_baselines")
        safe_fp = "".join(c if c.isalnum() or c in "._-" else "_"
                          for c in fp)
        base_path = os.path.join(base_dir, safe_fp + ".json")
        baseline_note = "no baseline recorded for this fingerprint"
        if args.write_baseline:
            save_snapshot(snap_b, base_path)
            baseline_note = f"baseline written to {base_path}"
        elif os.path.isfile(base_path):
            diff = diff_snapshots(load_snapshot(base_path), snap_b)
            print(render_diff(diff))
            if not diff["ok"]:
                return _fail(f"regression vs recorded baseline "
                             f"{base_path}")
            baseline_note = (f"within band of baseline {base_path} "
                             f"({diff['improvements']} improvement(s))")

        wall_ms = 1e3 * min(snap_b["tiers"]["gate"]["raw"]["update_wall_s"],
                            snap_a["tiers"]["gate"]["raw"]["update_wall_s"])
        print(f"perf gate: self-test green on re-measurement and red on "
              f"injected 2x slowdown (benchdiff exits 0/1), min-of-{n} "
              f"gate wall {wall_ms:.1f} ms at {GATE_SHAPE}, fingerprint "
              f"{fp}; {baseline_note}")
        return 0
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
