"""Column moments, TPM normalization, and variance scaling — JAX kernels.

These replace the reference's native-dependency statistics surface:
``StandardScaler(with_mean=False).fit`` column moments
(``/root/reference/src/cnmf/cnmf.py:128-131``), ``sc.pp.normalize_total``
TPM scaling (``cnmf.py:241-247``), and ``sc.pp.scale(zero_center=False)`` /
dense ``X /= X.std(ddof=1)`` unit-variance gene scaling (``cnmf.py:674-679``).

Sparse matrices are never densified for moment computation — and their
moments deliberately stay on HOST in exact float64 (the fused
``column_moments_staged`` engine): per-gene moments are O(nnz) bookkeeping
where ``np.bincount`` over CSR buffers beats shipping the matrix across the
host->device link, blocked so memory stays bounded for atlas-scale
(1M-cell) inputs. Dense inputs reduce on device in fp32 blocks with f64
cross-block accumulation.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

__all__ = ["cell_scale_factors", "column_mean_var", "column_moments_staged",
           "normalize_total", "scale_columns", "row_sums",
    "scale_hvg_columns_device",
]

# Row-block size for streaming sparse buffers host->device. Large enough to
# amortize transfer, small enough to bound device memory at atlas scale.
_BLOCK_ROWS = 262_144


@jax.jit
def _dense_block_sum(block):
    return block.sum(axis=0)


@jax.jit
def _dense_block_centered_sq(block, mean):
    d = block - mean[None, :]
    return (d * d).sum(axis=0)


def _iter_row_blocks(X, block_rows):
    for start in range(0, X.shape[0], block_rows):
        yield X[start : min(start + block_rows, X.shape[0])]


def column_mean_var(X, ddof: int = 0, block_rows: int = _BLOCK_ROWS):
    """Per-column mean and variance of a (cells x genes) matrix.

    Matches ``get_mean_var`` (``cnmf.py:128-131``): population moments
    (``ddof=0``) as produced by ``StandardScaler(with_mean=False)``.
    ``ddof=1`` gives the sample variance used by gene scaling.

    Sparse inputs compute entirely host-side in exact f64 (the fused
    ``column_moments_staged`` engine — see the module docstring). Dense
    inputs use a two-pass device reduction (mean, then centered squares):
    the naive E[x^2] - E[x]^2 form cancels catastrophically in fp32 at TPM
    scale (column means of 1e4 turn a true variance of 100 into 0-112);
    cross-block accumulation is float64 on host, per-block reductions fp32
    on device.
    """
    n, g = X.shape
    if sp.issparse(X):
        # sparse inputs route through the host-f64 fused engine: per-gene
        # moments are O(nnz) bookkeeping where np.bincount beats shipping
        # CSR blocks over the host->device link and back (the same call
        # that took prepare's moment pass from 24 s to ~1 s; swapping the
        # per-block device round trips here saved ~6 s of the islets
        # preprocess)
        (mean, var), _ = column_moments_staged(X, block_rows=block_rows)
        if ddof:
            # unconditional, like the dense path below: the Bessel factor
            # is computed in float64 so n <= ddof yields inf/nan with a
            # numpy runtime warning rather than a ZeroDivisionError (n and
            # ddof are Python ints; int/int would raise at n == ddof)
            var = var * (np.float64(n) / (n - ddof))
        return mean, var
    s1 = np.zeros((g,), dtype=np.float64)
    Xd = np.asarray(X)
    for block in _iter_row_blocks(Xd, block_rows):
        s1 += np.asarray(_dense_block_sum(jnp.asarray(block, dtype=jnp.float32)),
                         dtype=np.float64)
    mean = s1 / n
    mean_d = jnp.asarray(mean, dtype=jnp.float32)
    ssq = np.zeros((g,), dtype=np.float64)
    for block in _iter_row_blocks(Xd, block_rows):
        ssq += np.asarray(
            _dense_block_centered_sq(jnp.asarray(block, dtype=jnp.float32), mean_d),
            dtype=np.float64)
    var = np.maximum(ssq / n, 0.0)
    if ddof:
        var = var * (np.float64(n) / (n - ddof))
    return mean, var


def column_moments_staged(X, row_scale=None, block_rows: int = _BLOCK_ROWS):
    """Population (ddof=0) column moments of ``X`` — and, with ``row_scale``,
    of the row-scaled matrix ``diag(row_scale) @ X`` — in one fused pass.

    This is prepare's moment engine (``cnmf.py:570-580, 624-698``): it needs
    moments of the TPM matrix (tpm_stats artifact + Fano HVG selection) AND
    of the raw counts (gene unit-variance scaling). Both derive from the
    same CSR buffers, so one pass computes both.

    Deliberately HOST-side, exact float64: per-gene moments are O(nnz)
    bookkeeping, not FLOP-heavy compute — ``np.bincount`` over the CSR
    column indices beats shipping the matrix across the host->device link
    (the round-3 profile: 24 s of a 26 s prepare was moment-pass transfers),
    and exact f64 matches the reference's own numerics
    (``StandardScaler``/numpy, ``cnmf.py:128-131, 570-580``) better than
    any f32 device reduction. The FLOP-heavy stages (factorize, consensus)
    are where the device earns its keep. Blocked accumulation bounds memory
    at atlas scale (``block_rows`` rows of weights at a time).

    Returns ``((raw_mean, raw_var), (scaled_mean, scaled_var))``; the scaled
    pair is ``None`` when ``row_scale`` is None. Variances are population
    (ddof=0); sample variance is ``var * n / (n - ddof)``.
    """
    n, g = X.shape
    want_scaled = row_scale is not None
    scale = np.asarray(row_scale, dtype=np.float64) if want_scaled else None

    s1_raw = np.zeros((g,), dtype=np.float64)
    s1_sc = np.zeros((g,), dtype=np.float64)
    if sp.issparse(X):
        X = X.tocsr()
        nnz_per_col = np.zeros((g,), dtype=np.float64)

        def block_views(block, start):
            """f64 data and (optionally) its row-scaled view — derived per
            block in BOTH passes rather than cached, so peak memory stays
            O(block_rows of nnz), not O(nnz), at atlas scale."""
            data = np.asarray(block.data, dtype=np.float64)
            if not want_scaled:
                return data, None
            per_nnz = np.repeat(scale[start:start + block.shape[0]],
                                np.diff(block.indptr))
            return data, data * per_nnz

        for i, block in enumerate(_iter_row_blocks(X, block_rows)):
            if block.nnz == 0:
                continue
            data, sc = block_views(block, i * block_rows)
            s1_raw += np.bincount(block.indices, weights=data, minlength=g)
            nnz_per_col += np.bincount(block.indices, minlength=g)
            if want_scaled:
                s1_sc += np.bincount(block.indices, weights=sc, minlength=g)
        mean_raw = s1_raw / n
        mean_sc = s1_sc / n
        ssq_raw = np.zeros((g,), dtype=np.float64)
        ssq_sc = np.zeros((g,), dtype=np.float64)
        for i, block in enumerate(_iter_row_blocks(X, block_rows)):
            if block.nnz == 0:
                continue
            data, sc = block_views(block, i * block_rows)
            idx = block.indices
            d = data - mean_raw[idx]
            ssq_raw += np.bincount(idx, weights=d * d, minlength=g)
            if want_scaled:
                ds = sc - mean_sc[idx]
                ssq_sc += np.bincount(idx, weights=ds * ds, minlength=g)
        # implicit zeros each contribute mean^2 to the centered sums
        ssq_raw += (n - nnz_per_col) * mean_raw ** 2
        if want_scaled:
            ssq_sc += (n - nnz_per_col) * mean_sc ** 2
    else:
        Xd = np.asarray(X)
        for i, block in enumerate(_iter_row_blocks(Xd, block_rows)):
            start = i * block_rows
            b = np.asarray(block, dtype=np.float64)
            s1_raw += b.sum(axis=0)
            if want_scaled:
                s1_sc += (b * scale[start:start + b.shape[0], None]).sum(axis=0)
        mean_raw = s1_raw / n
        mean_sc = s1_sc / n
        ssq_raw = np.zeros((g,), dtype=np.float64)
        ssq_sc = np.zeros((g,), dtype=np.float64)
        for i, block in enumerate(_iter_row_blocks(Xd, block_rows)):
            start = i * block_rows
            b = np.asarray(block, dtype=np.float64)
            d = b - mean_raw[None, :]
            ssq_raw += (d * d).sum(axis=0)
            if want_scaled:
                ds = (b * scale[start:start + b.shape[0], None]
                      - mean_sc[None, :])
                ssq_sc += (ds * ds).sum(axis=0)

    var_raw = np.maximum(ssq_raw / n, 0.0)
    raw = (mean_raw, var_raw)
    if not want_scaled:
        return raw, None
    return raw, (mean_sc, np.maximum(ssq_sc / n, 0.0))


def row_sums(X, block_rows: int = _BLOCK_ROWS) -> np.ndarray:
    """Per-row totals (counts per cell)."""
    n = X.shape[0]
    out = np.empty((n,), dtype=np.float64)
    if sp.issparse(X):
        X = X.tocsr()
        # reduceat over indptr is a cheap O(nnz) host pass; row totals are a
        # bookkeeping quantity, not a compute hot spot.
        out[:] = np.add.reduceat(
            np.append(X.data.astype(np.float64), 0.0), X.indptr[:-1]
        ) * (np.diff(X.indptr) > 0)
    else:
        for i, block in enumerate(_iter_row_blocks(np.asarray(X), block_rows)):
            start = i * block_rows
            out[start : start + block.shape[0]] = np.asarray(
                jnp.asarray(block, dtype=jnp.float32).sum(axis=1), dtype=np.float64
            )
    return out


def cell_scale_factors(totals, target_sum: float) -> np.ndarray:
    """Per-cell multipliers that bring each total to ``target_sum``;
    zero-total cells get factor 1 (left at zero — ``sc.pp.normalize_total``
    semantics, ``cnmf.py:241-247``). The ONE definition shared by
    :func:`normalize_total` and prepare's fused moment pass, so the TPM
    artifact and the TPM moments can never drift apart."""
    totals = np.asarray(totals, dtype=np.float64)
    return np.where(totals > 0,
                    target_sum / np.where(totals > 0, totals, 1.0), 1.0)


def normalize_total(adata, target_sum: float = 1e6, inplace: bool = False,
                    totals=None):
    """Scale each cell to ``target_sum`` total counts.

    Equivalent of ``compute_tpm``'s ``sc.pp.normalize_total(tpm, 1e6)``
    (``cnmf.py:241-247``). Cells with zero total are left at zero.
    Returns a new ``AnnDataLite`` unless ``inplace``. ``totals``: optional
    precomputed :func:`row_sums` (skips a pass over the matrix).
    """
    from ..utils.anndata_lite import AnnDataLite

    if totals is None:
        totals = row_sums(adata.X)
    scale = cell_scale_factors(totals, target_sum)
    if sp.issparse(adata.X):
        Xcsr = adata.X.tocsr()
        per_nnz = np.repeat(scale, np.diff(Xcsr.indptr))
        X = sp.csr_matrix(
            (Xcsr.data.astype(np.float32) * per_nnz.astype(np.float32),
             Xcsr.indices, Xcsr.indptr),
            shape=Xcsr.shape,
        )
    else:
        X = np.asarray(adata.X, dtype=np.float32) * scale[:, None].astype(np.float32)
    if inplace:
        adata.X = X
        return adata
    return AnnDataLite(X, adata.obs.copy(), adata.var.copy())


def scale_columns(X, ddof: int = 1, zero_std_to_one: bool = True,
                  precomputed_var=None, out_dtype=None):
    """Scale columns to unit variance WITHOUT centering.

    ``zero_std_to_one=True`` mirrors ``sc.pp.scale(zero_center=False)``
    (sparse path, ``cnmf.py:675``) which maps zero-variance genes to an
    unchanged column; ``False`` mirrors the reference's dense path
    (``cnmf.py:679``) where division by a zero std produces NaN (the
    reference only warns). Returns (scaled matrix, std vector).

    ``precomputed_var``: per-column variance ALREADY at the requested ddof
    (prepare threads it from its one staged moment pass; the scaling itself
    is then a single O(nnz) host op).

    ``out_dtype`` (ISSUE 10 satellite): land the scaled values at this
    dtype while every quotient is still computed in float64 — the result
    is the ``out_dtype`` rounding of the exact f64 division, identical to
    casting the old f64 output, but the full-size f64 matrix never exists
    (the division streams through bounded blocks into the preallocated
    output). ``None`` keeps the legacy f64 result.
    """
    if precomputed_var is not None:
        var = np.asarray(precomputed_var, dtype=np.float64)
    else:
        # host-f64 fused engine: exact, and no host->device round trips for
        # what is O(nnz) bookkeeping (see column_moments_staged)
        (_, var), _ = column_moments_staged(X)
        n = X.shape[0]
        if ddof and n > ddof:
            var = var * (n / (n - ddof))
    std = np.sqrt(var)
    div = std.copy()
    if zero_std_to_one:
        div[div == 0] = 1.0
    if sp.issparse(X):
        Xcsr = X.tocsr()
        with np.errstate(divide="ignore", invalid="ignore"):
            if out_dtype is None:
                data = Xcsr.data / div[Xcsr.indices]
            else:
                # blocked f64 divide cast into the preallocated output:
                # the transient is one block, not an nnz-sized f64 copy
                data = np.empty(Xcsr.data.shape, dtype=out_dtype)
                step = 1 << 24
                for lo in range(0, data.size, step):
                    hi = min(lo + step, data.size)
                    np.divide(Xcsr.data[lo:hi].astype(np.float64,
                                                      copy=False),
                              div[Xcsr.indices[lo:hi]], out=data[lo:hi],
                              casting="unsafe")
        out = sp.csr_matrix((data, Xcsr.indices.copy(), Xcsr.indptr.copy()), shape=Xcsr.shape)
    else:
        Xd = np.asarray(X)
        with np.errstate(divide="ignore", invalid="ignore"):
            if out_dtype is None:
                out = Xd / div[None, :]
            else:
                out = np.empty(Xd.shape, dtype=out_dtype)
                step = max(1, (1 << 27) // max(Xd.shape[1] * 8, 1))
                for lo in range(0, Xd.shape[0], step):
                    hi = min(lo + step, Xd.shape[0])
                    np.divide(Xd[lo:hi].astype(np.float64, copy=False),
                              div[None, :], out=out[lo:hi],
                              casting="unsafe")
    return out, std


def scale_hvg_columns_device(X_resident, hvg_idx, div):
    """Slice HVG columns out of a DEVICE-resident dense matrix and divide
    by a host-computed per-column scale — all on device. The consensus
    final usage refit needs the std-scaled HVG TPM (``cnmf.py:1135-1149``);
    scaling on host and re-uploading the dense result cost ~2 s per
    consensus call on a tunneled TPU, while this ships only the (g_hvg,)
    scale vector. ``div`` follows scale_columns' conventions (zero stds
    already mapped to 1 for the sparse-input branch; left at 0 — NaN/inf
    on divide — for the dense branch, mirroring the reference's dense
    path which only warns)."""
    idx_h = np.asarray(hvg_idx)
    if idx_h.size and idx_h.min() < 0:
        # get_indexer marks missing names as -1; jnp.take would clamp that
        # to column 0 and silently scale the wrong gene, whereas the host
        # fallback (tpm[:, hvgs]) raises KeyError — fail as loudly here
        raise KeyError(
            f"{int((idx_h < 0).sum())} HVG name(s) missing from tpm.var")
    idx = jnp.asarray(idx_h, jnp.int32)
    d = jnp.asarray(np.asarray(div), jnp.float32)
    return jnp.take(X_resident, idx, axis=1) / d[None, :]
