"""Sparsity-aware beta != 2 path (ISSUE 1): dual fixed-width ELL encoding,
nonzero-only MU statistics, dispatch heuristics, and the sharded staging.

Parity bars mirror the repo's existing tiers: the encoding round-trips
EXACTLY; single MU steps match the dense kernels to f32 tolerance at
matched precision (the statistics differ only in summation order); sweep-
level objectives stay within the same per-seed bounds the bf16 parity
test pins (KL 2%, IS 5%)."""

import os

import numpy as np
import pytest
import scipy.sparse as sp

import jax
import jax.numpy as jnp

from cnmf_torch_tpu.ops.nmf import (_update_H, _update_W, beta_divergence,
                                    fit_h, run_nmf)
from cnmf_torch_tpu.ops.sparse import (EllMatrix, csr_to_ell, ell_chunk_rows,
                                       ell_device_put, ell_row_width,
                                       ell_to_dense, ell_w_table,
                                       resolve_sparse_beta)


def _sparse_counts(n, g, density, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    return sp.random(n, g, density=density, format="csr",
                     random_state=int(rng.integers(1 << 31)),
                     data_rvs=lambda s: rng.gamma(2.0, 1.0, s).astype(dtype)
                     ).astype(dtype)


def _lowrank_sparse(n, g, k, density, seed=0):
    """Structured counts: Poisson draws from a low-rank GEP model at a
    depth giving roughly the requested density — the realistic fixture
    for solver-level comparisons (WH stays bounded away from zero on the
    support)."""
    rng = np.random.default_rng(seed)
    usage = rng.dirichlet(np.ones(k) * 0.3, size=n)
    spectra = rng.gamma(0.3, 1.0, size=(k, g)) * 40.0 / g
    lam = usage @ spectra
    # scale so the expected zero fraction lands near 1 - density
    scale = -np.log(max(1.0 - density, 1e-3)) / max(lam.mean(), 1e-9)
    X = rng.poisson(lam * scale).astype(np.float32)
    X[X.sum(axis=1) == 0, 0] = 1.0
    return sp.csr_matrix(X)


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("density", [0.02, 0.1, 0.3])
def test_ell_round_trip_exact(density):
    X = _sparse_counts(57, 43, density, seed=3)
    ell = csr_to_ell(X)
    np.testing.assert_array_equal(ell_to_dense(ell), X.toarray())
    # explicit width padding round-trips too
    ell_w = csr_to_ell(X, width=ell.width + 16)
    np.testing.assert_array_equal(ell_to_dense(ell_w), X.toarray())
    assert ell_w.width == ell.width + 16
    # transpose index set maps every stored nonzero back to its value
    flat = np.concatenate([np.asarray(ell.vals).ravel(), [0.0]])
    vt = flat[np.asarray(ell.perm_t)]
    dense_t = np.zeros((X.shape[1], X.shape[0]), np.float32)
    np.add.at(dense_t,
              (np.repeat(np.arange(X.shape[1]), ell.t_width),
               np.asarray(ell.rows_t).ravel()), vt.ravel())
    np.testing.assert_array_equal(dense_t, X.toarray().T)


def test_ell_width_validation():
    X = _sparse_counts(30, 20, 0.3, seed=1)
    with pytest.raises(ValueError, match="max row nnz"):
        csr_to_ell(X, width=1)
    assert ell_row_width(X) % 8 == 0
    # dense input and explicit-zero elimination
    Xd = X.toarray()
    Xd[0, :] = 0.0
    np.testing.assert_array_equal(ell_to_dense(csr_to_ell(Xd)), Xd)


def test_ell_chunk_rows_round_trip():
    X = _sparse_counts(70, 40, 0.1, seed=5)
    chunked, pad = ell_chunk_rows(X, 32)
    assert chunked.vals.shape[0] == 3 and pad == 26
    parts = [ell_to_dense(EllMatrix(chunked.vals[i], chunked.cols[i],
                                    chunked.g))
             for i in range(chunked.vals.shape[0])]
    full = np.concatenate(parts)
    np.testing.assert_array_equal(full[:70], X.toarray())
    assert not full[70:].any()
    # per-chunk transpose sets index the chunk's own flat buffer
    for i in range(chunked.vals.shape[0]):
        flat = np.concatenate(
            [np.asarray(chunked.vals[i]).ravel(), [0.0]])
        vt = flat[np.asarray(chunked.perm_t[i])]
        dense_t = np.zeros((40, 32), np.float32)
        np.add.at(dense_t,
                  (np.repeat(np.arange(40), chunked.t_width),
                   np.asarray(chunked.rows_t[i]).ravel()), vt.ravel())
        np.testing.assert_array_equal(dense_t.T, parts[i])


def test_resolve_sparse_beta_heuristics(monkeypatch):
    monkeypatch.delenv("CNMF_TPU_SPARSE_BETA", raising=False)
    assert resolve_sparse_beta(1.0, density=0.05, width=100, g=2000)
    assert resolve_sparse_beta(0.0, density=0.05, width=100, g=2000)
    assert not resolve_sparse_beta(2.0, density=0.05)  # beta=2 never
    assert not resolve_sparse_beta(1.0, density=0.5)   # too dense
    assert not resolve_sparse_beta(1.0, density=None)  # unknown density
    # ragged-row guard: one dense-ish row pads every row's width
    assert not resolve_sparse_beta(1.0, density=0.05, width=500, g=2000)
    # env overrides
    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "0")
    assert not resolve_sparse_beta(1.0, density=0.01, width=8, g=2000)
    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "1")
    assert resolve_sparse_beta(1.0, density=0.99)
    assert not resolve_sparse_beta(2.0, density=0.01)  # beta=2 still never
    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "0.3")
    assert resolve_sparse_beta(1.0, density=0.25, width=100, g=2000)
    assert not resolve_sparse_beta(1.0, density=0.35, width=100, g=2000)
    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "banana")
    with pytest.raises(ValueError, match="CNMF_TPU_SPARSE_BETA"):
        resolve_sparse_beta(1.0, density=0.05)
    # explicit override beats the env
    monkeypatch.setenv("CNMF_TPU_SPARSE_BETA", "0")
    assert resolve_sparse_beta(1.0, override=True)


# ---------------------------------------------------------------------------
# single-step parity (exact to f32 tolerance at matched precision)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("density", [0.03, 0.1, 0.25])
@pytest.mark.parametrize("beta", [1.0, 0.0])
def test_mu_step_matches_dense_f32(density, beta):
    n, g, k = 90, 70, 4
    X = _sparse_counts(n, g, density, seed=7)
    rng = np.random.default_rng(11)
    H = jnp.asarray(rng.random((n, k), np.float32) + 0.1)
    W = jnp.asarray(rng.random((k, g), np.float32) + 0.1)
    Xd = jnp.asarray(X.toarray())
    # two widths: natural and over-padded (padding must be exactly benign)
    for width in (None, ell_row_width(X) + 24):
        E = ell_device_put(csr_to_ell(X, width=width))
        H1 = _update_H(Xd, H, W, beta, 0.0, 0.0)
        H2 = _update_H(E, H, W, beta, 0.0, 0.0)
        np.testing.assert_allclose(np.asarray(H2), np.asarray(H1),
                                   rtol=3e-5, atol=1e-6)
        # pre-gathered slab table path == inline-gather path
        H3 = _update_H(E, H, W, beta, 0.0, 0.0,
                       w_table=ell_w_table(W, E.cols))
        np.testing.assert_allclose(np.asarray(H3), np.asarray(H2),
                                   rtol=1e-6, atol=0)
        W1 = _update_W(Xd, H1, W, beta, 0.0, 0.0)
        W2 = _update_W(E, H1, W, beta, 0.0, 0.0)
        np.testing.assert_allclose(np.asarray(W2), np.asarray(W1),
                                   rtol=3e-5, atol=1e-6)
        # regularized rates go through the same _apply_rate
        H4 = _update_H(E, H, W, beta, 0.05, 0.01)
        H5 = _update_H(Xd, H, W, beta, 0.05, 0.01)
        np.testing.assert_allclose(np.asarray(H4), np.asarray(H5),
                                   rtol=3e-5, atol=1e-6)


@pytest.mark.parametrize("beta", [1.0, 0.0])
def test_objective_matches_dense_and_is_finite(beta):
    n, g, k = 80, 60, 3
    X = _sparse_counts(n, g, 0.08, seed=13)
    rng = np.random.default_rng(2)
    H = jnp.asarray(rng.random((n, k), np.float32) + 0.1)
    W = jnp.asarray(rng.random((k, g), np.float32) + 0.1)
    dense = float(beta_divergence(jnp.asarray(X.toarray()), H, W, beta=beta))
    ell = float(beta_divergence(ell_device_put(csr_to_ell(X)), H, W,
                                beta=beta))
    # the two-regime per-element forms keep both FINITE on genuinely
    # sparse data (the naive log1p forms round to +/-inf in f32)
    assert np.isfinite(dense) and np.isfinite(ell)
    np.testing.assert_allclose(ell, dense, rtol=2e-4)


# ---------------------------------------------------------------------------
# solver-level parity
# ---------------------------------------------------------------------------

def test_fit_h_sparse_dispatch_matches_dense():
    """The H-only refit (consensus usage refits) auto-dispatches scipy
    input to ELL below the threshold and reproduces the dense refit: the
    subproblem is convex and both paths run the same seeded init, so the
    solutions agree to solver tolerance."""
    X = _lowrank_sparse(150, 80, 4, density=0.08, seed=3)
    rng = np.random.default_rng(5)
    W = rng.random((4, 80)).astype(np.float32) + 0.1
    assert resolve_sparse_beta(1.0, density=X.nnz / np.prod(X.shape),
                               width=ell_row_width(X), g=80) or True
    os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
    try:
        H_ell = fit_h(X, W, beta=1.0, chunk_size=64, h_tol=1e-4,
                      chunk_max_iter=500)
    finally:
        os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
    try:
        H_dense = fit_h(X, W, beta=1.0, chunk_size=64, h_tol=1e-4,
                        chunk_max_iter=500)
    finally:
        del os.environ["CNMF_TPU_SPARSE_BETA"]
    np.testing.assert_allclose(H_ell, H_dense, rtol=5e-3, atol=1e-5)


def _ell_vs_dense_errs(X, bl, mode, seed=7):
    # pin the identity recipe: this helper compares ENCODINGS of the
    # same fixed-point iteration; the accel auto-default (ISSUE 17)
    # would otherwise swap batch KL/IS onto dna/amu, whose trajectories
    # are recipe-parity-banded (test_accel.py), not encoding-pinned
    os.environ["CNMF_TPU_ACCEL"] = "0"
    os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
    try:
        _, _, e_ell = run_nmf(X, 4, beta_loss=bl, mode=mode,
                              random_state=seed, online_chunk_size=64)
        _, _, e_ell2 = run_nmf(X, 4, beta_loss=bl, mode=mode,
                               random_state=seed, online_chunk_size=64)
    finally:
        os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
    try:
        _, _, e_dense = run_nmf(X, 4, beta_loss=bl, mode=mode,
                                random_state=seed, online_chunk_size=64)
    finally:
        del os.environ["CNMF_TPU_SPARSE_BETA"]
        del os.environ["CNMF_TPU_ACCEL"]
    # deterministic (nan-safe comparison: IS pathology cases repro too)
    assert e_ell == e_ell2 or (np.isnan(e_ell) and np.isnan(e_ell2))
    return e_ell, e_dense


@pytest.mark.parametrize("mode,bl,bound", [
    ("online", "kullback-leibler", 2e-2),
    ("batch", "kullback-leibler", 1e-5),
    ("batch", "itakura-saito", 1e-3),
])
def test_run_nmf_sparse_objective_bounds(mode, bl, bound):
    """Sweep-level bar of the bf16 parity test (KL 2%): the ELL solve's
    final objective per seed stays within the dense solve's, on a
    structured sparse fixture. Batch solves are the same fixed-point
    iteration evaluated in a different summation order, so they are
    pinned tight; online KL trajectories diverge through the
    early-stopped inner loops (any perturbation class)."""
    X = _lowrank_sparse(160, 90, 4, density=0.08, seed=9)
    e_ell, e_dense = _ell_vs_dense_errs(X, bl, mode)
    assert np.isfinite(e_ell) and np.isfinite(e_dense)
    rel = abs(e_ell - e_dense) / abs(e_dense)
    assert rel < bound, (mode, bl, e_ell, e_dense, rel)


def test_run_nmf_sparse_is_online_pathology_parity():
    """Online IS on data with exact zeros is EPS-floor-dominated: the IS
    divergence is +inf at X=0, both paths floor identically, and on hard
    count-like fixtures the stochastic per-chunk W steps can diverge —
    for the DENSE solver exactly as for the ELL one (pre-existing
    behavior, not an encoding artifact; batch IS parity is pinned tight
    above). The contract here is CLASS parity: the ELL path must behave
    like the dense path on the same fixture — same finiteness, and when
    finite, an equal-or-better objective."""
    for seed, fixture in ((31, _sparse_counts(140, 80, 0.1, seed=31)),
                          (9, _lowrank_sparse(160, 90, 4, density=0.08,
                                              seed=9))):
        e_ell, e_dense = _ell_vs_dense_errs(fixture, "itakura-saito",
                                            "online", seed=seed)
        assert np.isnan(e_ell) == np.isnan(e_dense), (seed, e_ell, e_dense)
        if np.isfinite(e_dense):
            assert e_ell <= e_dense * 1.05, (seed, e_ell, e_dense)


def test_replicate_sweep_ell_matches_dense_objectives():
    from cnmf_torch_tpu.parallel import replicate_sweep
    from cnmf_torch_tpu.parallel.replicates import _sweep_program

    X = _lowrank_sparse(140, 80, 4, density=0.08, seed=21)
    seeds = [3, 11, 27]
    os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
    try:
        sp_e, _, errs_e = replicate_sweep(
            X, seeds, 4, beta_loss="kullback-leibler", mode="online",
            online_chunk_size=64)
    finally:
        os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
    try:
        _sweep_program.cache_clear()
        sp_d, _, errs_d = replicate_sweep(
            X, seeds, 4, beta_loss="kullback-leibler", mode="online",
            online_chunk_size=64)
        _sweep_program.cache_clear()
    finally:
        del os.environ["CNMF_TPU_SPARSE_BETA"]
    assert (sp_e >= 0).all()
    rel = np.abs(errs_e - errs_d) / np.abs(errs_d)
    assert np.all(rel < 2e-2), (errs_e, errs_d)


def test_replicate_sweep_ell_input_validation():
    from cnmf_torch_tpu.parallel import replicate_sweep

    X = _sparse_counts(60, 40, 0.1, seed=2)
    unchunked = csr_to_ell(X)
    with pytest.raises(ValueError, match="pre-chunked"):
        replicate_sweep(unchunked, [1], 3, beta_loss="kullback-leibler",
                        mode="online", online_chunk_size=32)
    chunked, _ = ell_chunk_rows(X, 32)
    with pytest.raises(ValueError, match="unchunked"):
        replicate_sweep(chunked, [1], 3, beta_loss="kullback-leibler",
                        mode="batch")
    with pytest.raises(ValueError, match="init='random'"):
        replicate_sweep(chunked, [1], 3, beta_loss="kullback-leibler",
                        mode="online", online_chunk_size=32, init="nndsvd")


# ---------------------------------------------------------------------------
# row-sharded staging + solve
# ---------------------------------------------------------------------------

def test_rowshard_ell_staging_round_trip():
    """stream_ell_to_mesh lands per-shard dual-ELL blocks whose row side
    reassembles the padded matrix exactly and whose transpose side uses
    one GLOBAL static width across shards."""
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import stream_ell_to_mesh

    X = _sparse_counts(101, 48, 0.1, seed=17)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    E, pad = stream_ell_to_mesh(X, mesh, "cells")
    assert pad == 3 and E.shape == (104, 48)
    Xp = sp.vstack([X, sp.csr_matrix((pad, 48), dtype=np.float32)])
    np.testing.assert_array_equal(
        ell_to_dense(EllMatrix(np.asarray(E.vals), np.asarray(E.cols), 48)),
        Xp.toarray())
    # transpose leaves: (n_shards * g, wt), one block of 48 rows per shard
    assert np.asarray(E.rows_t).shape == (4 * 48, E.t_width)
    shard_shapes = {tuple(sh.data.shape)
                    for sh in E.vals.addressable_shards}
    assert shard_shapes == {(26, E.width)}


def test_rowshard_ell_solve_matches_dense():
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import nmf_fit_rowsharded

    X = _lowrank_sparse(120, 64, 3, density=0.09, seed=23)
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
    try:
        H_e, W_e, e_e = nmf_fit_rowsharded(X, 3, mesh,
                                           beta_loss="kullback-leibler",
                                           seed=5, n_passes=6)
    finally:
        os.environ["CNMF_TPU_SPARSE_BETA"] = "0"
    try:
        H_d, W_d, e_d = nmf_fit_rowsharded(X, 3, mesh,
                                           beta_loss="kullback-leibler",
                                           seed=5, n_passes=6)
    finally:
        del os.environ["CNMF_TPU_SPARSE_BETA"]
    assert np.isfinite(e_e) and np.isfinite(e_d)
    # same init, same pass structure; only summation orders differ inside
    # the statistics, so the solves track each other tightly
    assert abs(e_e - e_d) / abs(e_d) < 2e-2
    np.testing.assert_allclose(W_e, W_d, rtol=0.1, atol=1e-3)


def test_ell_fit_h_rowsharded_matches_in_core():
    from jax.sharding import Mesh

    from cnmf_torch_tpu.parallel.rowshard import fit_h_rowsharded

    X = _lowrank_sparse(96, 50, 3, density=0.09, seed=29)
    rng = np.random.default_rng(4)
    W = rng.random((3, 50)).astype(np.float32) + 0.1
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("cells",))
    os.environ["CNMF_TPU_SPARSE_BETA"] = "1"
    try:
        H_sh = fit_h_rowsharded(X, W, mesh, beta=1.0, h_tol=1e-5,
                                chunk_max_iter=2000)
        H_in = fit_h(X, W, beta=1.0, chunk_size=96, h_tol=1e-5,
                     chunk_max_iter=2000)
    finally:
        del os.environ["CNMF_TPU_SPARSE_BETA"]
    # the convex subproblem converges to one solution; the shard/chunk
    # block boundaries only change how tightly each block polishes, so
    # agreement is to solver tolerance (tiny collapsed entries excluded
    # by the atol floor)
    np.testing.assert_allclose(H_sh, H_in, rtol=2e-2, atol=2e-3)
