import numpy as np
import pandas as pd
import pytest
import scipy.sparse as sp

from cnmf_torch_tpu.utils import (
    AnnDataLite,
    build_paths,
    load_counts,
    load_df_from_npz,
    read_h5ad,
    save_df_to_npz,
    write_h5ad,
)


def _df(rng):
    return pd.DataFrame(
        rng.random((5, 3)),
        index=[f"cell{i}" for i in range(5)],
        columns=[f"g{j}" for j in range(3)],
    )


def test_df_npz_roundtrip(tmp_path, rng):
    df = _df(rng)
    fn = str(tmp_path / "x.df.npz")
    save_df_to_npz(df, fn)
    back = load_df_from_npz(fn)
    pd.testing.assert_frame_equal(df, back)


def test_df_npz_reference_layout(tmp_path, rng):
    # the on-disk container must keep the reference's three-array layout
    # (cnmf.py:32-33) so artifacts interchange between implementations
    df = _df(rng)
    fn = str(tmp_path / "x.df.npz")
    save_df_to_npz(df, fn)
    with np.load(fn, allow_pickle=True) as f:
        assert set(f.files) == {"data", "index", "columns"}
        np.testing.assert_array_equal(f["index"], df.index.values)


@pytest.mark.parametrize("sparse", [True, False])
def test_h5ad_roundtrip(tmp_path, rng, sparse):
    X = rng.random((10, 4)).astype(np.float32)
    if sparse:
        X = sp.csr_matrix(np.where(X > 0.5, X, 0))
    obs = pd.DataFrame({"batch": ["a", "b"] * 5}, index=[f"c{i}" for i in range(10)])
    var = pd.DataFrame(index=[f"g{i}" for i in range(4)])
    adata = AnnDataLite(X, obs, var)
    fn = str(tmp_path / "x.h5ad")
    write_h5ad(fn, adata)
    back = read_h5ad(fn)
    assert back.shape == (10, 4)
    assert list(back.obs_names) == list(obs.index)
    assert list(back.var_names) == list(var.index)
    assert list(back.obs["batch"]) == list(obs["batch"])
    A = back.X.toarray() if sp.issparse(back.X) else back.X
    B = X.toarray() if sp.issparse(X) else X
    np.testing.assert_allclose(A, B, rtol=1e-6)


def test_h5ad_interop_with_anndata_spec(tmp_path, rng):
    # files we write should carry the anndata encoding attrs
    import h5py

    X = sp.csr_matrix(rng.random((6, 5)))
    fn = str(tmp_path / "spec.h5ad")
    write_h5ad(fn, AnnDataLite(X))
    with h5py.File(fn) as f:
        assert f["X"].attrs["encoding-type"] == "csr_matrix"
        assert f["obs"].attrs["encoding-type"] == "dataframe"
        assert tuple(f["X"].attrs["shape"]) == (6, 5)


def test_subsetting_by_names_and_mask(rng):
    X = rng.random((6, 4))
    adata = AnnDataLite(X, var=pd.DataFrame(index=["a", "b", "c", "d"]))
    sub = adata[:, ["c", "a"]]
    assert list(sub.var_names) == ["c", "a"]
    np.testing.assert_allclose(sub.X, X[:, [2, 0]])
    mask = np.array([True, False, True, False, False, True])
    sub2 = adata[mask, :]
    assert sub2.shape == (3, 4)


def test_load_counts_tsv_and_npz(tmp_path, rng):
    df = _df(rng)
    tsv = str(tmp_path / "c.tsv")
    df.to_csv(tsv, sep="\t")
    adata = load_counts(tsv)
    assert sp.issparse(adata.X)
    np.testing.assert_allclose(np.asarray(adata.X.todense()), df.values)

    npz = str(tmp_path / "c.df.npz")
    save_df_to_npz(df, npz)
    adata2 = load_counts(npz, densify=True)
    assert not sp.issparse(adata2.X)
    np.testing.assert_allclose(adata2.X, df.values)


def test_load_counts_10x_mtx(tmp_path, rng):
    import scipy.io

    X = sp.random(7, 5, density=0.5, random_state=0, format="coo")
    d = tmp_path / "tenx"
    d.mkdir()
    scipy.io.mmwrite(str(d / "matrix.mtx"), X.T)  # genes x cells on disk
    pd.DataFrame({0: [f"ENSG{i}" for i in range(5)], 1: [f"G{i}" for i in range(5)],
                  2: ["Gene Expression"] * 5}).to_csv(d / "features.tsv", sep="\t",
                                                      header=False, index=False)
    pd.DataFrame({0: [f"BC{i}" for i in range(7)]}).to_csv(d / "barcodes.tsv", sep="\t",
                                                           header=False, index=False)
    adata = load_counts(str(d / "matrix.mtx"))
    assert adata.shape == (7, 5)
    assert list(adata.var_names) == [f"G{i}" for i in range(5)]
    np.testing.assert_allclose(np.asarray(adata.X.todense()), X.toarray(), rtol=1e-6)


def test_paths_registry(tmp_path):
    paths = build_paths(str(tmp_path), "run1")
    # every key of the reference registry (cnmf.py:423-455) plus
    # factorize_provenance (records the engaged solver path),
    # resilience_ledger (quarantine/retry records, ISSUE 5),
    # pass_checkpoint (mid-run pass-statistics checkpoint, ISSUE 6), and
    # shard_store (out-of-core row-slab store, ISSUE 10)
    assert len(paths) == 28
    assert "factorize_provenance" in paths
    assert "resilience_ledger" in paths
    assert paths["shard_store"] == str(
        tmp_path / "run1" / "cnmf_tmp" / "run1.norm_counts.store")
    assert paths["resilience_ledger"] % 2 == str(
        tmp_path / "run1" / "cnmf_tmp" / "run1.resilience.w2.json")
    assert paths["pass_checkpoint"] % (7, 3) == str(
        tmp_path / "run1" / "cnmf_tmp" / "run1.ckpt.k_7.iter_3.npz")
    assert paths["iter_spectra"] % (7, 3) == str(
        tmp_path / "run1" / "cnmf_tmp" / "run1.spectra.k_7.iter_3.df.npz"
    )
    assert (tmp_path / "run1" / "cnmf_tmp").is_dir()
