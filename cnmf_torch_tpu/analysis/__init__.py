"""Static analysis for the package's own invariants (``cnmf-tpu lint``).

See :mod:`.engine` for the rule engine and CLI; rule families live in
``rules_trace`` (host syncs / nondeterminism / traced branching inside
jitted scopes), ``rules_knobs`` (env-knob registry hygiene + README
drift), ``rules_artifacts`` (atomic-write discipline),
``rules_telemetry`` (event-schema conformance at emit sites), and
``rules_concurrency`` (module-state lock discipline). ``baseline.json``
is the checked-in grandfather list — shipped empty: the package lints
clean.
"""

from .engine import Finding, LintResult, lint_paths

__all__ = ["Finding", "LintResult", "lint_paths"]
