from .multihost import (
    HostBarrierTimeout,
    initialize_distributed,
    is_coordinator,
    mesh_2d,
    replicate_sweep_2d,
    sync_hosts,
)
from .replicates import (
    auto_replicates_per_batch,
    clear_sweep_cache,
    default_mesh,
    replicate_sweep,
    replicate_sweep_packed,
    warm_sweep_programs,
    worker_filter,
)
from .grid2d import (
    mesh_grid2d,
    nmf_fit_grid2d,
    stage_x_grid,
    measure_collectives,
)
from .rowshard import fit_h_rowsharded, nmf_fit_rowsharded, pad_rows_to_mesh
from .streaming import (
    ShardStallError,
    ShardUploadError,
    StreamStats,
    stream_put_leaves,
    stream_to_device,
)

__all__ = [
    "HostBarrierTimeout",
    "ShardStallError",
    "ShardUploadError",
    "StreamStats",
    "stream_put_leaves",
    "stream_to_device",
    "auto_replicates_per_batch",
    "clear_sweep_cache",
    "default_mesh",
    "initialize_distributed",
    "is_coordinator",
    "mesh_2d",
    "replicate_sweep",
    "replicate_sweep_packed",
    "replicate_sweep_2d",
    "sync_hosts",
    "warm_sweep_programs",
    "worker_filter",
    "fit_h_rowsharded",
    "nmf_fit_rowsharded",
    "pad_rows_to_mesh",
    "mesh_grid2d",
    "nmf_fit_grid2d",
    "stage_x_grid",
    "measure_collectives",
]
