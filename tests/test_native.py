"""Native C++ data-loader tests: parity with scipy.io.mmread across the
MatrixMarket variants the fast path claims, malformed-input rejection, and
the gzip path."""

import gzip

import numpy as np
import pytest
import scipy.io
import scipy.sparse as sp

from cnmf_torch_tpu.native import native_available, read_mtx

pytestmark = pytest.mark.skipif(
    not native_available(), reason="no C++ toolchain for the native loader")


def _roundtrip(tmp_path, M, name="m.mtx", field=None, gz=False):
    fn = tmp_path / name
    scipy.io.mmwrite(str(fn), M, field=field)
    if gz:
        with open(fn, "rb") as f:
            data = f.read()
        fn = tmp_path / (name + ".gz")
        with gzip.open(fn, "wb") as f:
            f.write(data)
    return str(fn)


@pytest.mark.parametrize("gz", [False, True])
def test_real_matrix_parity(tmp_path, rng, gz):
    M = sp.random(123, 47, density=0.15, random_state=0, format="coo")
    fn = _roundtrip(tmp_path, M, gz=gz)
    got = read_mtx(fn)
    np.testing.assert_allclose(got.toarray(), M.toarray(), rtol=1e-12)


def test_integer_matrix_parity(tmp_path, rng):
    M = sp.coo_matrix(rng.integers(0, 5, size=(30, 20)))
    fn = _roundtrip(tmp_path, M, field="integer")
    got = read_mtx(fn)
    np.testing.assert_array_equal(got.toarray(), M.toarray())


def test_pattern_matrix(tmp_path):
    fn = tmp_path / "p.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate pattern general\n"
        "% a comment\n3 4 3\n1 1\n2 3\n3 4\n")
    got = read_mtx(str(fn))
    expected = np.zeros((3, 4))
    expected[0, 0] = expected[1, 2] = expected[2, 3] = 1.0
    np.testing.assert_array_equal(got.toarray(), expected)


def test_comments_between_entries(tmp_path):
    fn = tmp_path / "c.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "2 2 2\n1 1 5.5\n% interleaved comment\n2 2 -1e-3\n")
    got = read_mtx(str(fn))
    np.testing.assert_allclose(got.toarray(), [[5.5, 0.0], [0.0, -1e-3]])


def test_symmetric_falls_back_to_scipy(tmp_path):
    fn = tmp_path / "s.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate real symmetric\n"
        "2 2 2\n1 1 1.0\n2 1 3.0\n")
    got = read_mtx(str(fn))  # scipy expands the symmetric half
    np.testing.assert_allclose(got.toarray(), [[1.0, 3.0], [3.0, 0.0]])


def test_malformed_entry_rejected(tmp_path):
    fn = tmp_path / "bad.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "2 2 2\n1 1 1.0\n2 oops 2.0\n")
    with pytest.raises(ValueError, match="malformed|entries"):
        read_mtx(str(fn))


def test_truncated_body_rejected(tmp_path):
    fn = tmp_path / "trunc.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "5 5 10\n1 1 1.0\n")
    with pytest.raises(ValueError, match="declares 10 entries"):
        read_mtx(str(fn))


def test_out_of_bounds_indices_rejected(tmp_path):
    fn = tmp_path / "oob.mtx"
    fn.write_text(
        "%%MatrixMarket matrix coordinate real general\n"
        "2 2 1\n3 1 1.0\n")
    with pytest.raises(ValueError, match="out of declared bounds"):
        read_mtx(str(fn))


def test_large_file_performance_parity(tmp_path, rng):
    """The native loader must stay within striking distance of scipy's
    fast_matrix_market C++ backend on one core (it overtakes on multi-core
    hosts via chunked threading; this box may have a single core). A loose
    bound guards against a regression to pure-Python-parser speeds without
    being timing-flaky."""
    import io
    import time

    M = sp.random(20000, 500, density=0.05, random_state=1, format="coo")
    fn = _roundtrip(tmp_path, M, name="big.mtx")
    raw = open(fn, "rb").read()

    t0 = time.perf_counter()
    ours = read_mtx(fn)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    theirs = sp.coo_matrix(scipy.io.mmread(io.BytesIO(raw)))
    t_scipy = time.perf_counter() - t0

    np.testing.assert_allclose(ours.toarray(), theirs.toarray(), rtol=1e-12)
    assert t_native < 2.0 * t_scipy + 0.05, (
        f"native {t_native:.3f}s vs scipy {t_scipy:.3f}s: parser regressed")
