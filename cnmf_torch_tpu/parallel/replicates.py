"""Replicate-sweep execution — the reference's worker processes as one XLA program.

The reference runs ``n_iter x |K|`` independent NMF replicates as separate OS
processes, statically sharded by ``worker_filter`` and communicating through
files (``/root/reference/src/cnmf/cnmf.py:53-54, 744-749, 839-892``). Here the
replicate axis becomes a ``vmap`` dimension of one jit-compiled solver call,
and device parallelism is a ``jax.sharding`` annotation over a 1-D mesh: XLA
partitions the batched program across chips, with the data matrix replicated
(it is shared, read-only input for every replicate) and the factor states
sharded along the replicate axis. "combine" becomes an all-gather the runtime
inserts when the host fetches the sharded spectra — no per-iteration files.

K changes array shapes, so the sweep compiles once per K (SURVEY.md §7:
per-K jit is the safe first cut); seeds only change data, never shapes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import functools

from ..ops.nmf import (
    _chunk_rows,
    beta_loss_to_float,
    bundle_width,
    lane_health,
    nmf_fit_batch,
    nmf_fit_batch_bundled,
    nmf_fit_batch_hals,
    nmf_fit_online,
    nndsvd_init,
    random_init,
    resolve_bf16_ratio,
    resolve_online_schedule,
    split_regularization,
)
from ..ops.nmf import EVAL_EVERY, SolverTelemetry
from ..ops.pallas import kernel_label, resolve_pallas
from ..ops.recipe import SolverRecipe, resolve_recipe
from ..ops.sparse import EllMatrix, ell_device_put


def _sweep_telemetry_payload(k, beta, mode, seeds, cap, tm, errs,
                             recipe: SolverRecipe | None = None,
                             kernel: str | None = None):
    """The dict a sweep's ``telemetry_sink`` receives. Array values are
    DEVICE arrays (one dispatch-ordered fetch per sweep already covers
    them) — callers ``np.asarray`` when they land events, so a
    ``fetch=False`` pipeline keeps its overlap. ``recipe`` labels the
    engaged solver recipe; ``kernel`` labels the engaged inner-loop
    statistics kernel (``ops/pallas/__init__.py:kernel_label``, ISSUE
    16); the batch solvers' inner-update counts and dna fallback-lane
    fractions ride along when tracked."""
    out = {
        "k": int(k), "beta": float(beta), "mode": mode,
        "seeds": [int(s) for s in seeds],
        "cap": int(cap),
        "cadence": "pass" if mode == "online" else f"iter/{EVAL_EVERY}",
        "trace": tm.trace, "iters": tm.iters, "nonfinite": tm.nonfinite,
        "errs": errs,
    }
    if recipe is not None:
        out["recipe"] = recipe.label
    if kernel is not None:
        out["kernel"] = kernel
    if tm.inner_iters is not None:
        out["inner_iters"] = tm.inner_iters
    if tm.dna_fallback is not None:
        out["dna_fallback"] = tm.dna_fallback
    return out


def _concat_telemetry(parts):
    if len(parts) == 1:
        return parts[0]

    def cat(field):
        leaves = [getattr(t, field) for t in parts]
        if any(v is None for v in leaves):
            return None
        return jnp.concatenate(leaves)

    return SolverTelemetry(
        trace=cat("trace"), iters=cat("iters"), nonfinite=cat("nonfinite"),
        inner_iters=cat("inner_iters"), dna_fallback=cat("dna_fallback"))


def _telemetry_requested(telemetry_sink) -> bool:
    if telemetry_sink is None:
        return False
    from ..utils.telemetry import telemetry_enabled

    return telemetry_enabled()

__all__ = ["replicate_sweep", "replicate_sweep_packed", "worker_filter",
           "default_mesh", "auto_replicates_per_batch", "clear_sweep_cache",
           "warm_sweep_programs", "lane_health"]
# lane_health (ops/nmf.py) is re-exported here as the sweep-level health
# surface: callers grade the per-replicate outputs of replicate_sweep /
# replicate_sweep_packed with it (errs + optional telemetry latch) —
# computed on host from outputs the sweeps already fetch, so the
# telemetry-off programs stay byte-identical (ISSUE 5).


def worker_filter(iterable, worker_index: int, total_workers: int):
    """Round-robin task partition, contract-identical to the reference
    (``cnmf.py:53-54``): worker i takes every task whose position is
    congruent to i modulo total_workers."""
    return (p for i, p in enumerate(iterable)
            if (i - worker_index) % total_workers == 0)


def default_mesh(axis_name: str = "replicates") -> Mesh | None:
    """1-D mesh over all local devices; None when a single device makes
    sharding annotations pure overhead."""
    devices = jax.devices()
    if len(devices) <= 1:
        return None
    return Mesh(np.asarray(devices), (axis_name,))


_FALLBACK_BUDGET_ELEMS = 1 << 28  # 1 GiB of fp32 live state (v5e-tuned)


def _device_budget_elems() -> int:
    """fp32 element budget derived from the accelerator's actual free HBM:
    30% of (bytes_limit - bytes_in_use), leaving ~70% headroom for the
    resident X, XLA scratch/double-buffering, and the returned stacks.
    Falls back to the 1 GiB constant when the runtime exposes no memory
    stats (CPU, and the axon-tunneled TPU, whose memory_stats() is empty)
    — so on a >=16 GB part with real stats the budget scales up instead of
    undersubscribing at the v5e-tuned constant.
    ``CNMF_TPU_BUDGET_ELEMS`` overrides both."""
    from ..utils.envknobs import env_int

    env = env_int("CNMF_TPU_BUDGET_ELEMS", 0, lo=0)
    if env:
        return env
    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:
        stats = {}
    limit = stats.get("bytes_limit")
    if limit:
        free = max(int(limit) - int(stats.get("bytes_in_use", 0)), 0)
        # trust the derivation in BOTH directions: flooring at the 1 GiB
        # constant on a nearly-full device would re-admit the OOM class
        # this budget exists to prevent; 16 MiB keeps degenerate stats
        # from zeroing the slice size (callers still floor at n_dev reps)
        return max((free * 3 // 10) // 4, 1 << 22)
    return _FALLBACK_BUDGET_ELEMS


def auto_replicates_per_batch(n: int, g: int, k: int, beta: float = 2.0,
                              chunk: int | None = None, n_dev: int = 1,
                              budget_elems: int | None = None,
                              ell_width: int | None = None,
                              kl_newton: bool = False) -> int:
    """How many vmapped replicates fit one device slice under the fp32
    element budget (device-derived via :func:`_device_budget_elems` when
    ``budget_elems`` is None).

    Each replicate carries its factor state (3x (n*k + k*g) for the
    current/next/temporary H and W, plus the returned usage stack). For
    beta != 2 the MU numerators materialize chunk x genes intermediates
    *per replicate* (``ops/nmf.py:_update_H``: H@W, X/WH, and the rate
    product all live at once inside the inner while_loop) — the beta=2
    path never builds them (it works from k x k / k x g sufficient
    statistics). Omitting that charge is what let a 100-replicate KL
    sweep admit ~4 GB of live intermediates per buffer and crash the TPU
    worker (round-2 bench, BENCH_r02.json).

    ``ell_width``: the sweep runs the fixed-width ELL kernels
    (``ops/sparse.py``) — the dominant per-replicate intermediate is the
    pre-gathered (chunk, width, k) W slab table (built once per chunk
    solve), plus a handful of (chunk, width) ratio/accumulator buffers;
    the IS hybrid still holds one dense WH + its reciprocal.

    ``kl_newton``: the dna recipe additionally holds the two candidate
    factor blocks and their reconstructions during the per-lane
    selection — charge two more chunk x genes (dense) / chunk x width
    (ELL) buffers per replicate.
    """
    if budget_elems is None:
        budget_elems = _device_budget_elems()
    per_rep = 3 * (n * k + k * g) + n * k
    if beta != 2.0:
        c = n if chunk is None else min(int(chunk), n)
        if ell_width is not None:
            per_rep += c * int(ell_width) * (k + 5)
            if beta == 0.0:
                per_rep += 2 * c * g  # IS hybrid: dense WH + 1/WH
            if kl_newton:
                per_rep += 2 * c * int(ell_width)
        else:
            per_rep += 3 * c * g
            if kl_newton:
                per_rep += 2 * c * g
    return max(n_dev, int(budget_elems // max(per_rep, 1)))


def _slice_specs(n: int, g: int, k: int, R: int, beta: float, mode: str,
                 online_chunk_size: int, replicates_per_batch: int | None,
                 n_dev: int, ell_width: int | None = None,
                 kl_newton: bool = False):
    """The ONE derivation of how a sweep's replicates split into device
    slices — shared by :func:`replicate_sweep` (execution) and
    :func:`warm_sweep_programs` (ahead-of-time compilation), so the warmer
    can never compile for slice shapes the sweep won't use. Returns
    ``(replicates_per_batch, [(start, r, r_padded), ...])``.
    """
    rpb = replicates_per_batch
    if rpb is None:
        chunk = int(min(online_chunk_size, n)) if mode == "online" else n
        rpb = auto_replicates_per_batch(n, g, k, beta=beta, chunk=chunk,
                                        n_dev=n_dev, ell_width=ell_width,
                                        kl_newton=kl_newton)
    # slices must stay mesh-multiples so every shard stays busy
    rpb = max(n_dev, (rpb // n_dev) * n_dev)
    specs = []
    for start in range(0, R, rpb):
        r = min(rpb, R - start)
        specs.append((start, r, r + ((-r) % n_dev)))
    return rpb, specs


def clear_sweep_cache() -> None:
    """Evict the per-(shape, config) compiled sweep executables (and the
    mesh/device references they retain), for both the 1-D and the 2-D
    (multihost) sweep programs. Long-lived library use across many
    datasets/meshes can otherwise accumulate unbounded compile-cache
    memory; CLI runs never need this."""
    _sweep_program.cache_clear()
    from .multihost import _sweep2d_program

    _sweep2d_program.cache_clear()


def warm_sweep_programs(n: int, g: int, k_to_count: dict,
                        beta_loss="frobenius", init: str = "random",
                        mode: str = "online", tol: float = 1e-4,
                        online_chunk_size: int = 5000,
                        online_chunk_max_iter: int = 1000,
                        batch_max_iter: int = 500,
                        n_passes: int | None = None,
                        alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                        alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                        mesh: Mesh | None = None, return_usages: bool = False,
                        replicates_per_batch: int | None = None,
                        online_h_tol: float | None = None,
                        max_workers: int | None = None,
                        ell_dims: tuple | None = None,
                        recipe: SolverRecipe | None = None) -> int:
    """Compile every sweep executable a K-sweep will need, CONCURRENTLY.

    A multi-K ``factorize`` compiles one program per (K, slice-size); the
    compiles dominate cold wall-clock (e.g. ~174 s of a 245 s PBMC-10k
    run) because each first call compiles serially. XLA compilation
    releases the GIL and scales across Python threads (measured ~1.8x for
    2 concurrent TPU compiles), and an AOT ``lower().compile()`` populates
    the same dispatch cache the later ``replicate_sweep`` call hits — so
    warming in a thread pool turns the serial compile wall into roughly
    the longest single compile.

    ``k_to_count`` maps K -> replicate count, and every other argument
    must match the subsequent :func:`replicate_sweep` calls exactly (same
    static-argument derivation, same ``lru_cache`` keys). Returns the
    number of distinct programs warmed. ``ell_dims`` = ``(width,
    t_width)``: the sweep will run ELL-encoded (``ops/sparse.py``) at
    those fixed widths — the warmer then lowers against the dual-ELL
    pytree structure (pre-chunked for mode='online') so the AOT compiles
    land in the same jit cache entries the ELL sweep dispatches into.
    ``recipe``: the resolved solver recipe the sweeps will run —
    recipe fields are part of the program cache key, so warming a
    different recipe would put the compile wall back on the first sweep;
    ``None`` resolves it exactly as :func:`replicate_sweep` does.
    """
    beta = beta_loss_to_float(beta_loss)
    # default resolution mirrors replicate_sweep's PER-K resolution (the
    # auto amu rho is k-dependent for beta=2): one recipe per K, or the
    # caller's recipe for every K — warming a recipe the sweep won't
    # dispatch would put the compile wall back on the first sweep call
    per_k_recipe = {
        int(kk): (recipe if recipe is not None else resolve_recipe(
            beta, mode, ell=ell_dims is not None, n=n, g=g, k=int(kk),
            ell_width=None if ell_dims is None else int(ell_dims[0])))
        for kk in k_to_count}
    online_h_tol, n_passes, h_tol_start = resolve_online_schedule(
        beta, online_h_tol, n_passes)
    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)
    n_dev = 1 if mesh is None else math.prod(mesh.devices.shape)
    x_sharding = None if mesh is None else NamedSharding(mesh, P())

    specs: set[tuple[int, int]] = set()
    for k, R in k_to_count.items():
        k, R = int(k), int(R)
        if R <= 0:
            continue
        _, slices = _slice_specs(n, g, k, R, beta, mode, online_chunk_size,
                                 replicates_per_batch, n_dev,
                                 ell_width=(None if ell_dims is None
                                            else int(ell_dims[0])),
                                 kl_newton=per_k_recipe[k].kl_newton)
        for _start, _r, r_pad in slices:
            specs.add((k, r_pad))
    if not specs:
        return 0

    # warm the SAME telemetry variant the sweep will dispatch (the flag is
    # part of the program cache key; warming the other variant would put
    # the compile wall right back on the first sweep call)
    from ..utils.telemetry import telemetry_enabled

    telem = telemetry_enabled()

    def compile_one(spec):
        k, r_pad = spec
        # the pallas kwarg rides only when the knob engages — same
        # omit-on-default convention as _recipe_statics, so the warm key
        # matches replicate_sweep's dispatch key either way (ISSUE 16)
        pallas_kw = ({"use_pallas": True}
                     if (ell_dims is not None and beta == 1.0
                         and per_k_recipe[k].algo != "sketch"
                         and resolve_pallas()) else {})
        prog = _sweep_program(
            n, g, k, r_pad, init, mode, beta, float(tol),
            float(online_h_tol), int(min(online_chunk_size, n)),
            int(online_chunk_max_iter), int(n_passes), int(batch_max_iter),
            l1_H, l2_H, l1_W, l2_W, mesh, bool(return_usages),
            h_tol_start=h_tol_start,
            # dna/sketch recipes run strict f32 inside the solver; resolve
            # the chain off so the warm key matches the dispatch key and
            # the bf16 announcement never fires for an f32 recipe
            bf16_ratio=(False if (per_k_recipe[k].kl_newton
                                  or per_k_recipe[k].algo == "sketch")
                        else resolve_bf16_ratio(beta, mode)),
            telemetry=telem, **_recipe_statics(per_k_recipe[k]),
            **pallas_kw)
        if ell_dims is not None:
            w_e, wt_e = int(ell_dims[0]), int(ell_dims[1])
            if mode == "online":
                chunk_e = int(min(online_chunk_size, n))
                C = max(1, -(-n // chunk_e))
                row_shape = (C, chunk_e, w_e)
                t_shape = (C, g, wt_e)
            else:
                row_shape = (n, w_e)
                t_shape = (g, wt_e)

            def sds(shape, dt):
                return jax.ShapeDtypeStruct(shape, dt, sharding=x_sharding)

            xs = EllMatrix(sds(row_shape, jnp.float32),
                           sds(row_shape, jnp.int32), g,
                           sds(t_shape, jnp.int32), sds(t_shape, jnp.int32))
        else:
            xs = jax.ShapeDtypeStruct((n, g), jnp.float32,
                                      sharding=x_sharding)
        ss = jax.ShapeDtypeStruct((r_pad,), jnp.uint32)
        prog.lower(xs, ss).compile()

    # swallow=False propagates the first compile error instead of hiding
    # it: a warm failure here means the real sweep would fail identically
    run_warm_jobs([functools.partial(compile_one, s)
                   for s in sorted(specs)],
                  max_workers=max_workers or min(8, len(specs)),
                  swallow=False)
    return len(specs)


def run_warm_jobs(jobs, max_workers: int = 8, swallow: bool = True):
    """Run program-warming callables CONCURRENTLY in a thread pool — the
    ONE warm executor shared by the AOT sweep warmer above, the model's
    consensus/K-selection warmers (``models/cnmf.py``), and the serving
    tier's bucket warmup (``serving/batcher.py``). XLA compiles release
    the GIL, and on a tunneled device each executable's first dispatch
    pays its own upload round trip, so overlapping them turns a serial
    warm wall into roughly the longest single job.

    ``swallow=True`` (the consensus warmers' stance) makes a failed warm
    cost only its own warm; ``swallow=False`` (the AOT warmers' stance)
    propagates the first failure — use it when a warm failure means the
    real dispatch would fail identically."""
    import concurrent.futures

    jobs = list(jobs)
    if not jobs:
        return 0

    def run_one(job):
        try:
            job()
        except Exception:
            if not swallow:
                raise

    with concurrent.futures.ThreadPoolExecutor(
            min(max_workers, len(jobs))) as ex:
        # list() propagates the first error when swallow=False
        list(ex.map(run_one, jobs))
    return len(jobs)


def _slice_telemetry(tm: SolverTelemetry, r: int) -> SolverTelemetry:
    """Trim a slice's telemetry to its real (unpadded) replicates."""
    return SolverTelemetry(
        trace=tm.trace[:r], iters=tm.iters[:r],
        nonfinite=tm.nonfinite[:r],
        inner_iters=None if tm.inner_iters is None else tm.inner_iters[:r],
        dna_fallback=(None if tm.dna_fallback is None
                      else tm.dna_fallback[:r]))


def _recipe_statics(recipe: SolverRecipe) -> dict:
    """The resolved recipe as :func:`_sweep_program` static kwargs (the
    program-family algo plus the amu/dna fields) — one mapping so every
    dispatch site and the AOT warmer key the same cache entries.

    An identity mu recipe returns ``{}``: the call sites then invoke
    ``_sweep_program`` with EXACTLY the argument signature a build
    without the recipe layer uses, so ``CNMF_TPU_ACCEL=0`` (the default)
    hits the same lru_cache entry — same program object, byte for byte
    (pinned by tests/test_accel.py)."""
    if recipe.algo == "mu" and recipe.is_identity:
        return {}
    out = {"algo": "hals" if recipe.algo == "hals" else "mu",
           "inner_repeats": int(recipe.inner_repeats),
           "kl_newton": bool(recipe.kl_newton)}
    if recipe.algo == "sketch":
        out["sketch_dim"] = int(recipe.sketch_dim)
        out["sketch_exact_every"] = int(recipe.sketch_exact_every)
    return out


def _stacked_inits(X, k: int, seeds, init: str, n_rows: int | None = None):
    """Per-replicate (H0, W0) init stacks — traced inside the sweep program.

    ``init='random'`` vmaps the seeded init over replicate keys. For the
    nndsvd family the SVD base is computed once (it is deterministic given
    X), then each replicate fills the base's exact zeros with its own
    seeded small values (nndsvdar semantics, Boutsidis & Gallopoulos 2008):
    exact zeros are absorbing under MU, so without per-replicate filling
    every replicate would follow the identical deterministic trajectory and
    consensus over replicates would be vacuous. (``init='nndsvda'`` keeps
    its defining deterministic mean-fill and therefore *is* degenerate
    across replicates — use 'nndsvd'/'nndsvdar' for consensus sweeps.)
    """
    if isinstance(X, EllMatrix):
        # the nndsvd family's SVD base needs the dense matrix; ELL sweeps
        # are restricted to the seeded random init (the beta != 2
        # production default). n comes from the caller (a pre-chunked
        # encoding's leaves carry padded rows).
        if init != "random":
            raise ValueError(
                f"ELL-encoded sweeps require init='random', got {init!r}")
        g = X.g
        n = int(n_rows) if n_rows is not None else int(
            np.prod(X.vals.shape[:-1]))
    else:
        n, g = X.shape
    R = len(seeds)
    seeds = jnp.asarray(seeds, dtype=jnp.uint32)
    if init == "random":
        # same scaled init as the dense path; mean over ALL n*g entries
        # (the stored values plus the implicit zeros — padded rows are
        # all-zero and contribute nothing)
        x_mean = (jnp.sum(X.vals) / (n * g) if isinstance(X, EllMatrix)
                  else jnp.mean(X))
        return jax.vmap(
            lambda s: random_init(jax.random.key(s), n, g, k, x_mean))(seeds)
    if init not in ("nndsvd", "nndsvda", "nndsvdar"):
        raise ValueError(f"unknown init {init!r}")
    Hb, Wb = nndsvd_init(X, k, variant="nndsvd")
    fill = jnp.mean(X) / 100.0
    if init == "nndsvda":
        Hb = jnp.where(Hb == 0.0, fill, Hb)
        Wb = jnp.where(Wb == 0.0, fill, Wb)
        return (jnp.broadcast_to(Hb, (R, n, k)),
                jnp.broadcast_to(Wb, (R, k, g)))

    def perturb(s):
        kh, kw = jax.random.split(jax.random.key(s))
        H = jnp.where(Hb == 0.0, fill * jax.random.uniform(kh, Hb.shape), Hb)
        W = jnp.where(Wb == 0.0, fill * jax.random.uniform(kw, Wb.shape), Wb)
        return H, W

    return jax.vmap(perturb)(seeds)


@functools.lru_cache(maxsize=128)
def _sweep_program(n: int, g: int, k: int, R: int, init: str, mode: str,
                   beta: float, tol: float, h_tol: float, chunk: int,
                   chunk_max_iter: int, n_passes: int, batch_max_iter: int,
                   l1_H: float, l2_H: float, l1_W: float, l2_W: float,
                   mesh: Mesh | None, return_usages: bool,
                   packed: bool = False, h_tol_start: float | None = None,
                   bf16_ratio: bool = False, telemetry: bool = False,
                   algo: str = "mu", inner_repeats: int = 1,
                   kl_newton: bool = False, sketch_dim: int = 0,
                   sketch_exact_every: int = 1, use_pallas: bool = False):
    """Build (once per static configuration) the jitted sweep executable
    ``(X (n,g), seeds (R,)) -> (usages | (0,), spectra (R,k,g), errs (R,))``.

    Everything — seeded inits, row chunking, the vmapped solver — lives
    inside ONE jit so a steady-state sweep call is a single cached XLA
    dispatch. (Building the vmap wrapper per call re-traced the whole solver
    through Python each time, which cost ~3x the actual device time.)

    ``telemetry=True`` (a separate cache entry — the False program is
    structurally unchanged) appends a replicate-stacked
    :class:`~cnmf_torch_tpu.ops.nmf.SolverTelemetry` to the outputs:
    (trace (R, TRACE_LEN), iters (R,), nonfinite (R,)), fetched by the
    caller in ONE device->host read alongside the spectra.

    ``algo`` / ``inner_repeats`` / ``kl_newton`` are the resolved solver
    recipe's static fields (ISSUE 9; ``ops/recipe.py``): ``algo='hals'``
    routes the batch solve through ``nmf_fit_batch_hals`` and the online
    solve through the ``halsvar`` inner solvers (β=2 only);
    ``inner_repeats``/``kl_newton`` thread the amu/dna recipes into
    ``nmf_fit_batch``/``nmf_fit_online``. The identity recipe
    ``('mu', 1, False)`` hits the same cache entries (and compiles the
    byte-identical programs) as a build without the recipe layer.

    ``use_pallas`` (ISSUE 16) threads the fused Pallas KL kernel dispatch
    into the ELL β=1 solvers. Call sites follow the ``_recipe_statics``
    convention: the kwarg is passed ONLY when the ``CNMF_TPU_PALLAS``
    knob engages, so the default resolution hits the same lru_cache
    entry — same program object — as a build without the kernel layer.

    ``packed=True`` builds the PACKED K-sweep variant: ``k`` is K_max, the
    program additionally takes the slice's actual component count (a traced
    scalar), and replicates initialize at K_max via the threefry
    flat-prefix gather (a draw of shape ``(n, k)`` equals the flat draw's
    prefix, so the padded init reproduces the per-K init bit-exactly with
    exact-zero padding) — zeros MU provably keeps at zero, so one
    executable covers every K of a sweep with per-seed results
    bit-identical to the per-K programs (tested). ``init='random'`` only.
    """
    spec = (None if mesh is None
            else NamedSharding(mesh, P(mesh.axis_names[0], None, None)))

    # beta=2 batch sweeps run the bundle-packed solver over the whole
    # replicate stack (ops/nmf.py: nmf_fit_batch_bundled) — bit-identical
    # to the vmapped per-replicate solver with ~2x the MXU utilization at
    # consensus-sweep ks. Other (mode, beta) combinations vmap the
    # per-replicate solver. Single-device only: bundle_stacks' reshape
    # folds the replicate axis into the packed lane axis, so on a >1-device
    # mesh GSPMD would have to reshard every iteration where the vmapped
    # solver keeps replicates device-local.
    if algo not in ("mu", "hals"):
        raise ValueError(f"unknown sweep algo {algo!r}")
    if algo == "hals" and beta != 2.0:
        raise ValueError("the hals recipe optimizes the Frobenius "
                         "objective (beta=2)")
    if kl_newton and beta != 1.0:
        # loud on every path (bundled included, which has no Newton
        # lane): telemetry/checkpoint identity must never claim dna for
        # a sweep that ran plain MU
        raise ValueError(
            f"the dna recipe requires beta=1 (KL); this sweep has "
            f"beta={beta}")
    if sketch_dim and beta != 1.0:
        # same loudness contract for the sketch lane (ISSUE 11)
        raise ValueError(
            f"the sketch recipe requires beta=1 (KL); this sweep has "
            f"beta={beta}")

    stacked_solver = (mode == "batch" and beta == 2.0
                      and bundle_width(k) > 1 and algo == "mu"
                      and inner_repeats == 1
                      and (mesh is None
                           or int(np.prod(mesh.devices.shape)) == 1))

    if mode == "batch":
        if algo == "hals":
            def solve(X, h0, w0):
                return nmf_fit_batch_hals(
                    X, h0, w0, tol=tol, max_iter=batch_max_iter,
                    l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                    telemetry=telemetry)
        else:
            def solve(X, h0, w0):
                kw = ({"sketch_dim": sketch_dim,
                       "sketch_exact_every": sketch_exact_every}
                      if sketch_dim else {})
                if use_pallas:
                    kw["use_pallas"] = True
                return nmf_fit_batch(
                    X, h0, w0, beta=beta, tol=tol, max_iter=batch_max_iter,
                    l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                    telemetry=telemetry, inner_repeats=inner_repeats,
                    kl_newton=kl_newton, **kw)
    elif mode == "online":
        def solve(X, h0, w0):
            kw = ({"sketch_dim": sketch_dim,
                   "sketch_exact_every": sketch_exact_every}
                  if sketch_dim else {})
            if use_pallas:
                kw["use_pallas"] = True
            Xc, Hc, _ = _chunk_rows(X, h0, chunk)
            out = nmf_fit_online(
                Xc, Hc, w0, beta=beta, tol=tol, h_tol=h_tol,
                chunk_max_iter=chunk_max_iter, n_passes=n_passes,
                l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                h_tol_start=h_tol_start, bf16_ratio=bf16_ratio,
                telemetry=telemetry,
                algo=("halsvar" if algo == "hals" else "mu"),
                kl_newton=kl_newton, **kw)
            Hc, W, err = out[:3]
            H_flat = Hc.reshape(-1, k)[:n]
            return (H_flat, W, err, out[3]) if telemetry else \
                (H_flat, W, err)
    else:
        raise ValueError(f"unknown mode {mode!r}")

    if packed:
        if init != "random":
            raise ValueError("packed K-sweeps require init='random'")
        if algo != "mu":
            raise ValueError("packed K-sweeps run the mu-family recipes "
                             "only; use per-K programs for hals")
        if sketch_dim:
            raise ValueError("packed K-sweeps run the exact mu-family "
                             "programs; the sketch recipe dispatches "
                             "per-K (models/cnmf.py forces packed off)")

        def sweep(X, seeds, k_actual):
            # batched padded random_init: all replicates of a slice share
            # one K, so the prefix-gather index grid is computed once and
            # applied as a single batched take — a per-replicate vmapped
            # gather with traced k blew XLA compile up 5x
            x_mean = jnp.mean(X)
            kf = k_actual.astype(jnp.float32)
            avg = jnp.sqrt(jnp.maximum(x_mean, 1e-16) / kf)

            def draws(s):
                kh, kw = jax.random.split(jax.random.key(s))
                return (jax.random.normal(kh, (n * k,), jnp.float32),
                        jax.random.normal(kw, (k, g), jnp.float32))

            FH, FW = jax.vmap(draws)(seeds)
            cols = jnp.arange(k)[None, :]
            idx = jnp.clip(jnp.arange(n)[:, None] * k_actual + cols,
                           0, n * k - 1)
            H0 = jnp.where(cols[None, :, :] < k_actual,
                           avg * jnp.abs(jnp.take(FH, idx, axis=1)), 0.0)
            W0 = jnp.where((jnp.arange(k)[:, None] < k_actual)[None],
                           avg * jnp.abs(FW), 0.0)
            if spec is not None:
                H0 = jax.lax.with_sharding_constraint(H0, spec)
                W0 = jax.lax.with_sharding_constraint(W0, spec)
            if stacked_solver:
                # zero-padded components survive the bundled updates too:
                # their factor rows are exact zeros, so every masked-Gram
                # and numerator contribution they touch is exactly zero
                out = nmf_fit_batch_bundled(
                    X, H0, W0, tol=tol, max_iter=batch_max_iter,
                    l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                    telemetry=telemetry)
            else:
                out = jax.vmap(solve, in_axes=(None, 0, 0))(X, H0, W0)
            H, W, err = out[:3]
            res = ((H if return_usages
                    else jnp.zeros((0,), X.dtype)), W, err)
            return res + ((out[3],) if telemetry else ())
    else:
        def sweep(X, seeds):
            H0, W0 = _stacked_inits(X, k, seeds, init, n_rows=n)
            if spec is not None:
                H0 = jax.lax.with_sharding_constraint(H0, spec)
                W0 = jax.lax.with_sharding_constraint(W0, spec)
            if stacked_solver:
                out = nmf_fit_batch_bundled(
                    X, H0, W0, tol=tol, max_iter=batch_max_iter,
                    l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                    telemetry=telemetry)
            else:
                out = jax.vmap(solve, in_axes=(None, 0, 0))(X, H0, W0)
            H, W, err = out[:3]
            # drop the usage stack inside the program when the caller
            # doesn't want it — saves the (R, n, k) device->host transfer
            res = ((H if return_usages
                    else jnp.zeros((0,), X.dtype)), W, err)
            return res + ((out[3],) if telemetry else ())

    return jax.jit(sweep)


def replicate_sweep_packed(X, ks, seeds, beta_loss="frobenius",
                           mode: str = "online", tol: float = 1e-4,
                           online_chunk_size: int = 5000,
                           online_chunk_max_iter: int = 1000,
                           batch_max_iter: int = 500,
                           n_passes: int | None = None,
                           alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                           alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                           mesh: Mesh | None = None,
                           return_usages: bool = False,
                           replicates_per_batch: int | None = None,
                           online_h_tol: float | None = None,
                           fetch: bool = True,
                           on_slice=None, telemetry_sink=None,
                           recipe: SolverRecipe | None = None):
    """Run an entire multi-K sweep — ``len(seeds)`` (k, seed) tasks — as ONE
    compiled program at ``K_max``.

    The per-K path (:func:`replicate_sweep`) compiles one executable per
    (K, slice) — the cold-compile wall of a K=5..13 production sweep. Here
    every replicate runs at the static shape ``K_max`` with its components
    beyond ``k`` initialized to exact zeros, which the MU update provably
    keeps at zero (its numerator carries a factor of the zero entry), and
    trailing zeros never perturb any reduction — so per-(seed, k) spectra
    are BIT-IDENTICAL to the per-K programs' (pinned by
    ``tests/test_parallel.py``) while the whole sweep costs one compile and
    one dispatch per memory slice. ``init='random'`` only (the nndsvd
    family's SVD base is K-truncated; use the per-K path there).

    Returns ``(spectra (R, K_max, g), usages (R, n, K_max) | None,
    errs (R,))`` in task order — callers trim row/component padding per
    task (``spectra[r][:ks[r]]``).

    ``on_slice(task_indices, spectra (r,K_max,g), errs (r,))`` — optional
    callback invoked with fetched numpy results as each execution slice
    completes, so callers can land per-task artifacts eagerly (crash-resume
    keeps working mid-sweep). When given, the function returns ``None``
    instead of accumulating the full result.

    ``telemetry_sink(task_indices, payload)``: optional per-slice
    convergence-telemetry callback (active only under
    ``CNMF_TPU_TELEMETRY``) — ``payload`` is a
    :func:`_sweep_telemetry_payload` dict for that slice's replicates.
    """
    if isinstance(X, EllMatrix):
        # the packed program's K_max-padded init gathers x_mean from the
        # dense matrix; ELL sweeps take the per-K path (models/cnmf.py
        # forces packed=False when the ELL dispatch engages)
        raise ValueError(
            "replicate_sweep_packed does not support ELL-encoded X; use "
            "per-K replicate_sweep calls (packed=False)")
    if not isinstance(X, jax.Array):
        # pipelined staging (parallel.streaming): sparse inputs densify
        # on device slab-by-slab — the full dense matrix never exists on
        # host — and dense inputs upload slab-wise off this thread
        from .streaming import stream_to_device

        X = stream_to_device(X, dtype=jnp.float32)
    n, g = X.shape
    beta = beta_loss_to_float(beta_loss)
    if recipe is None:
        recipe = resolve_recipe(beta, mode, n=n, g=g,
                                k=max((int(v) for v in ks), default=None))
    if recipe.algo == "hals":
        raise ValueError("packed K-sweeps run the mu-family recipes only; "
                         "use per-K replicate_sweep calls for hals")
    if recipe.algo == "sketch":
        raise ValueError("packed K-sweeps run the exact mu-family "
                         "programs; use per-K replicate_sweep calls for "
                         "the sketch recipe")
    online_h_tol, n_passes, h_tol_start = resolve_online_schedule(
        beta, online_h_tol, n_passes)
    ks = [int(v) for v in ks]
    seeds = [int(s) & 0x7FFFFFFF for s in seeds]
    if len(ks) != len(seeds):
        raise ValueError("ks and seeds must have equal length")
    R = len(seeds)
    if R == 0:
        return (np.zeros((0, 0, g), np.float32),
                np.zeros((0, n, 0), np.float32) if return_usages else None,
                np.zeros((0,), np.float32))
    kmax = max(ks)

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)
    n_dev = 1 if mesh is None else math.prod(mesh.devices.shape)

    if mesh is not None:
        target = NamedSharding(mesh, P())
        if X.sharding != target:
            X = jax.device_put(X, target)

    # execution slices are grouped BY K: the vmapped solver's while_loops
    # run to the max over the batch, so mixing Ks in one slice makes every
    # small-K replicate ride the largest K's convergence tail (measured 5x
    # on the K=5..13 production sweep). Per-K slices keep per-K tails and
    # batch shapes — the ONE K-agnostic executable is still shared by every
    # K whose slice size matches (equal n_iter => one (R_slice) program).
    by_k: dict[int, list[int]] = {}
    for i, kv in enumerate(ks):
        by_k.setdefault(kv, []).append(i)

    want_telem = _telemetry_requested(telemetry_sink)
    order: list[int] = []
    parts = []
    for kv in sorted(by_k):
        idxs = by_k[kv]
        _, slices = _slice_specs(n, g, kmax, len(idxs), beta, mode,
                                 online_chunk_size, replicates_per_batch,
                                 n_dev, kl_newton=recipe.kl_newton)
        for start, r, r_pad in slices:
            sl_idx = idxs[start:start + r]
            sl_s = [seeds[i] for i in sl_idx]
            if r_pad > r:
                sl_s = sl_s + [sl_s[i % r] for i in range(r_pad - r)]
            prog = _sweep_program(
                n, g, kmax, len(sl_s), "random", mode, beta, float(tol),
                float(online_h_tol), int(min(online_chunk_size, n)),
                int(online_chunk_max_iter), int(n_passes),
                int(batch_max_iter), l1_H, l2_H, l1_W, l2_W, mesh,
                bool(return_usages), packed=True, h_tol_start=h_tol_start,
                bf16_ratio=(False if (recipe.kl_newton
                                      or recipe.algo == "sketch")
                            else resolve_bf16_ratio(beta, mode)),
                telemetry=want_telem, **_recipe_statics(recipe))
            out = prog(X, np.asarray(sl_s, np.uint32), np.int32(kv))
            H, W, err = out[:3]
            if want_telem:
                telemetry_sink(sl_idx, _sweep_telemetry_payload(
                    kv, beta, mode, [seeds[i] for i in sl_idx],
                    n_passes if mode == "online" else batch_max_iter,
                    _slice_telemetry(out[3], r), err[:r], recipe=recipe,
                    kernel=kernel_label(
                        False, False,
                        (False if (recipe.kl_newton
                                   or recipe.algo == "sketch")
                         else resolve_bf16_ratio(beta, mode)))))
            if on_slice is not None:
                on_slice(sl_idx, np.asarray(W[:r]), np.asarray(err[:r]))
                continue
            order.extend(sl_idx)
            parts.append((H[:r] if return_usages else None, W[:r], err[:r]))

    if on_slice is not None:
        return None

    # scatter back to input task order
    inv = np.argsort(np.asarray(order))
    if len(parts) == 1:
        usages_d, spectra_d, errs_d = parts[0]
    else:
        usages_d = (jnp.concatenate([p[0] for p in parts])
                    if return_usages else None)
        spectra_d = jnp.concatenate([p[1] for p in parts])
        errs_d = jnp.concatenate([p[2] for p in parts])
    spectra_d = spectra_d[inv]
    errs_d = errs_d[inv]
    if return_usages:
        usages_d = usages_d[inv]
    if not fetch:
        return spectra_d, usages_d, errs_d
    return (np.asarray(spectra_d),
            np.asarray(usages_d) if return_usages else None,
            np.asarray(errs_d))


def replicate_sweep(X, seeds, k: int, beta_loss="frobenius", init: str = "random",
                    mode: str = "online", tol: float = 1e-4,
                    online_chunk_size: int = 5000,
                    online_chunk_max_iter: int = 1000,
                    batch_max_iter: int = 500,
                    n_passes: int | None = None,
                    alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
                    alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
                    mesh: Mesh | None = None, return_usages: bool = False,
                    replicates_per_batch: int | None = None,
                    online_h_tol: float | None = None, fetch: bool = True,
                    n_rows: int | None = None, telemetry_sink=None,
                    recipe: SolverRecipe | None = None):
    """Run ``len(seeds)`` NMF replicates at one K as a batched XLA program.

    Returns ``(spectra (R, k, g), usages (R, n, k) | None, errs (R,))`` in
    ledger seed order — the in-memory equivalent of the reference's
    per-(k, iter) spectra files (``cnmf.py:888-892``). With ``fetch=True``
    (default) the results are numpy; with ``fetch=False`` they stay device
    arrays and the call returns as soon as the work is *dispatched*, so a
    caller sweeping several Ks can enqueue every program and overlap all
    device->host copies with compute (one round trip per sweep otherwise —
    on high-latency links the copies dominate the whole sweep).

    ``mesh``: optional 1-D device mesh; the replicate axis is sharded across
    it (R is padded to a mesh multiple; pad replicates are computed and
    dropped). ``replicates_per_batch`` bounds device memory by running the
    sweep in host-level slices (each slice is still one XLA call).

    ``X`` may also be a fixed-width :class:`~cnmf_torch_tpu.ops.sparse.
    EllMatrix` (or a scipy-sparse matrix below the ELL density threshold
    with beta in {1, 0} and ``init='random'`` — auto-encoded): the sweep
    then runs the nonzero-only update kernels, with the same batching,
    slicing, and bf16-ratio chain as the dense path. Caller-staged
    encodings should pass the ORIGINAL cell count via ``n_rows`` —
    pre-chunked leaves carry padded rows, and without it the padded count
    leaks into the init scale, the returned usage shape, and the program
    cache key.

    ``telemetry_sink``: optional callable receiving ONE dict per sweep
    (:func:`_sweep_telemetry_payload`) with per-replicate convergence
    records — objective traces, iterations/passes run, nonfinite flags —
    threaded through the solvers' while_loop carries and fetched with the
    sweep results (no extra device syncs). Active only when
    ``CNMF_TPU_TELEMETRY`` is set; otherwise the sink is never called and
    the compiled programs are the unchanged telemetry-free ones.

    ``recipe``: the resolved :class:`~cnmf_torch_tpu.ops.recipe.
    SolverRecipe` (ISSUE 9) — ``hals`` routes the β=2 solves through the
    HALS family, ``amu``/``dna`` thread the accelerated inner loops /
    Diagonalized-Newton KL updates into the batch programs. ``None``
    resolves one from the env knobs (default: plain MU, byte-identical
    programs).
    """
    beta = beta_loss_to_float(beta_loss)
    if n_rows is not None:
        n_rows = int(n_rows)
    if isinstance(X, EllMatrix):
        want_chunked = (mode == "online")
        if want_chunked != (X.vals.ndim == 3):
            raise ValueError(
                "mode=%r needs %s EllMatrix (build online encodings with "
                "ops.sparse.ell_chunk_rows at the sweep's "
                "online_chunk_size, batch encodings with csr_to_ell)"
                % (mode, "a pre-chunked" if want_chunked else "an unchunked"))
        if X.rows_t is None:
            raise ValueError(
                "sweep EllMatrix needs the transpose index set "
                "(rows_t/perm_t) for the W updates")
        if not isinstance(X.vals, jax.Array):
            X = ell_device_put(X)
    elif not isinstance(X, jax.Array):
        # transfer once here; callers sweeping several Ks should device_put
        # X themselves and pass the jax.Array so the transfer amortizes
        # across calls (X rides as a jit *argument*, not a baked constant)
        if sp.issparse(X):
            from ..ops.sparse import (csr_to_ell, ell_chunk_rows,
                                      ell_row_width, resolve_sparse_beta)

            n_s, g_s = X.shape
            if (init == "random" and resolve_sparse_beta(
                    beta, density=X.nnz / max(n_s * g_s, 1),
                    width=ell_row_width(X), g=g_s)):
                if mode == "online":
                    Xe, _ = ell_chunk_rows(
                        X, int(min(online_chunk_size, n_s)))
                else:
                    Xe = csr_to_ell(X)
                X = ell_device_put(Xe)
                n_rows = n_s
        if not isinstance(X, (EllMatrix, jax.Array)):
            # pipelined staging (parallel.streaming) for host input:
            # above-ELL-threshold sparse densifies slab-by-slab (never the
            # full matrix on host), dense uploads slab-wise off-thread
            from .streaming import stream_to_device

            X = stream_to_device(X, dtype=jnp.float32)
    if isinstance(X, EllMatrix):
        if n_rows is None:
            # caller-staged encoding: padded rows (all-zero) are benign —
            # they collapse to zero usages and contribute nothing to W
            n_rows = int(np.prod(X.vals.shape[:-1]))
        n, g = n_rows, X.g
    else:
        n, g = X.shape
    k = int(k)
    online_h_tol, n_passes, h_tol_start = resolve_online_schedule(
        beta, online_h_tol, n_passes)
    seeds = [int(s) & 0x7FFFFFFF for s in seeds]
    R = len(seeds)
    if R == 0:
        return (np.zeros((0, k, g), np.float32),
                np.zeros((0, n, k), np.float32) if return_usages else None,
                np.zeros((0,), np.float32))

    if init == "nndsvda" and R > 1:
        import warnings

        warnings.warn(
            "init='nndsvda' is deterministic given X: all %d replicates of "
            "this sweep will be identical and consensus over them is "
            "vacuous. Use init='nndsvd' (seeded nndsvdar fill) or 'random' "
            "for replicate sweeps." % R, UserWarning, stacklevel=2)

    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)

    if recipe is None:
        recipe = resolve_recipe(
            beta, mode, ell=isinstance(X, EllMatrix), n=n, g=g, k=k,
            ell_width=X.width if isinstance(X, EllMatrix) else None)
    if recipe.algo == "hals" and beta != 2.0:
        raise ValueError("the hals recipe optimizes the Frobenius "
                         "objective; this sweep has beta=%g" % beta)
    if recipe.algo == "sketch" and beta != 1.0:
        raise ValueError("the sketch recipe requires beta=1 (KL); this "
                         "sweep has beta=%g" % beta)

    n_dev = 1 if mesh is None else math.prod(mesh.devices.shape)
    replicates_per_batch, slices = _slice_specs(
        n, g, k, R, beta, mode, online_chunk_size, replicates_per_batch,
        n_dev,
        ell_width=X.width if isinstance(X, EllMatrix) else None,
        kl_newton=recipe.kl_newton)

    if mesh is not None:
        target = NamedSharding(mesh, P())
        if isinstance(X, EllMatrix):
            if X.vals.sharding != target:
                X = jax.device_put(X, target)  # pytree: every leaf
        elif X.sharding != target:
            # callers sweeping several Ks should replicate X onto the mesh
            # themselves so this broadcast doesn't repeat per call
            X = jax.device_put(X, target)

    want_telem = _telemetry_requested(telemetry_sink)
    # fused Pallas KL dispatch (ISSUE 16): ELL β=1 sweeps only, and the
    # kwarg rides only when the knob engages — _recipe_statics convention,
    # so the default resolution shares the no-kernel-layer cache entry
    pallas_kw = ({"use_pallas": True}
                 if (isinstance(X, EllMatrix) and beta == 1.0
                     and recipe.algo != "sketch" and resolve_pallas())
                 else {})
    parts = []
    telem_parts = []
    for start, r, r_pad in slices:
        sl = seeds[start:start + r]
        if r_pad > r:
            # tile modulo r: works even when the slice is smaller than the
            # mesh (pad replicates recompute existing seeds and are dropped)
            sl = sl + [sl[i % r] for i in range(r_pad - r)]
        prog = _sweep_program(
            n, g, k, len(sl), init, mode, beta, float(tol),
            float(online_h_tol), int(min(online_chunk_size, n)),
            int(online_chunk_max_iter), int(n_passes), int(batch_max_iter),
            l1_H, l2_H, l1_W, l2_W, mesh, bool(return_usages),
            h_tol_start=h_tol_start,
            bf16_ratio=(False if (recipe.kl_newton
                                  or recipe.algo == "sketch")
                        else resolve_bf16_ratio(beta, mode)),
            telemetry=want_telem, **_recipe_statics(recipe), **pallas_kw)
        # async dispatch: every slice is enqueued before any result is read
        out = prog(X, np.asarray(sl, dtype=np.uint32))
        H, W, err = out[:3]
        parts.append((H[:r] if return_usages else None, W[:r], err[:r]))
        if want_telem:
            telem_parts.append(_slice_telemetry(out[3], r))

    if len(parts) == 1:
        usages_d, spectra_d, errs_d = parts[0]
    else:
        usages_d = (jnp.concatenate([p[0] for p in parts])
                    if return_usages else None)
        spectra_d = jnp.concatenate([p[1] for p in parts])
        errs_d = jnp.concatenate([p[2] for p in parts])

    if want_telem:
        telemetry_sink(_sweep_telemetry_payload(
            k, beta, mode, seeds,
            n_passes if mode == "online" else batch_max_iter,
            _concat_telemetry(telem_parts), errs_d, recipe=recipe,
            kernel=kernel_label(
                isinstance(X, EllMatrix), bool(pallas_kw),
                (False if (recipe.kl_newton or recipe.algo == "sketch")
                 else resolve_bf16_ratio(beta, mode)))))

    if not fetch:
        return spectra_d, usages_d, errs_d
    return (np.asarray(spectra_d),
            np.asarray(usages_d) if return_usages else None,
            np.asarray(errs_d))
