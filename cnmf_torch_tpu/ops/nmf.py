"""Beta-divergence NMF solvers — the compute core, as jit-compiled TPU kernels.

This module is the TPU-native replacement for the external ``nmf-torch``
package the reference delegates all factorization to
(``/root/reference/src/cnmf/cnmf.py:17, 805-821``) and for the in-repo torch
H-solver ``fit_H_online`` (``cnmf.py:260-388``). Model convention matches
nmf-torch (spectra/usage "switched w.r.t. sklearn", ``cnmf.py:758``):

    X (cells x genes)  ~=  H (cells x k, "usages") @ W (k x genes, "spectra")

Solvers are multiplicative-update (MU) for beta-divergence with
beta in {2: frobenius, 1: kullback-leibler, 0: itakura-saito}
(``cnmf.py:944-951``), with the nmf-torch regularization convention observed
in the reference: L1 subtracted from the numerator and clamped at zero, L2
added to the denominator, and update rates zeroed where the denominator
underflows (``cnmf.py:357-371``).

TPU-first design notes:
  * For beta=2, updates and the exact Frobenius objective are computed from
    k x k / k x g sufficient statistics (H^T H, H^T X, W W^T, X W^T) — no
    cells x genes intermediate is ever materialized, so the whole solve is
    MXU matmuls over an HBM-resident X.
  * ``mode='online'`` streams row chunks through a ``lax.scan``: each chunk's
    usage block is solved by an inner MU loop (the ``online_chunk_max_iter``
    / chunk-size contract of the reference ledger, ``cnmf.py:765-767``) while
    per-chunk W-update statistics accumulate; W updates once per pass. This
    is the scalable path for atlas-size inputs and row-sharding.
  * Replicate sweeps ``vmap`` these kernels over stacked (seed, H0, W0)
    states — the reference's 900 independent worker processes become one
    batched XLA program (see ``cnmf_torch_tpu.parallel``).
  * No data-dependent Python control flow: convergence is ``lax.while_loop``
    on the relative objective decrease, sklearn-style, evaluated every
    ``EVAL_EVERY`` iterations.
"""

from __future__ import annotations

import functools
import threading
import typing
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import scipy.sparse as sp

from ..utils.jax_compat import assert_threefry_partitionable, enable_x64
from .pallas import resolve_pallas
from .sparse import (
    EllMatrix,
    csr_to_ell,
    ell_beta_err,
    ell_chunk_rows,
    ell_device_put,
    ell_is_h_stats,
    ell_is_w_stats,
    ell_kl_h_newton_stats,
    ell_kl_h_stats,
    ell_kl_w_stats,
    ell_kl_w_stats_rows,
    ell_row_width,
    ell_w_table,
    ell_wh_at_nz,
    is_per_elem,
    kl_nz_term,
    resolve_sparse_beta,
)
from .recipe import SolverRecipe, resolve_recipe

__all__ = [
    "run_nmf",
    "nmf_fit_batch",
    "nmf_fit_online",
    "fit_h",
    "beta_divergence",
    "init_factors",
    "lane_health",
    "nndsvd_init",
    "BETA_LOSS",
    "SolverRecipe",
    "resolve_recipe",
    "SolverTelemetry",
    "TRACE_LEN",
]

EPS = 1e-16
EVAL_EVERY = 10

# accelerated-MU (recipe 'amu') repeat-loop stagnation floor: a repeat
# whose relative H change drops below this exits the repeat loop early —
# further fixed-W polish is wasted against a W about to move (the same
# trade resolve_online_schedule measured for the online inner loops).
# Small enough that the configured rho repeats actually run while real
# progress is being made.
INNER_STAG_TOL = 1e-4

BETA_LOSS = {"frobenius": 2.0, "kullback-leibler": 1.0, "itakura-saito": 0.0}

# fixed objective-trace length for solver telemetry: the while_loop carry
# cannot grow, so convergence traces live in a fixed buffer — one slot per
# objective evaluation (every EVAL_EVERY iterations for the batch solvers,
# one per pass for the online solver, whose pass caps resolve to <= 60).
# Evaluations beyond the buffer overwrite the last slot.
TRACE_LEN = 64


class SolverTelemetry(typing.NamedTuple):
    """Per-solve convergence record, threaded through the ``lax.while_loop``
    carries when the solver is traced with ``telemetry=True`` (a STATIC
    flag: the default-False program is byte-identical to a build without
    telemetry — zero ops, zero transfers).

    ``trace``: (TRACE_LEN,) objective values at each evaluation point
    (NaN-filled past the last evaluation; under ``vmap`` this stacks to
    (R, TRACE_LEN)).  ``iters``: iterations (batch) or passes (online)
    until the replicate's OWN stopping test first failed — LATCHED: under
    ``vmap`` the batched loop keeps stepping converged replicates until
    the last one finishes, and those extra monotone steps must not count
    even if a lane's windowed progress momentarily re-exceeds ``tol``
    afterwards (plateau-then-escape).
    ``nonfinite``: any evaluated objective (incl. the final recompute)
    was inf/NaN.  Whether a replicate was CAPPED is host-derivable:
    ``iters >= max_iter`` (resp. ``n_passes``).

    ``inner_iters`` (batch solvers only; None elsewhere): total inner
    update applications while active — equals ``iters`` under plain MU,
    counts the actual H sub-iterations under the accelerated-MU repeat
    schedule (ISSUE 9). ``dna_fallback`` (dna recipe only; None
    elsewhere): fraction of row/column lanes that took the monotone MU
    fallback instead of the Newton step, averaged over active steps."""

    trace: Any
    iters: Any
    nonfinite: Any
    inner_iters: Any = None
    dna_fallback: Any = None


def lane_health(errs, nonfinite=None, spectra=None):
    """Per-lane solver health bitmap (True = healthy) — the always-on
    promotion of the telemetry-only nonfinite latch (ISSUE 5).

    Derived ON HOST from outputs every solver already returns: the final
    per-lane objective recompute (``errs``). A lane whose factor state
    went nonfinite cannot produce a finite final objective — NaN/inf
    propagate absorbingly through the MU ratio chains and every
    beta-divergence form touches every factor entry — so ``isfinite``
    on the returned objective IS the health bit, with zero extra device
    ops or transfers: the telemetry-off factorize programs stay
    byte-identical to a build without this function.

    ``nonfinite``: the :class:`SolverTelemetry` latch array, when the
    sweep was traced with ``telemetry=True`` — folds in transient
    mid-solve nonfinites that happened to recover by the final
    evaluation. ``spectra``: optional (R, ...) stacked factor output for
    a belt-and-braces host-side finiteness sweep over what will actually
    be written to disk. Both accept device arrays (fetched here).
    """
    errs = np.asarray(errs, dtype=np.float64).reshape(-1)
    health = np.isfinite(errs)
    if nonfinite is not None:
        health = health & ~np.asarray(nonfinite).astype(bool).reshape(-1)
    if spectra is not None:
        S = np.asarray(spectra)
        health = health & np.isfinite(
            S.reshape(S.shape[0], -1)).all(axis=1)
    return health


def beta_loss_to_float(beta_loss) -> float:
    """Name -> numeric beta, mirroring ``refit_usage`` (cnmf.py:944-951)."""
    if isinstance(beta_loss, str):
        try:
            return BETA_LOSS[beta_loss]
        except KeyError:
            raise ValueError(
                "beta_loss must be one of ['frobenius', 'kullback-leibler', "
                "'itakura-saito'] or a numeric value."
            )
    if isinstance(beta_loss, (int, float)):
        return float(beta_loss)
    raise ValueError("beta_loss must be a string or numeric value.")


# ---------------------------------------------------------------------------
# objective
# ---------------------------------------------------------------------------

def _beta_div_dense(X, WH, beta: float):
    """Elementwise beta-divergence sum for a materialized WH (beta != 2 path)."""
    if beta == 1.0:
        # KL: sum(X log(X/WH) - X + WH), 0 log 0 := 0.  Rewritten as
        # X * (u - log1p(u)) with u = WH/X - 1: near convergence each term
        # is O(u^2) and the naive form loses it all to fp32 cancellation.
        # kl_nz_term (ops/sparse.py) additionally splits the logs where
        # WH/X underflows f32 — on genuinely sparse data the log1p form
        # rounds to -inf and poisons the whole objective.
        per_elem = jnp.where(
            X > 0,
            kl_nz_term(jnp.maximum(X, EPS), jnp.maximum(WH, EPS)), WH)
        return jnp.sum(per_elem)
    if beta == 0.0:
        # IS: sum(X/WH - log(X/WH) - 1) via the shared two-regime form
        # (ops/sparse.py:is_per_elem): v - log1p(v) near convergence, split
        # logs for EPS-floored zero counts — the naive log1p form rounds to
        # -inf in f32 on genuinely sparse X, turning the objective into inf
        # and disabling the relative-decrease stopping rule entirely
        return jnp.sum(is_per_elem(jnp.maximum(X, EPS),
                                   jnp.maximum(WH, EPS)))
    if beta == 2.0:
        return 0.5 * jnp.sum((X - WH) ** 2)
    # generic beta
    Xs = jnp.maximum(X, EPS)
    WHs = jnp.maximum(WH, EPS)
    b = beta
    return jnp.sum(
        (Xs ** b + (b - 1.0) * WHs ** b - b * Xs * WHs ** (b - 1.0))
        / (b * (b - 1.0))
    )


# elementwise-size threshold below which materializing X - HW is cheaper and
# numerically safer than the trace identity (which suffers cancellation when
# the residual is tiny relative to ||X||^2)
_DENSE_ERR_ELEMS = 1 << 22

# objective evaluations always use full-f32 matmuls: the TPU default (bf16
# multiplicands) is fine for MU update ratios but wrecks the convergence test
_HI = jax.lax.Precision.HIGHEST


@functools.partial(jax.jit, static_argnames=("beta", "use_pallas"))
def beta_divergence(X, H, W, beta: float = 2.0,
                    use_pallas: bool = False):
    """D_beta(X || HW). For beta=2 on large shapes uses the trace identity —
    no cells x genes buffer is materialized. ``X`` may be a fixed-width
    :class:`~cnmf_torch_tpu.ops.sparse.EllMatrix` for beta in {1, 0}: the
    KL objective is then evaluated on the nonzeros only (plus the k-sized
    ``sum WH`` term), matching the dense cancellation-safe form exactly.
    ``use_pallas`` (static, ELL beta=1 only) evaluates the nonzero terms
    with the fused kernel (``ops/pallas_kl.py``, f32-tolerance parity)."""
    if isinstance(X, EllMatrix):
        if use_pallas and beta == 1.0:
            from .pallas_kl import pallas_kl_beta_err

            return pallas_kl_beta_err(X, H, W)
        return ell_beta_err(X, H, W, beta)
    if beta == 2.0:
        if X.shape[0] * X.shape[1] <= _DENSE_ERR_ELEMS:
            R = X - jnp.matmul(H, W, precision=_HI)
            return 0.5 * jnp.sum(R * R)
        HtH = jnp.matmul(H.T, H, precision=_HI)
        HtX = jnp.matmul(H.T, X, precision=_HI)
        return jnp.maximum(
            0.5 * (jnp.sum(X * X) - 2.0 * jnp.sum(W * HtX)
                   + jnp.sum(jnp.matmul(HtH, W, precision=_HI) * W)),
            0.0,
        )
    return _beta_div_dense(X, H @ W, beta)


# ---------------------------------------------------------------------------
# MU update steps
# ---------------------------------------------------------------------------

def resolve_online_schedule(beta: float, h_tol=None, n_passes=None):
    """Per-loss defaults for the online solver's (inner tolerance, pass cap).

    For beta=2 each inner iteration is k-sized work after the per-chunk
    numerator precompute (``_chunk_h_solve``), but the tight-inner
    pathology still applies in miniature: measured on v5e (K=9 x 126
    replicates of 10000x2000), h_tol=1e-3 rides the inner while_loop's
    convergence tail for 4.22 s warm and a WORSE final objective than
    h_tol=3e-3 at 0.76 s (5.5x) — polishing usage blocks against an
    unconverged W wastes the pass budget. The beta=2 default is therefore
    (3e-3, 20).

    For beta != 2 every inner iteration is a full data pass (WH must be
    re-materialized), and measured on TPU v5e the tight schedule is
    pathological: at (1e-3, 20) the K=9 online-KL solve runs ~36,000 inner
    iterations per replicate — every chunk hits the 1000-iteration cap every
    pass — for a WORSE final objective than (1e-2, 60), which uses ~250
    inner iterations, 49x less wall-clock. Loose inner solves + more W
    passes is the right coordinate-descent trade when inner iterations cost
    O(n g k): W moves early instead of polishing H against a wrong W. The
    pass loop still stops on the relative objective test, and callers can
    pin both knobs explicitly (the factorize provenance records
    the resolved schedule).

    The two knobs resolve coherently: an unset ``n_passes`` follows the
    EFFECTIVE ``h_tol`` — loose inner solves get the 60-pass cap, a
    caller-pinned tight ``h_tol`` keeps the classic 20 (not 60 passes of
    the expensive tight solve).
    """
    h_tol_start = None
    if h_tol is None:
        # beta != 2 default schedules are coarse-to-fine (start loose,
        # halve per pass to the floor): the expensive inner iterations are
        # full data passes, and the early loose passes cost almost nothing
        # while W moves (KL tier 157 s -> 14 s). For beta=2 the constant
        # 3e-3 floor measured FASTER end-to-end (warm K=5..13 sweep 17.9 s
        # vs 27.1 s under coarse-to-fine — the cheap k-sized inner solves
        # don't need staging, and the forced coarse passes just add W
        # updates) at near-equal objectives, so beta=2 runs constant.
        # An EXPLICIT h_tol always runs constant — callers get the
        # schedule they pinned.
        h_tol = 3e-3 if beta == 2.0 else 1e-2
        if beta != 2.0:
            h_tol_start = 0.1
    if n_passes is None:
        n_passes = 60 if (beta != 2.0 and float(h_tol) >= 5e-3) else 20
    return float(h_tol), int(n_passes), h_tol_start


_bf16_ratio_announced = False
_bf16_announce_lock = threading.Lock()


def resolve_bf16_ratio(beta: float, mode: str, override=None) -> bool:
    """Production default for the bf16-intermediate beta!=2 chains: ON for
    online beta=1 (KL) and beta=0 (IS) sweeps — measured 1.78x / 2.09x per
    MU iteration on v5e at the k=9 sweep shape with objective-trajectory
    parity to <=0.001% (see ``_update_H``) — OFF everywhere else: the
    batch solver is element-wise oracle-pinned against sklearn's f64
    trajectories and keeps strict f32. Opt out with
    ``CNMF_TPU_BF16_RATIO=0``; an explicit ``override`` wins.

    The first activation per process is announced on stdout (ADVICE r5 #2):
    the chain changes per-replicate numerics vs a strict-f32/reference run
    (per-seed objectives bounded at ~2-5% by test), and parity-sensitive
    users should find the opt-out without reading this docstring."""
    if override is not None:
        return bool(override)
    from ..utils.envknobs import env_flag

    active = (beta in (1.0, 0.0) and mode == "online"
              and env_flag("CNMF_TPU_BF16_RATIO", True))
    if active:
        global _bf16_ratio_announced
        with _bf16_announce_lock:
            first = not _bf16_ratio_announced
            _bf16_ratio_announced = True
        if first:
            print("cnmf-tpu: bf16 ratio chain active for online "
                  "KL/IS updates (1.78-2.09x on v5e; per-seed objectives "
                  "within ~2-5% of strict f32 — set CNMF_TPU_BF16_RATIO=0 "
                  "for f32-parity runs).")
    return active


def split_regularization(alpha: float, l1_ratio: float) -> tuple[float, float]:
    """sklearn-convention (alpha, l1_ratio) -> (l1, l2) penalty split, as the
    reference's ledger kwargs encode it (cnmf.py:757-771)."""
    return (float(alpha) * float(l1_ratio),
            float(alpha) * (1.0 - float(l1_ratio)))


def mu_gamma(beta: float) -> float:
    """Févotte & Idier (2011) convergence exponent for the MU rate:
    ``rate ** gamma`` with gamma = 1/(2-beta) for beta < 1, 1/(beta-1) for
    beta > 2, and 1 in between. Without it the beta=0 (Itakura-Saito)
    update is not monotone; sklearn's MU solver applies the same exponent
    (our IS trajectory is element-wise oracle-tested against it)."""
    beta = float(beta)
    if beta < 1.0:
        return 1.0 / (2.0 - beta)
    if beta > 2.0:
        return 1.0 / (beta - 1.0)
    return 1.0


def _apply_rate(M, numer, denom, l1, l2, eps=EPS, gamma: float = 1.0):
    """nmf-torch-convention MU rate (observed at cnmf.py:357-371):
    numerator L1-shifted and clamped, L2 added to denominator, rate zeroed
    where the denominator underflows; ``gamma`` exponent per
    :func:`mu_gamma`."""
    numer = jnp.maximum(numer - l1, 0.0) if l1 else numer
    denom = denom + l2 * M if l2 else denom
    rate = jnp.where(denom < eps, 0.0, numer / jnp.maximum(denom, eps))
    if gamma != 1.0:
        rate = rate ** gamma
    return M * rate


def _apply_rate_sketched(W, numer, denom, l1, l2):
    """MU rate from SUBSAMPLED W statistics (the 'sketch' recipe): an
    entry whose sampled numerator carries no evidence — no sampled
    nonzero landed in its column, so ``numer`` is exactly 0 — HOLDS its
    value instead of multiplying by zero. Exact zeros are absorbing
    under MU, so one unlucky subsample would otherwise permanently kill
    a component weight (measured +74% final KL on the sparse fixture
    before this guard); genuinely dead entries still decay through the
    interleaved exact updates, whose numerators see every row."""
    return jnp.where(numer > 0.0,
                     _apply_rate(W, numer, denom, l1, l2), W)


def _update_H(X, H, W, beta: float, l1: float, l2: float,
              bf16_ratio: bool = False, w_table=None, w_colsum=None,
              use_pallas: bool = False):
    if isinstance(X, EllMatrix):
        # sparsity-aware path (ops/sparse.py): nonzero-only numerator
        # statistics from the fixed-width ELL encoding; the bf16 ratio
        # chain composes (bf16 values/gathers, f32 accumulation).
        # ``w_table``: pre-gathered W slabs for fixed-W inner loops.
        # ``use_pallas`` (static): the fused one-pass kernel for the
        # beta=1 statistics (ops/pallas_kl.py; the kernel re-gathers
        # its slab table in VMEM, so no host-side w_table is needed).
        if beta == 1.0:
            if use_pallas:
                from .pallas_kl import pallas_kl_h_stats

                numer, denom = pallas_kl_h_stats(X, H, W, bf16_ratio)
            else:
                numer, denom = ell_kl_h_stats(X, H, W, bf16_ratio,
                                              w_table)
        elif beta == 0.0:
            numer, denom = ell_is_h_stats(X, H, W, bf16_ratio, w_table)
        else:
            raise NotImplementedError(
                f"ELL updates implement beta in {{1, 0}}, got {beta}")
        return _apply_rate(H, numer, denom, l1, l2, gamma=mu_gamma(beta))
    if beta == 2.0:
        numer = X @ W.T
        denom = H @ (W @ W.T)
    elif beta == 1.0 and bf16_ratio:
        # HBM-roofline relief: the chain's traffic is X + WH + ratio reads/
        # writes (the matmul multiplicands are bf16 on TPU even for f32
        # arrays, so only the MEMORY format changes). Storing X and the
        # WH/ratio intermediates in bf16 with f32 matmul accumulation
        # measured 172 -> 96 us/iter/rep (MFU 0.021 -> 0.038) at the k=9
        # sweep shape with the 200-iteration KL objective matching f32 to
        # 5 decimal places (round-5 experiment; factor state stays f32).
        # Callers pass X already bf16 to keep the cast out of the loop.
        wb = W.astype(jnp.bfloat16)
        wh = jnp.matmul(H.astype(jnp.bfloat16), wb,
                        preferred_element_type=jnp.bfloat16)
        ratio = X.astype(jnp.bfloat16) / jnp.maximum(wh, jnp.bfloat16(EPS))
        numer = jnp.matmul(ratio, wb.T, preferred_element_type=jnp.float32)
        denom = jnp.broadcast_to(W.sum(axis=1)[None, :], H.shape)
    elif beta == 1.0:
        # measured on v5e: this DENSE chain is HBM-roofline-bound, and
        # XLA's fusion of the batched (vmapped) form already matches a
        # hand-fused Pallas one-pass kernel (ratio+both matmuls in VMEM
        # tiles) — that kernel won 3x single-replicate but 0x under
        # vmap, so the dense lane keeps the plain jnp form. That verdict
        # is dense-only: the ELL lane above, where XLA's gather chains
        # (not the matmuls) dominate, dispatches fused Pallas kernels
        # through CNMF_TPU_PALLAS (ops/pallas_kl.py; bench.py mfu tier
        # tracks both lanes with per-kernel labels).
        # ``w_colsum``: the serving tier's resident loop-invariant KL
        # denominator (ISSUE 12) — W is fixed across every request, so
        # the daemon computes the sum once at reference staging (the
        # same reduce op this line runs: results are bit-equal)
        R = X / jnp.maximum(H @ W, EPS)
        numer = R @ W.T
        denom = jnp.broadcast_to(
            (W.sum(axis=1) if w_colsum is None else w_colsum)[None, :],
            H.shape)
    elif beta == 0.0 and bf16_ratio:
        # same memory-format relief as the beta=1 branch; the bf16
        # reciprocal chain measured 2.09x with <=0.0008% objective
        # divergence over 200 damped (gamma=0.5) iterations (round 5)
        wb = W.astype(jnp.bfloat16)
        wh = jnp.maximum(jnp.matmul(H.astype(jnp.bfloat16), wb,
                                    preferred_element_type=jnp.bfloat16),
                         jnp.bfloat16(EPS))
        inv = 1.0 / wh
        numer = jnp.matmul(X.astype(jnp.bfloat16) * inv * inv, wb.T,
                           preferred_element_type=jnp.float32)
        denom = jnp.matmul(inv, wb.T, preferred_element_type=jnp.float32)
    elif beta == 0.0:
        WH = jnp.maximum(H @ W, EPS)
        numer = (X / (WH * WH)) @ W.T
        denom = (1.0 / WH) @ W.T
    else:
        WH = jnp.maximum(H @ W, EPS)
        numer = (X * WH ** (beta - 2.0)) @ W.T
        denom = (WH ** (beta - 1.0)) @ W.T
    return _apply_rate(H, numer, denom, l1, l2, gamma=mu_gamma(beta))


def _update_W(X, H, W, beta: float, l1: float, l2: float,
              bf16_ratio: bool = False, w_table=None,
              use_pallas: bool = False):
    if isinstance(X, EllMatrix):
        if beta == 1.0:
            if use_pallas:
                from .pallas_kl import pallas_kl_w_stats

                numer, denom = pallas_kl_w_stats(X, H, W, bf16_ratio)
            else:
                numer, denom = ell_kl_w_stats(X, H, W, bf16_ratio,
                                              w_table)
        elif beta == 0.0:
            numer, denom = ell_is_w_stats(X, H, W, bf16_ratio)
        else:
            raise NotImplementedError(
                f"ELL updates implement beta in {{1, 0}}, got {beta}")
        return _apply_rate(W, numer, denom, l1, l2, gamma=mu_gamma(beta))
    if beta == 2.0:
        numer = H.T @ X
        denom = (H.T @ H) @ W
    elif beta == 1.0 and bf16_ratio:
        hb = H.astype(jnp.bfloat16)
        wh = jnp.matmul(hb, W.astype(jnp.bfloat16),
                        preferred_element_type=jnp.bfloat16)
        ratio = X.astype(jnp.bfloat16) / jnp.maximum(wh, jnp.bfloat16(EPS))
        numer = jnp.matmul(hb.T, ratio, preferred_element_type=jnp.float32)
        denom = jnp.broadcast_to(H.sum(axis=0)[:, None], W.shape)
    elif beta == 1.0:
        R = X / jnp.maximum(H @ W, EPS)
        numer = H.T @ R
        denom = jnp.broadcast_to(H.sum(axis=0)[:, None], W.shape)
    elif beta == 0.0 and bf16_ratio:
        hb = H.astype(jnp.bfloat16)
        wh = jnp.maximum(jnp.matmul(hb, W.astype(jnp.bfloat16),
                                    preferred_element_type=jnp.bfloat16),
                         jnp.bfloat16(EPS))
        inv = 1.0 / wh
        numer = jnp.matmul(hb.T, X.astype(jnp.bfloat16) * inv * inv,
                           preferred_element_type=jnp.float32)
        denom = jnp.matmul(hb.T, inv, preferred_element_type=jnp.float32)
    elif beta == 0.0:
        WH = jnp.maximum(H @ W, EPS)
        numer = H.T @ (X / (WH * WH))
        denom = H.T @ (1.0 / WH)
    else:
        WH = jnp.maximum(H @ W, EPS)
        numer = H.T @ (X * WH ** (beta - 2.0))
        denom = H.T @ (WH ** (beta - 1.0))
    return _apply_rate(W, numer, denom, l1, l2, gamma=mu_gamma(beta))


# ---------------------------------------------------------------------------
# Diagonalized Newton (β=1) steps — the 'dna' recipe (arXiv:1301.3389)
# ---------------------------------------------------------------------------

def _kl_row_obj(X, C, W, l1, l2, w_table=None,
                use_pallas: bool = False):
    """Per-row KL objective of candidate usages ``C`` against fixed ``W``,
    up to X-only constants (identical across candidates, so they cancel
    in the lane selection): ``C @ W.sum(1) - Σ_g X log(max(CW, EPS))``
    plus the nmf-torch-convention penalties. Rows of D_KL(X‖CW) decouple
    for fixed W, so the per-row argmin over candidates is exactly the
    objective-minimizing composite — the fallback selection's
    monotonicity proof needs nothing more. ELL inputs evaluate the log
    term on the nonzeros only (zero entries contribute only their WH
    mass, which the linear term carries in full)."""
    lin = C @ W.sum(axis=1)
    if isinstance(X, EllMatrix):
        if use_pallas:
            from .pallas_kl import pallas_wh_at_nz

            wh = pallas_wh_at_nz(X, C, W)
        else:
            wh = ell_wh_at_nz(X, C, W, w_table)
        data = -jnp.sum(X.vals * jnp.log(jnp.maximum(wh, EPS)), axis=-1)
    else:
        data = -jnp.sum(X * jnp.log(jnp.maximum(C @ W, EPS)), axis=-1)
    obj = lin + data
    if l1:
        obj = obj + l1 * jnp.sum(C, axis=-1)
    if l2:
        obj = obj + 0.5 * l2 * jnp.sum(C * C, axis=-1)
    return obj


def _kl_col_obj(X, H, C, l1, l2):
    """Column analog of :func:`_kl_row_obj` for candidate spectra ``C``
    against fixed ``H`` (columns of D_KL(X‖HC) decouple for fixed H)."""
    obj = H.sum(axis=0) @ C \
        - jnp.sum(X * jnp.log(jnp.maximum(H @ C, EPS)), axis=0)
    if l1:
        obj = obj + l1 * jnp.sum(C, axis=0)
    if l2:
        obj = obj + 0.5 * l2 * jnp.sum(C * C, axis=0)
    return obj


def _dna_h_step(X, H, W, l1, l2, w_table=None,
                use_pallas: bool = False):
    """One Diagonalized-Newton KL H step with the per-row monotone MU
    fallback lane (Van hamme, arXiv:1301.3389; ISSUE 9).

    Both candidates are built from one statistics pass: the plain MU
    update, and the diagonal-Newton update
    ``H - grad / hess`` with ``grad = W.sum(1) - (X/WH)Wᵀ (+reg)`` and
    ``hess = (X/WH²)(W∘W)ᵀ (+l2)``, clipped to the nonnegativity
    boundary (EXACT-zero floor: a padded component's grad and hess are
    both exactly 0, so packed K-sweep zero-padding stays absorbing under
    Newton too; if clipping lands somewhere worse, the objective
    comparison below rejects the lane). Each row then keeps
    the candidate with the smaller exact row objective; since rows
    decouple for fixed W and the MU candidate is monotone, the composite
    is monotone non-increasing outright (pinned by test). Strict f32
    (curvature is cancellation-sensitive; the bf16 chain never composes
    with this recipe). Returns ``(H_new, fallback_fraction)``.
    """
    s = W.sum(axis=1)[None, :]
    if isinstance(X, EllMatrix):
        if use_pallas:
            from .pallas_kl import pallas_kl_h_newton_stats

            numer, denom, hess = pallas_kl_h_newton_stats(X, H, W)
        else:
            numer, denom, hess = ell_kl_h_newton_stats(X, H, W, w_table)
    else:
        WH = jnp.maximum(H @ W, EPS)
        ratio = X / WH
        numer = ratio @ W.T
        hess = (ratio / WH) @ (W * W).T
        denom = jnp.broadcast_to(s, H.shape)
    H_mu = _apply_rate(H, numer, denom, l1, l2)
    grad = s - numer + l1 + l2 * H
    H_nt = jnp.maximum(H - grad / jnp.maximum(hess + l2, EPS), 0.0)
    o_nt = _kl_row_obj(X, H_nt, W, l1, l2, w_table, use_pallas)
    o_mu = _kl_row_obj(X, H_mu, W, l1, l2, w_table, use_pallas)
    take_nt = (o_nt < o_mu)[..., None]
    H_new = jnp.where(take_nt, H_nt, H_mu)
    return H_new, 1.0 - jnp.mean(take_nt.astype(jnp.float32))


def _dna_w_step(X, H, W, l1, l2):
    """Per-column Diagonalized-Newton KL W step with the monotone MU
    fallback lane — the transpose of :func:`_dna_h_step` (dense only:
    the ELL batch recipe accelerates the H side and keeps the exact MU
    W step, whose transpose-gather statistics are the expensive half of
    the sparse pass). Returns ``(W_new, fallback_fraction)``."""
    WH = jnp.maximum(H @ W, EPS)
    ratio = X / WH
    numer = H.T @ ratio
    s = H.sum(axis=0)[:, None]
    W_mu = _apply_rate(W, numer, jnp.broadcast_to(s, W.shape), l1, l2)
    hess = (H * H).T @ (ratio / WH)
    grad = s - numer + l1 + l2 * W
    W_nt = jnp.maximum(W - grad / jnp.maximum(hess + l2, EPS), 0.0)
    o_nt = _kl_col_obj(X, H, W_nt, l1, l2)
    o_mu = _kl_col_obj(X, H, W_mu, l1, l2)
    take_nt = (o_nt < o_mu)[None, :]
    W_new = jnp.where(take_nt, W_nt, W_mu)
    return W_new, 1.0 - jnp.mean(take_nt.astype(jnp.float32))


# ---------------------------------------------------------------------------
# batch solver
# ---------------------------------------------------------------------------

def _trace_update(tm: SolverTelemetry, it, err_new, active,
                  inner_add=None, fallback=None):
    """Record one loop step into the telemetry carry: the objective lands
    in its evaluation slot (slot = evaluation ordinal, clamped to the last
    buffer entry), iterations count only while the replicate's own
    stopping test holds, and nonfinite latches on any evaluated inf/NaN.
    Outside an evaluation step the slot write is a value-preserving no-op
    (it writes back the current occupant).

    ``inner_add``: inner update applications this step (accelerated-MU
    repeat count; defaults to 1 when the carry tracks inner iterations).
    ``fallback``: this step's MU-fallback lane fraction (dna recipe).
    Both accumulate only while the lane is active, like ``iters``."""
    evald = it % EVAL_EVERY == 0
    idx = jnp.minimum(it // EVAL_EVERY - 1, TRACE_LEN - 1)
    inner = tm.inner_iters
    if inner is not None:
        add = jnp.int32(1) if inner_add is None else inner_add
        inner = inner + add * active.astype(jnp.int32)
    fb = tm.dna_fallback
    if fb is not None and fallback is not None:
        fb = fb + fallback * active.astype(jnp.float32)
    return SolverTelemetry(
        trace=tm.trace.at[idx].set(jnp.where(evald, err_new, tm.trace[idx])),
        iters=tm.iters + active.astype(jnp.int32),
        nonfinite=tm.nonfinite | (evald & ~jnp.isfinite(err_new)),
        inner_iters=inner, dna_fallback=fb)


def _trace_init(err0, with_inner: bool = False,
                with_fallback: bool = False) -> SolverTelemetry:
    return SolverTelemetry(
        trace=jnp.full((TRACE_LEN,), jnp.nan, jnp.float32),
        iters=jnp.int32(0),
        nonfinite=~jnp.isfinite(err0),
        inner_iters=jnp.int32(0) if with_inner else None,
        dna_fallback=jnp.float32(0.0) if with_fallback else None)


@functools.partial(
    jax.jit,
    static_argnames=("beta", "max_iter", "update_W_flag", "l1_H", "l2_H",
                     "l1_W", "l2_W", "telemetry", "inner_repeats",
                     "kl_newton", "sketch_dim", "sketch_exact_every",
                     "use_pallas"),
)
def nmf_fit_batch(X, H0, W0, beta: float = 2.0, tol: float = 1e-4,
                  max_iter: int = 200, l1_H: float = 0.0, l2_H: float = 0.0,
                  l1_W: float = 0.0, l2_W: float = 0.0,
                  update_W_flag: bool = True, telemetry: bool = False,
                  inner_repeats: int = 1, kl_newton: bool = False,
                  sketch_dim: int = 0, sketch_exact_every: int = 1,
                  use_pallas: bool = False):
    """Alternating MU until the relative objective decrease over an
    ``EVAL_EVERY``-iteration window falls below ``tol`` (sklearn-style
    criterion) or ``max_iter``. Returns ``(H, W, err)``.

    vmap-safe: under ``vmap`` the loop runs until every replicate in the
    batch converges (extra MU steps are monotone, hence harmless).

    ``telemetry`` (STATIC; default off adds zero ops): additionally
    returns a :class:`SolverTelemetry` — the objective trace at every
    ``EVAL_EVERY`` evaluation, the iteration count the replicate's own
    stopping test kept it active, a nonfinite flag, plus the recipe
    accounting (total inner updates; dna fallback-lane fraction).

    Iteration-count acceleration (ISSUE 9; both STATIC — the default
    ``(1, False)`` program is byte-identical to a build without them):

    ``inner_repeats`` (ρ > 1, the 'amu' recipe, arXiv:1107.5194): each
    outer iteration runs up to ρ H sub-iterations against loop-invariant
    W products — β=2 hoists the ``XWᵀ``/``WWᵀ`` statistics so repeats
    are k-sized; ELL β∈{1,0} pre-gathers the W slab table once per outer
    step — with a per-lane early exit once the repeat's relative H
    change stagnates below ``INNER_STAG_TOL``.

    ``kl_newton`` (β=1 only, the 'dna' recipe, arXiv:1301.3389): H and W
    take diagonal-Newton steps with per-row/per-column monotone MU
    fallback lanes (:func:`_dna_h_step` / :func:`_dna_w_step`; ELL
    inputs accelerate the H side and keep the exact MU W step). Measured
    4–6× fewer outer iterations to a fixed KL tolerance on the bench
    fixtures (``bench.py --tier accel``).

    ``sketch_dim``/``sketch_exact_every`` (β=1 only — the 'sketch'
    recipe, ISSUE 11, arXiv:1604.04026; both STATIC, the default
    ``(0, 1)`` program is byte-identical to a build without them): the
    H updates stay exact, while each W update runs from a fresh
    ``sketch_dim``-row subsample of X (seeded per-iteration threefry
    stream shared across vmapped replicates so the gather indices — and
    the X row gather — are batch-invariant), with the EXACT full-data
    W update at iteration 0 and every ``sketch_exact_every``-th
    iteration to control subsampling bias. Numerator and denominator
    come from the same subsample, so the MU rate's n/m scale cancels;
    the objective evaluations (and the stopping rule) stay exact.

    ``use_pallas`` (STATIC; default ``False`` is byte-identical): ELL
    β=1 statistics and objective evaluate through the fused Pallas
    kernels (``ops/pallas_kl.py``, CNMF_TPU_PALLAS) — the kernels
    re-gather their slab table in VMEM per tile, so the host-side
    ``ell_w_table`` hoist is skipped. Defined only for the ELL KL lane;
    anything else (dense, β≠1, the sketch recipe's scatter) quietly
    keeps the jnp path.
    """
    inner_repeats = int(inner_repeats)
    sketch_dim = int(sketch_dim)
    use_pallas = (bool(use_pallas) and isinstance(X, EllMatrix)
                  and beta == 1.0 and not sketch_dim)
    if kl_newton and beta != 1.0:
        raise ValueError(
            f"kl_newton is the beta=1 (KL) Newton recipe, got beta={beta}")
    if kl_newton and inner_repeats != 1:
        raise ValueError("kl_newton and inner_repeats>1 are exclusive "
                         "recipes (dna vs amu)")
    if sketch_dim:
        if beta != 1.0:
            raise ValueError(
                f"sketch_dim is the beta=1 (KL) sketch recipe's knob, "
                f"got beta={beta}")
        if kl_newton or inner_repeats != 1:
            raise ValueError("the sketch recipe is exclusive with the "
                             "dna/amu recipes")
        n_total = int(X.vals.shape[0] if isinstance(X, EllMatrix)
                      else X.shape[0])
        sketch_dim = min(sketch_dim, n_total)
    err0 = beta_divergence(X, H0, W0, beta=beta, use_pallas=use_pallas)

    # accelerated recipes on ELL input share ONE pre-gathered W slab
    # table per outer iteration (H sub-iterations, newton stats, both dna
    # candidate objectives, AND the W update — W only changes after
    # w_step, so the table stays valid throughout); the identity recipe
    # keeps the table-free calls so its program stays byte-identical to a
    # pre-recipe-layer build
    accel = kl_newton or inner_repeats > 1

    def h_step(H, W, table):
        """One recipe H step: ``(H_new, inner_count, fallback | None)``."""
        if kl_newton:
            H_new, fb = _dna_h_step(X, H, W, l1_H, l2_H, w_table=table,
                                    use_pallas=use_pallas)
            return H_new, jnp.int32(1), fb
        if inner_repeats <= 1:
            return (_update_H(X, H, W, beta, l1_H, l2_H,
                              use_pallas=use_pallas),
                    jnp.int32(1), None)
        # accelerated MU: hoist the loop-invariant W products out of the
        # repeat loop (this is where the per-repeat cost collapses);
        # under the Pallas kernels the hoist is the kernel's own VMEM
        # slab gather — the repeats re-enter it with W still on-chip
        if isinstance(X, EllMatrix):
            def one(h):
                return _update_H(X, h, W, beta, l1_H, l2_H,
                                 w_table=table, use_pallas=use_pallas)
        elif beta == 2.0:
            numer0 = X @ W.T
            WWT = W @ W.T

            def one(h):
                return _apply_rate(h, numer0, h @ WWT, l1_H, l2_H)
        else:
            def one(h):
                return _update_H(X, h, W, beta, l1_H, l2_H)

        def rbody(c):
            h, _, i = c
            h_new = one(h)
            rel = jnp.linalg.norm(h_new - h) / (jnp.linalg.norm(h) + EPS)
            return (h_new, rel, i + 1)

        def rcond(c):
            return (c[2] < inner_repeats) & (c[1] >= INNER_STAG_TOL)

        rel0 = jnp.inf + 0.0 * jnp.sum(H)
        H_new, _, cnt = jax.lax.while_loop(rcond, rbody,
                                           (H, rel0, jnp.int32(0)))
        return H_new, cnt, None

    def w_step(H, W, table, it):
        if not update_W_flag:
            return W, None
        if sketch_dim:
            # sketched KL W update (ISSUE 11): lax.cond so only the
            # engaged branch executes — the exact interleave (iteration
            # 0, every E-th, AND the iteration feeding each objective
            # evaluation) anchors the trajectory, the sketched branch
            # does O(m/n) of the statistics work. Anchoring the eval
            # iterations matters for the stopping rule: the relative-
            # decrease window must compare exactly-updated states, or
            # subsample noise reads as convergence and stops the solve
            # tens of iterations early (measured on the sparse fixture)
            def _exact(_):
                return _update_W(X, H, W, beta, l1_W, l2_W,
                                 use_pallas=use_pallas)

            def _sketched(_):
                idx = jax.random.randint(
                    jax.random.fold_in(jax.random.key(0), it),
                    (sketch_dim,), 0, n_total)
                if isinstance(X, EllMatrix):
                    numer, denom = ell_kl_w_stats_rows(X, H, W, idx)
                else:
                    Xs = jnp.take(X, idx, axis=0)
                    Hs = jnp.take(H, idx, axis=0)
                    WHs = jnp.maximum(Hs @ W, EPS)
                    numer = Hs.T @ (Xs / WHs)
                    denom = jnp.broadcast_to(Hs.sum(axis=0)[:, None],
                                             W.shape)
                # penalties scale with the sampled fraction: the m/n-
                # scaled statistics against the FULL l1/l2 would over-
                # regularize by ~n/m (and an l1 larger than a sampled
                # numerator would kill entries the evidence guard
                # protects); scaling both by m/n leaves the MU rate an
                # unbiased estimate of the exact regularized rate
                sc = sketch_dim / n_total
                return _apply_rate_sketched(W, numer, denom,
                                            l1_W * sc, l2_W * sc)

            exact_now = ((it % max(sketch_exact_every, 1) == 0)
                         | ((it + 1) % EVAL_EVERY == 0))
            return jax.lax.cond(exact_now, _exact, _sketched,
                                operand=None), None
        if kl_newton and not isinstance(X, EllMatrix):
            return _dna_w_step(X, H, W, l1_W, l2_W)
        if table is not None:
            return _update_W(X, H, W, beta, l1_W, l2_W,
                             w_table=table), None
        return _update_W(X, H, W, beta, l1_W, l2_W,
                         use_pallas=use_pallas), None

    def active_of(err_prev, err, it):
        not_converged = (err_prev - err) / jnp.maximum(err0, EPS) >= tol
        # before the first evaluation window, err_prev == err0 keeps us going
        return (it < max_iter) & (not_converged | (it < EVAL_EVERY))

    def body(carry):
        if telemetry:
            H, W, err_prev, err, it, tm, act = carry
            # LATCHED per-lane activity: under vmap the batched loop keeps
            # stepping converged lanes (their err/err_prev keep moving), so
            # a plateau-then-escape lane could re-satisfy the progress test
            # later — the latch pins iters at the lane's FIRST stop
            act = act & active_of(err_prev, err, it)
        else:
            H, W, err_prev, err, it = carry
        table = (ell_w_table(W, X.cols)
                 if accel and isinstance(X, EllMatrix) and not use_pallas
                 else None)
        H, inner_n, fb_h = h_step(H, W, table)
        W, fb_w = w_step(H, W, table, it)
        if fb_h is not None and fb_w is not None:
            fb = 0.5 * (fb_h + fb_w)
        else:
            fb = fb_h
        it = it + 1

        def with_err(_):
            return beta_divergence(X, H, W, beta=beta,
                                   use_pallas=use_pallas)

        err_new = jax.lax.cond(it % EVAL_EVERY == 0, with_err,
                               lambda _: err, operand=None)
        err_prev = jnp.where(it % EVAL_EVERY == 0, err, err_prev)
        if telemetry:
            return (H, W, err_prev, err_new, it,
                    _trace_update(tm, it, err_new, act,
                                  inner_add=inner_n, fallback=fb), act)
        return (H, W, err_prev, err_new, it)

    def cond(carry):
        return active_of(carry[2], carry[3], carry[4])

    init = (H0, W0, err0, err0, jnp.int32(0))
    if telemetry:
        # inner accounting only when an accelerated recipe is engaged:
        # the identity (plain-MU) program must stay byte-identical to a
        # pre-recipe-layer build even with telemetry on (inner == iters
        # by construction there, so nothing is lost)
        init = init + (_trace_init(err0,
                                   with_inner=(inner_repeats > 1
                                               or kl_newton),
                                   with_fallback=kl_newton),
                       jnp.bool_(True))
    out = jax.lax.while_loop(cond, body, init)
    H, W = out[0], out[1]
    err = beta_divergence(X, H, W, beta=beta)
    if telemetry:
        tm = out[5]
        if kl_newton:
            # per-step fractions accumulated while active -> mean fraction
            tm = tm._replace(dna_fallback=tm.dna_fallback / jnp.maximum(
                tm.iters.astype(jnp.float32), 1.0))
        return H, W, err, tm._replace(
            nonfinite=tm.nonfinite | ~jnp.isfinite(err))
    return H, W, err


# ---------------------------------------------------------------------------
# HALS solver (beta=2) — nmf-torch's second solver family ('halsvar')
# ---------------------------------------------------------------------------

def _hals_sweep(M, G, C, l1, l2):
    """One HALS sweep over the k columns of M against Gram G and target C:
    ``M[:, j] <- max((C[:, j] - M G[:, j] + G[j, j] M[:, j] - l1) /
    (G[j, j] + l2), 0)`` — the closed-form ridge column solve with the
    other components fixed (numer excludes component j's own contribution,
    so L2 shrinks toward zero). The ONE definition behind every HALS
    update: H directly ((n, k) against WW^T and XW^T), and W via transpose
    ((g, k) against H^T H and (H^T X)^T) — G is symmetric."""
    k = M.shape[1]

    def upd(j, M):
        numer = C[:, j] - M @ G[:, j] + G[j, j] * M[:, j] - l1
        denom = G[j, j] + l2 + EPS
        return M.at[:, j].set(jnp.maximum(numer / denom, 0.0))

    return jax.lax.fori_loop(0, k, upd, M)


@functools.partial(
    jax.jit,
    static_argnames=("max_iter", "l1_H", "l2_H", "l1_W", "l2_W",
                     "telemetry"),
)
def nmf_fit_batch_hals(X, H0, W0, tol: float = 1e-4, max_iter: int = 200,
                       l1_H: float = 0.0, l2_H: float = 0.0,
                       l1_W: float = 0.0, l2_W: float = 0.0,
                       telemetry: bool = False):
    """Hierarchical ALS (Cichocki & Phan 2009) for the Frobenius objective —
    the TPU equivalent of nmf-torch's ``algo='halsvar'`` solver family
    (upstream ships MU + HALS + NNLS-BPP; the reference pipeline only ever
    requests 'mu', cnmf.py:764, so this extends coverage beyond the observed
    contract). Dispatched as the ``hals`` solver recipe by the replicate
    sweeps (ISSUE 9; sklearn-CD parity pinned by test) in addition to
    ``run_nmf(algo='halsvar')``.

    Per sweep each component is updated in closed form against the others:

        H[:, j] <- max(H[:, j] + (XW^T - H WW^T)[:, j] / WW^T[j, j], 0)

    and symmetrically for W rows. All data passes are the same k-sized
    sufficient statistics as the MU path (XW^T, WW^T, H^T X, H^T H); the
    per-component sweep is a ``fori_loop`` over k — scalar-indexed column
    updates on (n, k)/(k, g) arrays, cheap next to the statistics matmuls.
    Regularization follows the same split convention as the MU path: L1
    subtracts from the update numerator, L2 adds to the denominator.
    Stopping matches ``nmf_fit_batch`` (relative objective decrease over an
    ``EVAL_EVERY`` window). Returns ``(H, W, err)``; with ``telemetry``
    (STATIC; default off adds zero ops) additionally a
    :class:`SolverTelemetry`, vmap-latched exactly like
    :func:`nmf_fit_batch`'s (one HALS sweep counts as one inner update).
    """
    k = H0.shape[1]

    def sweep_H(H, W):
        return _hals_sweep(H, W @ W.T, X @ W.T, l1_H, l2_H)

    def sweep_W(H, W):
        return _hals_sweep(W.T, H.T @ H, (H.T @ X).T, l1_W, l2_W).T

    err0 = beta_divergence(X, H0, W0, beta=2.0)

    def active_of(err_prev, err, it):
        not_conv = (err_prev - err) / jnp.maximum(err0, EPS) >= tol
        return (it < max_iter) & (not_conv | (it < EVAL_EVERY))

    def body(carry):
        if telemetry:
            H, W, err_prev, err, it, tm, act = carry
            act = act & active_of(err_prev, err, it)
        else:
            H, W, err_prev, err, it = carry
        H = sweep_H(H, W)
        W = sweep_W(H, W)
        it = it + 1
        err_new = jax.lax.cond(
            it % EVAL_EVERY == 0,
            lambda _: beta_divergence(X, H, W, beta=2.0),
            lambda _: err, operand=None)
        err_prev = jnp.where(it % EVAL_EVERY == 0, err, err_prev)
        if telemetry:
            return (H, W, err_prev, err_new, it,
                    _trace_update(tm, it, err_new, act), act)
        return (H, W, err_prev, err_new, it)

    def cond(carry):
        return active_of(carry[2], carry[3], carry[4])

    init = (H0, W0, err0, err0, jnp.int32(0))
    if telemetry:
        init = init + (_trace_init(err0, with_inner=True), jnp.bool_(True))
    out = jax.lax.while_loop(cond, body, init)
    H, W = out[0], out[1]
    err = beta_divergence(X, H, W, beta=2.0)
    if telemetry:
        tm = out[5]
        return H, W, err, tm._replace(
            nonfinite=tm.nonfinite | ~jnp.isfinite(err))
    return H, W, err


# ---------------------------------------------------------------------------
# bundle-packed replicate batch solver (beta=2)
# ---------------------------------------------------------------------------

def bundle_width(k: int) -> int:
    """Replicates per bundle for the packed beta=2 updates: as many k-wide
    factor blocks as fit one 128-lane MXU tile. A k=9 replicate sweep runs
    its heavy contractions at width 126 instead of 9 — measured on v5e this
    lifts the fixed-iteration MU probe from 0.18 to 0.38 MFU, with
    bit-identical single update steps at production shapes (the masked-away
    cross-replicate terms contribute exact +0.0 to every accumulation;
    across a full solve XLA's shape-dependent contraction tilings leave
    ~1e-6-relative fp differences, pinned by test)."""
    return max(1, 128 // int(k))


def _bundle_mask(per_b: int, k: int):
    """(per_b*k, per_b*k) block-diagonal 0/1 mask: bundle Gram matrices are
    computed at full width and the cross-replicate blocks masked to zero."""
    eye = jnp.eye(per_b, dtype=jnp.float32)
    return jnp.repeat(jnp.repeat(eye, k, axis=0), k, axis=1)


def bundle_stacks(H, W, per_b: int):
    """(R,n,k), (R,k,g) -> (B, n, per_b*k), (B, per_b*k, g); R pads to a
    bundle multiple by tiling existing replicates (padded lanes recompute
    real replicates and are sliced away by :func:`unbundle_stacks`)."""
    R, n, k = H.shape
    g = W.shape[2]
    R_b = -(-R // per_b) * per_b
    if R_b > R:
        idx = jnp.concatenate([jnp.arange(R), jnp.arange(R_b - R) % R])
        H, W = H[idx], W[idx]
    B = R_b // per_b
    Hb = H.reshape(B, per_b, n, k).transpose(0, 2, 1, 3).reshape(
        B, n, per_b * k)
    Wb = W.reshape(B, per_b * k, g)
    return Hb, Wb


def unbundle_stacks(Hb, Wb, R: int, k: int):
    """Inverse of :func:`bundle_stacks` (pure permutation — values exact)."""
    B, n, w = Hb.shape
    per_b = w // k
    g = Wb.shape[2]
    H = Hb.reshape(B, n, per_b, k).transpose(0, 2, 1, 3).reshape(
        B * per_b, n, k)
    return H[:R], Wb.reshape(B * per_b, k, g)[:R]


def bundled_beta2_update(X, Hb, Wb, mask, l1_H: float, l2_H: float,
                         l1_W: float, l2_W: float):
    """One alternating MU step for ALL bundled replicates — the packed form
    of ``_update_H`` + ``_update_W`` at beta=2. Every heavy contraction is
    ~128 wide: the numerators are single (n,g)x(g,w)-class matmuls, the
    denominators go through masked bundle Grams whose cross-replicate terms
    are exactly zero — one packed step reproduces the per-replicate updates
    bit-for-bit at production shapes (tests pin tight element-wise
    agreement across full solves, where XLA tiling differs)."""
    numer = jnp.einsum("ng,bwg->bnw", X, Wb)
    GW = jnp.einsum("bvg,bwg->bvw", Wb, Wb) * mask
    denom = jnp.einsum("bnv,bvw->bnw", Hb, GW)
    Hb = _apply_rate(Hb, numer, denom, l1_H, l2_H)
    numer2 = jnp.einsum("bnw,ng->bwg", Hb, X)
    GH = jnp.einsum("bnv,bnw->bvw", Hb, Hb) * mask
    denom2 = jnp.einsum("bvw,bwg->bvg", GH, Wb)
    Wb = _apply_rate(Wb, numer2, denom2, l1_W, l2_W)
    return Hb, Wb


@functools.partial(
    jax.jit,
    static_argnames=("max_iter", "l1_H", "l2_H", "l1_W", "l2_W", "telemetry"),
)
def nmf_fit_batch_bundled(X, H0, W0, tol: float = 1e-4,
                          max_iter: int = 200, l1_H: float = 0.0,
                          l2_H: float = 0.0, l1_W: float = 0.0,
                          l2_W: float = 0.0, telemetry: bool = False):
    """R-replicate beta=2 batch MU with bundle-packed contractions.

    Drop-in for ``jax.vmap(nmf_fit_batch)`` over stacked ``(H0 (R,n,k),
    W0 (R,k,g))`` — same stopping rule (relative objective decrease over an
    ``EVAL_EVERY`` window, per replicate, converged replicates frozen by
    selects exactly as vmap's batched while_loop does). Agreement with the
    vmapped solver is pinned to ~1e-5 relative by test (bit-identical per
    update step at production shapes). Returns ``(H (R,n,k), W (R,k,g),
    errs (R,))``.

    ``telemetry`` (STATIC; default off adds zero ops): additionally
    returns a replicate-stacked :class:`SolverTelemetry` (trace
    (R, TRACE_LEN), iters (R,), nonfinite (R,)) — the packed analog of
    ``vmap(nmf_fit_batch, telemetry=True)``. The per-replicate ``act``
    mask the freeze logic already maintains IS the per-replicate active
    flag, so iters are exact per replicate (not the batch max).
    """
    R, _, k = H0.shape
    per_b = bundle_width(k)
    Hb, Wb = bundle_stacks(H0, W0, per_b)
    B = Hb.shape[0]
    R_b = B * per_b
    mask = _bundle_mask(per_b, k)

    def errs_of(Hb, Wb):
        H, W = unbundle_stacks(Hb, Wb, R_b, k)
        return jax.vmap(lambda h, w: beta_divergence(X, h, w, beta=2.0))(H, W)

    err0 = errs_of(Hb, Wb)

    def active_of(err_prev, err, it):
        not_conv = (err_prev - err) / jnp.maximum(err0, EPS) >= tol
        return (it < max_iter) & (not_conv | (it < EVAL_EVERY))

    def body(carry):
        if telemetry:
            Hb, Wb, err_prev, err, it, tm = carry
        else:
            Hb, Wb, err_prev, err, it = carry
        act = active_of(err_prev, err, it)              # (R_b,)
        Hb_n, Wb_n = bundled_beta2_update(X, Hb, Wb, mask,
                                          l1_H, l2_H, l1_W, l2_W)
        colmask = jnp.repeat(act.reshape(B, per_b), k, axis=1)  # (B, w)
        Hb = jnp.where(colmask[:, None, :], Hb_n, Hb)
        Wb = jnp.where(colmask[:, :, None], Wb_n, Wb)
        it = it + 1

        def with_err(_):
            return errs_of(Hb, Wb)

        err_new = jax.lax.cond(it % EVAL_EVERY == 0, with_err,
                               lambda _: err, operand=None)
        err_new = jnp.where(act, err_new, err)
        err_prev = jnp.where((it % EVAL_EVERY == 0) & act, err, err_prev)
        if telemetry:
            return (Hb, Wb, err_prev, err_new, it,
                    _trace_update(tm, it, err_new, act))
        return (Hb, Wb, err_prev, err_new, it)

    def cond(carry):
        return jnp.any(active_of(carry[2], carry[3], carry[4]))

    init = (Hb, Wb, err0, err0, jnp.int32(0))
    if telemetry:
        # per-replicate telemetry: trace (TRACE_LEN, R_b) so the shared
        # slot-write helper applies row-wise; transposed to the vmap
        # convention (R, TRACE_LEN) on exit
        init = init + (SolverTelemetry(
            trace=jnp.full((TRACE_LEN, R_b), jnp.nan, jnp.float32),
            iters=jnp.zeros((R_b,), jnp.int32),
            nonfinite=~jnp.isfinite(err0)),)
    out = jax.lax.while_loop(cond, body, init)
    Hb, Wb = out[0], out[1]
    errs = errs_of(Hb, Wb)
    H, W = unbundle_stacks(Hb, Wb, R_b, k)
    if telemetry:
        tm = out[5]
        return H[:R], W[:R], errs[:R], SolverTelemetry(
            trace=tm.trace.T[:R], iters=tm.iters[:R],
            nonfinite=(tm.nonfinite | ~jnp.isfinite(errs))[:R])
    return H[:R], W[:R], errs[:R]


# ---------------------------------------------------------------------------
# online (streamed row-chunk) solver
# ---------------------------------------------------------------------------

def _solve_w_from_stats(W, A, B, l1_W, l2_W, max_iter, tol):
    """Solve the (convex) W-subproblem by MU from the sufficient statistics
    A = H^T X, B = H^T H alone — k x k / k x g work, no data pass. Shared by
    the online solver's per-pass W update and the row-sharded solver (where
    A and B arrive psum'd over shards)."""
    def w_body(carry):
        W, _, it = carry
        W_new = _apply_rate(W, A, B @ W, l1_W, l2_W)
        rel = jnp.linalg.norm(W_new - W) / (jnp.linalg.norm(W) + EPS)
        return (W_new, rel, it + 1)

    def w_cond(carry):
        _, rel, it = carry
        return (it < max_iter) & (rel >= tol)

    rel0 = jnp.inf + 0.0 * jnp.sum(W)
    W, _, _ = jax.lax.while_loop(w_cond, w_body, (W, rel0, jnp.int32(0)))
    return W


def _solve_w_from_stats_hals(W, A, B, l1_W, l2_W, max_iter, tol):
    """HALS analog of :func:`_solve_w_from_stats`: row sweeps of W from the
    accumulated pass statistics A = H^T X, B = H^T H alone, stopping on the
    same relative-change criterion."""
    def w_body(carry):
        W, _, it = carry
        W_new = _hals_sweep(W.T, B, A.T, l1_W, l2_W).T
        rel = jnp.linalg.norm(W_new - W) / (jnp.linalg.norm(W) + EPS)
        return (W_new, rel, it + 1)

    def w_cond(carry):
        _, rel, it = carry
        return (it < max_iter) & (rel >= tol)

    rel0 = jnp.inf + 0.0 * jnp.sum(W)
    W, _, _ = jax.lax.while_loop(w_cond, w_body, (W, rel0, jnp.int32(0)))
    return W


def _chunk_h_hals_solve(x, h, W, WWT, l1, l2, max_iter, h_tol):
    """HALS analog of :func:`_chunk_h_solve` (Frobenius only): column
    sweeps of one chunk's usage block with W fixed, until the block's
    relative Frobenius change drops below ``h_tol`` or ``max_iter``."""
    XWt = x @ W.T

    def body(carry):
        h, _, it = carry
        h_new = _hals_sweep(h, WWT, XWt, l1, l2)
        rel = jnp.linalg.norm(h_new - h) / (jnp.linalg.norm(h) + EPS)
        return (h_new, rel, it + 1)

    def cond(carry):
        _, rel, it = carry
        return (it < max_iter) & (rel >= h_tol)

    rel0 = jnp.inf + 0.0 * jnp.sum(h)
    h, _, _ = jax.lax.while_loop(cond, body, (h, rel0, jnp.int32(0)))
    return h


def _chunk_h_solve(x, h, W, WWT, beta, l1, l2, max_iter, h_tol,
                   bf16_ratio: bool = False, w_table=None,
                   kl_newton: bool = False, return_resid: bool = False,
                   w_colsum=None, use_pallas: bool = False):
    """Inner MU loop on one chunk's usage block with W fixed.

    Semantics of ``fit_H_online``'s per-chunk loop (cnmf.py:350-381):
    iterate until the relative Frobenius change of the block drops below
    ``h_tol`` or ``max_iter``; for beta=2 the numerator ``x @ W.T`` is
    precomputed once per chunk. ``bf16_ratio`` (beta in {1, 0}) stores the
    chunk and the WH/ratio intermediates in bf16 — cast once here, outside
    the while_loop (see ``_update_H``).

    ELL chunks additionally pre-gather the W slab table ONCE (W is fixed
    for the whole inner loop), so every inner iteration is pure
    contiguous slab arithmetic — the lever behind the measured 2x+ over
    the dense chain at single-cell sparsity (``ops/sparse.py``).

    ``kl_newton`` (STATIC; β=1 only — the 'dna' recipe, ISSUE 9): each
    inner step is a diagonal-Newton H step with the per-row monotone MU
    fallback lane (:func:`_dna_h_step`) instead of plain MU — fewer
    inner iterations to the same block tolerance. Strict f32 (callers
    force the bf16 ratio chain off for this recipe).

    The 'sketch' recipe (ISSUE 11) deliberately leaves this solver
    EXACT: every cell's usage block must be solved anyway (H has a row
    per cell), so the compressible work is the W statistics the
    callers' W steps compute — see ``nmf_fit_batch``/``nmf_fit_online``
    and ``parallel/rowshard.py:_rowsharded_pass``.
    """
    use_pallas = bool(use_pallas and isinstance(x, EllMatrix)
                      and beta == 1.0)
    if kl_newton and beta == 1.0:
        if isinstance(x, EllMatrix) and w_table is None and not use_pallas:
            w_table = ell_w_table(W, x.cols)

        def step(h):
            h_new, _ = _dna_h_step(x, h, W, l1, l2, w_table=w_table,
                                   use_pallas=use_pallas)
            return h_new
    elif beta == 2.0:
        numer0 = x @ W.T
        numer0 = jnp.maximum(numer0 - l1, 0.0) if l1 else numer0

        def step(h):
            denom = h @ WWT
            denom = denom + l2 * h if l2 else denom
            rate = jnp.where(denom < EPS, 0.0, numer0 / jnp.maximum(denom, EPS))
            return h * rate
    else:
        bf16 = bool(bf16_ratio) and beta in (1.0, 0.0)
        x_cast = x.astype(jnp.bfloat16) if bf16 else x
        if isinstance(x, EllMatrix) and w_table is None and not use_pallas:
            w_table = ell_w_table(W, x.cols, bf16=bf16)

        def step(h):
            return _update_H(x_cast, h, W, beta, l1, l2, bf16_ratio=bf16,
                             w_table=w_table, w_colsum=w_colsum,
                             use_pallas=use_pallas)

    def body(carry):
        h, _, it = carry
        h_new = step(h)
        rel = jnp.linalg.norm(h_new - h) / (jnp.linalg.norm(h) + EPS)
        return (h_new, rel, it + 1)

    def cond(carry):
        _, rel, it = carry
        return (it < max_iter) & (rel >= h_tol)

    # the initial `rel` is derived from h (not a literal) so its
    # varying-manual-axes type matches the loop body's under shard_map,
    # where h is device-varying; XLA folds the dead dependence otherwise
    rel0 = jnp.inf + 0.0 * jnp.sum(h)
    h, rel, _ = jax.lax.while_loop(cond, body, (h, rel0, jnp.int32(0)))
    if return_resid:
        # the last relative-change residual doubles as a per-chunk health
        # signal (ISSUE 12 serving): a nonfinite chunk stops its loop on
        # the first NaN comparison, leaving rel nonfinite — graded on host
        # by ops.nmf.lane_health with zero extra device ops
        return h, rel
    return h


@functools.partial(
    jax.jit,
    static_argnames=("beta", "chunk_max_iter", "n_passes", "l1_H", "l2_H",
                     "l1_W", "l2_W", "h_tol_start", "algo", "bf16_ratio",
                     "telemetry", "kl_newton", "sketch_dim",
                     "sketch_exact_every", "use_pallas"),
)
def nmf_fit_online(Xc, Hc0, W0, beta: float = 2.0, tol: float = 1e-4,
                   h_tol: float = 1e-3, chunk_max_iter: int = 1000,
                   n_passes: int = 20, l1_H: float = 0.0, l2_H: float = 0.0,
                   l1_W: float = 0.0, l2_W: float = 0.0,
                   h_tol_start: float | None = None, algo: str = "mu",
                   bf16_ratio: bool = False, telemetry: bool = False,
                   kl_newton: bool = False, sketch_dim: int = 0,
                   sketch_exact_every: int = 1,
                   use_pallas: bool = False):
    """Streamed MU over pre-chunked inputs.

    ``Xc``: (n_chunks, chunk, genes) row-chunked data (zero-padded rows are
    benign: their usage rows collapse to zero in one MU step and contribute
    nothing to the W statistics). ``Hc0``: (n_chunks, chunk, k).

    Each pass scans the chunks: the chunk's usage block is solved by the
    inner MU loop (W fixed), and the W-update sufficient statistics
    accumulate; W takes one MU step per pass from the accumulated
    statistics. Passes stop on relative objective decrease < ``tol``
    (mirrors the ledger's online contract, cnmf.py:765-767, with the pass
    loop playing nmf-torch's ``max_pass`` role). Returns ``(Hc, W, err)``.

    ``algo='halsvar'`` (beta=2 only — nmf-torch's online HALS family)
    swaps the inner chunk-usage solver and the per-pass W solve for HALS
    column/row sweeps over the SAME accumulated (A, B) statistics; the
    pass loop, coarse-to-fine tolerance schedule, and stopping rule are
    shared with the MU path.

    ``bf16_ratio`` (beta in {1, 0}): store X chunks and the WH/ratio
    intermediates in bf16 with f32 matmul accumulation — halves the
    HBM-roofline traffic that bounds these chains (measured 1.78x for KL,
    2.09x for IS on v5e; see ``_update_H``). Factor state, W sums, and
    the objective evaluation stay f32, so the stopping rule's semantics
    are unchanged.

    ``telemetry`` (STATIC; default off adds zero ops): additionally
    returns a :class:`SolverTelemetry` whose trace holds one objective
    per PASS (the pass loop is this solver's convergence loop; its caps
    resolve to <= 60 <= TRACE_LEN) and whose ``iters`` counts passes.

    ``kl_newton`` (STATIC; β=1 only — the 'dna' recipe, ISSUE 9): the
    per-chunk usage solves run diagonal-Newton steps with the monotone
    MU fallback lane; the per-chunk W step stays MU. Forces the bf16
    ratio chain off (DNA's curvature is cancellation-sensitive).

    ``sketch_dim``/``sketch_exact_every`` (STATIC; β=1 only — the
    'sketch' recipe, ISSUE 11): the per-chunk usage solves stay exact,
    while each chunk's W step runs from a ``sketch_dim``-row subsample
    of that chunk (per-(pass, chunk) seeded indices); every
    ``sketch_exact_every``-th PASS (and the first) runs exact chunk W
    steps. Per-chunk objectives — the pass stopping rule — stay exact.
    Strict f32 (the bf16 ratio chain is forced off like dna's).
    """
    if kl_newton and beta != 1.0:
        raise ValueError(
            f"kl_newton is the beta=1 (KL) Newton recipe, got beta={beta}")
    sketch_dim = int(sketch_dim)
    if sketch_dim:
        if beta != 1.0:
            raise ValueError(
                f"sketch_dim is the beta=1 (KL) sketch recipe's knob, "
                f"got beta={beta}")
        if kl_newton:
            raise ValueError("the sketch recipe is exclusive with dna")
    bf16_ratio = (bool(bf16_ratio) and beta in (1.0, 0.0)
                  and not kl_newton and not sketch_dim)
    # fused Pallas kernels (STATIC; ELL beta=1 only — the sketch lane's
    # sampled-row scatter and every dense/IS chunk keep the jnp path)
    use_pallas = (bool(use_pallas) and isinstance(Xc, EllMatrix)
                  and beta == 1.0 and not sketch_dim)
    if algo not in ("mu", "halsvar"):
        raise ValueError(f"unknown online algo {algo!r}")
    if algo == "halsvar" and beta != 2.0:
        raise ValueError("algo='halsvar' optimizes the Frobenius objective")
    k = W0.shape[0]
    g = W0.shape[1]

    def one_pass(carry, p):
        Hc, W, err_prev = carry
        # coarse-to-fine inner tolerance: early passes solve usage blocks
        # loosely (W is far from converged; polishing against it wastes the
        # pass budget — measured WORSE final objectives with constant-tight
        # inner solves), halving per pass down to the configured floor, so
        # late passes still reach full block-coordinate polish
        if h_tol_start is None:
            h_tol_p = jnp.float32(h_tol)
        else:
            h_tol_p = jnp.maximum(jnp.float32(h_tol),
                                  h_tol_start * 0.5 ** p.astype(jnp.float32))

        if beta == 2.0:
            # block coordinate descent: solve every usage block tightly with
            # W frozen while accumulating the exact pass statistics
            # A = H^T X, B = H^T H, then solve the (convex) W-subproblem
            # from (A, B) alone — k x k / k x g work, no second data pass.
            WWT = W @ W.T

            def scan_chunk(acc, xc_hc):
                A, B, err_acc = acc
                x, h = xc_hc
                if algo == "halsvar":
                    h = _chunk_h_hals_solve(x, h, W, WWT, l1_H, l2_H,
                                            chunk_max_iter, h_tol_p)
                else:
                    h = _chunk_h_solve(x, h, W, WWT, beta, l1_H, l2_H,
                                       chunk_max_iter, h_tol_p)
                A = A + h.T @ x
                B = B + h.T @ h
                err_c = beta_divergence(x, h, W, beta=2.0)
                return (A, B, err_acc + err_c), h

            acc0 = (jnp.zeros((k, g), Xc.dtype), jnp.zeros((k, k), Xc.dtype),
                    jnp.float32(0.0))
            (A, B, err), Hc = jax.lax.scan(scan_chunk, acc0, (Xc, Hc))
            w_solve = (_solve_w_from_stats_hals if algo == "halsvar"
                       else _solve_w_from_stats)
            W = w_solve(W, A, B, l1_W, l2_W, chunk_max_iter, h_tol_p)
        else:
            # true online flavor for the non-quadratic losses: each chunk's
            # usage block is solved with W frozen, then W takes one
            # stochastic MU step from that chunk's own statistics (the
            # statistics are W-dependent for beta != 2, so cross-chunk
            # accumulation would mix inconsistent (h, W) pairs).
            def scan_chunk(carry, xc_hc):
                W, err_acc = carry
                x, h = xc_hc
                if isinstance(x, EllMatrix):
                    # sparse chunk: the W slab table is shared by the
                    # whole inner solve AND the chunk's W step (W only
                    # moves after both); objective stays f32 nonzero-only
                    # (the pass stopping rule keeps production precision
                    # even when the update chain runs bf16). Under the
                    # fused kernels the table lives in VMEM inside each
                    # kernel instead — no host-side gather to share.
                    table = (None if use_pallas
                             else ell_w_table(W, x.cols, bf16=bf16_ratio))
                    h = _chunk_h_solve(x, h, W, None, beta, l1_H, l2_H,
                                       chunk_max_iter, h_tol_p,
                                       bf16_ratio=bf16_ratio,
                                       w_table=table, kl_newton=kl_newton,
                                       use_pallas=use_pallas)
                    if use_pallas:
                        from .pallas_kl import pallas_kl_beta_err

                        err_c = pallas_kl_beta_err(x, h, W)
                    else:
                        err_c = ell_beta_err(x, h, W, beta)
                    W = _update_W(x, h, W, beta, l1_W, l2_W,
                                  bf16_ratio=bf16_ratio, w_table=table,
                                  use_pallas=use_pallas)
                    return (W, err_acc + err_c), h
                h = _chunk_h_solve(x, h, W, None, beta, l1_H, l2_H,
                                   chunk_max_iter, h_tol_p,
                                   bf16_ratio=bf16_ratio,
                                   kl_newton=kl_newton)
                WH = jnp.maximum(h @ W, EPS)
                err_c = _beta_div_dense(x, WH, beta)
                if bf16_ratio:
                    # W step via the shared bf16 update (f32 accumulation);
                    # the objective above keeps the f32 WH so the pass
                    # stopping rule sees production-precision errors
                    W = _update_W(x, h, W, beta, l1_W, l2_W,
                                  bf16_ratio=True)
                    return (W, err_acc + err_c), h
                if beta == 1.0:
                    numer = h.T @ (x / WH)
                    denom = jnp.broadcast_to(h.sum(axis=0)[:, None], W.shape)
                elif beta == 0.0:
                    numer = h.T @ (x / (WH * WH))
                    denom = h.T @ (1.0 / WH)
                else:
                    numer = h.T @ (x * WH ** (beta - 2.0))
                    denom = h.T @ (WH ** (beta - 1.0))
                W = _apply_rate(W, numer, denom, l1_W, l2_W,
                                gamma=mu_gamma(beta))
                return (W, err_acc + err_c), h

            if sketch_dim:
                # sketched KL W steps (ISSUE 11): exact usage solves and
                # exact per-chunk objectives, W statistics from a fresh
                # per-(pass, chunk) row subsample of the chunk; every
                # sketch_exact_every-th PASS runs the exact chunk steps
                n_chunks_s = (Xc.vals.shape[0] if isinstance(Xc, EllMatrix)
                              else Xc.shape[0])
                chunk_rows_s = (Xc.vals.shape[1]
                                if isinstance(Xc, EllMatrix)
                                else Xc.shape[1])
                m_c = min(sketch_dim, chunk_rows_s)
                exact_pass = (p % max(sketch_exact_every, 1) == 0)

                def w_step_sk(x, h, W, ci, table=None):
                    def _exact(_):
                        return _update_W(x, h, W, beta, l1_W, l2_W,
                                         w_table=table)

                    def _sk(_):
                        idx = jax.random.randint(
                            jax.random.fold_in(jax.random.key(1),
                                               p * n_chunks_s + ci),
                            (m_c,), 0, chunk_rows_s)
                        if isinstance(x, EllMatrix):
                            numer, denom = ell_kl_w_stats_rows(x, h, W, idx)
                        else:
                            xs = jnp.take(x, idx, axis=0)
                            hs = jnp.take(h, idx, axis=0)
                            WHs = jnp.maximum(hs @ W, EPS)
                            numer = hs.T @ (xs / WHs)
                            denom = jnp.broadcast_to(
                                hs.sum(axis=0)[:, None], W.shape)
                        sc = m_c / chunk_rows_s
                        return _apply_rate_sketched(W, numer, denom,
                                                    l1_W * sc, l2_W * sc)

                    return jax.lax.cond(exact_pass, _exact, _sk,
                                        operand=None)

                def scan_chunk_sk(carry, xc_hc_i):
                    W, err_acc = carry
                    x, h, ci = xc_hc_i
                    # one pre-gathered W slab table serves the (exact)
                    # usage solve AND the exact-pass W step, exactly
                    # like the non-sketch ELL lane's shared table — the
                    # sketched W branch alone skips it (its sampled-row
                    # gather is the whole point)
                    table = (ell_w_table(W, x.cols)
                             if isinstance(x, EllMatrix) else None)
                    h = _chunk_h_solve(x, h, W, None, beta, l1_H, l2_H,
                                       chunk_max_iter, h_tol_p,
                                       w_table=table)
                    if isinstance(x, EllMatrix):
                        err_c = ell_beta_err(x, h, W, beta)
                    else:
                        err_c = _beta_div_dense(
                            x, jnp.maximum(h @ W, EPS), beta)
                    W = w_step_sk(x, h, W, ci, table)
                    return (W, err_acc + err_c), h

                (W, err), Hc = jax.lax.scan(
                    scan_chunk_sk, (W, jnp.float32(0.0)),
                    (Xc, Hc, jnp.arange(n_chunks_s)))
            else:
                (W, err), Hc = jax.lax.scan(scan_chunk,
                                            (W, jnp.float32(0.0)),
                                            (Xc, Hc))
        return (Hc, W, err), err

    # first pass to establish err0, then scan remaining passes with early
    # freeze once converged (carry a `done` mask; frozen passes still cost
    # compute under scan, so keep n_passes modest)
    (Hc, W, err0), _ = one_pass((Hc0, W0, jnp.float32(jnp.inf)),
                                jnp.int32(0))

    def active_of(err_prev, err, it):
        # it counts completed passes (the err0 pass is #1), so `it < n_passes`
        # allows exactly n_passes total. While the coarse-to-fine inner
        # tolerance is still above its floor, small per-pass progress must
        # NOT stop the loop — the tolerance hasn't tightened yet and later
        # passes resume real progress (premature stops here plateaued
        # exact-recovery cases well above the tight-schedule optimum).
        if h_tol_start is None:
            still_coarse = jnp.bool_(False)
        else:
            still_coarse = (h_tol_start * 0.5 ** it.astype(jnp.float32)
                            > h_tol)
        progressing = (err_prev - err) / jnp.maximum(err0, EPS) >= tol
        keep = still_coarse | progressing
        if sketch_dim:
            # only an exact-pass state may stop the loop: pass index
            # it-1 ran exact W steps iff (it-1) % E == 0 — subsample
            # noise reading as sub-tol progress must not freeze a
            # sketched W as the result (the rowshard lanes and
            # nmf_fit_batch's eval-boundary anchor share this contract)
            keep = keep | ((it - 1) % max(sketch_exact_every, 1) != 0)
        return (it < n_passes) & keep

    def pass_body(carry):
        if telemetry:
            Hc, W, err_prev, err, it, tm, act = carry
            # latched, as in nmf_fit_batch: under vmap a lane whose pass
            # progress re-exceeds tol after its own stop must not resume
            # counting passes
            act = act & active_of(err_prev, err, it)
        else:
            Hc, W, err_prev, err, it = carry
        (Hc, W, _), err_new = one_pass((Hc, W, err), it)
        if telemetry:
            # one trace slot per pass: pass it+1's objective lands at
            # 0-based slot `it` (slot 0 holds err0 from the init below)
            tm = SolverTelemetry(
                trace=tm.trace.at[jnp.minimum(it, TRACE_LEN - 1)].set(
                    err_new),
                iters=tm.iters + act.astype(jnp.int32),
                nonfinite=tm.nonfinite | ~jnp.isfinite(err_new))
            return (Hc, W, err, err_new, it + 1, tm, act)
        return (Hc, W, err, err_new, it + 1)

    def pass_cond(carry):
        return active_of(carry[2], carry[3], carry[4])

    init = (Hc, W, err0 * (1.0 + 2.0 * tol) + 1.0, err0, jnp.int32(1))
    if telemetry:
        init = init + (SolverTelemetry(
            trace=jnp.full((TRACE_LEN,), jnp.nan,
                           jnp.float32).at[0].set(err0),
            iters=jnp.int32(1),  # the err0 pass already ran
            nonfinite=~jnp.isfinite(err0)), jnp.bool_(True))
    out = jax.lax.while_loop(pass_cond, pass_body, init)
    Hc, W = out[0], out[1]

    # the per-pass err is accumulated against the W each chunk saw *before*
    # its update; report the exact objective of the returned (H, W) pair
    # with one extra err-only scan (matches nmf_fit_batch's final recompute)
    def err_chunk(acc, xc_hc):
        x, h = xc_hc
        return acc + beta_divergence(x, h, W, beta=beta,
                                     use_pallas=use_pallas), None

    err, _ = jax.lax.scan(err_chunk, jnp.float32(0.0), (Xc, Hc))
    if telemetry:
        tm = out[5]
        return Hc, W, err, tm._replace(
            nonfinite=tm.nonfinite | ~jnp.isfinite(err))
    return Hc, W, err


# ---------------------------------------------------------------------------
# fixed-W usage solver (refit path)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit,
                   static_argnames=("beta", "chunk_max_iter", "l1_H", "l2_H",
                                    "use_pallas"))
def _fit_h_chunked(Xc, Hc0, W, beta: float, chunk_max_iter: int, h_tol: float,
                   l1_H: float, l2_H: float, use_pallas: bool = False):
    WWT = W @ W.T if beta == 2.0 else None

    def scan_chunk(_, xc_hc):
        x, h = xc_hc
        h = _chunk_h_solve(x, h, W, WWT, beta, l1_H, l2_H, chunk_max_iter,
                           h_tol, use_pallas=use_pallas)
        return None, h

    _, Hc = jax.lax.scan(scan_chunk, None, (Xc, Hc0))
    return Hc


def fit_h_default_init(n: int, k: int, key=None):
    """The usage-refit's default H init: ``uniform(key, (n, k))`` with the
    fixed key 0 when ``key`` is None — split out of :func:`fit_h` so the
    serving tier's per-request lane builder (``serving/batcher.py``) draws
    the EXACT init a solo ``refit_usage`` dispatch would, instead of a
    hand-copied expression that could drift.

    Under the partitionable threefry (package default,
    ``utils/jax_compat.py``) the draw is a row-major counter stream, so
    for fixed ``k`` the first ``m`` rows of an ``(n, k)`` draw equal the
    ``(m, k)`` draw bit-exactly — the prefix property the serving tier's
    row-padded lanes rely on for bit-identity with solo dispatch."""
    if key is None:
        key = jax.random.key(0)
    return jax.random.uniform(key, (n, k), dtype=jnp.float32)


def _chunk_rows(X, H, chunk_size):
    """Zero-pad rows to a multiple of chunk_size and reshape to chunks.
    ``X`` may be dense or an :class:`EllMatrix` (both ELL buffers chunk
    identically; padded rows carry value-0/column-0 entries, exactly the
    benign padding convention the sparse kernels rely on)."""
    k = H.shape[1]
    if isinstance(X, EllMatrix) and X.vals.ndim == 3:
        # pre-chunked dual ELL (ops/sparse.py:ell_chunk_rows — the online
        # W step needs per-chunk transpose index sets, which only the host
        # staging can build): chunk H to match
        n_chunks, chunk_rows, _ = X.vals.shape
        pad = n_chunks * chunk_rows - H.shape[0]
        if pad:
            H = jnp.pad(H, ((0, pad), (0, 0)))
        return X, H.reshape(n_chunks, chunk_rows, k), pad
    n = X.shape[0]
    n_chunks = max(1, -(-n // chunk_size))
    pad = n_chunks * chunk_size - n
    if isinstance(X, EllMatrix):
        # in-jit chunking covers the row side only — the H-only solvers
        # (fit_h) never touch the transpose index set, which cannot be
        # re-derived inside a traced program
        vals, cols = X.vals, X.cols
        if pad:
            vals = jnp.pad(vals, ((0, pad), (0, 0)))
            cols = jnp.pad(cols, ((0, pad), (0, 0)))
            H = jnp.pad(H, ((0, pad), (0, 0)))
        w = vals.shape[1]
        Xc = EllMatrix(vals.reshape(n_chunks, chunk_size, w),
                       cols.reshape(n_chunks, chunk_size, w), X.g)
        return Xc, H.reshape(n_chunks, chunk_size, k), pad
    g = X.shape[1]
    if pad:
        X = jnp.pad(X, ((0, pad), (0, 0)))
        H = jnp.pad(H, ((0, pad), (0, 0)))
    return (X.reshape(n_chunks, chunk_size, g),
            H.reshape(n_chunks, chunk_size, k), pad)


def fit_h(X, W, H_init=None, chunk_size: int = 5000, chunk_max_iter: int = 200,
          h_tol: float = 0.05, l1_reg_H: float = 0.0, l2_reg_H: float = 0.0,
          beta: float = 2.0, key=None, k_pad: int | None = None) -> np.ndarray:
    """Fit usages H for fixed spectra W — the ``fit_H_online`` equivalent
    (cnmf.py:260-388): one pass over row chunks, inner MU loop per chunk with
    relative-change tolerance ``h_tol``, uniform random init when ``H_init``
    is None (clamped at zero otherwise).

    Accepts numpy/scipy-sparse inputs — or an already device-resident
    ``jax.Array`` (the consensus stage stages X once and reuses it across
    its three refits and the K sweep instead of re-crossing the host link
    per call) — and returns a numpy (n, k) array.

    ``k_pad``: compile the solve at component width ``k_pad`` with W
    zero-row-padded, so one executable serves every K of a selection
    sweep. Exact-zero padding is absorbing under MU (padded usage columns
    start at exact 0 via the threefry flat-prefix gather and never leave
    it; padded W rows contribute exact +0.0 to every real column's
    numerator/denominator), so the first k columns reproduce the per-K
    program to fp-tiling order. The returned array is sliced back to
    (n, k).

    Sparsity-aware dispatch: a scipy-sparse ``X`` with beta in {1, 0}
    below the ELL density threshold (``ops/sparse.py:resolve_sparse_beta``,
    ``CNMF_TPU_SPARSE_BETA`` override) is staged as a fixed-width ELL
    matrix and the whole refit runs on the nonzero-only kernels; an
    UNCHUNKED :class:`~cnmf_torch_tpu.ops.sparse.EllMatrix`
    (``csr_to_ell`` output — the transpose index set is optional here)
    may also be passed directly.
    """
    if isinstance(X, EllMatrix):
        if float(beta) not in (1.0, 0.0):
            raise ValueError(
                f"EllMatrix inputs require beta in {{1, 0}}, got {beta}")
        if X.vals.ndim != 2:
            # a sweep-staged pre-chunked encoding's leading dims are
            # (n_chunks, chunk_rows) — treating them as (cells, genes)
            # would silently return an (n_chunks, k) usage array
            raise ValueError(
                "fit_h takes an UNCHUNKED EllMatrix (vals.ndim == 2); "
                "re-encode with csr_to_ell (fit_h does its own chunking)")
        if not isinstance(X.vals, jax.Array):
            X = ell_device_put(X)
    elif isinstance(X, jax.Array):
        X = X.astype(jnp.float32)
    else:
        if sp.issparse(X):
            n_s, g_s = X.shape
            if resolve_sparse_beta(float(beta),
                                   density=X.nnz / max(n_s * g_s, 1),
                                   width=ell_row_width(X), g=g_s):
                # H-only refit: the W-side transpose index set is unused
                X = ell_device_put(csr_to_ell(X, transpose=False))
            else:
                X = X.toarray()
        if not isinstance(X, EllMatrix):
            X = jnp.asarray(np.asarray(X), dtype=jnp.float32)
    W = jnp.asarray(np.asarray(W), dtype=jnp.float32)
    n = X.shape[0]
    k = W.shape[0]
    k_solve = k
    if k_pad is not None:
        if k_pad < k:
            raise ValueError(f"k_pad={k_pad} < k={k}")
        # the flat-prefix init gather below is only bit-compatible with the
        # per-K draw under the partitionable threefry (ADVICE r5 #1)
        assert_threefry_partitionable("fit_h(k_pad=...)")
        k_solve = int(k_pad)
        W = jnp.pad(W, ((0, k_solve - k), (0, 0)))
    if H_init is None:
        if key is None:
            key = jax.random.key(0)
        if k_solve != k:
            # per-K parity: uniform(key, (n, k)) is the row-major prefix of
            # the flat (n*k_pad,) stream, so gathering flat[i*k + j] for
            # j < k (0.0 beyond) reproduces the unpadded init bit-exactly
            # in the real columns with exact-zero padding
            flat = jax.random.uniform(key, (n * k_solve,), dtype=jnp.float32)
            cols = np.arange(k_solve)[None, :]
            idx = np.arange(n)[:, None] * k + cols
            valid = jnp.asarray(cols < k)
            H = jnp.where(valid,
                          jnp.take(flat, jnp.asarray(np.where(cols < k, idx,
                                                              0))),
                          0.0)
        else:
            H = fit_h_default_init(n, k, key)
    else:
        H = jnp.maximum(jnp.asarray(np.asarray(H_init), dtype=jnp.float32), 0.0)
        if k_solve != H.shape[1]:
            H = jnp.pad(H, ((0, 0), (0, k_solve - H.shape[1])))
    chunk_size = int(min(chunk_size, n))
    Xc, Hc, pad = _chunk_rows(X, H, chunk_size)
    # fused Pallas kernels for the ELL KL refit (CNMF_TPU_PALLAS;
    # default 0 keeps the jnp program byte-identical)
    use_pallas = (isinstance(X, EllMatrix) and float(beta) == 1.0
                  and resolve_pallas())
    Hc = _fit_h_chunked(Xc, Hc, W, float(beta), int(chunk_max_iter),
                        float(h_tol), float(l1_reg_H), float(l2_reg_H),
                        use_pallas=use_pallas)
    H = Hc.reshape(-1, k_solve)
    if pad:
        H = H[:n]
    if k_solve != k:
        H = H[:, :k]
    return np.asarray(H)


def _fit_h_block(Xb, Hb0, W, beta, chunk_size, chunk_max_iter, h_tol,
                 l1_H, l2_H):
    """One slab's rows through the chunked fixed-W solver — exactly the
    unit :func:`fit_h` runs over the whole matrix (same ``_chunk_rows``
    zero-padding, same ``_fit_h_chunked`` program body), so slab-looped
    callers reproduce the resident refit chunk-for-chunk. Returns the
    slab's usage rows as numpy ``(rows, k)``."""
    rows = int(Xb.shape[0])
    k = int(Hb0.shape[1])
    Xc, Hc, pad = _chunk_rows(jnp.asarray(np.asarray(Xb), jnp.float32),
                              jnp.asarray(np.asarray(Hb0), jnp.float32),
                              int(chunk_size))
    Hc = _fit_h_chunked(Xc, Hc, jnp.asarray(np.asarray(W), jnp.float32),
                        float(beta), int(chunk_max_iter), float(h_tol),
                        float(l1_H), float(l2_H))
    H = np.asarray(Hc.reshape(-1, k))
    return H[:rows]


def fit_h_slabbed(blocks, n: int, W, *, chunk_size: int = 5000,
                  chunk_max_iter: int = 200, h_tol: float = 0.05,
                  l1_reg_H: float = 0.0, l2_reg_H: float = 0.0,
                  beta: float = 2.0, key=None,
                  collect=None) -> np.ndarray:
    """Slab-looped fixed-W usage refit — :func:`fit_h` re-expressed as a
    budget-bounded loop over row blocks (the streaming-consensus entry,
    ISSUE 13): host residency is one block, never the cells x genes
    matrix.

    BIT-identical to ``fit_h`` on the assembled matrix when every block
    boundary is a multiple of the (clamped) chunk size: the default
    init draws the same ``(n, k)`` threefry stream (row-major counters —
    rows ``lo:hi`` are position-determined, so slicing the one full
    draw reproduces the resident rows exactly; the draw is k-sized host
    bytes, not genes-sized), and chunks are solved INDEPENDENTLY by
    ``_fit_h_chunked``, so only the chunk partition — which this loop
    preserves — determines the result. Enforced: a misaligned block
    boundary raises rather than silently changing chunk composition.

    ``blocks``: iterable of ``(lo, hi, X_block)`` with ``X_block`` a
    dense ``(hi-lo, genes)`` array. ``collect(lo, hi, X_block, H_block)``
    runs per block before the buffers drop — a fused-statistics hook
    for single-spectra callers (accumulate HᵀX / HᵀH / ‖X‖² in the same
    pass that solves the usages). The MULTI-K K-selection error pass
    shares :func:`_fit_h_block` directly instead (one block read must
    serve every K, which a single-W loop cannot express)."""
    W = np.asarray(W, dtype=np.float32)
    k = int(W.shape[0])
    chunk_size = int(min(int(chunk_size), max(int(n), 1)))
    H0 = np.asarray(fit_h_default_init(int(n), k, key))
    out = np.zeros((int(n), k), np.float32)
    for lo, hi, Xb in blocks:
        lo, hi = int(lo), int(hi)
        if lo % chunk_size and lo < n:
            raise ValueError(
                f"block boundary {lo} is not a multiple of the chunk "
                f"size {chunk_size} — slab-looped fit_h is only "
                "bit-identical to the resident refit when the chunk "
                "partition is preserved")
        Hb = _fit_h_block(Xb, H0[lo:hi], W, beta, chunk_size,
                          chunk_max_iter, h_tol, l1_reg_H, l2_reg_H)
        out[lo:hi] = Hb
        if collect is not None:
            collect(lo, hi, Xb, Hb)
    return out


# ---------------------------------------------------------------------------
# initialization
# ---------------------------------------------------------------------------

def random_init(key, n, g, k, x_mean, dtype=jnp.float32):
    """sklearn-style scaled random init: entries ~ avg * |N(0,1)| with
    avg = sqrt(mean(X)/k)."""
    avg = jnp.sqrt(jnp.maximum(x_mean, EPS) / k)
    kh, kw = jax.random.split(key)
    H = avg * jnp.abs(jax.random.normal(kh, (n, k), dtype=dtype))
    W = avg * jnp.abs(jax.random.normal(kw, (k, g), dtype=dtype))
    return H, W


@functools.partial(jax.jit, static_argnames=("k", "variant"))
def nndsvd_init(X, k: int, variant: str = "nndsvd", key=None):
    """Nonnegative double SVD init (Boutsidis & Gallopoulos 2008), the
    ``init='nndsvd'`` option of the reference CLI (cnmf.py:1427).

    variant: 'nndsvd' (exact zeros), 'nndsvda' (zeros -> mean(X)),
    'nndsvdar' (zeros -> small seeded random).  For MU solvers exact zeros
    are absorbing, so the pipeline maps init='nndsvd' to seeded 'nndsvdar'
    filling (init_factors) — deterministic fills would also make every
    consensus replicate identical.
    """
    U, S, Vt = jnp.linalg.svd(X, full_matrices=False)
    U, S, Vt = U[:, :k], S[:k], Vt[:k, :]
    return _nndsvd_from_svd(U, S, Vt, k, variant, key, jnp.mean(X))


def gram_svd_base(X, k: int):
    """The deterministic truncated-SVD base of the gram-form nndsvd init:
    ``(U (n,k), S (k,), Vt (k,g))``. Split out so replicate sweeps can
    compute it ONCE and vmap only the seeded fill over replicate keys
    (``_nndsvd_from_svd``) instead of batching R identical g x g
    eigendecompositions."""
    G = jnp.matmul(X.T, X, precision=_HI)
    evals, evecs = jnp.linalg.eigh(G)           # ascending
    S = jnp.sqrt(jnp.clip(evals[::-1][:k], 0.0))
    V = evecs[:, ::-1][:, :k]                   # (g, k)
    # floor S relative to S[0]: when k exceeds the numerical rank, clipped
    # eigenvalues give S ~ 0, and X@V for those columns is fp32 noise —
    # dividing it by EPS would seed ~1e10-scale factors (the full-SVD path
    # has orthonormal U and no such blowup). Treat those components as rank
    # overflow: zero the U column so the nndsvda/ar fill takes over, which
    # is exactly how the full-SVD variant behaves on a zero singular pair.
    rank_ok = S > 1e-6 * jnp.maximum(S[0], EPS)
    S = jnp.where(rank_ok, S, 0.0)
    U = jnp.where(rank_ok[None, :],
                  jnp.matmul(X, V, precision=_HI) / jnp.maximum(S, EPS), 0.0)
    return U, S, V.T


def nndsvd_init_gram(X, k: int, variant: str = "nndsvdar", key=None):
    """nndsvd init computed from the gram matrix — the sharding-friendly
    form for row-sharded X: the only all-to-all object is the g x g gram
    (one psum'd matmul), eigendecomposed replicated; U comes back as a
    row-sharded matmul. ``jnp.linalg.svd`` of a sharded X would gather the
    full matrix to one device, which is exactly what the atlas path exists
    to avoid. Sign ambiguity of eigenvectors is harmless: nndsvd's
    positive/negative splitting is invariant to a joint (u, v) sign flip.
    """
    U, S, Vt = gram_svd_base(X, k)
    return _nndsvd_from_svd(U, S, Vt, k, variant, key, jnp.mean(X))


def _nndsvd_from_svd(U, S, Vt, k, variant, key, x_mean):
    def split_pair(j):
        u, v = U[:, j], Vt[j, :]
        up, un = jnp.maximum(u, 0.0), jnp.maximum(-u, 0.0)
        vp, vn = jnp.maximum(v, 0.0), jnp.maximum(-v, 0.0)
        n_up, n_un = jnp.linalg.norm(up), jnp.linalg.norm(un)
        n_vp, n_vn = jnp.linalg.norm(vp), jnp.linalg.norm(vn)
        termp, termn = n_up * n_vp, n_un * n_vn
        use_p = termp >= termn
        sigma = jnp.where(use_p, termp, termn)
        hj = jnp.where(use_p, up / jnp.maximum(n_up, EPS),
                       un / jnp.maximum(n_un, EPS))
        wj = jnp.where(use_p, vp / jnp.maximum(n_vp, EPS),
                       vn / jnp.maximum(n_vn, EPS))
        scale = jnp.sqrt(S[j] * sigma)
        return scale * hj, scale * wj

    cols = [jnp.sqrt(S[0]) * jnp.abs(U[:, 0])]
    rows = [jnp.sqrt(S[0]) * jnp.abs(Vt[0, :])]
    for j in range(1, k):
        hj, wj = split_pair(j)
        cols.append(hj)
        rows.append(wj)
    H = jnp.stack(cols, axis=1)
    W = jnp.stack(rows, axis=0)

    if variant == "nndsvda":
        avg = x_mean
        H = jnp.where(H == 0.0, avg / 100.0, H)
        W = jnp.where(W == 0.0, avg / 100.0, W)
    elif variant == "nndsvdar":
        avg = x_mean
        kh, kw = jax.random.split(key if key is not None else jax.random.key(0))
        H = jnp.where(H == 0.0,
                      avg / 100.0 * jax.random.uniform(kh, H.shape), H)
        W = jnp.where(W == 0.0,
                      avg / 100.0 * jax.random.uniform(kw, W.shape), W)
    return H, W


def init_factors(X, k: int, init: str, key, x_mean=None):
    """Dispatch on the reference's init choices {random, nndsvd}
    (cnmf.py:1427), plus the nndsvda/nndsvdar variants nmf-torch ships.

    ``init='nndsvd'`` maps to seeded nndsvdar filling: exact zeros are
    absorbing under MU (the factor never leaves them), and a deterministic
    fill would make every replicate of a consensus sweep identical —
    vacuous consensus. The seeded fill keeps replicates distinct and keeps
    this sequential path bit-consistent with the batched sweep's per-
    replicate inits (parallel/replicates.py:_stacked_inits) for the same
    ledger seed."""
    n, g = X.shape
    if init == "random":
        if x_mean is None:
            x_mean = jnp.mean(X)
        return random_init(key, n, g, k, x_mean)
    if init in ("nndsvd", "nndsvda", "nndsvdar"):
        variant = "nndsvdar" if init == "nndsvd" else init
        return nndsvd_init(X, k, variant=variant, key=key)
    raise ValueError(f"unknown init {init!r}")


# ---------------------------------------------------------------------------
# run_nmf — the nmf-torch-compatible entry point
# ---------------------------------------------------------------------------

def run_nmf_use_ell(X, beta: float, *, init: str = "random",
                    algo: str = "mu",
                    fp_precision: str = "float") -> bool:
    """The exact ELL-vs-dense condition :func:`run_nmf` applies to a
    given input. Shared with the provenance recorders (the sequential
    lane in ``models/cnmf.py``) so a recorded recipe can never
    desynchronize from the one ``run_nmf`` actually engages."""
    if not (sp.issparse(X) and init == "random" and algo == "mu"
            and fp_precision == "float" and float(beta) in (1.0, 0.0)):
        return False
    n_s, g_s = X.shape
    return bool(resolve_sparse_beta(
        float(beta), density=X.nnz / max(n_s * g_s, 1),
        width=ell_row_width(X), g=g_s))


def run_nmf(X, n_components: int, init: str = "random",
            beta_loss: Any = "frobenius", algo: str = "mu",
            mode: str = "online", tol: float = 1e-4,
            n_passes: int | None = None, online_chunk_size: int = 5000,
            online_chunk_max_iter: int = 1000, batch_max_iter: int = 500,
            alpha_W: float = 0.0, l1_ratio_W: float = 0.0,
            alpha_H: float = 0.0, l1_ratio_H: float = 0.0,
            random_state: int = 0, n_jobs: int = -1, use_gpu: bool = False,
            fp_precision: str = "float",
            online_h_tol: float | None = None,
            recipe: SolverRecipe | None = None):
    """Drop-in equivalent of ``nmf.run_nmf`` as called by the reference
    (kwargs contract fixed at cnmf.py:757-771, call at cnmf.py:819).

    Returns ``(H usages (n,k), W spectra (k,g), err)``. ``n_jobs`` and
    ``use_gpu`` are accepted for contract compatibility and ignored — device
    placement is JAX's job here. ``fp_precision`` follows the nmf-torch
    surface: ``'float'`` (fp32, the only value the reference ever passes,
    cnmf.py:757-771) or ``'double'`` — honored for ``mode='batch'`` by
    running the whole solve in float64 under x64 (the online solver's scan
    carries are fp32 and double is out of its contract).

    ``recipe``: an explicit :class:`~cnmf_torch_tpu.ops.recipe.
    SolverRecipe`; ``None`` resolves one from the ``CNMF_TPU_ACCEL`` /
    ``CNMF_TPU_INNER_REPEATS`` / ``CNMF_TPU_KL_NEWTON`` knobs (default:
    plain MU — byte-identical programs to a build without the recipe
    layer). The ``fp_precision='double'`` contract path always runs
    plain updates (its trajectories are the f64 oracle)."""
    if fp_precision not in ("float", "double"):
        raise ValueError(
            f"fp_precision={fp_precision!r}: expected 'float' or 'double'")
    if fp_precision == "double" and mode != "batch":
        raise NotImplementedError(
            "fp_precision='double' is implemented for mode='batch'; the "
            "online solver is fp32 by contract")
    if algo not in ("mu", "halsvar"):
        raise NotImplementedError(
            f"algo={algo!r}: 'mu' (all beta losses, batch+online) and "
            "'halsvar' (frobenius, batch+online) are implemented")
    beta = beta_loss_to_float(beta_loss)
    if algo == "halsvar" and beta != 2.0:
        raise ValueError(
            "algo='halsvar' optimizes the Frobenius objective; use "
            "algo='mu' for kullback-leibler / itakura-saito")
    online_h_tol, n_passes, h_tol_start = resolve_online_schedule(
        beta, online_h_tol, n_passes)
    # sparsity-aware dispatch (ops/sparse.py): scipy-sparse KL/IS solves
    # below the ELL density threshold keep the fixed-width ELL encoding —
    # nonzero-only update statistics instead of dense WH/ratio passes.
    # init='random' only (the nndsvd family's SVD base needs dense X);
    # CNMF_TPU_SPARSE_BETA=0 forces the dense path.
    x_mean_host = None
    use_ell = run_nmf_use_ell(X, beta, init=init, algo=algo,
                              fp_precision=fp_precision)
    if use_ell:
        n_s, g_s = X.shape
        x_mean_host = float(X.sum()) / (n_s * g_s)
    if sp.issparse(X) and not use_ell:
        X = X.toarray()
    if recipe is None:
        recipe = resolve_recipe(beta, mode, algo=algo, ell=use_ell)
    elif recipe.algo == "hals" and algo == "mu":
        # a caller-pinned hals recipe routes through the halsvar lane
        if beta != 2.0:
            raise ValueError(
                "the hals recipe optimizes the Frobenius objective; use "
                "algo='mu' recipes for kullback-leibler / itakura-saito")
        algo = "halsvar"
    if (recipe.kl_newton or recipe.algo == "sketch") and beta != 1.0:
        raise ValueError(
            f"recipe {recipe.label!r} requires beta=1 (KL), got "
            f"beta_loss={beta_loss!r}")
    # fused Pallas kernels (CNMF_TPU_PALLAS, ISSUE 16): ELL beta=1 only;
    # the sketch recipe's sampled-row scatter keeps the jnp path
    use_pallas = (use_ell and beta == 1.0 and recipe.algo != "sketch"
                  and resolve_pallas())
    k = int(n_components)
    l1_W, l2_W = split_regularization(alpha_W, l1_ratio_W)
    l1_H, l2_H = split_regularization(alpha_H, l1_ratio_H)
    key = jax.random.key(int(random_state) & 0x7FFFFFFF)

    if fp_precision == "double":
        # the batch kernels are dtype-generic (their constants are weakly
        # typed Python floats); tracing them on f64 operands under x64
        # yields a genuinely double-precision solve on device
        with enable_x64():
            Xd = jnp.asarray(np.asarray(X), dtype=jnp.float64)
            H0, W0 = init_factors(Xd, k, init, key)
            H0, W0 = H0.astype(jnp.float64), W0.astype(jnp.float64)
            fit = (nmf_fit_batch_hals if algo == "halsvar"
                   else functools.partial(nmf_fit_batch, beta=beta))
            H, W, err = fit(Xd, H0, W0, tol=float(tol),
                            max_iter=int(batch_max_iter),
                            l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W)
            return np.asarray(H), np.asarray(W), float(err)
    if use_ell:
        n, g = X.shape
        if mode == "online":
            # per-chunk transpose index sets for the online W steps are a
            # host-staging product — pre-chunk here (ops/sparse.py)
            X, _ = ell_chunk_rows(X, int(min(online_chunk_size, n)))
        else:
            X = csr_to_ell(X)
        X = ell_device_put(X)
        H0, W0 = random_init(key, n, g, k, jnp.float32(x_mean_host))
    else:
        X = jnp.asarray(np.asarray(X), dtype=jnp.float32)
        n, g = X.shape
        H0, W0 = init_factors(X, k, init, key)

    if mode == "batch":
        if algo == "halsvar":
            H, W, err = nmf_fit_batch_hals(
                X, H0, W0, tol=float(tol), max_iter=int(batch_max_iter),
                l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W)
        else:
            H, W, err = nmf_fit_batch(
                X, H0, W0, beta=beta, tol=float(tol),
                max_iter=int(batch_max_iter),
                l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
                inner_repeats=int(recipe.inner_repeats),
                kl_newton=bool(recipe.kl_newton),
                sketch_dim=int(recipe.sketch_dim),
                sketch_exact_every=int(recipe.sketch_exact_every),
                use_pallas=use_pallas)
    elif mode == "online":
        chunk = int(min(online_chunk_size, n))
        Xc, Hc, pad = _chunk_rows(X, H0, chunk)
        Hc, W, err = nmf_fit_online(
            Xc, Hc, W0, beta=beta, tol=float(tol), h_tol=float(online_h_tol),
            chunk_max_iter=int(online_chunk_max_iter), n_passes=int(n_passes),
            l1_H=l1_H, l2_H=l2_H, l1_W=l1_W, l2_W=l2_W,
            h_tol_start=h_tol_start, algo=algo,
            # same precision chain as the batched production sweep, so a
            # sequential rerun reproduces its numerics class and the env
            # opt-out governs both paths
            bf16_ratio=resolve_bf16_ratio(beta, mode),
            kl_newton=bool(recipe.kl_newton),
            sketch_dim=int(recipe.sketch_dim),
            sketch_exact_every=int(recipe.sketch_exact_every),
            use_pallas=use_pallas)
        H = Hc.reshape(-1, k)[:n]
    else:
        raise ValueError(f"unknown mode {mode!r}")

    return np.asarray(H), np.asarray(W), float(err)


# ---------------------------------------------------------------------------
# analytic cost hooks (ISSUE 19, obs/costmodel.py)
# ---------------------------------------------------------------------------

def dense_update_cost(n: int, g: int, k: int, beta: float = 2.0,
                      *, bf16_ratio: bool = False,
                      bundled: bool = False) -> dict:
    """Analytic flop/byte cost of ONE dense MU iteration (H update + W
    update) of the chains above, in XLA ``cost_analysis()`` accounting:
    2mnk flops per matmul, 1 flop per output element per elementwise op;
    bytes = operand + output buffer bytes per unfused matmul plus
    operand + output bytes per fused elementwise chain. Pure host
    arithmetic — no jax import, callable from the cost model at plan
    time. ``bundled`` counts USEFUL per-replicate work only (the packed
    kernel's masked-Gram padding flops are overhead, same convention as
    the bench MFU tier); ``bf16_ratio`` halves the X/WH/ratio traffic
    of the beta!=2 chains, flops unchanged."""
    n, g, k = int(n), int(g), int(k)
    f = 4.0                      # f32 operand bytes
    fx = 2.0 if (bf16_ratio and beta != 2.0) else 4.0
    if beta == 2.0:
        # H: X@W.T + W@W.T + H@WWT + rate chain; W: H.T@X + H.T@H +
        # HtH@W + rate chain (ops above: _update_H/_update_W beta=2)
        flops = (4 * n * g * k + 4 * n * k * k + 4 * g * k * k
                 + 3 * n * k + 3 * g * k)
        bytes_ = (
            # H side: three unfused matmuls + the fused rate chain
            (n * g + k * g + n * k) * f        # X @ W.T
            + (2 * k * g + k * k) * f          # W @ W.T
            + (n * k + k * k + n * k) * f      # H @ WWT
            + 4 * n * k * f                    # numer,denom,H -> H'
            # W side, symmetric
            + (n * g + n * k + k * g) * f      # H.T @ X
            + (2 * n * k + k * k) * f          # H.T @ H
            + (k * g + k * k + k * g) * f      # HtH @ W
            + 4 * k * g * f)
    elif beta == 1.0:
        # H: WH + ratio + R@W.T + colsum denom + rate; W mirrored
        flops = (8 * n * g * k + 4 * n * g + k * (g - 1) + (n - 1) * k
                 + 3 * n * k + 3 * k * g)
        bytes_ = (
            2 * ((n * k + k * g) * f + n * g * fx)   # H@W (x2: H and W upd)
            + 2 * 3 * n * g * fx                     # X/max(WH,eps) chains
            + (n * g * fx + k * g * f + n * k * f)   # R @ W.T
            + (n * g * fx + n * k * f + k * g * f)   # H.T @ R
            + (k * g * f + k * f)                    # W colsum
            + (n * k * f + k * f)                    # H rowsum
            + 4 * n * k * f + 4 * k * g * f)         # rate chains
    elif beta == 0.0:
        # IS: WH + two ratio chains + two stats matmuls per side + the
        # gamma=0.5 rate chain (approximate elementwise accounting)
        flops = (12 * n * g * k + 10 * n * g + 7 * n * k + 7 * k * g)
        bytes_ = (
            2 * ((n * k + k * g) * f + n * g * fx)
            + 2 * 5 * n * g * fx
            + 2 * (n * g * fx + k * g * f + n * k * f)
            + 2 * (n * g * fx + n * k * f + k * g * f)
            + 6 * n * k * f + 6 * k * g * f)
    else:
        raise ValueError(f"dense_update_cost implements beta in "
                         f"{{2, 1, 0}}, got {beta}")
    return {"flops": float(flops), "bytes": float(bytes_),
            "lane": ("bundled" if bundled else
                     ("vmapped-bf16" if (bf16_ratio and beta != 2.0)
                      else "vmapped"))}
