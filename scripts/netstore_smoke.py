"""Tier-1 remote-store network-fault smoke gate (scripts/verify_tier1.sh).

Runs the mini pipeline against the in-repo HTTP object store
(``utils/netstore.ObjectStoreServer``) with ``CNMF_TPU_STORE_URI``
pointed at it, under each injected network fault class
(``runtime/faults.py``), and pins the containment contract:

  * ``netflake`` (transient connection failures): the transport retry
    ladder heals invisibly — the run completes BIT-identical to the
    local-store run, with ``store_net`` fault events (``healed``) on
    the record;
  * ``netslow`` (a stalled GET): the hedged second request wins — the
    staging stream event reports ``store_hedges_won`` >= 1 and the run
    stays bit-identical;
  * ``netdown`` with a WARM cache: consensus completes served from the
    digest-validated read-through cache, bit-identical, with exactly
    one loud DEGRADED warning and ``degraded`` fault events;
  * ``netdown`` with a COLD cache: factorize fails with the NAMED
    ``RemoteStoreError`` (not a hang, not a generic crash), the
    resilience ledger records kind ``remote_store``, and no transport
    threads linger behind the failure;
  * every emitted event validates against the telemetry schema.

Exits nonzero on any violation, failing the gate.
"""

from __future__ import annotations

import os
import shutil
import sys
import tempfile
import threading
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["CNMF_TPU_TELEMETRY"] = "1"

_KNOBS = ("CNMF_TPU_OOC", "CNMF_TPU_OOC_BUDGET_BYTES",
          "CNMF_TPU_OOC_SLAB_ROWS", "CNMF_TPU_FAULT_SPEC",
          "CNMF_TPU_STORE_URI", "CNMF_TPU_STORE_RETRIES",
          "CNMF_TPU_STORE_BACKOFF_S", "CNMF_TPU_STORE_TIMEOUT_S",
          "CNMF_TPU_STORE_HEDGE_S", "CNMF_TPU_STORE_CACHE_BYTES")

N_CELLS, N_GENES_HV = 450, 100

# every remote run streams from the store (slab rows pinned to the
# refit chunk; 450/64 leaves a ragged 2-row final slab) with a tight
# transport budget so injected faults resolve in seconds, not minutes
_OOC_ENV = {"CNMF_TPU_OOC": "1", "CNMF_TPU_OOC_SLAB_ROWS": "64",
            "CNMF_TPU_STORE_BACKOFF_S": "0.02",
            "CNMF_TPU_STORE_TIMEOUT_S": "10"}


class _Env:
    """Save/patch/restore the knob environment around one scenario."""

    def __init__(self, env: dict):
        self.env = env

    def __enter__(self):
        self.prior = {k: os.environ.get(k) for k in _KNOBS}
        for k in _KNOBS:
            os.environ.pop(k, None)
        os.environ.update(self.env)

    def __exit__(self, *exc):
        for k, v in self.prior.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return False


def _counts_df(workdir: str) -> str:
    import numpy as np
    import pandas as pd

    from cnmf_torch_tpu.utils import save_df_to_npz

    rng = np.random.default_rng(3)
    usage = rng.dirichlet(np.ones(5) * 0.3, size=N_CELLS)
    spectra = rng.gamma(0.3, 1.0, size=(5, 130)) * 40.0 / 130
    counts = rng.poisson(usage @ spectra * 300.0).astype(np.float64)
    counts[counts.sum(axis=1) == 0, 0] = 1.0
    df = pd.DataFrame(counts, index=[f"c{i}" for i in range(N_CELLS)],
                      columns=[f"g{j}" for j in range(130)])
    fn = os.path.join(workdir, "counts.df.npz")
    save_df_to_npz(df, fn)
    return fn


def _make_obj(workdir: str):
    from cnmf_torch_tpu import cNMF

    obj = cNMF(output_dir=workdir, name="net")
    obj.prepare(_counts_df(workdir), components=[3], n_iter=4, seed=7,
                num_highvar_genes=N_GENES_HV, batch_size=64)
    return obj


def _pipeline(workdir: str, env: dict, mid_spec: str | None = None):
    """prepare → factorize → combine → consensus under ``env``;
    ``mid_spec`` installs a fault spec AFTER prepare (the writes go
    through clean; the fault hits the read path)."""
    with _Env(env):
        obj = _make_obj(workdir)
        if mid_spec is not None:
            os.environ["CNMF_TPU_FAULT_SPEC"] = mid_spec
        obj.factorize(rowshard=True)
        obj.combine()
        obj.consensus(k=3, density_threshold=2.0, show_clustering=False)
    return obj


def _load(obj, key, *fmt):
    import numpy as np

    return np.load(obj.paths[key] % fmt, allow_pickle=True)["data"]


def _assert_parity(base, other, label):
    import numpy as np

    for key, fmt in (("merged_spectra", (3,)),
                     ("consensus_spectra", (3, "2_0")),
                     ("consensus_usages", (3, "2_0"))):
        a, b = _load(base, key, *fmt), _load(other, key, *fmt)
        assert np.array_equal(a, b), \
            f"{label}: {key} is not bit-identical to the local-store run"


def _events(workdir: str) -> list:
    from cnmf_torch_tpu.utils.telemetry import (read_events,
                                                validate_events_file)

    path = os.path.join(workdir, "net", "cnmf_tmp", "net.events.jsonl")
    validate_events_file(path)
    return list(read_events(path))


def main() -> int:
    from cnmf_torch_tpu.utils.netstore import ObjectStoreServer
    from cnmf_torch_tpu.utils.shardstore import (RemoteStoreError,
                                                 open_shard_store)
    from cnmf_torch_tpu.utils.storebackend import _reset_degraded_warnings

    dirs = [tempfile.mkdtemp(prefix="netstore_smoke_%s_" % tag)
            for tag in ("local", "flaky", "slow", "warm", "cold")]
    d_local, d_flaky, d_slow, d_warm, d_cold = dirs
    try:
        # the local-store reference every remote scenario must match
        base = _pipeline(d_local, dict(_OOC_ENV))

        # -- 1. flaky network: transient faults heal via retries -------
        _reset_degraded_warnings()
        with ObjectStoreServer() as srv:
            flaky = _pipeline(
                d_flaky, dict(_OOC_ENV, CNMF_TPU_STORE_URI=srv.url + "/s1"),
                mid_spec="netflake:context=get:slab")
        _assert_parity(base, flaky, "netflake")
        evs = _events(d_flaky)
        net = [e for e in evs if e["t"] == "fault"
               and e.get("kind") == "store_net"]
        assert any(isinstance(e.get("context"), dict)
                   and e["context"].get("healed") for e in net), \
            "no healed store_net fault event after netflake"
        assert any(e["t"] == "dispatch" and e.get("decision") == "ooc_ingest"
                   and (e.get("context") or {}).get("backend") == "remote"
                   for e in evs), "ooc_ingest did not record a remote backend"
        print("[netstore_smoke] netflake: healed by transport retries, "
              "bit-identical ... ok")

        # -- 2. slow network: the hedged read wins the stall -----------
        _reset_degraded_warnings()
        with ObjectStoreServer() as srv:
            slow = _pipeline(
                d_slow, dict(_OOC_ENV, CNMF_TPU_STORE_URI=srv.url + "/s2",
                             CNMF_TPU_STORE_HEDGE_S="0.2"),
                mid_spec="netslow:context=get:slab,seconds=1.5")
        _assert_parity(base, slow, "netslow")
        hedged = [e for e in _events(d_slow) if e["t"] == "stream"
                  and int(e.get("store_hedges_won") or 0) > 0]
        assert hedged, "no stream event recorded a won hedge"
        print("[netstore_smoke] netslow: hedge won the stalled read, "
              "bit-identical ... ok")

        # -- 3. remote down, WARM cache: degraded completion -----------
        _reset_degraded_warnings()
        with ObjectStoreServer() as srv:
            env = dict(_OOC_ENV, CNMF_TPU_STORE_URI=srv.url + "/s3",
                       CNMF_TPU_STORE_RETRIES="1")
            with _Env(env):
                warm = _make_obj(d_warm)
                warm.factorize(rowshard=True)
                warm.combine()
                # pre-warm every object the degraded phase will need:
                # slabs + names land in the read-through cache (the
                # manifest was cached when factorize opened the store)
                st = open_shard_store(warm.paths["shard_store"])
                for i in range(len(st.slabs)):
                    st.read_slab(i)
                st.obs_names()
                os.environ["CNMF_TPU_FAULT_SPEC"] = "netdown:context=get:"
                with warnings.catch_warnings(record=True) as caught:
                    warnings.simplefilter("always")
                    warm.consensus(k=3, density_threshold=2.0,
                                   show_clustering=False)
        _assert_parity(base, warm, "netdown-warm")
        loud = [w for w in caught if issubclass(w.category, RuntimeWarning)
                and "DEGRADED" in str(w.message)]
        assert len(loud) == 1, \
            "expected exactly one degraded-service warning, got %d" \
            % len(loud)
        evs = _events(d_warm)
        assert any(e["t"] == "fault" and e.get("kind") == "store_net"
                   and isinstance(e.get("context"), dict)
                   and e["context"].get("degraded") for e in evs), \
            "no degraded store_net fault event on the record"
        print("[netstore_smoke] netdown warm-cache: consensus served from "
              "cache, one loud warning, bit-identical ... ok")

        # -- 4. remote down, COLD cache: loud named failure ------------
        _reset_degraded_warnings()
        with ObjectStoreServer() as srv:
            env = dict(_OOC_ENV, CNMF_TPU_STORE_URI=srv.url + "/s4",
                       CNMF_TPU_STORE_RETRIES="1",
                       CNMF_TPU_STORE_CACHE_BYTES="0")
            with _Env(env):
                cold = _make_obj(d_cold)
                os.environ["CNMF_TPU_FAULT_SPEC"] = \
                    "netdown:context=get:slab"
                try:
                    cold.factorize(rowshard=True)
                except RemoteStoreError as exc:
                    assert "CNMF_TPU_STORE_RETRIES" in str(exc), \
                        "RemoteStoreError does not name the retry knob"
                else:
                    raise AssertionError(
                        "cold-cache netdown factorize should raise "
                        "RemoteStoreError")
        import json

        ledger_path = cold.paths["resilience_ledger"] % 0
        assert os.path.exists(ledger_path), "no resilience ledger persisted"
        with open(ledger_path) as f:
            ledger = json.load(f)
        kinds = [r.get("kind") for r in ledger.get("shard_faults", [])]
        assert "remote_store" in kinds, \
            f"ledger shard_faults {kinds} missing kind remote_store"
        lingering = [t for t in threading.enumerate()
                     if t.name.startswith("cnmf-store")
                     or (not t.daemon and t is not threading.main_thread())]
        assert not lingering, f"threads survived the failure: {lingering}"
        print("[netstore_smoke] netdown cold-cache: named RemoteStoreError, "
              "ledger kind remote_store, no lingering threads ... ok")
        return 0
    finally:
        for d in dirs:
            shutil.rmtree(d, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
